"""GBDT boosting loop.

Parity surface: the reference's training orchestration
(``lightgbm/.../LightGBMBase.scala:43-66`` batch loop, ``TrainUtils.scala:92-160``
iteration loop with eval metrics and early stopping ``:126-152``; rank-0
model return ``:356-364``) and LightGBM's parameter surface rendered by
``params/TrainParams.scala:10-100``.

TPU-first structure: grad/hess, tree build, and score update are jitted and
stay on device between iterations; only eval metrics come back to host. The
``tree_learner='data_parallel'`` path wraps the tree builder in ``shard_map``
over the mesh's ``data`` axis — histograms psum over ICI, every shard makes
identical split decisions (the same invariant LightGBM's socket allreduce
maintains), rows never move. Multiclass trains K trees per iteration via
``vmap`` over the class axis — the K histograms batch into one kernel.
"""

from __future__ import annotations

import functools
import json
import math
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...observability import histogram as _metric_histogram
from .binning import BinMapper
from .booster import Booster
from .objectives import get_metric, get_objective
from .trees import build_tree

_M_GBDT_PHASE = _metric_histogram(
    "mmlspark_gbdt_phase_seconds",
    "Per-iteration GBDT training phase wall-clock (populated only when "
    "MMLSPARK_TPU_GBDT_PROF=1, like the _PhaseProf stderr report)",
    ("phase",))

__all__ = ["train", "TrainConfig", "resolve_params"]

_DEFAULTS = dict(
    objective="regression",
    boosting="gbdt",                # gbdt | goss | dart | rf
    top_rate=0.2,                   # goss: keep fraction by |grad|
    other_rate=0.1,                 # goss: sample fraction of the rest
    drop_rate=0.1,                  # dart: per-tree drop probability
    max_drop=50,                    # dart: cap on dropped trees per iter
    skip_drop=0.5,                  # dart: prob of skipping the drop entirely
    num_iterations=100,
    learning_rate=0.1,
    num_leaves=31,
    max_depth=-1,
    lambda_l1=0.0,
    lambda_l2=0.0,
    min_data_in_leaf=20,
    min_sum_hessian_in_leaf=1e-3,
    min_gain_to_split=0.0,
    feature_fraction=1.0,
    bagging_fraction=1.0,
    bagging_freq=0,
    max_bin=255,
    early_stopping_round=0,
    num_class=1,
    seed=0,
    metric="auto",
    tree_learner="serial",
    top_k=20,                       # voting_parallel: local nominations/node
    alpha=0.9,                      # huber/quantile parameter
    tweedie_variance_power=1.5,
    verbosity=-1,
    checkpoint_dir=None,            # step-level checkpoint/resume
    checkpoint_interval=0,          # iterations between checkpoints (0 = off)
    categorical_feature=None,       # feature indices with categorical splits
    enable_bundle=True,             # EFB on sparse input (LightGBM name)
    max_conflict_rate=0.0,          # EFB conflict budget as a row fraction
    max_bundle_bins=4096,           # cap on one bundle's bin span
    monotone_constraints=None,      # per-feature -1/0/+1 (LightGBM name)
    scale_pos_weight=1.0,           # binary: positive-class weight multiplier
    is_unbalance=False,             # binary: auto scale_pos_weight = neg/pos
    extra_trees=False,              # one random threshold per node×feature
    feature_fraction_bynode=1.0,    # feature subsample per NODE (not tree)
    path_smooth=0.0,                # smooth node outputs toward the parent
    boost_from_average=True,        # start from the objective's optimal const
    interaction_constraints=None,   # list of allowed feature groups
    cat_smooth=10.0,                # categorical: mean smoothing pseudo-count
    min_data_per_group=0,           # categorical: pool rarer categories
    linear_tree=False,              # ridge model per leaf over path features
    linear_lambda=0.0,              # L2 on linear-leaf weights (not bias)
    use_quantized_grad=False,       # bf16 histogram stats on the MXU
    #                                 (LightGBM's quantized-gradient analog)
)


def resolve_params(params: Dict) -> Dict:
    aliases = {"n_estimators": "num_iterations", "num_trees": "num_iterations",
               "num_round": "num_iterations", "eta": "learning_rate",
               "reg_alpha": "lambda_l1", "reg_lambda": "lambda_l2",
               "min_child_samples": "min_data_in_leaf",
               "min_child_weight": "min_sum_hessian_in_leaf",
               "subsample": "bagging_fraction", "subsample_freq": "bagging_freq",
               "colsample_bytree": "feature_fraction",
               "min_split_gain": "min_gain_to_split",
               "random_state": "seed",
               "application": "objective", "app": "objective",
               "boosting_type": "boosting", "boost": "boosting",
               "topK": "top_k",
               "parallelism": "tree_learner"}
    out = dict(_DEFAULTS)
    for k, v in params.items():
        out[aliases.get(k, k)] = v
    return out


def _depth_for(p: Dict) -> int:
    if p["max_depth"] and p["max_depth"] > 0:
        return int(p["max_depth"])
    # complete tree with num_leaves leaves at the bottom
    return max(1, int(math.ceil(math.log2(max(2, int(p["num_leaves"]))))))


def _thr_bins_to_raw(feats: np.ndarray, thr_bin: np.ndarray,
                     mapper: BinMapper, n_bins: int) -> np.ndarray:
    """Map split bins → raw thresholds ("x <= thr" ≡ "bin <= thr_bin").

    Fully vectorized over (tree, node) via the mapper's padded bounds table —
    the per-entry Python loop was a HIGGS-scale bottleneck (trees × nodes
    entries per iteration).
    """
    table, lengths = mapper.bounds_table()
    out = np.full(thr_bin.shape, np.inf, dtype=np.float32)
    valid = (feats >= 0) & (thr_bin < n_bins)
    f = np.clip(feats, 0, table.shape[0] - 1).astype(np.int64)
    i = np.clip(thr_bin.astype(np.int64) - 1, 0, np.maximum(lengths[f] - 1, 0))
    vals = table[f, i].astype(np.float32)
    out[valid] = vals[valid]
    return out


def _lambdarank_grad(scores: np.ndarray, y: np.ndarray, groups: np.ndarray,
                     sigma: float = 1.0) -> Tuple[np.ndarray, np.ndarray]:
    """LambdaRank gradients with |ΔNDCG| weighting, per query group.

    Vectorized: groups are padded to the max group size and all pairwise
    terms computed as (chunk, M, M) tensors, chunked so peak memory stays
    bounded — the per-group Python loop was a HIGGS-scale bottleneck.
    """
    g = np.zeros_like(scores)
    h = np.zeros_like(scores)
    groups = np.asarray(groups, dtype=np.int64)
    if len(groups) == 0:
        return g, h
    offs = np.concatenate([[0], np.cumsum(groups)])
    M = int(groups.max())
    if M <= 1:
        return g, h
    nG = len(groups)
    # padded (G, M) row-index matrix + validity mask
    idx = offs[:-1, None] + np.arange(M)[None, :]
    mask = np.arange(M)[None, :] < groups[:, None]
    idx = np.minimum(idx, len(scores) - 1)

    # chunk so the (C, M, M) pair tensors stay ~tens of MB
    chunk = max(1, int(4e6 / (M * M)))
    for lo in range(0, nG, chunk):
        sl = slice(lo, min(lo + chunk, nG))
        m = mask[sl]                                    # (C, M)
        ix = idx[sl]
        cnt = groups[sl]
        s = np.where(m, scores[ix], 0.0)
        yy = np.where(m, y[ix], 0.0)
        # ranks: padded entries sort last via -inf key
        key = np.where(m, s, -np.inf)
        order = np.argsort(-key, axis=1, kind="stable")
        rank = np.empty_like(order)
        np.put_along_axis(rank, order, np.arange(M)[None, :], axis=1)
        gain = np.where(m, 2.0 ** yy - 1, 0.0)
        disc = np.where(m, 1.0 / np.log2(rank + 2.0), 0.0)
        # idcg: zero-gain padding contributes 0 at any position
        ideal = -np.sort(-gain, axis=1) / np.log2(np.arange(2, M + 2))[None, :]
        idcg = np.maximum(ideal.sum(axis=1), 1e-12)
        pm = m[:, :, None] & m[:, None, :]              # valid pair mask
        sd = s[:, :, None] - s[:, None, :]
        Sij = np.sign(yy[:, :, None] - yy[:, None, :])
        live = pm & (Sij != 0)
        with np.errstate(over="ignore"):
            rho = 1.0 / (1.0 + np.exp(sigma * sd * Sij))
        delta_ndcg = np.abs((gain[:, :, None] - gain[:, None, :])
                            * (disc[:, :, None] - disc[:, None, :])) \
            / idcg[:, None, None]
        gi = np.where(live, -sigma * rho * delta_ndcg * Sij, 0.0)
        hi = np.where(live, sigma * sigma * rho * (1 - rho) * delta_ndcg, 0.0)
        grow = gi.sum(axis=2)
        hrow = np.maximum(hi.sum(axis=2), 1e-9)
        multi = (cnt > 1)[:, None] & m                  # cnt<=1 groups stay 0
        g[ix[multi]] = grow[multi]
        h[ix[multi]] = hrow[multi]
    return g, h


class TrainConfig:
    def __init__(self, params: Dict, n_features: int):
        self.p = resolve_params(params)
        self.depth = _depth_for(self.p)
        self.n_features = n_features


class _PhaseProf:
    """Opt-in wall-clock phase breakdown (``MMLSPARK_TPU_GBDT_PROF=1``).

    ``mark`` blocks on the given arrays before reading the clock, so each
    phase's time includes its device work — profiling deliberately defeats
    async dispatch; production runs leave it off and pipeline.
    """

    def __init__(self):
        self.enabled = os.environ.get("MMLSPARK_TPU_GBDT_PROF", "0") == "1"
        self.t: Dict[str, float] = {}
        self._last = time.perf_counter()

    def mark(self, name: str, *sync):
        if not self.enabled:
            return
        for a in sync:
            # tpulint: disable=TPU001 — opt-in profiler: the fence IS the
            # measurement (off unless MMLSPARK_TPU_GBDT_PROF=1)
            jax.block_until_ready(a)
        now = time.perf_counter()
        self.t[name] = self.t.get(name, 0.0) + (now - self._last)
        _M_GBDT_PHASE.observe(now - self._last, phase=name)
        self._last = now

    def reset(self):
        if self.enabled:
            self._last = time.perf_counter()

    def report(self, n_iter: int):
        if self.enabled:
            print(json.dumps({"gbdt_phase_seconds":
                              {k: round(v, 3) for k, v in self.t.items()},
                              "n_iter": n_iter}), file=sys.stderr, flush=True)


def train(params: Dict,
          X: np.ndarray, y: np.ndarray,
          sample_weight: Optional[np.ndarray] = None,
          group: Optional[np.ndarray] = None,
          valid_sets: Optional[List[Tuple[np.ndarray, np.ndarray]]] = None,
          init_model: Optional[Booster] = None,
          mesh: Optional[Mesh] = None,
          callbacks: Optional[List[Callable]] = None,
          eval_log: Optional[List] = None,
          init_score: Optional[np.ndarray] = None,
          valid_init_scores: Optional[List[np.ndarray]] = None,
          valid_weights: Optional[List[np.ndarray]] = None) -> Booster:
    """Fit a GBDT. ``params`` uses LightGBM names (aliases accepted).

    ``init_score``: per-row starting margin (LightGBM ``init_score``) —
    boosting fits residuals on top of it, and, as in LightGBM, the fitted
    model's predictions do NOT include it (the caller re-adds their margin
    at scoring time). With ``valid_sets``, matching per-set margins must
    come in ``valid_init_scores`` (each Dataset carries its own
    init_score in LightGBM too) so eval metrics are computed at the right
    margin. ``valid_weights``: per-set sample weights for eval metrics
    (LightGBM's Dataset weights apply to its eval too)."""
    p = resolve_params(params)
    # keep X in its incoming float width — a HIGGS-scale float32 matrix must
    # not be silently doubled to float64 (binning only ever copies a sample
    # and per-column temporaries); integers upcast to float64 so large ids
    # (> 2^24) stay distinct. scipy-sparse X stays sparse end-to-end: the
    # binned uint8 matrix is the only dense artifact (parity:
    # LGBM_DatasetCreateFromCSR, DatasetAggregator.scala:441-465)
    from .binning import is_sparse
    sparse_X = is_sparse(X)
    if sparse_X:
        X = X.tocsr()
        if X.dtype.kind != "f":
            X = X.astype(np.float64)
        if p["categorical_feature"]:
            raise ValueError(
                "categorical_feature is not supported with sparse input "
                "(rank-encode the categorical columns before sparsifying, "
                "or pass a dense matrix)")
    else:
        X = np.asarray(X)
        if X.dtype.kind != "f":
            X = X.astype(np.float64)
    y = np.asarray(y, dtype=np.float64)
    n, F = X.shape
    w = (np.asarray(sample_weight, dtype=np.float64) if sample_weight is not None
         else np.ones(n))
    depth = _depth_for(p)
    # single source of truth for "rows shard over a mesh" — consulted by
    # both the chunked-upload gate and the sharding setup below
    will_shard = (mesh is not None
                  and p["tree_learner"] in ("data_parallel",
                                            "voting_parallel"))
    num_class = int(p["num_class"])
    objective_name = p["objective"]
    # boosting mode (parity: LightGBMParams.boostingType, LightGBMParams.scala:389-393)
    boosting = {"gbrt": "gbdt", "random_forest": "rf"}.get(
        str(p["boosting"]).lower(), str(p["boosting"]).lower())
    if boosting not in ("gbdt", "goss", "dart", "rf"):
        raise ValueError(f"boosting must be gbdt/goss/dart/rf, got {boosting!r}")
    if boosting == "goss" and p["bagging_freq"]:
        raise ValueError("GOSS replaces bagging; unset bagging_freq")
    if boosting == "rf":
        if not (p["bagging_freq"] and 0 < float(p["bagging_fraction"]) < 1):
            raise ValueError("rf mode needs bagging_freq > 0 and "
                             "0 < bagging_fraction < 1 (LightGBM's own rule)")
        if p["early_stopping_round"]:
            raise ValueError("rf averages over the full planned forest; "
                             "early stopping would bias the average")
        if init_model is not None:
            raise ValueError("rf mode cannot warm-start (the 1/T average "
                             "is defined over one forest)")
    is_multi = objective_name in ("multiclass", "softmax") and num_class > 1
    is_rank = objective_name == "lambdarank"
    linear_tree = bool(p["linear_tree"])
    if linear_tree:
        # LightGBM linear_tree restrictions apply here too: leaf models
        # regress on raw numerical features only
        if sparse_X:
            raise ValueError("linear_tree needs dense input (the leaf "
                             "models regress on raw feature values)")
        if p["categorical_feature"]:
            raise ValueError("linear_tree regresses on numerical features "
                             "only; drop categorical_feature")
        if p["tree_learner"] == "voting_parallel":
            raise ValueError("linear_tree + voting_parallel is not "
                             "supported; use data_parallel")
        mc = p["monotone_constraints"]
        if mc is not None and np.asarray(mc).size and np.asarray(mc).any():
            # the split search could mask on constant child values, but the
            # fitted leaf ridge models are unclamped — predictions would
            # silently violate the declared direction (LightGBM refuses
            # this combination too)
            raise ValueError("linear_tree cannot honor "
                             "monotone_constraints; drop one of them")
        if float(p["lambda_l1"]) != 0.0:
            raise ValueError("lambda_l1 applies to constant leaf values "
                             "only; linear_tree leaves are L2-regularized "
                             "via linear_lambda (set lambda_l1=0)")
        if float(p["path_smooth"]) != 0.0:
            raise ValueError("path_smooth smooths constant leaf outputs; "
                             "it has no linear-leaf counterpart here "
                             "(set path_smooth=0)")
    obj = get_objective(objective_name, num_class=num_class,
                        alpha=p["alpha"],
                        tweedie_variance_power=p["tweedie_variance_power"])

    # class-imbalance reweighting (LightGBM scale_pos_weight/is_unbalance):
    # folded into the sample weights so gradients, hessians, and
    # boost-from-average all see it consistently
    spw = float(p["scale_pos_weight"])
    if p["is_unbalance"] or spw != 1.0:
        if objective_name != "binary":
            raise ValueError("scale_pos_weight/is_unbalance apply to the "
                             "binary objective only")
        if p["is_unbalance"]:
            if spw != 1.0:
                raise ValueError("set either is_unbalance or "
                                 "scale_pos_weight, not both (LightGBM's "
                                 "own rule)")
            pos = float(np.sum(w * (y == 1)))
            neg = float(np.sum(w * (y != 1)))
            if pos <= 0.0:
                raise ValueError(
                    "is_unbalance: no positive examples (or zero positive "
                    "weight) — the auto ratio would be unbounded")
            spw = neg / pos
        w = w * np.where(y == 1, spw, 1.0)

    # step-level checkpoint/resume (beyond the reference's model-level
    # warm start): a run killed mid-training resumes from the last step
    ckpt = None
    resumed_iters = 0
    if p["checkpoint_dir"] and init_score is not None:
        # checkpoints persist only the booster; a resume could not
        # reconstruct the margin-adjusted score state
        raise ValueError("init_score cannot combine with step checkpoints")
    if p["checkpoint_dir"]:
        from ...utils.checkpoint import TrainingCheckpointer
        ckpt = TrainingCheckpointer(str(p["checkpoint_dir"]))
        latest = ckpt.latest()
        if latest is not None:
            _, files = latest
            meta = TrainingCheckpointer.read_json(files["meta.json"])
            resumed_iters = int(meta["completed_iterations"])
            init_model = Booster.from_string(
                TrainingCheckpointer.read_text(files["booster.txt"]))

    X_raw = X
    cat_encoder = None
    if p["categorical_feature"] or (init_model is not None
                                    and init_model.cat_encoder is not None):
        # label-ordered rank encoding (categorical.py): the static
        # approximation of LightGBM's per-node category-subset search;
        # warm starts reuse the prior booster's encoding (its trees split
        # in that rank space)
        from .categorical import CategoricalEncoder
        if sparse_X:
            raise ValueError("categorical encoding and sparse input cannot "
                             "combine (the warm-start model was trained "
                             "with categorical_feature)")
        if init_model is not None and init_model.cat_encoder is not None:
            cat_encoder = init_model.cat_encoder
        elif init_model is not None:
            # the init model's trees split raw values; appending trees that
            # split rank-encoded values would mix spaces undetectably
            raise ValueError(
                "categorical_feature set, but the warm-start model was "
                "trained without categorical encoding; retrain from "
                "scratch or drop categorical_feature")
        else:
            cat_encoder = CategoricalEncoder(
                p["categorical_feature"],
                cat_smooth=float(p["cat_smooth"]),
                min_data_per_group=int(p["min_data_per_group"])).fit(X, y)
        X = cat_encoder.transform(X)

    prof = _PhaseProf()
    prof.reset()
    mapper = BinMapper(max_bin=int(p["max_bin"]), seed=int(p["seed"]))
    bundle_tables = None
    n_bundle_bins = 0
    if sparse_X and p["enable_bundle"]:
        # EFB: mutually-exclusive sparse features share histogram columns
        # (LightGBM enable_bundle/max_conflict_rate); per-level histogram
        # passes and bin-matrix bytes shrink from F to n_bundles columns
        # (total bins — and the psum payload — stay ≈ constant)
        from .bundling import FeatureBundler
        from .trees import BundleTables
        mapper.fit(X)
        bundler = FeatureBundler(
            max_conflict_rate=float(p["max_conflict_rate"]),
            max_bundle_bins=int(p["max_bundle_bins"])).fit(X, mapper)
        if bundler.worthwhile(F):
            xb = bundler.transform(X, mapper)
            bundle_tables = BundleTables(
                jnp.asarray(bundler.bundle_of),
                jnp.asarray(bundler.offset_of),
                jnp.asarray(bundler.width_of),
                jnp.asarray(bundler.zero_bin))
            n_bundle_bins = bundler.n_bundle_bins
        else:
            xb = mapper.transform(X)
    else:
        mapper.fit(X)
        prof.mark("bin_fit")
        if not will_shard and not sparse_X and n >= (1 << 21):
            # chunked bin→upload pipeline: while chunk i transfers (async
            # device_put), chunk i+1 bins on the host — at HIGGS scale this
            # hides most of the h2d time behind the native binning loop,
            # and the full host-side binned matrix never materializes
            CHR = 1 << 21
            # tpulint: disable=TPU021 — single-device branch by
            # construction (``not will_shard`` above): the chunked upload
            # stages bins on the default device; the mesh path device_puts
            # rows under NamedSharding(mesh, P("data")) (row_sharding)
            parts = [jax.device_put(mapper.transform(X[lo:lo + CHR]))
                     for lo in range(0, n, CHR)]
            xb_dev_early = (jnp.concatenate(parts, axis=0)
                            if len(parts) > 1 else parts[0])
            xb = None
            prof.mark("bin_upload_overlap", xb_dev_early)
        else:
            xb = mapper.transform(X)
            prof.mark("bin_transform")
    n_bins = mapper.n_bins

    if init_model is not None and init_score is not None:
        raise ValueError("init_score cannot combine with a warm-start "
                         "model (the model already defines the margin)")
    if init_model is not None \
            and getattr(init_model, "is_linear", False) != linear_tree:
        raise ValueError("warm start must keep the leaf model family: "
                         "set linear_tree to match the init model")
    if init_model is not None:
        # dart mutates leaf values in place (scale_trees) — work on a deep
        # copy so the caller's model object is never changed under them
        booster = (init_model.truncated(init_model.num_trees)
                   if boosting == "dart" else init_model)
        base_score = booster.base_score
        # raw_score applies the encoder itself — feed the UN-encoded matrix
        # (sparse passes through; raw_score densifies in bounded chunks)
        scores = booster.raw_score(
            X_raw if X_raw.dtype == np.float32 else X_raw.astype(np.float32)
        ) - np.float32(base_score)
        init_trees = booster.num_trees
        init_arr = None
    else:
        init_trees = 0
        if init_score is not None:
            # per-row starting margin: boost-from-average is skipped
            # (LightGBM semantics) and predictions exclude the margin
            init_arr = np.asarray(init_score, dtype=np.float64)
            want = (n, num_class) if is_multi else (n,)
            if init_arr.shape != want:
                raise ValueError(f"init_score shape {init_arr.shape} != "
                                 f"{want}")
            base_score = 0.0
            scores = init_arr.copy()
        else:
            init_arr = None
            # LightGBM boost_from_average: the first margin is the
            # objective's optimal constant; off → boosting starts at 0
            base_score = 0.0 if (is_multi or is_rank
                                 or not p["boost_from_average"]) \
                else obj.init_score(y, w)
            scores = np.zeros((n, num_class) if is_multi else n)
        booster = Booster(depth, F, objective_name, base_score,
                          num_class if is_multi else 1)
        booster.cat_encoder = cat_encoder

    # device residency; shard rows when data-parallel over a mesh
    axis_name = None
    n_pad = n
    if will_shard:
        axis_name = "data"
        shards = mesh.shape[axis_name]
        n_pad = ((n + shards - 1) // shards) * shards
        row_sharding = NamedSharding(mesh, P("data"))
    if n_pad != n:
        pad = n_pad - n
        xb = np.concatenate([xb, np.zeros((pad, xb.shape[1]),
                                          dtype=xb.dtype)])
        y_pad = np.concatenate([y, np.zeros(pad)])
        w_pad = np.concatenate([w, np.zeros(pad)])
        scores = np.concatenate(
            [scores, np.zeros((pad,) + scores.shape[1:])], axis=0)
    else:
        y_pad, w_pad = y, w
    live = np.concatenate([np.ones(n), np.zeros(n_pad - n)])

    # scores live on device between iterations as the DELTA from
    # base_score: a host round-trip of the full score vector every iteration
    # dominates tunnel-bound training at HIGGS scale, and centering keeps
    # f32 accumulation exact-ish (leaf deltas are small; adding them into a
    # large absolute base like mean(y)~1e3 would round at ~6e-5 ULP each
    # iteration). grad inputs re-add base_score on device.
    init_pad = None
    if init_arr is not None:
        ip = (np.concatenate([init_arr,
                              np.zeros((n_pad - n,) + init_arr.shape[1:])])
              if n_pad != n else init_arr)
        init_pad = jnp.asarray(ip, jnp.float32)
        if axis_name is not None:
            init_pad = jax.device_put(init_pad, row_sharding)
    scores = jnp.asarray(scores, jnp.float32)
    if axis_name is not None:
        scores = jax.device_put(scores, row_sharding)
        xb_d = jax.device_put(jnp.asarray(xb), row_sharding)
        y_d = jax.device_put(jnp.asarray(y_pad), row_sharding)
        w_d = jax.device_put(jnp.asarray(w_pad), row_sharding)
        live_d = jax.device_put(jnp.asarray(live), row_sharding)
    else:
        xb_d = xb_dev_early if xb is None else jnp.asarray(xb)
        y_d = jnp.asarray(y_pad)
        w_d = jnp.asarray(w_pad)
        live_d = jnp.asarray(live)
    prof.mark("upload", xb_d, y_d, w_d, live_d, scores)

    # kernel lane layout, once per RUN (the per-level transpose it replaces
    # cost a full read+write of the bin matrix each level of each tree)
    xb_lanes_d = None
    if axis_name is None:
        from ...ops.pallas_kernels import (histogram_enabled,
                                           pallas_preferred,
                                           prepare_bins_lanes,
                                           tree_row_block)
        kbins = int(n_bundle_bins) if n_bundle_bins else int(n_bins)
        if histogram_enabled() and pallas_preferred(
                n_pad, 2 ** max(depth - 1, 0), kbins):
            # row block must match build_tree's tree_row_block choice (the
            # kernel validates npad divisibility against it)
            xb_lanes_d = prepare_bins_lanes(
                xb_d, row_block=tree_row_block(2 ** max(depth - 1, 0),
                                               kbins))

    X_lin = None
    if linear_tree:
        # linear leaves regress on RAW values — the binned matrix loses
        # them, so the float32 feature matrix also lives on device
        xf = np.asarray(X, dtype=np.float32)
        if n_pad != n:
            xf = np.concatenate(
                [xf, np.zeros((n_pad - n, F), np.float32)])
        X_lin = jnp.asarray(xf)
        if axis_name is not None:
            X_lin = jax.device_put(X_lin, row_sharding)

    # PV-Tree voting (LightGBM tree_learner=voting_parallel, topK param —
    # params/LightGBMParams.scala:23-30): comm per level 2k×B instead of F×B
    voting_k = (int(p["top_k"]) if p["tree_learner"] == "voting_parallel"
                else 0)
    ffbn = float(p["feature_fraction_bynode"])
    if not 0.0 < ffbn <= 1.0:
        raise ValueError(f"feature_fraction_bynode must be in (0, 1], "
                         f"got {ffbn}")
    if float(p["path_smooth"]) < 0.0:
        raise ValueError("path_smooth must be >= 0")
    build_kwargs = dict(depth=depth, n_bins=int(n_bins),
                        voting_k=voting_k,
                        lam=float(p["lambda_l2"]) + 1e-10,
                        alpha=float(p["lambda_l1"]),
                        min_gain=float(p["min_gain_to_split"]),
                        min_child_weight=float(p["min_sum_hessian_in_leaf"]),
                        min_data_in_leaf=float(p["min_data_in_leaf"]),
                        bundles=bundle_tables,
                        n_bundle_bins=int(n_bundle_bins),
                        extra_trees=bool(p["extra_trees"]),
                        ff_bynode=ffbn,
                        path_smooth=float(p["path_smooth"]),
                        hist_dtype=("bfloat16" if p["use_quantized_grad"]
                                    else None))
    if p["extra_trees"]:
        # per-feature populated bin counts (incl. missing bin 0): the
        # random-threshold draw samples each feature's own range
        build_kwargs["feat_bins"] = jnp.asarray(
            [len(b) + 1 for b in mapper.upper_bounds], jnp.int32)
    ic_raw = p["interaction_constraints"]
    if ic_raw:
        # list of allowed feature groups; a branch may only combine
        # features that share at least one group, and features in no
        # group are unusable (LightGBM interaction_constraints semantics)
        groups = np.zeros((len(ic_raw), F), dtype=bool)
        for gi, grp in enumerate(ic_raw):
            idx = np.asarray(list(grp), dtype=np.int64)
            if idx.size == 0:
                raise ValueError("interaction_constraints groups must be "
                                 "non-empty")
            if idx.min() < 0 or idx.max() >= F:
                raise ValueError(
                    f"interaction_constraints[{gi}] has feature indices "
                    f"outside [0, {F})")
            groups[gi, idx] = True
        build_kwargs["ic_groups"] = jnp.asarray(groups)

    mono_raw = p["monotone_constraints"]
    if mono_raw is not None and np.asarray(mono_raw).size:
        # validate RAW values before the int cast (int32 would silently
        # zero fractional entries — a vacuous constraint, not an error)
        raw = np.asarray(mono_raw)
        if raw.shape != (F,):
            raise ValueError(
                f"monotone_constraints needs one entry per feature "
                f"({F}), got shape {raw.shape}")
        if not np.isin(raw, (-1, 0, 1)).all():
            raise ValueError("monotone_constraints entries must be "
                             "-1, 0, or +1")
        mono = raw.astype(np.int32)
        if cat_encoder is not None:
            cat_set = set(cat_encoder.feature_indices)
            cat_idx = [int(i) for i in np.nonzero(mono)[0]
                       if int(i) in cat_set]
            if cat_idx:
                # the encoder rewrites these columns to label-ordered
                # ranks; a "monotone in the raw value" promise would be
                # silently vacuous (LightGBM rejects this combination too)
                raise ValueError(
                    f"monotone_constraints on categorical features "
                    f"{cat_idx} are not supported")
        if mono.any():
            build_kwargs["monotone"] = jnp.asarray(mono)

    if axis_name is None:
        def build(xb_, g_, h_, live_, fmask, key, lanes=None):
            # lanes passed as an ARG (not closed over): a closure-captured
            # device array would be baked into the jitted program as a
            # constant
            return build_tree(xb_, g_, h_, live_, feature_mask=fmask,
                              rng=key, xb_lanes=lanes, **build_kwargs)
    else:
        n_int = 2 ** depth - 1

        @functools.partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P("data", None), P("data"), P("data"), P("data"),
                      P(None), P(None)),
            out_specs=(P(None), P(None), P(None), P("data"), P(None), P(None)),
            check_vma=False)
        def _build_sharded(xb_, g_, h_, live_, fmask, key):
            # key replicated: every shard draws identical random masks, so
            # extra_trees/by-node sampling stays bitwise-deterministic
            # across the mesh (same invariant as the psum'd histogram)
            return build_tree(xb_, g_, h_, live_, feature_mask=fmask,
                              rng=key, axis_name=axis_name, **build_kwargs)

        def build(xb_, g_, h_, live_, fmask, key, lanes=None):
            # per-shard lane layouts are prepared inside build_tree (once
            # per tree); a replicated global layout is ignored here
            return _build_sharded(xb_, g_, h_, live_, fmask, key)

    lin_fit = None
    if linear_tree:
        from .trees import fit_linear_leaves
        lin_kwargs = dict(n_leaf=2 ** depth,
                          lam_lin=float(p["linear_lambda"]),
                          lam=float(p["lambda_l2"]) + 1e-10)
        if axis_name is None:
            def lin_fit(Xr, li, g_, h_, live_, pf):
                return fit_linear_leaves(Xr, li, g_, h_, live_, pf,
                                         **lin_kwargs)
        else:
            @functools.partial(
                jax.shard_map, mesh=mesh,
                in_specs=(P("data", None), P("data"), P("data"), P("data"),
                          P("data"), P(None)),
                out_specs=(P(None), P("data")), check_vma=False)
            def lin_fit(Xr, li, g_, h_, live_, pf):
                # normal equations psum inside, so coefficients are
                # identical on every shard (bitwise-deterministic like
                # the histogram path)
                return fit_linear_leaves(Xr, li, g_, h_, live_, pf,
                                         axis_name=axis_name, **lin_kwargs)

    def _pred_stack(feats_a, thr_a, leaf_a, Xq, coefs_a=None, pf_a=None):
        """Tree-stack prediction, constant or linear leaves."""
        from .trees import (predict_trees_any, predict_trees_linear_any,
                            predict_trees_linear_multi_any)
        if linear_tree:
            if is_multi:
                # class-major tree order (t % K) holds for every stack
                # this sees: full prefixes, one-iteration groups, dart's
                # whole-group drops
                return predict_trees_linear_multi_any(
                    feats_a, thr_a, coefs_a, pf_a, Xq, depth=depth,
                    num_class=num_class)
            return predict_trees_linear_any(feats_a, thr_a, coefs_a, pf_a,
                                            Xq, depth=depth)
        return predict_trees_any(feats_a, thr_a, leaf_a, Xq, depth=depth)

    booster.fit_params = {"learning_rate": float(p["learning_rate"]),
                          "lambda_l2": float(p["lambda_l2"])}
    grad_fn = jax.jit(obj.grad_hess) if obj.grad_hess is not None else None
    lr = float(p["learning_rate"])
    rng = np.random.default_rng(int(p["seed"]))
    base_key = jax.random.PRNGKey(int(p["seed"]))
    n_iter = max(0, int(p["num_iterations"]) - resumed_iters)
    ckpt_iv = int(p["checkpoint_interval"]) if ckpt is not None else 0

    # eval bookkeeping. LightGBM accepts a METRIC LIST: every metric is
    # computed and logged per iteration; early stopping follows the FIRST
    # (LightGBM's first_metric_only=True discipline — the stable subset of
    # its any-metric default, which couples the stop decision to list
    # order anyway)
    m_raw = p["metric"]
    metric_list = (list(m_raw) if isinstance(m_raw, (list, tuple))
                   else [m_raw])
    if not metric_list:
        metric_list = ["auto"]      # empty list = objective default (LGBM)
    resolved = [get_metric(m if m not in ("auto", "") else "",
                           objective_name) for m in metric_list]
    metric_name, (metric_fn, higher_better) = resolved[0]
    best_score = -np.inf if higher_better else np.inf
    best_iter = 0
    best_model = None               # dart: snapshot at each new best
    patience = int(p["early_stopping_round"])
    valid_scores = None
    if valid_sets:
        valid_sets = [(vx if is_sparse(vx) else np.asarray(vx), vy)
                      for vx, vy in valid_sets]
        if init_score is not None and valid_init_scores is None:
            raise ValueError(
                "init_score with valid_sets needs valid_init_scores "
                "(one margin array per validation set) — eval at margin "
                "zero would select a wrong best_iteration")
        if init_trees:
            valid_scores = [booster.raw_score(
                vx if is_sparse(vx) else np.asarray(vx, dtype=np.float32))
                .astype(np.float64) for vx, _vy in valid_sets]
        else:
            valid_scores = [np.full(
                (vx.shape[0], num_class) if is_multi else vx.shape[0],
                base_score, dtype=np.float64) for vx, _vy in valid_sets]
        if valid_weights is not None:
            if len(valid_weights) != len(valid_sets):
                raise ValueError(
                    f"valid_weights has {len(valid_weights)} entries for "
                    f"{len(valid_sets)} valid_sets")
            valid_weights = [np.asarray(w, dtype=np.float64)
                             for w in valid_weights]
            for vi, (w_, (vx_, _vy)) in enumerate(
                    zip(valid_weights, valid_sets)):
                if len(w_) != vx_.shape[0]:
                    raise ValueError(
                        f"valid_weights[{vi}] has {len(w_)} rows for a "
                        f"{vx_.shape[0]}-row validation set")
        valid_margins = None
        if valid_init_scores is not None:
            if len(valid_init_scores) != len(valid_sets):
                raise ValueError(
                    f"valid_init_scores has {len(valid_init_scores)} "
                    f"entries for {len(valid_sets)} valid_sets")
            valid_margins = []
            for vi, vis in enumerate(valid_init_scores):
                vis = np.asarray(vis, dtype=np.float64)
                if vis.shape != valid_scores[vi].shape:
                    raise ValueError(
                        f"valid_init_scores[{vi}] shape {vis.shape} != "
                        f"{valid_scores[vi].shape}")
                valid_margins.append(vis)
                valid_scores[vi] = valid_scores[vi] + vis
        if cat_encoder is not None:
            # the per-iteration eval path feeds trees directly (bypassing
            # booster.raw_score), so hand it rank-encoded matrices once
            if any(is_sparse(vx) for vx, _ in valid_sets):
                raise ValueError("sparse validation sets cannot combine "
                                 "with categorical_feature")
            valid_sets = [(cat_encoder.transform(np.asarray(vx)), vy)
                          for vx, vy in valid_sets]

    X_f32 = ((X.astype(np.float32) if sparse_X
              else np.asarray(X, dtype=np.float32))
             if boosting == "dart" else None)
    rf_scale = 1.0 / max(1, int(p["num_iterations"])) if boosting == "rf" \
        else None
    K_trees = num_class if is_multi else 1

    # -- fused/deferred fast path -------------------------------------------
    # For the plain-gbdt configuration (the HIGGS north-star shape) the whole
    # iteration — gradients, masking, tree build, score update — is ONE
    # jitted dispatch, and the fitted tree arrays stay on device until after
    # the loop. The Python loop then never blocks: iterations pipeline
    # back-to-back on the chip and per-dispatch/transfer round-trips (70 ms
    # each over a tunneled link) amortize away, where the materializing path
    # paid ~5 of them per iteration. Excluded modes keep the general path:
    # goss (host top-k), dart (host drop bookkeeping), rf (constant-margin
    # grads), lambdarank (host pairwise grads), multiclass (vmap build),
    # linear_tree (host path_features), eval/callback/checkpoint consumers
    # (need the booster per iteration).
    defer = (boosting == "gbdt" and not is_rank and not is_multi
             and not linear_tree and not valid_sets and not callbacks
             and ckpt is None and grad_fn is not None)
    fused_step = None
    if defer:
        lr_fast = lr     # gbdt: tree_scale == 1.0 always

        @functools.partial(jax.jit, donate_argnums=(0,))
        def fused_step(scores_, xb_, y_, w_, gh_w_, live_it_, fmask_, key_,
                       lanes_):
            g_, h_ = obj.grad_hess(scores_ + jnp.float32(base_score),
                                   y_, w_)
            # gh_w always carries the live-row factor (it is live_d or a
            # bagged subset of it), so one multiply applies both masks
            g_ = g_ * gh_w_
            h_ = h_ * gh_w_
            feats_, thr_, leaf_, node_, gains_, covers_ = build(
                xb_, g_, h_, live_it_, fmask_, key_, lanes_)
            scores2 = scores_ + jnp.take(leaf_, node_) * lr_fast
            return scores2, feats_, thr_, leaf_, gains_, covers_

    pending: List[Tuple] = []
    fmask_all = jnp.ones(F, dtype=bool)     # hoisted: constant across iters

    def _bagging_masks(it):
        """(live_it, gh_w) for this iteration. Shared by the fused and the
        general loop paths so the rng stream stays in lockstep — a given
        seed must yield identical row subsets either way."""
        if p["bagging_freq"] and p["bagging_fraction"] < 1.0 \
                and it % int(p["bagging_freq"]) == 0:
            keep = rng.random(n_pad) < float(p["bagging_fraction"])
            live_it = live_d * jnp.asarray(keep.astype(np.float64))
            return live_it, live_it
        return live_d, live_d

    def _feature_mask():
        """Per-tree feature subsample mask (same rng-lockstep contract)."""
        if float(p["feature_fraction"]) < 1.0:
            k = max(1, int(round(F * float(p["feature_fraction"]))))
            sel = rng.choice(F, size=k, replace=False)
            m = np.zeros(F, dtype=bool)
            m[sel] = True
            return jnp.asarray(m)
        return fmask_all

    for it in range(n_iter):
        prof.reset()
        if defer:
            # one fused dispatch; tree arrays stay on device (materialized
            # in one batch after the loop)
            live_it, gh_w = _bagging_masks(it)
            fmask = _feature_mask()
            it_key = jax.random.fold_in(base_key, resumed_iters + it)
            scores, feats, thr_bin, leaf_val, gains, covers = fused_step(
                scores, xb_d, y_d, w_d, gh_w, live_it, fmask, it_key,
                xb_lanes_d)
            pending.append((feats, thr_bin, leaf_val, gains, covers))
            prof.mark("fused_step", scores)
            continue
        # -- dart: pick an iteration subset to drop, score without it ------
        drop_idx = None
        drop_pred = None
        tree_scale = 1.0
        if boosting == "dart":
            n_groups = booster.num_trees // K_trees
            drop_groups = np.array([], dtype=np.int64)
            if n_groups and rng.random() >= float(p["skip_drop"]):
                cand = np.nonzero(rng.random(n_groups)
                                  < float(p["drop_rate"]))[0]
                md = int(p["max_drop"])
                if md > 0 and len(cand) > md:
                    cand = np.sort(rng.choice(cand, size=md, replace=False))
                drop_groups = cand
            if len(drop_groups):
                k_drop = len(drop_groups)
                tree_scale = 1.0 / (k_drop + 1.0)   # DART-paper weights
                drop_idx = (drop_groups[:, None] * K_trees
                            + np.arange(K_trees)[None, :]).ravel()
                lin = booster.linear if linear_tree else None
                dp = _pred_stack(
                    booster.feats[drop_idx], booster.thr_raw[drop_idx],
                    booster.leaf_values[drop_idx], X_f32,
                    coefs_a=lin["coefs"][drop_idx] if lin else None,
                    pf_a=lin["pf"][drop_idx] if lin else None)
                drop_pred = jnp.pad(
                    dp, ((0, n_pad - n),) + ((0, 0),) * (dp.ndim - 1))
                if axis_name is not None:
                    # dp is committed to one device by predict_trees; the
                    # subtraction partner is mesh-sharded
                    drop_pred = jax.device_put(drop_pred, row_sharding)
        elif boosting == "rf":
            tree_scale = rf_scale

        # trees fit gradients at: scores minus dropped trees (dart), the
        # constant init score (rf: every tree fits the same residual and
        # the 1/T-scaled sum is the forest average), else current scores
        scores_for_grad = scores + jnp.float32(base_score)
        if drop_pred is not None:
            scores_for_grad = scores_for_grad - drop_pred
        elif boosting == "rf":
            # rf: every tree fits the same residual — at the per-row margin
            # when init_score was given, else at the constant init score
            scores_for_grad = (init_pad if init_pad is not None
                               else jnp.full_like(scores, base_score))

        # gradients
        if is_rank:
            g_np, h_np = _lambdarank_grad(
                np.asarray(scores_for_grad[:n], dtype=np.float64), y, group)
            g_np, h_np = g_np * w, h_np * w
            if n_pad != n:
                g_np = np.concatenate([g_np, np.zeros(n_pad - n)])
                h_np = np.concatenate([h_np, np.zeros(n_pad - n)])
            g_d, h_d = jnp.asarray(g_np), jnp.asarray(h_np)
            if axis_name is not None:
                g_d = jax.device_put(g_d, row_sharding)
                h_d = jax.device_put(h_d, row_sharding)
        else:
            g_d, h_d = grad_fn(scores_for_grad, y_d, w_d)
            g_d = g_d * live_d[..., None] if is_multi else g_d * live_d
            h_d = h_d * live_d[..., None] if is_multi else h_d * live_d
        prof.mark("grad", g_d, h_d)

        # goss / bagging / feature sampling. ``live_it`` is the 0/1 row
        # membership (drives min_data_in_leaf counts and stored covers);
        # ``gh_w`` additionally carries GOSS's gradient amplification —
        # LightGBM amplifies only grad/hess, never the count channel
        if boosting == "goss":
            # gradient-based one-side sampling: keep the top_rate fraction
            # by |grad|, sample other_rate of the rest amplified by
            # (1-a)/b so the small-gradient mass stays unbiased
            g_host = np.asarray(g_d)[:n]
            gabs = (np.abs(g_host).sum(axis=1) if is_multi
                    else np.abs(g_host))
            a, b = float(p["top_rate"]), float(p["other_rate"])
            top_n = min(n, max(1, int(math.ceil(a * n))))
            rest_n = max(0, int(math.ceil(b * n)))
            order = np.argpartition(-gabs, top_n - 1)
            sel_bin = np.zeros(n_pad)
            sel_amp = np.zeros(n_pad)
            sel_bin[order[:top_n]] = 1.0
            sel_amp[order[:top_n]] = 1.0
            rest = order[top_n:]
            if rest_n and len(rest):
                samp = rng.choice(rest, size=min(rest_n, len(rest)),
                                  replace=False)
                sel_bin[samp] = 1.0
                sel_amp[samp] = (1.0 - a) / max(b, 1e-12)
            live_it = live_d * jnp.asarray(sel_bin)
            gh_w = live_d * jnp.asarray(sel_amp)
        else:
            live_it, gh_w = _bagging_masks(it)
        fmask = _feature_mask()
        mask_g = gh_w if not is_multi else gh_w[:, None]
        # rf has no shrinkage — each tree enters at 1/T so the sum is the
        # forest average; dart additionally scales the new tree by 1/(k+1)
        lr_eff = (1.0 if boosting == "rf" else lr) * tree_scale

        it_key = jax.random.fold_in(base_key, resumed_iters + it)
        new_coefs = new_pf = None
        if is_multi:
            g_mk = g_d * mask_g
            h_mk = h_d * mask_g

            def build_k(gk, hk, kk):
                return build(xb_d, gk, hk, live_it, fmask, kk)
            feats_k, thr_k, leaf_k, node_k, gains_k, covers_k = jax.vmap(
                build_k, in_axes=(1, 1, 0))(
                    g_mk, h_mk, jax.random.split(it_key, num_class))
            feats_np = np.asarray(feats_k)      # (K, n_int)
            thr_raw_k = np.stack([
                _thr_bins_to_raw(feats_np[k], np.asarray(thr_k)[k], mapper,
                                 int(n_bins)) for k in range(num_class)])
            if linear_tree:
                # per-class linear leaves: each class's tree fits its own
                # leaf ridge models on that class's gradients; trees stay
                # class-major so t % K routes predictions (trees.py
                # predict_trees_linear_multi_any)
                from .trees import path_features
                pf_k = np.stack([path_features(feats_np[k], depth)
                                 for k in range(num_class)])
                coefs_list, contrib_cols = [], []
                for k in range(num_class):
                    beta, contrib = lin_fit(X_lin, node_k[k], g_mk[:, k],
                                            h_mk[:, k], live_it,
                                            jnp.asarray(pf_k[k]))
                    coefs_list.append(
                        np.asarray(beta, np.float32) * np.float32(lr_eff))
                    contrib_cols.append(contrib)
                coefs_k = np.stack(coefs_list)       # (K, n_leaf, D+1)
                # per-class leaf value view: the coefs' bias (constant
                # fallback) for linear leaves
                vals_k = coefs_k[:, :, -1]
                scores = scores + jnp.stack(contrib_cols, axis=1) * lr_eff
                new_coefs = coefs_k
                new_pf = pf_k
            else:
                vals_k = np.asarray(leaf_k) * lr_eff
                # score update via leaf assignment, on device
                upd = jax.vmap(jnp.take)(leaf_k, node_k).T * lr_eff
                scores = scores + upd
            for k in range(num_class):
                lv = np.zeros((num_class, 2 ** depth), dtype=np.float32)
                lv[k] = vals_k[k]
                booster.append_tree(
                    feats_np[k], thr_raw_k[k], lv,
                    np.asarray(gains_k)[k], np.asarray(covers_k)[k],
                    **(dict(coefs=coefs_k[k], pf=pf_k[k])
                       if linear_tree else {}))
            new_feats = feats_np
            new_thr = thr_raw_k
            new_leaf = np.stack([
                np.eye(num_class, dtype=np.float32)[k][:, None]
                * np.asarray(vals_k[k])[None, :] for k in range(num_class)])
        else:
            g_m = g_d * gh_w
            h_m = h_d * gh_w
            feats, thr_bin, leaf_val, node_rel, gains, covers = build(
                xb_d, g_m, h_m, live_it, fmask, it_key, xb_lanes_d)
            prof.mark("build", feats, leaf_val, node_rel)
            feats_np = np.asarray(feats)
            thr_raw = _thr_bins_to_raw(feats_np, np.asarray(thr_bin), mapper,
                                       int(n_bins))
            if linear_tree:
                from .trees import path_features
                pf_np = path_features(feats_np, depth)
                beta, contrib = lin_fit(X_lin, node_rel, g_m, h_m, live_it,
                                        jnp.asarray(pf_np))
                coefs_np = np.asarray(beta, np.float32) * np.float32(lr_eff)
                # leaf_values keep the bias (the constant-fallback view)
                leaf_np = coefs_np[:, -1].copy()
                booster.append_tree(feats_np, thr_raw, leaf_np,
                                    np.asarray(gains), np.asarray(covers),
                                    coefs=coefs_np, pf=pf_np)
                scores = scores + contrib * lr_eff
                new_coefs = coefs_np[None]
                new_pf = pf_np[None]
            else:
                leaf_np = np.asarray(leaf_val) * lr_eff
                booster.append_tree(feats_np, thr_raw, leaf_np,
                                    np.asarray(gains), np.asarray(covers))
                prof.mark("host_tree")
                scores = scores + jnp.take(leaf_val, node_rel) * lr_eff
                prof.mark("score_update", scores)
            new_feats = feats_np[None]
            new_thr = thr_raw[None]
            new_leaf = leaf_np[None]

        if drop_idx is not None:
            # dart normalization: dropped trees re-enter at k/(k+1); the
            # running scores still hold them at full weight, so pull the
            # 1/(k+1) difference back out (grad was taken at scores - drop)
            k_drop = len(drop_idx) // K_trees
            booster.scale_trees(drop_idx, k_drop * tree_scale)
            scores = scores - drop_pred * tree_scale

        # eval + early stopping (uses this iteration's trees directly so the
        # booster's lazy tree stack is not re-materialized every round)
        if valid_sets:
            results = []
            per_set_log = (eval_log is not None
                           and (len(resolved) > 1 or len(valid_sets) > 1))
            for vi, (vx, vy) in enumerate(valid_sets):
                if drop_idx is not None:
                    # past trees were just re-scaled (dart drop) —
                    # incremental tracking is invalid for this round,
                    # recompute from the full tree stack; no-drop rounds
                    # keep the O(1)-tree incremental path
                    lin = booster.linear if linear_tree else None
                    valid_scores[vi] = base_score + _pred_stack(
                        booster.feats, booster.thr_raw, booster.leaf_values,
                        vx, coefs_a=lin["coefs"] if lin else None,
                        pf_a=lin["pf"] if lin else None)
                    if valid_margins is not None:
                        valid_scores[vi] = valid_scores[vi] \
                            + valid_margins[vi]
                else:
                    delta = _pred_stack(new_feats, new_thr, new_leaf, vx,
                                        coefs_a=new_coefs, pf_a=new_pf)
                    valid_scores[vi] = valid_scores[vi] + delta
                pred = np.asarray(obj.transform(jnp.asarray(valid_scores[vi])))
                vw = (valid_weights[vi] if valid_weights is not None
                      else np.ones(len(vy)))
                vy_arr = np.asarray(vy)
                # non-primary metrics only cost compute when something
                # consumes them (the per-set log)
                use = resolved if per_set_log else resolved[:1]
                vals = {mname: mfn(vy_arr, pred, vw)
                        for mname, (mfn, _hb) in use}
                results.append(vals[metric_name])
                if per_set_log:
                    for mname, mv in vals.items():
                        eval_log.append({"iteration": it, "valid_set": vi,
                                         mname: mv})
            primary = results[0]
            if eval_log is not None:
                # tagged so consumers can tell the early-stopping summary
                # from the self-describing per-set entries (which repeat
                # this value for set 0 when per_set_log is on)
                entry = {"iteration": it, metric_name: primary}
                if per_set_log:
                    entry["primary"] = True
                eval_log.append(entry)
            improved = primary > best_score if higher_better else primary < best_score
            if improved:
                best_score = primary
                best_iter = it + 1
                if boosting == "dart":
                    # later drop iterations rescale EARLIER trees in place,
                    # so a truncation taken at patience time would not be
                    # the model that scored best — snapshot it now
                    # (truncated() copies arrays)
                    best_model = booster.truncated(
                        init_trees + best_iter * K_trees)
            elif patience and (it + 1 - best_iter) >= patience:
                booster.best_iteration = best_iter
                final = (best_model if best_model is not None
                         else booster.truncated(
                             init_trees + best_iter * K_trees))
                if ckpt is not None:
                    # mark the run complete (full budget) so an idempotent
                    # rerun returns this truncated booster, not a resumed one
                    ckpt.save(int(p["num_iterations"]), {
                        "booster.txt": final.to_string(),
                        "meta.json": {"completed_iterations":
                                      int(p["num_iterations"])},
                    })
                return final
        if callbacks:
            scores_np = np.asarray(scores, dtype=np.float64) + base_score
            for cb in callbacks:
                cb(it, booster, scores_np)
        if ckpt_iv and (it + 1) % ckpt_iv == 0:
            ckpt.save(resumed_iters + it + 1, {
                "booster.txt": booster.to_string(),
                "meta.json": {"completed_iterations": resumed_iters + it + 1},
            })

    if pending:
        # materialize the deferred device-side tree stack: stack in chunks
        # (bounding trace size), one host transfer per chunk instead of ~5
        # per iteration, then one vectorized bin→raw threshold conversion
        CH = 64
        cols = [[], [], [], [], []]
        for lo in range(0, len(pending), CH):
            grp = pending[lo:lo + CH]
            for i in range(5):
                cols[i].append(np.asarray(jnp.stack([t[i] for t in grp])))
        feats_all, thr_all, leaf_all, gains_all, covers_all = (
            np.concatenate(c) for c in cols)
        thr_raw_all = _thr_bins_to_raw(feats_all, thr_all, mapper,
                                       int(n_bins))
        leaf_all = leaf_all.astype(np.float32) * np.float32(lr)
        for t in range(feats_all.shape[0]):
            booster.append_tree(feats_all[t], thr_raw_all[t], leaf_all[t],
                                gains_all[t], covers_all[t])
        prof.mark("materialize")

    if ckpt is not None and n_iter > 0:
        ckpt.save(resumed_iters + n_iter, {
            "booster.txt": booster.to_string(),
            "meta.json": {"completed_iterations": resumed_iters + n_iter},
        })
    prof.report(n_iter)
    if valid_sets and n_iter == 0:
        # fully-completed checkpointed run rerun idempotently: the eval loop
        # never executed, so keep the restored booster's best_iteration
        pass
    else:
        # ABSOLUTE iterations (warm-start init included): predict's
        # num_iteration cap slices the whole-model tree prefix
        booster.best_iteration = (init_trees // K_trees + best_iter
                                  if valid_sets
                                  else resumed_iters + n_iter)
    if patience and best_model is not None:
        # dart reaching the iteration budget without the patience branch
        # firing: later drop rounds rescaled the best iteration's trees in
        # place, so only the snapshot reproduces best_score — a truncation
        # of the final stack would not (unlike every other boosting mode)
        return best_model
    return booster
