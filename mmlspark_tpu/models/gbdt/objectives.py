"""GBDT objectives and eval metrics.

Parity surface: LightGBM objectives exposed through the reference
(``lightgbm/.../params/TrainParams.scala:10-100`` renders
``objective=binary|multiclass|regression|...``; custom objectives via
``FObjTrait`` gradients, ``TrainUtils.scala:67-90``). Each objective maps
raw scores → (grad, hess) as pure jax functions so the boosting loop stays
inside one jit.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["get_objective", "get_metric", "OBJECTIVES", "METRICS",
           "Objective"]


class Objective:
    """grad/hess + score→prediction transform + #model outputs per row."""

    def __init__(self, grad_hess: Callable, transform: Callable,
                 n_scores: int = 1, init_score: Optional[Callable] = None):
        self.grad_hess = grad_hess
        self.transform = transform
        self.n_scores = n_scores
        self.init_score = init_score or (lambda y, w: 0.0)


# -- regression --------------------------------------------------------------

def _l2_grad(scores, y, w):
    g = scores - y
    h = jnp.ones_like(scores)
    return g * w, h * w


def _l1_grad(scores, y, w):
    g = jnp.sign(scores - y)
    h = jnp.ones_like(scores)  # LightGBM uses hessian 1 for L1
    return g * w, h * w


def _huber_grad(delta):
    def f(scores, y, w):
        r = scores - y
        g = jnp.where(jnp.abs(r) <= delta, r, delta * jnp.sign(r))
        h = jnp.ones_like(scores)
        return g * w, h * w
    return f


def _quantile_grad(alpha):
    def f(scores, y, w):
        r = scores - y
        g = jnp.where(r >= 0, 1.0 - alpha, -alpha) * 2 * 0.5  # slope of pinball
        g = jnp.where(r >= 0, (1.0 - alpha), -alpha)
        h = jnp.ones_like(scores)
        return g * w, h * w
    return f


def _poisson_grad(scores, y, w):
    mu = jnp.exp(scores)
    return (mu - y) * w, mu * w


def _tweedie_grad(rho):
    def f(scores, y, w):
        mu = jnp.exp(scores)
        g = -y * jnp.exp((1.0 - rho) * scores) + jnp.exp((2.0 - rho) * scores)
        h = (-y * (1.0 - rho) * jnp.exp((1.0 - rho) * scores)
             + (2.0 - rho) * jnp.exp((2.0 - rho) * scores))
        return g * w, h * w
    return f


def _gamma_grad(scores, y, w):
    g = 1.0 - y * jnp.exp(-scores)
    h = y * jnp.exp(-scores)
    return g * w, h * w


# -- classification ----------------------------------------------------------

def _binary_grad(scores, y, w):
    p = jax.nn.sigmoid(scores)
    return (p - y) * w, jnp.maximum(p * (1 - p), 1e-16) * w


def _multiclass_grad(scores, y, w):
    # scores: (n, K); y int labels (n,)
    p = jax.nn.softmax(scores, axis=-1)
    onehot = jax.nn.one_hot(y.astype(jnp.int32), scores.shape[-1],
                            dtype=scores.dtype)
    g = (p - onehot) * w[:, None]
    h = jnp.maximum(p * (1 - p), 1e-16) * 2.0 * w[:, None]
    return g, h


OBJECTIVES: Dict[str, Callable[..., Objective]] = {
    "regression": lambda **kw: Objective(
        _l2_grad, lambda s: s,
        init_score=lambda y, w: float(np.average(y, weights=w))),
    "regression_l1": lambda **kw: Objective(
        _l1_grad, lambda s: s,
        init_score=lambda y, w: float(np.median(y))),
    "huber": lambda alpha=0.9, **kw: Objective(_huber_grad(alpha), lambda s: s),
    "quantile": lambda alpha=0.5, **kw: Objective(
        _quantile_grad(alpha), lambda s: s,
        init_score=lambda y, w: float(np.quantile(y, alpha))),
    "poisson": lambda **kw: Objective(
        _poisson_grad, jnp.exp,
        init_score=lambda y, w: float(np.log(max(np.average(y, weights=w), 1e-9)))),
    "tweedie": lambda tweedie_variance_power=1.5, **kw: Objective(
        _tweedie_grad(tweedie_variance_power), jnp.exp,
        init_score=lambda y, w: float(np.log(max(np.average(y, weights=w), 1e-9)))),
    "gamma": lambda **kw: Objective(
        _gamma_grad, jnp.exp,
        init_score=lambda y, w: float(np.log(max(np.average(y, weights=w), 1e-9)))),
    "binary": lambda **kw: Objective(
        _binary_grad, jax.nn.sigmoid,
        init_score=lambda y, w: float(np.log(max(np.average(y, weights=w), 1e-9)
                                             / max(1 - np.average(y, weights=w), 1e-9))),
    ),
    "multiclass": lambda num_class=2, **kw: Objective(
        _multiclass_grad, lambda s: jax.nn.softmax(s, axis=-1),
        n_scores=num_class),
    "lambdarank": lambda **kw: Objective(None, jax.nn.sigmoid),  # special-cased
}

# aliases (parity with LightGBM names)
OBJECTIVES["l2"] = OBJECTIVES["mse"] = OBJECTIVES["mean_squared_error"] = \
    OBJECTIVES["regression"]
OBJECTIVES["l1"] = OBJECTIVES["mae"] = OBJECTIVES["regression_l1"]
OBJECTIVES["softmax"] = OBJECTIVES["multiclass"]


def get_objective(name: str, **kw) -> Objective:
    if name not in OBJECTIVES:
        raise ValueError(f"unknown objective {name!r}; known: {sorted(OBJECTIVES)}")
    return OBJECTIVES[name](**kw)


# -- eval metrics (host-side numpy; used for early stopping & logging) -------

def _auc(y, p, w):
    order = np.argsort(-p)
    y, w = np.asarray(y)[order], np.asarray(w)[order]
    tp = np.cumsum(y * w)
    fp = np.cumsum((1 - y) * w)
    tot_p, tot_n = tp[-1], fp[-1]
    if tot_p == 0 or tot_n == 0:
        return 0.5
    # trapezoid over ROC
    tpr = np.concatenate([[0], tp / tot_p])
    fpr = np.concatenate([[0], fp / tot_n])
    return float(np.trapezoid(tpr, fpr))


def _binary_logloss(y, p, w):
    p = np.clip(p, 1e-15, 1 - 1e-15)
    return float(np.average(-(y * np.log(p) + (1 - y) * np.log(1 - p)),
                            weights=w))


def _multi_logloss(y, p, w):
    p = np.clip(p, 1e-15, 1.0)
    ll = -np.log(p[np.arange(len(y)), np.asarray(y, dtype=int)])
    return float(np.average(ll, weights=w))


def _ndcg_at(k):
    def f(y, p, w, groups=None):
        if groups is None:
            groups = np.array([len(y)])
        scores, start = [], 0
        for g in groups:
            g = int(g)
            yy, pp = np.asarray(y[start:start + g]), p[start:start + g]
            start += g
            if g == 0:
                continue
            order = np.argsort(-pp)[:k]
            gains = (2.0 ** yy[order] - 1) / np.log2(np.arange(2, len(order) + 2))
            ideal_order = np.argsort(-yy)[:k]
            ideal = (2.0 ** yy[ideal_order] - 1) / np.log2(np.arange(2, len(ideal_order) + 2))
            scores.append(gains.sum() / ideal.sum() if ideal.sum() > 0 else 1.0)
        return float(np.mean(scores)) if scores else 1.0
    return f


METRICS: Dict[str, Tuple[Callable, bool]] = {
    # name → (fn(y, pred, w), higher_is_better)
    "l2": (lambda y, p, w: float(np.average((p - y) ** 2, weights=w)), False),
    "rmse": (lambda y, p, w: float(np.sqrt(np.average((p - y) ** 2, weights=w))), False),
    "l1": (lambda y, p, w: float(np.average(np.abs(p - y), weights=w)), False),
    "auc": (_auc, True),
    "binary_logloss": (_binary_logloss, False),
    "multi_logloss": (_multi_logloss, False),
    "binary_error": (lambda y, p, w: float(np.average((p > 0.5) != (y > 0.5),
                                                      weights=w)), False),
    "multi_error": (lambda y, p, w: float(np.average(np.argmax(p, 1) != y,
                                                     weights=w)), False),
    "ndcg": (_ndcg_at(10), True),
}

_DEFAULT_METRIC = {"regression": "l2", "regression_l1": "l1", "huber": "l2",
                   "quantile": "l2", "poisson": "l2", "tweedie": "l2",
                   "gamma": "l2", "binary": "binary_logloss",
                   "multiclass": "multi_logloss", "lambdarank": "ndcg"}


def get_metric(name: str, objective: Optional[str] = None):
    if name in ("", "auto", None) and objective:
        name = _DEFAULT_METRIC.get(objective, "l2")
    if name not in METRICS:
        raise ValueError(f"unknown metric {name!r}; known: {sorted(METRICS)}")
    return name, METRICS[name]
