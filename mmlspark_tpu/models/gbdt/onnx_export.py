"""Export a trained GBDT booster as an ONNX TreeEnsemble graph.

Parity surface: the reference's flagship ONNX demo converts a trained
LightGBM booster to ONNX (``onnxmltools.convert_lightgbm`` →
``TreeEnsembleClassifier``) and serves it through ``ONNXModel``
(``website/docs/features/onnx/about.md``). Here the exporter reads our own
booster's flat fixed-depth arrays directly — every internal node becomes a
``BRANCH_LEQ`` row (missing tracks true, matching the trainer's NaN→left
rule), disabled nodes become always-true splits against +inf, and leaves
carry the class/target weights.

The resulting bytes round-trip through ``onnx.convert_model`` /
``ONNXModel`` — and, being spec-compliant ai.onnx.ml, load in onnxruntime
or any other ONNX consumer.
"""

from __future__ import annotations

import numpy as np

from ...onnx.builder import (make_graph, make_model, make_node,
                             make_tensor_value_info)

__all__ = ["booster_to_onnx"]


def _node_tables(booster):
    """Flat per-node attribute lists from the (T, 2^d-1)/(T, 2^d) arrays."""
    depth = booster.depth
    n_int = 2 ** depth - 1
    n_all = 2 ** (depth + 1) - 1
    feats = np.asarray(booster.feats)
    thr = np.asarray(booster.thr_raw, np.float64)
    T = feats.shape[0]

    tids, nids, fids, vals, modes, tnid, fnid, miss = \
        [], [], [], [], [], [], [], []
    for t in range(T):
        for n in range(n_all):
            tids.append(t)
            nids.append(n)
            if n < n_int:
                f = int(feats[t, n])
                modes.append("BRANCH_LEQ")
                # disabled node (f < 0): the trainer always descends left —
                # an always-true split (x <= +inf, NaN tracks true too)
                fids.append(max(f, 0))
                vals.append(float(thr[t, n]) if f >= 0 else float("inf"))
                tnid.append(2 * n + 1)
                fnid.append(2 * n + 2)
                miss.append(1)          # NaN goes left = the true branch
            else:
                modes.append("LEAF")
                fids.append(0)
                vals.append(0.0)
                tnid.append(0)
                fnid.append(0)
                miss.append(0)
    return {"nodes_treeids": tids, "nodes_nodeids": nids,
            "nodes_featureids": fids, "nodes_values": vals,
            "nodes_modes": modes, "nodes_truenodeids": tnid,
            "nodes_falsenodeids": fnid,
            "nodes_missing_value_tracks_true": miss}


def booster_to_onnx(booster, n_features: int = None) -> bytes:
    """Serialize ``booster`` (models.gbdt.booster.Booster) to ONNX bytes.

    Classifiers (objective binary/multiclass*) become
    ``TreeEnsembleClassifier`` with outputs ``label`` (int64) and
    ``probabilities``; everything else becomes ``TreeEnsembleRegressor``
    with output ``variable`` — the names onnxmltools emits for LightGBM.
    """
    if booster.cat_encoder is not None:
        raise ValueError(
            "booster splits in a label-encoded categorical space; ONNX "
            "TreeEnsemble consumers would see raw features. Export only "
            "supports numeric-feature boosters.")
    if getattr(booster, "is_linear", False):
        raise ValueError(
            "ONNX TreeEnsemble has no linear-leaf representation "
            "(onnxmltools rejects LightGBM linear_tree models too)")
    depth = booster.depth
    n_int = 2 ** depth - 1
    n_leaf = 2 ** depth
    F = n_features or booster.n_features
    lv = np.asarray(booster.leaf_values, np.float64)
    T = lv.shape[0]
    nodes_attrs = _node_tables(booster)
    classify = booster.objective.startswith(("binary", "multiclass"))

    if classify:
        K = booster.num_class if booster.num_class > 1 else 2
        ctids, cnids, cids, cws = [], [], [], []
        for t in range(T):
            for leaf in range(n_leaf):
                node_id = n_int + leaf
                if booster.num_class > 1:
                    for k in range(booster.num_class):
                        ctids.append(t)
                        cnids.append(node_id)
                        cids.append(k)
                        cws.append(float(lv[t, k, leaf]))
                else:
                    ctids.append(t)
                    cnids.append(node_id)
                    cids.append(1)      # binary: weights score class 1
                    cws.append(float(lv[t, leaf]))
        post = "SOFTMAX" if booster.num_class > 1 else "LOGISTIC"
        base = [float(booster.base_score)] * \
            (booster.num_class if booster.num_class > 1 else 1)
        node = make_node(
            "TreeEnsembleClassifier", ["features"],
            ["label", "probabilities"], domain="ai.onnx.ml",
            classlabels_int64s=list(range(K)),
            post_transform=post, base_values=base,
            class_treeids=ctids, class_nodeids=cnids, class_ids=cids,
            class_weights=cws, **nodes_attrs)
        outputs = [make_tensor_value_info("label", np.int64, ["N"]),
                   make_tensor_value_info("probabilities", np.float32,
                                          ["N", K])]
    else:
        ttids, tnids_, tids_, tws = [], [], [], []
        for t in range(T):
            for leaf in range(n_leaf):
                ttids.append(t)
                tnids_.append(n_int + leaf)
                tids_.append(0)
                tws.append(float(lv[t, leaf]))
        node = make_node(
            "TreeEnsembleRegressor", ["features"], ["variable"],
            domain="ai.onnx.ml", n_targets=1,
            base_values=[float(booster.base_score)],
            aggregate_function="SUM", post_transform="NONE",
            target_treeids=ttids, target_nodeids=tnids_,
            target_ids=tids_, target_weights=tws, **nodes_attrs)
        outputs = [make_tensor_value_info("variable", np.float32,
                                          ["N", 1])]

    g = make_graph(
        [node], "gbdt",
        [make_tensor_value_info("features", np.float32, ["N", F])],
        outputs)
    return make_model(g, opset=17, extra_opsets={"ai.onnx.ml": 3})
