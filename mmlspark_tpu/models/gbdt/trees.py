"""Histogram tree construction — the XLA compute core of the GBDT trainer.

Replaces LightGBM's native distributed tree learner (histogram build +
socket-ring allreduce + split/partition inside ``LGBM_BoosterUpdateOneIter``,
reached via ``lightgbm/.../booster/LightGBMBooster.scala:351-361``) with a
TPU formulation:

* trees grow **depth-wise** with a complete binary tree of static depth, so
  every step is fixed-shape: one histogram scatter-add per level
  (``segment_sum`` over (node, bin) ids, vmapped over features), one
  vectorized split search, one gather-based row routing. No data-dependent
  control flow — the whole ``build_tree`` jits.
* data parallelism = ``psum`` of the (nodes, F, B, 3) histogram over the mesh
  axis — the exact collective LightGBM's ``tree_learner=data_parallel``
  performs over its socket ring (``params/LightGBMParams.scala:16-21``).
* early-stopped nodes route all rows left with a sentinel split, so the
  complete-tree shape is preserved and leaf values computed at the bottom
  level are correct for stopped subtrees too.

Trees store raw-value thresholds (converted from bins by the caller) so
prediction is independent of the bin mapper; NaN always routes left,
mirroring the missing-value bin 0 used during training.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["TreeArrays", "BundleTables", "build_tree", "predict_trees",
           "predict_leaf_indices", "path_features", "fit_linear_leaves",
           "predict_trees_linear", "predict_trees_linear_any",
           "predict_trees_linear_multi_any"]


class BundleTables(NamedTuple):
    """EFB decode tables (``bundling.FeatureBundler``), all (F,) int32:
    feature → its bundle, slot offset inside the bundle, bin count, and
    default (zero-value) bin."""
    bundle_of: jnp.ndarray
    offset_of: jnp.ndarray
    width_of: jnp.ndarray
    zero_bin: jnp.ndarray


class TreeArrays(NamedTuple):
    """One fitted tree in complete-binary-tree layout (depth D).

    feat: (2^D - 1,) int32 — split feature per internal node, -1 = leaf/stub
    thr_bin: (2^D - 1,) int32 — split bin (left iff bin <= thr_bin)
    thr_raw: (2^D - 1,) float32 — raw threshold (left iff x <= thr or NaN)
    leaf_value: (2^D,) float32 — values at bottom level
    """
    feat: jnp.ndarray
    thr_bin: jnp.ndarray
    thr_raw: jnp.ndarray
    leaf_value: jnp.ndarray


def _level_histogram(xb, node_rel, g, h, w_count, n_nodes, n_bins, axis_name,
                     bins_lanes=None, stats_dtype=None, row_block=0):
    """(n,F) bins × per-row (g,h,count) → (n_nodes, F, B, 3) histogram.

    Two interchangeable builders: the Pallas MXU kernel
    (``ops/pallas_kernels.py``, used on TPU) and an XLA ``segment_sum``
    fallback. Both replace LightGBM's native C++ histogram construction.
    ``bins_lanes`` is the kernel's precomputed (F, 1, npad) layout;
    ``stats_dtype`` bfloat16 runs the kernel matmul at native MXU rate.
    """
    from ...ops.pallas_kernels import (histogram_enabled,
                                       level_histogram_pallas,
                                       pallas_preferred)
    if histogram_enabled() and pallas_preferred(xb.shape[0], n_nodes, n_bins):
        from ...utils.device import is_tpu
        # force-on off-TPU runs the interpreter (Mosaic can't compile there)
        hist = level_histogram_pallas(xb, node_rel, g, h, w_count,
                                      n_nodes, n_bins,
                                      interpret=not is_tpu(),
                                      bins_lanes=bins_lanes,
                                      stats_dtype=stats_dtype,
                                      row_block=row_block)
    else:
        data = jnp.stack([g, h, w_count], axis=-1)  # (n, 3)

        def per_feature(bins_col):
            seg = node_rel * n_bins + bins_col.astype(jnp.int32)
            return jax.ops.segment_sum(data, seg, num_segments=n_nodes * n_bins)

        hist = jax.vmap(per_feature, in_axes=1)(xb)      # (F, nodes*B, 3)
        hist = jnp.transpose(hist.reshape(xb.shape[1], n_nodes, n_bins, 3),
                             (1, 0, 2, 3))               # (nodes, F, B, 3)
    if axis_name is not None:
        hist = jax.lax.psum(hist, axis_name)
    return hist


def _debundle(hist_b, bundles: "BundleTables", n_bins: int):
    """Bundled histogram (nodes, n_bundles, B_bundle, 3) → exact
    per-feature histogram (nodes, F, n_bins, 3).

    Non-default bins are a static gather (feature f's slot range); the
    default bin is reconstructed by subtraction — node totals (the sum of
    any one bundle's bins: every row lands in exactly one bin per bundle)
    minus f's non-default stats. Exact for conflict-free bundles; a
    conflict row is counted at the losing feature's default bin, the EFB
    approximation.
    """
    F = bundles.bundle_of.shape[0]
    pos = bundles.offset_of[:, None] + jnp.arange(n_bins)[None, :]   # (F, B)
    pos = jnp.clip(pos, 0, hist_b.shape[2] - 1)
    gathered = hist_b[:, bundles.bundle_of[:, None], pos, :]  # (nodes,F,B,3)
    validpos = (jnp.arange(n_bins)[None, :]
                < bundles.width_of[:, None])                  # (F, B)
    gathered = gathered * validpos[None, :, :, None]
    total = hist_b[:, 0, :, :].sum(axis=1)                    # (nodes, 3)
    default = total[:, None, :] - gathered.sum(axis=2)        # (nodes, F, 3)
    zslot = (jnp.arange(n_bins)[None, :]
             == bundles.zero_bin[:, None])                    # (F, B)
    return jnp.where(zslot[None, :, :, None], default[:, :, None, :],
                     gathered)


def _smooth(raw, cnt, parent, path_smooth):
    """LightGBM path smoothing: pull a node's output toward its parent's
    with ``path_smooth`` pseudo-counts (root smooths toward 0)."""
    t = cnt / jnp.maximum(cnt + path_smooth, 1e-12)
    return t * raw + (1.0 - t) * parent


def _split_gains(hist, lam, min_gain, min_child_weight, min_data_in_leaf,
                 feature_mask, monotone=None, bounds=None,
                 cand_mask=None, path_smooth=0.0, parent_value=None):
    """hist (nodes, F, B, 3) → masked split gains (nodes, F, B); invalid
    candidates are -inf. ``feature_mask`` may be (F,) or per-node (nodes, F)
    (the latter after a voting gather, where the column set differs per
    node). ``monotone`` (F,) in {-1, 0, +1} with ``bounds`` (lo, hi) each
    (nodes,) masks candidates whose (bound-clamped) child values violate
    the feature's direction — LightGBM monotone_constraints semantics.
    ``cand_mask`` (nodes, F, B) restricts the threshold candidates
    (extra_trees samples one random bin per node×feature). With
    ``path_smooth > 0`` gains are computed at the SMOOTHED child outputs
    (``parent_value`` (nodes,) = each node's own smoothed output, so
    children smooth toward it) — at 0 this reduces to the closed form."""
    G = hist[..., 0]
    H = hist[..., 1]
    C = hist[..., 2]
    GL = jnp.cumsum(G, axis=-1)
    HL = jnp.cumsum(H, axis=-1)
    CL = jnp.cumsum(C, axis=-1)
    Gt = GL[..., -1:]
    Ht = HL[..., -1:]
    Ct = CL[..., -1:]
    GR, HR, CR = Gt - GL, Ht - HL, Ct - CL

    def score(g, h):
        return (g * g) / (h + lam)

    if path_smooth > 0.0:
        # gain at the smoothed outputs: lg(g,h,w) = -(g·w + ½(h+λ)w²);
        # with w = -g/(h+λ) (no smoothing) this is ½·g²/(h+λ), the
        # closed form below
        pv = parent_value[:, None, None]
        wL = _smooth(-GL / (HL + lam), CL, pv, path_smooth)
        wR = _smooth(-GR / (HR + lam), CR, pv, path_smooth)

        def lg(g, h, w):
            return -(g * w + 0.5 * (h + lam) * w * w)

        gain = lg(GL, HL, wL) + lg(GR, HR, wR) - lg(Gt, Ht, pv)
    else:
        gain = 0.5 * (score(GL, HL) + score(GR, HR) - score(Gt, Ht))
    valid = ((HL >= min_child_weight) & (HR >= min_child_weight)
             & (CL >= min_data_in_leaf) & (CR >= min_data_in_leaf)
             & (gain > min_gain))
    if feature_mask is not None:
        fm = feature_mask if feature_mask.ndim == 2 else feature_mask[None, :]
        valid = valid & fm[:, :, None]
    if cand_mask is not None:
        valid = valid & cand_mask
    if monotone is not None:
        lo, hi = bounds                              # (nodes,)
        vL = jnp.clip(-GL / (HL + lam), lo[:, None, None], hi[:, None, None])
        vR = jnp.clip(-GR / (HR + lam), lo[:, None, None], hi[:, None, None])
        m = monotone[None, :, None]                  # (1, F, 1)
        valid = valid & (m.astype(jnp.float32) * (vR - vL) >= 0)
    return jnp.where(valid, gain, -jnp.inf)


def _voting_splits(local_hist, axis_name, k, lam, min_gain,
                   min_child_weight, min_data_in_leaf, feature_mask):
    """PV-Tree voting split finder over LOCAL per-shard histograms.

    Every shard nominates its local top-k features per node, votes psum,
    and only the global top-2k features' histogram columns are all-reduced
    (the PV-Tree guarantee: the true best feature is among the top-2k with
    high probability). Vote counts and the gathered histogram are identical
    on every shard after psum, so split decisions stay bitwise-identical
    across the mesh — the invariant the data-parallel path also maintains.
    Returns (best_feat, best_bin, best_gain, level_cover).
    """
    n_nodes, F, _B, _ = local_hist.shape
    kk = min(int(k), F)
    # nominate from UNCONSTRAINED local gains: the global count/hessian
    # thresholds don't apply to a 1/shards-sized local histogram (a node
    # whose every shard fails them would nominate all -inf → top_k degrades
    # to index order, a data-free vote); validity is enforced on the GLOBAL
    # histogram below
    lgain = _split_gains(local_hist, lam, -jnp.inf, 0.0, 0.0, feature_mask)
    per_feat = lgain.max(axis=-1)                              # (nodes, F)
    top_local = jax.lax.top_k(per_feat, kk)[1]                 # (nodes, kk)
    votes = jnp.zeros((n_nodes, F), jnp.float32).at[
        jnp.arange(n_nodes)[:, None], top_local].add(1.0)
    votes = jax.lax.psum(votes, axis_name)
    sel = jax.lax.top_k(votes, min(2 * kk, F))[1]              # (nodes, 2k)
    hist_sel = jnp.take_along_axis(local_hist, sel[:, :, None, None], axis=1)
    hist_sel = jax.lax.psum(hist_sel, axis_name)   # comm: 2k×B, not F×B
    fm_sel = feature_mask[sel] if feature_mask is not None else None
    bf_s, bb, bg = _find_splits(hist_sel, lam, min_gain, min_child_weight,
                                min_data_in_leaf, fm_sel)
    bf = jnp.where(
        bf_s >= 0,
        jnp.take_along_axis(sel, jnp.clip(bf_s, 0, sel.shape[1] - 1)[:, None],
                            axis=1)[:, 0].astype(jnp.int32),
        -1)
    level_cover = jax.lax.psum(local_hist[:, 0, :, 2].sum(axis=-1), axis_name)
    return bf, bb, bg, level_cover


def _chosen_child_values(hist, bf, bb, lam, lo, hi):
    """Clamped left/right child values at each node's chosen (feat, bin).
    hist (nodes, F, B, 3); bf/bb (nodes,); lo/hi (nodes,) → (vL, vR, mid)."""
    nodes, F, B, _ = hist.shape
    f = jnp.clip(bf, 0, F - 1)
    sel = jnp.take_along_axis(hist, f[:, None, None, None], axis=1)[:, 0]
    G = jnp.cumsum(sel[..., 0], axis=-1)              # (nodes, B)
    H = jnp.cumsum(sel[..., 1], axis=-1)
    b = jnp.clip(bb, 0, B - 1)
    GL = jnp.take_along_axis(G, b[:, None], axis=1)[:, 0]
    HL = jnp.take_along_axis(H, b[:, None], axis=1)[:, 0]
    GR, HR = G[:, -1] - GL, H[:, -1] - HL
    vL = jnp.clip(-GL / (HL + lam), lo, hi)
    vR = jnp.clip(-GR / (HR + lam), lo, hi)
    return vL, vR, 0.5 * (vL + vR)


def _find_splits(hist, lam, min_gain, min_child_weight, min_data_in_leaf,
                 feature_mask, monotone=None, bounds=None,
                 cand_mask=None, path_smooth=0.0, parent_value=None):
    """hist (nodes, F, B, 3) → best (gain, feat, bin) per node."""
    gain = _split_gains(hist, lam, min_gain, min_child_weight,
                        min_data_in_leaf, feature_mask,
                        monotone=monotone, bounds=bounds,
                        cand_mask=cand_mask, path_smooth=path_smooth,
                        parent_value=parent_value)
    flat = gain.reshape(gain.shape[0], -1)           # (nodes, F*B)
    best = jnp.argmax(flat, axis=-1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=-1)[:, 0]
    n_bins = hist.shape[2]
    best_feat = (best // n_bins).astype(jnp.int32)
    best_bin = (best % n_bins).astype(jnp.int32)
    ok = jnp.isfinite(best_gain)
    return (jnp.where(ok, best_feat, -1),
            jnp.where(ok, best_bin, n_bins),         # sentinel: all rows left
            jnp.where(ok, best_gain, 0.0))


@functools.partial(jax.jit, static_argnames=("depth", "n_bins", "axis_name",
                                             "voting_k", "n_bundle_bins",
                                             "extra_trees", "ff_bynode",
                                             "path_smooth", "hist_dtype"))
def build_tree(xb: jnp.ndarray, g: jnp.ndarray, h: jnp.ndarray,
               sample_weight_count: jnp.ndarray,
               depth: int, n_bins: int,
               lam: float = 1e-3, alpha: float = 0.0, min_gain: float = 0.0,
               min_child_weight: float = 1e-3, min_data_in_leaf: float = 1.0,
               feature_mask: Optional[jnp.ndarray] = None,
               axis_name: Optional[str] = None, voting_k: int = 0,
               bundles: Optional[BundleTables] = None,
               n_bundle_bins: int = 0,
               monotone: Optional[jnp.ndarray] = None,
               rng: Optional[jnp.ndarray] = None,
               extra_trees: bool = False, ff_bynode: float = 1.0,
               path_smooth: float = 0.0,
               ic_groups: Optional[jnp.ndarray] = None,
               feat_bins: Optional[jnp.ndarray] = None,
               xb_lanes: Optional[jnp.ndarray] = None,
               hist_dtype: Optional[str] = None):
    """Grow one depth-`depth` tree. All shapes static; jits once per config.

    xb: (n, F) int bins — or, with ``bundles``, the (n, n_bundles) EFB
    matrix whose histogram is debundled back to per-feature space before
    split finding (splits, masks, voting, and thresholds always speak
    original features); g/h: (n,) gradients/hessians (already weighted);
    sample_weight_count: (n,) 1.0 for live rows, 0.0 for padding/bagged-out.
    Returns (feat, thr_bin, leaf_value, leaf_index_per_row).

    ``voting_k > 0`` with an ``axis_name`` enables PV-Tree voting-parallel
    (LightGBM ``tree_learner=voting_parallel``, ``topK`` —
    ``params/LightGBMParams.scala:23-30``): each shard nominates its local
    top-k features per node from its LOCAL histogram, the votes psum, and
    only the global top-2k features' histograms are all-reduced — per-level
    comm drops from F×B to 2k×B.
    """
    n = xb.shape[0]
    F = bundles.bundle_of.shape[0] if bundles is not None else xb.shape[1]
    n_internal = 2 ** depth - 1
    feats = jnp.full(n_internal, -1, dtype=jnp.int32)
    thrs = jnp.full(n_internal, n_bins, dtype=jnp.int32)
    gains = jnp.zeros(n_internal, dtype=jnp.float32)
    covers = jnp.zeros(2 ** (depth + 1) - 1, dtype=jnp.float32)
    node_rel = jnp.zeros(n, dtype=jnp.int32)
    use_voting = voting_k > 0 and axis_name is not None and 2 * voting_k < F
    if monotone is not None and use_voting:
        raise ValueError("monotone_constraints + voting_parallel is not "
                         "supported (constraint masking needs the full "
                         "histogram; use data_parallel)")
    if use_voting and (extra_trees or ff_bynode < 1.0 or path_smooth > 0.0
                       or ic_groups is not None):
        raise ValueError("extra_trees/feature_fraction_bynode/path_smooth/"
                         "interaction_constraints need per-node candidate "
                         "masking over the full histogram; use "
                         "tree_learner=data_parallel")
    # per-node value bounds inherited down the tree (LightGBM
    # monotone_constraints): candidates violating a feature's direction
    # are masked in the gain search, children tighten around the split's
    # mid value, leaf values clamp into their node's interval
    lo = jnp.full((1,), -jnp.inf) if monotone is not None else None
    hi = jnp.full((1,), jnp.inf) if monotone is not None else None
    # path smoothing carries each node's PARENT's smoothed output down the
    # tree (root's parent output is 0 — LightGBM path_smooth semantics)
    pp = jnp.zeros((1,)) if path_smooth > 0.0 else None
    # interaction constraints carry the set of still-compatible groups per
    # node (a group stays compatible iff it contains every feature used on
    # the path); allowed features = union of compatible groups, so features
    # in no group are never usable — LightGBM interaction_constraints
    compat = (jnp.ones((1, ic_groups.shape[0]), dtype=bool)
              if ic_groups is not None else None)

    # one lane-layout transpose per TREE (not per level); callers that hold
    # the bin matrix across iterations pass ``xb_lanes`` precomputed so the
    # cost is paid once per RUN. The row block is sized for the deepest
    # level (``tree_row_block``) so one layout serves every level within
    # the kernel's VMEM budget.
    from ...ops.pallas_kernels import (histogram_enabled, pallas_preferred,
                                       prepare_bins_lanes, tree_row_block)
    kbins = n_bundle_bins if bundles is not None else n_bins
    rb = tree_row_block(2 ** max(depth - 1, 0), kbins)
    if (xb_lanes is None and histogram_enabled()
            and pallas_preferred(n, 2 ** max(depth - 1, 0), kbins)):
        xb_lanes = prepare_bins_lanes(xb, row_block=rb)

    def level_hist(n_nodes, psum_axis):
        if bundles is None:
            return _level_histogram(xb, node_rel, g, h, sample_weight_count,
                                    n_nodes, n_bins, psum_axis,
                                    bins_lanes=xb_lanes,
                                    stats_dtype=hist_dtype, row_block=rb)
        # bundled scatter-add (and, data-parallel, the psum) run in the
        # narrow bundle space; the exact per-feature view is a gather
        hist_b = _level_histogram(xb, node_rel, g, h, sample_weight_count,
                                  n_nodes, n_bundle_bins, psum_axis,
                                  bins_lanes=xb_lanes,
                                  stats_dtype=hist_dtype, row_block=rb)
        return _debundle(hist_b, bundles, n_bins)

    leaf_stats = None           # (2^depth, 3) [G, H, count] when derivable
    for d in range(depth):
        n_nodes = 2 ** d
        level_off = 2 ** d - 1
        # per-level randomized masks (extra_trees thresholds, by-node
        # feature draws) — keys fold in the level so every level redraws
        cand = None
        if extra_trees:
            # sample each feature's candidate within ITS populated bin
            # range (feat_bins (F,) = per-feature bin count incl. the
            # missing bin) — a global [0, n_bins) draw would leave
            # low-cardinality features with an almost-always-empty right
            # child (LightGBM samples per-feature ranges too)
            u = jax.random.uniform(jax.random.fold_in(rng, 2 * d),
                                   (n_nodes, F))
            bin_hi = (jnp.maximum(feat_bins - 1, 1)[None, :]
                      if feat_bins is not None
                      else jnp.full((1, F), max(n_bins - 1, 1)))
            r = jnp.minimum((u * bin_hi).astype(jnp.int32), bin_hi - 1)
            cand = jnp.arange(n_bins)[None, None, :] == r[:, :, None]
        fm_level = feature_mask
        if ic_groups is not None:
            allowed = (compat[:, :, None] & ic_groups[None, :, :]).any(axis=1)
            if fm_level is not None:
                fm_b = (fm_level if fm_level.ndim == 2 else fm_level[None, :])
                allowed = allowed & fm_b
            fm_level = allowed                           # (n_nodes, F)
        if ff_bynode < 1.0:
            kk = max(1, int(round(F * ff_bynode)))
            u = jax.random.uniform(jax.random.fold_in(rng, 2 * d + 1),
                                   (n_nodes, F))
            if fm_level is not None:
                fm_b = (fm_level if fm_level.ndim == 2
                        else fm_level[None, :])
                u = jnp.where(fm_b, u, -1.0)     # draw from survivors only
            kth = jax.lax.top_k(u, kk)[0][:, -1:]
            node_mask = u >= kth
            if fm_level is not None:
                node_mask = node_mask & fm_b
            fm_level = node_mask
        if use_voting:
            local = level_hist(n_nodes, None)
            bf, bb, bg, level_cover = _voting_splits(
                local, axis_name, voting_k, lam, min_gain, min_child_weight,
                min_data_in_leaf, feature_mask)
        else:
            hist = level_hist(n_nodes, axis_name)
            level_cover = hist[:, 0, :, 2].sum(axis=-1)  # counts per node
            node_val = None
            if path_smooth > 0.0:
                # each node's own smoothed output: raw optimum over its
                # totals (feature 0's bins partition the node's rows),
                # smoothed toward the carried parent output
                Gt = hist[:, 0, :, 0].sum(axis=-1)
                Ht = hist[:, 0, :, 1].sum(axis=-1)
                node_val = _smooth(-Gt / (Ht + lam), level_cover, pp,
                                   path_smooth)
            bf, bb, bg = _find_splits(hist, lam, min_gain, min_child_weight,
                                      min_data_in_leaf, fm_level,
                                      monotone=monotone,
                                      bounds=(lo, hi)
                                      if monotone is not None else None,
                                      cand_mask=cand,
                                      path_smooth=path_smooth,
                                      parent_value=node_val)
        if d == depth - 1 and not use_voting:
            # bottom-level leaf stats fall out of the last level's histogram
            # and chosen splits — left child = cumsum at the split bin,
            # right = node total minus left (LightGBM's parent-minus-sibling
            # identity) — replacing two O(n) segment-sum scatters with
            # (nodes, B) arithmetic. Stub nodes route all rows left via the
            # thr = n_bins sentinel (clipped to the last bin: left = total).
            f_sel = jnp.clip(bf, 0, F - 1)
            sel = jnp.take_along_axis(
                hist, f_sel[:, None, None, None], axis=1)[:, 0]  # (n, B, 3)
            cs = jnp.cumsum(sel, axis=1)
            b_sel = jnp.clip(bb, 0, cs.shape[1] - 1)
            left = jnp.take_along_axis(cs, b_sel[:, None, None],
                                       axis=1)[:, 0]             # (n, 3)
            right = cs[:, -1] - left
            leaf_stats = jnp.stack([left, right], axis=1) \
                .reshape(2 * n_nodes, 3)
        covers = jax.lax.dynamic_update_slice(covers, level_cover, (level_off,))
        feats = jax.lax.dynamic_update_slice(feats, bf, (level_off,))
        thrs = jax.lax.dynamic_update_slice(thrs, bb, (level_off,))
        gains = jax.lax.dynamic_update_slice(gains, bg.astype(jnp.float32),
                                             (level_off,))
        # route rows: bin <= thr → left. Stub splits have thr = n_bins → left.
        row_feat = jnp.clip(bf[node_rel], 0, F - 1)
        if bundles is None:
            row_bin = jnp.take_along_axis(
                xb, row_feat[:, None].astype(jnp.int32), axis=1)[:, 0] \
                .astype(jnp.int32)
        else:
            # decode the split feature's bin from its bundle column: in
            # the feature's slot range → offset-shifted bin, else default
            bcol = jnp.take_along_axis(
                xb, bundles.bundle_of[row_feat][:, None], axis=1)[:, 0] \
                .astype(jnp.int32)
            rel = bcol - bundles.offset_of[row_feat]
            row_bin = jnp.where(
                (rel >= 0) & (rel < bundles.width_of[row_feat]),
                rel, bundles.zero_bin[row_feat])
        go_right = row_bin > bb[node_rel]
        node_rel = node_rel * 2 + go_right.astype(jnp.int32)
        if monotone is not None:
            vL, vR, mid = _chosen_child_values(hist, bf, bb, lam, lo, hi)
            m_node = jnp.where(bf >= 0,
                               monotone[jnp.clip(bf, 0, F - 1)], 0)
            left_lo = jnp.where(m_node < 0, jnp.maximum(lo, mid), lo)
            left_hi = jnp.where(m_node > 0, jnp.minimum(hi, mid), hi)
            right_lo = jnp.where(m_node > 0, jnp.maximum(lo, mid), lo)
            right_hi = jnp.where(m_node < 0, jnp.minimum(hi, mid), hi)
            lo = jnp.stack([left_lo, right_lo], axis=1).reshape(-1)
            hi = jnp.stack([left_hi, right_hi], axis=1).reshape(-1)
        if path_smooth > 0.0:
            # both children smooth toward THIS node's output next level
            pp = jnp.repeat(node_val, 2)
        if ic_groups is not None:
            # children keep only groups containing the chosen feature;
            # stub nodes (no split) pass their set through unchanged
            contains = ic_groups[:, jnp.clip(bf, 0, F - 1)].T   # (nodes, G)
            child = jnp.where((bf >= 0)[:, None], compat & contains, compat)
            compat = jnp.repeat(child, 2, axis=0)

    # leaf values from bottom-level stats
    n_leaves = 2 ** depth
    if leaf_stats is not None:
        # derived from the (already psum'd) last-level histogram: no extra
        # O(n) pass, globally identical on every shard
        G = leaf_stats[:, 0]
        H = leaf_stats[:, 1]
        leaf_counts = leaf_stats[:, 2]
    else:
        data = jnp.stack([g, h], axis=-1)
        sums = jax.ops.segment_sum(data, node_rel, num_segments=n_leaves)
        if axis_name is not None:
            sums = jax.lax.psum(sums, axis_name)
        G = sums[:, 0]
        H = sums[:, 1]
        leaf_counts = jax.ops.segment_sum(sample_weight_count, node_rel,
                                          num_segments=n_leaves)
        if axis_name is not None:
            leaf_counts = jax.lax.psum(leaf_counts, axis_name)
    G_reg = jnp.sign(G) * jnp.maximum(jnp.abs(G) - alpha, 0.0)  # L1 shrink
    leaf_value = -G_reg / (H + lam)
    leaf_value = jnp.where(jnp.abs(H) > 0, leaf_value, 0.0)
    if path_smooth > 0.0:
        # empty leaves (count 0) land exactly on the parent's output —
        # a better imputation than 0.0 for rows routed there at predict
        leaf_value = _smooth(leaf_value, leaf_counts, pp, path_smooth)
    if monotone is not None:
        # inherited interval per leaf; empty leaves clamp too (their
        # imputed value may sit outside the bounds of a constrained subtree)
        leaf_value = jnp.clip(leaf_value, lo, hi)
    covers = jax.lax.dynamic_update_slice(covers, leaf_counts,
                                          (2 ** depth - 1,))
    return feats, thrs, leaf_value.astype(jnp.float32), node_rel, gains, covers


@functools.partial(jax.jit, static_argnames=("depth",))
def predict_trees(feats, thr_raw, leaf_values, X, depth: int):
    """Sum of tree outputs for raw features.

    feats (T, 2^D-1) int32, thr_raw (T, 2^D-1) f32, leaf_values (T, 2^D) or
    (T, K, 2^D); X (n, F) float. Returns (n,) or (n, K).
    """
    n = X.shape[0]

    def one_tree(carry, tree):
        f, t, lv = tree
        idx = jnp.zeros(n, dtype=jnp.int32)
        for _ in range(depth):
            nf = f[idx]
            nt = t[idx]
            x = jnp.take_along_axis(X, jnp.clip(nf, 0, X.shape[1] - 1)[:, None],
                                    axis=1)[:, 0]
            go_left = (nf < 0) | (x <= nt) | jnp.isnan(x)
            idx = 2 * idx + 1 + (1 - go_left.astype(jnp.int32))
        leaf = idx - (2 ** depth - 1)
        contrib = jnp.take(lv, leaf, axis=-1)        # (n,) or (K, n)
        if contrib.ndim == 2:
            contrib = contrib.T
        return carry + contrib, None

    k_dim = leaf_values.shape[1] if leaf_values.ndim == 3 else None
    init = jnp.zeros((n, k_dim) if k_dim else (n,), dtype=jnp.float32)
    out, _ = jax.lax.scan(one_tree, init, (feats, thr_raw, leaf_values))
    return out


@functools.partial(jax.jit, static_argnames=("depth",))
def predict_leaf_indices(feats, thr_raw, X, depth: int):
    """Leaf index per (row, tree) — parity with LightGBM predictLeaf."""
    n = X.shape[0]

    def one_tree(_, tree):
        f, t = tree
        idx = jnp.zeros(n, dtype=jnp.int32)
        for _ in range(depth):
            nf = f[idx]
            nt = t[idx]
            x = jnp.take_along_axis(X, jnp.clip(nf, 0, X.shape[1] - 1)[:, None],
                                    axis=1)[:, 0]
            go_left = (nf < 0) | (x <= nt) | jnp.isnan(x)
            idx = 2 * idx + 1 + (1 - go_left.astype(jnp.int32))
        return None, idx - (2 ** depth - 1)

    _, leaves = jax.lax.scan(one_tree, None, (feats, thr_raw))
    return leaves.T  # (n, T)


def path_features(feats_np: np.ndarray, depth: int) -> np.ndarray:
    """(2^D - 1,) split features → (2^D, D) features on each leaf's path.

    Used by linear trees (LightGBM ``linear_tree``): leaf l's linear model
    regresses on the features its root→leaf path split on. Duplicate
    features on a path keep their FIRST slot only (later occurrences → -1)
    so the per-leaf design matrix never carries collinear copies; stub
    levels contribute -1 (masked column).
    """
    n_leaf = 2 ** depth
    pf = np.full((n_leaf, depth), -1, dtype=np.int32)
    for leaf in range(n_leaf):
        idx = 0
        seen = set()
        for d in range(depth):
            f = int(feats_np[idx])
            if f >= 0 and f not in seen:
                pf[leaf, d] = f
                seen.add(f)
            bit = (leaf >> (depth - 1 - d)) & 1
            idx = 2 * idx + 1 + bit
    return pf


def _leaf_design(X, leaf_idx, pf):
    """Per-row linear-leaf design matrix [x_path-features, 1] — (n, D+1).
    Masked slots (pf = -1) and missing values contribute 0."""
    pfl = pf[leaf_idx]                                        # (n, D)
    xsel = jnp.take_along_axis(
        X, jnp.clip(pfl, 0, X.shape[1] - 1).astype(jnp.int32), axis=1)
    xsel = jnp.where((pfl >= 0) & ~jnp.isnan(xsel), xsel, 0.0)
    return jnp.concatenate([xsel, jnp.ones((X.shape[0], 1), X.dtype)],
                           axis=1)


@functools.partial(jax.jit, static_argnames=("n_leaf", "axis_name"))
def fit_linear_leaves(X, leaf_idx, g, h, live, pf,
                      n_leaf: int, lam_lin: float, lam: float,
                      axis_name=None):
    """Fit one hessian-weighted ridge model per leaf (LightGBM
    ``linear_tree``), TPU-shaped: every leaf's normal equations accumulate
    with one ``segment_sum`` of (D+1)×(D+1) outer products and solve in a
    single batched ``jnp.linalg.solve`` — no per-leaf control flow.

    Minimizes Σ_i g_i·(β·a_i) + ½ h_i (β·a_i)² + ½ lam_lin |w|² + ½ lam b²
    per leaf (a_i = [x_path, 1], β = [w, b]) — the second-order boosting
    objective, so a leaf whose features carry no signal recovers exactly
    the constant leaf value -G/(H+lam). Data-parallel: M, v, and counts
    psum over ``axis_name`` before the solve, so every shard computes
    identical coefficients (the builder's bitwise-determinism invariant).

    Degenerate leaves (fewer weighted rows than D+2, or a non-finite
    solve) fall back to that constant. Returns (beta (n_leaf, D+1),
    per-row contribution (n,)).
    """
    D = pf.shape[1]
    A = _leaf_design(X, leaf_idx, pf)                         # (n, D+1)
    M = jax.ops.segment_sum(A[:, :, None] * A[:, None, :]
                            * h[:, None, None], leaf_idx,
                            num_segments=n_leaf)              # (L, D+1, D+1)
    v = jax.ops.segment_sum(A * g[:, None], leaf_idx,
                            num_segments=n_leaf)              # (L, D+1)
    cnt = jax.ops.segment_sum(live, leaf_idx, num_segments=n_leaf)
    if axis_name is not None:
        M = jax.lax.psum(M, axis_name)
        v = jax.lax.psum(v, axis_name)
        cnt = jax.lax.psum(cnt, axis_name)
    reg = jnp.diag(jnp.concatenate(
        [jnp.full((D,), lam_lin + 1e-6), jnp.full((1,), lam)]))
    beta = jnp.linalg.solve(M + reg[None],
                            -v[..., None]).squeeze(-1)        # (L, D+1)
    const = -v[:, D] / (M[:, D, D] + lam)      # bias-only = constant leaf
    const = jnp.where(M[:, D, D] > 0, const, 0.0)
    bad = (cnt < D + 2) | ~jnp.isfinite(beta).all(axis=1)
    fallback = jnp.concatenate(
        [jnp.zeros((n_leaf, D)), const[:, None]], axis=1)
    beta = jnp.where(bad[:, None], fallback, beta)
    contrib = (A * beta[leaf_idx]).sum(axis=1)
    return beta, contrib


@functools.partial(jax.jit, static_argnames=("depth",))
def predict_trees_linear(feats, thr_raw, coefs, pf, X, depth: int):
    """Sum of linear-tree outputs: route each row by the usual descent,
    then evaluate its leaf's linear model on the path features.

    feats/thr_raw (T, 2^D-1); coefs (T, 2^D, D+1); pf (T, 2^D, D);
    X (n, F) float → (n,).
    """
    n = X.shape[0]

    def one_tree(carry, tree):
        f, t, cf, p = tree
        idx = jnp.zeros(n, dtype=jnp.int32)
        for _ in range(depth):
            nf = f[idx]
            nt = t[idx]
            x = jnp.take_along_axis(X, jnp.clip(nf, 0, X.shape[1] - 1)[:, None],
                                    axis=1)[:, 0]
            go_left = (nf < 0) | (x <= nt) | jnp.isnan(x)
            idx = 2 * idx + 1 + (1 - go_left.astype(jnp.int32))
        leaf = idx - (2 ** depth - 1)
        A = _leaf_design(X, leaf, p)
        return carry + (A * cf[leaf]).sum(axis=1), None

    out, _ = jax.lax.scan(one_tree, jnp.zeros(n, jnp.float32),
                          (feats, thr_raw, coefs, pf))
    return out


def predict_trees_linear_any(feats, thr_raw, coefs, pf, X, depth: int,
                             chunk: int = 1 << 16) -> np.ndarray:
    """``predict_trees_linear`` accepting dense OR scipy-sparse X."""
    return apply_chunked_dense(
        lambda xd: predict_trees_linear(feats, thr_raw, coefs, pf, xd,
                                        depth=depth),
        X, empty_shape=(0,), chunk=chunk)


def predict_trees_linear_multi_any(feats, thr_raw, coefs, pf, X,
                                   depth: int, num_class: int,
                                   chunk: int = 1 << 16) -> np.ndarray:
    """Multiclass linear-tree prediction, dense OR scipy-sparse X: each
    tree's linear-leaf output lands in that tree's class column. Trees
    append class-major within every boosting iteration (train.py
    multiclass loop), so tree t belongs to class ``t % num_class`` — an
    invariant every caller's slice preserves (full prefixes,
    one-iteration groups, and dart's whole-group drops all keep the
    class-major period). Delegates per class to ``predict_trees_linear``
    over the ``k::K`` stride, so the descent/NaN routing lives in ONE
    place. Returns (n, num_class)."""
    cols = [predict_trees_linear_any(
        feats[k::num_class], thr_raw[k::num_class], coefs[k::num_class],
        pf[k::num_class], X, depth=depth, chunk=chunk)
        for k in range(num_class)]
    return np.stack(cols, axis=1)


def apply_chunked_dense(fn, X, empty_shape, chunk: int = 1 << 16,
                        concat_axis: int = 0,
                        empty_dtype=np.float32) -> np.ndarray:
    """Run ``fn(dense_f32_rows) -> np.ndarray`` over X in bounded row
    chunks, densifying scipy-sparse input one chunk at a time so peak host
    memory is O(chunk × F) rather than the full dense matrix. Dense input
    passes through in one call. ``empty_shape`` is the result shape for a
    0-row X (shape evidence a concatenation of zero parts cannot supply).
    """
    from .binning import is_sparse
    if not is_sparse(X):
        return np.asarray(fn(np.asarray(X, np.float32)))
    X = X.tocsr()
    chunk = max(1, chunk)
    parts = [np.asarray(fn(X[lo:lo + chunk].toarray().astype(np.float32)))
             for lo in range(0, X.shape[0], chunk)]
    if not parts:
        return np.zeros(empty_shape, empty_dtype)
    return np.concatenate(parts, axis=concat_axis)


def predict_trees_any(feats, thr_raw, leaf_values, X, depth: int,
                      chunk: int = 1 << 16) -> np.ndarray:
    """``predict_trees`` accepting dense OR scipy-sparse X.

    The tree-descent gather needs row-major dense features on device
    either way (parity note: LightGBM predicts sparse via per-row CSR
    pointer chases, ``LightGBMBooster.scala:510-527``; batched dense
    chunks are the TPU-shaped equivalent).
    """
    k_dim = leaf_values.shape[1] if leaf_values.ndim == 3 else None
    return apply_chunked_dense(
        lambda xd: predict_trees(feats, thr_raw, leaf_values, xd,
                                 depth=depth),
        X, empty_shape=(0, k_dim) if k_dim else (0,), chunk=chunk)
