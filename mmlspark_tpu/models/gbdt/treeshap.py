"""Path-dependent TreeSHAP (Lundberg & Lee), vectorized over samples.

The reference gets SHAP contributions from LightGBM's native TreeSHAP
(``LGBM_BoosterPredictForMatSingle`` with ``C_API_PREDICT_CONTRIB``, surfaced
as ``featuresShap`` in ``booster/LightGBMBooster.scala:414-423``). This is a
from-scratch implementation of the polynomial algorithm:

The recursion walks *tree nodes* (every branch), carrying the "unique path"
state m = [(feature, zero_fraction, one_fraction, weight), ...]. For a fixed
tree the node path and zero-fractions (cover ratios) are sample-independent;
only the one-fractions (did this sample follow the branch?) vary per sample —
so the weights are (n_samples, path_len) arrays and every EXTEND/UNWIND is a
vectorized numpy op. Complexity O(nodes * depth^2) per tree, amortized over
all samples at once.
"""

from __future__ import annotations

from typing import List

import numpy as np

__all__ = ["tree_shap"]


def _extend(w, z, o, zf, of):
    """Append path element (zf scalar, of (n,)-vector) and update weights.

    w: (n, l) permutation weights, z: (l,) zero fractions, o: (n, l) ones.
    """
    n, l = w.shape
    w2 = np.concatenate([w, np.zeros((n, 1))], axis=1)
    if l == 0:
        w2[:, 0] = 1.0
    z2 = np.append(z, zf)
    o2 = np.concatenate([o, of[:, None]], axis=1)
    for i in range(l - 1, -1, -1):
        w2[:, i + 1] += of * w2[:, i] * (i + 1) / (l + 1)
        w2[:, i] = zf * w2[:, i] * (l - i) / (l + 1)
    return w2, z2, o2


def _unwound_sum(w, z, o, idx):
    """Sum of permutation weights with path element ``idx`` removed."""
    n, L = w.shape
    l = L - 1
    oi = o[:, idx]          # (n,) values in {0., 1.} (products stay 0/1 here)
    zi = z[idx]
    # branch oi != 0
    total_one = np.zeros(n)
    nxt = w[:, l].copy()
    safe_oi = np.where(oi != 0, oi, 1.0)
    for j in range(l - 1, -1, -1):
        t = nxt * (l + 1) / ((j + 1) * safe_oi)
        total_one += t
        nxt = w[:, j] - t * zi * (l - j) / (l + 1)
    # branch oi == 0
    total_zero = np.zeros(n)
    if zi != 0:
        for j in range(l - 1, -1, -1):
            total_zero += w[:, j] * (l + 1) / (zi * (l - j))
    return np.where(oi != 0, total_one, total_zero)


def _unwind(w, z, o, idx):
    """Remove path element ``idx``, inverting its EXTEND."""
    n, L = w.shape
    l = L - 1
    oi = o[:, idx]
    zi = z[idx]
    safe_oi = np.where(oi != 0, oi, 1.0)
    nxt = w[:, l].copy()
    w_new = w.copy()
    for j in range(l - 1, -1, -1):
        t_one = nxt * (l + 1) / ((j + 1) * safe_oi)
        t_zero = (w_new[:, j] * (l + 1) / (zi * (l - j))) if zi != 0 else \
            np.zeros(n)
        nxt = w_new[:, j] - t_one * zi * (l - j) / (l + 1)
        w_new[:, j] = np.where(oi != 0, t_one, t_zero)
    # w is subset-size-indexed: unwinding drops the LAST size slot, while the
    # element-indexed z/o lose element idx
    return (w_new[:, :l], np.delete(z, idx), np.delete(o, idx, axis=1))


def tree_shap(feats: np.ndarray, thr: np.ndarray, leaf_value: np.ndarray,
              cover: np.ndarray, depth: int, X: np.ndarray,
              phi: np.ndarray) -> None:
    """Accumulate SHAP values of one complete-binary tree into ``phi``.

    feats/thr: (2^depth - 1,); leaf_value: (2^depth,); cover: (2^(depth+1)-1,)
    X: (n, F) float32; phi: (n, F+1) float64, last column gets E[f(x)].
    """
    n_int = 2 ** depth - 1
    n_all = 2 ** (depth + 1) - 1
    n = len(X)
    cover = cover.astype(np.float64)

    # cover-weighted mean value per node (for expected value at root)
    node_val = np.zeros(n_all)
    node_val[n_int:] = leaf_value
    for i in range(n_int - 1, -1, -1):
        cl, cr = cover[2 * i + 1], cover[2 * i + 2]
        tot = cl + cr
        node_val[i] = ((cl * node_val[2 * i + 1] + cr * node_val[2 * i + 2]) / tot
                       if tot > 0 else node_val[2 * i + 1])
    phi[:, -1] += node_val[0]

    def leaf_contrib(node, w, z, o, d_path: List[int]):
        v = node_val[node]
        for pi in range(1, w.shape[1]):
            s = _unwound_sum(w, z, o, pi)
            phi[:, d_path[pi]] += s * (o[:, pi] - z[pi]) * v

    def recurse(node, w, z, o, pz, po, pfeat, d_path: List[int]):
        w, z, o = _extend(w, z, o, pz, po)
        d_path = d_path + [pfeat]
        if node >= n_int or feats[node] < 0:
            leaf_contrib(node, w, z, o, d_path)
            return
        f = int(feats[node])
        x = X[:, f]
        goes_left = ((x <= thr[node]) | np.isnan(x)).astype(np.float64)
        c_node, cl, cr = cover[node], cover[2 * node + 1], cover[2 * node + 2]
        if c_node <= 0:
            return
        iz, io = 1.0, np.ones(n)
        k = next((i for i in range(1, len(d_path)) if d_path[i] == f), None)
        if k is not None:
            iz, io = z[k], o[:, k].copy()
            w, z, o = _unwind(w, z, o, k)
            d_path = d_path[:k] + d_path[k + 1:]
        recurse(2 * node + 1, w, z, o, iz * cl / c_node, io * goes_left, f,
                d_path)
        recurse(2 * node + 2, w, z, o, iz * cr / c_node, io * (1 - goes_left),
                f, d_path)

    recurse(0, np.zeros((n, 0)), np.zeros(0), np.zeros((n, 0)),
            1.0, np.ones(n), -1, [])
