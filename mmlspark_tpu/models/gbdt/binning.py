"""Feature binning — dataset construction for histogram GBDT.

Plays the role of LightGBM's native dataset build
(``LGBM_DatasetCreateFromMat/CSR`` reached through
``lightgbm/.../dataset/DatasetAggregator.scala:331-356,441-465``): continuous
features are quantile-discretized into at most ``max_bin`` integer bins so
tree training operates on a dense uint8/uint16 matrix — the layout the TPU
histogram kernel wants (small integer gather/scatter indices, contiguous
rows).

Bin 0 is reserved for missing values (NaN), matching LightGBM's
missing-handling semantics. Bin upper bounds are stored so fitted models
split on *raw* thresholds and prediction never needs the bin mapper.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["BinMapper", "MAX_BIN_DEFAULT"]

MAX_BIN_DEFAULT = 255


class BinMapper:
    """Per-feature quantile binning. Fit on (a sample of) the data."""

    def __init__(self, max_bin: int = MAX_BIN_DEFAULT,
                 sample_cnt: int = 200_000, seed: int = 0):
        if not 2 <= max_bin <= 65535:
            raise ValueError(f"max_bin must be in [2, 65535], got {max_bin}")
        self.max_bin = int(max_bin)
        self.sample_cnt = sample_cnt
        self.seed = seed
        self.upper_bounds: List[np.ndarray] = []  # per feature, ascending
        self.n_features: Optional[int] = None
        self._table = None

    def fit(self, X: np.ndarray) -> "BinMapper":
        X = np.asarray(X)
        n, f = X.shape
        self.n_features = f
        self._table = None
        if n > self.sample_cnt:
            # sample *rows indices* first so only the sample is ever copied /
            # upcast — fitting on HIGGS-scale input must not materialize an
            # n×f float64 matrix
            rng = np.random.default_rng(self.seed)
            X = X[np.sort(rng.choice(n, self.sample_cnt, replace=False))]
        X = np.asarray(X, dtype=np.float64)
        self.upper_bounds = []
        for j in range(f):
            col = X[:, j]
            col = col[~np.isnan(col)]
            if col.size == 0:
                self.upper_bounds.append(np.array([np.inf]))
                continue
            uniq = np.unique(col)
            if len(uniq) <= self.max_bin - 1:
                # exact: one bin per distinct value; bound = midpoint
                mids = (uniq[:-1] + uniq[1:]) / 2
                bounds = np.append(mids, np.inf)
            else:
                qs = np.quantile(col, np.linspace(0, 1, self.max_bin),
                                 method="linear")
                bounds = np.unique(qs[1:-1])
                bounds = np.append(bounds, np.inf)
            self.upper_bounds.append(bounds.astype(np.float64))
        return self

    @property
    def n_bins(self) -> int:
        """Max bins over features incl. the missing bin (index 0)."""
        return 1 + max((len(b) for b in self.upper_bounds), default=1)

    def transform(self, X: np.ndarray) -> np.ndarray:
        """Bin a matrix, streaming column-by-column.

        Never materializes a float64 copy of the input: only per-column
        temporaries (O(n)) exist at any moment, so an 11M×28 float32 HIGGS
        matrix bins without doubling resident memory.
        """
        X = np.asarray(X)
        n, f = X.shape
        if f != self.n_features:
            raise ValueError(f"expected {self.n_features} features, got {f}")
        is_float = X.dtype.kind == "f"
        dtype = np.uint8 if self.n_bins <= 256 else np.uint16
        out = np.zeros((n, f), dtype=dtype)
        for j in range(f):
            col = X[:, j]
            # bins 1..len(bounds); searchsorted gives 0-based interval index
            binned = np.searchsorted(self.upper_bounds[j], col, side="left") + 1
            if is_float:
                binned = np.where(np.isnan(col), 0, binned)
            out[:, j] = binned.astype(dtype)
        return out

    def fit_transform(self, X: np.ndarray) -> np.ndarray:
        return self.fit(X).transform(X)

    def bounds_table(self):
        """Padded (n_features, max_len) bounds matrix + per-feature lengths,
        for vectorized bin→threshold lookups (cached)."""
        if self._table is None:
            lengths = np.array([len(b) for b in self.upper_bounds],
                               dtype=np.int64)
            L = int(lengths.max()) if len(lengths) else 1
            table = np.full((max(1, len(self.upper_bounds)), L), np.inf)
            for j, b in enumerate(self.upper_bounds):
                table[j, :len(b)] = b
            self._table = (table, lengths)
        return self._table

    def bin_threshold_value(self, feature: int, bin_idx: int) -> float:
        """Raw-value threshold for "go left if x <= threshold" at this bin."""
        bounds = self.upper_bounds[feature]
        i = min(max(int(bin_idx) - 1, 0), len(bounds) - 1)
        return float(bounds[i])

    def to_dict(self) -> dict:
        return {"max_bin": self.max_bin,
                "upper_bounds": [b.tolist() for b in self.upper_bounds]}

    @staticmethod
    def from_dict(d: dict) -> "BinMapper":
        bm = BinMapper(max_bin=d["max_bin"])
        bm.upper_bounds = [np.asarray(b) for b in d["upper_bounds"]]
        bm.n_features = len(bm.upper_bounds)
        return bm
