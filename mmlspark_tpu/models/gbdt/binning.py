"""Feature binning — dataset construction for histogram GBDT.

Plays the role of LightGBM's native dataset build
(``LGBM_DatasetCreateFromMat/CSR`` reached through
``lightgbm/.../dataset/DatasetAggregator.scala:331-356,441-465``): continuous
features are quantile-discretized into at most ``max_bin`` integer bins so
tree training operates on a dense uint8/uint16 matrix — the layout the TPU
histogram kernel wants (small integer gather/scatter indices, contiguous
rows).

Bin 0 is reserved for missing values (NaN), matching LightGBM's
missing-handling semantics. Bin upper bounds are stored so fitted models
split on *raw* thresholds and prediction never needs the bin mapper.

Sparse input (scipy CSR/CSC) is a first-class path (parity:
``DatasetAggregator.scala:127-183`` sparse-vs-dense auto-detect feeding
``LGBM_DatasetCreateFromCSR:441-465``): implicit zeros are real zero values,
binned per column without ever materializing the dense float matrix — the
only dense artifact is the binned uint8/uint16 matrix itself, which is what
the TPU histogram kernel wants and is 4-8x smaller than a float32
densification.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

try:                                    # scipy is in the image; guarded so a
    import scipy.sparse as _sp          # trimmed env degrades to dense-only
except Exception:                       # pragma: no cover
    _sp = None

__all__ = ["BinMapper", "MAX_BIN_DEFAULT", "is_sparse"]

MAX_BIN_DEFAULT = 255


def is_sparse(X) -> bool:
    """True when X is a scipy sparse matrix (and scipy is available)."""
    return _sp is not None and _sp.issparse(X)


class BinMapper:
    """Per-feature quantile binning. Fit on (a sample of) the data."""

    def __init__(self, max_bin: int = MAX_BIN_DEFAULT,
                 sample_cnt: int = 200_000, seed: int = 0):
        if not 2 <= max_bin <= 65535:
            raise ValueError(f"max_bin must be in [2, 65535], got {max_bin}")
        self.max_bin = int(max_bin)
        self.sample_cnt = sample_cnt
        self.seed = seed
        self.upper_bounds: List[np.ndarray] = []  # per feature, ascending
        self.n_features: Optional[int] = None
        self._table = None

    def fit(self, X) -> "BinMapper":
        sparse = is_sparse(X)
        if not sparse:
            X = np.asarray(X)
        n, f = X.shape
        self.n_features = f
        self._table = None
        if n > self.sample_cnt:
            # sample *rows indices* first so only the sample is ever copied /
            # upcast — fitting on HIGGS-scale input must not materialize an
            # n×f float64 matrix (sparse: CSR row slicing is cheap; the
            # sampled submatrix is the only thing converted to CSC below)
            rng = np.random.default_rng(self.seed)
            rows = np.sort(rng.choice(n, self.sample_cnt, replace=False))
            X = X.tocsr()[rows] if sparse else X[rows]
        if sparse:
            X = X.tocsc()
        else:
            X = np.asarray(X, dtype=np.float64)
        self.upper_bounds = []
        for j in range(f):
            if sparse:
                # densify ONE sampled column at a time: implicit zeros are
                # genuine 0.0 values and must weigh into the quantiles
                col = np.zeros(X.shape[0], dtype=np.float64)
                lo, hi = X.indptr[j], X.indptr[j + 1]
                col[X.indices[lo:hi]] = X.data[lo:hi]
            else:
                col = X[:, j]
            col = col[~np.isnan(col)]
            if col.size == 0:
                self.upper_bounds.append(np.array([np.inf]))
                continue
            uniq = np.unique(col)
            if len(uniq) <= self.max_bin - 1:
                # exact: one bin per distinct value; bound = midpoint
                mids = (uniq[:-1] + uniq[1:]) / 2
                bounds = np.append(mids, np.inf)
            else:
                qs = np.quantile(col, np.linspace(0, 1, self.max_bin),
                                 method="linear")
                bounds = np.unique(qs[1:-1])
                bounds = np.append(bounds, np.inf)
            self.upper_bounds.append(bounds.astype(np.float64))
        return self

    @property
    def n_bins(self) -> int:
        """Max bins over features incl. the missing bin (index 0)."""
        return 1 + max((len(b) for b in self.upper_bounds), default=1)

    def transform(self, X) -> np.ndarray:
        """Bin a matrix, streaming column-by-column.

        Never materializes a float64 copy of the input: only per-column
        temporaries (O(n)) exist at any moment, so an 11M×28 float32 HIGGS
        matrix bins without doubling resident memory. Sparse input bins
        only the stored values — each column is initialized to its
        zero-value bin and the nonzeros scattered on top, so cost scales
        with nnz, not n×f.
        """
        if is_sparse(X):
            return self._transform_sparse(X.tocsc())
        X = np.asarray(X)
        n, f = X.shape
        if f != self.n_features:
            raise ValueError(f"expected {self.n_features} features, got {f}")
        want_u16 = self.n_bins > 256
        if X.dtype.kind == "f":
            # native single-pass loop (or its numpy fallback inside);
            # ~4-5x the per-column searchsorted on this host — dataset
            # construction is LightGBM's own native hot path. The native
            # kernel speaks f32/f64 only; rarer float widths (f16,
            # longdouble) upcast first instead of crashing it.
            from ...native import bin_columns
            if X.dtype not in (np.float32, np.float64):
                X = X.astype(np.float64)
            table, lengths = self.bounds_table()
            return bin_columns(X, table, lengths, want_u16)
        dtype = np.uint16 if want_u16 else np.uint8
        out = np.zeros((n, f), dtype=dtype)
        for j in range(f):
            col = X[:, j]
            # bins 1..len(bounds); searchsorted gives 0-based interval index
            binned = np.searchsorted(self.upper_bounds[j], col, side="left") + 1
            out[:, j] = binned.astype(dtype)
        return out

    def _transform_sparse(self, X) -> np.ndarray:
        """CSC → dense binned matrix; per-column scatter of binned nonzeros
        over the column's zero-value bin."""
        n, f = X.shape
        if f != self.n_features:
            raise ValueError(f"expected {self.n_features} features, got {f}")
        dtype = np.uint8 if self.n_bins <= 256 else np.uint16
        out = np.empty((n, f), dtype=dtype)
        is_float = X.data.dtype.kind == "f"
        for j in range(f):
            bounds = self.upper_bounds[j]
            zero_bin = np.searchsorted(bounds, 0.0, side="left") + 1
            out[:, j] = dtype(zero_bin)
            lo, hi = X.indptr[j], X.indptr[j + 1]
            vals = X.data[lo:hi]
            binned = np.searchsorted(bounds, vals, side="left") + 1
            if is_float:
                binned = np.where(np.isnan(vals), 0, binned)
            out[X.indices[lo:hi], j] = binned.astype(dtype)
        return out

    def fit_transform(self, X) -> np.ndarray:
        return self.fit(X).transform(X)

    def bounds_table(self):
        """Padded (n_features, max_len) bounds matrix + per-feature lengths,
        for vectorized bin→threshold lookups (cached)."""
        if self._table is None:
            lengths = np.array([len(b) for b in self.upper_bounds],
                               dtype=np.int64)
            L = int(lengths.max()) if len(lengths) else 1
            table = np.full((max(1, len(self.upper_bounds)), L), np.inf)
            for j, b in enumerate(self.upper_bounds):
                table[j, :len(b)] = b
            self._table = (table, lengths)
        return self._table

    def bin_threshold_value(self, feature: int, bin_idx: int) -> float:
        """Raw-value threshold for "go left if x <= threshold" at this bin."""
        bounds = self.upper_bounds[feature]
        i = min(max(int(bin_idx) - 1, 0), len(bounds) - 1)
        return float(bounds[i])

    def to_dict(self) -> dict:
        return {"max_bin": self.max_bin,
                "upper_bounds": [b.tolist() for b in self.upper_bounds]}

    @staticmethod
    def from_dict(d: dict) -> "BinMapper":
        bm = BinMapper(max_bin=d["max_bin"])
        bm.upper_bounds = [np.asarray(b) for b in d["upper_bounds"]]
        bm.n_features = len(bm.upper_bounds)
        return bm
