"""LightGBM-parity estimators over the DataFrame pipeline API.

Parity surface: ``LightGBMClassifier`` (``lightgbm/.../LightGBMClassifier.scala:26-100``),
``LightGBMRegressor`` (tweedie/quantile objectives), ``LightGBMRanker``
(lambdarank with group column), their fitted models with
predict/leaf/SHAP output columns (``LightGBMModelMethods``), warm start via
model string (``LightGBMBase.scala:49-61``), and the main training params
(``params/LightGBMParams.scala``). ``tree_learner`` values map to the mesh:
``serial`` = single chip, ``data_parallel``/``voting_parallel`` = histogram
psum over the default mesh's ``data`` axis.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...core.dataframe import DataFrame
from ...core.params import (ComplexParam, Param, HasFeaturesCol, HasLabelCol,
                            HasPredictionCol, HasProbabilityCol, HasWeightCol)
from ...core.pipeline import Estimator, Model
from ...core.schema import assemble_features, set_label_metadata
from ...parallel.mesh import get_default_mesh
from .booster import Booster
from .train import train

__all__ = ["LightGBMClassifier", "LightGBMRegressor", "LightGBMRanker",
           "LightGBMClassificationModel", "LightGBMRegressionModel",
           "LightGBMRankerModel"]


def _str_or_str_list(v):
    """One metric name, or a list/tuple of them — anything else (ints,
    dicts, sets) is a typed error, not a silent iteration."""
    if isinstance(v, str):
        return v
    if isinstance(v, (list, tuple)):
        return [str(m) for m in v]
    raise TypeError(f"expected str or list of str, got "
                    f"{type(v).__name__}: {v!r}")


class _LightGBMParams(HasFeaturesCol, HasLabelCol, HasWeightCol):
    boosting_type = Param(str, default="gbdt",
                          choices=["gbdt", "gbrt", "goss", "dart", "rf",
                                   "random_forest"],
                          doc="boosting mode (parity: LightGBMParams."
                              "boostingType, LightGBMParams.scala:389-393)")
    top_rate = Param(float, default=0.2, doc="goss: keep fraction by |grad|")
    other_rate = Param(float, default=0.1,
                       doc="goss: sampled fraction of the rest")
    drop_rate = Param(float, default=0.1, doc="dart: tree drop probability")
    max_drop = Param(int, default=50, doc="dart: max dropped trees per iter")
    skip_drop = Param(float, default=0.5,
                      doc="dart: probability of skipping the drop")
    num_iterations = Param(int, default=100, doc="boosting rounds")
    learning_rate = Param(float, default=0.1, doc="shrinkage rate")
    num_leaves = Param(int, default=31, doc="max leaves per tree")
    max_depth = Param(int, default=-1, doc="max tree depth (-1: from num_leaves)")
    lambda_l1 = Param(float, default=0.0, doc="L1 regularization")
    lambda_l2 = Param(float, default=0.0, doc="L2 regularization")
    min_data_in_leaf = Param(int, default=20, doc="min rows per leaf")
    min_sum_hessian_in_leaf = Param(float, default=1e-3, doc="min hessian per leaf")
    min_gain_to_split = Param(float, default=0.0, doc="min split gain")
    feature_fraction = Param(float, default=1.0, doc="feature subsample per tree")
    bagging_fraction = Param(float, default=1.0, doc="row subsample")
    bagging_freq = Param(int, default=0, doc="bagging every k iterations")
    max_bin = Param(int, default=255, doc="max histogram bins")
    early_stopping_round = Param(int, default=0, doc="early stopping patience")
    top_k = Param(int, default=20,
                  doc="voting_parallel: local feature nominations per node "
                      "(parity: LightGBMParams.topK)")
    parallelism = Param(str, default="serial",
                        choices=["serial", "data_parallel", "voting_parallel"],
                        doc="tree learner (reference LightGBMParams.parallelism)")
    metric = Param((str, list), default="auto",
                   converter=_str_or_str_list,
                   doc="eval metric name, or a LIST of names (all logged; "
                       "early stopping follows the first)")
    seed = Param(int, default=0, doc="random seed")
    validation_indicator_col = Param(str, default=None,
                                     doc="bool column marking validation rows")
    model_string = Param(str, default=None,
                         doc="serialized booster for warm start")
    leaf_prediction_col = Param(str, default=None, doc="emit leaf indices here")
    features_shap_col = Param(str, default=None, doc="emit SHAP contributions here")
    checkpoint_dir = Param(str, default=None,
                           doc="directory for step-level checkpoint/resume")
    checkpoint_interval = Param(int, default=0,
                                doc="iterations between checkpoints (0 = off)")
    categorical_feature = Param((list, int), default=[],
                                doc="feature-vector indices treated as "
                                    "categorical (label-ordered rank "
                                    "encoding; reference "
                                    "LightGBMBase.scala:168-199)")
    enable_bundle = Param(bool, default=True,
                          doc="EFB: bundle mutually-exclusive sparse "
                              "features into shared histogram columns "
                              "(LightGBM enable_bundle; active on sparse "
                              "features columns)")
    max_conflict_rate = Param(float, default=0.0,
                              doc="EFB conflict budget as a fraction of "
                                  "rows (0 = lossless bundling)")
    monotone_constraints = Param((list, int), default=[],
                                 doc="per-feature -1/0/+1 directions the "
                                     "model's predictions must respect "
                                     "(LightGBM monotone_constraints)")
    scale_pos_weight = Param(float, default=1.0,
                             doc="binary: positive-class weight multiplier "
                                 "(LightGBM scale_pos_weight)")
    is_unbalance = Param(bool, default=False,
                         doc="binary: auto-set scale_pos_weight to "
                             "neg/pos (LightGBM is_unbalance)")
    init_score_col = Param(str, default=None,
                           doc="per-row starting margin column (LightGBM "
                               "initScoreCol); predictions exclude it")
    extra_trees = Param(bool, default=False,
                        doc="extremely randomized trees: one random "
                            "threshold candidate per node x feature "
                            "(LightGBM extra_trees)")
    feature_fraction_bynode = Param(float, default=1.0,
                                    doc="feature subsample drawn per NODE "
                                        "(LightGBM feature_fraction_bynode)")
    path_smooth = Param(float, default=0.0,
                        doc="smooth node outputs toward the parent's with "
                            "this many pseudo-counts (LightGBM path_smooth)")
    boost_from_average = Param(bool, default=True,
                               doc="start boosting from the objective's "
                                   "optimal constant (LightGBM "
                                   "boost_from_average)")
    interaction_constraints = Param((list, list), default=[],
                                    doc="allowed feature groups; a branch "
                                        "only combines features sharing a "
                                        "group (LightGBM "
                                        "interaction_constraints)")
    cat_smooth = Param(float, default=10.0,
                       doc="categorical: target-mean smoothing "
                           "pseudo-count (LightGBM cat_smooth)")
    min_data_per_group = Param(int, default=0,
                               doc="categorical: pool categories rarer "
                                   "than this into one shared rank "
                                   "(LightGBM min_data_per_group; off by "
                                   "default — global pooling is stronger "
                                   "than LightGBM's per-node grouping)")
    linear_tree = Param(bool, default=False,
                        doc="fit a ridge model per leaf over the leaf's "
                            "path features (LightGBM linear_tree)")
    linear_lambda = Param(float, default=0.0,
                          doc="L2 on linear-leaf weights (LightGBM "
                              "linear_lambda)")

    def _train_params(self, extra: dict) -> dict:
        keys = ["num_iterations", "learning_rate", "num_leaves", "max_depth",
                "lambda_l1", "lambda_l2", "min_data_in_leaf",
                "min_sum_hessian_in_leaf", "min_gain_to_split",
                "feature_fraction", "bagging_fraction", "bagging_freq",
                "max_bin", "early_stopping_round", "metric", "seed",
                "checkpoint_interval", "boosting_type", "top_rate",
                "other_rate", "drop_rate", "max_drop", "skip_drop", "top_k",
                "enable_bundle", "max_conflict_rate", "scale_pos_weight",
                "is_unbalance", "extra_trees", "feature_fraction_bynode",
                "path_smooth", "boost_from_average", "cat_smooth",
                "min_data_per_group", "linear_tree", "linear_lambda"]
        p = {k: self.get(k) for k in keys}
        if self.get_or_none("checkpoint_dir"):
            p["checkpoint_dir"] = self.get("checkpoint_dir")
        p["tree_learner"] = self.parallelism
        if self.categorical_feature:
            p["categorical_feature"] = list(self.categorical_feature)
        if self.monotone_constraints:
            p["monotone_constraints"] = list(self.monotone_constraints)
        if self.interaction_constraints:
            p["interaction_constraints"] = [list(g) for g in
                                            self.interaction_constraints]
        p.update(extra)
        return p

    def _split_valid(self, df: DataFrame):
        vcol = self.get_or_none("validation_indicator_col")
        if vcol and vcol in df:
            mask = np.asarray(df[vcol], dtype=bool)
            return df.filter(~mask), df.filter(mask)
        return df, None

    def _fit_core(self, df: DataFrame, extra_params: dict,
                  group_col: Optional[str] = None) -> Booster:
        train_df, valid_df = self._split_valid(df)
        X = assemble_features(train_df, [self.features_col])
        y = np.asarray(train_df[self.label_col], dtype=np.float64)
        w = (np.asarray(train_df[self.weight_col], dtype=np.float64)
             if self.get_or_none("weight_col") and self.weight_col in train_df
             else None)
        valid_sets = None
        valid_weights = None
        if valid_df is not None and len(valid_df):
            valid_sets = [(assemble_features(valid_df, [self.features_col]),
                           np.asarray(valid_df[self.label_col], dtype=np.float64))]
            if w is not None and self.weight_col in valid_df:
                # LightGBM's Dataset weights apply to its eval metrics:
                # the validation split's weight rows drive early stopping
                valid_weights = [np.asarray(valid_df[self.weight_col],
                                            dtype=np.float64)]
        group = None
        if group_col is not None:
            gcol = np.asarray(train_df[group_col])
            # lambdarank consumes contiguous runs; a group id reappearing
            # after another would silently mix queries — reject it
            boundaries = np.flatnonzero(gcol[1:] != gcol[:-1]) + 1
            starts = np.concatenate([[0], boundaries, [len(gcol)]])
            run_ids = gcol[starts[:-1]]
            if len(np.unique(run_ids)) != len(run_ids):
                raise ValueError(
                    f"group column {group_col!r} is not contiguous: the same "
                    "group id appears in separate runs; sort the DataFrame by "
                    "group first")
            group = np.diff(starts)
        init_model = None
        ms = self.get_or_none("model_string")
        if ms:
            init_model = Booster.from_string(ms)
        iscol = self.get_or_none("init_score_col")
        init_score = (np.asarray(train_df[iscol], dtype=np.float64)
                      if iscol and iscol in train_df else None)
        valid_init_scores = None
        if init_score is not None and valid_sets is not None:
            # the validation split carries its own margin column rows
            valid_init_scores = [np.asarray(valid_df[iscol],
                                            dtype=np.float64)]
        mesh = get_default_mesh() if self.parallelism != "serial" else None
        return train(self._train_params(extra_params), X, y, sample_weight=w,
                     group=group, valid_sets=valid_sets, init_model=init_model,
                     mesh=mesh, init_score=init_score,
                     valid_init_scores=valid_init_scores,
                     valid_weights=valid_weights)


class _LightGBMModelBase(Model, HasFeaturesCol, HasPredictionCol):
    booster_string = ComplexParam(doc="fitted booster payload")
    leaf_prediction_col = Param(str, default=None, doc="emit leaf indices here")
    features_shap_col = Param(str, default=None, doc="emit SHAP contributions here")

    def __init__(self, booster: Optional[Booster] = None, **kw):
        super().__init__(**kw)
        self._booster = booster
        if booster is not None:
            self.set(booster_string=booster.to_string().encode())

    @property
    def booster(self) -> Booster:
        if getattr(self, "_booster", None) is None:
            self._booster = Booster.from_string(
                self.get("booster_string").decode())
        return self._booster

    def to_onnx(self) -> bytes:
        """Serialize the fitted booster as a spec-compliant ONNX
        TreeEnsemble graph — the native counterpart of the reference's
        onnxmltools LightGBM conversion (``website/docs/features/onnx/
        about.md``); consumable by this framework's ONNXModel or any
        other ONNX runtime."""
        from .onnx_export import booster_to_onnx
        return booster_to_onnx(self.booster)

    def _load_extra(self, path):
        self._booster = None

    def _features(self, df: DataFrame) -> np.ndarray:
        return assemble_features(df, [self.features_col]).astype(np.float32)

    def _add_aux_cols(self, df: DataFrame, X: np.ndarray) -> DataFrame:
        lcol = self.get_or_none("leaf_prediction_col")
        if lcol:
            leaves = self.booster.predict_leaf(X)
            vals = np.empty(len(leaves), dtype=object)
            for i, row in enumerate(leaves):
                vals[i] = row.astype(np.float64)
            df = df.with_column(lcol, vals)
        scol = self.get_or_none("features_shap_col")
        if scol:
            shap = self.booster.shap_values(X)
            if shap.ndim == 3:
                shap = np.concatenate(list(shap), axis=-1)
            vals = np.empty(len(shap), dtype=object)
            for i, row in enumerate(shap):
                vals[i] = row
            df = df.with_column(scol, vals)
        return df

    def feature_importances(self, importance_type: str = "split") -> np.ndarray:
        return self.booster.feature_importance(importance_type)


class LightGBMClassifier(Estimator, _LightGBMParams, HasPredictionCol,
                         HasProbabilityCol):
    objective = Param(str, default="binary", doc="binary or multiclass")
    prediction_col = Param(str, default="prediction", doc="predicted label")
    probability_col = Param(str, default="probability", doc="class probabilities")
    raw_prediction_col = Param(str, default="rawPrediction", doc="raw scores")

    def _fit(self, df: DataFrame) -> "LightGBMClassificationModel":
        y = np.asarray(df[self.label_col])
        classes = np.unique(y[~np.isnan(y.astype(np.float64))])
        n_classes = len(classes)
        objective = self.objective
        if n_classes > 2 and objective == "binary":
            objective = "multiclass"
        extra = {"objective": objective}
        if objective in ("multiclass", "softmax"):
            extra["num_class"] = n_classes
        booster = self._fit_core(df, extra)
        model = LightGBMClassificationModel(
            booster,
            features_col=self.features_col,
            prediction_col=self.prediction_col,
            probability_col=self.probability_col,
            raw_prediction_col=self.get("raw_prediction_col"),
            leaf_prediction_col=self.get_or_none("leaf_prediction_col"),
            features_shap_col=self.get_or_none("features_shap_col"),
            num_classes=n_classes)
        return model


class LightGBMClassificationModel(_LightGBMModelBase, HasProbabilityCol):
    raw_prediction_col = Param(str, default="rawPrediction", doc="raw scores")
    num_classes = Param(int, default=2, doc="number of classes")

    def _transform(self, df: DataFrame) -> DataFrame:
        X = self._features(df)
        raw = self.booster.predict(X, raw_score=True)
        prob = self.booster.predict(X)
        if prob.ndim == 1:
            prob2 = np.stack([1 - prob, prob], axis=1)
            raw2 = np.stack([-raw, raw], axis=1)
        else:
            prob2, raw2 = prob, raw
        pred = prob2.argmax(axis=1).astype(np.float64)
        obj = np.empty(len(prob2), dtype=object)
        for i in range(len(prob2)):
            obj[i] = prob2[i].astype(np.float64)
        rawo = np.empty(len(raw2), dtype=object)
        for i in range(len(raw2)):
            rawo[i] = np.asarray(raw2[i], dtype=np.float64).ravel()
        out = (df.with_column(self.get("raw_prediction_col"), rawo)
                 .with_column(self.probability_col, obj)
                 .with_column(self.prediction_col, pred))
        out = set_label_metadata(out, self.prediction_col,
                                 num_classes=self.num_classes)
        return self._add_aux_cols(out, X)


class LightGBMRegressor(Estimator, _LightGBMParams, HasPredictionCol):
    objective = Param(str, default="regression",
                      doc="regression/l1/huber/quantile/poisson/tweedie/gamma")
    alpha = Param(float, default=0.9, doc="huber/quantile parameter")
    tweedie_variance_power = Param(float, default=1.5, doc="tweedie power")

    def _fit(self, df: DataFrame) -> "LightGBMRegressionModel":
        booster = self._fit_core(df, {
            "objective": self.objective, "alpha": self.alpha,
            "tweedie_variance_power": self.tweedie_variance_power})
        return LightGBMRegressionModel(
            booster, features_col=self.features_col,
            prediction_col=self.prediction_col,
            leaf_prediction_col=self.get_or_none("leaf_prediction_col"),
            features_shap_col=self.get_or_none("features_shap_col"))


class LightGBMRegressionModel(_LightGBMModelBase):
    def _transform(self, df: DataFrame) -> DataFrame:
        X = self._features(df)
        pred = self.booster.predict(X).astype(np.float64)
        return self._add_aux_cols(df.with_column(self.prediction_col, pred), X)


class LightGBMRanker(Estimator, _LightGBMParams, HasPredictionCol):
    group_col = Param(str, default="group", doc="query-group column")
    evaluate_at = Param((list, int), default=[5], doc="NDCG@k positions")

    def _fit(self, df: DataFrame) -> "LightGBMRankerModel":
        booster = self._fit_core(df, {"objective": "lambdarank"},
                                 group_col=self.group_col)
        return LightGBMRankerModel(
            booster, features_col=self.features_col,
            prediction_col=self.prediction_col,
            leaf_prediction_col=self.get_or_none("leaf_prediction_col"),
            features_shap_col=self.get_or_none("features_shap_col"))


class LightGBMRankerModel(_LightGBMModelBase):
    def _transform(self, df: DataFrame) -> DataFrame:
        X = self._features(df)
        pred = self.booster.predict(X, raw_score=True).astype(np.float64)
        return self._add_aux_cols(df.with_column(self.prediction_col, pred), X)
