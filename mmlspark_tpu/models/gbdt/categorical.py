"""Categorical feature handling for the GBDT trainer.

Parity surface: the reference detects categorical slots from SparkML
attribute metadata and passes them to LightGBM's native
``categorical_feature`` handling (``LightGBMBase.scala:168-199``), where
splits are optimal category *subsets* found per node by sorting categories
by gradient statistics (Fisher's trick).

TPU-first redesign: the subset search is approximated **statically** — each
categorical feature's values are re-indexed once per fit by their mean
target (the same sufficient ordering LightGBM computes per node, evaluated
globally), so ordinary threshold splits over the encoded rank correspond to
contiguous runs of label-ordered categories. This keeps every tree kernel
(histogram build, split scan, routing, TreeSHAP) untouched and static-
shaped; the encoder persists inside the booster and is applied on the raw
matrix before binning/prediction.

Unseen categories at predict time encode as NaN → the missing bin.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

__all__ = ["CategoricalEncoder"]


class CategoricalEncoder:
    """Label-ordered rank encoding of selected feature columns.

    Regularization knobs mirror LightGBM's categorical parameters in this
    static setting: ``cat_smooth`` smooths each category's target mean
    toward the global mean with that many pseudo-counts (rare categories'
    noisy means stop dominating the ordering), and ``min_data_per_group``
    pools categories rarer than the threshold into one shared rank — a
    threshold split can then never isolate them, the analog of LightGBM
    refusing per-category treatment below its group-size floor. Because
    this pooling is GLOBAL (LightGBM's is per-node, far weaker), it
    defaults to off here — a deliberate deviation from LightGBM's 100.
    Pooling also skips when every category is rare (nothing to pool into).
    """

    def __init__(self, feature_indices: Sequence[int],
                 cat_smooth: float = 10.0, min_data_per_group: int = 0):
        self.feature_indices: List[int] = sorted(int(i)
                                                 for i in set(feature_indices))
        if cat_smooth < 0:
            raise ValueError("cat_smooth must be >= 0")
        if min_data_per_group < 0:
            raise ValueError("min_data_per_group must be >= 0")
        self.cat_smooth = float(cat_smooth)
        self.min_data_per_group = int(min_data_per_group)
        #: per feature: category values sorted ascending (lookup keys)
        self.values: List[np.ndarray] = []
        #: per feature: rank of each value under the label ordering
        self.ranks: List[np.ndarray] = []

    # -- fit ----------------------------------------------------------------
    def fit(self, X: np.ndarray, y: np.ndarray) -> "CategoricalEncoder":
        self.values, self.ranks = [], []
        y = np.asarray(y, dtype=np.float64)
        for j in self.feature_indices:
            col = np.asarray(X[:, j], dtype=np.float64)
            ok = ~np.isnan(col)
            uniq, inv = np.unique(col[ok], return_inverse=True)
            sums = np.bincount(inv, weights=y[ok], minlength=len(uniq))
            cnts = np.bincount(inv, minlength=len(uniq)).astype(np.float64)
            gmean = float(y[ok].mean()) if ok.any() else 0.0
            mean = ((sums + self.cat_smooth * gmean)
                    / np.maximum(cnts + self.cat_smooth, 1e-12))
            if self.min_data_per_group > 0:
                rare = cnts < self.min_data_per_group
                if rare.any() and not rare.all():
                    pooled = ((sums[rare].sum() + self.cat_smooth * gmean)
                              / (cnts[rare].sum() + self.cat_smooth))
                    mean[rare] = pooled
            # equal means share one rank (pooled/tied categories become
            # inseparable by any threshold split)
            _, rank = np.unique(mean, return_inverse=True)
            self.values.append(uniq)
            self.ranks.append(rank.astype(np.float64))
        return self

    # -- transform ----------------------------------------------------------
    def transform(self, X: np.ndarray) -> np.ndarray:
        """Return a float copy with categorical columns replaced by their
        label-ordered ranks (unseen values / NaN → NaN → missing bin)."""
        if not self.feature_indices:
            return X
        # preserve float width: ranks are small integers (exact in float32)
        # and a HIGGS-scale float32 matrix must not silently double
        dt = X.dtype if np.asarray(X).dtype.kind == "f" else np.float64
        out = np.array(X, dtype=dt, copy=True)
        for (j, vals, rank) in zip(self.feature_indices, self.values,
                                   self.ranks):
            col = out[:, j]
            idx = np.searchsorted(vals, col)
            idx_c = np.clip(idx, 0, max(len(vals) - 1, 0))
            seen = (len(vals) > 0) & np.isfinite(col) \
                & (vals[idx_c] == col) if len(vals) else np.zeros(len(col),
                                                                  bool)
            enc = np.where(seen, rank[idx_c] if len(vals) else 0.0, np.nan)
            out[:, j] = enc
        return out

    # -- persistence --------------------------------------------------------
    def to_dict(self) -> Dict:
        return {"feature_indices": self.feature_indices,
                "values": [v.tolist() for v in self.values],
                "ranks": [r.tolist() for r in self.ranks]}

    @staticmethod
    def from_dict(d: Dict) -> "CategoricalEncoder":
        enc = CategoricalEncoder(d["feature_indices"])
        enc.values = [np.asarray(v, dtype=np.float64) for v in d["values"]]
        enc.ranks = [np.asarray(r, dtype=np.float64) for r in d["ranks"]]
        return enc
