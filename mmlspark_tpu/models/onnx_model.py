"""ONNXModel — batched ONNX inference on TPU through the DataFrame API.

Parity surface: the reference's ``ONNXModel``
(``deep-learning/.../onnx/ONNXModel.scala``):

* ``feed_dict`` {model input → column} / ``fetch_dict`` {column → model
  output} (`SharedParams.scala:9-33`)
* ``softmax_dict`` / ``argmax_dict`` post-ops (`ONNXModel.scala:519-562`)
* minibatch → coerce → run per partition → flatten (`ONNXModel.scala:482-517`)
* device selection per partition (`ONNXModel.scala:293-303`) → here chips
  round-robin via ``parallel.device_for_partition``.

TPU-first differences: the graph is compiled by XLA (no ORT session); batches
are padded to power-of-two buckets so the jit cache stays small
(`ops/padding.py`); model I/O metadata comes from the proto directly
(`ONNXModel.scala:437-457` needs a live ORT session for this).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Model, Transformer
from ..onnx.convert import ConvertedModel, convert_model
from ..ops.padding import bucket_size, pad_axis
from ..parallel.mesh import device_for_partition
from ..stages.batching import FixedMiniBatchTransformer, FlattenBatch, batch_slices

__all__ = ["ONNXModel"]


class ONNXModel(Model):
    model_bytes = ComplexParam(doc="serialized ONNX ModelProto")
    feed_dict = Param(dict, default={}, doc="{model input name: dataframe column}")
    fetch_dict = Param(dict, default={}, doc="{output column: model output name}")
    mini_batch_size = Param(int, default=64, doc="rows per device batch")
    softmax_dict = Param(dict, default={}, doc="{output col: col to softmax}")
    argmax_dict = Param(dict, default={}, doc="{output col: col to argmax}")
    compute_dtype = Param(str, default="float32",
                          doc="cast float inputs/params to this dtype "
                              "(bfloat16 recommended on TPU)")
    pin_devices = Param(bool, default=True,
                        doc="round-robin partitions over local chips")

    def __init__(self, model_bytes: Optional[bytes] = None, **kw):
        super().__init__(**kw)
        if model_bytes is not None:
            self.set(model_bytes=model_bytes)
        self._converted: Optional[ConvertedModel] = None
        self._jitted = None
        self._device_params: Dict[int, dict] = {}

    # -- metadata (proto-only, no session) ----------------------------------
    def _ensure_converted(self) -> ConvertedModel:
        if self._converted is None:
            self._converted = convert_model(self.get("model_bytes"))
            self._jitted = jax.jit(self._converted.__call__)
        return self._converted

    def model_inputs(self) -> Dict[str, tuple]:
        cm = self._ensure_converted()
        return {vi.name: (vi.numpy_dtype, tuple(vi.shape)) for vi in cm.inputs}

    def model_outputs(self) -> Dict[str, tuple]:
        cm = self._ensure_converted()
        return {vi.name: (vi.numpy_dtype, tuple(vi.shape)) for vi in cm.outputs}

    # -- column coercion (parity: ONNXModel.coerceBatchedDf :564-584) -------
    def _coerce(self, col: np.ndarray, dtype, shape) -> np.ndarray:
        if col.dtype == object:
            col = np.stack([np.asarray(v) for v in col])
        arr = np.asarray(col)
        want = np.dtype(dtype)
        if want.kind == "f" and self.compute_dtype != "float32":
            want = jnp.dtype(self.compute_dtype)
        if arr.dtype != want:
            arr = arr.astype(want)
        # reshape flat rows to the model's per-row shape if one is declared
        row_shape = [d for d in shape[1:] if isinstance(d, int)]
        if row_shape and list(arr.shape[1:]) != row_shape \
                and int(np.prod(arr.shape[1:])) == int(np.prod(row_shape)):
            arr = arr.reshape((arr.shape[0],) + tuple(row_shape))
        return arr

    def _params_for_device(self, device) -> dict:
        key = id(device)
        if key not in self._device_params:
            cm = self._ensure_converted()
            params = cm.params
            if self.compute_dtype != "float32":
                dt = jnp.dtype(self.compute_dtype)
                params = {k: (v.astype(dt) if np.issubdtype(v.dtype, np.floating)
                              else v) for k, v in params.items()}
            self._device_params[key] = jax.device_put(params, device)
        return self._device_params[key]

    # -- execution ----------------------------------------------------------
    def _run_batches(self, part: DataFrame, pidx: int) -> DataFrame:
        cm = self._ensure_converted()
        feed = self.feed_dict or {cm.input_names[0]: part.columns[0]}
        fetch = self.fetch_dict or {n: n for n in cm.output_names}
        in_meta = {vi.name: vi for vi in cm.inputs}

        device = device_for_partition(pidx) if self.pin_devices else None
        params = self._params_for_device(device) if device is not None \
            else self._params_for_device(jax.devices()[0])

        n = len(part)
        out_cols: Dict[str, List[np.ndarray]] = {c: [] for c in fetch}
        for sl in batch_slices(n, self.mini_batch_size):
            feeds = {}
            b = None
            for input_name, col_name in feed.items():
                vi = in_meta[input_name]
                arr = self._coerce(part[col_name][sl], vi.numpy_dtype, vi.shape)
                b = len(arr)
                target = bucket_size(b)
                arr = pad_axis(arr, target)
                feeds[input_name] = jax.device_put(arr, device)
            outs = self._jitted(params, feeds)
            for col_name, out_name in fetch.items():
                res = np.asarray(outs[out_name])[:b]
                out_cols[col_name].append(res)
        merged = {}
        for col_name, chunks in out_cols.items():
            if chunks:
                merged[col_name] = np.concatenate(chunks)
            else:
                merged[col_name] = np.zeros((0,))
        out = part
        for col_name, arr in merged.items():
            vals = np.empty(len(arr), dtype=object)
            for i in range(len(arr)):
                vals[i] = arr[i]
            out = out.with_column(col_name, vals if arr.ndim > 1 else arr)
        return out

    def _transform(self, df: DataFrame) -> DataFrame:
        out = df.map_partitions(self._run_batches)
        # post-ops (parity: softMaxTransform/argMaxTransform :519-562)
        for out_col, src_col in self.softmax_dict.items():
            col = out[src_col]
            probs = np.empty(len(col), dtype=object)
            for i, v in enumerate(col):
                v = np.asarray(v, dtype=np.float64)
                e = np.exp(v - v.max(axis=-1, keepdims=True))
                probs[i] = e / e.sum(axis=-1, keepdims=True)
            out = out.with_column(out_col, probs)
        for out_col, src_col in self.argmax_dict.items():
            col = out[src_col]
            out = out.with_column(
                out_col,
                np.asarray([int(np.argmax(np.asarray(v))) for v in col],
                           dtype=np.int64))
        return out

    # -- persistence: rebuild session state after load ----------------------
    def _load_extra(self, path: str) -> None:
        self._converted = None
        self._jitted = None
        self._device_params = {}
