"""ONNXModel — batched ONNX inference on TPU through the DataFrame API.

Parity surface: the reference's ``ONNXModel``
(``deep-learning/.../onnx/ONNXModel.scala``):

* ``feed_dict`` {model input → column} / ``fetch_dict`` {column → model
  output} (`SharedParams.scala:9-33`)
* ``softmax_dict`` / ``argmax_dict`` post-ops (`ONNXModel.scala:519-562`)
* minibatch → coerce → run per partition → flatten (`ONNXModel.scala:482-517`)
* device selection per partition (`ONNXModel.scala:293-303`) → here chips
  round-robin via ``parallel.device_for_partition``.

TPU-first differences: the graph is compiled by XLA (no ORT session); batches
are padded to power-of-two buckets so the jit cache stays small
(`ops/padding.py`); model I/O metadata comes from the proto directly
(`ONNXModel.scala:437-457` needs a live ORT session for this).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Model, Transformer
from ..onnx.convert import ConvertedModel, convert_model
from ..ops.padding import bucket_size, pad_axis
from ..parallel.mesh import batch_placement, local_devices
from ..stages.batching import FixedMiniBatchTransformer, FlattenBatch, batch_slices

__all__ = ["ONNXModel"]


class ONNXModel(Model):
    model_bytes = ComplexParam(doc="serialized ONNX ModelProto")
    feed_dict = Param(dict, default={}, doc="{model input name: dataframe column}")
    fetch_dict = Param(dict, default={}, doc="{output column: model output name}")
    mini_batch_size = Param(int, default=64, doc="rows per device batch")
    softmax_dict = Param(dict, default={}, doc="{output col: col to softmax}")
    argmax_dict = Param(dict, default={}, doc="{output col: col to argmax}")
    compute_dtype = Param(str, default="float32",
                          doc="cast float inputs/params to this dtype "
                              "(bfloat16 recommended on TPU)")
    normalize_dict = Param(dict, default={},
                           doc="{model input: {scale, mean, std}} applied on "
                               "device after the dtype cast — the tensor "
                               "normalization the reference does host-side in "
                               "ImageTransformer (ImageTransformer.scala:417+) "
                               "fused into the XLA graph; mean/std broadcast "
                               "over the channel axis (axis 1)")
    transpose_dict = Param(dict, default={},
                           doc="{model input: permutation} applied on device "
                               "before normalization, e.g. NHWC uint8 images "
                               "to the NCHW the graph expects: [0, 3, 1, 2]")
    pin_devices = Param(bool, default=True,
                        doc="round-robin partitions over local chips")
    mesh_sharded = Param(bool, default=False,
                         doc="SPMD inference: shard each batch's leading "
                             "axis over the default mesh's first axis "
                             "(params replicated) — one XLA program spans "
                             "every chip instead of one partition per chip. "
                             "Install a mesh with MeshContext/"
                             "set_default_mesh; overrides pin_devices")
    external_data_dir = Param(str, default="",
                              doc="directory with sidecar files for models "
                                  "saved with external data")
    weights_override = ComplexParam(default=None,
                                    doc="npz payload of fine-tuned params "
                                        "layered over the graph's own "
                                        "initializers (ONNXEstimator.fit "
                                        "sets this; the original model "
                                        "bytes stay untouched)")
    quantize = Param(str, default="", choices=["", "int8"],
                     doc="weight-only quantization: 2-D float weights live "
                         "in HBM as symmetric per-column int8 + scale and "
                         "dequantize on device (XLA fuses the multiply "
                         "into the consumer matmul) — 4x less weight "
                         "bandwidth, activations stay in compute_dtype")

    def __init__(self, model_bytes: Optional[bytes] = None, **kw):
        super().__init__(**kw)
        if model_bytes is not None:
            self.set(model_bytes=model_bytes)
        self._converted: Optional[ConvertedModel] = None
        self._jitted = None
        self._jit_sig = None
        self._fused_cols: set = set()
        self._argmax_cols: set = set()
        self._out_col_names: List[str] = []
        self._device_params: Dict[Optional[int], dict] = {}
        self._params_lock = threading.Lock()

    # -- metadata (proto-only, no session) ----------------------------------
    def _ensure_converted(self) -> ConvertedModel:
        if self._converted is None:
            self._converted = convert_model(
                self.get("model_bytes"),
                external_data_dir=self.external_data_dir or None)
        return self._converted

    def _fetch_map(self, cm: ConvertedModel) -> Dict[str, str]:
        return dict(self.fetch_dict) or {n: n for n in cm.output_names}

    def _ensure_jitted(self):
        """One jitted program: model graph + softmax/argmax post-ops fused.

        The reference applies softmax/argmax as per-row UDFs *after* the
        inference pass (``ONNXModel.scala:519-562``); on TPU those are free
        when fused into the XLA graph, so outputs cross the host boundary
        exactly once.
        """
        cm = self._ensure_converted()
        fetch = self._fetch_map(cm)
        softmax = {k: v for k, v in self.softmax_dict.items() if v in fetch}
        argmax = {k: v for k, v in self.argmax_dict.items() if v in fetch}
        normalize = dict(self.normalize_dict)
        transpose = dict(self.transpose_dict)
        float_inputs = {vi.name for vi in cm.inputs
                        if np.issubdtype(vi.numpy_dtype, np.floating)}
        bad_norm = set(normalize) - float_inputs
        if bad_norm:
            # normalizing an integer-typed model input would silently zero it
            # (e.g. uint8 * 1/255 truncates); the uint8-image case is a float
            # model input fed an int column, which is fine
            raise ValueError(
                f"normalize_dict targets non-float model inputs {sorted(bad_norm)}; "
                f"normalization requires a float-typed graph input")
        compute_dt = jnp.dtype(self.compute_dtype)
        sig = (tuple(sorted(fetch.items())), tuple(sorted(softmax.items())),
               tuple(sorted(argmax.items())),
               tuple(sorted((k, str(v)) for k, v in normalize.items())),
               tuple(sorted((k, tuple(v)) for k, v in transpose.items())),
               str(compute_dt), self.quantize)
        if self._jitted is None or self._jit_sig != sig:
            if set(fetch.values()) != set(cm.output_names):
                # dead-node elimination from the requested outputs: a
                # training graph (loss output + labels input) serves
                # inference on just its prediction outputs with the loss
                # subtree pruned away (no dummy label feeds at serving
                # time), and fetching an internal tensor name works too —
                # the cut-layer read ImageFeaturizer's reference does by
                # re-exporting a truncated model. Inside the jit-miss
                # branch: the ancestor walk is trace-time work, not
                # per-partition overhead.
                cm = cm.pruned(sorted(set(fetch.values())))
            def prep(name, x):
                """On-device input prep: layout, dtype cast, normalization.

                Feeds cross the host→device link in the column's native dtype
                (uint8 images are 4x smaller than float32, and a host-side
                bfloat16 cast would both burn CPU and hit the slow narrow-type
                transfer path); all massaging happens on device where it is
                fused into the first convolution's input.
                """
                perm = transpose.get(name)
                if perm is not None:
                    x = jnp.transpose(x, perm)
                if name in float_inputs and x.dtype != compute_dt:
                    x = x.astype(compute_dt)
                spec = normalize.get(name)
                if spec:
                    scale = spec.get("scale")
                    if scale is not None:
                        x = x * jnp.asarray(scale, x.dtype)
                    mean = spec.get("mean")
                    if mean is not None:
                        m = jnp.asarray(mean, x.dtype)
                        x = x - m.reshape((1, -1) + (1,) * (x.ndim - 2))
                    std = spec.get("std")
                    if std is not None:
                        s = jnp.asarray(std, x.dtype)
                        x = x / s.reshape((1, -1) + (1,) * (x.ndim - 2))
                return x

            def run(params, feeds):
                feeds = {k: prep(k, v) for k, v in feeds.items()}
                params = self._unpack_params(params, compute_dt)
                outs = cm(params, feeds)
                cols = {col: outs[name] for col, name in fetch.items()}
                for out_col, src in softmax.items():
                    cols[out_col] = jax.nn.softmax(
                        cols[src].astype(jnp.float32), axis=-1)
                for out_col, src in argmax.items():
                    cols[out_col] = jnp.argmax(cols[src], axis=-1).astype(jnp.int32)
                return cols

            self._jitted = jax.jit(run)
            self._jit_sig = sig
            self._fused_cols = set(softmax) | set(argmax)
            self._argmax_cols = set(argmax)
            self._out_col_names = list(fetch) + \
                [c for c in self._fused_cols if c not in fetch]
        return self._jitted

    def model_inputs(self) -> Dict[str, tuple]:
        cm = self._ensure_converted()
        return {vi.name: (vi.numpy_dtype, tuple(vi.shape)) for vi in cm.inputs}

    def model_outputs(self) -> Dict[str, tuple]:
        cm = self._ensure_converted()
        return {vi.name: (vi.numpy_dtype, tuple(vi.shape)) for vi in cm.outputs}

    # -- column coercion (parity: ONNXModel.coerceBatchedDf :564-584) -------
    def _coerce(self, col: np.ndarray, dtype, shape,
                device_prepped: bool = False) -> np.ndarray:
        if col.dtype == object:
            col = np.stack([np.asarray(v) for v in col])
        arr = np.asarray(col)
        want = np.dtype(dtype)
        if want.kind == "f":
            # floats cross the wire as-is (except f64, halved to f32: the
            # model can't use the precision and transfer is the bottleneck);
            # the cast to compute_dtype happens on device in the jitted prep
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            elif arr.dtype.kind not in "fiu":
                arr = arr.astype(np.float32)
        elif arr.dtype != want:
            arr = arr.astype(want)
        if device_prepped:
            return arr  # layout handled on device; shape is not NCHW yet
        # reshape flat rows to the model's per-row shape if one is declared
        row_shape = [d for d in shape[1:] if isinstance(d, int)]
        if row_shape and list(arr.shape[1:]) != row_shape \
                and int(np.prod(arr.shape[1:])) == int(np.prod(row_shape)):
            arr = arr.reshape((arr.shape[0],) + tuple(row_shape))
        return arr

    def _cast_params(self, params: dict) -> dict:
        """Float params → compute_dtype, on whatever devices hold them."""
        if self.compute_dtype == "float32":
            return params
        dt = jnp.dtype(self.compute_dtype)
        cast = jax.jit(
            lambda p: {k: (v.astype(dt)
                           if jnp.issubdtype(v.dtype, jnp.floating)
                           else v) for k, v in p.items()})
        return cast(params)

    # -- int8 weight-only quantization --------------------------------------
    _QUANT_MIN_DIM = 16

    def _quantizable(self, v) -> bool:
        """2-D float weights (the matmul bulk of transformer/MLP graphs);
        conv kernels (4-D) and vectors stay full precision."""
        return (v.ndim == 2 and jnp.issubdtype(v.dtype, jnp.floating)
                and min(v.shape) >= self._QUANT_MIN_DIM)

    def _pack_params(self, params: dict) -> dict:
        """Symmetric per-column int8 packing: HBM holds q (int8) + a
        per-column scale; the jitted run dequantizes on device, where XLA
        fuses the multiply into the consumer matmul — weight reads cost
        1/4 the bandwidth (weight-ONLY quantization: activations and
        accumulation stay in compute_dtype)."""
        @jax.jit
        def pack(p):
            out = {}
            for k, v in p.items():
                if self._quantizable(v):
                    v32 = v.astype(jnp.float32)
                    s = jnp.max(jnp.abs(v32), axis=0, keepdims=True) / 127.0
                    s = jnp.where(s == 0, jnp.float32(1.0), s)
                    q = jnp.clip(jnp.round(v32 / s), -127, 127) \
                        .astype(jnp.int8)
                    out[k] = {"q": q, "s": s}
                else:
                    out[k] = v
            return out
        return pack(params)

    @staticmethod
    def _unpack_params(params: dict, dt) -> dict:
        return {k: ((v["q"].astype(dt) * v["s"].astype(dt))
                    if isinstance(v, dict) else v)
                for k, v in params.items()}

    def _effective_params(self, cm: ConvertedModel) -> dict:
        """Graph initializers with any fine-tuned override layered on top
        (``weights_override`` npz — set by ONNXEstimator.fit)."""
        ov = self.get_or_none("weights_override")
        if not ov:
            return cm.params
        import io
        with np.load(io.BytesIO(ov)) as z:
            override = {k: z[k] for k in z.files}
        unknown = sorted(set(override) - set(cm.params))
        if unknown:
            raise ValueError(
                f"weights_override names unknown params {unknown[:5]} "
                "(the override must come from this graph's fine-tune)")
        return {**cm.params, **override}

    def set(self, **kwargs):
        if ("weights_override" in kwargs or "quantize" in kwargs) \
                and getattr(self, "_device_params", None):
            # cached device params embed the previous override/packing —
            # drop them so the change takes effect (an id()-keyed cache
            # would risk stale hits after the old payload's address is
            # reused). getattr: Params.__init__ may route constructor
            # kwargs through set() before __init__ has built the caches.
            with self._params_lock:
                self._device_params.clear()
        return super().set(**kwargs)

    def _params_for_device(self, device) -> dict:
        if device is None:
            # normalize to the concrete default device so pinned and
            # unpinned callers share one cached weight copy
            devs = local_devices()
            device = devs[0] if devs else None
        key = id(device) if device is not None else None
        with self._params_lock:
            if key not in self._device_params:
                cm = self._ensure_converted()
                # transfer in f32, cast on device: narrow-dtype host buffers
                # (bfloat16) take a slow serialization path over the link
                # params are committed to `device`; the cast jit follows
                # its operands
                p = self._cast_params(
                    jax.device_put(self._effective_params(cm), device))
                if self.quantize == "int8":
                    p = self._pack_params(p)
                self._device_params[key] = p
            return self._device_params[key]

    def _params_for_mesh(self, mesh) -> dict:
        """Weights replicated over the mesh (cached per mesh)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.mesh import replicated_sharding
        key = ("mesh", mesh)
        with self._params_lock:
            if key not in self._device_params:
                cm = self._ensure_converted()
                p = self._cast_params(
                    jax.device_put(self._effective_params(cm),
                                   replicated_sharding(mesh)))
                if self.quantize == "int8":
                    p = self._pack_params(p)
                self._device_params[key] = p
            return self._device_params[key]

    # -- execution ----------------------------------------------------------
    def _run_batches(self, part: DataFrame, pidx: int) -> DataFrame:
        """Dispatch every minibatch asynchronously, drain once at the end.

        JAX dispatch returns futures, so host coerce/pad of batch k+1
        overlaps device compute of batch k; outputs stay on device until the
        partition finishes (the reference's per-batch ``session.run`` +
        NIO-buffer marshalling, ``ONNXModel.scala:305-402``, is fully
        synchronous — this pipelining is the TPU-side throughput win).
        """
        cm = self._ensure_converted()
        jitted = self._ensure_jitted()
        feed = self.feed_dict or {cm.input_names[0]: part.columns[0]}
        in_meta = {vi.name: vi for vi in cm.inputs}

        mesh, device, shards, put = batch_placement(
            self.get("mesh_sharded"), pidx, self.pin_devices)
        params = (self._params_for_mesh(mesh) if mesh is not None
                  else self._params_for_device(device))

        n = len(part)
        pending = []  # (device outputs dict, valid rows) per batch, in order
        for sl in batch_slices(n, self.mini_batch_size):
            feeds = {}
            b = 0
            for input_name, col_name in feed.items():
                vi = in_meta[input_name]
                arr = self._coerce(part[col_name][sl], vi.numpy_dtype, vi.shape,
                                   device_prepped=input_name in self.transpose_dict)
                b = len(arr)
                # pad to the jit bucket AND to a multiple of the mesh's
                # batch-axis size so the leading dim shards evenly; the
                # explicit async put (even unpinned) enqueues the transfer
                # immediately so it overlaps the previous batch's compute
                padded = bucket_size(b)
                padded = -(-padded // shards) * shards
                arr = pad_axis(arr, padded)
                feeds[input_name] = put(arr)
            pending.append((jitted(params, feeds), b))

        out = part
        for col_name in self._out_col_names:
            chunks = [np.asarray(outs[col_name])[:b] for outs, b in pending]
            arr = np.concatenate(chunks) if chunks \
                else np.zeros((0,), dtype=np.float32)
            if arr.dtype == jnp.bfloat16:
                arr = arr.astype(np.float32)
            if col_name in self._argmax_cols:
                arr = arr.astype(np.int64)
            out = out.with_column(col_name, arr)
        return out

    def _transform(self, df: DataFrame) -> DataFrame:
        self._ensure_converted()
        self._ensure_jitted()
        out = df.map_partitions(self._run_batches)
        # host fallback for post-ops whose source column does not come out of
        # the jitted graph (parity: softMaxTransform/argMaxTransform :519-562)
        for out_col, src_col in self.softmax_dict.items():
            if out_col in self._fused_cols:
                continue
            out = out.with_column(out_col, _host_softmax(out[src_col]))
        for out_col, src_col in self.argmax_dict.items():
            if out_col in self._fused_cols:
                continue
            out = out.with_column(out_col, _host_argmax(out[src_col]))
        return out

    # -- persistence: rebuild session state after load ----------------------
    def _load_extra(self, path: str) -> None:
        self._converted = None
        self._jitted = None
        self._jit_sig = None
        self._fused_cols = set()
        self._argmax_cols = set()
        self._out_col_names = []
        self._device_params = {}
        self._params_lock = threading.Lock()


def _host_softmax(col: np.ndarray) -> np.ndarray:
    if col.dtype != object:
        v = np.asarray(col, dtype=np.float64)
        e = np.exp(v - v.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)
    probs = np.empty(len(col), dtype=object)
    for i, v in enumerate(col):
        v = np.asarray(v, dtype=np.float64)
        e = np.exp(v - v.max(axis=-1, keepdims=True))
        probs[i] = e / e.sum(axis=-1, keepdims=True)
    return probs


def _host_argmax(col: np.ndarray) -> np.ndarray:
    if col.dtype != object:
        return np.argmax(np.asarray(col), axis=-1).astype(np.int64)
    return np.asarray([int(np.argmax(np.asarray(v))) for v in col],
                      dtype=np.int64)
