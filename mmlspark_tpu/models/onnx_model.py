"""ONNXModel — batched ONNX inference on TPU through the DataFrame API.

Parity surface: the reference's ``ONNXModel``
(``deep-learning/.../onnx/ONNXModel.scala``):

* ``feed_dict`` {model input → column} / ``fetch_dict`` {column → model
  output} (`SharedParams.scala:9-33`)
* ``softmax_dict`` / ``argmax_dict`` post-ops (`ONNXModel.scala:519-562`)
* minibatch → coerce → run per partition → flatten (`ONNXModel.scala:482-517`)
* device selection per partition (`ONNXModel.scala:293-303`) → here chips
  round-robin via ``parallel.device_for_partition``.

TPU-first differences: the graph is compiled by XLA (no ORT session); batches
are padded to power-of-two buckets so the jit cache stays small
(`ops/padding.py`); model I/O metadata comes from the proto directly
(`ONNXModel.scala:437-457` needs a live ORT session for this).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Model
from ..onnx.convert import ConvertedModel, convert_model
from ..ops.compile_cache import (StageCounters, resolve_input_specs,
                                 warm_up_model)
from ..core.residency import DeviceColumn
from ..parallel.mesh import feed_placement, local_devices
from .runner import BatchRunner, StagingSlabPool

__all__ = ["ONNXModel"]


class ONNXModel(Model):
    model_bytes = ComplexParam(doc="serialized ONNX ModelProto")
    feed_dict = Param(dict, default={}, doc="{model input name: dataframe column}")
    fetch_dict = Param(dict, default={}, doc="{output column: model output name}")
    mini_batch_size = Param(int, default=64, doc="rows per device batch")
    softmax_dict = Param(dict, default={}, doc="{output col: col to softmax}")
    argmax_dict = Param(dict, default={}, doc="{output col: col to argmax}")
    compute_dtype = Param(str, default="float32",
                          doc="cast float inputs/params to this dtype "
                              "(bfloat16 recommended on TPU)")
    normalize_dict = Param(dict, default={},
                           doc="{model input: {scale, mean, std}} applied on "
                               "device after the dtype cast — the tensor "
                               "normalization the reference does host-side in "
                               "ImageTransformer (ImageTransformer.scala:417+) "
                               "fused into the XLA graph; mean/std broadcast "
                               "over the channel axis (axis 1)")
    transpose_dict = Param(dict, default={},
                           doc="{model input: permutation} applied on device "
                               "before normalization, e.g. NHWC uint8 images "
                               "to the NCHW the graph expects: [0, 3, 1, 2]")
    pin_devices = Param(bool, default=True,
                        doc="round-robin partitions over local chips")
    mesh_sharded = Param(bool, default=False,
                         doc="SPMD inference: shard each batch's leading "
                             "axis over the default mesh's first axis "
                             "(params replicated) — one XLA program spans "
                             "every chip instead of one partition per chip. "
                             "Install a mesh with MeshContext/"
                             "set_default_mesh; overrides pin_devices")
    external_data_dir = Param(str, default="",
                              doc="directory with sidecar files for models "
                                  "saved with external data")
    weights_override = ComplexParam(default=None,
                                    doc="npz payload of fine-tuned params "
                                        "layered over the graph's own "
                                        "initializers (ONNXEstimator.fit "
                                        "sets this; the original model "
                                        "bytes stay untouched)")
    quantize = Param(str, default="", choices=["", "int8"],
                     doc="weight-only quantization: 2-D float weights live "
                         "in HBM as symmetric per-column int8 + scale and "
                         "dequantize on device (XLA fuses the multiply "
                         "into the consumer matmul) — 4x less weight "
                         "bandwidth, activations stay in compute_dtype")
    prefetch_depth = Param(int, default=2,
                           doc="prepared batches coerced/padded ahead on a "
                               "background worker while the current batch "
                               "dispatches; bounds host memory at that many "
                               "padded batches. 0 = prepare inline on the "
                               "dispatch thread")
    output_device = Param(bool, default=False,
                          doc="keep fetch outputs device-resident (attached "
                              "as DeviceColumns, no drain) so a downstream "
                              "device stage or sink pays the single d2h; "
                              "outputs keep their device dtypes (bf16 stays "
                              "bf16, argmax stays int32) until "
                              "DataFrame.to_host materializes them")
    buckets = Param((list, int), default=[],
                    doc="custom padding-bucket ladder (sorted batch sizes); "
                        "empty = next-power-of-two. Warm-up and the runner "
                        "derive every padded shape through the same ladder, "
                        "so only these buckets ever compile")
    tuning = Param(str, default="", choices=["", "auto"],
                   doc="'auto' consults the measurement-driven tuning store "
                       "(MMLSPARK_TPU_TUNING_DIR) at transform/warm_up: the "
                       "fitted cost model picks mini_batch_size, "
                       "prefetch_depth and the bucket ladder for the "
                       "observed row counts; a cold store keeps the "
                       "defaults and this run's measurements train it")

    def __init__(self, model_bytes: Optional[bytes] = None, **kw):
        super().__init__(**kw)
        if model_bytes is not None:
            self.set(model_bytes=model_bytes)
        self._converted: Optional[ConvertedModel] = None
        self._jitted = None
        self._jit_sig = None
        self._fused_cols: set = set()
        self._argmax_cols: set = set()
        self._out_col_names: List[str] = []
        self._device_params: Dict[Optional[int], dict] = {}
        self._params_lock = threading.Lock()
        self._counters = StageCounters()
        self._staging = StagingSlabPool()
        self._tuning_sig: Optional[str] = None
        self._tuning_decisions: Dict[tuple, object] = {}

    @property
    def stage_counters(self) -> StageCounters:
        """coerce/pad/h2d/compile/dispatch/d2h instrumentation, cumulative
        over every transform/warm_up on this instance."""
        return self._counters

    # -- tuning --------------------------------------------------------------
    def tuning_signature(self) -> str:
        """Stable identity for the observation store: content hash of the
        graph plus the knobs that change its cost profile."""
        sig = getattr(self, "_tuning_sig", None)
        if sig is None:
            from ..onnx.proto import model_content_digest
            mb = self.get_or_none("model_bytes") or b""
            h = model_content_digest(bytes(mb))[:16]
            sig = f"onnx:{h}:{self.compute_dtype}:{self.quantize or 'fp'}"
            self._tuning_sig = sig
        return sig

    def _resolve_tuning(self, histogram: Dict[int, int]):
        """The store's pick for this histogram (None = off or cold store).
        Resolved sig-wide (placement "default"): one vocabulary serves all
        chips, so warm-up and every partition agree on the ladder."""
        if self.get_or_none("tuning") != "auto":
            return None
        key = tuple(sorted(histogram.items()))
        if key not in self._tuning_decisions:
            from ..tuning.cost_model import resolve_tuning
            self._tuning_decisions[key] = resolve_tuning(
                self.tuning_signature(), "default", histogram,
                defaults=(self.mini_batch_size, self.prefetch_depth))
        return self._tuning_decisions[key]

    def _runner_config(self, n_rows: int):
        """Effective ``(mini_batch_size, prefetch_depth, ladder)`` — the
        Params unless ``tuning="auto"`` found a measured pick."""
        ladder = tuple(self.buckets) if self.get_or_none("buckets") else None
        decision = self._resolve_tuning({int(n_rows): 1})
        if decision is None:
            return self.mini_batch_size, self.prefetch_depth, ladder
        return (decision.mini_batch_size, decision.prefetch_depth,
                decision.buckets)

    # -- metadata (proto-only, no session) ----------------------------------
    def _ensure_converted(self) -> ConvertedModel:
        if self._converted is None:
            self._converted = convert_model(
                self.get("model_bytes"),
                external_data_dir=self.external_data_dir or None)
        return self._converted

    def _fetch_map(self, cm: ConvertedModel) -> Dict[str, str]:
        return dict(self.fetch_dict) or {n: n for n in cm.output_names}

    def _ensure_jitted(self):
        """One jitted program: model graph + softmax/argmax post-ops fused.

        The reference applies softmax/argmax as per-row UDFs *after* the
        inference pass (``ONNXModel.scala:519-562``); on TPU those are free
        when fused into the XLA graph, so outputs cross the host boundary
        exactly once.
        """
        cm = self._ensure_converted()
        fetch = self._fetch_map(cm)
        softmax = {k: v for k, v in self.softmax_dict.items() if v in fetch}
        argmax = {k: v for k, v in self.argmax_dict.items() if v in fetch}
        normalize = dict(self.normalize_dict)
        transpose = dict(self.transpose_dict)
        float_inputs = {vi.name for vi in cm.inputs
                        if np.issubdtype(vi.numpy_dtype, np.floating)}
        bad_norm = set(normalize) - float_inputs
        if bad_norm:
            # normalizing an integer-typed model input would silently zero it
            # (e.g. uint8 * 1/255 truncates); the uint8-image case is a float
            # model input fed an int column, which is fine
            raise ValueError(
                f"normalize_dict targets non-float model inputs {sorted(bad_norm)}; "
                f"normalization requires a float-typed graph input")
        compute_dt = jnp.dtype(self.compute_dtype)
        sig = (tuple(sorted(fetch.items())), tuple(sorted(softmax.items())),
               tuple(sorted(argmax.items())),
               tuple(sorted((k, str(v)) for k, v in normalize.items())),
               tuple(sorted((k, tuple(v)) for k, v in transpose.items())),
               str(compute_dt), self.quantize)
        if self._jitted is None or self._jit_sig != sig:
            if set(fetch.values()) != set(cm.output_names):
                # dead-node elimination from the requested outputs: a
                # training graph (loss output + labels input) serves
                # inference on just its prediction outputs with the loss
                # subtree pruned away (no dummy label feeds at serving
                # time), and fetching an internal tensor name works too —
                # the cut-layer read ImageFeaturizer's reference does by
                # re-exporting a truncated model. Inside the jit-miss
                # branch: the ancestor walk is trace-time work, not
                # per-partition overhead.
                cm = cm.pruned(sorted(set(fetch.values())))
            def prep(name, x):
                """On-device input prep: layout, dtype cast, normalization.

                Feeds cross the host→device link in the column's native dtype
                (uint8 images are 4x smaller than float32, and a host-side
                bfloat16 cast would both burn CPU and hit the slow narrow-type
                transfer path); all massaging happens on device where it is
                fused into the first convolution's input.
                """
                perm = transpose.get(name)
                if perm is not None:
                    x = jnp.transpose(x, perm)
                if name in float_inputs and x.dtype != compute_dt:
                    x = x.astype(compute_dt)
                spec = normalize.get(name)
                if spec:
                    scale = spec.get("scale")
                    if scale is not None:
                        x = x * jnp.asarray(scale, x.dtype)
                    mean = spec.get("mean")
                    if mean is not None:
                        m = jnp.asarray(mean, x.dtype)
                        x = x - m.reshape((1, -1) + (1,) * (x.ndim - 2))
                    std = spec.get("std")
                    if std is not None:
                        s = jnp.asarray(std, x.dtype)
                        x = x / s.reshape((1, -1) + (1,) * (x.ndim - 2))
                return x

            def run(params, feeds):
                feeds = {k: prep(k, v) for k, v in feeds.items()}
                params = self._unpack_params(params, compute_dt)
                outs = cm(params, feeds)
                cols = {col: outs[name] for col, name in fetch.items()}
                for out_col, src in softmax.items():
                    cols[out_col] = jax.nn.softmax(
                        cols[src].astype(jnp.float32), axis=-1)
                for out_col, src in argmax.items():
                    cols[out_col] = jnp.argmax(cols[src], axis=-1).astype(jnp.int32)
                return cols

            self._jitted = jax.jit(run)
            self._jit_sig = sig
            self._fused_cols = set(softmax) | set(argmax)
            self._argmax_cols = set(argmax)
            self._out_col_names = list(fetch) + \
                [c for c in self._fused_cols if c not in fetch]
        return self._jitted

    def model_inputs(self) -> Dict[str, tuple]:
        cm = self._ensure_converted()
        return {vi.name: (vi.numpy_dtype, tuple(vi.shape)) for vi in cm.inputs}

    def model_outputs(self) -> Dict[str, tuple]:
        cm = self._ensure_converted()
        return {vi.name: (vi.numpy_dtype, tuple(vi.shape)) for vi in cm.outputs}

    # -- column coercion (parity: ONNXModel.coerceBatchedDf :564-584) -------
    def _coerce(self, col: np.ndarray, dtype, shape,
                device_prepped: bool = False) -> np.ndarray:
        if col.dtype == object:
            col = np.stack([np.asarray(v) for v in col])
        arr = np.asarray(col)
        want = np.dtype(dtype)
        if want.kind == "f":
            # floats cross the wire as-is (except f64, halved to f32: the
            # model can't use the precision and transfer is the bottleneck);
            # the cast to compute_dtype happens on device in the jitted prep
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            elif arr.dtype.kind not in "fiu":
                arr = arr.astype(np.float32)
        elif arr.dtype != want:
            arr = arr.astype(want)
        if device_prepped:
            return arr  # layout handled on device; shape is not NCHW yet
        # reshape flat rows to the model's per-row shape if one is declared
        row_shape = [d for d in shape[1:] if isinstance(d, int)]
        if row_shape and list(arr.shape[1:]) != row_shape \
                and int(np.prod(arr.shape[1:])) == int(np.prod(row_shape)):
            arr = arr.reshape((arr.shape[0],) + tuple(row_shape))
        return arr

    def _coerce_device(self, arr, dtype, shape,
                       device_prepped: bool = False):
        """:meth:`_coerce` for an already-resident (device) column slice —
        same dtype/shape policy, but every op is a device op so the column
        never round-trips through host."""
        want = np.dtype(dtype)
        if want.kind == "f":
            if arr.dtype == jnp.float64:
                arr = arr.astype(jnp.float32)
        elif arr.dtype != want:
            arr = arr.astype(want)
        if device_prepped:
            return arr
        row_shape = [d for d in shape[1:] if isinstance(d, int)]
        if row_shape and list(arr.shape[1:]) != row_shape \
                and int(np.prod(arr.shape[1:])) == int(np.prod(row_shape)):
            arr = arr.reshape((arr.shape[0],) + tuple(row_shape))
        return arr

    def _cast_params(self, params: dict) -> dict:
        """Float params → compute_dtype, on whatever devices hold them."""
        if self.compute_dtype == "float32":
            return params
        dt = jnp.dtype(self.compute_dtype)
        cast = jax.jit(
            lambda p: {k: (v.astype(dt)
                           if jnp.issubdtype(v.dtype, jnp.floating)
                           else v) for k, v in p.items()})
        return cast(params)

    # -- int8 weight-only quantization --------------------------------------
    _QUANT_MIN_DIM = 16

    def _quantizable(self, v) -> bool:
        """2-D float weights (the matmul bulk of transformer/MLP graphs);
        conv kernels (4-D) and vectors stay full precision."""
        return (v.ndim == 2 and jnp.issubdtype(v.dtype, jnp.floating)
                and min(v.shape) >= self._QUANT_MIN_DIM)

    def _pack_params(self, params: dict) -> dict:
        """Symmetric per-column int8 packing: HBM holds q (int8) + a
        per-column scale; the jitted run dequantizes on device, where XLA
        fuses the multiply into the consumer matmul — weight reads cost
        1/4 the bandwidth (weight-ONLY quantization: activations and
        accumulation stay in compute_dtype)."""
        @jax.jit
        def pack(p):
            out = {}
            for k, v in p.items():
                if self._quantizable(v):
                    v32 = v.astype(jnp.float32)
                    s = jnp.max(jnp.abs(v32), axis=0, keepdims=True) / 127.0
                    s = jnp.where(s == 0, jnp.float32(1.0), s)
                    q = jnp.clip(jnp.round(v32 / s), -127, 127) \
                        .astype(jnp.int8)
                    out[k] = {"q": q, "s": s}
                else:
                    out[k] = v
            return out
        return pack(params)

    @staticmethod
    def _unpack_params(params: dict, dt) -> dict:
        return {k: ((v["q"].astype(dt) * v["s"].astype(dt))
                    if isinstance(v, dict) else v)
                for k, v in params.items()}

    def _effective_params(self, cm: ConvertedModel) -> dict:
        """Graph initializers with any fine-tuned override layered on top
        (``weights_override`` npz — set by ONNXEstimator.fit)."""
        ov = self.get_or_none("weights_override")
        if not ov:
            return cm.params
        import io
        with np.load(io.BytesIO(ov)) as z:
            override = {k: z[k] for k in z.files}
        unknown = sorted(set(override) - set(cm.params))
        if unknown:
            raise ValueError(
                f"weights_override names unknown params {unknown[:5]} "
                "(the override must come from this graph's fine-tune)")
        return {**cm.params, **override}

    _PARAM_CACHE_KEYS = ("weights_override", "quantize", "compute_dtype")

    def set(self, **kwargs):
        if any(k in kwargs for k in self._PARAM_CACHE_KEYS) \
                and getattr(self, "_device_params", None):
            # cached device params embed the previous override/packing/dtype
            # cast — drop them so the change takes effect (an id()-keyed
            # cache would risk stale hits after the old payload's address is
            # reused; a compute_dtype change used to leave bf16-cast params
            # serving a float32 run). getattr: Params.__init__ may route
            # constructor kwargs through set() before __init__ has built
            # the caches.
            with self._params_lock:
                self._device_params.clear()
        if kwargs and getattr(self, "_tuning_decisions", None) is not None:
            # any reconfiguration may change the model signature or the
            # defaults the tuner compares against
            self._tuning_decisions.clear()
            self._tuning_sig = None
        return super().set(**kwargs)

    def _params_for_device(self, device) -> dict:
        if device is None:
            # normalize to the concrete default device so pinned and
            # unpinned callers share one cached weight copy
            devs = local_devices()
            device = devs[0] if devs else None
        key = id(device) if device is not None else None
        with self._params_lock:
            if key not in self._device_params:
                cm = self._ensure_converted()
                # transfer in f32, cast on device: narrow-dtype host buffers
                # (bfloat16) take a slow serialization path over the link
                # params are committed to `device`; the cast jit follows
                # its operands. staging stays under the lock on purpose:
                # first touch per device must be single-flight — racing
                # threads would both device_put the full param tree
                p = self._cast_params(
                    jax.device_put(self._effective_params(cm), device))  # tpulint: disable=TPU014
                if self.quantize == "int8":
                    p = self._pack_params(p)
                self._device_params[key] = p
            return self._device_params[key]

    def _params_for_mesh(self, mesh) -> dict:
        """Weights replicated over the mesh (cached per mesh)."""
        from ..parallel.mesh import replicated_sharding
        key = ("mesh", mesh)
        with self._params_lock:
            if key not in self._device_params:
                cm = self._ensure_converted()
                # single-flight staging, as in _params_for_device
                p = self._cast_params(
                    jax.device_put(self._effective_params(cm),  # tpulint: disable=TPU014
                                   replicated_sharding(mesh)))
                if self.quantize == "int8":
                    p = self._pack_params(p)
                self._device_params[key] = p
            return self._device_params[key]

    # -- execution ----------------------------------------------------------
    def _placement_params(self, pidx: int):
        placement = feed_placement(
            self.get("mesh_sharded"), pidx, self.pin_devices)
        params = (self._params_for_mesh(placement.mesh)
                  if placement.mesh is not None
                  else self._params_for_device(placement.device))
        return placement, params

    def _run_batches(self, part: DataFrame, pidx: int) -> DataFrame:
        """One partition through the shared feed/drain pipeline.

        :class:`BatchRunner` overlaps all three host boundaries: coerce/pad
        of batch k+1 on a prefetch worker, async host→device puts at
        dispatch, ``copy_to_host_async`` per batch with ONE batched
        ``jax.device_get`` at partition end (the reference's per-batch
        ``session.run`` + NIO-buffer marshalling, ``ONNXModel.scala:305-402``,
        is fully synchronous — this pipelining is the TPU-side throughput
        win).
        """
        cm = self._ensure_converted()
        jitted = self._ensure_jitted()
        feed = self.feed_dict or {cm.input_names[0]: part.columns[0]}
        in_meta = {vi.name: vi for vi in cm.inputs}
        placement, params = self._placement_params(pidx)

        # resident input columns feed device slices straight through —
        # no host coercion, no padding slab, zero h2d payload (BatchRunner
        # counts the residency hits); one concat per partition, then every
        # batch slice is a cheap device view
        resident = {col_name: part.device_column(col_name).device_array()
                    for col_name in feed.values()
                    if part.is_resident(col_name)}

        def coerce(sl: slice) -> Dict[str, np.ndarray]:
            out = {}
            for input_name, col_name in feed.items():
                meta = in_meta[input_name]
                prepped = input_name in self.transpose_dict
                dev = resident.get(col_name)
                if dev is not None:
                    out[input_name] = self._coerce_device(
                        dev[sl], meta.numpy_dtype, meta.shape,
                        device_prepped=prepped)
                else:
                    out[input_name] = self._coerce(
                        part[col_name][sl], meta.numpy_dtype, meta.shape,
                        device_prepped=prepped)
            return out

        mbs, depth, ladder = self._runner_config(len(part))
        runner = BatchRunner(jitted, params, coerce, placement.put,
                             shards=placement.shards,
                             mini_batch_size=mbs,
                             prefetch_depth=depth,
                             counters=self._counters,
                             staging=self._staging,
                             buckets=ladder,
                             model_sig=self.tuning_signature(),
                             placement_key=str(placement.key))
        if self.output_device:
            # keep outputs resident: no drain — the sink (DataFrame.to_host
            # or a downstream device stage) decides when to cross back
            pending = runner.run(len(part))
            out = part
            for col_name in self._out_col_names:
                chunks = [outs[col_name][:b] for outs, b in pending if b]
                if not chunks:
                    chunks = [jnp.zeros((0,), dtype=jnp.float32)]
                out = out.with_device_column(
                    col_name, DeviceColumn.from_device(chunks))
            return out
        pending = runner.run_and_drain(len(part))

        out = part
        for col_name in self._out_col_names:
            chunks = [outs[col_name][:b] for outs, b in pending]
            arr = np.concatenate(chunks) if chunks \
                else np.zeros((0,), dtype=np.float32)
            if arr.dtype == jnp.bfloat16:
                arr = arr.astype(np.float32)
            if col_name in self._argmax_cols:
                arr = arr.astype(np.int64)
            out = out.with_column(col_name, arr)
        return out

    # -- AOT warm-up ---------------------------------------------------------
    def warm_up(self, batch_sizes: Optional[List[int]] = None,
                input_specs: Optional[Dict[str, tuple]] = None,
                background: bool = False):
        """Compile every padding-bucket shape ahead of first traffic.

        Runs one zero-filled batch per bucket through the jitted program on
        every placement real traffic can hit (each pinned chip, or the
        default mesh), so neither bench nor serving eats a compile stall
        mid-stream — and, with the persistent compilation cache enabled
        (``MMLSPARK_TPU_COMPILE_CACHE_DIR``), neither does the *next*
        process.

        ``batch_sizes`` defaults to ``[mini_batch_size]``; pass the expected
        ragged sizes too to pre-warm their buckets. ``input_specs`` maps a
        model input to its fed ``(dtype, per-row shape)`` and is required
        when a column feeds a different dtype/layout than the graph declares
        (e.g. uint8 HWC images into a float NCHW input via
        ``transpose_dict``) or when the declared shape is symbolic.
        ``background=True`` warms on a daemon thread and returns it;
        otherwise returns ``{"buckets", "compiles", "seconds",
        "placements"}``.
        """
        cm = self._ensure_converted()
        jitted = self._ensure_jitted()
        fed = dict(self.feed_dict) or {cm.input_names[0]: None}
        specs = resolve_input_specs(cm.inputs, fed, self.transpose_dict,
                                    overrides=input_specs)
        sizes = [int(b) for b in (batch_sizes or [self.mini_batch_size])]
        ladder = tuple(self.buckets) if self.get_or_none("buckets") else None
        decision = self._resolve_tuning({s: 1 for s in sizes})
        if decision is not None:
            # compile exactly the chosen vocabulary, not the full
            # power-of-two ladder
            sizes = list(decision.warm_up_sizes) or sizes
            ladder = decision.buckets
        return warm_up_model(self, jitted, specs, sizes,
                             background=background, buckets=ladder)

    def _transform(self, df: DataFrame) -> DataFrame:
        self._ensure_converted()
        self._ensure_jitted()
        out = df.map_partitions(self._run_batches)
        # host fallback for post-ops whose source column does not come out of
        # the jitted graph (parity: softMaxTransform/argMaxTransform :519-562)
        for out_col, src_col in self.softmax_dict.items():
            if out_col in self._fused_cols:
                continue
            out = out.with_column(out_col, _host_softmax(out[src_col]))
        for out_col, src_col in self.argmax_dict.items():
            if out_col in self._fused_cols:
                continue
            out = out.with_column(out_col, _host_argmax(out[src_col]))
        return out

    # -- persistence: rebuild session state after load ----------------------
    def _load_extra(self, path: str) -> None:
        self._converted = None
        self._jitted = None
        self._jit_sig = None
        self._fused_cols = set()
        self._argmax_cols = set()
        self._out_col_names = []
        # load-time rebuild of a just-deserialized instance: the lock
        # itself is recreated on the next line, so nothing can hold it
        # tpulint: disable=TPU012
        self._device_params = {}
        self._params_lock = threading.Lock()
        self._counters = StageCounters()
        self._staging = StagingSlabPool()
        self._tuning_sig = None
        self._tuning_decisions = {}


def _host_softmax(col: np.ndarray) -> np.ndarray:
    if col.dtype != object:
        v = np.asarray(col, dtype=np.float64)
        e = np.exp(v - v.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)
    probs = np.empty(len(col), dtype=object)
    for i, v in enumerate(col):
        v = np.asarray(v, dtype=np.float64)
        e = np.exp(v - v.max(axis=-1, keepdims=True))
        probs[i] = e / e.sum(axis=-1, keepdims=True)
    return probs


def _host_argmax(col: np.ndarray) -> np.ndarray:
    if col.dtype != object:
        return np.argmax(np.asarray(col), axis=-1).astype(np.int64)
    return np.asarray([int(np.argmax(np.asarray(v))) for v in col],
                      dtype=np.int64)
