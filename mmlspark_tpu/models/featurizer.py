"""Transfer-learning image featurization.

Parity: ``deep-learning/.../cntk/ImageFeaturizer.scala`` — wraps an inner
DNN, optionally cutting the head layers (``cutOutputLayers``,
``:100-108``): 0 = full model predictions (logits), 1 = headless features.
Auto-resizes images to the model's input shape and unrolls them into the
tensor feed (``:137-184``), dropping undecodable rows (``:176-180``).

TPU-first: the inner model is an :class:`~mmlspark_tpu.models.onnx_model.ONNXModel`
whose graph carries both ``logits`` and pre-head ``feat`` outputs, so cutting
layers is output selection on the same jitted XLA program — no graph surgery
per configuration.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from ..core.pipeline import Model
from ..image.schema import ImageSchema, decode_image
from ..image.unroll import _resize
from .onnx_model import ONNXModel

__all__ = ["ImageFeaturizer"]


class ImageFeaturizer(Model, HasInputCol, HasOutputCol):
    onnx_model = ComplexParam(default=None, doc="inner ONNXModel (or bytes)")
    cut_output_layers = Param(int, default=1,
                              doc="0 = logits, 1 = headless features "
                                  "(reference cutOutputLayers semantics)")
    input_size = Param(int, default=224, doc="model input H=W")
    channel_order = Param(str, default="rgb", choices=["rgb", "bgr"],
                          doc="channel order the model expects")
    scale = Param(float, default=1.0 / 255.0, doc="pixel scale factor")
    mean = Param((list, float), default=None, doc="per-channel mean (model order)")
    std = Param((list, float), default=None, doc="per-channel std (model order)")
    drop_na = Param(bool, default=True, doc="drop undecodable image rows")
    mini_batch_size = Param(int, default=64, doc="device batch size")
    feature_output = Param(str, default="feat", doc="graph output for features")
    logits_output = Param(str, default="logits", doc="graph output for logits")

    def __init__(self, onnx_model=None, **kw):
        super().__init__(**kw)
        self._set_default(input_col="image", output_col="features")
        if onnx_model is not None:
            self.set(onnx_model=onnx_model)

    def _inner(self) -> ONNXModel:
        m = self.get("onnx_model")
        if isinstance(m, (bytes, bytearray)):
            m = ONNXModel(bytes(m))
            self.set(onnx_model=m)
        if not isinstance(m, ONNXModel):
            raise TypeError("onnx_model must be an ONNXModel or ONNX bytes")
        return m

    def _prep_cell(self, cell) -> Optional[np.ndarray]:
        """image struct / bytes / array → HWC uint8.

        Host work stops at decode/resize/channel-order; the float scale,
        mean/std normalization, and HWC→CHW layout run ON DEVICE fused into
        the graph (the inner ONNXModel's transpose/normalize prep) — a
        uint8 image crosses the host→device link at 1/4 the bytes of the
        float32 tensor this method used to build, and the link is the
        bottleneck (BASELINE.md: config #4 was transfer-bound)."""
        if cell is None:
            return None
        if isinstance(cell, (bytes, bytearray)):
            cell = decode_image(bytes(cell))
            if cell is None:
                return None
        if ImageSchema.is_image(cell):
            img = np.asarray(cell["data"], dtype=np.uint8)  # HWC BGR
        else:
            img = np.asarray(cell, dtype=np.uint8)
            if img.ndim == 2:
                img = img[:, :, None]
        size = self.get("input_size")
        if img.shape[0] != size or img.shape[1] != size:
            img = _resize(img, size, size)
        if img.shape[-1] == 1:
            img = np.repeat(img, 3, axis=-1)
        if self.get("channel_order") == "rgb" and img.shape[-1] >= 3:
            img = img[:, :, [2, 1, 0] + list(range(3, img.shape[-1]))]
        return np.ascontiguousarray(img)

    def _transform(self, df: DataFrame) -> DataFrame:
        inner = self._inner()
        tensors = [self._prep_cell(c) for c in df[self.get("input_col")]]
        keep = np.asarray([t is not None for t in tensors], dtype=bool)
        cur = df
        if self.get("drop_na"):
            cur = cur.filter(keep)
            tensors = [t for t in tensors if t is not None]
        elif not keep.all():
            raise ValueError("undecodable image rows present and drop_na=False")
        if not tensors:
            return cur.with_column(self.get("output_col"),
                                   object_col([]))
        tensor_col = "__img_tensor__"
        feed_name = list(inner.model_inputs())[0]
        out_name = (self.get("feature_output") if self.get("cut_output_layers") >= 1
                    else self.get("logits_output"))
        staged = cur.with_column(tensor_col, object_col(tensors))
        norm = {"scale": float(self.get("scale"))}
        if self.get_or_none("mean") is not None:
            norm["mean"] = [float(v) for v in np.atleast_1d(self.get("mean"))]
        if self.get_or_none("std") is not None:
            norm["std"] = [float(v) for v in np.atleast_1d(self.get("std"))]
        inner = inner.copy({"feed_dict": {feed_name: tensor_col},
                            "fetch_dict": {self.get("output_col"): out_name},
                            "mini_batch_size": self.get("mini_batch_size"),
                            # uint8 HWC over the link; layout + normalize
                            # fuse into the graph on device
                            "transpose_dict": {feed_name: [0, 3, 1, 2]},
                            "normalize_dict": {feed_name: norm}})
        out = inner.transform(staged)
        return out.drop(tensor_col)
