"""Vision Transformer (ViT) for the model zoo — torch-exporter-style ONNX.

Widens the zoo's image family beyond CNNs: the reference's downloader
ships CNN image models consumed by ``ImageFeaturizer``
(``cntk/ImageFeaturizer.scala:100-108``); a ViT exercises the SAME
cut-layer surface (outputs named ``feat``/``logits``, the featurizer's
defaults) with a transformer body, so the featurizer, ONNXModel, int8
weight-only quantization, and fine-tuning all compose unchanged.

The export mirrors how torch serializes ViTs: patchify is a strided
``Conv`` + ``Reshape`` + ``Transpose``, the class token ``Expand``s over
a Shape-derived batch dim, encoder blocks are pre-LN attention/MLP, and
``feat`` is the final-LN class-token row. ``vit_reference`` is the
pure-numpy oracle the tests pin the converted graph against.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from ...onnx.builder import make_graph, make_model, make_node, \
    make_tensor_value_info
from .bert_onnx import _G, _gelu_np, _ln_np

__all__ = ["ViTConfig", "init_vit_params", "vit_reference",
           "export_vit_onnx"]


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 64
    patch: int = 16
    d_model: int = 128
    heads: int = 4
    layers: int = 4
    d_ff: int = 256
    num_classes: int = 10

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2


def init_vit_params(cfg: ViTConfig, seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    D, F = cfg.d_model, cfg.d_ff

    def w(*shape, scale=None):
        s = scale if scale is not None else 1.0 / np.sqrt(shape[0])
        return rng.normal(0, s, shape).astype(np.float32)

    p = {
        "patch.w": rng.normal(0, 0.02,
                              (D, 3, cfg.patch, cfg.patch)).astype(np.float32),
        "patch.b": np.zeros(D, np.float32),
        "cls": rng.normal(0, 0.02, (1, 1, D)).astype(np.float32),
        "pos": rng.normal(0, 0.02,
                          (1, cfg.n_patches + 1, D)).astype(np.float32),
        "final_ln.g": np.ones(D, np.float32),
        "final_ln.b": np.zeros(D, np.float32),
        "head.w": w(D, cfg.num_classes),
        "head.b": np.zeros(cfg.num_classes, np.float32),
    }
    for i in range(cfg.layers):
        for nm, shape in [("q", (D, D)), ("k", (D, D)), ("v", (D, D)),
                          ("o", (D, D)), ("ff1", (D, F)), ("ff2", (F, D))]:
            p[f"l{i}.{nm}.w"] = w(*shape)
            p[f"l{i}.{nm}.b"] = np.zeros(shape[1], np.float32)
        for ln in ("ln1", "ln2"):
            p[f"l{i}.{ln}.g"] = np.ones(D, np.float32)
            p[f"l{i}.{ln}.b"] = np.zeros(D, np.float32)
    return p


def vit_reference(params: Dict[str, np.ndarray], pixels: np.ndarray,
                  cfg: ViTConfig):
    """Numpy forward: pixels (B, 3, S, S) float32 → (feat (B, D),
    logits (B, classes)). Patchify exploits stride == kernel: a reshape
    + one matmul equals the strided conv."""
    B = pixels.shape[0]
    P, D, H = cfg.patch, cfg.d_model, cfg.heads
    hd = D // H
    n_side = cfg.image_size // P
    x = pixels.reshape(B, 3, n_side, P, n_side, P)
    x = x.transpose(0, 2, 4, 1, 3, 5).reshape(B, n_side * n_side, 3 * P * P)
    wp = params["patch.w"].reshape(D, 3 * P * P)
    x = x @ wp.T + params["patch.b"]                       # (B, N, D)
    x = np.concatenate([np.broadcast_to(params["cls"], (B, 1, D)), x],
                       axis=1) + params["pos"]
    N = x.shape[1]
    for i in range(cfg.layers):
        h = _ln_np(x, params[f"l{i}.ln1.g"], params[f"l{i}.ln1.b"])

        def heads(nm, h=h, i=i):
            t = h @ params[f"l{i}.{nm}.w"] + params[f"l{i}.{nm}.b"]
            return t.reshape(B, N, H, hd).transpose(0, 2, 1, 3)

        q, k, v = heads("q"), heads("k"), heads("v")
        s = q @ k.transpose(0, 1, 3, 2) / np.sqrt(hd)
        a = np.exp(s - s.max(-1, keepdims=True))
        a = a / a.sum(-1, keepdims=True)
        ctx = (a @ v).transpose(0, 2, 1, 3).reshape(B, N, D)
        x = x + ctx @ params[f"l{i}.o.w"] + params[f"l{i}.o.b"]
        h = _ln_np(x, params[f"l{i}.ln2.g"], params[f"l{i}.ln2.b"])
        h = _gelu_np(h @ params[f"l{i}.ff1.w"] + params[f"l{i}.ff1.b"])
        x = x + h @ params[f"l{i}.ff2.w"] + params[f"l{i}.ff2.b"]
    x = _ln_np(x, params["final_ln.g"], params["final_ln.b"])
    feat = x[:, 0]
    return feat, feat @ params["head.w"] + params["head.b"]


def export_vit_onnx(cfg: ViTConfig = ViTConfig(), seed: int = 0,
                    opset: int = 17,
                    params: Optional[Dict[str, np.ndarray]] = None) -> bytes:
    """Serialize the ViT as ONNX with outputs ``feat`` (class-token
    embedding, the ImageFeaturizer default) and ``logits``."""
    p = params if params is not None else init_vit_params(cfg, seed)
    D, H = cfg.d_model, cfg.heads
    hd = D // H
    g = _G(opset)
    g.inits.update(p)

    px = "pixel_values"
    conv = g.add("Conv", [px, "patch.w", "patch.b"],
                 strides=[cfg.patch, cfg.patch])            # (B, D, h, w)
    flat = g.add("Reshape", [conv, g.const(np.array([0, D, -1], np.int64))])
    toks = g.add("Transpose", [flat], perm=[0, 2, 1])       # (B, N, D)
    # cls token expands over the Shape-derived batch dim (torch's pattern)
    shp = g.add("Shape", [px])
    b_dim = g.add("Gather", [shp, g.const(np.array(0, np.int64))], axis=0)
    b_1d = g.unsqueeze(b_dim, [0])
    tgt = g.add("Concat", [b_1d, g.const(np.array([1, D], np.int64))],
                axis=0)
    cls = g.add("Expand", ["cls", tgt])
    x = g.add("Concat", [cls, toks], axis=1)
    x = g.add("Add", [x, "pos"])

    for i in range(cfg.layers):
        h = g.layernorm(x, f"l{i}.ln1.g", f"l{i}.ln1.b")

        def head_proj(nm, h=h, i=i):
            mm = g.add("MatMul", [h, f"l{i}.{nm}.w"])
            ad = g.add("Add", [mm, f"l{i}.{nm}.b"])
            r = g.dyn_reshape(ad, h, (H, hd))
            return g.add("Transpose", [r], perm=[0, 2, 1, 3])

        q, k, v = head_proj("q"), head_proj("k"), head_proj("v")
        kT = g.add("Transpose", [k], perm=[0, 1, 3, 2])
        s = g.add("MatMul", [q, kT])
        s = g.add("Div", [s, g.const(np.array(np.sqrt(hd), np.float32))])
        a = g.add("Softmax", [s], axis=3)
        ctx = g.add("MatMul", [a, v])
        ctx = g.add("Transpose", [ctx], perm=[0, 2, 1, 3])
        ctx = g.dyn_reshape(ctx, h, (D,))
        attn = g.add("Add", [g.add("MatMul", [ctx, f"l{i}.o.w"]),
                             f"l{i}.o.b"])
        x = g.add("Add", [x, attn])                        # pre-LN residual
        h2 = g.layernorm(x, f"l{i}.ln2.g", f"l{i}.ln2.b")
        ff = g.gelu(g.add("Add", [g.add("MatMul", [h2, f"l{i}.ff1.w"]),
                                  f"l{i}.ff1.b"]))
        ff = g.add("Add", [g.add("MatMul", [ff, f"l{i}.ff2.w"]),
                           f"l{i}.ff2.b"])
        x = g.add("Add", [x, ff])

    x = g.layernorm(x, "final_ln.g", "final_ln.b")
    cls_row = g.add("Gather", [x, g.const(np.array(0, np.int64))], axis=1)
    g.nodes.append(make_node("Identity", [cls_row], ["feat"]))
    logits = g.add("Add", [g.add("MatMul", [cls_row, "head.w"]), "head.b"])
    g.nodes.append(make_node("Identity", [logits], ["logits"]))

    S = cfg.image_size
    graph = make_graph(
        g.nodes, "vit",
        inputs=[make_tensor_value_info(px, np.float32,
                                       ("batch", 3, S, S))],
        outputs=[make_tensor_value_info("feat", np.float32,
                                        ("batch", D)),
                 make_tensor_value_info("logits", np.float32,
                                        ("batch", cfg.num_classes))],
        initializers=g.inits)
    return make_model(graph, opset=opset, producer="pytorch-style")
