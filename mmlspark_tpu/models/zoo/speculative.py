"""Speculative decoding: draft-then-verify generation.

A small draft model proposes ``gamma`` greedy tokens per round; the
target model scores the whole proposal in ONE cached window forward
(:func:`transformer.decode_window`) and accepts the longest prefix that
matches its own greedy choices, emitting one bonus token on top — so each
round costs one target forward for 1..gamma+1 emitted tokens. The
guarantee (and the test invariant): greedy speculative output is
token-for-token IDENTICAL to decoding the target alone; the draft only
changes how fast, never what.

Serving context: the reference's model serving replays one ORT session
per request (no notion of drafting); this is the latency optimization the
continuous-batching decoder stack picks up for free because every piece
— prefill, window verify, stale-cache masking — is already a jitted
static-shape program. Stale entries past an accepted prefix need no
rollback: attention masks keys by position, and later windows overwrite
them.

Two contracts, two entry points:

* greedy (:func:`generate_speculative` / :func:`generate_speculative_fused`)
  — output token-for-token IDENTICAL to decoding the target alone; simply
  verifiable, the serving default.
* sampled (:func:`generate_speculative_sampled`) — temperature>0 with the
  rejection-sampling correction from the speculative-sampling literature:
  draft tokens are accepted with probability min(1, p_target/p_draft) and
  a rejection resamples from the normalized residual max(p_target −
  p_draft, 0), so the OUTPUT DISTRIBUTION exactly equals sampling from
  the target (bit-identity is impossible — the two procedures consume
  randomness differently — so the contract, and the test, is
  distributional).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (TransformerConfig, _warp_scaled_rows,
                          decode_step, decode_window, decode_window_paged,
                          init_kv_cache, init_paged_cache,
                          paged_scatter_rows, prefill_cache)
from ...ops.paged_attention import resolve_impl

__all__ = ["generate_speculative", "generate_speculative_fused",
           "generate_speculative_paged", "generate_speculative_sampled"]


def generate_speculative_sampled(t_params: Dict, d_params: Dict,
                                 prompt_ids, t_cfg: TransformerConfig,
                                 d_cfg: TransformerConfig,
                                 max_new_tokens: int = 32,
                                 gamma: int = 4,
                                 temperature: float = 1.0,
                                 top_k: int = 0, top_p: float = 1.0,
                                 seed: int = 0) -> Tuple[jnp.ndarray, dict]:
    """Speculative SAMPLING: temperature>0 generation whose output
    distribution exactly equals sampling from the target alone.

    Per round the draft SAMPLES gamma tokens from its own (temperature-
    warped) distribution; the target scores the window once and each
    proposal x_i is accepted with probability min(1, p_t(x_i)/p_d(x_i));
    the first rejection resamples from the normalized residual
    max(p_t − p_d, 0) — the speculative-sampling correction that makes
    the emitted sequence exactly target-distributed (Leviathan et al. /
    Chen et al.). Full acceptance samples the bonus token from p_t at the
    window tail, which the same residual formula produces with the draft
    term zeroed. Rows are independent streams (per-row keys); rounds
    advance by the batch's minimum acceptance like the greedy impl —
    truncated positions redraw next round with FRESH keys, which keeps
    the restart unbiased (a prefix of a speculative-sampling emission is
    itself exactly target-distributed; discarded randomness is never
    reused). The subtle branch at the cut position: a row whose own
    acceptance ran PAST the batch-min/capacity cut emits its accepted
    draft token there (already target-distributed), never a residual
    resample — conflating the two biases the output, and the
    distributional test catches it at ~19% absolute marginal error.

    Top-k/top-p warping composes: the SAME warp (HF convention,
    ``transformer._warp_scaled_rows``) is applied to the target and the
    draft before the ratio test, so the output is exactly
    warped-target distributed. Returns ``(ids (B, P+max_new), stats)``.
    """
    if t_cfg.vocab != d_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if gamma < 1:
        raise ValueError("gamma must be >= 1")
    if not temperature > 0.0:
        raise ValueError("temperature must be > 0 — use "
                         "generate_speculative_fused for greedy")
    if top_k < 0 or not 0.0 < top_p <= 1.0:
        raise ValueError("top_k must be >= 0 and top_p in (0, 1]")
    t_params = jax.tree.map(jnp.asarray, t_params)
    d_params = jax.tree.map(jnp.asarray, d_params)
    prompt_ids = jnp.asarray(prompt_ids)
    # key and temperature are TRACED args: per-request seeds/temps must
    # not recompile the fused loop (the r4 verdict's exact failure mode)
    ids, stats = _speculative_sampled_impl(
        t_params, d_params, prompt_ids, jax.random.PRNGKey(int(seed)),
        jnp.float32(temperature), t_cfg=t_cfg, d_cfg=d_cfg,
        max_new_tokens=int(max_new_tokens), gamma=int(gamma),
        top_k=int(top_k), top_p=float(top_p))
    s = np.asarray(stats)
    return ids, {"target_forwards": int(s[0]) + 1, "rounds": int(s[1]),
                 "accepted_drafts": int(s[2]),
                 "draft_steps": int(s[1]) * (gamma + 1)}


@functools.partial(jax.jit, static_argnames=("t_cfg", "d_cfg",
                                             "max_new_tokens", "gamma",
                                             "top_k", "top_p"))
def _speculative_sampled_impl(t_params, d_params, prompt_ids, key,
                              temperature, t_cfg, d_cfg, max_new_tokens,
                              gamma, top_k=0, top_p=1.0):
    B, P = prompt_ids.shape
    L = P + max_new_tokens + gamma + 1
    V = t_cfg.vocab
    lengths = jnp.full((B,), P, jnp.int32)
    t_logits, t_cache = prefill_cache(t_params, prompt_ids, lengths,
                                      t_cfg, L)
    _, d_cache = prefill_cache(d_params, prompt_ids, lengths, d_cfg, L)
    # per-row base keys: rows are independent streams
    row_keys = jax.vmap(jax.random.fold_in,
                        (None, 0))(key, jnp.arange(B, dtype=jnp.uint32))

    def warm_logp(logits):
        """Temperature scale + (static) top-k/top-p warp, 2D or 3D —
        shared by draft and target so the ratio test stays exact."""
        scaled = logits.astype(jnp.float32) / temperature
        if top_k > 0 or top_p < 1.0:
            flat = scaled.reshape(-1, scaled.shape[-1])
            n = flat.shape[0]
            flat = _warp_scaled_rows(
                flat, jnp.full((n,), top_k, jnp.int32),
                jnp.full((n,), top_p, jnp.float32))
            scaled = flat.reshape(scaled.shape)
        return jax.nn.log_softmax(scaled, axis=-1)

    def sample_rows(keys, logp):
        return jax.vmap(jax.random.categorical)(keys, logp).astype(
            jnp.int32)

    def keys_for(round_idx, j, purpose):
        # (round, window-position, purpose) → one key per row; fresh
        # randomness every round so batch-min restarts never reuse a
        # rejected draw
        k = jax.vmap(jax.random.fold_in, (0, None))(row_keys, round_idx)
        k = jax.vmap(jax.random.fold_in, (0, None))(k, j)
        return jax.vmap(jax.random.fold_in, (0, None))(k, purpose)

    # first emitted token: sampled from the target's prompt continuation
    pending0 = sample_rows(keys_for(jnp.uint32(0), 0, 0),
                           warm_logp(t_logits))
    ids0 = jnp.zeros((B, L), prompt_ids.dtype)
    ids0 = jax.lax.dynamic_update_slice(ids0, prompt_ids, (0, 0))
    ids0 = jax.lax.dynamic_update_slice(
        ids0, pending0.astype(prompt_ids.dtype)[:, None], (0, P))
    stats0 = jnp.zeros((3,), jnp.int32)

    def emitted(m):
        return m - P + 1

    def cond(carry):
        _, m, *_ = carry
        return emitted(m) < max_new_tokens

    def body(carry):
        ids, m, pending, t_cache, d_cache, rnd, stats = carry

        # draft samples gamma proposals (and consumes its own last one so
        # the cache stays hole-free at full acceptance), keeping its
        # full warped log-distribution at every proposal position
        def dstep(c, i):
            cache, tok = c
            logits, cache = decode_step(d_params, tok, m + i, cache,
                                        d_cfg)
            logp = warm_logp(logits)
            nxt = sample_rows(keys_for(rnd, i, 1), logp)
            return (cache, nxt), (nxt, logp)

        (d_cache, _), (props, d_logps) = jax.lax.scan(
            dstep, (d_cache, pending), jnp.arange(gamma + 1))
        drafts = jnp.moveaxis(props[:gamma], 0, 1)          # (B, gamma)
        d_logp = jnp.moveaxis(d_logps[:gamma], 0, 1)        # (B, g, V)

        wtoks = jnp.concatenate([pending[:, None], drafts], axis=1)
        w_logits, t_cache = decode_window(t_params, wtoks, m, t_cache,
                                          t_cfg)
        t_logp = warm_logp(w_logits)                        # (B, g+1, V)

        # accept x_i iff u_i < p_t(x_i)/p_d(x_i)  ⇔  log u_i < Δlogp
        us = jnp.stack([jax.vmap(jax.random.uniform)(keys_for(rnd, i, 2))
                        for i in range(gamma)], axis=1)     # (B, gamma)
        lp_t = jnp.take_along_axis(t_logp[:, :gamma], drafts[..., None],
                                   axis=-1)[..., 0]
        lp_d = jnp.take_along_axis(d_logp, drafts[..., None],
                                   axis=-1)[..., 0]
        acc = jnp.log(jnp.maximum(us, 1e-38)) < (lp_t - lp_d)
        k_rows = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), -1), -1)
        k = jnp.minimum(jnp.min(k_rows),
                        max_new_tokens - emitted(m) - 1).astype(jnp.int32)

        # the token at window position k, PER ROW. k ≤ k_rows[r] always
        # (batch-min + the capacity cap only ever truncate), so a row is
        # in exactly one of two cases, and conflating them is the classic
        # bias: a row with k_rows[r] > k ACCEPTED x_k — the accepted
        # draft IS p_t-distributed and must be emitted as-is; only a row
        # with k_rows[r] == k (< gamma) actually rejected at k and
        # resamples from the normalized residual max(p_t − p_d, 0). At
        # k == gamma every row has k_rows == k and the padded draft term
        # is zero, so the residual IS the bonus sample from p_t.
        p_t_k = jnp.take_along_axis(
            jnp.exp(t_logp), k[None, None, None].repeat(B, 0),
            axis=1)[:, 0]                                   # (B, V)
        d_logp_pad = jnp.concatenate(
            [d_logp, jnp.full((B, 1, V), -jnp.inf, jnp.float32)], axis=1)
        p_d_k = jnp.take_along_axis(
            jnp.exp(d_logp_pad), k[None, None, None].repeat(B, 0),
            axis=1)[:, 0]
        resid = jnp.maximum(p_t_k - p_d_k, 0.0)
        # numerical guard: an (almost-)empty residual falls back to p_t —
        # it only occurs when p_d ≈ p_t everywhere, where both agree
        total = jnp.sum(resid, axis=-1, keepdims=True)
        resid = jnp.where(total > 1e-30, resid / total, p_t_k)
        resampled = sample_rows(keys_for(rnd, gamma + 1, 3),
                                jnp.log(jnp.maximum(resid, 1e-38)))
        pad_drafts = jnp.concatenate([drafts, drafts[:, -1:]], axis=1)
        accepted_at_k = jnp.take_along_axis(
            pad_drafts, k[None, None].repeat(B, 0), axis=1)[:, 0]
        nxt = jnp.where(k_rows > k, accepted_at_k, resampled)

        idxs = jnp.arange(gamma + 1)
        emit = jnp.where(idxs[None, :] < k, pad_drafts,
                         nxt[:, None]).astype(prompt_ids.dtype)
        ids = jax.lax.dynamic_update_slice(ids, emit, (0, m + 1))
        stats = stats + jnp.array([1, 1, 0], jnp.int32) \
            + jnp.array([0, 0, 1], jnp.int32) * k
        return (ids, m + k + 1, nxt, t_cache, d_cache,
                rnd + jnp.uint32(1), stats)

    ids, m, pending, _, _, _, stats = jax.lax.while_loop(
        cond, body, (ids0, jnp.asarray(P, jnp.int32), pending0,
                     t_cache, d_cache, jnp.uint32(1), stats0))
    return ids[:, :P + max_new_tokens], stats


def generate_speculative_fused(t_params: Dict, d_params: Dict,
                               prompt_ids, t_cfg: TransformerConfig,
                               d_cfg: TransformerConfig,
                               max_new_tokens: int = 32,
                               gamma: int = 4) -> Tuple[jnp.ndarray, dict]:
    """:func:`generate_speculative` as ONE compiled program.

    The whole draft→verify→accept loop runs inside ``lax.while_loop`` —
    no host round-trips between rounds (the python-loop variant pays one
    dispatch per round, which behind a network-attached TPU costs more
    than the compute it saves). Dynamic acceptance under static shapes:
    each round optimistically writes all gamma+1 window emissions into the
    ids buffer and advances by the accepted length only — later rounds
    overwrite the rejected tail. The draft consumes its own last proposal
    (one extra step per round) so its cache never holds a hole regardless
    of how much was accepted.

    Output is token-for-token identical to the python-loop variant and to
    target-only greedy decoding.
    """
    if t_cfg.vocab != d_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if gamma < 1:
        raise ValueError("gamma must be >= 1")
    t_params = jax.tree.map(jnp.asarray, t_params)
    d_params = jax.tree.map(jnp.asarray, d_params)
    prompt_ids = jnp.asarray(prompt_ids)
    # module-level cached jit: a per-call `@jax.jit` closure re-traced and
    # remote-recompiled the whole loop on EVERY generation — the r4
    # "speculative is slower" verdict measured compiles, not decoding
    ids, stats = _speculative_impl(t_params, d_params, prompt_ids,
                                   t_cfg=t_cfg, d_cfg=d_cfg,
                                   max_new_tokens=int(max_new_tokens),
                                   gamma=int(gamma))
    s = np.asarray(stats)
    return ids, {"target_forwards": int(s[0]) + 1, "rounds": int(s[1]),
                 "accepted_drafts": int(s[2]),
                 "draft_steps": int(s[1]) * (gamma + 1)}


@functools.partial(jax.jit, static_argnames=("t_cfg", "d_cfg",
                                             "max_new_tokens", "gamma"))
def _speculative_impl(t_params, d_params, prompt_ids, t_cfg, d_cfg,
                      max_new_tokens, gamma):
    B, P = prompt_ids.shape
    L = P + max_new_tokens + gamma + 1
    lengths = jnp.full((B,), P, jnp.int32)
    t_logits, t_cache = prefill_cache(t_params, prompt_ids, lengths,
                                      t_cfg, L)
    _, d_cache = prefill_cache(d_params, prompt_ids, lengths, d_cfg, L)
    pending0 = jnp.argmax(t_logits, axis=-1).astype(prompt_ids.dtype)
    ids0 = jnp.zeros((B, L), prompt_ids.dtype)
    ids0 = jax.lax.dynamic_update_slice(ids0, prompt_ids, (0, 0))
    ids0 = jax.lax.dynamic_update_slice(ids0, pending0[:, None], (0, P))
    # carry: ids, m (position of pending), pending, caches, stats
    stats0 = jnp.zeros((3,), jnp.int32)    # forwards, rounds, accepted

    def emitted(m):
        return m - P + 1

    def cond(carry):
        ids, m, pending, t_cache, d_cache, stats = carry
        return emitted(m) < max_new_tokens

    def body(carry):
        ids, m, pending, t_cache, d_cache, stats = carry

        # draft proposes gamma tokens, then consumes its own last
        # proposal so the cache stays hole-free at full acceptance
        def draft_scan(cache, pending, m):
            def step(c, i):
                cache, tok = c
                logits, cache = decode_step(d_params, tok, m + i,
                                            cache, d_cfg)
                nxt = jnp.argmax(logits, -1).astype(pending.dtype)
                return (cache, nxt), nxt
            (cache, _), drafts = jax.lax.scan(
                step, (cache, pending), jnp.arange(gamma + 1))
            return cache, jnp.moveaxis(drafts[:gamma], 0, 1)

        d_cache, drafts = draft_scan(d_cache, pending, m)
        wtoks = jnp.concatenate([pending[:, None], drafts], axis=1)
        w_logits, t_cache = decode_window(t_params, wtoks, m, t_cache,
                                          t_cfg)
        greedy = jnp.argmax(w_logits, -1).astype(pending.dtype)
        match = greedy[:, :gamma] == drafts
        accept = jnp.min(jnp.sum(jnp.cumprod(
            match.astype(jnp.int32), -1), -1))
        k = jnp.minimum(accept,
                        max_new_tokens - emitted(m) - 1).astype(jnp.int32)
        # optimistic emission: positions m+1..m+gamma+1 get the drafts
        # up to k and the bonus at k (later slots are garbage a future
        # round overwrites; only ids[:, :m+k+2] is ever final)
        bonus = jnp.take_along_axis(greedy, k[None, None].repeat(B, 0),
                                    axis=1)[:, 0]
        idxs = jnp.arange(gamma + 1)
        emit = jnp.where(idxs[None, :] < k,
                         jnp.concatenate(
                             [drafts, drafts[:, -1:]], axis=1),
                         bonus[:, None])
        ids = jax.lax.dynamic_update_slice(ids, emit, (0, m + 1))
        stats = stats + jnp.array([1, 1, 0], jnp.int32) \
            + jnp.array([0, 0, 1], jnp.int32) * k
        return (ids, m + k + 1, bonus, t_cache, d_cache, stats)

    ids, m, pending, _, _, stats = jax.lax.while_loop(
        cond, body, (ids0, jnp.asarray(P, jnp.int32), pending0,
                     t_cache, d_cache, stats0))
    return ids[:, :P + max_new_tokens], stats


def generate_speculative(t_params: Dict, d_params: Dict,
                         prompt_ids, t_cfg: TransformerConfig,
                         d_cfg: TransformerConfig,
                         max_new_tokens: int = 32,
                         gamma: int = 4) -> Tuple[jnp.ndarray, dict]:
    """Greedy generation from the TARGET model, accelerated by the draft.

    Returns ``(ids (B, P+max_new), stats)`` — ids exactly equal to
    ``generate_cached(t_params, ..., temperature=0)``; stats counts
    target forwards and accepted drafts (the speedup evidence).
    B>1 works; rounds advance by the batch's MINIMUM acceptance so all
    rows stay position-aligned (per-row raggedness is the continuous
    decoder's job, not this reference loop's).
    """
    if t_cfg.vocab != d_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if gamma < 1:
        raise ValueError("gamma must be >= 1")
    t_params = jax.tree.map(jnp.asarray, t_params)
    d_params = jax.tree.map(jnp.asarray, d_params)
    prompt_ids = jnp.asarray(prompt_ids)
    B, P = prompt_ids.shape
    L = P + max_new_tokens + gamma + 1          # slack: windows overshoot
    t_cache = init_kv_cache(t_cfg, B, L)
    d_cache = init_kv_cache(d_cfg, B, L)
    lengths = jnp.full((B,), P, jnp.int32)

    @jax.jit
    def draft_propose(tail, pending, pos, cache):
        """Consume ``tail`` (B, T — already-emitted tokens the draft cache
        is missing; T is 0 or 1) then ``pending`` at the following
        position, continuing greedily until gamma proposals exist."""
        for i in range(tail.shape[1]):
            _, cache = decode_step(d_params, tail[:, i], pos + i, cache,
                                   d_cfg)
        start = pos + tail.shape[1]

        def step(carry, _):
            tok, p, cache = carry
            logits, cache = decode_step(d_params, tok, p, cache, d_cfg)
            nxt = jnp.argmax(logits, axis=-1).astype(tok.dtype)
            return (nxt, p + 1, cache), nxt

        (_, _, cache), drafts = jax.lax.scan(
            step, (pending, start, cache), None, length=gamma)
        return jnp.moveaxis(drafts, 0, 1), cache       # (B, gamma)

    @jax.jit
    def verify(wtoks, pos, cache):
        logits, cache = decode_window(t_params, wtoks, pos, cache, t_cfg)
        greedy = jnp.argmax(logits, axis=-1)           # (B, gamma+1)
        match = greedy[:, :-1] == wtoks[:, 1:].astype(greedy.dtype)
        accept = jnp.min(jnp.sum(jnp.cumprod(
            match.astype(jnp.int32), axis=-1), axis=-1))
        return greedy, accept, cache

    # prompt prefill on both models; the target's last-token logits give
    # the first pending token (its greedy continuation of the prompt)
    t_logits, t_cache = prefill_cache(t_params, prompt_ids, lengths,
                                      t_cfg, L)
    _, d_cache = prefill_cache(d_params, prompt_ids, lengths, d_cfg, L)
    pending = jnp.argmax(t_logits, axis=-1).astype(prompt_ids.dtype)  # (B,)

    ids = np.zeros((B, P + max_new_tokens), np.asarray(prompt_ids).dtype)
    ids[:, :P] = np.asarray(prompt_ids)
    out = [np.asarray(pending)[:, None]]          # pending IS emitted
    emitted = 1
    m = P                                         # caches valid thru m-1
    tail = jnp.zeros((B, 0), prompt_ids.dtype)    # draft-cache catch-up
    stats = {"target_forwards": 1, "draft_steps": 0, "accepted_drafts": 0,
             "rounds": 0}

    while emitted < max_new_tokens:
        drafts, d_cache = draft_propose(tail, pending, m - tail.shape[1],
                                        d_cache)
        stats["draft_steps"] += gamma
        # verify window [pending, d_1..d_gamma] at positions m..m+gamma:
        # greedy[:, i] is the target's choice after wtoks[:, :i+1], so
        # drafts[:, i] must equal greedy[:, i] to be accepted
        wtoks = jnp.concatenate([pending[:, None], drafts], axis=1)
        greedy, accept, t_cache = verify(wtoks, m, t_cache)
        stats["target_forwards"] += 1
        stats["rounds"] += 1
        k = min(int(accept), max_new_tokens - emitted - 1)
        stats["accepted_drafts"] += k
        if k > 0:
            out.append(np.asarray(drafts[:, :k]))
            emitted += k
        bonus = greedy[:, k].astype(prompt_ids.dtype)
        out.append(np.asarray(bonus)[:, None])
        emitted += 1
        # k == gamma: the draft never consumed d_gamma (it only proposed
        # it), so its cache misses position m+gamma — hand it back as the
        # next round's tail
        tail = drafts[:, gamma - 1:gamma] if k == gamma \
            else jnp.zeros((B, 0), prompt_ids.dtype)
        pending = bonus
        m = m + k + 1

    new = np.concatenate(out, axis=1)
    ids[:, P:] = new[:, :max_new_tokens]
    return jnp.asarray(ids), stats


def generate_speculative_paged(t_params: Dict, d_params: Dict,
                               prompt_ids, t_cfg: TransformerConfig,
                               d_cfg: TransformerConfig,
                               max_new_tokens: int = 32,
                               gamma: int = 4,
                               page_size: int = 16,
                               paged_attn: Optional[str] = None,
                               ) -> Tuple[jnp.ndarray, dict]:
    """:func:`generate_speculative` with the TARGET cache held in a paged
    pool — the reference loop for the paged verify path the continuous
    decoder runs, and the parity oracle ``tests/test_kv_pool.py`` checks.

    Each row owns a dense range of physical pages (block table row b maps
    logical page j to ``1 + b*n + j``; page 0 is the trash page), prefill
    output is scattered into the pool through the table, and every verify
    window runs :func:`transformer.decode_window_paged` at the full
    logical length — which delegates to the same ragged window math as
    :func:`transformer.decode_window`, so output is token-for-token
    IDENTICAL to :func:`generate_speculative` (and hence to greedy
    target-only decoding). The draft cache stays contiguous: it is small,
    never shared, and paging it buys nothing.

    ``paged_attn`` selects the verify window's implementation (``None``
    → the ``MMLSPARK_TPU_PAGED_ATTN`` knob, default the Pallas kernel
    reading pages in place; ``"gather"`` keeps the bitwise
    gather-then-ragged path). The chosen impl is recorded in
    ``stats["paged_attn_impl"]``.
    """
    if t_cfg.vocab != d_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if gamma < 1:
        raise ValueError("gamma must be >= 1")
    if page_size < 1:
        raise ValueError("page_size must be >= 1")
    impl = resolve_impl(paged_attn)
    t_params = jax.tree.map(jnp.asarray, t_params)
    d_params = jax.tree.map(jnp.asarray, d_params)
    prompt_ids = jnp.asarray(prompt_ids)
    B, P = prompt_ids.shape
    L = P + max_new_tokens + gamma + 1          # slack: windows overshoot
    n_pages_row = -(-L // page_size)
    # dense per-row page ranges: no fragmentation to manage here, the
    # point is exercising the gather/scatter path, not the allocator
    bt = (1 + np.arange(B)[:, None] * n_pages_row
          + np.arange(n_pages_row)[None, :]).astype(np.int32)
    bt = jnp.asarray(bt)
    t_pages = init_paged_cache(t_cfg, 1 + B * n_pages_row, page_size)
    d_cache = init_kv_cache(d_cfg, B, L)
    lengths = jnp.full((B,), P, jnp.int32)

    @jax.jit
    def draft_propose(tail, pending, pos, cache):
        for i in range(tail.shape[1]):
            _, cache = decode_step(d_params, tail[:, i], pos + i, cache,
                                   d_cfg)
        start = pos + tail.shape[1]

        def step(carry, _):
            tok, p, cache = carry
            logits, cache = decode_step(d_params, tok, p, cache, d_cfg)
            nxt = jnp.argmax(logits, axis=-1).astype(tok.dtype)
            return (nxt, p + 1, cache), nxt

        (_, _, cache), drafts = jax.lax.scan(
            step, (pending, start, cache), None, length=gamma)
        return jnp.moveaxis(drafts, 0, 1), cache       # (B, gamma)

    @jax.jit
    def verify(wtoks, pos, pages):
        logits, pages = decode_window_paged(
            t_params, wtoks, jnp.full((B,), pos, jnp.int32), pages, bt,
            t_cfg, page_size=page_size, length=L, impl=impl)
        greedy = jnp.argmax(logits, axis=-1)           # (B, gamma+1)
        match = greedy[:, :-1] == wtoks[:, 1:].astype(greedy.dtype)
        accept = jnp.min(jnp.sum(jnp.cumprod(
            match.astype(jnp.int32), axis=-1), axis=-1))
        return greedy, accept, pages

    @jax.jit
    def scatter_prefill(pages, rows):
        return paged_scatter_rows(pages, rows, bt, page_size)

    t_logits, t_rows = prefill_cache(t_params, prompt_ids, lengths,
                                     t_cfg, L)
    t_pages = scatter_prefill(t_pages, t_rows)
    _, d_cache = prefill_cache(d_params, prompt_ids, lengths, d_cfg, L)
    pending = jnp.argmax(t_logits, axis=-1).astype(prompt_ids.dtype)

    ids = np.zeros((B, P + max_new_tokens), np.asarray(prompt_ids).dtype)
    ids[:, :P] = np.asarray(prompt_ids)
    out = [np.asarray(pending)[:, None]]
    emitted = 1
    m = P
    tail = jnp.zeros((B, 0), prompt_ids.dtype)
    stats = {"target_forwards": 1, "draft_steps": 0, "accepted_drafts": 0,
             "rounds": 0, "pages_per_row": n_pages_row,
             "page_size": page_size, "paged_attn_impl": impl}

    while emitted < max_new_tokens:
        drafts, d_cache = draft_propose(tail, pending, m - tail.shape[1],
                                        d_cache)
        stats["draft_steps"] += gamma
        wtoks = jnp.concatenate([pending[:, None], drafts], axis=1)
        greedy, accept, t_pages = verify(wtoks, m, t_pages)
        stats["target_forwards"] += 1
        stats["rounds"] += 1
        k = min(int(accept), max_new_tokens - emitted - 1)
        stats["accepted_drafts"] += k
        if k > 0:
            out.append(np.asarray(drafts[:, :k]))
            emitted += k
        bonus = greedy[:, k].astype(prompt_ids.dtype)
        out.append(np.asarray(bonus)[:, None])
        emitted += 1
        tail = drafts[:, gamma - 1:gamma] if k == gamma \
            else jnp.zeros((B, 0), prompt_ids.dtype)
        pending = bonus
        m = m + k + 1

    new = np.concatenate(out, axis=1)
    ids[:, P:] = new[:, :max_new_tokens]
    return jnp.asarray(ids), stats
