"""Speculative decoding: draft-then-verify generation.

A small draft model proposes ``gamma`` greedy tokens per round; the
target model scores the whole proposal in ONE cached window forward
(:func:`transformer.decode_window`) and accepts the longest prefix that
matches its own greedy choices, emitting one bonus token on top — so each
round costs one target forward for 1..gamma+1 emitted tokens. The
guarantee (and the test invariant): greedy speculative output is
token-for-token IDENTICAL to decoding the target alone; the draft only
changes how fast, never what.

Serving context: the reference's model serving replays one ORT session
per request (no notion of drafting); this is the latency optimization the
continuous-batching decoder stack picks up for free because every piece
— prefill, window verify, stale-cache masking — is already a jitted
static-shape program. Stale entries past an accepted prefix need no
rollback: attention masks keys by position, and later windows overwrite
them.

No sampling mode here by design: temperature>0 speculative decoding
needs the rejection-sampling correction from the speculative-sampling
literature to keep the output distribution exact, which is a different
contract than this zoo reference implements (greedy-exactness, simply
verifiable).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (TransformerConfig, decode_step, decode_window,
                          init_kv_cache, prefill_cache)

__all__ = ["generate_speculative", "generate_speculative_fused"]


def generate_speculative_fused(t_params: Dict, d_params: Dict,
                               prompt_ids, t_cfg: TransformerConfig,
                               d_cfg: TransformerConfig,
                               max_new_tokens: int = 32,
                               gamma: int = 4) -> Tuple[jnp.ndarray, dict]:
    """:func:`generate_speculative` as ONE compiled program.

    The whole draft→verify→accept loop runs inside ``lax.while_loop`` —
    no host round-trips between rounds (the python-loop variant pays one
    dispatch per round, which behind a network-attached TPU costs more
    than the compute it saves). Dynamic acceptance under static shapes:
    each round optimistically writes all gamma+1 window emissions into the
    ids buffer and advances by the accepted length only — later rounds
    overwrite the rejected tail. The draft consumes its own last proposal
    (one extra step per round) so its cache never holds a hole regardless
    of how much was accepted.

    Output is token-for-token identical to the python-loop variant and to
    target-only greedy decoding.
    """
    if t_cfg.vocab != d_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if gamma < 1:
        raise ValueError("gamma must be >= 1")
    t_params = jax.tree.map(jnp.asarray, t_params)
    d_params = jax.tree.map(jnp.asarray, d_params)
    prompt_ids = jnp.asarray(prompt_ids)
    # module-level cached jit: a per-call `@jax.jit` closure re-traced and
    # remote-recompiled the whole loop on EVERY generation — the r4
    # "speculative is slower" verdict measured compiles, not decoding
    ids, stats = _speculative_impl(t_params, d_params, prompt_ids,
                                   t_cfg=t_cfg, d_cfg=d_cfg,
                                   max_new_tokens=int(max_new_tokens),
                                   gamma=int(gamma))
    s = np.asarray(stats)
    return ids, {"target_forwards": int(s[0]) + 1, "rounds": int(s[1]),
                 "accepted_drafts": int(s[2]),
                 "draft_steps": int(s[1]) * (gamma + 1)}


@functools.partial(jax.jit, static_argnames=("t_cfg", "d_cfg",
                                             "max_new_tokens", "gamma"))
def _speculative_impl(t_params, d_params, prompt_ids, t_cfg, d_cfg,
                      max_new_tokens, gamma):
    B, P = prompt_ids.shape
    L = P + max_new_tokens + gamma + 1
    lengths = jnp.full((B,), P, jnp.int32)
    t_logits, t_cache = prefill_cache(t_params, prompt_ids, lengths,
                                      t_cfg, L)
    _, d_cache = prefill_cache(d_params, prompt_ids, lengths, d_cfg, L)
    pending0 = jnp.argmax(t_logits, axis=-1).astype(prompt_ids.dtype)
    ids0 = jnp.zeros((B, L), prompt_ids.dtype)
    ids0 = jax.lax.dynamic_update_slice(ids0, prompt_ids, (0, 0))
    ids0 = jax.lax.dynamic_update_slice(ids0, pending0[:, None], (0, P))
    # carry: ids, m (position of pending), pending, caches, stats
    stats0 = jnp.zeros((3,), jnp.int32)    # forwards, rounds, accepted

    def emitted(m):
        return m - P + 1

    def cond(carry):
        ids, m, pending, t_cache, d_cache, stats = carry
        return emitted(m) < max_new_tokens

    def body(carry):
        ids, m, pending, t_cache, d_cache, stats = carry

        # draft proposes gamma tokens, then consumes its own last
        # proposal so the cache stays hole-free at full acceptance
        def draft_scan(cache, pending, m):
            def step(c, i):
                cache, tok = c
                logits, cache = decode_step(d_params, tok, m + i,
                                            cache, d_cfg)
                nxt = jnp.argmax(logits, -1).astype(pending.dtype)
                return (cache, nxt), nxt
            (cache, _), drafts = jax.lax.scan(
                step, (cache, pending), jnp.arange(gamma + 1))
            return cache, jnp.moveaxis(drafts[:gamma], 0, 1)

        d_cache, drafts = draft_scan(d_cache, pending, m)
        wtoks = jnp.concatenate([pending[:, None], drafts], axis=1)
        w_logits, t_cache = decode_window(t_params, wtoks, m, t_cache,
                                          t_cfg)
        greedy = jnp.argmax(w_logits, -1).astype(pending.dtype)
        match = greedy[:, :gamma] == drafts
        accept = jnp.min(jnp.sum(jnp.cumprod(
            match.astype(jnp.int32), -1), -1))
        k = jnp.minimum(accept,
                        max_new_tokens - emitted(m) - 1).astype(jnp.int32)
        # optimistic emission: positions m+1..m+gamma+1 get the drafts
        # up to k and the bonus at k (later slots are garbage a future
        # round overwrites; only ids[:, :m+k+2] is ever final)
        bonus = jnp.take_along_axis(greedy, k[None, None].repeat(B, 0),
                                    axis=1)[:, 0]
        idxs = jnp.arange(gamma + 1)
        emit = jnp.where(idxs[None, :] < k,
                         jnp.concatenate(
                             [drafts, drafts[:, -1:]], axis=1),
                         bonus[:, None])
        ids = jax.lax.dynamic_update_slice(ids, emit, (0, m + 1))
        stats = stats + jnp.array([1, 1, 0], jnp.int32) \
            + jnp.array([0, 0, 1], jnp.int32) * k
        return (ids, m + k + 1, bonus, t_cache, d_cache, stats)

    ids, m, pending, _, _, stats = jax.lax.while_loop(
        cond, body, (ids0, jnp.asarray(P, jnp.int32), pending0,
                     t_cache, d_cache, stats0))
    return ids[:, :P + max_new_tokens], stats


def generate_speculative(t_params: Dict, d_params: Dict,
                         prompt_ids, t_cfg: TransformerConfig,
                         d_cfg: TransformerConfig,
                         max_new_tokens: int = 32,
                         gamma: int = 4) -> Tuple[jnp.ndarray, dict]:
    """Greedy generation from the TARGET model, accelerated by the draft.

    Returns ``(ids (B, P+max_new), stats)`` — ids exactly equal to
    ``generate_cached(t_params, ..., temperature=0)``; stats counts
    target forwards and accepted drafts (the speedup evidence).
    B>1 works; rounds advance by the batch's MINIMUM acceptance so all
    rows stay position-aligned (per-row raggedness is the continuous
    decoder's job, not this reference loop's).
    """
    if t_cfg.vocab != d_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if gamma < 1:
        raise ValueError("gamma must be >= 1")
    t_params = jax.tree.map(jnp.asarray, t_params)
    d_params = jax.tree.map(jnp.asarray, d_params)
    prompt_ids = jnp.asarray(prompt_ids)
    B, P = prompt_ids.shape
    L = P + max_new_tokens + gamma + 1          # slack: windows overshoot
    t_cache = init_kv_cache(t_cfg, B, L)
    d_cache = init_kv_cache(d_cfg, B, L)
    lengths = jnp.full((B,), P, jnp.int32)

    @jax.jit
    def draft_propose(tail, pending, pos, cache):
        """Consume ``tail`` (B, T — already-emitted tokens the draft cache
        is missing; T is 0 or 1) then ``pending`` at the following
        position, continuing greedily until gamma proposals exist."""
        for i in range(tail.shape[1]):
            _, cache = decode_step(d_params, tail[:, i], pos + i, cache,
                                   d_cfg)
        start = pos + tail.shape[1]

        def step(carry, _):
            tok, p, cache = carry
            logits, cache = decode_step(d_params, tok, p, cache, d_cfg)
            nxt = jnp.argmax(logits, axis=-1).astype(tok.dtype)
            return (nxt, p + 1, cache), nxt

        (_, _, cache), drafts = jax.lax.scan(
            step, (pending, start, cache), None, length=gamma)
        return jnp.moveaxis(drafts, 0, 1), cache       # (B, gamma)

    @jax.jit
    def verify(wtoks, pos, cache):
        logits, cache = decode_window(t_params, wtoks, pos, cache, t_cfg)
        greedy = jnp.argmax(logits, axis=-1)           # (B, gamma+1)
        match = greedy[:, :-1] == wtoks[:, 1:].astype(greedy.dtype)
        accept = jnp.min(jnp.sum(jnp.cumprod(
            match.astype(jnp.int32), axis=-1), axis=-1))
        return greedy, accept, cache

    # prompt prefill on both models; the target's last-token logits give
    # the first pending token (its greedy continuation of the prompt)
    t_logits, t_cache = prefill_cache(t_params, prompt_ids, lengths,
                                      t_cfg, L)
    _, d_cache = prefill_cache(d_params, prompt_ids, lengths, d_cfg, L)
    pending = jnp.argmax(t_logits, axis=-1).astype(prompt_ids.dtype)  # (B,)

    ids = np.zeros((B, P + max_new_tokens), np.asarray(prompt_ids).dtype)
    ids[:, :P] = np.asarray(prompt_ids)
    out = [np.asarray(pending)[:, None]]          # pending IS emitted
    emitted = 1
    m = P                                         # caches valid thru m-1
    tail = jnp.zeros((B, 0), prompt_ids.dtype)    # draft-cache catch-up
    stats = {"target_forwards": 1, "draft_steps": 0, "accepted_drafts": 0,
             "rounds": 0}

    while emitted < max_new_tokens:
        drafts, d_cache = draft_propose(tail, pending, m - tail.shape[1],
                                        d_cache)
        stats["draft_steps"] += gamma
        # verify window [pending, d_1..d_gamma] at positions m..m+gamma:
        # greedy[:, i] is the target's choice after wtoks[:, :i+1], so
        # drafts[:, i] must equal greedy[:, i] to be accepted
        wtoks = jnp.concatenate([pending[:, None], drafts], axis=1)
        greedy, accept, t_cache = verify(wtoks, m, t_cache)
        stats["target_forwards"] += 1
        stats["rounds"] += 1
        k = min(int(accept), max_new_tokens - emitted - 1)
        stats["accepted_drafts"] += k
        if k > 0:
            out.append(np.asarray(drafts[:, :k]))
            emitted += k
        bonus = greedy[:, k].astype(prompt_ids.dtype)
        out.append(np.asarray(bonus)[:, None])
        emitted += 1
        # k == gamma: the draft never consumed d_gamma (it only proposed
        # it), so its cache misses position m+gamma — hand it back as the
        # next round's tail
        tail = drafts[:, gamma - 1:gamma] if k == gamma \
            else jnp.zeros((B, 0), prompt_ids.dtype)
        pending = bonus
        m = m + k + 1

    new = np.concatenate(out, axis=1)
    ids[:, P:] = new[:, :max_new_tokens]
    return jnp.asarray(ids), stats
