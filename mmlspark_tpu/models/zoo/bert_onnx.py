"""BERT-encoder ONNX exporter in *torch-exporter style* — foreign-graph
fodder for the converter.

The reference's ONNXModel consumes graphs produced by real exporters
(``deep-learning/.../onnx/ONNXModel.scala:195-245`` type handling). Our
converter must therefore digest the patterns ``torch.onnx.export`` actually
emits for transformer encoders, not just the clean graphs of our own zoo:

* dynamic batch/sequence axes (``dim_param`` on graph inputs)
* Shape → Gather → Unsqueeze → Concat → Reshape arithmetic for every
  attention head split/merge (no static reshape targets)
* attention-mask path: Unsqueeze/Cast/Sub/Mul by -1e4, added to the logits
* opset-dependent emission: ``axes`` as attributes (opset 11) vs inputs
  (13+); decomposed LayerNorm (ReduceMean/Sub/Pow/Sqrt/Div) below opset 17
  vs fused ``LayerNormalization``; decomposed erf-GELU at every opset
* optionally spills weight matrices to external data files

``bert_reference`` recomputes the same network in pure numpy so tests can
assert numerical parity with the converted graph.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from ...onnx.builder import (make_external_tensor, make_graph, make_model,
                             make_node, make_tensor_value_info)

__all__ = ["BertOnnxConfig", "init_bert_params", "export_bert_onnx",
           "bert_reference"]


@dataclass
class BertOnnxConfig:
    vocab: int = 128
    layers: int = 2
    d_model: int = 64
    heads: int = 4
    d_ff: int = 128
    max_len: int = 64


def init_bert_params(cfg: BertOnnxConfig, seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    p: Dict[str, np.ndarray] = {
        "embed.word": rng.normal(0, 0.02, (cfg.vocab, cfg.d_model)),
        "embed.pos": rng.normal(0, 0.02, (cfg.max_len, cfg.d_model)),
        "embed.ln.g": np.ones(cfg.d_model), "embed.ln.b": np.zeros(cfg.d_model),
    }
    for i in range(cfg.layers):
        for nm in ("q", "k", "v", "o"):
            p[f"l{i}.{nm}.w"] = rng.normal(0, 0.02, (cfg.d_model, cfg.d_model))
            p[f"l{i}.{nm}.b"] = np.zeros(cfg.d_model)
        p[f"l{i}.ln1.g"] = np.ones(cfg.d_model)
        p[f"l{i}.ln1.b"] = np.zeros(cfg.d_model)
        p[f"l{i}.ff1.w"] = rng.normal(0, 0.02, (cfg.d_model, cfg.d_ff))
        p[f"l{i}.ff1.b"] = np.zeros(cfg.d_ff)
        p[f"l{i}.ff2.w"] = rng.normal(0, 0.02, (cfg.d_ff, cfg.d_model))
        p[f"l{i}.ff2.b"] = np.zeros(cfg.d_model)
        p[f"l{i}.ln2.g"] = np.ones(cfg.d_model)
        p[f"l{i}.ln2.b"] = np.zeros(cfg.d_model)
    return {k: v.astype(np.float32) for k, v in p.items()}


def _ln_np(x, g, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * g + b


def _gelu_np(x):
    from scipy.special import erf  # scipy ships with sklearn's deps
    return 0.5 * x * (1.0 + erf(x / np.sqrt(2.0)))


def bert_reference(params: Dict[str, np.ndarray], ids: np.ndarray,
                   mask: np.ndarray, cfg: BertOnnxConfig) -> np.ndarray:
    """Numpy forward pass matching export_bert_onnx's graph exactly."""
    B, S = ids.shape
    H, Dh = cfg.heads, cfg.d_model // cfg.heads
    x = params["embed.word"][ids] + params["embed.pos"][:S][None]
    x = _ln_np(x, params["embed.ln.g"], params["embed.ln.b"])
    att_bias = (1.0 - mask.astype(np.float32))[:, None, None, :] * -10000.0
    for i in range(cfg.layers):
        def proj(nm):
            w, b = params[f"l{i}.{nm}.w"], params[f"l{i}.{nm}.b"]
            return (x @ w + b).reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        q, k, v = proj("q"), proj("k"), proj("v")
        logits = q @ k.transpose(0, 1, 3, 2) / np.sqrt(Dh) + att_bias
        e = np.exp(logits - logits.max(-1, keepdims=True))
        a = e / e.sum(-1, keepdims=True)
        ctxt = (a @ v).transpose(0, 2, 1, 3).reshape(B, S, cfg.d_model)
        attn_out = ctxt @ params[f"l{i}.o.w"] + params[f"l{i}.o.b"]
        x = _ln_np(x + attn_out, params[f"l{i}.ln1.g"], params[f"l{i}.ln1.b"])
        h = _gelu_np(x @ params[f"l{i}.ff1.w"] + params[f"l{i}.ff1.b"])
        ff = h @ params[f"l{i}.ff2.w"] + params[f"l{i}.ff2.b"]
        x = _ln_np(x + ff, params[f"l{i}.ln2.g"], params[f"l{i}.ln2.b"])
    return x


class _G:
    """Tiny emission helper: unique names + node list."""

    def __init__(self, opset: int):
        self.nodes = []
        self.inits: Dict[str, object] = {}
        self.opset = opset
        self._n = 0

    def name(self, hint="t"):
        self._n += 1
        return f"{hint}_{self._n}"

    def add(self, op, inputs, n_out=1, **attrs):
        outs = [self.name(op.lower()) for _ in range(n_out)]
        self.nodes.append(make_node(op, inputs, outs, **attrs))
        return outs[0] if n_out == 1 else outs

    def const(self, arr, hint="c"):
        nm = self.name(hint)
        self.inits[nm] = np.asarray(arr)
        return nm

    # -- opset-sensitive emission ------------------------------------------
    def unsqueeze(self, x, axes):
        if self.opset >= 13:
            return self.add("Unsqueeze", [x, self.const(np.array(axes, np.int64))])
        return self.add("Unsqueeze", [x], axes=[int(a) for a in axes])

    def reduce_mean(self, x, axes, keepdims=1):
        if self.opset >= 18:
            return self.add("ReduceMean",
                            [x, self.const(np.array(axes, np.int64))],
                            keepdims=keepdims)
        return self.add("ReduceMean", [x], axes=[int(a) for a in axes],
                        keepdims=keepdims)

    def layernorm(self, x, g_name, b_name):
        if self.opset >= 17:
            return self.add("LayerNormalization", [x, g_name, b_name],
                            axis=-1, epsilon=1e-5)
        mu = self.reduce_mean(x, [-1])
        diff = self.add("Sub", [x, mu])
        sq = self.add("Pow", [diff, self.const(np.array(2.0, np.float32))])
        var = self.reduce_mean(sq, [-1])
        veps = self.add("Add", [var, self.const(np.array(1e-5, np.float32))])
        std = self.add("Sqrt", [veps])
        normed = self.add("Div", [diff, std])
        scaled = self.add("Mul", [normed, g_name])
        return self.add("Add", [scaled, b_name])

    def gelu(self, x):
        # erf-GELU exactly as torch decomposes it
        scaled = self.add("Div", [x, self.const(np.array(np.sqrt(2.0), np.float32))])
        e = self.add("Erf", [scaled])
        one = self.add("Add", [e, self.const(np.array(1.0, np.float32))])
        half = self.add("Mul", [x, one])
        return self.add("Mul", [half, self.const(np.array(0.5, np.float32))])

    def dyn_reshape(self, x, shape_src, tail):
        """Reshape x to (dim0(shape_src), dim1(shape_src), *tail) computed
        via Shape/Gather/Concat — the torch exporter's dynamic pattern."""
        shp = self.add("Shape", [shape_src])
        dims = []
        for ax in (0, 1):
            g = self.add("Gather", [shp, self.const(np.array(ax, np.int64))],
                         axis=0)
            dims.append(self.unsqueeze(g, [0]))
        dims.append(self.const(np.array(list(tail), np.int64)))
        target = self.add("Concat", dims, axis=0)
        return self.add("Reshape", [x, target])


def export_bert_onnx(cfg: BertOnnxConfig = BertOnnxConfig(), seed: int = 0,
                     opset: int = 13,
                     external_data_dir: Optional[str] = None,
                     params: Optional[Dict[str, np.ndarray]] = None) -> bytes:
    """Serialize the encoder as an ONNX graph in torch-exporter style.

    With ``external_data_dir`` set, weight matrices are spilled to a sidecar
    ``weights.bin`` (single file, offset-packed — the torch layout)."""
    p = params if params is not None else init_bert_params(cfg, seed)
    H, Dh = cfg.heads, cfg.d_model // cfg.heads
    g = _G(opset)

    # parameters as initializers (optionally external)
    offset = 0
    for k, v in p.items():
        if external_data_dir is not None and v.ndim >= 2:
            g.inits[k] = make_external_tensor(k, v, "weights.bin",
                                              external_data_dir, offset)
            offset += v.nbytes
        else:
            g.inits[k] = v

    ids, mask = "input_ids", "attention_mask"
    # embeddings: word Gather + position Slice (torch emits Slice over the
    # position table with a Shape-derived end)
    we = g.add("Gather", ["embed.word", ids], axis=0)
    seq_shape = g.add("Shape", [ids])
    s_dim = g.add("Gather", [seq_shape, g.const(np.array(1, np.int64))], axis=0)
    s_1d = g.unsqueeze(s_dim, [0])
    pos = g.add("Slice", ["embed.pos", g.const(np.array([0], np.int64)), s_1d,
                          g.const(np.array([0], np.int64))])
    x = g.add("Add", [we, pos])
    x = g.layernorm(x, "embed.ln.g", "embed.ln.b")

    # attention bias: (1 - mask) * -1e4, broadcast (B,1,1,S)
    mf = g.add("Cast", [mask], to=1)  # float32
    inv = g.add("Sub", [g.const(np.array(1.0, np.float32)), mf])
    bias = g.add("Mul", [inv, g.const(np.array(-10000.0, np.float32))])
    bias = g.unsqueeze(bias, [1, 2])

    for i in range(cfg.layers):
        def head_proj(nm, x=x, i=i):
            mm = g.add("MatMul", [x, f"l{i}.{nm}.w"])
            ad = g.add("Add", [mm, f"l{i}.{nm}.b"])
            r = g.dyn_reshape(ad, ids, (H, Dh))
            return g.add("Transpose", [r], perm=[0, 2, 1, 3])
        q, k, v = head_proj("q"), head_proj("k"), head_proj("v")
        kT = g.add("Transpose", [k], perm=[0, 1, 3, 2])
        logits = g.add("MatMul", [q, kT])
        logits = g.add("Div", [logits,
                               g.const(np.array(np.sqrt(Dh), np.float32))])
        logits = g.add("Add", [logits, bias])
        att = g.add("Softmax", [logits], axis=3)
        ctxt = g.add("MatMul", [att, v])
        ctxt = g.add("Transpose", [ctxt], perm=[0, 2, 1, 3])
        ctxt = g.dyn_reshape(ctxt, ids, (cfg.d_model,))
        attn_out = g.add("Add", [g.add("MatMul", [ctxt, f"l{i}.o.w"]),
                                 f"l{i}.o.b"])
        x = g.layernorm(g.add("Add", [x, attn_out]),
                        f"l{i}.ln1.g", f"l{i}.ln1.b")
        h = g.gelu(g.add("Add", [g.add("MatMul", [x, f"l{i}.ff1.w"]),
                                 f"l{i}.ff1.b"]))
        ff = g.add("Add", [g.add("MatMul", [h, f"l{i}.ff2.w"]), f"l{i}.ff2.b"])
        x = g.layernorm(g.add("Add", [x, ff]), f"l{i}.ln2.g", f"l{i}.ln2.b")

    # rename final output
    g.nodes.append(make_node("Identity", [x], ["last_hidden_state"]))

    # mask-weighted mean pooling → "pooled" (B, D): the sentence-embedding
    # output (sentence-transformers' mean_pooling pattern). Fetching this
    # instead of last_hidden_state cuts the device→host transfer by S×,
    # which is what the BASELINE config #3 pipeline actually wants.
    mexp = g.unsqueeze(mf, [2])                       # (B, S, 1)
    xm = g.add("Mul", [x, mexp])
    if opset >= 13:
        ssum = g.add("ReduceSum", [xm, g.const(np.array([1], np.int64))],
                     keepdims=0)
        cnt = g.add("ReduceSum", [mexp, g.const(np.array([1], np.int64))],
                    keepdims=0)
    else:
        ssum = g.add("ReduceSum", [xm], axes=[1], keepdims=0)
        cnt = g.add("ReduceSum", [mexp], axes=[1], keepdims=0)
    cnt = g.add("Clip", [cnt, g.const(np.array(1e-9, np.float32)),
                         g.const(np.array(3.4e38, np.float32))])
    pooled = g.add("Div", [ssum, cnt])
    g.nodes.append(make_node("Identity", [pooled], ["pooled"]))

    graph = make_graph(
        g.nodes, "bert_encoder",
        inputs=[make_tensor_value_info(ids, np.int64, ("batch", "seq")),
                make_tensor_value_info(mask, np.int64, ("batch", "seq"))],
        outputs=[make_tensor_value_info("last_hidden_state", np.float32,
                                        ("batch", "seq", cfg.d_model)),
                 make_tensor_value_info("pooled", np.float32,
                                        ("batch", cfg.d_model))],
        initializers=g.inits)
    return make_model(graph, opset=opset, producer="pytorch-style")
