"""LM training + draft distillation for the zoo decoder.

Speculative decoding (``speculative.py``) only pays off when the draft's
greedy choices agree with the target's — an untrained draft accepts ~0
proposals and the machinery slows generation down (BASELINE.md, round-4
campaign). This module supplies the missing piece as a first-class
capability:

* :func:`train_lm` — next-token cross-entropy training of any zoo
  ``TransformerConfig`` model (one jitted ``optax`` step, scan-free host
  loop: the batch iterator is a plain callable).
* :func:`distill_draft` — knowledge distillation of a small draft from a
  frozen target: KL(target ‖ draft) on teacher logits over sampled
  prompts. This is the "draft model" production recipe the speculative
  literature assumes; the reference has no serving-side analog (its
  deep-learning module is stateless batch ONNX inference,
  ``deep-learning/.../onnx/ONNXModel.scala:305-355``).

Both run as compiled-per-step programs on whatever backend JAX has; at
zoo scale a few hundred steps take seconds on a TPU chip.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from .transformer import TransformerConfig, init_transformer, transformer_apply

__all__ = ["train_lm", "distill_draft", "markov_sampler"]


def _lm_logits(params: Dict, ids: jnp.ndarray,
               cfg: TransformerConfig) -> jnp.ndarray:
    """(B, S) ids → (B, S, V) next-token logits (f32 head like the
    generators, so training and serving argmax see the same numerics)."""
    h = transformer_apply(params, ids, cfg)
    return h.astype(jnp.float32) @ params["lm_head"]["w"].astype(jnp.float32)


def train_lm(params: Dict, cfg: TransformerConfig,
             batch_fn: Callable[[int], np.ndarray], steps: int,
             learning_rate: float = 3e-4,
             log_every: int = 0) -> Tuple[Dict, list]:
    """Next-token CE training; returns (trained params, loss history).

    ``batch_fn(step) -> (B, S) int32`` supplies token batches (host side —
    corpora are the caller's business). One ``jax.jit`` step: loss grad +
    adamw update; the loop never fetches anything but the scalar loss.
    """
    params = jax.tree.map(jnp.asarray, params)
    opt = optax.adamw(learning_rate)
    opt_state = opt.init(params)

    @jax.jit
    def step_fn(params, opt_state, ids):
        def loss_fn(p):
            logits = _lm_logits(p, ids[:, :-1], cfg)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, ids[:, 1:]).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # losses stay ON DEVICE during the loop (a float() per step would cost
    # one host round-trip each — serialized dead time behind a tunneled
    # chip); one stacked fetch at the end returns the whole history
    dev_losses = []
    for s in range(int(steps)):
        ids = jnp.asarray(np.asarray(batch_fn(s), dtype=np.int32))
        params, opt_state, loss = step_fn(params, opt_state, ids)
        if log_every and (s + 1) % log_every == 0:
            dev_losses.append(loss)
    history = ([float(x) for x in np.asarray(jnp.stack(dev_losses))]
               if dev_losses else [])
    return params, history


def distill_draft(t_params: Dict, t_cfg: TransformerConfig,
                  d_cfg: TransformerConfig,
                  batch_fn: Callable[[int], np.ndarray], steps: int,
                  learning_rate: float = 1e-3, tau: float = 1.0,
                  seed: int = 0,
                  d_params: Optional[Dict] = None) -> Tuple[Dict, list]:
    """Distill a draft for speculative decoding from a frozen target.

    Minimizes KL(softmax(target/τ) ‖ softmax(draft/τ)) over ``batch_fn``
    prompts. The objective is exactly what acceptance measures: the
    draft's greedy choice matching the target's. Returns (draft params,
    loss history). Vocabularies must match (the verifier compares ids).
    """
    if t_cfg.vocab != d_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if d_params is None:
        d_params = init_transformer(d_cfg, seed=seed)
    t_params = jax.tree.map(jnp.asarray, t_params)
    d_params = jax.tree.map(jnp.asarray, d_params)
    opt = optax.adamw(learning_rate)
    opt_state = opt.init(d_params)
    inv_tau = 1.0 / float(tau)

    @jax.jit
    def step_fn(t_params, d_params, opt_state, ids):
        # teacher passed as an ARG: a closure-captured 100M-param tree
        # would be baked into the program as constants (and blow the
        # remote-compile payload behind a tunneled chip)
        t_logits = _lm_logits(t_params, ids, t_cfg) * inv_tau
        t_prob = jax.nn.softmax(t_logits, axis=-1)
        t_ent = -(t_prob * jax.nn.log_softmax(t_logits, axis=-1)).sum(-1)

        def loss_fn(p):
            d_logits = _lm_logits(p, ids, d_cfg) * inv_tau
            ce = -(t_prob * jax.nn.log_softmax(d_logits, axis=-1)).sum(-1)
            return (ce - t_ent).mean()          # KL, >= 0
        loss, grads = jax.value_and_grad(loss_fn)(d_params)
        updates, opt_state = opt.update(grads, opt_state, d_params)
        return optax.apply_updates(d_params, updates), opt_state, loss

    # same device-side loss accumulation as train_lm: zero per-step syncs
    dev_losses = []
    for s in range(int(steps)):
        ids = jnp.asarray(np.asarray(batch_fn(s), dtype=np.int32))
        d_params, opt_state, loss = step_fn(t_params, d_params, opt_state,
                                            ids)
        dev_losses.append(loss)
    history = ([float(x) for x in np.asarray(jnp.stack(dev_losses))]
               if dev_losses else [])
    return d_params, history


def markov_sampler(vocab: int, batch: int, seq: int, seed: int = 0,
                   branching: int = 4):
    """A low-entropy first-order Markov language: every token has
    ``branching`` plausible successors with a dominant mode. Structured
    enough that a trained model's greedy continuations are confident and
    predictable — the regime speculative decoding exists for — while
    synthetic (zero-egress image: no downloadable corpus).

    Returns ``batch_fn(step) -> (batch, seq) int32`` for the trainers.
    """
    rng = np.random.default_rng(seed)
    succ = rng.integers(0, vocab, (vocab, branching))
    probs = np.full(branching, 0.1 / max(branching - 1, 1))
    probs[0] = 0.9
    probs = probs / probs.sum()

    def batch_fn(step: int) -> np.ndarray:
        r = np.random.default_rng(seed * 1_000_003 + step)
        out = np.empty((batch, seq), np.int32)
        out[:, 0] = r.integers(0, vocab, batch)
        for t in range(1, seq):
            choice = r.choice(branching, size=batch, p=probs)
            out[:, t] = succ[out[:, t - 1], choice]
        return out

    return batch_fn
