"""Export the zoo's Llama-style decoder as an ONNX decode-step graph.

The graph is the shape real decoder exports take for serving: ONE token in,
logits out, per-layer kv caches as static (B, H, S_max, D) inputs/outputs
flowing through ORT-contrib ``GroupQueryAttention`` nodes with fused rotary
(``do_rotary``) — the exact op surface ``onnx/convert.py`` executes with
in-place ``dynamic_update_slice`` cache writes. Stepping this graph through
``convert_model`` must reproduce :func:`..transformer.decode_step` logits
bit-for-bit in fp32 (pinned by ``tests/test_decoder_onnx.py``), which
cross-validates the GQA/rotary/RMSNorm handlers against an independent
implementation with learned weights.

Parity role: the reference serves exported decoder graphs through
ONNXModel/ORT (``deep-learning/.../onnx/ONNXModel.scala:173-193``); this is
the native path for bringing OUR trained decoders to that same wire format.
"""

from __future__ import annotations

import numpy as np

from ...onnx.builder import (make_graph, make_model, make_node,
                             make_tensor_value_info)
from .transformer import TransformerConfig

__all__ = ["export_decoder_onnx"]


def export_decoder_onnx(cfg: TransformerConfig, params: dict,
                        max_len: int) -> bytes:
    """Serialize ``params`` (an :func:`init_transformer` pytree for a
    causal/rmsnorm/rope config) as a decode-step ONNX graph with
    ``max_len``-slot kv caches."""
    if not (cfg.causal and cfg.norm == "rmsnorm"
            and cfg.position == "rope"):
        raise ValueError("export_decoder_onnx needs the decoder switches "
                         "(causal=True, norm='rmsnorm', position='rope')")
    if cfg.moe_experts:
        raise ValueError("MoE layers have no ONNX decode-step form here")
    D = cfg.d_model
    H = cfg.heads
    hd = D // H
    if hd % 2:
        # same guard as the zoo's _rope_tables: an odd head dim has no
        # split-half rotation, so the export would match no native model
        raise ValueError(f"rotary embeddings need an even head dim, got "
                         f"{hd} (d_model/heads)")
    half = hd // 2

    inits = {"embed_tok": np.asarray(params["embed"]["tok"], np.float32)}
    # rope caches, the zoo's exact split-half tables
    freqs = 1.0 / (cfg.rope_theta
                   ** (np.arange(0, half, dtype=np.float32) / half))
    ang = np.arange(max_len, dtype=np.float32)[:, None] * freqs
    inits["cos_cache"] = np.cos(ang).astype(np.float32)
    inits["sin_cache"] = np.sin(ang).astype(np.float32)

    nodes = [make_node("Gather", ["embed_tok", "token"], ["h0"], axis=0)]
    h = "h0"
    graph_inputs = [
        make_tensor_value_info("token", np.int64, ["B", 1]),
        make_tensor_value_info("seqlens", np.int32, ["B"]),
        make_tensor_value_info("total", np.int32, []),
    ]
    graph_outputs = []

    for i, lp in enumerate(params["layers"]):
        w = np.asarray(lp["qkv"]["w"], np.float32)
        b = np.asarray(lp["qkv"]["b"], np.float32)
        inits[f"ln1_{i}"] = np.asarray(lp["ln1"]["scale"], np.float32)
        inits[f"ln2_{i}"] = np.asarray(lp["ln2"]["scale"], np.float32)
        for name, sl in (("q", slice(0, D)), ("k", slice(D, 2 * D)),
                         ("v", slice(2 * D, 3 * D))):
            inits[f"w{name}_{i}"] = w[:, sl].copy()
            inits[f"b{name}_{i}"] = b[sl].copy()
        inits[f"wo_{i}"] = np.asarray(lp["out"]["w"], np.float32)
        inits[f"bo_{i}"] = np.asarray(lp["out"]["b"], np.float32)
        inits[f"w1_{i}"] = np.asarray(lp["w1"]["w"], np.float32)
        inits[f"b1_{i}"] = np.asarray(lp["w1"]["b"], np.float32)
        inits[f"w2_{i}"] = np.asarray(lp["w2"]["w"], np.float32)
        inits[f"b2_{i}"] = np.asarray(lp["w2"]["b"], np.float32)

        nodes += [
            make_node("SimplifiedLayerNormalization", [h, f"ln1_{i}"],
                      [f"x_{i}"], epsilon=1e-6, axis=-1),
        ]
        for name in ("q", "k", "v"):
            nodes += [
                make_node("MatMul", [f"x_{i}", f"w{name}_{i}"],
                          [f"{name}mm_{i}"]),
                make_node("Add", [f"{name}mm_{i}", f"b{name}_{i}"],
                          [f"{name}_{i}"]),
            ]
        nodes.append(make_node(
            "GroupQueryAttention",
            [f"q_{i}", f"k_{i}", f"v_{i}", f"past_k_{i}", f"past_v_{i}",
             "seqlens", "total", "cos_cache", "sin_cache"],
            [f"attn_{i}", f"present_k_{i}", f"present_v_{i}"],
            domain="com.microsoft", num_heads=H, kv_num_heads=H,
            do_rotary=1, rotary_interleaved=0))
        nodes += [
            make_node("MatMul", [f"attn_{i}", f"wo_{i}"], [f"omm_{i}"]),
            make_node("Add", [f"omm_{i}", f"bo_{i}"], [f"oproj_{i}"]),
            make_node("Add", [h, f"oproj_{i}"], [f"hattn_{i}"]),
            make_node("SimplifiedLayerNormalization",
                      [f"hattn_{i}", f"ln2_{i}"], [f"y_{i}"],
                      epsilon=1e-6, axis=-1),
            make_node("MatMul", [f"y_{i}", f"w1_{i}"], [f"ff1_{i}"]),
            # FastGelu (com.microsoft): tanh-approximate gelu with a fused
            # bias input — matches the zoo's jax.nn.gelu default AND loads
            # in real onnxruntime (ai.onnx Gelu only exists from opset 20;
            # this graph targets the ORT-optimizer op surface anyway)
            make_node("FastGelu", [f"ff1_{i}", f"b1_{i}"], [f"act_{i}"],
                      domain="com.microsoft"),
            make_node("MatMul", [f"act_{i}", f"w2_{i}"], [f"ff2_{i}"]),
            make_node("Add", [f"ff2_{i}", f"b2_{i}"], [f"ff2b_{i}"]),
            make_node("Add", [f"hattn_{i}", f"ff2b_{i}"], [f"h{i + 1}"]),
        ]
        h = f"h{i + 1}"
        graph_inputs += [
            make_tensor_value_info(f"past_k_{i}", np.float32,
                                   ["B", H, max_len, hd]),
            make_tensor_value_info(f"past_v_{i}", np.float32,
                                   ["B", H, max_len, hd]),
        ]
        graph_outputs += [
            make_tensor_value_info(f"present_k_{i}", np.float32,
                                   ["B", H, max_len, hd]),
            make_tensor_value_info(f"present_v_{i}", np.float32,
                                   ["B", H, max_len, hd]),
        ]

    inits["final_ln"] = np.asarray(params["final_ln"]["scale"], np.float32)
    inits["lm_w"] = np.asarray(params["lm_head"]["w"], np.float32)
    inits["sq_ax"] = np.array([1], np.int64)
    nodes += [
        make_node("SimplifiedLayerNormalization", [h, "final_ln"],
                  ["hf"], epsilon=1e-6, axis=-1),
        make_node("MatMul", ["hf", "lm_w"], ["logits3"]),
        make_node("Squeeze", ["logits3", "sq_ax"], ["logits"]),
    ]
    graph_outputs.insert(0, make_tensor_value_info(
        "logits", np.float32, ["B", cfg.vocab]))

    g = make_graph(nodes, "decoder_step", graph_inputs, graph_outputs,
                   initializers=inits)
    # the com.microsoft import is required for the GQA/FastGelu/
    # SimplifiedLayerNormalization nodes to load in real onnxruntime
    return make_model(g, opset=17, extra_opsets={"com.microsoft": 1})
