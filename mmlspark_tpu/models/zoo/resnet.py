"""ResNet family — native JAX implementation + ONNX exporter.

Serves two roles:

* the flagship CNN for benchmarks (NHWC + bfloat16, the TPU-preferred
  layout: convs land on the MXU with no transposes), and
* a generator of real ResNet-50 ONNX graphs (NCHW, the ONNX convention) so
  the ONNX→JAX path is exercised at the scale of BASELINE config #1
  ("ONNXModel ResNet-50 image classification").

Reference parity: the reference runs ResNet-class models through
``ONNXModel``/``ImageFeaturizer`` (``deep-learning/.../onnx/ONNXModel.scala``,
``cntk/ImageFeaturizer.scala``); it has no model zoo of its own beyond
``ModelDownloader``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ResNetConfig", "RESNET50", "init_resnet", "resnet_apply",
           "export_resnet_onnx"]


class ResNetConfig:
    def __init__(self, stage_sizes: List[int], num_classes: int = 1000,
                 width: int = 64, dtype=jnp.bfloat16):
        self.stage_sizes = stage_sizes
        self.num_classes = num_classes
        self.width = width
        self.dtype = dtype

RESNET50 = ResNetConfig([3, 4, 6, 3])
RESNET18_CFG = ResNetConfig([2, 2, 2, 2])

# -- native NHWC implementation ---------------------------------------------

def _conv_init(rng, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return (rng.normal(0, np.sqrt(2.0 / fan_in), (kh, kw, cin, cout))
            .astype(np.float32))


def init_resnet(cfg: ResNetConfig = RESNET50, seed: int = 0) -> Dict:
    """He-initialized parameter pytree (BN folded to scale/bias for inference)."""
    rng = np.random.default_rng(seed)
    params: Dict = {"stem": {
        "w": _conv_init(rng, 7, 7, 3, cfg.width),
        "scale": np.ones(cfg.width, np.float32),
        "bias": np.zeros(cfg.width, np.float32),
    }, "stages": []}
    cin = cfg.width
    for si, nblocks in enumerate(cfg.stage_sizes):
        cmid = cfg.width * (2 ** si)
        cout = cmid * 4
        stage = []
        for bi in range(nblocks):
            blk = {
                "conv1": {"w": _conv_init(rng, 1, 1, cin, cmid),
                          "scale": np.ones(cmid, np.float32),
                          "bias": np.zeros(cmid, np.float32)},
                "conv2": {"w": _conv_init(rng, 3, 3, cmid, cmid),
                          "scale": np.ones(cmid, np.float32),
                          "bias": np.zeros(cmid, np.float32)},
                "conv3": {"w": _conv_init(rng, 1, 1, cmid, cout),
                          "scale": np.ones(cout, np.float32),
                          "bias": np.zeros(cout, np.float32)},
            }
            if bi == 0:
                blk["proj"] = {"w": _conv_init(rng, 1, 1, cin, cout),
                               "scale": np.ones(cout, np.float32),
                               "bias": np.zeros(cout, np.float32)}
            stage.append(blk)
            cin = cout
        params["stages"].append(stage)
    params["head"] = {
        "w": rng.normal(0, 0.01, (cin, cfg.num_classes)).astype(np.float32),
        "b": np.zeros(cfg.num_classes, np.float32)}
    return params


def _conv_bn(x, p, stride=1, dtype=jnp.bfloat16):
    w = p["w"].astype(dtype)
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=[(w.shape[0] // 2, w.shape[0] // 2),
                 (w.shape[1] // 2, w.shape[1] // 2)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=dtype)
    return y * p["scale"].astype(dtype) + p["bias"].astype(dtype)


def resnet_apply(params: Dict, x: jnp.ndarray,
                 cfg: ResNetConfig = RESNET50,
                 features_only: bool = False) -> jnp.ndarray:
    """Forward pass. ``x`` is NHWC float; compute in ``cfg.dtype`` (bf16)."""
    dt = cfg.dtype
    x = x.astype(dt)
    x = _conv_bn(x, params["stem"], stride=2, dtype=dt)
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), [(0, 0), (1, 1), (1, 1), (0, 0)])
    for si, stage in enumerate(params["stages"]):
        for bi, blk in enumerate(stage):
            stride = 2 if (si > 0 and bi == 0) else 1
            shortcut = x
            y = jax.nn.relu(_conv_bn(x, blk["conv1"], dtype=dt))
            y = jax.nn.relu(_conv_bn(y, blk["conv2"], stride=stride, dtype=dt))
            y = _conv_bn(y, blk["conv3"], dtype=dt)
            if "proj" in blk:
                shortcut = _conv_bn(x, blk["proj"], stride=stride, dtype=dt)
            x = jax.nn.relu(y + shortcut)
    x = jnp.mean(x, axis=(1, 2))  # global average pool
    if features_only:
        return x.astype(jnp.float32)
    logits = x.astype(jnp.float32) @ params["head"]["w"] + params["head"]["b"]
    return logits


# -- ONNX exporter -----------------------------------------------------------

def export_resnet_onnx(cfg: ResNetConfig = RESNET50, seed: int = 0,
                       params: Optional[Dict] = None,
                       input_size: int = 224) -> bytes:
    """Emit a standard NCHW ResNet ONNX graph (Conv+BN pre-folded to
    Conv-with-bias via scale/bias multiplication, matching inference form)."""
    from ...onnx import (make_graph, make_model, make_node,
                         make_tensor_value_info)
    if params is None:
        params = init_resnet(cfg, seed)
    nodes, inits = [], {}
    uid = [0]

    def conv(x_name, p, stride, out_name):
        uid[0] += 1
        wname, bname = f"w{uid[0]}", f"b{uid[0]}"
        # fold BN scale/bias into conv weight+bias (inference form)
        w_nhwc = p["w"] * p["scale"][None, None, None, :]
        w_oihw = np.transpose(w_nhwc, (3, 2, 0, 1)).astype(np.float32)
        inits[wname] = np.ascontiguousarray(w_oihw)
        inits[bname] = p["bias"].astype(np.float32)
        kh = p["w"].shape[0]
        nodes.append(make_node("Conv", [x_name, wname, bname], [out_name],
                               strides=[stride, stride],
                               pads=[kh // 2, kh // 2, kh // 2, kh // 2],
                               kernel_shape=[kh, p["w"].shape[1]]))
        return out_name

    x = conv("input", params["stem"], 2, "stem")
    nodes.append(make_node("Relu", [x], ["stem_r"]))
    nodes.append(make_node("MaxPool", ["stem_r"], ["pool0"],
                           kernel_shape=[3, 3], strides=[2, 2],
                           pads=[1, 1, 1, 1]))
    x = "pool0"
    for si, stage in enumerate(params["stages"]):
        for bi, blk in enumerate(stage):
            stride = 2 if (si > 0 and bi == 0) else 1
            base = f"s{si}b{bi}"
            y = conv(x, blk["conv1"], 1, f"{base}_c1")
            nodes.append(make_node("Relu", [y], [f"{base}_r1"]))
            y = conv(f"{base}_r1", blk["conv2"], stride, f"{base}_c2")
            nodes.append(make_node("Relu", [y], [f"{base}_r2"]))
            y = conv(f"{base}_r2", blk["conv3"], 1, f"{base}_c3")
            if "proj" in blk:
                sc = conv(x, blk["proj"], stride, f"{base}_proj")
            else:
                sc = x
            nodes.append(make_node("Add", [y, sc], [f"{base}_add"]))
            nodes.append(make_node("Relu", [f"{base}_add"], [f"{base}_out"]))
            x = f"{base}_out"
    nodes.append(make_node("GlobalAveragePool", [x], ["gap"]))
    nodes.append(make_node("Flatten", ["gap"], ["feat"], axis=1))
    inits["head_w"] = params["head"]["w"].astype(np.float32)
    inits["head_b"] = params["head"]["b"].astype(np.float32)
    nodes.append(make_node("Gemm", ["feat", "head_w", "head_b"], ["logits"]))
    graph = make_graph(
        nodes, "resnet",
        [make_tensor_value_info("input", np.float32,
                                ["N", 3, input_size, input_size])],
        [make_tensor_value_info("logits", np.float32,
                                ["N", cfg.num_classes]),
         make_tensor_value_info("feat", np.float32, ["N", None])],
        initializers=inits)
    return make_model(graph)
