"""Pretrained-model repository.

Parity: ``deep-learning/.../downloader/ModelDownloader.scala``
(``Repository[S]:26``, ``HDFSRepo:42``, ``DefaultModelRepo:112``) and the
``ModelSchema`` metadata (``downloader/Schema.scala``) the featurizer uses
to find layer names/input shapes.

This environment has zero egress, so the "remote" repository is the
built-in generator zoo (ResNet family ONNX export); ``LocalRepo`` plays
the HDFSRepo role for models already materialized on disk. The schema
format is JSON and the layout is one directory per model, so a real
remote repo can be mounted the same way.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

__all__ = ["ModelSchema", "ModelDownloader", "LocalRepo", "BUILTIN_MODELS"]


@dataclasses.dataclass
class ModelSchema:
    """Parity: ``downloader/Schema.scala:89`` — the metadata a featurizer
    needs (input shape, layer names to cut, output info)."""
    name: str
    dataset: str = "ImageNet"
    model_type: str = "image"
    uri: str = ""
    input_size: int = 224
    num_outputs: int = 1000
    #: outputs ordered head→features: cutOutputLayers indexes into this
    layer_names: List[str] = dataclasses.field(
        default_factory=lambda: ["logits", "feat"])

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @staticmethod
    def from_json(s: str) -> "ModelSchema":
        return ModelSchema(**json.loads(s))


def _gen_resnet50() -> bytes:
    from .resnet import RESNET50, export_resnet_onnx
    return export_resnet_onnx(RESNET50, seed=0)


def _gen_resnet18() -> bytes:
    from .resnet import RESNET18_CFG, export_resnet_onnx
    return export_resnet_onnx(RESNET18_CFG, seed=0)


def _gen_vit_b16() -> bytes:
    from .vit import ViTConfig, export_vit_onnx
    return export_vit_onnx(ViTConfig(image_size=224, patch=16, d_model=768,
                                     heads=12, layers=12, d_ff=3072,
                                     num_classes=1000), seed=0)

BUILTIN_MODELS: Dict[str, tuple] = {
    # name → (schema, generator)
    "ResNet50": (ModelSchema("ResNet50"), _gen_resnet50),
    "ResNet18": (ModelSchema("ResNet18"), _gen_resnet18),
    "ViT-B-16": (ModelSchema("ViT-B-16"), _gen_vit_b16),
}


class ModelDownloader:
    """Materialize models into a local directory and enumerate them
    (parity: ``ModelDownloader.downloadModel`` / ``models`` iterator)."""

    def __init__(self, local_path: str,
                 generators: Optional[Dict[str, tuple]] = None):
        self.local_path = local_path
        self.generators = dict(generators or BUILTIN_MODELS)
        os.makedirs(local_path, exist_ok=True)

    def remote_models(self) -> List[ModelSchema]:
        return [schema for schema, _gen in self.generators.values()]

    def local_models(self) -> List[ModelSchema]:
        out = []
        for name in sorted(os.listdir(self.local_path)):
            meta = os.path.join(self.local_path, name, "schema.json")
            if os.path.isfile(meta):
                with open(meta) as f:
                    out.append(ModelSchema.from_json(f.read()))
        return out

    def download_model(self, name: str) -> ModelSchema:
        """Generate/copy the model into the local repo; idempotent."""
        if name not in self.generators:
            raise KeyError(f"unknown model {name!r}; "
                           f"known: {sorted(self.generators)}")
        schema, gen = self.generators[name]
        mdir = os.path.join(self.local_path, name)
        schema_path = os.path.join(mdir, "schema.json")
        onnx_path = os.path.join(mdir, "model.onnx")
        # schema.json is the commit marker and is written LAST via rename, so
        # a crash mid-download leaves a repairable dir, never a bricked one
        if not os.path.isfile(schema_path):
            os.makedirs(mdir, exist_ok=True)
            tmp = onnx_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(gen())
            os.replace(tmp, onnx_path)
            schema = dataclasses.replace(schema, uri=onnx_path)
            tmp_s = schema_path + ".tmp"
            with open(tmp_s, "w") as f:
                f.write(schema.to_json())
            os.replace(tmp_s, schema_path)
        with open(schema_path) as f:
            return ModelSchema.from_json(f.read())

    def load_bytes(self, name: str) -> bytes:
        schema = self.download_model(name)
        with open(schema.uri, "rb") as f:
            return f.read()


class LocalRepo:
    """Enumerate an already-materialized model directory (HDFSRepo parity)."""

    def __init__(self, path: str):
        self.path = path

    def models(self) -> List[ModelSchema]:
        return ModelDownloader(self.path, generators={}).local_models()
