"""Transformer encoder — native JAX, mesh-sharded (dp × tp with Megatron-style
sequence parallelism), plus a full training step.

The reference has **no** intra-model sharding anywhere (SURVEY.md §2.8) — its
largest models run whole-per-executor through ONNX/CNTK sessions. This module
is where the TPU rebuild goes past parity: a BERT-class encoder whose weights
and activations are laid out over a ``Mesh(('dp','tp'))``:

* batch sharded over ``dp``;
* attention heads and MLP hidden dim sharded over ``tp`` (Megatron split:
  QKV/W1 column-parallel, O/W2 row-parallel — XLA inserts the psum);
* activations outside attention/MLP sharded over the sequence axis on ``tp``
  (sequence parallelism), so layernorm/residual memory scales with 1/tp;
* ring attention over long sequences lives in ``parallel/ring.py`` and mounts
  on the same mesh (axis ``sp``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["TransformerConfig", "init_transformer", "transformer_apply",
           "train_step", "param_shardings", "BERT_BASE", "BERT_MINI",
           "DECODER_MINI", "generate", "generate_cached",
           "decode_step", "init_kv_cache", "decode_window_ragged",
           "init_paged_cache", "paged_gather", "paged_scatter_rows",
           "decode_step_paged", "decode_window_paged"]


class TransformerConfig(NamedTuple):
    vocab: int = 30522
    layers: int = 12
    d_model: int = 768
    heads: int = 12
    d_ff: int = 3072
    max_len: int = 512
    dtype: Any = jnp.bfloat16
    #: >0 turns every ``moe_every``-th FFN into a mixture-of-experts block
    #: (experts sharded over dp — the GShard deployment; parallel/moe.py)
    moe_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    #: weight of the Switch/GShard load-balance loss (keeps the router from
    #: collapsing onto one expert, which silently drops tokens)
    moe_aux_weight: float = 0.01
    #: route attention through the Pallas flash kernel (``ops/flash_attention``)
    #: — O(S) memory streaming softmax instead of the (B, H, S, S) score
    #: matrix; on a mesh it mounts per-shard via shard_map (heads on tp).
    #: Semantics differ from the dense path only for a row whose mask is
    #: all-False (a fully-padded sequence): dense -1e9 bias degenerates to
    #: uniform attention (mean of v), flash yields exact zeros — the
    #: better-defined output, but flip-sensitive if a consumer pools padded
    #: rows without masking
    use_flash: bool = False
    #: decoder (Llama-family) switches: causal attention, RMSNorm instead
    #: of LayerNorm, rotary position embeddings instead of the learned
    #: position table
    causal: bool = False
    norm: str = "layernorm"        # "layernorm" | "rmsnorm"
    position: str = "learned"      # "learned" | "rope"
    rope_theta: float = 10000.0

    def is_moe_layer(self, i: int) -> bool:
        return (self.moe_experts > 0 and self.moe_every > 0
                and (i % self.moe_every) == (self.moe_every - 1))


BERT_BASE = TransformerConfig()
#: Llama-style decoder shape (causal + RMSNorm + RoPE); small enough to test
DECODER_MINI = TransformerConfig(vocab=1024, layers=4, d_model=256, heads=8,
                                 d_ff=1024, max_len=128, causal=True,
                                 norm="rmsnorm", position="rope")
BERT_MINI = TransformerConfig(vocab=1024, layers=4, d_model=256, heads=8,
                              d_ff=1024, max_len=128)


def init_transformer(cfg: TransformerConfig, seed: int = 0) -> Dict:
    rng = np.random.default_rng(seed)

    def dense(din, dout, scale=None):
        s = scale or np.sqrt(2.0 / (din + dout))
        return rng.normal(0, s, (din, dout)).astype(np.float32)

    def norm_p():
        p = {"scale": np.ones(cfg.d_model, np.float32)}
        if cfg.norm != "rmsnorm":       # RMSNorm has no bias
            p["bias"] = np.zeros(cfg.d_model, np.float32)
        return p

    params: Dict = {
        "embed": {"tok": dense(cfg.vocab, cfg.d_model, 0.02)},
        "layers": [],
        "final_ln": norm_p(),
        "lm_head": {"w": dense(cfg.d_model, cfg.vocab, 0.02)},
    }
    if cfg.position == "learned":
        params["embed"]["pos"] = dense(cfg.max_len, cfg.d_model, 0.02)
    for i in range(cfg.layers):
        layer = {
            "ln1": norm_p(),
            "qkv": {"w": dense(cfg.d_model, 3 * cfg.d_model),
                    "b": np.zeros(3 * cfg.d_model, np.float32)},
            "out": {"w": dense(cfg.d_model, cfg.d_model),
                    "b": np.zeros(cfg.d_model, np.float32)},
            "ln2": norm_p(),
        }
        if cfg.is_moe_layer(i):
            from ...parallel.moe import init_moe_params
            layer["moe"] = init_moe_params(cfg.d_model, cfg.d_ff,
                                           cfg.moe_experts,
                                           seed=seed * 1000 + i)
        else:
            layer["w1"] = {"w": dense(cfg.d_model, cfg.d_ff),
                           "b": np.zeros(cfg.d_ff, np.float32)}
            layer["w2"] = {"w": dense(cfg.d_ff, cfg.d_model),
                           "b": np.zeros(cfg.d_model, np.float32)}
        params["layers"].append(layer)
    return params


def param_shardings(mesh: Mesh) -> Dict:
    """PartitionSpec pytree matching ``init_transformer`` (Megatron layout)."""
    def norm_spec(lp):
        return {k: P() for k in lp}

    def layer_spec(is_moe: bool = False, lp=None):
        lp = lp or {}
        spec = {
            "ln1": norm_spec(lp.get("ln1", {"scale": 0, "bias": 0})),
            "qkv": {"w": P(None, "tp"), "b": P("tp")},      # column-parallel
            "out": {"w": P("tp", None), "b": P()},          # row-parallel
            "ln2": norm_spec(lp.get("ln2", {"scale": 0, "bias": 0})),
        }
        if is_moe:
            # experts over dp (GShard: ep == dp), expert hidden over tp
            spec["moe"] = {"gate": P(),
                           "w1": P("dp", None, "tp"),
                           "b1": P("dp", "tp"),
                           "w2": P("dp", "tp", None),
                           "b2": P("dp", None)}
        else:
            spec["w1"] = {"w": P(None, "tp"), "b": P("tp")}
            spec["w2"] = {"w": P("tp", None), "b": P()}
        return spec

    return {
        "embed": {"tok": P(None, "tp"), "pos": P(None, "tp")},
        "layers": [],  # filled dynamically by tree mapping below
        "final_ln": {"scale": P(), "bias": P()},
        "lm_head": {"w": P(None, "tp")},
        "_layer_template": layer_spec,
        "_norm_template": norm_spec,
    }


def shardings_for(params: Dict, mesh: Mesh) -> Dict:
    spec = param_shardings(mesh)
    template = spec.pop("_layer_template")
    norm_template = spec.pop("_norm_template")
    spec["layers"] = [template(is_moe="moe" in lp, lp=lp)
                      for lp in params["layers"]]
    spec["embed"] = {k: spec["embed"][k] for k in params["embed"]}
    spec["final_ln"] = norm_template(params["final_ln"])
    return jax.tree.map(lambda p: NamedSharding(mesh, p), spec,
                        is_leaf=lambda x: isinstance(x, P))


def _ln(x, p, eps=1e-5):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * p["scale"] + p["bias"]


def _rms(x, p, eps=1e-6):
    inv = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return x * inv * p["scale"]


def _norm(x, p, cfg):
    return _rms(x, p) if cfg.norm == "rmsnorm" else _ln(x, p)


def _rope_tables(positions, D: int, theta: float, dtype):
    """cos/sin tables for split-half rotation at the given positions
    (any shape); shared by the full forward and the cached decode step."""
    if D % 2:
        raise ValueError(f"rotary embeddings need an even head dim, got {D} "
                         f"(d_model/heads)")
    half = D // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    ang = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(ang).astype(dtype), jnp.sin(ang).astype(dtype)


def _rot_half(t, cos, sin):
    half = t.shape[-1] // 2
    t0, t1 = t[..., :half], t[..., half:]
    return jnp.concatenate([t0 * cos - t1 * sin,
                            t0 * sin + t1 * cos], axis=-1)


def _rope(q, k, theta: float):
    """Rotary position embeddings on (B, H, S, D) q/k (split-half form)."""
    cos, sin = _rope_tables(jnp.arange(q.shape[2]), q.shape[-1], theta,
                            q.dtype)
    cos, sin = cos[None, None], sin[None, None]
    return _rot_half(q, cos, sin), _rot_half(k, cos, sin)


def transformer_apply(params: Dict, ids: jnp.ndarray,
                      cfg: TransformerConfig,
                      mesh: Optional[Mesh] = None,
                      mask: Optional[jnp.ndarray] = None,
                      return_aux: bool = False):
    """Encoder forward → final hidden states (B, S, D) in cfg.dtype.

    ``return_aux=True`` additionally returns the accumulated MoE
    auxiliaries {``balance``: load-balance loss the trainer must add,
    ``dropped``: over-capacity token count} — a functional return, not an
    out-parameter, so it survives jit (a mutated-dict argument would be a
    trace-local copy)."""
    if cfg.norm not in ("layernorm", "rmsnorm"):
        raise ValueError(f"cfg.norm {cfg.norm!r} (layernorm | rmsnorm)")
    if cfg.position not in ("learned", "rope"):
        raise ValueError(f"cfg.position {cfg.position!r} (learned | rope)")
    dt = cfg.dtype
    B, S = ids.shape

    def constrain(x, spec):
        if mesh is not None:
            return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
        return x

    moe_aux = {"balance": jnp.float32(0.0), "dropped": jnp.float32(0.0)}
    h = params["embed"]["tok"].astype(dt)[ids]
    if cfg.position == "learned":
        h = h + params["embed"]["pos"].astype(dt)[:S][None, :, :]
    # sequence-parallel region: activations sharded (dp, tp) on (B, S)
    h = constrain(h, P("dp", "tp", None))

    if mask is not None:
        bias = jnp.where(mask[:, None, None, :], 0.0, -1e9).astype(jnp.float32)
    else:
        bias = None

    for lp in params["layers"]:
        x = _norm(h.astype(jnp.float32), lp["ln1"], cfg).astype(dt)
        x = constrain(x, P("dp", None, None))  # gather sequence for attention
        qkv = x @ lp["qkv"]["w"].astype(dt) + lp["qkv"]["b"].astype(dt)
        qkv = constrain(qkv, P("dp", None, "tp"))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        hd = cfg.d_model // cfg.heads

        def heads(t):
            return t.reshape(B, S, cfg.heads, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if cfg.position == "rope":
            q, k = _rope(q, k, cfg.rope_theta)
        if cfg.use_flash:
            from ...ops.flash_attention import (flash_attention,
                                                flash_attention_sharded)
            if mesh is not None:
                ctx = flash_attention_sharded(q, k, v, mesh, kv_mask=mask,
                                              causal=cfg.causal)
            else:
                ctx = flash_attention(q, k, v, kv_mask=mask,
                                      causal=cfg.causal)
            ctx = ctx.astype(dt)
        else:
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                                preferred_element_type=jnp.float32) / np.sqrt(hd)
            if bias is not None:
                scores = scores + bias
            if cfg.causal:
                tri = jnp.tril(jnp.ones((S, S), bool))
                scores = jnp.where(tri[None, None], scores,
                                   jnp.float32(-1e9))
            attn = jax.nn.softmax(scores, axis=-1).astype(dt)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", attn, v,
                             preferred_element_type=dt)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, cfg.d_model)
        proj = ctx @ lp["out"]["w"].astype(dt) + lp["out"]["b"].astype(dt)
        h = h + constrain(proj, P("dp", "tp", None))  # back to sequence-parallel

        x = _norm(h.astype(jnp.float32), lp["ln2"], cfg).astype(dt)
        x = constrain(x, P("dp", None, None))
        if "moe" in lp:
            from ...parallel.moe import moe_capacity, moe_ffn_gspmd
            cap = moe_capacity(S, cfg.moe_experts, cfg.moe_capacity_factor)
            y, aux = moe_ffn_gspmd(x, lp["moe"], cfg.moe_experts, cap,
                                   mesh=mesh, ep_axis="dp",
                                   tp_axis="tp")
            moe_aux["balance"] = moe_aux["balance"] + aux["balance_loss"]
            moe_aux["dropped"] = moe_aux["dropped"] + aux["dropped"]
        else:
            y = jax.nn.gelu(x @ lp["w1"]["w"].astype(dt)
                            + lp["w1"]["b"].astype(dt))
            y = constrain(y, P("dp", None, "tp"))
            y = y @ lp["w2"]["w"].astype(dt) + lp["w2"]["b"].astype(dt)
        h = h + constrain(y, P("dp", "tp", None))

    hidden = _norm(h.astype(jnp.float32), params["final_ln"], cfg).astype(dt)
    return (hidden, moe_aux) if return_aux else hidden


def loss_fn(params, ids, labels, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None):
    hidden, moe_aux = transformer_apply(params, ids, cfg, mesh,
                                        return_aux=True)
    logits = (hidden.astype(jnp.float32) @ params["lm_head"]["w"])
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return nll.mean() + cfg.moe_aux_weight * moe_aux["balance"]


def train_step(params, opt_state, ids, labels, cfg: TransformerConfig,
               mesh: Optional[Mesh] = None, lr: float = 1e-4):
    """One SGD-with-momentum step; grads/opt-state shard like params."""
    loss, grads = jax.value_and_grad(loss_fn)(params, ids, labels, cfg, mesh)
    new_m = jax.tree.map(lambda m, g: 0.9 * m + g, opt_state, grads)
    new_p = jax.tree.map(lambda p, m: p - lr * m, params, new_m)
    return new_p, new_m, loss


def _warp_scaled_rows(scaled, top_k, top_p):
    """Top-k then nucleus filtering on temperature-scaled (S, V) logit
    rows with PER-ROW parameters (-inf outside the kept set) — the HF
    convention ``transformer._sample_logits`` follows. Neutral values
    (top_k=0 → k=V, top_p≥1 → cutoff at the sorted tail) reduce every
    filter to a no-op. Shared by the continuous
    engine's per-slot sampler and both speculative-sampling ratio
    tests (zoo + pool), which must warp the TARGET and the DRAFT
    with the same function to stay distribution-exact."""
    S, V = scaled.shape
    sorted_l = jnp.sort(scaled, axis=-1)[:, ::-1]
    k = jnp.where(top_k > 0, jnp.minimum(top_k, V), V)          # (S,)
    kth = jnp.take_along_axis(sorted_l, (k - 1)[:, None], axis=-1)
    filtered = jnp.where(scaled < kth, -jnp.inf, scaled)
    # nucleus mass over the k-filtered renormalized distribution
    posn = jnp.arange(V)[None]
    sorted_f = jnp.where(posn >= k[:, None], -jnp.inf, sorted_l)
    probs = jax.nn.softmax(sorted_f, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    eff_p = jnp.where((top_p > 0.0) & (top_p < 1.0), top_p, 1.0)
    cutoff_idx = jnp.sum(cum < eff_p[:, None], axis=-1)
    cutoff = jnp.take_along_axis(sorted_f, cutoff_idx[:, None], axis=-1)
    return jnp.where(filtered < cutoff, -jnp.inf, filtered)



def _sample_logits(logits, key, temperature: float, top_k: int,
                   top_p: float):
    """Greedy (temperature 0) or filtered sampling shared by both
    generators: optional top-k truncation then nucleus (top-p) truncation,
    applied to (B, V) float32 logits."""
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    need_k = top_k > 0
    need_p = 0.0 < top_p < 1.0
    if need_k or need_p:
        # ONE descending sort serves both filters (per emitted token,
        # inside the decode scan — worth not doing twice)
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        if need_k:
            k = min(int(top_k), logits.shape[-1])   # oversized k = no-op
            logits = jnp.where(logits < sorted_l[:, k - 1][:, None],
                               -jnp.inf, logits)
        if need_p:
            # nucleus mass comes from the top-k-FILTERED renormalized
            # distribution (the HF convention) — mask the sorted tail
            # before the softmax/cumsum; renormalized mass reaches top_p
            # at an equal-or-earlier rank, so pre-filter mass would keep
            # MORE tokens inside the top-k set than callers expect
            if need_k:
                pos = jnp.arange(sorted_l.shape[-1])[None, :]
                sorted_l = jnp.where(pos >= k, -jnp.inf, sorted_l)
            probs = jax.nn.softmax(sorted_l, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep the smallest prefix with mass >= top_p (always >= 1)
            cutoff_idx = jnp.sum(cum < top_p, axis=-1)
            cutoff = jnp.take_along_axis(sorted_l, cutoff_idx[:, None],
                                         axis=-1)
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def generate(params: Dict, prompt_ids, cfg: TransformerConfig,
             max_new_tokens: int = 32, temperature: float = 0.0,
             seed: int = 0, top_k: int = 0, top_p: float = 1.0,
             eos_id: Optional[int] = None):
    """Autoregressive generation from a causal config (greedy when
    ``temperature == 0``, else softmax sampling). ``eos_id``: rows that
    emit it keep repeating it (static shapes — the convention the
    continuous engine's per-request truncation builds on).

    One jitted program: the sequence is padded to prompt+new length and the
    whole forward runs each step — causality guarantees position ``t``'s
    logits never see the not-yet-generated tail, so no KV-cache machinery
    is needed for correctness (the cache is a latency optimization this
    zoo model omits; cost is O(steps · full-forward)).
    """
    if not cfg.causal:
        raise ValueError("generate() needs cfg.causal=True")
    # numpy params indexed by a traced token array would force a tracer
    # →numpy conversion inside the scan
    params = jax.tree.map(jnp.asarray, params)
    prompt_ids = jnp.asarray(prompt_ids)
    B, P_len = prompt_ids.shape
    if P_len < 1:
        raise ValueError("generate() needs at least one prompt token "
                         "(an empty prompt would condition on padding)")
    L = P_len + max_new_tokens
    if L > cfg.max_len and cfg.position == "learned":
        raise ValueError(f"prompt+new = {L} exceeds max_len {cfg.max_len}")
    ids0 = jnp.pad(prompt_ids, ((0, 0), (0, max_new_tokens)))
    key0 = jax.random.PRNGKey(seed)

    def step(carry, t):
        ids, done = carry
        hidden = transformer_apply(params, ids, cfg)
        logits = (hidden[:, t - 1].astype(jnp.float32)
                  @ params["lm_head"]["w"])
        # fold_in by position: the cached generator derives the same key
        # at the same emit position, keeping the two paths seed-compatible
        nxt = _sample_logits(logits, jax.random.fold_in(key0, t),
                             temperature, top_k, top_p)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.full_like(nxt, eos_id), nxt)
            done = done | (nxt == eos_id)
        ids = jax.lax.dynamic_update_slice(
            ids, nxt[:, None].astype(ids.dtype), (0, t))
        return (ids, done), nxt

    (ids, _), _ = jax.lax.scan(step, (ids0, jnp.zeros(B, bool)),
                               jnp.arange(P_len, L))
    return ids


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int):
    """Per-layer (B, H, L, D) key/value buffers for incremental decoding."""
    hd = cfg.d_model // cfg.heads
    shape = (batch, cfg.heads, max_len, hd)
    return [{"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.layers)]


def decode_step(params: Dict, token: jnp.ndarray, pos, cache,
                cfg: TransformerConfig):
    """One incremental decode step: ``token`` (B,) int at position ``pos``
    → (logits (B, vocab), updated cache). The KV-cache latency path of
    :func:`generate` — O(L) attention per step instead of a full forward.

    The shared-``pos`` special case of :func:`decode_step_ragged` (one
    layer-loop implementation keeps the two bit-identical — the continuous
    batching engine's parity invariant depends on it)."""
    B = token.shape[0]
    return decode_step_ragged(
        params, token, jnp.full((B,), pos, jnp.int32), cache, cfg)


def decode_step_ragged(params: Dict, tokens: jnp.ndarray, pos: jnp.ndarray,
                       cache, cfg: TransformerConfig,
                       active: Optional[jnp.ndarray] = None):
    """:func:`decode_step` with PER-ROW positions — the continuous-batching
    step (``serving/continuous.py``): each cache slot advances at its own
    position, so requests at different depths share one compiled program.

    ``tokens`` (B,) int, ``pos`` (B,) int32 per-row write positions,
    ``active`` (B,) bool (inactive rows keep their cache untouched and
    their logits are don't-care) → (logits (B, vocab), updated cache).

    Same math as :func:`decode_step` per row; the only structural deltas
    are per-row RoPE/learned-position gathers, a vmapped per-row cache
    scatter, and the per-row key mask ``arange(L) <= pos[:, None]``.
    """
    if cfg.moe_experts:
        raise ValueError("cached decoding does not support MoE layers")
    dt = cfg.dtype
    B = tokens.shape[0]
    L = cache[0]["k"].shape[2]
    hd = cfg.d_model // cfg.heads
    pos = pos.astype(jnp.int32)
    h = params["embed"]["tok"].astype(dt)[tokens][:, None, :]   # (B, 1, D)
    if cfg.position == "learned":
        h = h + params["embed"]["pos"].astype(dt)[pos][:, None, :]
    if cfg.position == "rope":
        cos, sin = _rope_tables(pos, hd, cfg.rope_theta, dt)    # (B, hd/2)
        cos, sin = cos[:, None, None], sin[:, None, None]       # (B,1,1,·)

    def scatter_row(buf, val, p):
        # (H, L, hd) ← (H, 1, hd) at key-position p; vmapped over rows
        return jax.lax.dynamic_update_slice(buf, val, (0, p, 0))

    row_scatter = jax.vmap(scatter_row)
    # decode_step's shared-pos path passes active=None: skip the masking
    # entirely so the delegation costs nothing
    keep = None if active is None else active[:, None, None, None]
    key_mask = (jnp.arange(L)[None] <= pos[:, None])[:, None, None]  # B,1,1,L
    new_cache = []
    for lp, c in zip(params["layers"], cache):
        x = _norm(h.astype(jnp.float32), lp["ln1"], cfg).astype(dt)
        qkv = x @ lp["qkv"]["w"].astype(dt) + lp["qkv"]["b"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads1(t):
            return t.reshape(B, 1, cfg.heads, hd).transpose(0, 2, 1, 3)

        q, k, v = heads1(q), heads1(k), heads1(v)
        if cfg.position == "rope":
            q = _rot_half(q, cos, sin)
            k = _rot_half(k, cos, sin)
        kc = row_scatter(c["k"], k.astype(dt), pos)
        vc = row_scatter(c["v"], v.astype(dt), pos)
        if keep is not None:
            kc = jnp.where(keep, kc, c["k"])
            vc = jnp.where(keep, vc, c["v"])
        new_cache.append({"k": kc, "v": vc})
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kc,
                       preferred_element_type=jnp.float32) / np.sqrt(hd)
        s = jnp.where(key_mask, s, jnp.float32(-1e30))
        p = jax.nn.softmax(s, axis=-1).astype(dt)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", p, vc,
                         preferred_element_type=dt)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, 1, cfg.d_model)
        h = h + ctx @ lp["out"]["w"].astype(dt) + lp["out"]["b"].astype(dt)
        x = _norm(h.astype(jnp.float32), lp["ln2"], cfg).astype(dt)
        y = jax.nn.gelu(x @ lp["w1"]["w"].astype(dt) + lp["w1"]["b"].astype(dt))
        y = y @ lp["w2"]["w"].astype(dt) + lp["w2"]["b"].astype(dt)
        h = h + y
    hidden = _norm(h.astype(jnp.float32), params["final_ln"], cfg).astype(dt)
    logits = hidden[:, 0].astype(jnp.float32) @ params["lm_head"]["w"]
    return logits, new_cache


def prefill_cache(params: Dict, ids: jnp.ndarray, length,
                  cfg: TransformerConfig, max_len: int):
    """Batched prompt prefill for continuous batching: ONE causal forward
    over the (padded) prompt, capturing every layer's K/V into ``max_len``
    cache buffers, plus the logits at the last real token.

    ``ids`` (B, P) right-padded prompts, ``length`` (B,) real lengths
    (1 ≤ length ≤ P) → (logits (B, vocab), cache list of (B, H, max_len,
    hd) k/v). O(P) attention per token instead of :func:`generate_cached`'s
    token-by-token prefill — the standard serving split (prefill batched,
    decode incremental).
    """
    if cfg.moe_experts:
        raise ValueError("cached decoding does not support MoE layers")
    dt = cfg.dtype
    B, P = ids.shape
    if P > max_len:
        raise ValueError(f"prompt {P} exceeds cache max_len {max_len}")
    hd = cfg.d_model // cfg.heads
    length = length.astype(jnp.int32)
    valid = jnp.arange(P)[None] < length[:, None]               # (B, P)
    h = params["embed"]["tok"].astype(dt)[ids]
    if cfg.position == "learned":
        h = h + params["embed"]["pos"].astype(dt)[:P][None]
    tri = jnp.tril(jnp.ones((P, P), bool))
    # causal AND key-valid: padded key columns never attend anywhere
    attn_ok = tri[None, None] & valid[:, None, None, :]
    cache = []
    for lp in params["layers"]:
        x = _norm(h.astype(jnp.float32), lp["ln1"], cfg).astype(dt)
        qkv = x @ lp["qkv"]["w"].astype(dt) + lp["qkv"]["b"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, P, cfg.heads, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if cfg.position == "rope":
            q, k = _rope(q, k, cfg.rope_theta)
        kc = jnp.pad(k.astype(dt), ((0, 0), (0, 0), (0, max_len - P), (0, 0)))
        vc = jnp.pad(v.astype(dt), ((0, 0), (0, 0), (0, max_len - P), (0, 0)))
        cache.append({"k": kc, "v": vc})
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32) / np.sqrt(hd)
        s = jnp.where(attn_ok, s, jnp.float32(-1e30))
        p = jax.nn.softmax(s, axis=-1).astype(dt)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", p, v,
                         preferred_element_type=dt)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, P, cfg.d_model)
        h = h + ctx @ lp["out"]["w"].astype(dt) + lp["out"]["b"].astype(dt)
        x = _norm(h.astype(jnp.float32), lp["ln2"], cfg).astype(dt)
        y = jax.nn.gelu(x @ lp["w1"]["w"].astype(dt) + lp["w1"]["b"].astype(dt))
        y = y @ lp["w2"]["w"].astype(dt) + lp["w2"]["b"].astype(dt)
        h = h + y
    hidden = _norm(h.astype(jnp.float32), params["final_ln"], cfg).astype(dt)
    last = jnp.take_along_axis(hidden, (length - 1)[:, None, None], axis=1)
    logits = last[:, 0].astype(jnp.float32) @ params["lm_head"]["w"]
    return logits, cache


def decode_window(params: Dict, tokens: jnp.ndarray, pos, cache,
                  cfg: TransformerConfig):
    """Cached forward over a WINDOW of W tokens at positions
    ``pos..pos+W-1``: the chunk-sized middle ground between
    :func:`decode_step` (W=1) and :func:`prefill_cache` (fresh cache).

    ``tokens`` (B, W) int, ``pos`` scalar start (traced ok) →
    (logits (B, W, vocab), cache with the window's K/V written). Queries
    attend causally within the window and to everything cached before it —
    the verify primitive of speculative decoding, and a chunked-prefill
    building block.

    Delegates to :func:`decode_window_ragged` with a uniform position
    vector — one layer-loop implementation keeps the scalar and per-row
    paths bit-identical (the decode_step / decode_step_ragged pattern;
    the speculative-verify parity invariant depends on it).
    """
    B = tokens.shape[0]
    pos = jnp.full((B,), pos, jnp.int32)
    return decode_window_ragged(params, tokens, pos, cache, cfg)


def decode_window_ragged(params: Dict, tokens: jnp.ndarray,
                         pos: jnp.ndarray, cache, cfg: TransformerConfig,
                         active: Optional[jnp.ndarray] = None):
    """:func:`decode_window` with PER-ROW start positions — the verify
    primitive for speculative decoding inside the continuous-batching slot
    pool (``serving/continuous.py``): every slot scores its own gamma+1
    proposal window at its own depth in ONE compiled forward.

    ``tokens`` (B, W) int, ``pos`` (B,) int32 per-row window starts,
    ``active`` (B,) bool (inactive rows keep their cache untouched,
    logits are don't-care) → (logits (B, W, vocab), updated cache).
    Row b's query at window index j sits at absolute position
    ``pos[b] + j``, attends cached keys ``<= pos[b] + j``, and the
    window's K/V land at ``pos[b]..pos[b]+W-1`` in that row's cache —
    exactly :func:`decode_window` per row with a scalar start.
    """
    if cfg.moe_experts:
        raise ValueError("cached decoding does not support MoE layers")
    dt = cfg.dtype
    B, W = tokens.shape
    L = cache[0]["k"].shape[2]
    hd = cfg.d_model // cfg.heads
    pos = pos.astype(jnp.int32)
    wpos = pos[:, None] + jnp.arange(W, dtype=jnp.int32)       # (B, W)
    h = params["embed"]["tok"].astype(dt)[tokens]              # (B, W, D)
    if cfg.position == "learned":
        h = h + params["embed"]["pos"].astype(dt)[wpos]
    if cfg.position == "rope":
        cos, sin = _rope_tables(wpos, hd, cfg.rope_theta, dt)  # (B, W, h/2)
        cos, sin = cos[:, None], sin[:, None]                  # (B,1,W,·)
    # row b, query j sees cached keys at positions <= pos[b] + j
    key_ok = (jnp.arange(L)[None, None, :]
              <= wpos[:, :, None])[:, None]                    # (B,1,W,L)
    keep = None if active is None else active[:, None, None, None]

    def scatter_row(buf, val, p):
        # (H, L, hd) ← (H, W, hd) at key-position p; vmapped over rows
        return jax.lax.dynamic_update_slice(buf, val, (0, p, 0))

    row_scatter = jax.vmap(scatter_row)
    new_cache = []
    for lp, c in zip(params["layers"], cache):
        x = _norm(h.astype(jnp.float32), lp["ln1"], cfg).astype(dt)
        qkv = x @ lp["qkv"]["w"].astype(dt) + lp["qkv"]["b"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, W, cfg.heads, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if cfg.position == "rope":
            q = _rot_half(q, cos, sin)
            k = _rot_half(k, cos, sin)
        kc = row_scatter(c["k"], k.astype(dt), pos)
        vc = row_scatter(c["v"], v.astype(dt), pos)
        if keep is not None:
            kc = jnp.where(keep, kc, c["k"])
            vc = jnp.where(keep, vc, c["v"])
        new_cache.append({"k": kc, "v": vc})
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kc,
                       preferred_element_type=jnp.float32) / np.sqrt(hd)
        s = jnp.where(key_ok, s, jnp.float32(-1e30))
        p = jax.nn.softmax(s, axis=-1).astype(dt)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", p, vc,
                         preferred_element_type=dt)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, W, cfg.d_model)
        h = h + ctx @ lp["out"]["w"].astype(dt) + lp["out"]["b"].astype(dt)
        x = _norm(h.astype(jnp.float32), lp["ln2"], cfg).astype(dt)
        y = jax.nn.gelu(x @ lp["w1"]["w"].astype(dt) + lp["w1"]["b"].astype(dt))
        y = y @ lp["w2"]["w"].astype(dt) + lp["w2"]["b"].astype(dt)
        h = h + y
    hidden = _norm(h.astype(jnp.float32), params["final_ln"], cfg).astype(dt)
    logits = hidden.astype(jnp.float32) @ params["lm_head"]["w"]
    return logits, new_cache


# ---- paged KV cache (vLLM-style PagedAttention, XLA-level) -----------------
# The physical cache is a pool of fixed-size PAGES — per layer a
# (num_pages, H, page_size, hd) buffer pair — and each batch row owns a
# BLOCK TABLE row mapping its logical pages to physical ones. A decode/
# window step gathers the row's pages into the familiar contiguous
# (B, H, L, hd) layout, runs the EXACT ragged-step math on it (reusing
# decode_step_ragged / decode_window_ragged — the paged path is bitwise
# equal to the contiguous path by construction: post-mask scores are
# identical and masked lanes contribute exactly 0 to the f32 softmax),
# and scatters only the freshly-written positions back into their pages.
# Physical page 0 is reserved as the TRASH page: block-table entries for
# unallocated logical pages point at it, and inactive rows' writebacks
# are redirected there, so a retired slot can never corrupt pages that
# were freed and handed to another request.
#
# Gathering costs one O(B·L) copy per step — the price of page-granular
# allocation and cross-request prefix sharing (serving/kv_pool.py). The
# fused Pallas paged-attention kernel (ops/paged_attention.py) reads
# pages in place and eliminates that copy; under a mesh it mounts via
# shard_map with heads split over tp and slots over dp, so the gather
# path below survives only as the parity oracle and env-knob escape
# hatch.

def init_paged_cache(cfg: TransformerConfig, num_pages: int,
                     page_size: int, kv_dtype=None):
    """Per-layer (num_pages, H, page_size, hd) k/v page pools (page 0 is
    the trash page — allocators must never hand it out). With
    ``kv_dtype`` ("int8"/"fp8") pages store quantized values and each
    layer dict gains ``(num_pages, H, page_size)`` ``k_scale``/
    ``v_scale`` arrays (see ``ops/kv_quant.py``)."""
    from ...ops.kv_quant import SCALE_DTYPE, kv_store_dtype
    hd = cfg.d_model // cfg.heads
    shape = (num_pages, cfg.heads, page_size, hd)
    store = kv_store_dtype(kv_dtype)
    if store is None:
        return [{"k": jnp.zeros(shape, cfg.dtype),
                 "v": jnp.zeros(shape, cfg.dtype)}
                for _ in range(cfg.layers)]
    sshape = shape[:3]
    return [{"k": jnp.zeros(shape, store),
             "v": jnp.zeros(shape, store),
             "k_scale": jnp.ones(sshape, SCALE_DTYPE),
             "v_scale": jnp.ones(sshape, SCALE_DTYPE)}
            for _ in range(cfg.layers)]


def _is_quant_cache(c) -> bool:
    """A quantized page-pool layer dict carries its scale arrays."""
    return "k_scale" in c


def paged_gather(cache_pages, block_tables, length: int, out_dtype=None):
    """Assemble each row's pages into contiguous (B, H, length, hd) k/v.

    ``block_tables`` (B, P) int32 physical page ids per logical page;
    ``length`` trims the last page's tail so the result has EXACTLY the
    contiguous cache's key length — attention reductions then run over
    the same number of lanes, which is what keeps the paged step bitwise
    equal to the contiguous one. Quantized pools dequantize through
    their gathered scales (in ``out_dtype``, default f32) — this is the
    oracle path the quant-error gauge measures the kernel against."""
    from ...ops.kv_quant import dequantize_kv
    out = []
    for c in cache_pages:
        quant = _is_quant_cache(c)
        row = {}
        for kk in ("k", "v"):
            g = c[kk][block_tables]              # (B, P, H, page, hd)
            B, Pp, H, pg, hd = g.shape
            if quant:
                s = c[kk + "_scale"][block_tables]   # (B, P, H, page)
                g = dequantize_kv(g, s, out_dtype or jnp.float32)
            elif out_dtype is not None:
                g = g.astype(out_dtype)
            g = g.transpose(0, 2, 1, 3, 4).reshape(B, H, Pp * pg, hd)
            row[kk] = g[:, :, :length]
        out.append(row)
    return out


def paged_scatter_rows(cache_pages, rows, block_tables, page_size: int):
    """Write full contiguous (B, H, L, hd) k/v rows (a prefill output)
    into the pool through each row's block table. Logical pages past a
    row's allocation must map to the trash page in ``block_tables`` —
    their writes collide harmlessly there. Quantized pools quantize each
    position through the sanctioned ``quantize_kv`` and scatter the
    per-head scales alongside."""
    from ...ops.kv_quant import quantize_kv
    n_pages = (rows[0]["k"].shape[2] + page_size - 1) // page_size
    dest = block_tables[:, :n_pages].reshape(-1)         # (B*n_pages,)
    out = []
    for c, rc in zip(cache_pages, rows):
        quant = _is_quant_cache(c)
        row = {}
        for kk in ("k", "v"):
            r = rc[kk]                                   # (B, H, L, hd)
            B, H, L, hd = r.shape
            r = jnp.pad(r, ((0, 0), (0, 0),
                            (0, n_pages * page_size - L), (0, 0)))
            r = r.reshape(B, H, n_pages, page_size, hd)
            r = r.transpose(0, 2, 1, 3, 4).reshape(
                B * n_pages, H, page_size, hd)
            if quant:
                q, sc = quantize_kv(r, c[kk].dtype)
                row[kk] = c[kk].at[dest].set(q)
                row[kk + "_scale"] = c[kk + "_scale"].at[dest].set(
                    sc.astype(c[kk + "_scale"].dtype))
            else:
                row[kk] = c[kk].at[dest].set(r)
        out.append(row)
    return out


def _paged_writeback(cache_pages, new_cache, block_tables, wpos,
                     page_size: int, active):
    """Scatter the freshly-written positions ``wpos`` (B, W) of an updated
    gathered cache back into the physical pages. Inactive rows (and only
    they) are redirected to trash page 0 — their "new" values are the old
    ones decode_step_ragged preserved, but their block-table rows may
    reference pages that were freed and reallocated to another request.
    Quantized pools write ``quantize_kv``'d bytes plus scales — the same
    helper every other writer uses, so the bytes agree bit-for-bit."""
    from ...ops.kv_quant import quantize_kv
    B, W = wpos.shape
    phys = jnp.take_along_axis(block_tables, wpos // page_size, axis=1)
    if active is not None:
        phys = jnp.where(active[:, None], phys, 0)
    pf = phys.reshape(-1)
    of = (wpos % page_size).reshape(-1)
    out = []
    for c, nc in zip(cache_pages, new_cache):
        quant = _is_quant_cache(c)
        row = {}
        for kk in ("k", "v"):
            vals = jnp.take_along_axis(
                nc[kk], wpos[:, None, :, None], axis=2)  # (B, H, W, hd)
            H, hd = vals.shape[1], vals.shape[3]
            vals = vals.transpose(0, 2, 1, 3).reshape(B * W, H, hd)
            if quant:
                q, sc = quantize_kv(vals, c[kk].dtype)
                row[kk] = c[kk].at[pf, :, of].set(q)
                row[kk + "_scale"] = c[kk + "_scale"].at[pf, :, of].set(
                    sc.astype(c[kk + "_scale"].dtype))
            else:
                row[kk] = c[kk].at[pf, :, of].set(vals)
        out.append(row)
    return out


def _decode_window_paged_kernel(params: Dict, tokens: jnp.ndarray,
                                pos: jnp.ndarray, cache_pages,
                                block_tables, cfg: TransformerConfig,
                                page_size: int,
                                active: Optional[jnp.ndarray],
                                mesh=None, slot_axis=None, head_axis=None):
    """The Pallas paged-attention layer loop: identical embedding / rope /
    projection / FFN math to :func:`decode_window_ragged`, but attention
    reads K/V pages IN PLACE through the block table and scatters the
    window's fresh rows in the same launch
    (:func:`~mmlspark_tpu.ops.paged_attention.paged_attention_window`) —
    no contiguous gather, no separate writeback. Page contents written
    are bit-identical to ``_paged_writeback``'s; the context differs from
    the gather path only by f32 online-softmax accumulation order."""
    from ...ops.paged_attention import paged_attention_window
    dt = cfg.dtype
    B, W = tokens.shape
    hd = cfg.d_model // cfg.heads
    pos = pos.astype(jnp.int32)
    wpos = pos[:, None] + jnp.arange(W, dtype=jnp.int32)       # (B, W)
    h = params["embed"]["tok"].astype(dt)[tokens]              # (B, W, D)
    if cfg.position == "learned":
        h = h + params["embed"]["pos"].astype(dt)[wpos]
    if cfg.position == "rope":
        cos, sin = _rope_tables(wpos, hd, cfg.rope_theta, dt)  # (B, W, h/2)
        cos, sin = cos[:, None], sin[:, None]                  # (B,1,W,·)
    new_pages = []
    for lp, c in zip(params["layers"], cache_pages):
        x = _norm(h.astype(jnp.float32), lp["ln1"], cfg).astype(dt)
        qkv = x @ lp["qkv"]["w"].astype(dt) + lp["qkv"]["b"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, W, cfg.heads, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if cfg.position == "rope":
            q = _rot_half(q, cos, sin)
            k = _rot_half(k, cos, sin)
        if _is_quant_cache(c):
            ctx, kp, vp, ks, vs = paged_attention_window(
                q, k.astype(dt), v.astype(dt), c["k"], c["v"],
                block_tables, pos, active=active,
                k_scale=c["k_scale"], v_scale=c["v_scale"], mesh=mesh,
                slot_axis=slot_axis, head_axis=head_axis)
            new_pages.append({"k": kp, "v": vp,
                              "k_scale": ks, "v_scale": vs})
        else:
            ctx, kp, vp = paged_attention_window(
                q, k.astype(dt), v.astype(dt), c["k"], c["v"],
                block_tables, pos, active=active, mesh=mesh,
                slot_axis=slot_axis, head_axis=head_axis)
            new_pages.append({"k": kp, "v": vp})
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, W, cfg.d_model)
        h = h + ctx @ lp["out"]["w"].astype(dt) + lp["out"]["b"].astype(dt)
        x = _norm(h.astype(jnp.float32), lp["ln2"], cfg).astype(dt)
        y = jax.nn.gelu(x @ lp["w1"]["w"].astype(dt) + lp["w1"]["b"].astype(dt))
        y = y @ lp["w2"]["w"].astype(dt) + lp["w2"]["b"].astype(dt)
        h = h + y
    hidden = _norm(h.astype(jnp.float32), params["final_ln"], cfg).astype(dt)
    logits = hidden.astype(jnp.float32) @ params["lm_head"]["w"]
    return logits, new_pages


def decode_step_paged(params: Dict, tokens: jnp.ndarray, pos: jnp.ndarray,
                      cache_pages, block_tables, cfg: TransformerConfig, *,
                      page_size: int, length: int,
                      active: Optional[jnp.ndarray] = None,
                      impl: Optional[str] = None,
                      mesh=None, slot_axis=None, head_axis=None):
    """One paged decode step. Two implementations, selected by ``impl``
    (``None`` → the ``MMLSPARK_TPU_PAGED_ATTN`` env knob, default
    ``"kernel"``):

    * ``"kernel"`` — the Pallas paged-attention kernel attends directly
      over the page pool through the block table and scatters the fresh
      K/V row in the same launch. Page writes are bit-identical to the
      gather path; logits agree to f32 accumulation-order tolerance.
    * ``"gather"`` — PR 7's path: gather through the block table, run the
      IDENTICAL ragged-step math, scatter the one new K/V position per
      row back to its page. Logits are bitwise equal to the contiguous
      path on the same cache contents (masked garbage lanes contribute
      exactly 0). ``length`` is the logical cache length (the contiguous
      L); every ``pos`` must be < length."""
    from ...ops.paged_attention import resolve_impl
    if resolve_impl(impl) == "kernel":
        logits, pages = _decode_window_paged_kernel(
            params, tokens[:, None], pos.astype(jnp.int32), cache_pages,
            block_tables, cfg, page_size, active, mesh=mesh,
            slot_axis=slot_axis, head_axis=head_axis)
        return logits[:, 0], pages
    gathered = paged_gather(cache_pages, block_tables, length,
                            out_dtype=cfg.dtype)
    logits, new = decode_step_ragged(params, tokens, pos.astype(jnp.int32),
                                     gathered, cfg, active)
    pages = _paged_writeback(cache_pages, new, block_tables,
                             pos.astype(jnp.int32)[:, None], page_size,
                             active)
    return logits, pages


def decode_window_paged(params: Dict, tokens: jnp.ndarray,
                        pos: jnp.ndarray, cache_pages, block_tables,
                        cfg: TransformerConfig, *, page_size: int,
                        length: int,
                        active: Optional[jnp.ndarray] = None,
                        impl: Optional[str] = None,
                        mesh=None, slot_axis=None, head_axis=None):
    """Paged window decode — the speculative verify and chunked-prefill
    primitive. Row b's window writes positions ``pos[b]..pos[b]+W-1``
    into its pages; every such position must be < ``length`` (the engine
    sizes allocations so windows never clamp). ``impl`` selects the
    Pallas kernel (default) or PR 7's gather path exactly as in
    :func:`decode_step_paged`."""
    from ...ops.paged_attention import resolve_impl
    W = tokens.shape[1]
    pos = pos.astype(jnp.int32)
    if resolve_impl(impl) == "kernel":
        return _decode_window_paged_kernel(params, tokens, pos,
                                           cache_pages, block_tables,
                                           cfg, page_size, active,
                                           mesh=mesh, slot_axis=slot_axis,
                                           head_axis=head_axis)
    wpos = pos[:, None] + jnp.arange(W, dtype=jnp.int32)
    gathered = paged_gather(cache_pages, block_tables, length,
                            out_dtype=cfg.dtype)
    logits, new = decode_window_ragged(params, tokens, pos, gathered,
                                       cfg, active)
    pages = _paged_writeback(cache_pages, new, block_tables, wpos,
                             page_size, active)
    return logits, pages


def generate_cached(params: Dict, prompt_ids, cfg: TransformerConfig,
                    max_new_tokens: int = 32, temperature: float = 0.0,
                    seed: int = 0, top_k: int = 0, top_p: float = 1.0,
                    eos_id: Optional[int] = None):
    """KV-cached :func:`generate`: O(L) attention per emitted token.

    The prompt prefills the cache token-by-token through the same
    ``decode_step`` (a zoo model: simplicity over a batched prefill).
    ``eos_id`` repeats after firing, token-compatible with
    :func:`generate` (the key schedule is consumed identically)."""
    if not cfg.causal:
        raise ValueError("generate_cached() needs cfg.causal=True")
    params = jax.tree.map(jnp.asarray, params)
    prompt_ids = jnp.asarray(prompt_ids)
    B, P_len = prompt_ids.shape
    if P_len < 1:
        raise ValueError("generate_cached() needs at least one prompt token")
    L = P_len + max_new_tokens
    if L > cfg.max_len and cfg.position == "learned":
        raise ValueError(f"prompt+new = {L} exceeds max_len {cfg.max_len}")
    key0 = jax.random.PRNGKey(seed)
    # module-level cached jit: a per-call closure would RETRACE (and,
    # behind a tunneled chip, remote-RECOMPILE) the whole scan on every
    # generation — seconds per call that r4/r5 benches mistook for decode
    # cost
    return _generate_cached_impl(params, prompt_ids, key0, cfg=cfg,
                                 max_new_tokens=int(max_new_tokens),
                                 temperature=float(temperature),
                                 top_k=int(top_k), top_p=float(top_p),
                                 eos_id=eos_id)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_tokens", "temperature",
                                    "top_k", "top_p", "eos_id"))
def _generate_cached_impl(params, prompt_ids, key0, cfg, max_new_tokens,
                          temperature, top_k, top_p, eos_id):
    B, P_len = prompt_ids.shape
    L = P_len + max_new_tokens
    cache = init_kv_cache(cfg, B, L)
    ids0 = jnp.pad(prompt_ids, ((0, 0), (0, max_new_tokens)))

    def step(carry, t):
        ids, cache, done = carry
        token = jax.lax.dynamic_slice_in_dim(ids, t, 1, axis=1)[:, 0]
        logits, cache = decode_step(params, token, t, cache, cfg)
        # keyed by EMIT position (t+1), matching generate() exactly —
        # prefill steps consume no randomness
        nxt = _sample_logits(logits.astype(jnp.float32),
                             jax.random.fold_in(key0, t + 1),
                             temperature, top_k, top_p)
        # scan covers t = 0..L-2, so t+1 is always a valid position; only
        # emit past the prompt (prompt positions keep their tokens)
        keep = t + 1 >= P_len
        if eos_id is not None:
            # post-sampling override keeps the key schedule identical to
            # the no-eos run (and to generate())
            nxt = jnp.where(done & keep, jnp.full_like(nxt, eos_id), nxt)
            done = done | (keep & (nxt == eos_id))
        cur = jax.lax.dynamic_slice_in_dim(ids, t + 1, 1, axis=1)[:, 0]
        upd = jnp.where(keep, nxt.astype(ids.dtype), cur)
        ids = jax.lax.dynamic_update_slice(ids, upd[:, None], (0, t + 1))
        return (ids, cache, done), None

    (ids, _, _), _ = jax.lax.scan(step, (ids0, cache, jnp.zeros(B, bool)),
                                  jnp.arange(L - 1))
    # the final position's token comes from the last step's write; the scan
    # covers t = 0..L-2, emitting into positions P_len..L-1
    return ids


def generate_beam(params: Dict, prompt_ids, cfg: TransformerConfig,
                  max_new_tokens: int = 32, num_beams: int = 4,
                  length_penalty: float = 1.0,
                  eos_id: Optional[int] = None):
    """Beam search over the cached decoder — one jitted program.

    Standard HF-convention semantics with fully static shapes: the
    prompt prefills once (:func:`prefill_cache`), beams fold into the
    batch axis (B·W cache rows), and every step is (1) one ragged-free
    ``decode_step``, (2) a (B, W·V) top-2W candidate scan — 2W because at
    most W of them can be eos-extensions, so W live beams always survive
    (the HF rationale) — and (3) a per-layer cache row gather to reorder
    beams. Finished hypotheses bank into a static (B, W) pool scored by
    ``sum_logprob / len**length_penalty``; the final answer is the best
    of banked + still-live beams. With ``num_beams=1`` and no eos this
    reduces exactly to greedy :func:`generate_cached`.

    Returns ``(ids (B, P+max_new), scores (B,))`` — the best hypothesis
    per batch row, prompt included, padded with ``eos_id`` (or the last
    token) past each hypothesis' end.
    """
    if not cfg.causal:
        raise ValueError("generate_beam() needs cfg.causal=True")
    if num_beams < 1:
        raise ValueError("num_beams must be >= 1")
    if num_beams > cfg.vocab:
        raise ValueError(f"num_beams {num_beams} exceeds vocab {cfg.vocab} "
                         "(only vocab distinct first tokens exist)")
    params = jax.tree.map(jnp.asarray, params)
    prompt_ids = jnp.asarray(prompt_ids)
    B, P_len = prompt_ids.shape
    if P_len < 1:
        raise ValueError("generate_beam() needs at least one prompt token")
    W, V, M = int(num_beams), cfg.vocab, int(max_new_tokens)
    L = P_len + M
    if L > cfg.max_len and cfg.position == "learned":
        raise ValueError(f"prompt+new = {L} exceeds max_len {cfg.max_len}")

    def penalize(score, length):
        return score / (length.astype(jnp.float32) ** jnp.float32(
            length_penalty))

    # prefill once per batch row, then replicate every cache row W times
    logits0, cache = prefill_cache(
        params, prompt_ids, jnp.full((B,), P_len, jnp.int32), cfg, L)
    cache = [{k: jnp.repeat(c[k], W, axis=0) for k in ("k", "v")}
             for c in cache]
    logp0 = jax.nn.log_softmax(logits0.astype(jnp.float32), axis=-1)
    batch_ix = jnp.arange(B)[:, None]                       # (B, 1)
    # first step follows the same top-2W discipline as the loop: an eos
    # among the top-W banks AND its live slot refills from the next-best
    # non-eos token (taking only top-W here would let a first-step eos
    # permanently narrow the beam). k0 caps at V; when W == V and eos
    # ranks, one live slot legitimately dies (-inf) — V-1 non-eos first
    # tokens exist.
    k0 = min(2 * W, V)
    c_scores, c_tok = jax.lax.top_k(logp0, k0)              # (B, k0)
    c_seqs = jnp.zeros((B, k0, M), jnp.int32).at[:, :, 0].set(c_tok)
    fin_scores = jnp.full((B, W), -jnp.inf)
    fin_seqs = jnp.zeros((B, W, M), jnp.int32)
    if eos_id is not None:
        c_eos = c_tok == eos_id
        bank = jnp.where(c_eos, penalize(c_scores, jnp.int32(1)), -jnp.inf)
        fin_scores, keep = jax.lax.top_k(bank, W)           # W <= k0 always
        fin_seqs = c_seqs[batch_ix, keep]
        live_key0 = jnp.where(c_eos, -jnp.inf, c_scores)
    else:
        live_key0 = c_scores
    scores, pick0 = jax.lax.top_k(live_key0, W)             # W <= k0
    tok0 = c_tok[batch_ix, pick0]
    seqs = c_seqs[batch_ix, pick0]
    tok = tok0.reshape(B * W)

    def step(carry, t):
        seqs, scores, fin_scores, fin_seqs, tok, cache = carry
        logits, cache = decode_step(params, tok, P_len + t - 1, cache, cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        cand = scores[:, :, None] + logp.reshape(B, W, V)   # (B, W, V)
        c_scores, c_idx = jax.lax.top_k(cand.reshape(B, W * V), 2 * W)
        c_parent = c_idx // V                               # (B, 2W)
        c_tok = (c_idx % V).astype(jnp.int32)
        c_seqs = seqs[batch_ix, c_parent]                   # (B, 2W, M)
        c_seqs = jnp.where(jnp.arange(M)[None, None] == t,
                           c_tok[:, :, None], c_seqs)
        if eos_id is not None:
            c_eos = c_tok == eos_id
            # bank eos candidates (penalized), keep the best W of old+new
            pool_s = jnp.concatenate(
                [fin_scores,
                 jnp.where(c_eos, penalize(c_scores, t + 1), -jnp.inf)],
                axis=1)                                     # (B, 3W)
            pool_q = jnp.concatenate([fin_seqs, c_seqs], axis=1)
            fin_scores, keep = jax.lax.top_k(pool_s, W)
            fin_seqs = pool_q[batch_ix, keep]
            live_key = jnp.where(c_eos, -jnp.inf, c_scores)
        else:
            live_key = c_scores
        # top-W live (non-eos) continuations — ≥ W exist among the 2W
        scores, pick = jax.lax.top_k(live_key, W)
        parent = c_parent[batch_ix, pick]                   # (B, W)
        seqs = c_seqs[batch_ix, pick]
        tok = c_tok[batch_ix, pick].reshape(B * W)
        # reorder the cache rows onto the surviving beams
        rows = (jnp.arange(B)[:, None] * W + parent).reshape(B * W)
        cache = [{k: c[k][rows] for k in ("k", "v")} for c in cache]
        return (seqs, scores, fin_scores, fin_seqs, tok, cache), None

    if M > 1:
        (seqs, scores, fin_scores, fin_seqs, tok, cache), _ = jax.lax.scan(
            step, (seqs, scores, fin_scores, fin_seqs, tok, cache),
            jnp.arange(1, M))

    # final pool: banked hypotheses + live beams at full length
    all_s = jnp.concatenate(
        [fin_scores, penalize(scores, jnp.int32(M))], axis=1)  # (B, 2W)
    all_q = jnp.concatenate([fin_seqs, seqs], axis=1)
    best = jnp.argmax(all_s, axis=1)
    best_seq = all_q[jnp.arange(B), best]                   # (B, M)
    best_score = all_s[jnp.arange(B), best]
    if eos_id is not None:
        # pad past each hypothesis' eos with eos (generate()'s convention)
        hit = jnp.cumsum(
            (best_seq == eos_id).astype(jnp.int32), axis=1) > 0
        after = jnp.pad(hit, ((0, 0), (1, 0)))[:, :-1]      # strictly after
        best_seq = jnp.where(after, eos_id, best_seq)
    ids = jnp.concatenate([prompt_ids.astype(jnp.int32), best_seq], axis=1)
    return ids, best_score
