# Hand-written stub (runner.py defines no PipelineStage, so codegen skips
# it); kept in sync by tpulint rule TPU006 (stub-drift).
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.ops.compile_cache import StageCounters

class StagingSlabPool:
    depth: int
    allocs: int
    reuses: int
    def __init__(self, depth: int = ...) -> None: ...
    def acquire(self, shape: Any, dtype: Any) -> np.ndarray: ...
    def release(self, arr: Any) -> bool: ...
    def stats(self) -> Dict[str, float]: ...

class BatchRunner:
    jitted: Any
    params: Any
    coerce: Callable[[slice], Dict[str, np.ndarray]]
    put: Callable[..., Any]
    shards: int
    mini_batch_size: int
    prefetch_depth: int
    counters: StageCounters
    staging: Optional[StagingSlabPool]
    buckets: Optional[Tuple[int, ...]]
    tuning: str
    model_sig: Optional[str]
    placement_key: str
    decision: Any
    def __init__(self, jitted: Any, params: Any,
                 coerce: Callable[[slice], Dict[str, np.ndarray]],
                 put: Callable[..., Any], shards: int = ...,
                 mini_batch_size: int = ..., prefetch_depth: int = ...,
                 counters: Optional[StageCounters] = ...,
                 staging: Optional[StagingSlabPool] = ...,
                 buckets: Optional[Tuple[int, ...]] = ...,
                 tuning: str = ..., model_sig: Optional[str] = ...,
                 placement_key: str = ...) -> None: ...
    def run(self, n_rows: int) -> List[Tuple[dict, int]]: ...
    def drain(self, pending: List[Tuple[dict, int]]
              ) -> List[Tuple[Dict[str, np.ndarray], int]]: ...
    def run_and_drain(self, n_rows: int
                      ) -> List[Tuple[Dict[str, np.ndarray], int]]: ...

def __getattr__(name: str) -> Any: ...
