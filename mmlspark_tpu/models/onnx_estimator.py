"""ONNXEstimator — fine-tune an ONNX graph as a pipeline Estimator.

Completes the DataFrame-level story for :mod:`mmlspark_tpu.onnx.train`:
``fit(df)`` runs optax steps over the imported graph's params and returns
a fitted :class:`ONNXModel` whose ``weights_override`` carries the tuned
weights (the original model bytes stay untouched, so the artifact remains
a standard ONNX file plus a weight delta).

The reference has no counterpart — its ONNX stage wraps a frozen ORT
session (``deep-learning/.../onnx/ONNXModel.scala:173-193``) and
fine-tuning means returning to the exporting framework. Two objectives:

* the graph carries its own loss output (e.g. a SoftmaxCrossEntropyLoss
  node): set ``loss_output`` and ``label_input``;
* or compute one here: ``objective='softmax_cross_entropy' | 'mse'`` over
  ``target_output`` against ``label_col``.
"""

from __future__ import annotations

import io
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Estimator
from .onnx_model import ONNXModel

__all__ = ["ONNXEstimator"]

_INFERENCE_KEYS = ["feed_dict", "fetch_dict", "mini_batch_size",
                   "softmax_dict", "argmax_dict", "compute_dtype",
                   "normalize_dict", "transpose_dict", "pin_devices",
                   "mesh_sharded", "external_data_dir"]


class ONNXEstimator(Estimator):
    model_bytes = ComplexParam(doc="serialized ONNX ModelProto")
    feed_dict = Param(dict, default={},
                      doc="{model input name: dataframe column}")
    fetch_dict = Param(dict, default={},
                       doc="{output column: model output name} for the "
                           "fitted model")
    label_col = Param(str, default="label", doc="label column")
    label_input = Param(str, default=None,
                        doc="graph input the labels feed (graph-carried "
                            "loss mode)")
    loss_output = Param(str, default=None,
                        doc="graph output that IS the scalar loss")
    objective = Param(str, default="softmax_cross_entropy",
                      choices=["softmax_cross_entropy", "mse"],
                      doc="loss computed here when the graph has none")
    target_output = Param(str, default=None,
                          doc="graph output the objective scores "
                              "(default: the graph's only output)")
    optimizer = Param(str, default="adam", choices=["adam", "sgd"],
                      doc="optax optimizer")
    learning_rate = Param(float, default=1e-3, doc="step size")
    epochs = Param(int, default=1, doc="passes over the frame")
    batch_size = Param(int, default=64, doc="rows per training step")
    shuffle = Param(bool, default=True, doc="reshuffle rows every epoch")
    seed = Param(int, default=0, doc="shuffle seed")
    validation_indicator_col = Param(str, default=None,
                                     doc="bool column marking validation "
                                         "rows (enables early stopping)")
    early_stopping_epochs = Param(int, default=0,
                                  doc="stop after this many epochs without "
                                      "validation-loss improvement (0 = "
                                      "off); the best epoch's params win")
    trainable_prefix = Param((list, str), default=[],
                             doc="train only params whose name starts "
                                 "with one of these (empty = all); the "
                                 "frozen-backbone cut-layer pattern")
    lora_rank = Param(int, default=0,
                      doc="LoRA: train rank-r adapters over the 2-D "
                          "weights instead of the weights themselves "
                          "(0 = full fine-tuning); merged deltas serve "
                          "through weights_override like any fine-tune")
    lora_alpha = Param(float, default=0.0,
                       doc="LoRA delta scale alpha (alpha/rank * a@b); "
                           "0 = rank (scale 1)")
    mini_batch_size = Param(int, default=64,
                            doc="fitted model's inference batch size")
    softmax_dict = Param(dict, default={}, doc="fitted model passthrough")
    argmax_dict = Param(dict, default={}, doc="fitted model passthrough")
    compute_dtype = Param(str, default="float32",
                          doc="fitted model passthrough")
    normalize_dict = Param(dict, default={}, doc="fitted model passthrough")
    transpose_dict = Param(dict, default={}, doc="fitted model passthrough")
    pin_devices = Param(bool, default=True, doc="fitted model passthrough")
    mesh_sharded = Param(bool, default=False, doc="fitted model passthrough")
    external_data_dir = Param(str, default="", doc="fitted model passthrough")

    def __init__(self, model_bytes: Optional[bytes] = None,
                 eval_log: Optional[list] = None, **kw):
        if isinstance(kw.get("trainable_prefix"), str):
            # a single prefix as a bare string is the natural spelling
            kw["trainable_prefix"] = [kw["trainable_prefix"]]
        super().__init__(**kw)
        if model_bytes is not None:
            self.set(model_bytes=model_bytes)
        #: live list per-step losses append to during fit (a plain
        #: attribute, not a Param — params are serialized values, and this
        #: is a mutable channel back to the caller)
        self._eval_log = eval_log

    # -- batching ------------------------------------------------------------
    def _column_feed(self, df: DataFrame, col: str) -> np.ndarray:
        c = df[col]
        if c.dtype == object:
            return np.stack([np.asarray(v) for v in c])
        return np.asarray(c)

    def _loss_fn(self, output_names):
        obj = self.get("objective")
        target = self.get_or_none("target_output")
        if target is None:
            if len(output_names) != 1:
                raise ValueError(
                    f"graph has outputs {list(output_names)}; pass "
                    "target_output to pick the one the objective scores")
            target = output_names[0]

        def loss_fn(outputs, feeds):
            out = outputs[target]
            y = feeds["__labels__"]
            if obj == "mse":
                # (N, 1) regression heads vs (N,) labels would broadcast
                # to an (N, N) outer-difference matrix — align first
                if out.shape != y.shape:
                    out = out.reshape(y.shape)
                return jnp.mean((out - y) ** 2)
            lp = jax.nn.log_softmax(out, axis=-1)
            return -jnp.take_along_axis(
                lp, y[..., None].astype(jnp.int32), axis=-1).mean()
        return loss_fn

    def _fit(self, df: DataFrame) -> ONNXModel:
        import optax
        from ..onnx.convert import convert_model
        from ..onnx.train import make_train_step

        cm = convert_model(self.get("model_bytes"),
                           external_data_dir=self.external_data_dir or None)
        feeds_cols: Dict[str, np.ndarray] = {
            inp: self._column_feed(df, col)
            for inp, col in self.feed_dict.items()}
        vcol = self.get_or_none("validation_indicator_col")
        val_feeds = None
        y_val = None
        if vcol and vcol not in df:
            # silent fallthrough would TRAIN on the intended holdout rows
            raise ValueError(f"validation_indicator_col {vcol!r} not in "
                             f"the frame (columns: {list(df.columns)})")
        if vcol:
            mask = np.asarray(df[vcol], dtype=bool)
            val_feeds = {k: v[mask] for k, v in feeds_cols.items()}
            y_val = np.asarray(df[self.label_col])[mask]
            feeds_cols = {k: v[~mask] for k, v in feeds_cols.items()}
            df = df.filter(~mask)
        y = np.asarray(df[self.label_col])
        n = len(df)
        patience = int(self.early_stopping_epochs)
        if patience and val_feeds is None:
            raise ValueError("early_stopping_epochs needs "
                             "validation_indicator_col rows")
        if n < int(self.batch_size):
            raise ValueError(
                f"fewer rows ({n}) than batch_size ({self.batch_size}); "
                "no training step would run")

        loss_output = self.get_or_none("loss_output")
        label_input = self.get_or_none("label_input")
        if loss_output is not None:
            if label_input is None:
                raise ValueError("loss_output mode needs label_input (the "
                                 "graph input the labels feed)")
            loss_fn = None
        else:
            loss_fn = self._loss_fn(cm.output_names)

        opt = (optax.adam if self.optimizer == "adam" else optax.sgd)(
            float(self.learning_rate))
        prefixes = list(self.trainable_prefix)
        trainable = (None if not prefixes else
                     (lambda name: any(name.startswith(p)
                                       for p in prefixes)))
        lora_rank = int(self.lora_rank)
        lora_names = None
        if lora_rank > 0:
            # adapters train instead of the weights; trainable_prefix
            # narrows WHICH matrices get adapters
            from ..onnx.train import (init_lora, lora_merge,
                                      lora_targets, make_lora_train_step)
            lora_names = lora_targets(cm, lora_rank, trainable)
            state = init_lora(cm, lora_rank, targets=lora_names,
                              seed=int(self.seed))
            alpha = float(self.lora_alpha) or float(lora_rank)
            l_step, l_init = make_lora_train_step(
                cm, opt, alpha=alpha, loss_fn=loss_fn, output=loss_output)
            base = {k: jnp.asarray(v) for k, v in cm.params.items()}
            opt_state = l_init(state)
            # base travels as a jit ARGUMENT (a closure would bake every
            # frozen matrix into the executable as constants — doubling
            # base memory in exactly the large-model regime LoRA targets)
            merged = jax.jit(lambda b, lo: lora_merge(b, lo, alpha))

            def do_step(state, opt_state, feeds):
                return l_step(base, state, opt_state, feeds)

            def params_of(state):
                return merged(base, state)
        else:
            step, init = make_train_step(cm, opt, loss_fn=loss_fn,
                                         output=loss_output,
                                         trainable=trainable)
            state = {k: jnp.asarray(v) for k, v in cm.params.items()}
            opt_state = init(state)
            do_step = step

            def params_of(state):
                return state

        val_loss_fn = None
        if val_feeds is not None:
            # whole-validation loss in one jitted call per epoch; the
            # validation data travels as jit ARGUMENTS (a closure would
            # bake it into the compiled program as constants)
            @jax.jit
            def _val_loss(params, feeds):
                if loss_output is not None:
                    return cm(params, feeds)[loss_output]
                return loss_fn(cm(params, feeds), feeds)

            _vf = dict(val_feeds)
            _vf[label_input if loss_output is not None
                else "__labels__"] = y_val
            val_loss_fn = lambda params: _val_loss(params, _vf)  # noqa: E731

        bs = int(self.batch_size)
        rng = np.random.default_rng(int(self.seed))
        log = getattr(self, "_eval_log", None)
        best_val = np.inf
        best_params = None
        since_best = 0
        for ep in range(int(self.epochs)):
            # full batches only: each distinct batch shape is its own XLA
            # compile. Shuffled epochs fold the trailing remainder into the
            # next permutation; unshuffled epochs rotate the start offset so
            # no fixed tail of the frame is permanently excluded.
            if self.shuffle:
                order = rng.permutation(n)
            else:
                order = np.roll(np.arange(n), -(ep * bs) % max(n, 1))
            for lo in range(0, n - bs + 1, bs):
                sel = order[lo:lo + bs]
                feeds = {k: v[sel] for k, v in feeds_cols.items()}
                if loss_output is not None:
                    feeds[label_input] = y[sel]
                else:
                    feeds["__labels__"] = y[sel]
                state, opt_state, val = do_step(state, opt_state, feeds)
                if log is not None:
                    log.append(float(val))
            if val_feeds is not None:
                vl = float(val_loss_fn(params_of(state)))
                if log is not None:
                    log.append({"epoch": ep, "val_loss": vl})
                if vl < best_val - 1e-12:
                    best_val = vl
                    since_best = 0
                    if patience:
                        # LoRA snapshots are the tiny adapter tree
                        best_params = jax.tree.map(np.asarray, state)
                else:
                    since_best += 1
                    if patience and since_best >= patience:
                        break
        if best_params is not None:
            state = best_params
        params = params_of(state)
        if lora_names is not None:
            # the override only needs the adapted matrices; everything
            # else layers from the graph's own initializers
            params = {k: params[k] for k in lora_names}

        buf = io.BytesIO()
        np.savez(buf, **{k: np.asarray(v) for k, v in params.items()})
        inference = {k: self.get(k) for k in _INFERENCE_KEYS}
        if loss_output is not None and not inference["fetch_dict"]:
            # default fetch would include the loss output, whose labels
            # input is never fed at inference — serve the non-loss outputs
            inference["fetch_dict"] = {o: o for o in cm.output_names
                                       if o != loss_output}
        m = ONNXModel(self.get("model_bytes"), **inference)
        m.set(weights_override=buf.getvalue())
        return m
