"""Schema & metadata utilities.

Parity surface: ``core/schema`` in the reference — ``Categoricals`` (314 LoC),
``SparkSchema`` label/score metadata (225 LoC),
``DatasetExtensions.findUnusedColumnName``, and the ``SparkBindings`` struct
codecs (``core/schema/SparkBindings.scala:13-47``). Here column metadata is a
plain dict carried by the DataFrame; these helpers standardize the keys.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .dataframe import DataFrame

__all__ = [
    "py_scalar",
    "find_unused_column_name",
    "set_categorical_metadata",
    "get_categorical_levels",
    "is_categorical",
    "set_label_metadata",
    "get_label_metadata",
    "assemble_vector",
    "assemble_features",
    "struct_column",
    "unpack_struct_column",
]

CATEGORICAL_KEY = "ml_categorical"
LABEL_KEY = "ml_label"
SCORE_KEY = "ml_score"


def py_scalar(v):
    """numpy scalar → plain Python scalar (identity otherwise)."""
    return v.item() if isinstance(v, np.generic) else v


def find_unused_column_name(base: str, df: DataFrame) -> str:
    """Reference: ``DatasetExtensions.findUnusedColumnName``."""
    name = base
    i = 0
    while name in df:
        i += 1
        name = f"{base}_{i}"
    return name


# -- categorical metadata ----------------------------------------------------

def set_categorical_metadata(df: DataFrame, col: str, levels: Sequence) -> DataFrame:
    return df.with_column_metadata(col, {CATEGORICAL_KEY: {
        "levels": [l.item() if isinstance(l, np.generic) else l for l in levels]}})


def get_categorical_levels(df: DataFrame, col: str) -> Optional[List]:
    meta = df.column_metadata(col).get(CATEGORICAL_KEY)
    return None if meta is None else list(meta["levels"])


def is_categorical(df: DataFrame, col: str) -> bool:
    return CATEGORICAL_KEY in df.column_metadata(col)


# -- label/score metadata (reference: SparkSchema.scala) ---------------------

def set_label_metadata(df: DataFrame, col: str, num_classes: Optional[int] = None,
                       classes: Optional[Sequence] = None) -> DataFrame:
    meta: Dict = {}
    if num_classes is not None:
        meta["num_classes"] = int(num_classes)
    if classes is not None:
        meta["classes"] = [c.item() if isinstance(c, np.generic) else c for c in classes]
    return df.with_column_metadata(col, {LABEL_KEY: meta})


def get_label_metadata(df: DataFrame, col: str) -> dict:
    return df.column_metadata(col).get(LABEL_KEY, {})


# -- vector assembly (reference: FastVectorAssembler) ------------------------

def assemble_vector(df: DataFrame, input_cols: Sequence[str],
                    allow_none: bool = False) -> np.ndarray:
    """Stack numeric/vector columns into a dense 2-D float array (n, d).

    Object columns must be fixed-width vectors; with ``allow_none`` a None
    row becomes NaN (the width comes from the non-None rows — an all-None
    column is an error, never a silently-zero-width block)."""
    parts = []
    for c in input_cols:
        col = df[c]
        if col.dtype == object:
            if allow_none and any(v is None for v in col):
                first = next((v for v in col if v is not None), None)
                if first is None:
                    raise ValueError(
                        f"column {c!r} is entirely None; its vector width "
                        f"is undefined")
                width = int(np.asarray(first).size)
                block = np.full((len(col), width), np.nan)
                for i, v in enumerate(col):
                    if v is not None:
                        arr = np.asarray(v, dtype=np.float64).ravel()
                        if arr.size != width:
                            raise ValueError(
                                f"column {c!r} row {i}: width {arr.size} != "
                                f"{width} (vectors must be fixed-width)")
                        block[i] = arr
                parts.append(block)
                continue
            rows = [np.asarray(v, dtype=np.float64).ravel() for v in col]
            widths = {r.size for r in rows}
            if len(widths) > 1:
                raise ValueError(
                    f"column {c!r} has mixed widths {sorted(widths)} "
                    f"(vectors must be fixed-width)")
            if not rows:
                # a 0-row frame has no width evidence — a silent (0, 0)
                # block would change the assembled width between empty and
                # non-empty inputs
                raise ValueError(
                    f"column {c!r} is empty; its vector width is undefined "
                    f"(assemble a non-empty frame, or drop the column)")
            col = np.stack(rows)
        col = np.asarray(col, dtype=np.float64)
        if col.ndim == 1:
            col = col[:, None]
        elif col.ndim > 2:
            col = col.reshape(len(col), -1)
        parts.append(col)
    if not parts:
        return np.zeros((len(df), 0))
    return np.concatenate(parts, axis=1)


def assemble_features(df: DataFrame, input_cols: Sequence[str]):
    """``assemble_vector`` that preserves sparsity.

    When the single input column holds scipy sparse row vectors (1×F
    matrices — the stand-in for Spark ML's ``SparseVector`` rows consumed
    by the reference's dataset build, ``DatasetAggregator.scala:127-183``),
    returns one stacked CSR matrix instead of densifying. Every other
    shape defers to :func:`assemble_vector` (dense ``(n, d)`` float array).
    """
    try:
        import scipy.sparse as sp
    except Exception:               # pragma: no cover - scipy is in the image
        sp = None
    if sp is not None and len(input_cols) == 1:
        col = df[input_cols[0]]
        if col.dtype == object and len(col) \
                and any(sp.issparse(v) for v in col):
            rows = []
            for i, v in enumerate(col):
                if not sp.issparse(v):
                    raise ValueError(
                        f"column {input_cols[0]!r} mixes sparse and "
                        f"non-sparse rows (row {i}); a sparse features "
                        "column must be sparse throughout")
                rows.append(v.tocsr().reshape(1, -1))
            widths = {r.shape[1] for r in rows}
            if len(widths) > 1:
                raise ValueError(
                    f"column {input_cols[0]!r} has mixed widths "
                    f"{sorted(widths)} (vectors must be fixed-width)")
            # direct buffer concat — sp.vstack over n 1-row blocks costs
            # an order of magnitude more object churn at large n
            data = np.concatenate([r.data for r in rows]) if rows else \
                np.zeros(0, np.float64)
            indices = np.concatenate([r.indices for r in rows]) if rows \
                else np.zeros(0, np.int32)
            indptr = np.concatenate(
                [[0], np.cumsum([r.nnz for r in rows])])
            return sp.csr_matrix((data, indices, indptr),
                                 shape=(len(rows), widths.pop()))
    return assemble_vector(df, input_cols)


# -- struct columns (reference: SparkBindings row codecs) --------------------

def struct_column(dicts: Sequence[dict]) -> np.ndarray:
    arr = np.empty(len(dicts), dtype=object)
    for i, d in enumerate(dicts):
        arr[i] = d
    return arr


def unpack_struct_column(col: np.ndarray, field: str) -> np.ndarray:
    out = np.empty(len(col), dtype=object)
    for i, v in enumerate(col):
        out[i] = None if v is None else v.get(field)
    return out
