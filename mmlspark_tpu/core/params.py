"""Parameter system for pipeline stages.

Plays the role of Spark ML's ``Param``/``Params`` machinery in the reference
(``core/src/main/scala/com/microsoft/azure/synapse/ml/core/contracts/Params.scala:1-207``
and the 21 custom param types under ``org/apache/spark/ml/param/``), redesigned
for a Python/JAX-first framework:

* Params are declared as class attributes (descriptors), so every stage gets
  typed, documented, introspectable configuration for free.
* ``ComplexParam`` covers non-JSON values (ndarrays, nested stages, callables,
  model bytes) with pluggable save/load — the equivalent of the reference's
  ``ComplexParamsSerializer`` (``org/apache/spark/ml/ComplexParamsSerializer.scala``).
* Shared mixin traits (``HasInputCol`` etc.) mirror the reference's contracts.
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable, Dict, Optional

import numpy as np

__all__ = [
    "Param",
    "ComplexParam",
    "Params",
    "ParamMap",
    "identity",
    "HasInputCol",
    "HasOutputCol",
    "HasInputCols",
    "HasOutputCols",
    "HasLabelCol",
    "HasFeaturesCol",
    "HasWeightCol",
    "HasPredictionCol",
    "HasProbabilityCol",
    "HasBatchSize",
    "HasErrorCol",
    "HasSeed",
]


def identity(x):
    return x


# ---------------------------------------------------------------------------
# Type converters
# ---------------------------------------------------------------------------

def _to_int(v):
    import numbers
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, bool):
        raise TypeError(f"expected int, got bool {v!r}")
    if isinstance(v, numbers.Integral):
        return int(v)
    if isinstance(v, float) and v.is_integer():
        return int(v)
    raise TypeError(f"expected int, got {type(v).__name__}: {v!r}")


def _to_float(v):
    import numbers
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, bool):
        raise TypeError(f"expected float, got bool {v!r}")
    if isinstance(v, numbers.Real):
        return float(v)
    raise TypeError(f"expected float, got {type(v).__name__}: {v!r}")


def _to_bool(v):
    if isinstance(v, np.bool_):
        v = bool(v)
    if isinstance(v, bool):
        return v
    raise TypeError(f"expected bool, got {type(v).__name__}: {v!r}")


def _to_str(v):
    if isinstance(v, str):
        return v
    raise TypeError(f"expected str, got {type(v).__name__}: {v!r}")


def _to_list_of(conv):
    def convert(v):
        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        raise TypeError(f"expected list, got {type(v).__name__}: {v!r}")

    return convert


def _to_dict(v):
    if isinstance(v, dict):
        return dict(v)
    raise TypeError(f"expected dict, got {type(v).__name__}: {v!r}")


_CONVERTERS: Dict[Any, Callable[[Any], Any]] = {
    int: _to_int,
    float: _to_float,
    bool: _to_bool,
    str: _to_str,
    dict: _to_dict,
    list: lambda v: list(v) if isinstance(v, (list, tuple)) else (_ for _ in ()).throw(
        TypeError(f"expected list, got {type(v).__name__}")),
    None: identity,
}


class Param:
    """A declared, typed, documented parameter of a pipeline stage.

    Declared as a class attribute::

        class MyStage(Transformer):
            batch_size = Param(int, default=10, doc="rows per minibatch")

    Reads go through the descriptor protocol (``stage.batch_size``); writes via
    ``stage.set(batch_size=...)`` or the constructor.
    """

    #: marker for "no default"
    _NO_DEFAULT = object()

    def __init__(self, dtype=None, default: Any = _NO_DEFAULT, doc: str = "",
                 converter: Optional[Callable[[Any], Any]] = None,
                 choices: Optional[list] = None):
        self.dtype = dtype
        self.doc = doc
        self.choices = choices
        if converter is not None:
            self._convert = converter
        elif dtype in _CONVERTERS:
            self._convert = _CONVERTERS[dtype]
        elif isinstance(dtype, tuple) and len(dtype) == 2 and dtype[0] is list:
            self._convert = _to_list_of(_CONVERTERS.get(dtype[1], identity))
        else:
            self._convert = identity
        self.default = default if (default is Param._NO_DEFAULT
                                   or default is None) \
            else self._convert(default)
        self.name: str = "<unbound>"
        self.owner: Optional[type] = None

    def __set_name__(self, owner, name):
        self.name = name
        self.owner = owner

    @property
    def has_default(self) -> bool:
        return self.default is not Param._NO_DEFAULT

    def convert(self, value):
        if value is None:
            # None is only a legal value for optional params (default None);
            # for typed params with a real default it would bypass validation
            if self.default is None:
                return None
            raise TypeError(f"param {self.name} does not accept None")
        v = self._convert(value)
        if self.choices is not None and v not in self.choices:
            raise ValueError(f"param {self.name}: {v!r} not in {self.choices}")
        return v

    # -- descriptor protocol ------------------------------------------------
    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj.get(self.name)

    def __set__(self, obj, value):
        obj.set(**{self.name: value})

    # -- (de)serialization of values ---------------------------------------
    def json_value(self, value):
        """Value → JSON-compatible object. ComplexParam overrides."""
        return value

    def from_json_value(self, value, load_dir=None):
        return self.convert(value)

    def __repr__(self):
        return f"Param({self.name!r}, dtype={self.dtype}, default={self.default!r})"


class ComplexParam(Param):
    """A param whose value is not JSON-serializable (ndarray, stage, fn, bytes).

    ``saver(value, path)`` / ``loader(path)`` hooks persist the value into the
    stage's save directory. Stages with callables that cannot be persisted can
    pass ``saver=None`` to mark the param transient (skipped on save; must be
    re-set after load).
    """

    def __init__(self, default: Any = Param._NO_DEFAULT, doc: str = "",
                 saver="default", loader="default"):
        super().__init__(None, default, doc, converter=identity)
        self.saver = saver
        self.loader = loader

    def json_value(self, value):  # handled out-of-band by the serializer
        raise TypeError(f"ComplexParam {self.name} has no JSON form")


class ParamMap(dict):
    """A {param_name: value} override map, used by fit/transform and AutoML."""


class _ParamsMeta(type):
    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        declared: Dict[str, Param] = {}
        for base in reversed(cls.__mro__):
            for k, v in vars(base).items():
                if isinstance(v, Param):
                    declared[k] = v
        cls._declared_params = declared
        return cls


class Params(metaclass=_ParamsMeta):
    """Base for everything configurable. Holds explicit values + defaults."""

    _declared_params: Dict[str, Param] = {}
    _uid_counter = [0]

    def __init__(self, **kwargs):
        Params._uid_counter[0] += 1
        self.uid = f"{type(self).__name__}_{Params._uid_counter[0]:08x}"
        self._param_values: Dict[str, Any] = {}
        self.set(**kwargs)

    # -- core accessors -----------------------------------------------------
    @classmethod
    def params(cls) -> Dict[str, Param]:
        return dict(cls._declared_params)

    def param(self, name: str) -> Param:
        try:
            return self._declared_params[name]
        except KeyError:
            raise KeyError(
                f"{type(self).__name__} has no param {name!r}; "
                f"known: {sorted(self._declared_params)}") from None

    def has_param(self, name: str) -> bool:
        return name in self._declared_params

    def is_set(self, name: str) -> bool:
        return name in self._param_values

    def is_defined(self, name: str) -> bool:
        return self.is_set(name) or self.param(name).has_default

    def get(self, name: str, default=Param._NO_DEFAULT):
        if name in self._param_values:
            return self._param_values[name]
        p = self.param(name)
        if p.has_default:
            # mutable defaults are class-shared; hand out copies
            if isinstance(p.default, (list, dict)):
                return _copy.copy(p.default)
            return p.default
        if default is not Param._NO_DEFAULT:
            return default
        raise ValueError(f"param {name!r} of {self.uid} is not set and has no default")

    def get_or_none(self, name: str):
        return self.get(name, default=None)

    def set(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            p = self.param(k)
            if v is None and not p.has_default:
                # allow explicit clearing of optional params
                self._param_values.pop(k, None)
                continue
            self._param_values[k] = p.convert(v) if not isinstance(p, ComplexParam) else v
        return self

    def clear(self, name: str) -> "Params":
        self._param_values.pop(name, None)
        return self

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self._declared_params.items()):
            cur = self._param_values.get(name, p.default if p.has_default else "<unset>")
            lines.append(f"{name}: {p.doc} (current: {cur!r})")
        return "\n".join(lines)

    def extract_param_map(self) -> ParamMap:
        m = ParamMap()
        for name, p in self._declared_params.items():
            if self.is_defined(name):
                m[name] = self.get(name)
        return m

    def copy(self, extra: Optional[dict] = None) -> "Params":
        other = _copy.copy(self)
        other._param_values = dict(self._param_values)
        if extra:
            other.set(**extra)
        return other

    def _set_default(self, **kwargs):
        """Adjust per-instance defaults (e.g. subclasses tightening a default)."""
        for k, v in kwargs.items():
            p = self.param(k)
            if k not in self._param_values:
                self._param_values[k] = p.convert(v) if not isinstance(p, ComplexParam) else v

    def __repr__(self):
        set_vals = {k: v for k, v in self._param_values.items()
                    if not isinstance(self.param(k), ComplexParam)}
        return f"{type(self).__name__}(uid={self.uid}, {set_vals})"


# ---------------------------------------------------------------------------
# Shared contracts (reference: core/contracts/Params.scala:1-207)
# ---------------------------------------------------------------------------

class HasInputCol(Params):
    input_col = Param(str, default="input", doc="name of the input column")


class HasOutputCol(Params):
    output_col = Param(str, default="output", doc="name of the output column")


class HasInputCols(Params):
    input_cols = Param((list, str), default=[], doc="names of the input columns")


class HasOutputCols(Params):
    output_cols = Param((list, str), default=[], doc="names of the output columns")


class HasLabelCol(Params):
    label_col = Param(str, default="label", doc="name of the label column")


class HasFeaturesCol(Params):
    features_col = Param(str, default="features", doc="name of the features column")


class HasWeightCol(Params):
    weight_col = Param(str, default=None, converter=identity,
                       doc="name of the sample-weight column (optional)")


class HasPredictionCol(Params):
    prediction_col = Param(str, default="prediction", doc="name of the prediction column")


class HasProbabilityCol(Params):
    probability_col = Param(str, default="probability", doc="name of the probability column")


class HasBatchSize(Params):
    batch_size = Param(int, default=10, doc="rows per minibatch fed to the device")


class HasErrorCol(Params):
    error_col = Param(str, default="error", doc="column to receive per-row errors")


class HasSeed(Params):
    seed = Param(int, default=0, doc="PRNG seed")
