"""Device residency — columns that *live on device* across pipeline stages.

BENCH_r04 measured the gap this module closes: 11,529 img/s device-resident
vs 268 img/s host-fed on a v5e, with h2d crawling at 0.098 GB/s. The
reference stack's L3 mini-batch layer shuttles every stage through host
memory; the compiled-region literature (Julia-to-TPU arXiv:1810.09868, TVM
arXiv:1802.04799) shows the win is keeping tensors resident across the whole
chain rather than round-tripping per operator. Here a :class:`DataFrame`
column can be *host* (plain ndarray), *device* (a ``jax.Array`` per
partition), or *spilled* (was device, evicted back to host under memory
pressure) — and a ``Pipeline`` of stages pays **one** h2d at ingest and
**one** d2h at the sink.

Three moving parts:

* :class:`DeviceColumn` — an ordered list of device-array chunks (one per
  DataFrame partition at ingest; alignment with later repartitioning is not
  required, slicing walks the chunks). Knows how to gather/slice/concat on
  device without leaving the chip.
* :class:`ResidencyManager` — process-global LRU over every resident
  partition, spilling least-recently-used chunks when a configurable
  device-memory budget (``MMLSPARK_TPU_DEVICE_BUDGET_BYTES``) is exceeded.
  Ingest-staged chunks keep a host view, so their spill is free (drop the
  device buffer); device-born chunks pay one counted d2h to spill.
* :class:`HostMirror` — the lazy host facade a device-born column presents
  inside ``DataFrame._columns``; the first host access materializes it with
  a counted d2h so accidental round-trips show up in metrics (and in
  tpulint's TPU010 ``host-roundtrip`` rule) instead of hiding.

Every transfer is accounted through ``mmlspark_residency_*`` counters in the
shared :mod:`..observability` registry; ``h2d``/``d2h`` count *transfer
operations issued* (a batched multi-chunk put/get is one operation), with
byte totals alongside. jax is imported lazily inside methods so ``core/``
stays importable on hosts without an accelerator stack.
"""

from __future__ import annotations

import os
import sys
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import charge as _ledger_charge
from ..observability import counter as _counter
from ..observability import gauge as _gauge
from ..reliability.lock_sanitizer import new_rlock

__all__ = [
    "DeviceColumn", "HostMirror", "ResidencyManager",
    "get_residency_manager", "configure_residency", "residency_stats",
    "is_device_array", "record_hit", "record_miss",
    "BUDGET_ENV",
]

BUDGET_ENV = "MMLSPARK_TPU_DEVICE_BUDGET_BYTES"

M_H2D = _counter("mmlspark_residency_h2d_total",
                 "host-to-device transfer operations, by site "
                 "(ingest = first staging, restage = reload after spill)",
                 ("site",))
M_H2D_BYTES = _counter("mmlspark_residency_h2d_bytes_total",
                       "bytes moved host-to-device, by site", ("site",))
M_D2H = _counter("mmlspark_residency_d2h_total",
                 "device-to-host transfer operations, by site "
                 "(sink = explicit to_host, materialize = lazy host access "
                 "of a device-born column, spill = eviction writeback)",
                 ("site",))
M_D2H_BYTES = _counter("mmlspark_residency_d2h_bytes_total",
                       "bytes moved device-to-host, by site", ("site",))
M_HITS = _counter("mmlspark_residency_hits_total",
                  "device_put requests served by an already-resident column")
M_MISSES = _counter("mmlspark_residency_misses_total",
                    "device_put requests that had to stage a column")
M_SPILLS = _counter("mmlspark_residency_spills_total",
                    "partition chunks evicted from device under the budget")
M_MATERIALIZE = _counter("mmlspark_residency_host_materializations_total",
                         "device-born columns pulled to host, by op",
                         ("op",))
M_RESIDENT = _gauge("mmlspark_residency_resident_bytes",
                    "bytes currently resident on device under the manager")
M_RESIDENT_CHUNKS = _gauge("mmlspark_residency_resident_chunks",
                           "partition chunks currently resident on device")
M_RESERVED = _gauge("mmlspark_residency_reserved_bytes",
                    "bytes pinned by fixed reservations (e.g. paged KV "
                    "pools) — counted against the budget, never spilled")


def is_device_array(value) -> bool:
    """True iff ``value`` is a ``jax.Array`` — without importing jax.

    If jax was never imported, nothing in the process can be a jax array,
    so the ``sys.modules`` probe is exact and keeps host-only paths free of
    accelerator initialization.
    """
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(value, jax.Array)


def record_hit(n: int = 1) -> None:
    M_HITS.inc(n)


def record_miss(n: int = 1) -> None:
    M_MISSES.inc(n)


def _default_put(x):
    import jax
    return jax.device_put(x)


def _to_host_dtype(arr: np.ndarray) -> np.ndarray:
    """bf16 device chunks come back as ml_dtypes bfloat16 — widen for host
    numpy consumers (same convention as ONNXModel's drain)."""
    if arr.dtype.kind == "V" or str(arr.dtype) == "bfloat16":
        return np.asarray(arr, dtype=np.float32)
    return arr


class _Chunk:
    """One partition-sized chunk of one resident column — the LRU/spill unit.

    ``state`` is "device" or "spilled". ``host`` is the host copy when one
    exists (always for ingest-staged chunks — a zero-copy view of the source
    column — and after a spill writeback for device-born ones); a chunk with
    a host copy spills for free by dropping its device buffer.
    """

    __slots__ = ("state", "dev", "host", "nbytes", "put", "__weakref__")

    def __init__(self, dev, host: Optional[np.ndarray],
                 put: Optional[Callable] = None):
        self.state = "device"
        self.dev = dev
        self.host = host
        self.nbytes = int(getattr(dev, "nbytes", 0))
        self.put = put


class ResidencyManager:
    """Process-global LRU of resident chunks under a device-memory budget.

    ``budget_bytes`` <= 0 means unlimited (the default). The budget is a
    target, not a hard cap: the chunk being admitted is never evicted to
    make room for itself, so a single chunk larger than the budget stays
    resident (and everything else spills).
    """

    def __init__(self, budget_bytes: Optional[int] = None):
        if budget_bytes is None:
            budget_bytes = int(os.environ.get(BUDGET_ENV, "0") or 0)
        self.budget_bytes = int(budget_bytes)
        # gc of a resident chunk can fire the weakref callback mid-admit on
        # the same thread — the lock must be reentrant
        self._lock = new_rlock("core.residency.ResidencyManager._lock")
        self._lru: "OrderedDict[int, object]" = OrderedDict()  # id -> weakref
        self._accounted: Dict[int, int] = {}                   # id -> bytes
        self._resident_bytes = 0
        self._reservations: Dict[int, Tuple[int, str]] = {}    # token -> (bytes, label)
        self._next_reservation = 0

    # -- bookkeeping --------------------------------------------------------
    def _publish(self) -> None:
        M_RESIDENT.set(self._resident_bytes)
        M_RESIDENT_CHUNKS.set(len(self._lru))

    def _forget(self, key: int) -> None:
        with self._lock:
            self._lru.pop(key, None)
            self._resident_bytes -= self._accounted.pop(key, 0)
            self._publish()

    def admit(self, chunk: _Chunk) -> None:
        """Register a device-resident chunk and evict LRU peers over budget."""
        import weakref
        key = id(chunk)
        with self._lock:
            if key not in self._lru:
                self._lru[key] = weakref.ref(
                    chunk, lambda _ref, k=key: self._forget(k))
                self._accounted[key] = chunk.nbytes
                self._resident_bytes += chunk.nbytes
            self._lru.move_to_end(key)
            self._evict_over_budget(exclude=key)
            self._publish()

    def touch(self, chunk: _Chunk) -> None:
        with self._lock:
            key = id(chunk)
            if key in self._lru:
                self._lru.move_to_end(key)

    # -- fixed reservations --------------------------------------------------
    def reserve(self, nbytes: int, label: str = "reserved") -> int:
        """Pin ``nbytes`` of device memory against the budget without a
        spillable chunk behind it — engine state (a paged KV pool's page
        buffers, a slot pool) that must never be evicted but must still
        push LRU *columns* out so the total stays under budget. Returns a
        token for :meth:`release`."""
        nbytes = int(nbytes)
        if nbytes < 0:
            raise ValueError("reserve() needs nbytes >= 0")
        with self._lock:
            token = self._next_reservation
            self._next_reservation += 1
            self._reservations[token] = (nbytes, str(label))
            self._resident_bytes += nbytes
            M_RESERVED.set(self.reserved_bytes())
            self._evict_over_budget()
            self._publish()
        return token

    def release(self, token: int) -> None:
        """Drop a :meth:`reserve` pin (idempotent for unknown tokens)."""
        with self._lock:
            nbytes, _ = self._reservations.pop(token, (0, ""))
            self._resident_bytes -= nbytes
            M_RESERVED.set(self.reserved_bytes())
            self._publish()

    def reserved_bytes(self) -> int:
        return sum(n for n, _ in self._reservations.values())

    def _evict_over_budget(self, exclude: Optional[int] = None) -> None:
        if self.budget_bytes <= 0:
            return
        for key in list(self._lru):
            if self._resident_bytes <= self.budget_bytes:
                break
            if key == exclude:
                continue
            ref = self._lru[key]
            chunk = ref()
            if chunk is not None:
                self._spill(chunk)
            else:
                self._forget(key)

    def _spill(self, chunk: _Chunk) -> None:
        """Evict one chunk: free the device buffer, keeping/making a host
        copy. Host-backed chunks spill for free; device-born ones pay one
        counted d2h writeback."""
        key = id(chunk)
        if chunk.state != "device":
            self._forget(key)
            return
        if chunk.host is None:
            import jax
            # the d2h writeback stays under the manager lock on purpose:
            # it must be atomic with the state flip below — releasing
            # between them would let a concurrent ensure_device resurrect
            # a half-spilled chunk (dev still set, host mid-copy). Spills
            # only happen on the over-budget path; the hold is measured
            # by the lock sanitizer's mmlspark_lock_held_seconds metric.
            host = np.asarray(jax.device_get(chunk.dev))  # tpulint: disable=TPU014
            M_D2H.inc(1, site="spill")
            M_D2H_BYTES.inc(chunk.nbytes, site="spill")
            _ledger_charge("d2h_bytes", chunk.nbytes)
            chunk.host = host
        chunk.dev = None
        chunk.state = "spilled"
        M_SPILLS.inc()
        self._forget(key)

    def ensure_device(self, chunk: _Chunk):
        """Return the chunk's device array, restaging (counted) if spilled."""
        with self._lock:
            if chunk.state == "spilled":
                put = chunk.put or _default_put
                chunk.dev = put(chunk.host)
                chunk.state = "device"
                M_H2D.inc(1, site="restage")
                M_H2D_BYTES.inc(chunk.nbytes, site="restage")
                _ledger_charge("h2d_bytes", chunk.nbytes)
                self.admit(chunk)
            else:
                self.touch(chunk)
            return chunk.dev

    def spill_all(self) -> None:
        """Evict everything resident (test/debug hook)."""
        with self._lock:
            for key in list(self._lru):
                chunk = self._lru[key]()
                if chunk is not None:
                    self._spill(chunk)
                else:
                    self._forget(key)
            self._publish()

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {"resident_bytes": self._resident_bytes,
                    "resident_chunks": len(self._lru),
                    "reserved_bytes": self.reserved_bytes(),
                    "budget_bytes": self.budget_bytes}


_MANAGER = ResidencyManager()


def get_residency_manager() -> ResidencyManager:
    return _MANAGER


def configure_residency(budget_bytes: Optional[int] = None) -> ResidencyManager:
    """Set (or re-read from ``MMLSPARK_TPU_DEVICE_BUDGET_BYTES``) the device
    memory budget and immediately enforce it on what is already resident."""
    if budget_bytes is None:
        budget_bytes = int(os.environ.get(BUDGET_ENV, "0") or 0)
    with _MANAGER._lock:
        _MANAGER.budget_bytes = int(budget_bytes)
        _MANAGER._evict_over_budget()
        _MANAGER._publish()
    return _MANAGER


def residency_stats() -> Dict[str, object]:
    """One JSON-safe dict of the residency story — embedded by bench.py."""
    hits = M_HITS.labels().get()
    misses = M_MISSES.labels().get()
    total = hits + misses
    out: Dict[str, object] = dict(_MANAGER.stats())
    out.update({
        "hits": hits, "misses": misses,
        "residency_hit_rate": (hits / total) if total else None,
        "spills": M_SPILLS.labels().get(),
        "h2d_ops": {s: M_H2D.labels(site=s).get()
                    for s in ("ingest", "restage")},
        "h2d_bytes": {s: M_H2D_BYTES.labels(site=s).get()
                      for s in ("ingest", "restage")},
        "d2h_ops": {s: M_D2H.labels(site=s).get()
                    for s in ("sink", "materialize", "spill")},
        "d2h_bytes": {s: M_D2H_BYTES.labels(site=s).get()
                      for s in ("sink", "materialize", "spill")},
    })
    return out


class DeviceColumn:
    """A column resident on device, chunked for spill granularity.

    Chunks are created per DataFrame partition at ingest but consumers never
    assume alignment — :meth:`slice_rows` walks the chunk list, so the same
    DeviceColumn survives ``repartition`` untouched. Chunk objects may be
    *shared* between DeviceColumns (slicing on exact chunk boundaries, and
    ``concatenate``, reuse them), which keeps the LRU honest: one physical
    buffer, one entry.
    """

    def __init__(self, chunks: List[_Chunk], sizes: List[int],
                 dtype, row_shape: Tuple[int, ...]):
        self._chunks = chunks
        self._sizes = sizes
        self._dtype = dtype
        self._row_shape = tuple(row_shape)

    # -- construction -------------------------------------------------------
    @classmethod
    def from_host(cls, arr: np.ndarray, bounds: Sequence[Tuple[int, int]],
                  put: Optional[Callable] = None) -> "DeviceColumn":
        """Stage a host column: ONE batched put for all chunks, counted as a
        single ``site="ingest"`` h2d operation (and one residency miss).

        Each chunk keeps its host slice (a zero-copy view of ``arr``), so a
        later spill of ingest-staged data is free.
        """
        if arr.dtype == object:
            raise TypeError("object columns cannot be device-resident")
        bounds = [(lo, hi) for lo, hi in bounds] or [(0, len(arr))]
        hosts = [arr[lo:hi] for lo, hi in bounds]
        put_fn = put or _default_put
        devs = put_fn(hosts)  # one transfer op over the whole pytree
        record_miss()
        M_H2D.inc(1, site="ingest")
        M_H2D_BYTES.inc(int(arr.nbytes), site="ingest")
        _ledger_charge("h2d_bytes", int(arr.nbytes))
        chunks = [_Chunk(d, h, put) for d, h in zip(devs, hosts)]
        mgr = get_residency_manager()
        for c in chunks:
            mgr.admit(c)
        col = cls(chunks, [hi - lo for lo, hi in bounds],
                  devs[0].dtype if devs else arr.dtype, arr.shape[1:])
        return col

    @classmethod
    def from_device(cls, arrays: Sequence, put: Optional[Callable] = None,
                    ) -> "DeviceColumn":
        """Wrap device-born arrays (stage outputs) — no transfer, no count."""
        arrays = list(arrays)
        if not arrays:
            raise ValueError("from_device needs at least one array")
        chunks = [_Chunk(a, None, put) for a in arrays]
        mgr = get_residency_manager()
        for c in chunks:
            mgr.admit(c)
        return cls(chunks, [int(a.shape[0]) for a in arrays],
                   arrays[0].dtype, tuple(arrays[0].shape[1:]))

    @classmethod
    def concatenate(cls, cols: Sequence["DeviceColumn"]) -> "DeviceColumn":
        """Stack columns end-to-end, sharing their chunks (no transfer)."""
        cols = list(cols)
        chunks: List[_Chunk] = []
        sizes: List[int] = []
        for c in cols:
            chunks.extend(c._chunks)
            sizes.extend(c._sizes)
        return cls(chunks, sizes, cols[0]._dtype, cols[0]._row_shape)

    # -- properties ---------------------------------------------------------
    @property
    def nrows(self) -> int:
        return sum(self._sizes)

    def __len__(self) -> int:
        return self.nrows

    @property
    def dtype(self):
        return self._dtype

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.nrows,) + self._row_shape

    @property
    def nbytes(self) -> int:
        return sum(c.nbytes for c in self._chunks)

    def chunk_states(self) -> List[str]:
        return [c.state for c in self._chunks]

    # -- device access ------------------------------------------------------
    def device_chunks(self) -> List[object]:
        """The chunk arrays, restaging any spilled ones (counted)."""
        mgr = get_residency_manager()
        return [mgr.ensure_device(c) for c in self._chunks]

    def device_array(self):
        """One device array for the whole column (concat on device)."""
        parts = self.device_chunks()
        if len(parts) == 1:
            return parts[0]
        import jax.numpy as jnp
        return jnp.concatenate(parts, axis=0)

    # -- device-side ops (no host round-trip) -------------------------------
    def slice_rows(self, lo: int, hi: int) -> "DeviceColumn":
        """Rows ``[lo, hi)`` as a new column. Chunks covered exactly are
        shared (no copy, no LRU churn); partial overlaps slice — on host if
        the chunk is host-backed (spill-state preserved, no transfer), else
        on device."""
        lo, hi = max(0, int(lo)), min(self.nrows, int(hi))
        chunks: List[_Chunk] = []
        sizes: List[int] = []
        off = 0
        mgr = get_residency_manager()
        for chunk, size in zip(self._chunks, self._sizes):
            a, b = max(lo, off), min(hi, off + size)
            if a < b:
                if a == off and b == off + size:
                    chunks.append(chunk)  # exact cover: share the buffer
                elif chunk.host is not None:
                    host = chunk.host[a - off:b - off]
                    if chunk.state == "device":
                        sub = _Chunk(chunk.dev[a - off:b - off], host,
                                     chunk.put)
                        mgr.admit(sub)
                    else:  # stay spilled: host view only, no transfer
                        sub = _Chunk(None, host, chunk.put)
                        sub.nbytes = int(host.nbytes)
                        sub.state = "spilled"
                    chunks.append(sub)
                else:
                    dev = mgr.ensure_device(chunk)
                    sub = _Chunk(dev[a - off:b - off], None, chunk.put)
                    mgr.admit(sub)
                    chunks.append(sub)
                sizes.append(b - a)
            off += size
        if not chunks:
            import jax.numpy as jnp
            empty = jnp.zeros((0,) + self._row_shape, dtype=self._dtype)
            return DeviceColumn.from_device([empty])
        return DeviceColumn(chunks, sizes, self._dtype, self._row_shape)

    def take(self, indices) -> "DeviceColumn":
        """Device gather — the index vector rides along uncounted (it is
        addressing, not column payload)."""
        idx = np.asarray(indices)
        arr = self.device_array()
        return DeviceColumn.from_device([arr[idx]])

    def compress(self, mask: np.ndarray) -> "DeviceColumn":
        """Boolean-mask filter on device (eager jax supports it)."""
        mask = np.asarray(mask)
        arr = self.device_array()
        return DeviceColumn.from_device([arr[mask]])

    # -- host exit ----------------------------------------------------------
    def to_host(self, site: str = "sink") -> np.ndarray:
        """Materialize the whole column on host.

        Chunks with a host copy are free; the rest come back in ONE batched
        ``jax.device_get`` counted as a single d2h operation at ``site``.
        bf16 widens to f32 for host consumers.
        """
        need = [(i, c.dev) for i, c in enumerate(self._chunks)
                if c.host is None]
        fetched: Dict[int, np.ndarray] = {}
        if need:
            import jax
            got = jax.device_get([d for _, d in need])
            nbytes = sum(int(getattr(d, "nbytes", 0)) for _, d in need)
            M_D2H.inc(1, site=site)
            M_D2H_BYTES.inc(nbytes, site=site)
            _ledger_charge("d2h_bytes", nbytes)
            fetched = {i: np.asarray(a) for (i, _), a in zip(need, got)}
        parts = [fetched.get(i, c.host) for i, c in enumerate(self._chunks)]
        parts = [_to_host_dtype(np.asarray(p)) for p in parts]
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)


class HostMirror:
    """Lazy host facade of a device-born :class:`DeviceColumn`.

    Lives in ``DataFrame._columns`` where a plain ndarray would. Shape/dtype
    queries are free; the first *data* access (indexing, ``np.asarray``,
    iteration) pulls the column to host exactly once, counted as a
    ``site="materialize"`` d2h plus a ``host_materializations`` increment —
    so a stage that quietly round-trips shows up in the metrics.
    """

    __slots__ = ("_dcol", "_arr")

    def __init__(self, dcol: DeviceColumn):
        self._dcol = dcol
        self._arr: Optional[np.ndarray] = None

    @property
    def source(self) -> DeviceColumn:
        return self._dcol

    def fetch(self, site: str = "materialize") -> np.ndarray:
        if self._arr is None:
            M_MATERIALIZE.inc(1, op=site)
            self._arr = self._dcol.to_host(site=site)
        return self._arr

    def materialize(self) -> np.ndarray:
        return self.fetch("materialize")

    # -- array-protocol surface (free) --------------------------------------
    def __len__(self) -> int:
        return self._dcol.nrows

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._dcol.shape

    @property
    def ndim(self) -> int:
        return len(self._dcol.shape)

    @property
    def dtype(self) -> np.dtype:
        if str(self._dcol.dtype) == "bfloat16":
            return np.dtype(np.float32)
        return np.dtype(self._dcol.dtype)

    @property
    def nbytes(self) -> int:
        return self._dcol.nbytes

    # -- data access (counted, materializes once) ---------------------------
    def __getitem__(self, key):
        return self.materialize()[key]

    def __iter__(self):
        return iter(self.materialize())

    def __array__(self, dtype=None):
        arr = self.materialize()
        return np.asarray(arr, dtype=dtype) if dtype is not None else arr

    def __repr__(self) -> str:
        state = "materialized" if self._arr is not None else "device"
        return (f"HostMirror({self._dcol.shape}, {self._dcol.dtype}, "
                f"{state})")
