"""Transformer / Estimator / Pipeline — the stage algebra.

Parity surface: Spark ML's ``Transformer``/``Estimator``/``Pipeline`` as used
throughout the reference (every feature ships as one of these; see
``SURVEY.md`` §1 L3/L4). Stages here are eager (DataFrames are materialized
columns), configured via the Param system, and serializable via
``mmlspark_tpu.core.serialize``.

Telemetry parity: ``BasicLogging`` (reference
``core/.../logging/BasicLogging.scala:26-71``) logs a JSON envelope per
fit/transform — here a stdlib logger emits the same shape.
"""

from __future__ import annotations

import json
import logging
import time
from typing import List, Optional, Sequence

from .dataframe import DataFrame
from .params import ComplexParam, Params

__all__ = ["PipelineStage", "Transformer", "DeviceTransformer", "Estimator",
           "Model", "Pipeline", "PipelineModel"]

_telemetry = logging.getLogger("mmlspark_tpu.telemetry")


def _log_event(stage: "PipelineStage", method: str, **extra):
    payload = {"uid": stage.uid, "className": type(stage).__qualname__,
               "method": method, **extra}
    _telemetry.debug(json.dumps(payload))


class PipelineStage(Params):
    """Common base: params + save/load + telemetry."""

    def save(self, path: str, overwrite: bool = True) -> None:
        from . import serialize
        serialize.save_stage(self, path, overwrite=overwrite)

    @classmethod
    def load(cls, path: str) -> "PipelineStage":
        from . import serialize
        stage = serialize.load_stage(path)
        if cls is not PipelineStage and not isinstance(stage, cls):
            raise TypeError(f"loaded {type(stage).__name__}, expected {cls.__name__}")
        return stage

    # Hooks for stages carrying non-param state (e.g. fitted arrays).
    def _save_extra(self, path: str) -> None:
        pass

    def _load_extra(self, path: str) -> None:
        pass


class Transformer(PipelineStage):
    """A stage mapping DataFrame → DataFrame."""

    def transform(self, df: DataFrame, params: Optional[dict] = None) -> DataFrame:
        stage = self.copy(params) if params else self
        t0 = time.perf_counter()
        from ..utils.profiling import span
        with span(f"{type(stage).__name__}.transform"):
            out = stage._transform(df)
        _log_event(stage, "transform", rows=len(df),
                   millis=round(1e3 * (time.perf_counter() - t0), 3))
        return out

    def _transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class DeviceTransformer(Transformer):
    """A Transformer whose compute runs on **device-resident** columns.

    Subclasses implement :meth:`_transform_device` over a dict of
    ``jax.Array`` inputs and return device arrays; the base class stages
    inputs at most once (``DataFrame.device_put`` is idempotent — the first
    stage of a chain pays the single ingest h2d, later stages count
    residency hits and move nothing) and attaches outputs as device-born
    resident columns. A chain of these therefore costs one h2d at ingest
    and one d2h when the caller finally exits via ``DataFrame.to_host`` —
    the residency contract the bench's device-resident leg measures.
    """

    input_cols = ComplexParam(default=[],
                              doc="columns staged and passed to "
                                  "_transform_device; [] = every dense "
                                  "numeric column")

    def __init__(self, input_cols: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        if input_cols is not None:
            self.set(input_cols=list(input_cols))

    def _transform_device(self, arrays: dict) -> dict:
        """``{col: jax.Array} -> {col: jax.Array}`` — stays on device."""
        raise NotImplementedError

    def _transform(self, df: DataFrame) -> DataFrame:
        names = list(self.get("input_cols") or [])
        staged = df.device_put(names or None)
        arrays = {n: staged.device_column(n).device_array()
                  for n in (names or staged.resident_columns)}
        out = staged
        for name, arr in (self._transform_device(arrays) or {}).items():
            out = out.with_device_column(name, arr)
        return out


class Estimator(PipelineStage):
    """A stage whose ``fit`` produces a :class:`Model` (a Transformer)."""

    def fit(self, df: DataFrame, params: Optional[dict] = None) -> "Model":
        est = self.copy(params) if params else self
        t0 = time.perf_counter()
        from ..utils.profiling import span
        with span(f"{type(est).__name__}.fit"):
            model = est._fit(df)
        _log_event(est, "fit", rows=len(df),
                   millis=round(1e3 * (time.perf_counter() - t0), 3))
        return model

    def _fit(self, df: DataFrame) -> "Model":
        raise NotImplementedError

    def fit_multiple(self, df: DataFrame, param_maps: Sequence[dict]) -> List["Model"]:
        """Fit one model per param override; AutoML entry point (reference
        ``VowpalWabbitContextualBandit.fitMultiple`` / ``TuneHyperparameters``)."""
        return [self.fit(df, dict(m)) for m in param_maps]


class Model(Transformer):
    """A fitted Transformer, optionally keeping a pointer to its parent."""

    parent: Optional[Estimator] = None


class Pipeline(Estimator):
    """Sequential composition of stages (reference: Spark ML Pipeline)."""

    stages = ComplexParam(default=[], doc="ordered list of pipeline stages")

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))

    def _fit(self, df: DataFrame) -> "PipelineModel":
        fitted: List[Transformer] = []
        cur = df
        stages = self.get("stages")
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                if i < len(stages) - 1:
                    cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                if i < len(stages) - 1:
                    cur = stage.transform(cur)
            else:
                raise TypeError(f"pipeline stage {stage!r} is neither "
                                "Estimator nor Transformer")
        return PipelineModel(fitted)


class PipelineModel(Model):
    stages = ComplexParam(default=[], doc="ordered list of fitted transformers")

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))

    def _transform(self, df: DataFrame) -> DataFrame:
        cur = df
        for stage in self.get("stages"):
            cur = stage.transform(cur)
        return cur
