from .dataframe import DataFrame, concat
from .params import (ComplexParam, Param, Params, ParamMap, HasInputCol,
                     HasOutputCol, HasInputCols, HasOutputCols, HasLabelCol,
                     HasFeaturesCol, HasWeightCol, HasPredictionCol,
                     HasProbabilityCol, HasBatchSize, HasErrorCol, HasSeed)
from .pipeline import (Estimator, Model, Pipeline, PipelineModel,
                       PipelineStage, Transformer)

__all__ = [
    "DataFrame", "concat",
    "Param", "ComplexParam", "Params", "ParamMap",
    "HasInputCol", "HasOutputCol", "HasInputCols", "HasOutputCols",
    "HasLabelCol", "HasFeaturesCol", "HasWeightCol", "HasPredictionCol",
    "HasProbabilityCol", "HasBatchSize", "HasErrorCol", "HasSeed",
    "PipelineStage", "Transformer", "Estimator", "Model",
    "Pipeline", "PipelineModel",
]
