"""Columnar DataFrame — the host-side data plane of the framework.

The reference is built on Spark DataFrames (lazy, partitioned, JVM row
iterators). A TPU-first framework wants the opposite at the boundary:
**columnar, contiguous, zero-copy into ``jax.device_put``**. This DataFrame is
a thin partitioned wrapper over numpy arrays:

* dense numeric columns → ``np.ndarray`` (1-D, or n-D for tensor columns)
* strings / ragged / struct values → object arrays
* partitions are row-ranges, not separate allocations, so repartitioning is
  free and device feeds stay contiguous.

Interop with pandas and pyarrow is provided for IO. Transformers operate on
whole columns (vectorized) or via ``map_partitions`` when they need the
per-partition device pinning the reference gets from Spark ``mapPartitions``
(e.g. ``ONNXModel.scala:499-508``).

Columns can also be **device-resident** (see :mod:`.residency`): a column
staged with :meth:`DataFrame.device_put` lives on device across pipeline
stages — ``filter``/``take``/``sort_values``/``repartition``/``head`` and
partition traversal all stay on device, so a Transformer chain pays one h2d
at ingest and one d2h at the sink instead of a round-trip per stage. A
device-born column (a stage output attached via
:meth:`DataFrame.with_device_column`) is represented on the host side by a
lazy :class:`~.residency.HostMirror`; touching its data materializes it once,
with the transfer counted in ``mmlspark_residency_*`` metrics.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np

from ..observability.tracing import propagate as _propagate
from .residency import DeviceColumn, HostMirror, is_device_array, record_hit

__all__ = ["DataFrame", "concat", "object_col"]


# Shared partition-mapping pools, keyed by worker count. A serving loop calls
# `transform` per request batch, and a fresh ThreadPoolExecutor per call put
# thread spawn/teardown on every one of them — the pool now amortizes to
# zero per call. Keyed (not single) so an explicit `max_workers` bound still
# bounds concurrency; never shut down (Python's atexit hook joins the idle
# workers at interpreter exit).
_POOLS: Dict[int, "object"] = {}
_POOLS_LOCK = threading.Lock()
_IN_POOL = threading.local()


def _shared_pool(max_workers: int):
    from concurrent.futures import ThreadPoolExecutor
    with _POOLS_LOCK:
        ex = _POOLS.get(max_workers)
        if ex is None:
            ex = _POOLS[max_workers] = ThreadPoolExecutor(
                max_workers=max_workers,
                thread_name_prefix="mmlspark-partitions")
        return ex


def object_col(values) -> np.ndarray:
    """Build a 1-D object column without numpy coercing nested sequences."""
    values = list(values) if not isinstance(values, (list, np.ndarray)) else values
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def _as_column(values) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    if isinstance(values, HostMirror):
        return values  # lazy device-born facade; never list() a jax array
    if hasattr(values, "to_numpy"):
        return values.to_numpy()
    values = list(values)
    if values and isinstance(values[0], (str, bytes, dict, list, tuple, np.ndarray)):
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr
    return np.asarray(values)


class DataFrame:
    """An immutable-ish columnar table with logical partitions."""

    def __init__(self, columns: Dict[str, Union[np.ndarray, Sequence]],
                 npartitions: int = 1, metadata: Optional[Dict[str, dict]] = None,
                 partition_sizes: Optional[Sequence[int]] = None,
                 device_columns: Optional[Dict[str, DeviceColumn]] = None):
        self._columns: Dict[str, np.ndarray] = {}
        self._metadata: Dict[str, dict] = dict(metadata or {})
        self._device: Dict[str, DeviceColumn] = {}
        device_columns = dict(device_columns or {})
        n = None
        for name, col in columns.items():
            if col is None and name in device_columns:
                self._columns[name] = None  # placeholder: mirror comes below
                continue
            if isinstance(col, DeviceColumn):
                device_columns.setdefault(name, col)
                self._columns[name] = None  # placeholder keeps column order
                continue
            if is_device_array(col):
                # a raw jax array is a device-born column, not host data —
                # never round-trip it through list()/np.asarray
                device_columns.setdefault(name, DeviceColumn.from_device([col]))
                self._columns[name] = None
                continue
            arr = _as_column(col)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(
                    f"column {name!r} has {len(arr)} rows, expected {n}")
            self._columns[name] = arr
        for name, dcol in device_columns.items():
            if n is None:
                n = dcol.nrows
            elif dcol.nrows != n:
                raise ValueError(
                    f"device column {name!r} has {dcol.nrows} rows, "
                    f"expected {n}")
            self._device[name] = dcol
            host = self._columns.get(name)
            # keep a real host array (ingest-staged: host view is free) or an
            # existing mirror of this very column (preserves its cache);
            # otherwise install a fresh lazy mirror
            if not (isinstance(host, np.ndarray)
                    or (isinstance(host, HostMirror) and host.source is dcol)):
                self._columns[name] = HostMirror(dcol)
        self._nrows = n if n is not None else 0
        # explicit (possibly uneven) partition sizes — e.g. parquet row
        # groups — override the equal-range split
        self._partition_sizes: Optional[List[int]] = None
        if partition_sizes is not None:
            sizes = [int(s) for s in partition_sizes]
            if sum(sizes) != self._nrows or any(s < 0 for s in sizes):
                raise ValueError(
                    f"partition_sizes {sizes} do not sum to {self._nrows}")
            self._partition_sizes = sizes
            self._npartitions = max(1, len(sizes))
        else:
            self._npartitions = max(1, min(int(npartitions),
                                           max(1, self._nrows)))

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_pandas(pdf, npartitions: int = 1) -> "DataFrame":
        return DataFrame({c: pdf[c].to_numpy() for c in pdf.columns}, npartitions)

    @staticmethod
    def from_arrow(table, npartitions: int = 1) -> "DataFrame":
        import pyarrow as pa
        cols = {}
        for name in table.column_names:
            col = table.column(name)
            typ = col.type
            if pa.types.is_fixed_size_list(typ):
                # dense tensor columns round-trip as FixedSizeList; restore
                # the (N, k) block zero-copy (inverse of to_arrow)
                chunk = col.combine_chunks()
                flat = chunk.values.to_numpy(zero_copy_only=False)
                cols[name] = flat.reshape(len(chunk), typ.list_size)
                continue
            try:
                cols[name] = col.to_numpy(zero_copy_only=False)
            except Exception:
                cols[name] = _as_column(col.to_pylist())
        return DataFrame(cols, npartitions)

    @staticmethod
    def from_rows(rows: Iterable[dict], npartitions: int = 1) -> "DataFrame":
        rows = list(rows)
        if not rows:
            return DataFrame({}, npartitions)
        keys = list(rows[0].keys())
        return DataFrame({k: _as_column([r[k] for r in rows]) for k in keys},
                         npartitions)

    def to_pandas(self):
        import pandas as pd
        # object and n-D tensor columns become per-row lists of arrays;
        # self[k] materializes device-born columns (counted)
        cols = {k: self[k] for k in self._columns}
        return pd.DataFrame({k: list(v) if (v.dtype == object or v.ndim > 1)
                             else v for k, v in cols.items()})

    def to_arrow(self):
        """Columnar handoff to pyarrow.

        Dense 2-D tensor columns go zero-copy as FixedSizeList (restored to
        a dense block by :meth:`from_arrow`); object columns (ragged/None/
        higher-rank cells) fall back to per-row list values."""
        import pyarrow as pa

        arrays, names = [], []
        for name in self._columns:
            col = self[name]  # materializes device-born columns (counted)
            if col.dtype != object and col.ndim == 2:
                flat = pa.array(np.ascontiguousarray(col).reshape(-1))
                arrays.append(pa.FixedSizeListArray.from_arrays(
                    flat, col.shape[1]))
            elif col.dtype == object or col.ndim > 2:
                vals = [None if v is None
                        else (v.tolist() if isinstance(v, np.ndarray) else v)
                        for v in col]
                arrays.append(pa.array(vals))
            else:
                arrays.append(pa.array(col))
            names.append(name)
        return pa.table(dict(zip(names, arrays)))

    # -- basic properties ---------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    @property
    def npartitions(self) -> int:
        return self._npartitions

    def __len__(self) -> int:
        return self._nrows

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def __getitem__(self, name: str) -> np.ndarray:
        if name not in self._columns:
            raise KeyError(f"no column {name!r}; have {self.columns}")
        col = self._columns[name]
        if isinstance(col, HostMirror):
            return col.materialize()  # counted d2h, once per mirror
        return col

    def column(self, name: str) -> np.ndarray:
        return self[name]

    # -- device residency ---------------------------------------------------
    def device_put(self, names: Optional[Sequence[str]] = None,
                   put=None) -> "DataFrame":
        """Stage columns on device (idempotent — already-resident columns
        count a residency *hit* and move no bytes; each newly staged column
        is one counted ``site="ingest"`` h2d + one *miss*).

        ``names=None`` stages every dense numeric column. ``put`` overrides
        the transfer (e.g. a :class:`~..parallel.mesh.Placement` put).
        """
        if names is None:
            names = [k for k, v in self._columns.items()
                     if k in self._device
                     or getattr(v, "dtype", None) != np.dtype(object)]
        dev = dict(self._device)
        for n in names:
            if n in dev:
                record_hit()
                continue
            arr = self[n]
            dev[n] = DeviceColumn.from_host(arr, self.partition_bounds(),
                                            put=put)
        return DataFrame(self._columns, self._npartitions, self._metadata,
                         partition_sizes=self._partition_sizes,
                         device_columns=dev)

    def with_device_column(self, name: str, dcol) -> "DataFrame":
        """Attach a device-born column (a :class:`DeviceColumn` or a raw
        ``jax.Array``) without any transfer; the host side becomes a lazy
        mirror."""
        if not isinstance(dcol, DeviceColumn):
            dcol = DeviceColumn.from_device([dcol])
        cols = {k: v for k, v in self._columns.items() if k != name}
        cols[name] = HostMirror(dcol)
        dev = {k: v for k, v in self._device.items() if k != name}
        dev[name] = dcol
        return DataFrame(cols, self._npartitions, self._metadata,
                         partition_sizes=self._partition_sizes,
                         device_columns=dev)

    def device_column(self, name: str) -> DeviceColumn:
        if name not in self._device:
            raise KeyError(f"column {name!r} is not device-resident; "
                           f"resident: {self.resident_columns}")
        return self._device[name]

    def is_resident(self, name: str) -> bool:
        return name in self._device

    @property
    def resident_columns(self) -> List[str]:
        return list(self._device)

    def to_host(self, names: Optional[Sequence[str]] = None) -> "DataFrame":
        """The sink: drop device residency, materializing device-born
        columns in one counted ``site="sink"`` d2h each. Ingest-staged
        columns still hold their host array, so their exit is free."""
        names = list(self._device) if names is None else list(names)
        cols = dict(self._columns)
        dev = dict(self._device)
        for n in names:
            if n not in dev:
                continue
            dev.pop(n)
            host = cols.get(n)
            if isinstance(host, HostMirror):
                cols[n] = host.fetch(site="sink")
        return DataFrame(cols, self._npartitions, self._metadata,
                         partition_sizes=self._partition_sizes,
                         device_columns=dev)

    # -- column metadata (parity: Spark column Metadata / Categoricals) -----
    def column_metadata(self, name: str) -> dict:
        return dict(self._metadata.get(name, {}))

    def with_column_metadata(self, name: str, meta: dict) -> "DataFrame":
        md = dict(self._metadata)
        md[name] = {**md.get(name, {}), **meta}
        return DataFrame(self._columns, self._npartitions, md,
                         partition_sizes=self._partition_sizes,
                         device_columns=self._device)

    def _meta_for(self, names) -> Dict[str, dict]:
        return {k: v for k, v in self._metadata.items() if k in names}

    def schema(self) -> Dict[str, str]:
        out = {}
        for k, v in self._columns.items():
            if v.dtype == object and len(v):
                out[k] = type(v[0]).__name__
            else:
                out[k] = str(v.dtype)
        return out

    # -- transformations (all return new DataFrames) ------------------------
    def with_column(self, name: str, values) -> "DataFrame":
        if isinstance(values, DeviceColumn) or is_device_array(values):
            return self.with_device_column(name, values)
        cols = dict(self._columns)
        cols[name] = _as_column(values)  # host overwrite drops residency
        dev = {k: v for k, v in self._device.items() if k != name}
        return DataFrame(cols, self._npartitions, self._metadata,
                         partition_sizes=self._partition_sizes,
                         device_columns=dev)

    def with_columns(self, new: Dict[str, Union[np.ndarray, Sequence]]) -> "DataFrame":
        out = self
        for k, v in new.items():
            out = out.with_column(k, v)
        return out

    def select(self, names: Sequence[str]) -> "DataFrame":
        return DataFrame({n: self._columns[n] for n in names},
                         self._npartitions, self._meta_for(names),
                         partition_sizes=self._partition_sizes,
                         device_columns={n: self._device[n] for n in names
                                         if n in self._device})

    def drop(self, *names: str) -> "DataFrame":
        keep = [k for k in self._columns if k not in names]
        return DataFrame({k: self._columns[k] for k in keep}, self._npartitions,
                         self._meta_for(keep),
                         partition_sizes=self._partition_sizes,
                         device_columns={k: self._device[k] for k in keep
                                         if k in self._device})

    def rename(self, mapping: Dict[str, str]) -> "DataFrame":
        md = {mapping.get(k, k): v for k, v in self._metadata.items()}
        return DataFrame({mapping.get(k, k): v for k, v in self._columns.items()},
                         self._npartitions, md,
                         partition_sizes=self._partition_sizes,
                         device_columns={mapping.get(k, k): v
                                         for k, v in self._device.items()})

    def _gather(self, host_op, device_op, npartitions=None) -> "DataFrame":
        """Shared row-gather: resident columns gather on device (no
        round-trip), host columns on host."""
        cols, dev = {}, {}
        for k, v in self._columns.items():
            if k in self._device:
                dev[k] = device_op(self._device[k])
                cols[k] = None
            else:
                cols[k] = host_op(v)
        return DataFrame(cols, npartitions or self._npartitions,
                         self._metadata, device_columns=dev)

    def filter(self, mask: np.ndarray) -> "DataFrame":
        mask = np.asarray(mask)
        if mask.dtype != bool:
            raise TypeError("filter expects a boolean mask")
        return self._gather(lambda v: v[mask], lambda d: d.compress(mask))

    def take(self, indices) -> "DataFrame":
        idx = np.asarray(indices)
        return self._gather(lambda v: v[idx], lambda d: d.take(idx))

    def head(self, n: int) -> "DataFrame":
        return self._gather(lambda v: v[:n], lambda d: d.slice_rows(0, n),
                            npartitions=1)

    def repartition(self, npartitions: int) -> "DataFrame":
        # DeviceColumn chunking is alignment-agnostic: residency rides along
        return DataFrame(self._columns, npartitions, self._metadata,
                         device_columns=self._device)

    def sort_values(self, by: str, ascending: bool = True) -> "DataFrame":
        if by in self._device:
            # argsort on device: only the index vector crosses the bus,
            # never the key column's payload
            order = np.asarray(self._device[by].device_array().argsort())
            if order.ndim > 1:  # tensor column: sort by first component
                order = order[:, 0]
        else:
            order = np.argsort(self[by], kind="stable")
        if not ascending:
            order = order[::-1]
        return self.take(order)

    def sample(self, frac: float, seed: int = 0, replace: bool = False) -> "DataFrame":
        rng = np.random.default_rng(seed)
        k = int(round(frac * self._nrows))
        idx = rng.choice(self._nrows, size=k, replace=replace)
        return self.take(idx)

    def shuffle(self, seed: int = 0) -> "DataFrame":
        rng = np.random.default_rng(seed)
        return self.take(rng.permutation(self._nrows))

    def cache(self) -> "DataFrame":
        return self  # materialized already; parity no-op (stages/Cacher)

    # -- partition machinery ------------------------------------------------
    def partition_bounds(self) -> List[tuple]:
        if self._partition_sizes is not None:
            bounds, start = [], 0
            for size in self._partition_sizes:
                bounds.append((start, start + size))
                start += size
            return bounds
        n, p = self._nrows, self._npartitions
        base, rem = divmod(n, p)
        bounds, start = [], 0
        for i in range(p):
            size = base + (1 if i < rem else 0)
            bounds.append((start, start + size))
            start += size
        return bounds

    def partitions(self) -> Iterator["DataFrame"]:
        for lo, hi in self.partition_bounds():
            cols, dev = {}, {}
            for k, v in self._columns.items():
                if k in self._device:
                    # slice on device; chunks covered exactly are shared, so
                    # per-partition views cost no transfer and no LRU churn
                    dev[k] = self._device[k].slice_rows(lo, hi)
                    cols[k] = None
                else:
                    cols[k] = v[lo:hi]
            yield DataFrame(cols, 1, self._metadata, device_columns=dev)

    def map_partitions(self, fn: Callable[["DataFrame", int], "DataFrame"],
                       max_workers: Optional[int] = None) -> "DataFrame":
        """Apply ``fn(part_df, part_index)`` to each partition and concat.

        The moral equivalent of Spark ``mapPartitions`` — the unit at which
        device pinning and batching happen. Partitions run **concurrently**
        on a thread pool (Spark runs one task per core the same way,
        ``ONNXModel.scala:499-508``): numpy and JAX release the GIL during
        heavy work and JAX dispatch is async, so round-robin device pinning
        actually keeps k local chips busy. Results preserve partition order;
        the first exception propagates. ``max_workers=1`` forces the
        sequential path; env ``MMLSPARK_TPU_PARTITION_THREADS`` overrides
        the default pool size. Pools are module-level and reused across
        calls (serving loops invoke ``transform`` per request batch, and a
        per-call executor made every one pay thread spawn/teardown); a
        ``map_partitions`` issued from inside a pool worker runs
        sequentially instead of queueing on its own pool, which could
        deadlock.
        """
        parts = list(self.partitions())
        if max_workers is None:
            max_workers = int(os.environ.get("MMLSPARK_TPU_PARTITION_THREADS", "0")) \
                or min(len(parts), 8)
        if len(parts) <= 1 or max_workers <= 1 \
                or getattr(_IN_POOL, "active", False):
            results = [fn(p, i) for i, p in enumerate(parts)]
        else:
            def wrapped(p, i):
                _IN_POOL.active = True
                try:
                    return fn(p, i)
                finally:
                    _IN_POOL.active = False
            ex = _shared_pool(max_workers)
            # pool workers are long-lived and start with an empty context:
            # re-install the caller's (active trace span, SpanTracer) around
            # each partition call so spans recorded there stay attributable
            results = list(ex.map(_propagate(wrapped), parts,
                                  range(len(parts))))
        out = concat(results, npartitions=self._npartitions)
        # per-partition result sizes become the output boundaries, so uneven
        # splits (parquet row groups) survive a map_partitions round
        if len(results) > 1:
            out = DataFrame(dict(out._columns), metadata=out._metadata,
                            partition_sizes=[len(r) for r in results],
                            device_columns=out._device)
        return out

    # -- row view (for HTTP/serving paths that are row-oriented) ------------
    def iter_rows(self) -> Iterator[dict]:
        names = self.columns
        cols = [self._columns[n] for n in names]
        for i in range(self._nrows):
            yield {n: c[i] for n, c in zip(names, cols)}

    def to_rows(self) -> List[dict]:
        return list(self.iter_rows())

    def __repr__(self):
        return (f"DataFrame({self._nrows} rows x {len(self._columns)} cols, "
                f"{self._npartitions} partitions: {self.schema()})")


def concat(dfs: Sequence[DataFrame], npartitions: Optional[int] = None) -> DataFrame:
    dfs = [d for d in dfs if len(d.columns) > 0 or len(d) > 0]
    if not dfs:
        return DataFrame({})
    names = dfs[0].columns
    for d in dfs[1:]:
        if d.columns != names:
            raise ValueError(f"column mismatch in concat: {names} vs {d.columns}")
    cols, dev = {}, {}
    for n in names:
        if all(d.is_resident(n) for d in dfs):
            # resident everywhere: stitch the chunk lists, zero transfers
            dev[n] = DeviceColumn.concatenate([d._device[n] for d in dfs])
            hosts = [d._columns[n] for d in dfs]
            if all(isinstance(h, np.ndarray) for h in hosts):
                cols[n] = np.concatenate(hosts)  # host views are free
            else:
                cols[n] = None  # lazy mirror of the combined column
            continue
        # np.concatenate promotes mixed parts to object dtype on its own;
        # d[n] materializes any mirrors (counted) — concat off-device is a
        # genuine host exit for device-born parts
        cols[n] = np.concatenate([d[n] for d in dfs])
    md = {}
    for d in dfs:
        md.update(d._metadata)
    return DataFrame(cols, npartitions or dfs[0].npartitions, md,
                     device_columns=dev)
