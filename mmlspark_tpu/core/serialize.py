"""Stage persistence.

Parity surface: the reference's ``ComplexParamsWritable`` + custom
``Serializer`` (``org/apache/spark/ml/ComplexParamsSerializer.scala``,
``Serializer.scala``) which let whole pipelines — including fitted models and
non-JSON params — round-trip through disk. Layout here:

    <path>/metadata.json          class, uid, simple params
    <path>/complex/<param>/...    one subdir per complex param (typed payload)
    <path>/extra/...              stage-specific fitted state (_save_extra hook)

Complex values are saved by type tag: ndarray (npz), bytes (bin), pytree of
ndarrays (npz + treedef json), stage / list-of-stages (nested save), plain
JSON-able values (json). Callables are transient: skipped with a marker, and
must be re-attached after load.
"""

from __future__ import annotations

import importlib
import json
import os
import shutil
from typing import Any, List

import numpy as np

from .params import ComplexParam
from .pipeline import PipelineStage

__all__ = ["save_stage", "load_stage", "save_value", "load_value",
           "to_jsonable"]


def to_jsonable(v):
    """Coerce numpy scalars/arrays to JSON-encodable python values."""
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    return v

_FORMAT_VERSION = 1


def _class_path(obj) -> str:
    cls = type(obj)
    if cls.__module__ == "__main__":
        import warnings
        warnings.warn(
            f"{cls.__qualname__} is defined in __main__; the saved stage will "
            "not be loadable from another process. Define stages in an "
            "importable module.", stacklevel=4)
    return f"{cls.__module__}:{cls.__qualname__}"


def _resolve_class(path: str):
    module, _, qualname = path.partition(":")
    mod = importlib.import_module(module)
    obj = mod
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def _is_jsonable(v) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


def save_value(value: Any, path: str) -> str:
    """Persist one complex value under ``path`` (a directory). Returns a tag."""
    os.makedirs(path, exist_ok=True)
    if isinstance(value, PipelineStage):
        save_stage(value, os.path.join(path, "stage"))
        return "stage"
    if isinstance(value, (list, tuple)) and value and all(
            isinstance(s, PipelineStage) for s in value):
        for i, s in enumerate(value):
            save_stage(s, os.path.join(path, f"stage_{i:04d}"))
        with open(os.path.join(path, "count.json"), "w") as f:
            json.dump(len(value), f)
        return "stage_list"
    if isinstance(value, np.ndarray):
        if value.dtype == object or value.dtype.kind in "US":
            # string/object columns (e.g. KNN values/labels) can't go through
            # savez without pickle (save would succeed, load would fail) —
            # store as shape-preserving JSON, or fail fast at save time
            with open(os.path.join(path, "objarray.json"), "w") as f:
                json.dump(_obj_array_to_json(value), f)
            return "objarray"
        np.savez(os.path.join(path, "array.npz"), value=value)
        return "ndarray"
    if isinstance(value, (bytes, bytearray)):
        with open(os.path.join(path, "value.bin"), "wb") as f:
            f.write(value)
        return "bytes"
    # pytree of arrays (dict/list nesting with ndarray/scalar leaves)
    flat = _try_flatten_tree(value)
    if flat is not None:
        leaves, treedef = flat
        np.savez(os.path.join(path, "tree.npz"),
                 **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)})
        with open(os.path.join(path, "treedef.json"), "w") as f:
            json.dump(treedef, f)
        return "pytree"
    if _is_jsonable(value):
        with open(os.path.join(path, "value.json"), "w") as f:
            json.dump(value, f)
        return "json"
    if callable(value):
        # module-level functions persist by import path (the way the
        # reference persists model graphs by file reference); anything else
        # (lambdas, closures, bound methods) stays transient
        mod = getattr(value, "__module__", None)
        qual = getattr(value, "__qualname__", "")
        if mod and mod != "__main__" and "." not in qual and "<" not in qual:
            try:
                import importlib
                if getattr(importlib.import_module(mod), qual, None) is value:
                    with open(os.path.join(path, "callable_ref.json"), "w") as f:
                        json.dump({"module": mod, "qualname": qual}, f)
                    return "callable_ref"
            except ImportError:
                pass
        return "transient"
    raise TypeError(f"cannot serialize complex value of type {type(value).__name__}")


def load_value(tag: str, path: str) -> Any:
    if tag == "stage":
        return load_stage(os.path.join(path, "stage"))
    if tag == "stage_list":
        with open(os.path.join(path, "count.json")) as f:
            n = json.load(f)
        return [load_stage(os.path.join(path, f"stage_{i:04d}")) for i in range(n)]
    if tag == "ndarray":
        with np.load(os.path.join(path, "array.npz"), allow_pickle=False) as z:
            return z["value"]
    if tag == "objarray":
        with open(os.path.join(path, "objarray.json")) as f:
            return _obj_array_from_json(json.load(f))
    if tag == "bytes":
        with open(os.path.join(path, "value.bin"), "rb") as f:
            return f.read()
    if tag == "pytree":
        with np.load(os.path.join(path, "tree.npz"), allow_pickle=False) as z:
            leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
        with open(os.path.join(path, "treedef.json")) as f:
            treedef = json.load(f)
        return _unflatten_tree(treedef, leaves)
    if tag == "json":
        with open(os.path.join(path, "value.json")) as f:
            return json.load(f)
    if tag == "callable_ref":
        import importlib
        with open(os.path.join(path, "callable_ref.json")) as f:
            ref = json.load(f)
        fn = getattr(importlib.import_module(ref["module"]), ref["qualname"], None)
        if fn is None:
            raise ImportError(
                f"callable {ref['module']}:{ref['qualname']} saved by "
                f"reference no longer exists")
        return fn
    if tag == "transient":
        return None
    raise ValueError(f"unknown complex-value tag {tag!r}")


# -- minimal pytree codec (dict/list nesting, ndarray/number leaves) --------

def _canon_scalar(v):
    from .schema import py_scalar
    return py_scalar(v)


def _obj_array_to_json(arr: np.ndarray) -> dict:
    """String/object ndarray → {"shape": [...], "values": flat list}.
    Raises TypeError when elements are not JSON-able (fail at SAVE, never
    at load)."""
    flat = [_canon_scalar(v) for v in arr.ravel()]
    payload = {"shape": list(arr.shape), "values": flat,
               "dtype": arr.dtype.str}
    json.dumps(payload)   # TypeError on non-JSON-able elements
    return payload


def _obj_array_from_json(payload: dict) -> np.ndarray:
    out = np.empty(len(payload["values"]), dtype=object)
    for i, v in enumerate(payload["values"]):
        out[i] = v
    out = out.reshape(payload["shape"])
    # restore string ('U'/'S') dtypes so loaded arrays match what was saved
    dt = payload.get("dtype")
    if dt and np.dtype(dt).kind in "US":
        out = out.astype(dt)
    return out


def _try_flatten_tree(value):
    leaves: List[np.ndarray] = []

    def rec(v):
        if isinstance(v, str):
            raise TypeError  # strings are not leaves; JSON path handles them
        if isinstance(v, np.ndarray):
            if v.dtype == object or v.dtype.kind in "US":
                # string/object leaves (e.g. BallTree labels) go inline as
                # JSON — savez would silently pickle them and fail on load
                return {"strs": _obj_array_to_json(v)}
            leaves.append(v)
            return {"leaf": len(leaves) - 1}
        if np.isscalar(v):
            leaves.append(np.asarray(v))
            return {"leaf": len(leaves) - 1, "scalar": True}
        # jax arrays quack like ndarrays
        if hasattr(v, "__array__") and not isinstance(v, (list, tuple, dict, bytes)):
            leaves.append(np.asarray(v))
            return {"leaf": len(leaves) - 1}
        if isinstance(v, dict):
            if not all(isinstance(k, (str, int, float, bool)) for k in v):
                raise TypeError  # non-JSON-able keys cannot round-trip
            # keys stored as json list items so int keys survive round-trip
            return {"dict": [[k, rec(x)] for k, x in sorted(v.items(), key=repr)]}
        if isinstance(v, (list, tuple)):
            node = {"list": [rec(x) for x in v]}
            if isinstance(v, tuple):
                node["tuple"] = True
            return node
        raise TypeError

    try:
        treedef = rec(value)
    except TypeError:
        return None
    return leaves, treedef


def _unflatten_tree(treedef, leaves):
    if "strs" in treedef:
        return _obj_array_from_json(treedef["strs"])
    if "leaf" in treedef:
        arr = leaves[treedef["leaf"]]
        return arr.item() if treedef.get("scalar") else arr
    if "dict" in treedef:
        return {k: _unflatten_tree(v, leaves) for k, v in treedef["dict"]}
    if "list" in treedef:
        seq = [_unflatten_tree(v, leaves) for v in treedef["list"]]
        return tuple(seq) if treedef.get("tuple") else seq
    raise ValueError(f"bad treedef {treedef!r}")


# ---------------------------------------------------------------------------

def save_stage(stage: PipelineStage, path: str, overwrite: bool = True) -> None:
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(path)
    # Serialize into a sibling temp dir first so a mid-save failure cannot
    # destroy an existing good save; swap in atomically at the end.
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    import tempfile
    tmp = tempfile.mkdtemp(prefix=".save_", dir=parent)
    try:
        _save_stage_into(stage, tmp)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if os.path.exists(path):
        shutil.rmtree(path)
    os.replace(tmp, path)


def _save_stage_into(stage: PipelineStage, path: str) -> None:

    simple, complex_tags = {}, {}
    for name in stage._param_values:
        p = stage.param(name)
        v = stage._param_values[name]
        if isinstance(p, ComplexParam):
            tag = save_value(v, os.path.join(path, "complex", name))
            complex_tags[name] = tag
        else:
            simple[name] = p.json_value(v)

    meta = {
        "format_version": _FORMAT_VERSION,
        "class": _class_path(stage),
        "uid": stage.uid,
        "params": simple,
        "complex": complex_tags,
    }
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)

    extra_dir = os.path.join(path, "extra")
    os.makedirs(extra_dir, exist_ok=True)
    stage._save_extra(extra_dir)


def load_stage(path: str) -> PipelineStage:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cls = _resolve_class(meta["class"])
    stage = cls.__new__(cls)
    PipelineStage.__init__(stage)  # fresh uid + empty values
    stage.uid = meta["uid"]
    stage.set(**meta["params"])
    for name, tag in meta["complex"].items():
        if tag == "transient":
            continue  # callable param: must be re-attached by the caller
        stage._param_values[name] = load_value(tag, os.path.join(path, "complex", name))
    stage._load_extra(os.path.join(path, "extra"))
    return stage
