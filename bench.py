"""Headline benchmark: ResNet-50 ONNX inference through DataFrame.transform.

Mirrors BASELINE.json config #1 — the reference runs a ResNet-class ONNX
model through ``ONNXModel.transform`` on onnxruntime (CUDA EP on GPU, CPU EP
in the quickstart). Here the same user-visible pipeline (DataFrame →
minibatch → ONNX graph → output column) executes as an XLA-compiled program
on the local TPU chip. Prints ONE JSON line with images/sec/chip;
``vs_baseline`` is against the 3000 img/s/chip north-star target. Extra keys:
``platform``/``device`` (what actually ran) and ``mfu`` (model FLOPs
utilization, FLOPs taken from XLA cost analysis, peak from the device kind).

The bench must degrade, never crash: if the TPU backend fails to initialize
(transient tunnel errors happen), it falls back to CPU and still reports a
number.
"""

import json
import os
import time

import numpy as np

TARGET_IMG_PER_SEC = 3000.0

# peak bf16 FLOP/s per chip by device_kind substring (public spec sheets)
PEAK_FLOPS = {
    "v6": 918e12,
    "v5p": 459e12,
    "v5": 394e12,      # v5e / "TPU v5 lite"
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def _probe_default_backend(timeout_s: float):
    """Check in a subprocess that the default JAX backend initializes AND
    answers a tiny computation within timeout. Returns (platform, kind) or
    None. A subprocess is the only safe probe: a wedged TPU plugin can hang
    `jax.devices()` forever while holding the backend-init lock."""
    import subprocess
    import sys
    code = ("import jax; d=jax.devices()[0];"
            "x=jax.numpy.ones((8,8));(x@x).block_until_ready();"
            "print(d.platform+'|'+d.device_kind)")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
        if r.returncode == 0 and "|" in r.stdout:
            return tuple(r.stdout.strip().rsplit("|", 1))
    except subprocess.TimeoutExpired:
        pass
    return None


def _init_backend():
    """Return (platform, device_kind); fall back to CPU when the default
    backend is broken or wedged. The bench must always print a number."""
    probe = _probe_default_backend(
        float(os.environ.get("BENCH_BACKEND_PROBE_TIMEOUT", "180")))
    import jax
    if probe is None:
        os.environ.pop("JAX_PLATFORMS", None)
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        d = jax.devices("cpu")[0]
        return d.platform, d.device_kind
    for attempt in range(3):
        try:
            d = jax.devices()[0]
            return d.platform, d.device_kind
        except RuntimeError:
            time.sleep(2.0 * (attempt + 1))
    d = jax.devices("cpu")[0]
    return d.platform, d.device_kind


def _peak_for(device_kind: str):
    kind = device_kind.lower()
    if "tpu" not in kind:
        return None
    for key, peak in PEAK_FLOPS.items():
        if key in kind:
            return peak
    return None


def main():
    platform, device_kind = _init_backend()

    import jax

    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.models.onnx_model import ONNXModel
    from mmlspark_tpu.models.zoo.resnet import RESNET50, export_resnet_onnx

    batch = int(os.environ.get("BENCH_BATCH", "512"))
    n_rows = int(os.environ.get("BENCH_ROWS", "2048"))
    passes = int(os.environ.get("BENCH_PASSES", "3"))
    if platform == "cpu":
        # degraded mode: still report a number, but keep the wall-clock sane
        batch = min(batch, 32)
        n_rows = min(n_rows, 128)
    rng = np.random.default_rng(0)

    model_bytes = export_resnet_onnx(RESNET50, seed=0)
    # The input column holds what an image decoder produces: uint8 HWC.
    # Layout (NHWC→NCHW), dtype cast, and ImageNet normalization all run on
    # device fused into the graph — a uint8 image is 4x smaller than its
    # float32 tensor, and the host→device link is the bottleneck.
    m = ONNXModel(model_bytes,
                  feed_dict={"input": "image"},
                  fetch_dict={"logits": "logits"},
                  argmax_dict={"pred": "logits"},
                  transpose_dict={"input": [0, 3, 1, 2]},
                  normalize_dict={"input": {
                      "scale": 1.0 / 255.0,
                      "mean": [0.485, 0.456, 0.406],
                      "std": [0.229, 0.224, 0.225]}},
                  mini_batch_size=batch,
                  compute_dtype="bfloat16")

    X = rng.integers(0, 256, (n_rows, 224, 224, 3), dtype=np.uint8)
    col = np.empty(n_rows, dtype=object)
    for i in range(n_rows):
        col[i] = X[i]
    df = DataFrame({"image": col})

    # warmup: compile + first transfer
    warm = m.transform(df.head(batch))
    assert len(warm) == batch

    # The TPU here sits behind a shared tunnel whose host->device bandwidth
    # swings over time; best-of-N passes measures the framework rather than
    # a congestion spike, and the observed link speed is reported alongside.
    ips = 0.0
    for _ in range(max(1, passes)):
        t0 = time.perf_counter()
        out = m.transform(df)
        elapsed = time.perf_counter() - t0
        assert len(out) == n_rows
        ips = max(ips, n_rows / elapsed)

    import jax
    probe = np.zeros((batch, 224, 224, 3), dtype=np.uint8)
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(probe))
    h2d_gbps = round(probe.nbytes / (time.perf_counter() - t0) / 1e9, 3)

    # Device-resident compute rate: what the chip sustains once inputs are
    # on device — separates the framework from the session's tunnel, whose
    # congestion can swing end-to-end 100x between runs. Fencing is a
    # fetched scalar depending on the LAST dispatched call (in-order device
    # execution fences the earlier ones; block_until_ready is unreliable
    # behind the tunnel).
    device_ips = None
    try:
        import jax.numpy as jnp
        jitted = m._ensure_jitted()
        params = m._params_for_device(None)
        xdev = jax.device_put(X[:batch])
        rows_timed = int(xdev.shape[0])     # may be < batch when BENCH_ROWS is
        tail = jax.jit(lambda c: jnp.sum(c["logits"][0, :2]
                                         .astype(jnp.float32)))
        float(tail(jitted(params, {"input": xdev})))   # compile + warm
        reps = 3 if platform == "cpu" else 20
        t0 = time.perf_counter()
        outs = None
        for _ in range(reps):
            outs = jitted(params, {"input": xdev})
        float(tail(outs))
        device_ips = round(rows_timed * reps / (time.perf_counter() - t0), 2)
    except Exception:
        pass

    # MFU: per-image FLOPs straight from XLA's cost model for the compiled
    # program (not a hand-waved constant), peak from the device spec.
    mfu = None
    device_mfu = None
    try:
        import jax.numpy as jnp
        compiled = m._jitted.lower(
            m._params_for_device(None),
            {"input": jnp.zeros((batch, 224, 224, 3), jnp.uint8)}).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        flops_per_img = float(cost.get("flops", 0.0)) / batch
        peak = _peak_for(device_kind)
        if flops_per_img and peak:
            mfu = round(ips * flops_per_img / peak, 4)
            if device_ips:
                device_mfu = round(device_ips * flops_per_img / peak, 4)
    except Exception:
        mfu = None

    record = {
        "metric": "resnet50_onnx_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips / TARGET_IMG_PER_SEC, 4),
        "platform": platform,
        "device": device_kind,
        "mfu": mfu,
        "device_resident_ips": device_ips,
        "device_mfu": device_mfu,
        "h2d_gbps": h2d_gbps,
    }
    if platform != "tpu":
        record["note"] = ("degraded CPU fallback (TPU backend unavailable "
                          "at run time); measured TPU numbers incl. "
                          "device-resident 11.6K img/s are in BASELINE.md")
    print(json.dumps(record))


if __name__ == "__main__":
    main()
