"""Headline benchmark: ResNet-50 ONNX inference through DataFrame.transform.

Mirrors BASELINE.json config #1 — the reference runs a ResNet-class ONNX
model through ``ONNXModel.transform`` on onnxruntime (CUDA EP on GPU, CPU EP
in the quickstart). Here the same user-visible pipeline (DataFrame →
minibatch → ONNX graph → output column) executes as an XLA-compiled program
on the local TPU chip. Prints ONE JSON line with images/sec/chip;
``vs_baseline`` is against the 3000 img/s/chip north-star target.
"""

import json
import os
import time

import numpy as np

TARGET_IMG_PER_SEC = 3000.0


def main():
    import jax

    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.models.onnx_model import ONNXModel
    from mmlspark_tpu.models.zoo.resnet import RESNET50, export_resnet_onnx

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    n_rows = int(os.environ.get("BENCH_ROWS", "2048"))
    rng = np.random.default_rng(0)

    model_bytes = export_resnet_onnx(RESNET50, seed=0)
    m = ONNXModel(model_bytes,
                  feed_dict={"input": "image"},
                  fetch_dict={"logits": "logits"},
                  mini_batch_size=batch,
                  compute_dtype="bfloat16")

    X = rng.normal(0, 1, (n_rows, 3, 224, 224)).astype(np.float32)
    col = np.empty(n_rows, dtype=object)
    for i in range(n_rows):
        col[i] = X[i]
    df = DataFrame({"image": col})

    # warmup: compile + first transfer
    warm = df.head(batch)
    m.transform(warm)
    jax.block_until_ready(jax.device_put(0))

    t0 = time.perf_counter()
    out = m.transform(df)
    elapsed = time.perf_counter() - t0
    assert len(out) == n_rows
    ips = n_rows / elapsed

    print(json.dumps({
        "metric": "resnet50_onnx_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips / TARGET_IMG_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
