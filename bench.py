"""Headline benchmark: ResNet-50 ONNX inference through DataFrame.transform.

Mirrors BASELINE.json config #1 — the reference runs a ResNet-class ONNX
model through ``ONNXModel.transform`` on onnxruntime (CUDA EP on GPU, CPU EP
in the quickstart). Here the same user-visible pipeline (DataFrame →
minibatch → ONNX graph → output column) executes as an XLA-compiled program
on the local TPU chip. Prints ONE JSON line with images/sec/chip;
``vs_baseline`` is against the 3000 img/s/chip north-star target. Extra keys:
``platform``/``device`` (what actually ran) and ``mfu`` (model FLOPs
utilization, FLOPs taken from XLA cost analysis, peak from the device kind).

The bench must degrade, never crash: if the TPU backend fails to initialize
(transient tunnel errors happen), it falls back to CPU and still reports a
number.
"""

import contextlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

TARGET_IMG_PER_SEC = 3000.0

#: internal wall-clock budget (seconds): the bench must emit its one JSON
#: line before any external `timeout` kills it (campaign logs show rc=124
#: with an empty tail when the timed section overran). A watchdog thread
#: emits whatever has been measured so far and exits 0 at the deadline.
DEFAULT_WALL_BUDGET_S = 540.0


def _partial_path():
    """Where per-phase checkpoints land. ``BENCH_PARTIAL_PATH`` overrides;
    empty string disables; default sits next to this file so the driver
    finds it with the BENCH_r0*.json trajectory."""
    p = os.environ.get("BENCH_PARTIAL_PATH")
    if p is None:
        p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_partial.json")
    return p or None


class _OneShotReport:
    """The bench's single JSON line, emittable exactly once from any thread.

    The main path fills ``record`` in place as results land and emits at the
    end; the budget watchdog emits the partial record at the deadline. The
    lock guarantees the driver never sees zero or two lines.

    ``checkpoint`` additionally persists the record-so-far to
    ``_partial_path()`` after every completed phase (tmp + atomic rename):
    the SIGTERM handlers cannot outrun ``timeout -k``'s follow-up SIGKILL
    (BENCH_r05.json: rc=124, empty tail, every completed phase lost), but
    a file already on disk survives any kill.
    """

    def __init__(self, record: dict, path=None):
        self.record = record
        self.path = path
        self._phases = []
        self._lock = threading.Lock()
        self._emitted = False

    def _write_file(self, payload: str) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
            os.replace(tmp, self.path)  # atomic: never a torn partial
        except OSError:
            pass                        # checkpointing must never kill a run

    def checkpoint(self, phase: str) -> None:
        """Persist the record after ``phase`` completed (atomic rename)."""
        with self._lock:
            if self._emitted:
                return
            self._phases.append(phase)
            snap = dict(self.record)
            snap["partial"] = {"complete": False,
                               "phases_done": list(self._phases)}
            payload = json.dumps(snap, default=str)
        self._write_file(payload)

    def emit(self) -> bool:
        with self._lock:
            if self._emitted:
                return False
            self._emitted = True
            self.record["partial"] = {"complete": True,
                                      "phases_done": list(self._phases)}
        payload = json.dumps(self.record, default=str)
        sys.stdout.write(payload + "\n")
        sys.stdout.flush()
        self._write_file(payload)
        return True

class _PhaseTimeout(BaseException):
    """Raised in the main thread by the SIGALRM phase guard. Inherits
    BaseException so the per-pass ``except Exception`` blocks cannot
    swallow it and mislabel a phase deadline as a pass failure."""


def _bench_costs(harvest=False):
    """Cost-attribution sub-record from the process-global CostLedger:
    per-class resource totals (device-seconds, transfer bytes, KV page
    holds), the heavy-hitter table size and its top entry, and — on the
    emit paths — how many rows landed in the tuning ObservationStore.
    Refreshed on EVERY exit path, including the atomic per-phase partial
    checkpoints, so a SIGKILLed run still reports where its device time
    went (docs/observability.md, "Cost attribution")."""
    try:
        from mmlspark_tpu.observability.ledger import get_ledger
        snap = get_ledger().snapshot()
        out = {"classes": snap["classes"],
               "weights": snap["weights"],
               "top_k": snap["top_k"],
               "heavy_hitters": len(snap["heavy_hitters"])}
        if snap["heavy_hitters"]:
            out["top_hitter"] = snap["heavy_hitters"][0]
        if harvest:
            from mmlspark_tpu.tuning.observations import harvest_costs
            out["harvested_observations"] = harvest_costs(snap)
        return out
    except Exception:                   # noqa: BLE001
        return None


_MULTI_MODEL_DRILL: dict = {}


def _multi_model_drill() -> dict:
    """Deterministic in-process drill of the multi-model traffic plane
    (docs/guide.md, "Multi-model serving and tenant fairness"): no
    sockets, no sleeps — measures the three headline properties directly
    against the primitives the worker server composes."""
    import types as _types

    from mmlspark_tpu.observability import get_tracker
    from mmlspark_tpu.serving.admission import (AdmissionQueue,
                                                ConsistentHashRing)
    from mmlspark_tpu.serving.registry import ModelRegistry

    # (a) weighted-fair goodput shares under standing backlog: with
    # weights 3/2/1 the first 24 DRR dequeues must split 12/8/4
    weights = {"acme": 3.0, "beta": 2.0, "gamma": 1.0}
    q = AdmissionQueue(weight_fn=lambda t: weights.get(t, 1.0))
    for _ in range(12):
        for t in weights:
            q.put_nowait(_types.SimpleNamespace(tenant=t))
    drained = [q.get_nowait().tenant for _ in range(24)]
    shares = {t: round(drained.count(t) / 24, 4) for t in weights}

    # (b) prefix-affinity retention across one membership change: the
    # ring moves ~1/n of the keyspace where hash(key) % n moves ~(n-1)/n
    ring = ConsistentHashRing()
    ring.rebuild(["w0", "w1", "w2"])
    keys = [f"prefix-{i:03d}" for i in range(200)]
    before = {k: ring.route(k) for k in keys}
    ring.rebuild(["w0", "w1", "w2", "w3"])
    kept = sum(before[k] == ring.route(k) for k in keys)
    hit_rate = round(kept / len(keys), 4)

    # (c) canary auto-rollback: a local registry (the process-global one
    # stays untouched) with a breaching canary window must roll back
    reg = ModelRegistry(min_requests=5, check_every=1)
    reg.load("bench-canary", "v1", handle=lambda df: df)
    reg.load("bench-canary", "v2", handle=lambda df: df, canary_percent=50)
    tracker = get_tracker()
    for _ in range(8):
        tracker.observe(transport="bench", route="api",
                        model="bench-canary@v1", seconds=0.01, error=False)
        tracker.observe(transport="bench", route="api",
                        model="bench-canary@v2", seconds=0.01, error=True)
    verdicts = reg.check_canaries()
    rollbacks = sum(1 for v in verdicts if v.get("breach"))
    states = {v.label: v.state for v in reg.versions("bench-canary")}
    reg.reset()
    return {"goodput_shares": shares,
            "goodput_shares_expected": {"acme": 0.5, "beta": round(1 / 3, 4),
                                        "gamma": round(1 / 6, 4)},
            "ring_hit_rate_after_member_join": hit_rate,
            "canary_rollbacks": rollbacks,
            "canary_states_after_drill": states}


def _bench_multi_model():
    """Multi-model traffic-plane sub-record: the cached one-shot drill
    above plus the live registry/WFQ/ring counters, re-read on EVERY
    exit path (like the cost sub-record) so partial checkpoints still
    carry the traffic plane's state."""
    try:
        if not _MULTI_MODEL_DRILL:
            _MULTI_MODEL_DRILL.update(_multi_model_drill())
        out: dict = {"drill": dict(_MULTI_MODEL_DRILL)}
    except Exception as e:              # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        from mmlspark_tpu.observability import snapshot
        snap = snapshot()

        def _series(name):
            return (snap.get(name) or {}).get("series") or []

        def _total(name):
            return sum(s.get("value", 0) for s in _series(name))

        deq = {s["labels"].get("tenant", "?"): s["value"]
               for s in _series("mmlspark_wfq_dequeued_total")}
        total_deq = sum(deq.values())
        routes = {s["labels"].get("outcome", "?"): s["value"]
                  for s in _series("mmlspark_ring_routes_total")}
        total_routes = sum(routes.values())
        out["counters"] = {
            "wfq_dequeued": total_deq,
            "wfq_goodput_shares": (
                {t: round(v / total_deq, 4) for t, v in sorted(deq.items())}
                if total_deq else {}),
            "wfq_shed": _total("mmlspark_wfq_shed_total"),
            "canary_rollbacks": _total("mmlspark_registry_rollbacks_total"),
            "ring_rebuilds": _total("mmlspark_ring_rebuilds_total"),
            "ring_affine_route_rate": (
                round(routes.get("affine", 0) / total_routes, 4)
                if total_routes else None),
        }
    except Exception:                   # noqa: BLE001
        pass
    return out


@contextlib.contextmanager
def _phase_guard(record: dict, name: str, seconds: float, report=None):
    """Per-phase wall-clock guard: arm SIGALRM so a stuck phase raises in
    the MAIN thread at its deadline and is skipped (named in the record)
    instead of dragging the whole bench into the external timeout — the
    BENCH_r05 failure mode was one overrunning section eating every later
    phase AND the JSON emit. No-ops off the main thread (signals only
    deliver there) and for non-positive budgets. When ``report`` is given,
    the record-so-far is checkpointed to disk as the phase ends — timed
    out or not — so a later SIGKILL cannot erase it."""
    def _observe_phase(elapsed: float, timed_out: bool) -> None:
        # per-phase SLO sample: bench phases land in the same scorecard
        # machinery the serving plane uses (transport="bench", route=phase),
        # so the emitted record's "slo" block carries phase p99s/timeouts
        try:
            from mmlspark_tpu.observability import get_tracker
            get_tracker().observe(transport="bench", route=name,
                                  seconds=elapsed, error=timed_out)
        except Exception:               # noqa: BLE001
            pass
        # keep the checkpoint's cost attribution as fresh as its phases
        # (harvest only on the emit paths — not once per checkpoint)
        record["costs"] = _bench_costs()
        record["multi_model"] = _bench_multi_model()

    if (seconds <= 0
            or threading.current_thread() is not threading.main_thread()):
        t0 = time.perf_counter()
        yield
        _observe_phase(time.perf_counter() - t0, False)
        if report is not None:
            report.checkpoint(name)
        return

    def _on_alarm(signum, frame):
        raise _PhaseTimeout(name)

    prev = signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(max(1, int(seconds)))
    t0 = time.perf_counter()
    timed_out = False
    try:
        yield
    except _PhaseTimeout:
        timed_out = True
        record.setdefault("phase_timeouts", []).append(name)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, prev)
        _observe_phase(time.perf_counter() - t0, timed_out)
        if report is not None:
            report.checkpoint(name)


def _install_signal_handlers(report: "_OneShotReport", fill_partial):
    """SIGTERM/SIGALRM → emit the partial record, then exit 0.

    An external ``timeout`` sends SIGTERM before SIGKILL; without this the
    run's completed phases are lost (campaign log BENCH_r05.json: rc=124,
    empty tail). SIGALRM lands here only when no phase guard is armed —
    same response. ``fill_partial`` folds the counters measured so far
    into the record before the emit."""
    def _on_signal(signum, frame):
        name = signal.Signals(signum).name
        report.record["signal"] = name
        report.record.setdefault(
            "midrun_error",
            f"killed by {name}; partial record with completed phases")
        try:
            fill_partial()
        except Exception:               # noqa: BLE001
            pass
        report.emit()
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGALRM, _on_signal)


# peak bf16 FLOP/s per chip by device_kind substring (public spec sheets)
PEAK_FLOPS = {
    "v6": 918e12,
    "v5p": 459e12,
    "v5": 394e12,      # v5e / "TPU v5 lite"
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}

_PROBE_CHILD = r"""
import os, sys, time
out = sys.argv[1]
t0 = time.time()
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.ones((128, 128))
val = float(jnp.sum(x @ x))          # fetched scalar = the only real fence
assert val == 128.0 ** 3             # ones(128,128) @ ones(128,128) sums to n^3
tmp = out + ".tmp"
with open(tmp, "w") as fh:
    fh.write("%s|%s|%.1f" % (d.platform, d.device_kind, time.time() - t0))
os.replace(tmp, out)                  # atomic: parent never sees a torn file
"""


def _probe_default_backend(window_s: float):
    """Probe the default backend in a child that writes a result file and
    exits ON ITS OWN. Returns (platform, device_kind, probe_info).

    The child is NEVER killed: a SIGKILLed process holding the TPU claim
    wedges the chip for hours (BASELINE.md postmortem — the previous
    ``subprocess.run(timeout=...)`` probe was itself a wedge mechanism).
    On a hang the child is abandoned to finish whenever the tunnel recovers
    and we fall back to CPU; on a crash (tunnel error) we retry over a
    multi-minute window matched to the documented tunnel swings."""
    info = {"attempts": 0, "window_s": window_s, "reason": None}
    deadline = time.monotonic() + window_s
    result_dir = tempfile.mkdtemp(prefix="bench_probe_")
    attempt = 0
    while deadline - time.monotonic() > 2.0:    # no point spawning an
        attempt += 1                            # attempt with no time left
        info["attempts"] = attempt
        out = os.path.join(result_dir, f"probe_{attempt}")
        # stderr goes to a FILE, not a pipe: an undrained pipe can block a
        # chatty plugin init, and an abandoned child would crash with
        # BrokenPipeError — while holding the TPU claim — once the parent's
        # pipe end is gc'd. A file stays writable after the parent exits.
        errpath = out + ".stderr"
        with open(errpath, "w") as errfh:
            child = subprocess.Popen(
                [sys.executable, "-c", _PROBE_CHILD, out],
                stdout=subprocess.DEVNULL, stderr=errfh, text=True)
        def _success():
            # claim release: wait (bounded) for the child's own exit so
            # the parent's backend init doesn't race the claim
            for _ in range(120):
                if child.poll() is not None:
                    break
                time.sleep(0.5)
            with open(out) as fh:
                platform, kind, elapsed = fh.read().split("|")
            info["init_s"] = float(elapsed)
            info["reason"] = None   # earlier failed attempts don't make a
            #                         successful probe look degraded
            return platform, kind, info

        def _stderr_tail():
            try:
                with open(errpath) as fh:
                    return fh.read()[-500:]
            except OSError:
                return ""

        while time.monotonic() < deadline:
            if os.path.exists(out):
                return _success()
            if child.poll() is not None:
                if os.path.exists(out):
                    # wrote-then-exited between the two checks — handle
                    # inline: re-entering the loop could hit an expired
                    # deadline and misreport the success as a hang
                    return _success()
                # crashed — retry after a pause
                info["reason"] = f"probe exited rc={child.returncode}: " \
                                 f"{_stderr_tail()}"
                time.sleep(min(30.0, 5.0 * attempt))
                break
            time.sleep(1.0)
        else:
            # window expired mid-attempt: one last poll so a crash that
            # raced the deadline keeps its diagnostic instead of being
            # mislabeled as a hang (exists re-checked after poll — the
            # wrote-then-exited race, same as the inner loop)
            if os.path.exists(out):
                return _success()
            if child.poll() is not None:
                if os.path.exists(out):
                    return _success()
                info["reason"] = (f"probe exited rc={child.returncode} at "
                                  f"window end: {_stderr_tail()}")
            else:
                info["reason"] = (
                    f"probe hung past the {window_s:.0f}s window; "
                    "child left to exit on its own (never killed)")
            return None, None, info
    if info["reason"] is None:
        info["reason"] = f"window {window_s:.0f}s exhausted"
    return None, None, info


def _init_backend(window_cap=None):
    """Return (platform, device_kind, probe_info); fall back to CPU when the
    default backend is broken or wedged. The bench must always print a
    number, and the JSON must say WHY a fallback happened. ``window_cap``
    bounds the probe window so it cannot eat the whole wall-clock budget."""
    window = float(os.environ.get(
        "BENCH_PROBE_WINDOW",
        os.environ.get("BENCH_BACKEND_PROBE_TIMEOUT", "600")))
    if window_cap is not None:
        window = min(window, max(10.0, float(window_cap)))
    platform, kind, info = _probe_default_backend(window)
    if platform is None:
        # config.update (not env): setting JAX_PLATFORMS=cpu via env hangs
        # under this image's plugin discovery
        os.environ.pop("JAX_PLATFORMS", None)
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        d = jax.devices("cpu")[0]
        return d.platform, d.device_kind, info
    import jax
    for attempt in range(3):
        try:
            d = jax.devices()[0]
            return d.platform, d.device_kind, info
        except RuntimeError as e:
            info["reason"] = f"parent backend init failed: {e}"
            time.sleep(2.0 * (attempt + 1))
    os.environ.pop("JAX_PLATFORMS", None)
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    d = jax.devices("cpu")[0]
    return d.platform, d.device_kind, info


def _looks_tpu(platform: str, device_kind: str) -> bool:
    # pure-string helper from the library (no backend init in this process)
    from mmlspark_tpu.utils.device import looks_tpu
    return looks_tpu(platform, device_kind)


def _peak_for(platform: str, device_kind: str):
    from mmlspark_tpu.utils.device import generation_from_kind
    if not _looks_tpu(platform, device_kind):
        return None
    return PEAK_FLOPS.get(generation_from_kind(device_kind))


def _generation_phase(on_tpu: bool) -> dict:
    """Continuous-decoding throughput through the paged-KV engine.

    Mixed prompt lengths (short, medium, and one longer than the prefill
    chunk budget) plus a shared-prefix cohort drive the whole scheduler:
    chunked prefill interleaves with decode ticks, prefix pages are CoW-
    shared, and the autotuner walks gamma/chunk from live occupancy and
    acceptance. Reports tok/s (the >4,265 target on real TPU hardware),
    p50/p99 decode-step latency, the prefix-page share rate, and the
    gamma trajectory — the numbers ROADMAP item 3 exists to move."""
    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     init_transformer)
    from mmlspark_tpu.serving.continuous import ContinuousDecoder
    if on_tpu:
        cfg = TransformerConfig(vocab=8192, d_model=512, heads=8,
                                layers=8, d_ff=2048, max_len=1024,
                                causal=True)
        d_cfg = TransformerConfig(vocab=8192, d_model=128, heads=4,
                                  layers=2, d_ff=512, max_len=1024,
                                  causal=True)
        slots, max_new, chunk, n_reqs = 16, 64, 256, 48
        lens = (24, 96, 384)
    else:
        # tiny deterministic config: the phase must finish in seconds on
        # the CPU fallback — the POINT there is exercising the scheduler
        # end-to-end, not the absolute number
        cfg = TransformerConfig(vocab=211, d_model=64, heads=4,
                                layers=2, d_ff=128, max_len=192,
                                causal=True)
        d_cfg = TransformerConfig(vocab=211, d_model=32, heads=2,
                                  layers=1, d_ff=64, max_len=192,
                                  causal=True)
        slots, max_new, chunk, n_reqs = 4, 12, 32, 10
        lens = (6, 20, 48)
    params = init_transformer(cfg, 0)
    d_params = init_transformer(d_cfg, 1)
    eng = ContinuousDecoder(params, cfg, max_slots=slots,
                            max_len=cfg.max_len, draft_params=d_params,
                            draft_cfg=d_cfg, gamma=2,
                            page_size=16, prefill_chunk=chunk,
                            autotune=True)
    rng = np.random.default_rng(0)
    sys_prompt = rng.integers(1, cfg.vocab, lens[1], dtype=np.int32)

    def _drain():
        while any(r is not None for r in eng._slot_req) or eng._waiting:
            eng.step()

    # warm every program shape OUTSIDE the timed section (one request per
    # prompt-length bucket, incl. a chunked one and a prefix pair)
    warm = [eng.submit(rng.integers(1, cfg.vocab, n, dtype=np.int32),
                       max_new_tokens=4) for n in lens]
    warm.append(eng.submit(sys_prompt, max_new_tokens=4,
                           prefix_key="bench-sys"))
    warm.append(eng.submit(
        np.concatenate([sys_prompt,
                        rng.integers(1, cfg.vocab, 4, dtype=np.int32)]),
        max_new_tokens=4, prefix_key="bench-sys"))
    _drain()
    # NOTE: an autotuner gamma change mid-run compiles that gamma's tick
    # once; on a cold compile cache that lands in the latency tail (the
    # max, usually the p99 too on short runs). decode_step_p50_ms is the
    # steady-state number; the trajectory fields say when gamma moved.
    share_before = eng._kv.stats["prefix_share_hits"]

    reqs = []
    for i in range(n_reqs):
        if i % 3 == 2:          # shared-prefix cohort
            ids = np.concatenate([
                sys_prompt, rng.integers(1, cfg.vocab, 4, dtype=np.int32)])
            reqs.append(eng.submit(ids, max_new_tokens=max_new,
                                   prefix_key="bench-sys"))
        else:
            n = lens[i % 2] if i % 6 else lens[2]   # every 6th is chunked
            reqs.append(eng.submit(
                rng.integers(1, cfg.vocab, n, dtype=np.int32),
                max_new_tokens=max_new))
    step_s = []
    t0 = time.perf_counter()
    # one watch over the whole decode loop, heartbeat per engine tick: the
    # stall budget bounds ONE step, so a wedged device call mid-generation
    # produces a diagnostic bundle instead of a silent external timeout
    from mmlspark_tpu.observability import watch as _wd_watch
    from mmlspark_tpu.observability.timeseries import get_store as _ts_store
    _history = _ts_store()
    with _wd_watch("bench_generation") as _w:
        while any(r is not None for r in eng._slot_req) or eng._waiting:
            s0 = time.perf_counter()
            eng.step()
            _w.beat()
            step = time.perf_counter() - s0
            step_s.append(step)
            # per-tick history: the embedded timeline shows step latency
            # over the run (warmup spike, steady state), not just the
            # batch quantiles below
            _history.record("bench_decode_step_ms", step * 1e3)
    elapsed = time.perf_counter() - t0
    toks = sum(len(r.tokens) for r in reqs)
    lat = np.sort(np.asarray(step_s))
    pool = eng._kv
    shared = pool.stats["prefix_share_hits"] - share_before
    n_prefix = sum(1 for i in range(n_reqs) if i % 3 == 2)
    out = {
        "tok_per_sec": round(toks / elapsed, 2),
        "mesh_shape": "single",
        # send-wait-send latency regime: never compare these quantiles
        # with the scenarios phase's open-loop (CO-corrected) numbers
        "loop_mode": "closed",
        "tokens": toks, "requests": n_reqs, "wall_s": round(elapsed, 3),
        "decode_step_p50_ms": round(float(lat[len(lat) // 2]) * 1e3, 3),
        "decode_step_p99_ms": round(
            float(lat[min(len(lat) - 1, int(len(lat) * 0.99))]) * 1e3, 3),
        "decode_step_max_ms": round(float(lat[-1]) * 1e3, 3),
        "steps": len(step_s),
        "prefix_share_hits": int(shared),
        # pages a prefix-cohort request reused instead of recomputing,
        # per request — the CoW payoff the pool exists for
        "prefix_pages_shared_per_hit": (
            round(shared / n_prefix, 2) if n_prefix else None),
        "kvpool": {"pages_total": pool.num_pages - 1,
                   "high_water": pool.high_water,
                   "defrag_moves": pool.stats["defrag_moves"],
                   "prefill_chunks": pool.stats["prefill_chunks"]},
        # which paged-attention impl decoded, and what the kernel saved:
        # the gather fallback materializes a contiguous K/V copy per paged
        # call — hbm_bytes_saved_per_step is that per-engine-tick traffic
        # the Pallas kernel never moves (0 when gather actually ran,
        # since nothing was saved)
        "paged_attn": {
            "impl": eng._attn_impl,
            "kv_dtype": eng._kv_dtype,
            "ticks_kernel": pool.stats.get("attn_ticks_kernel", 0),
            "ticks_gather": pool.stats.get("attn_ticks_gather", 0),
            "gather_bytes_total": pool.stats.get("gather_bytes", 0),
            "hbm_bytes_saved_per_step": (
                eng._k * eng._gather_bytes_tick
                if eng._attn_impl == "kernel" else 0)},
        "gamma_trajectory": [h for h in (eng._tuner.history
                                         if eng._tuner else [])
                             if h["knob"] == "gamma"],
        "chunk_trajectory": [h for h in (eng._tuner.history
                                         if eng._tuner else [])
                             if h["knob"] == "chunk"],
        "engine_stats": dict(eng.stats),
        # time-resolved view of the same run: per-bucket min/max/mean of
        # the step latency series recorded in the loop above, so a spike
        # mid-run is visible even though the quantiles flatten it
        "timeseries": _history.snapshot(max(elapsed + 5.0, 30.0),
                                        names=["bench_decode_step_ms"]),
    }
    out["quantized"] = _quantized_generation_pass(cfg, params)
    return out


def _quantized_generation_pass(cfg, params) -> dict:
    """One int8-KV pass through the same engine: the quantized data plane's
    realized savings, counter-asserted from the pool's own byte accounting.

    ``hbm_bytes_saved_per_step`` is what a decode tick stopped reading from
    HBM versus the bf16 layout at identical geometry (the >=1.9x acceptance
    number at hd=64); ``contexts_held_at_budget`` is how many max_len
    contexts the SAME page-budget bytes now hold. ``kv_quant_error_*`` is
    the dequant-oracle relative RMS the SLO canary watches."""
    import jax.numpy as jnp
    from mmlspark_tpu.ops.kv_quant import kv_bytes_per_position
    from mmlspark_tpu.serving.continuous import ContinuousDecoder
    eng = ContinuousDecoder(params, cfg, max_slots=4, max_len=min(
        cfg.max_len, 96), page_size=16, kv_dtype="int8", quant_probe=1)
    rng = np.random.default_rng(7)
    reqs = [eng.submit(rng.integers(1, cfg.vocab, 6 + 5 * i,
                                    dtype=np.int32), max_new_tokens=8)
            for i in range(4)]
    t0 = time.perf_counter()
    steps = 0
    while any(r is not None for r in eng._slot_req) or eng._waiting:
        eng.step()
        steps += 1
    elapsed = time.perf_counter() - t0
    toks = sum(len(r.tokens) for r in reqs)
    pool = eng._kv
    hd = cfg.d_model // cfg.heads
    bf16_pos = cfg.layers * kv_bytes_per_position(
        cfg.heads, hd, jnp.bfloat16, False)
    quant_pos = pool.bytes_per_position()
    bf16_tick = eng._S * eng._Lc * bf16_pos
    stats = pool.stats
    probes = stats["quant_error_probes"]
    return {
        "kv_dtype": eng._kv_dtype,
        "tok_per_sec": round(toks / elapsed, 2) if elapsed > 0 else None,
        "tokens": toks, "steps": steps,
        "kv_bytes_per_position": quant_pos,
        "kv_bytes_per_position_bf16": bf16_pos,
        "hbm_bytes_per_tick": eng._gather_bytes_tick,
        "hbm_bytes_saved_per_step": bf16_tick - eng._gather_bytes_tick,
        "hbm_bytes_ratio_vs_bf16": round(bf16_pos / quant_pos, 4),
        "bytes_per_token": round(
            steps * eng._gather_bytes_tick / max(1, toks), 1),
        # fixed byte budget = the bf16 pool's device footprint; the
        # quantized layout packs this many more max_len contexts in it
        "contexts_held_at_budget": {
            "budget_bytes": pool.num_pages * eng._page * bf16_pos,
            "bf16": pool.num_pages * eng._page * bf16_pos
            // max(1, eng._L * bf16_pos),
            "quantized": pool.num_pages * eng._page * bf16_pos
            // max(1, eng._L * quant_pos)},
        "kv_quant_error_probes": probes,
        "kv_quant_error_mean": (
            round(stats["quant_error_sum"] / probes, 6) if probes else None),
        "kv_quant_error_max": (
            round(stats["quant_error_max"], 6) if probes else None),
    }


def _failover_phase() -> dict:
    """Session-failover sub-record: checkpoint a live mid-decode session
    on engine A, restore it on engine B both cold (journal-style
    re-prefill of prompt+emitted) and warm (KV page-blob adoption), and
    time each handoff. ``*_parity`` must be True — both paths are
    token-identical to the uninterrupted run by construction; the numbers
    this phase exists for are ``warm_adopt_ms`` vs ``cold_restore_ms``
    (what a graceful drain saves over a kill) and ``blob_bytes`` (what
    the warm path costs on the wire)."""
    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     init_transformer)
    from mmlspark_tpu.serving.continuous import ContinuousDecoder
    cfg = TransformerConfig(vocab=128, d_model=64, heads=4, layers=2,
                            d_ff=128, max_len=64, causal=True)
    params = init_transformer(cfg, 0)
    prompt = np.arange(5, 13, dtype=np.int32)
    max_new = 16

    def _drain(eng, req):
        while not req.done:
            eng.step()
        return eng.session_result(req)

    base = ContinuousDecoder(params, cfg, max_slots=2, max_len=64,
                             page_size=8)
    want = _drain(base, base.submit(prompt, max_new))
    src = ContinuousDecoder(params, cfg, max_slots=2, max_len=64,
                            page_size=8)
    live = src.submit(prompt, max_new)
    for _ in range(6):                  # genuinely mid-decode
        src.step()
    ckpt = src.checkpoint_session(live)
    blob_bytes = (sum(len(e[k]) for e in ckpt["kv"]["data"] for k in e)
                  if ckpt["kv"] else 0)
    cold_eng = ContinuousDecoder(params, cfg, max_slots=2, max_len=64,
                                 page_size=8)
    warm_eng = ContinuousDecoder(params, cfg, max_slots=2, max_len=64,
                                 page_size=8)
    # prime both engines' compiled programs so the timings below measure
    # the handoff, not first-touch compilation
    for e in (cold_eng, warm_eng):
        _drain(e, e.submit(prompt, 2))
    t0 = time.perf_counter()
    cold_req = cold_eng.restore_session(ckpt["session"])
    while not cold_req.tokens and not cold_req.done:
        cold_eng.step()                 # includes the re-prefill
    cold_ms = (time.perf_counter() - t0) * 1e3
    cold = cold_eng.session_result(cold_req) if cold_req.done else \
        _drain(cold_eng, cold_req)
    t0 = time.perf_counter()
    warm_req = warm_eng.restore_session(ckpt["session"],
                                        kv_blob=ckpt["kv"])
    while not warm_req.tokens and not warm_req.done:
        warm_eng.step()                 # first token off adopted pages
    warm_ms = (time.perf_counter() - t0) * 1e3
    warm = warm_eng.session_result(warm_req) if warm_req.done else \
        _drain(warm_eng, warm_req)
    return {
        "emitted_at_checkpoint": len(ckpt["session"]["emitted"]),
        "blob_bytes": blob_bytes,
        "cold_restore_ms": round(cold_ms, 3),
        "warm_adopt_ms": round(warm_ms, 3),
        # prefill count past the priming request — 0 proves the warm
        # path re-prefilled nothing
        "warm_reprefills": warm_eng.stats["prefills"] - 1,
        "cold_parity": cold == want,
        "warm_parity": warm == want,
    }


def _multichip_generation_phase(mesh=None) -> dict:
    """Mesh-sharded decode: the same paged-KV engine run once single-chip
    and once shard_map-mounted on ``mesh`` (default: a dp×tp mesh over
    every visible device — dp4×tp2 on 8), with the SAME greedy workload,
    so the record carries tok/s vs chips, scaling efficiency against the
    single-chip rate, and a per-tick collective-time estimate (mesh step
    p50 minus single-chip step p50 — what the ICI adds to a tick). On
    simulated CPU devices the absolute numbers mean nothing; the phase
    exists so real-mesh runs land these fields in the trajectory and so
    the dryrun counter-asserts the kernel actually ran sharded."""
    import jax
    from jax.sharding import Mesh
    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     init_transformer)
    from mmlspark_tpu.parallel.mesh import mesh_shape
    from mmlspark_tpu.serving.continuous import ContinuousDecoder
    if mesh is None:
        devs = jax.devices()
        n = len(devs)
        tp = 2 if (n % 2 == 0 and n >= 2) else 1
        dp = max(1, n // tp)
        mesh = Mesh(np.array(devs[:dp * tp]).reshape(dp, tp),
                    ("dp", "tp"))
    chips = int(mesh.devices.size)
    # vocab/heads/d_ff all divisible by tp — the Megatron shardings split
    # lm_head on the vocab axis, so the tiny config must tile cleanly
    cfg = TransformerConfig(vocab=256, d_model=64, heads=4, layers=2,
                            d_ff=128, max_len=96, causal=True)
    params = init_transformer(cfg, 0)
    dp = mesh.shape.get("dp", 1)
    slots = max(4, int(dp))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab, 6 + (i % 3) * 7, dtype=np.int32)
               for i in range(2 * slots)]

    def _run(m, kv_dtype=None):
        eng = ContinuousDecoder(params, cfg, max_slots=slots, max_len=64,
                                mesh=m, page_size=8, kv_dtype=kv_dtype,
                                quant_probe=1 if kv_dtype else 0)
        warm = [eng.submit(p, max_new_tokens=2) for p in prompts[:3]]
        while any(r is not None for r in eng._slot_req) or eng._waiting:
            eng.step()
        reqs = [eng.submit(p, max_new_tokens=12) for p in prompts]
        step_s = []
        t0 = time.perf_counter()
        while any(r is not None for r in eng._slot_req) or eng._waiting:
            s0 = time.perf_counter()
            eng.step()
            step_s.append(time.perf_counter() - s0)
        elapsed = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in reqs)
        p50 = float(np.sort(np.asarray(step_s))[len(step_s) // 2])
        return (toks / elapsed, toks, elapsed, p50,
                [list(r.tokens) for r in reqs], eng)

    tps_1, _, _, p50_1, toks_1, _ = _run(None)
    tps_m, toks, wall, p50_m, toks_m, eng = _run(mesh)
    # one quantized pass through the SAME mesh mount: the sharded int8
    # data plane (scale pools ride P(None, tp, None)) must decode the
    # same workload; token parity vs the quantized single-chip run is
    # the dryrun counter-assert that the sharded dequant kernel ran
    _, _, _, _, toks_q1, _ = _run(None, kv_dtype="int8")
    tps_q, toks_qn, _, _, toks_qm, eng_q = _run(mesh, kv_dtype="int8")
    pool = eng._kv
    return {
        "mesh_shape": mesh_shape(mesh), "chips": chips,
        "tok_per_sec": round(tps_m, 2), "tokens": toks,
        "wall_s": round(wall, 3),
        "tok_per_sec_single_chip": round(tps_1, 2),
        # fixed workload: ideal scaling is chips × the single-chip rate
        "scaling_efficiency": round(tps_m / (tps_1 * chips), 4)
        if tps_1 > 0 else None,
        "collective_ms_per_tick_est": round(
            max(0.0, p50_m - p50_1) * 1e3, 3),
        "token_parity_vs_single_chip": toks_m == toks_1,
        "paged_attn": {
            "impl": eng._attn_impl,
            "kv_dtype": eng._kv_dtype,
            "ticks_kernel": pool.stats.get("attn_ticks_kernel", 0),
            "ticks_gather": pool.stats.get("attn_ticks_gather", 0),
            "gather_bytes_total": pool.stats.get("gather_bytes", 0)},
        "quantized": {
            "kv_dtype": eng_q._kv_dtype,
            "tok_per_sec": round(tps_q, 2), "tokens": toks_qn,
            "hbm_bytes_per_tick": eng_q._gather_bytes_tick,
            # int8 rounding amplifies the tp psum reduction-order ulps,
            # so mesh-vs-single parity is asserted over a short horizon;
            # drift past it is accumulation, not a data-plane bug (the
            # written pages themselves are bit-identical per write)
            "token_parity_horizon": 4,
            "token_parity_vs_single_chip": (
                [t[:4] for t in toks_qm] == [t[:4] for t in toks_q1]),
            "kv_quant_error_probes":
                eng_q._kv.stats["quant_error_probes"],
            "kv_quant_error_last":
                eng_q._kv.stats["quant_error_last"]},
    }


def _scenarios_phase(record: dict) -> dict:
    """Open-loop scenario sub-record (ROADMAP item 5): run the seeded
    ``smoke`` scenario from ``mmlspark_tpu.loadgen`` against a live
    3-worker ServingCluster and report its scorecard — the only latency
    numbers in BENCH measured from *scheduled* send time
    (``loop_mode: "open"``), next to the generation phase's closed-loop
    quantiles. The harvest lands ``slo_scorecard``/``cost_ledger`` rows
    in the ObservationStore, so the tuning phase that follows sees
    traffic-shaped observations from the same run."""
    import threading as _threading

    from mmlspark_tpu.loadgen import (cluster_echo_engine, get_scenario,
                                      run_scenario)
    from mmlspark_tpu.observability.federation import (
        FEDERATION_INTERVAL_ENV)
    from mmlspark_tpu.serving.distributed import ServingCluster

    gen = record.get("generation") or {}
    mesh_shape = str(gen.get("mesh_shape", "single"))
    kv_dtype = (gen.get("paged_attn") or {}).get("kv_dtype")
    prior = os.environ.get(FEDERATION_INTERVAL_ENV)
    os.environ[FEDERATION_INTERVAL_ENV] = "0"   # federate every heartbeat
    cluster = ServingCluster(3, reply_timeout=10.0, max_queue=256)
    stop = _threading.Event()
    engine = cluster_echo_engine(cluster, stop, service_s=0.005, batch=16)
    try:
        card = run_scenario(get_scenario("smoke"), cluster,
                            closed_loop_n=20,
                            mesh_shape=mesh_shape,
                            kv_dtype=kv_dtype)
    finally:
        stop.set()
        engine.join(timeout=2.0)
        cluster.close()
        if prior is None:
            os.environ.pop(FEDERATION_INTERVAL_ENV, None)
        else:
            os.environ[FEDERATION_INTERVAL_ENV] = prior
    # worker-side sampled history (the store outlives cluster.close()):
    # queue pressure over the run, next to the scorecard's own
    # `timeline` sub-record
    from mmlspark_tpu.observability.timeseries import get_store as _ts_store
    card["timeseries"] = _ts_store().snapshot(
        max(float(card.get("window_s") or 0.0) + 10.0, 60.0),
        names=["mmlspark_queue_saturation",
               "mmlspark_queue_drain_rate"])
    return card


def _tuning_phase(record: dict, model, *, batch: int, n_rows: int,
                  ips: float) -> dict:
    """Measurement-driven autotuning sub-record (ROADMAP item 4).

    Folds this run's harvested runner samples together with every prior
    ``BENCH_r0*.json`` into one observation store, fits the cost model, and
    reports (a) the config it would pick for this workload, (b) per-knob
    predicted deltas against the config that actually ran, and (c) a
    regression guard comparing the headline number against the best prior
    round on the same platform — a dip becomes a flagged field in the JSON
    record, not a silent regression in the trajectory.
    """
    import glob

    from mmlspark_tpu.tuning import (CostModel, ObservationStore,
                                     compare_kv_dtype, compare_paged_attn,
                                     get_store, import_bench_records)

    here = os.path.dirname(os.path.abspath(__file__))
    priors = sorted(glob.glob(os.path.join(here, "BENCH_r0*.json")))
    sig = model.tuning_signature()
    store = ObservationStore()          # scratch: this run + the trajectory
    for row in get_store().rows(sig=sig):
        store.record(row)
    imported = import_bench_records(priors, store)
    out = {"imported_bench_records": imported, "store_rows": len(store),
           "sig": sig}
    # this run's generation phase + the imported trajectory, grouped by
    # paged-attention impl: the kernel-vs-gather evidence per placement
    gen = record.get("generation")
    if isinstance(gen, dict) and isinstance(gen.get("tok_per_sec"),
                                            (int, float)):
        from mmlspark_tpu.tuning.observations import _generation_observation
        row = _generation_observation(record, __file__)
        if row is not None:
            store.record(row)
    pa = compare_paged_attn(store)
    if pa:
        out["paged_attn_comparison"] = pa
    kd = compare_kv_dtype(store)
    if kd:
        out["kv_dtype_comparison"] = kd

    histogram = {batch: n_rows // batch}
    if n_rows % batch:
        histogram[n_rows % batch] = 1
    depth0 = int(model.prefetch_depth)
    rows = store.rows(sig=sig)
    if rows:
        cm = CostModel.fit(rows)
        decision = cm.choose(histogram, defaults=(batch, depth0))
        out["decision"] = decision.as_dict()
        # predicted-vs-measured for the config that actually ran, plus the
        # predicted effect of moving each knob alone to its chosen value
        base = cm.predict_seconds(histogram, batch, depth0, None)
        pred_cur = (n_rows / base) if base > 0 else None
        out["predicted_rows_per_sec_current"] = (
            round(pred_cur, 2) if pred_cur else None)
        out["measured_rows_per_sec"] = round(ips, 2)
        out["predicted_vs_measured_delta"] = (
            round((pred_cur - ips) / ips, 4) if pred_cur and ips else None)
        per_knob = {}
        for name, chosen, default in (
                ("mini_batch_size", decision.mini_batch_size, batch),
                ("prefetch_depth", decision.prefetch_depth, depth0),
                ("buckets",
                 None if decision.buckets is None
                 else list(decision.buckets), None)):
            cand = {"mini_batch_size": batch, "prefetch_depth": depth0,
                    "buckets": None}
            cand[name] = chosen
            sec = cm.predict_seconds(histogram, **cand)
            per_knob[name] = {
                "default": default, "chosen": chosen,
                "predicted_speedup": (round(base / sec, 4)
                                      if sec > 0 else None)}
        out["per_knob"] = per_knob

    # regression guard: best prior round of the same metric on the same
    # platform (a CPU-fallback round must not be judged against TPU rounds)
    best_prior, best_file = 0.0, None
    for path in priors:
        try:
            with open(path, encoding="utf-8") as fh:
                raw = json.load(fh)
        except (OSError, ValueError):
            continue
        parsed = raw.get("parsed") if isinstance(raw.get("parsed"), dict) \
            else (raw if "value" in raw else None)
        if not parsed or parsed.get("metric") != record.get("metric") \
                or parsed.get("platform") != record.get("platform"):
            continue
        v = parsed.get("value")
        if isinstance(v, (int, float)) and v > best_prior:
            best_prior, best_file = float(v), os.path.basename(path)
    tol = float(os.environ.get("BENCH_REGRESSION_TOLERANCE", "0.1"))
    if best_prior > 0:
        out["regression"] = {
            "best_prior": round(best_prior, 2),
            "best_prior_file": best_file, "tolerance": tol,
            "delta": round((ips - best_prior) / best_prior, 4),
            "dip": bool(ips < best_prior * (1.0 - tol))}
    else:
        out["regression"] = {"best_prior": None, "dip": False}
    return out


def main():
    t_start = time.monotonic()
    budget = float(os.environ.get("BENCH_WALL_BUDGET_S",
                                  str(DEFAULT_WALL_BUDGET_S)))

    def remaining() -> float:
        return budget - (time.monotonic() - t_start)

    record = {
        "metric": "resnet50_onnx_images_per_sec_per_chip",
        "value": 0.0, "unit": "images/sec/chip", "vs_baseline": 0.0,
        "platform": "unknown", "platform_raw": None, "device": None,
        "mfu": None, "device_resident_ips": None, "device_mfu": None,
        "device_resident_ips_fused": None, "device_mfu_fused": None,
        "h2d_gbps": None, "backend_probe": None, "residency": None,
    }
    report = _OneShotReport(record, path=_partial_path())
    # registered once the model exists, so even a budget-truncated record
    # carries the stage counters measured so far
    counter_sources = []

    # device-stall watchdog: enabled for the whole bench run regardless of
    # MMLSPARK_TPU_WATCHDOG (env budget/interval/diag-dir knobs still
    # apply). A stall stamps the shared record with the bundle path and
    # checkpoints the partial JSON immediately — a later SIGKILL cannot
    # erase the verdict.
    from mmlspark_tpu.observability import configure_watchdog

    def _on_stall(stall: dict) -> None:
        record.setdefault("watchdog_stalls", []).append(
            {"site": stall.get("site"), "bundle": stall.get("bundle"),
             "stalled_seconds": stall.get("stalled_seconds"),
             "t": stall.get("t")})
        _fill_partial()
        report.checkpoint("watchdog_stall")

    configure_watchdog(enabled=True).on_stall(_on_stall)

    def _slo_card():
        # rolling scorecard of the run's phases + any serving traffic —
        # attached on EVERY exit path (budget watchdog, signals, clean end)
        try:
            from mmlspark_tpu.observability import get_tracker
            return get_tracker().scorecard()
        except Exception:               # noqa: BLE001
            return None

    def _telemetry():
        # stdlib-only registry snapshot: compile-cache hits/misses/
        # steady_state_recompiles plus aggregate stage counters, so the
        # perf trajectory carries observability data (docs/observability.md)
        from mmlspark_tpu.observability import snapshot
        return snapshot()

    def _residency():
        # data-plane residency scorecard: hit rate + transfer-op counts from
        # the residency layer, staging-slab churn, and the h2d-overlap
        # fraction (how much of coerce+pad host prep the prefetch worker hid
        # from the dispatch thread; 1.0 = prep fully overlapped transfers)
        try:
            from mmlspark_tpu.core.residency import residency_stats
            from mmlspark_tpu.models.runner import (M_SLAB_ALLOCS,
                                                    M_SLAB_REUSE)
            from mmlspark_tpu.ops.compile_cache import M_STAGE_SECONDS
            stats = residency_stats()
            allocs = M_SLAB_ALLOCS.labels().get()
            reuses = M_SLAB_REUSE.labels().get()
            issued = allocs + reuses
            prep_s = (M_STAGE_SECONDS.labels(stage="coerce").get()
                      + M_STAGE_SECONDS.labels(stage="pad").get())
            wait_s = M_STAGE_SECONDS.labels(stage="prefetch_wait").get()
            stats.update(
                staging_slab_allocs=allocs,
                staging_slab_reuses=reuses,
                staging_slab_reuse_rate=(
                    round(reuses / issued, 4) if issued else None),
                h2d_overlap_fraction=(
                    round(max(0.0, min(1.0, 1.0 - wait_s / prep_s)), 4)
                    if prep_s > 0 else None))
            return stats
        except Exception:               # noqa: BLE001
            return None

    def _fill_partial():
        # shared by the budget watchdog and the SIGTERM handler: fold in
        # whatever was measured before the interruption
        try:
            for snap in counter_sources:
                record["stage_counters"] = snap()
            record["telemetry"] = _telemetry()
            record["residency"] = _residency()
            record["slo"] = _slo_card()
            record["costs"] = _bench_costs(harvest=True)
            record["multi_model"] = _bench_multi_model()
        except Exception:                   # noqa: BLE001
            pass

    def _watchdog():
        time.sleep(max(1.0, budget))
        record["budget_truncated"] = True
        record.setdefault("midrun_error",
                          f"wall-clock budget {budget:.0f}s exhausted; "
                          "partial results reported")
        _fill_partial()
        if report.emit():
            os._exit(0)

    threading.Thread(target=_watchdog, daemon=True).start()
    _install_signal_handlers(report, _fill_partial)

    # leave at least ~2 min of budget for the measurement itself
    platform, device_kind, probe_info = _init_backend(
        window_cap=remaining() - 120.0)
    on_tpu = _looks_tpu(platform, device_kind)
    record.update(platform="tpu" if on_tpu else "cpu",
                  platform_raw=platform, device=device_kind,
                  backend_probe=probe_info)

    import jax

    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.models.onnx_model import ONNXModel
    from mmlspark_tpu.models.zoo.resnet import RESNET50, export_resnet_onnx

    batch = int(os.environ.get("BENCH_BATCH", "512"))
    n_rows = int(os.environ.get("BENCH_ROWS", "2048"))
    passes = int(os.environ.get("BENCH_PASSES", "3"))
    if not on_tpu:
        # degraded mode: still report a number, but keep the wall-clock sane
        batch = min(batch, 32)
        n_rows = min(n_rows, 128)
    rng = np.random.default_rng(0)

    model_bytes = export_resnet_onnx(RESNET50, seed=0)
    # The input column holds what an image decoder produces: uint8 HWC.
    # Layout (NHWC→NCHW), dtype cast, and ImageNet normalization all run on
    # device fused into the graph — a uint8 image is 4x smaller than its
    # float32 tensor, and the host→device link is the bottleneck.
    m = ONNXModel(model_bytes,
                  feed_dict={"input": "image"},
                  fetch_dict={"logits": "logits"},
                  argmax_dict={"pred": "logits"},
                  transpose_dict={"input": [0, 3, 1, 2]},
                  normalize_dict={"input": {
                      "scale": 1.0 / 255.0,
                      "mean": [0.485, 0.456, 0.406],
                      "std": [0.229, 0.224, 0.225]}},
                  mini_batch_size=batch,
                  compute_dtype="bfloat16")
    counter_sources.append(m.stage_counters.snapshot)

    X = rng.integers(0, 256, (n_rows, 224, 224, 3), dtype=np.uint8)
    col = np.empty(n_rows, dtype=object)
    for i in range(n_rows):
        col[i] = X[i]
    df = DataFrame({"image": col})

    # AOT warm-up: every padding bucket the run will hit is compiled BEFORE
    # any timed section (full batches land in bucket_size(batch); a ragged
    # tail lands in its own bucket), so steady-state img/s excludes compile
    # by construction, not by hoping the first pass absorbed it. With
    # MMLSPARK_TPU_COMPILE_CACHE_DIR set the executables also persist to
    # disk for the next process.
    warm_sizes = sorted({batch, n_rows % batch or batch})
    with _phase_guard(record, "warm_up", min(remaining() - 90.0, 300.0),
                      report=report):
        try:
            t0 = time.perf_counter()
            record["warm_up"] = m.warm_up(
                batch_sizes=warm_sizes,
                input_specs={"input": (np.uint8, (224, 224, 3))})
            record["warm_up"]["wall_s"] = round(time.perf_counter() - t0, 3)
        except Exception as e:              # noqa: BLE001
            record["warm_up"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}

    # warmup transform: first full trip through the DataFrame path (host
    # transfers, drain) — timed as a last-resort number so even a run whose
    # timed passes all die still reports something real
    warm_ips = 0.0
    try:
        t0 = time.perf_counter()
        warm = m.transform(df.head(batch))
        warm_ips = batch / (time.perf_counter() - t0)
        assert len(warm) == batch
        # floor for a truncated record; the timed passes overwrite it
        record["value"] = round(warm_ips, 2)
        record["vs_baseline"] = round(warm_ips / TARGET_IMG_PER_SEC, 4)
    except Exception as e:              # noqa: BLE001
        # backend died between probe and warmup: still emit the one JSON
        # line the driver expects, with the reason, instead of crashing
        record["midrun_error"] = \
            f"warmup failed: {type(e).__name__}: {e}"[:300]
        record["stage_counters"] = m.stage_counters.snapshot()
        record["telemetry"] = _telemetry()
        record["residency"] = _residency()
        record["slo"] = _slo_card()
        record["costs"] = _bench_costs(harvest=True)
        record["multi_model"] = _bench_multi_model()
        report.emit()
        return

    # The TPU here sits behind a shared tunnel whose host->device bandwidth
    # swings over time; best-of-N passes measures the framework rather than
    # a congestion spike, and the observed link speed is reported alongside.
    # A pass that dies on a backend loss (the tunnel can drop mid-run)
    # keeps the passes that DID complete — round-4 postmortem: a full TPU
    # measurement was discarded because a later, optional leg crashed.
    #
    # The link probe STREAMS the same batches the pipeline sends (several
    # puts in flight) and runs interleaved between the e2e passes, so the
    # reported fraction-of-link compares numbers from the same congestion
    # window — a single put in a different window over/under-states the
    # link by multiples (the round-4 "40% of link" verdict was exactly
    # this artifact).
    import jax.numpy as jnp

    from mmlspark_tpu.observability import watch as _wd_watch

    def _h2d_streaming_gbps():
        parts = [X[lo:lo + batch] for lo in range(0, n_rows, batch)]
        with _wd_watch("bench_h2d_probe"):
            t0 = time.perf_counter()
            devs = [jax.device_put(a) for a in parts]
            for d in devs:
                float(jnp.sum(d[0, 0, 0, :].astype(jnp.float32)))   # fence
            el = time.perf_counter() - t0
        return sum(a.nbytes for a in parts) / el / 1e9

    ips = 0.0
    pass_ips = []
    h2d_samples = []
    midrun_error = None
    from mmlspark_tpu.observability import tracing as _tracing
    from mmlspark_tpu.ops.compile_cache import jit_cache_size
    cache_before_passes = jit_cache_size(m._jitted)
    with _phase_guard(record, "timed_passes", remaining() - 60.0,
                      report=report):
        for i in range(max(1, passes)):
            if remaining() < 45.0:
                # keep enough budget to assemble and emit the report; a
                # truncated run reports fewer passes, not nothing
                record["budget_truncated"] = True
                break
            if i > 0:
                # interleaved link probe in its OWN try: a probe failure
                # must neither abort the remaining e2e passes nor
                # masquerade as a pass failure (round-4 postmortem: an
                # optional leg's crash discarded a full TPU measurement)
                try:
                    h2d_samples.append(_h2d_streaming_gbps())
                except Exception:                   # noqa: BLE001
                    pass
            try:
                # each timed pass runs under a root trace: the flight
                # recorder keeps the per-stage span tree (coerce/pad on
                # the prefetch worker, h2d, dispatch, d2h) of every
                # measured pass, so a slow pass is diagnosable from the
                # emitted record alone
                root = _tracing.start_trace("bench.pass", index=i)
                t0 = time.perf_counter()
                with _tracing.activate(root):
                    out = m.transform(df)
                elapsed = time.perf_counter() - t0
                root.end(rows=n_rows)
                assert len(out) == n_rows
                pass_ips.append(n_rows / elapsed)
                ips = max(ips, pass_ips[-1])
                # keep the shared record current: a budget-truncated run
                # reports the best pass measured so far, not 0
                record["value"] = round(ips, 2)
                record["vs_baseline"] = round(ips / TARGET_IMG_PER_SEC, 4)
                record["best_of"] = len(pass_ips)
            except Exception as e:                  # noqa: BLE001
                midrun_error = f"pass failed: {type(e).__name__}: {e}"[:300]
                break
    if ips == 0.0:
        # warmup DID execute on device — report its rate (compile already
        # hoisted into warm_up) rather than discarding the run
        ips = warm_ips
    cache_after_passes = jit_cache_size(m._jitted)
    record["steady_state_recompiles"] = (
        cache_after_passes - cache_before_passes
        if cache_after_passes is not None and cache_before_passes is not None
        else None)
    try:
        record["pass_traces"] = [
            t.summary() for t in _tracing.get_flight_recorder().traces()
            if t.root is not None and t.root.name == "bench.pass"]
    except Exception:                   # noqa: BLE001
        pass

    # generation phase: the continuous-decoder trajectory number (paged KV,
    # chunked prefill, autotuner). Runs BEFORE the optional device probes:
    # a probe stalled inside one long native XLA call cannot be preempted
    # by the SIGALRM guard, and must not starve this phase -- it is the
    # number this bench exists to move. Own guard + own try so a failure
    # here never costs the image numbers above.
    with _phase_guard(record, "generation", min(remaining() - 30.0, 240.0),
                      report=report):
        try:
            if remaining() > 45.0:
                record["generation"] = _generation_phase(on_tpu)
            else:
                record["generation"] = {"skipped": "budget exhausted"}
        except Exception as e:          # noqa: BLE001
            record["generation"] = {
                "error": f"{type(e).__name__}: {e}"[:300]}

    # multichip generation: the mesh-mounted engine vs single chip on the
    # same workload — tok/s vs chips, scaling efficiency, per-tick
    # collective estimate. Needs >= 2 devices (real or simulated); on one
    # device the phase records why it abstained instead of fake numbers.
    with _phase_guard(record, "multichip_generation",
                      min(remaining() - 25.0, 180.0), report=report):
        try:
            if jax.device_count() < 2:
                record["multichip_generation"] = {
                    "skipped": "single device"}
            elif remaining() > 40.0:
                record["multichip_generation"] = \
                    _multichip_generation_phase()
            else:
                record["multichip_generation"] = {
                    "skipped": "budget exhausted"}
        except Exception as e:          # noqa: BLE001
            record["multichip_generation"] = {
                "error": f"{type(e).__name__}: {e}"[:300]}

    # failover phase: checkpoint/restore a live session cold and warm —
    # the drain-vs-kill handoff cost numbers, with token parity asserted
    with _phase_guard(record, "failover", min(remaining() - 25.0, 90.0),
                      report=report):
        try:
            if remaining() > 35.0:
                record["failover"] = _failover_phase()
            else:
                record["failover"] = {"skipped": "budget exhausted"}
        except Exception as e:          # noqa: BLE001
            record["failover"] = {
                "error": f"{type(e).__name__}: {e}"[:300]}

    # scenarios phase: the smoke scenario open-loop against a 3-worker
    # in-process cluster — scorecard rows land in the ObservationStore
    # BEFORE the tuning phase reads it, so the tuner scores against
    # traffic-shaped observations from this very run
    with _phase_guard(record, "scenarios", min(remaining() - 25.0, 90.0),
                      report=report):
        try:
            if remaining() > 35.0:
                record["scenarios"] = {"smoke": _scenarios_phase(record)}
            else:
                record["scenarios"] = {"skipped": "budget exhausted"}
        except Exception as e:          # noqa: BLE001
            record["scenarios"] = {
                "error": f"{type(e).__name__}: {e}"[:300]}

    # tuning phase: pure host arithmetic over this run's harvested samples
    # + the historical bench records — chosen config, per-knob predicted
    # deltas, and the trajectory regression guard
    with _phase_guard(record, "tuning", min(remaining() - 20.0, 60.0),
                      report=report):
        try:
            record["tuning"] = _tuning_phase(record, m, batch=batch,
                                             n_rows=n_rows, ips=ips)
            record["regression_flag"] = bool(
                (record["tuning"].get("regression") or {}).get("dip"))
        except Exception as e:          # noqa: BLE001
            record["tuning"] = {"error": f"{type(e).__name__}: {e}"[:200]}

    h2d_gbps = None
    link_bound_ips = None
    link_fraction = None
    device_ips = None
    device_ips_fused = None
    dev_setup = None
    mfu = None
    device_mfu = None
    device_mfu_fused = None
    # One guard over every optional device probe (h2d link, device-resident
    # rate, fused scan, XLA cost analysis): on a host where d2h crawls, any
    # one of these can silently eat the remaining budget -- the BENCH_r05
    # failure mode -- and starve the generation phase below.
    with _phase_guard(record, "device_probes",
                      min(remaining() - 90.0, 300.0), report=report):
        try:
            if not h2d_samples and remaining() > 30.0:
                h2d_samples.append(_h2d_streaming_gbps())
            if h2d_samples:
                h2d_gbps = round(max(h2d_samples), 3)
                bytes_per_img = 224 * 224 * 3
                link_bound_ips = round(h2d_gbps * 1e9 / bytes_per_img, 1)
                if link_bound_ips:
                    link_fraction = round(ips / link_bound_ips, 3)
        except Exception as e:              # noqa: BLE001
            if midrun_error is None:
                midrun_error = f"h2d probe failed: {type(e).__name__}: {e}"[:300]

        # Device-resident compute rate: what the chip sustains once inputs are
        # on device — separates the framework from the session's tunnel, whose
        # congestion can swing end-to-end 100x between runs. Fencing is a
        # fetched scalar depending on the LAST dispatched call (in-order device
        # execution fences the earlier ones; block_until_ready is unreliable
        # behind the tunnel).
        try:
            if remaining() > 60.0:   # optional leg — skip under a tight budget
                import jax.numpy as jnp
                jitted = m._ensure_jitted()
                params = m._params_for_device(None)
                xdev = jax.device_put(X[:batch])
                rows_timed = int(xdev.shape[0])  # may be < batch when BENCH_ROWS is
                dev_setup = (jitted, params, xdev, rows_timed)
        except Exception:
            pass
        if dev_setup is not None:
            jitted, params, xdev, rows_timed = dev_setup
            try:
                with _wd_watch("bench_device_resident"):
                    tail = jax.jit(lambda c: jnp.sum(c["logits"][0, :2]
                                                     .astype(jnp.float32)))
                    float(tail(jitted(params,
                                      {"input": xdev})))   # compile + warm
                    reps = 20 if on_tpu else 3
                    t0 = time.perf_counter()
                    outs = None
                    for _ in range(reps):
                        outs = jitted(params, {"input": xdev})
                    float(tail(outs))
                device_ips = round(
                    rows_timed * reps / (time.perf_counter() - t0), 2)
            except Exception:
                pass

            # Fused-scan variant: R forwards inside ONE compiled program, each
            # iteration's input data-dependent on the previous output (the
            # carry perturbs the uint8 image, so XLA cannot hoist the
            # loop-invariant forward out of the scan). This isolates the
            # chip's sustained rate from the ~ms per-dispatch overhead this
            # runtime pays, which the per-dispatch loop above includes R times.
            try:
                if remaining() < 60.0:
                    raise TimeoutError("budget")
                R = 10

                @jax.jit
                def fused(params, x):
                    def body(t, _):
                        outs = jitted(params, {"input": x + t})
                        return (outs["pred"][0] % 2).astype(jnp.uint8), None
                    t, _ = jax.lax.scan(body, jnp.uint8(0), None, length=R)
                    return t
                with _wd_watch("bench_fused_scan"):
                    int(fused(params, xdev))               # compile + warm
                    # mean over reps, matching the per-dispatch loop's
                    # estimator — a best-of here would overstate the
                    # dispatch-overhead gap the two numbers exist to expose
                    reps_f = 3 if on_tpu else 1
                    t0 = time.perf_counter()
                    for _ in range(reps_f):
                        int(fused(params, xdev))           # fetched = fence
                    mean_f = (time.perf_counter() - t0) / reps_f
                device_ips_fused = round(rows_timed * R / mean_f, 2)
            except Exception:
                pass

        # MFU: per-image FLOPs straight from XLA's cost model for the compiled
        # program (not a hand-waved constant), peak from the device spec.
        try:
            if remaining() < 60.0:   # lower().compile() skips the jit cache —
                raise TimeoutError   # a full compile a truncated run can't pay
            import jax.numpy as jnp
            with _wd_watch("bench_cost_analysis"):
                compiled = m._jitted.lower(
                    m._params_for_device(None),
                    {"input": jnp.zeros((batch, 224, 224, 3),
                                        jnp.uint8)}).compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, list):
                cost = cost[0]
            flops_per_img = float(cost.get("flops", 0.0)) / batch
            peak = _peak_for(platform, device_kind)
            if flops_per_img and peak:
                mfu = round(ips * flops_per_img / peak, 4)
                if device_ips:
                    device_mfu = round(device_ips * flops_per_img / peak, 4)
                if device_ips_fused:
                    device_mfu_fused = round(
                        device_ips_fused * flops_per_img / peak, 4)
        except Exception:
            mfu = None

    # mutate the watchdog-shared record in place — rebinding the name would
    # orphan the reference the budget thread emits on timeout
    record.update(
        value=round(ips, 2),
        vs_baseline=round(ips / TARGET_IMG_PER_SEC, 4),
        mfu=mfu,
        device_resident_ips=device_ips,
        device_mfu=device_mfu,
        device_resident_ips_fused=device_ips_fused,
        device_mfu_fused=device_mfu_fused,
        h2d_gbps=h2d_gbps,
        h2d_probe_kind="streaming-interleaved",
        link_bound_ips=link_bound_ips,
        link_fraction=link_fraction,
        best_of=len(pass_ips) if pass_ips else None,
        pass_spread=(round((max(pass_ips) - min(pass_ips))
                           / max(pass_ips), 3)
                     if pass_ips else None),
        stage_counters=m.stage_counters.snapshot(),
        telemetry=_telemetry(),
        residency=_residency(),
        slo=_slo_card(),
        costs=_bench_costs(harvest=True),
        multi_model=_bench_multi_model(),
        wall_s=round(time.monotonic() - t_start, 2),
    )
    if midrun_error is not None:
        record["midrun_error"] = midrun_error
    if not on_tpu:
        record["note"] = ("degraded CPU fallback (TPU backend unavailable "
                          "at run time; see backend_probe.reason); measured "
                          "TPU numbers are in BASELINE.md")
    report.emit()


if __name__ == "__main__":
    main()
