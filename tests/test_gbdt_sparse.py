"""Sparse-input GBDT: CSR/CSC ingestion end to end.

Parity surface: the reference's sparse dataset path — sparse-vs-dense
auto-detect in ``lightgbm/.../dataset/DatasetAggregator.scala:127-183``
feeding ``LGBM_DatasetCreateFromCSR:441-465``, and sparse single-row
prediction (``booster/LightGBMBooster.scala:510-527``). TPU-first design
under test: sparse input is binned column-by-column straight from CSC
(cost ∝ nnz) into the dense uint8 matrix the histogram kernel consumes —
the float matrix is never densified; prediction densifies in bounded row
chunks.

The load-bearing invariant: binning a sparse matrix must produce the SAME
bins as binning its densification, so training and every prediction path
are bit-identical between the two representations.
"""

import numpy as np
import pytest
import scipy.sparse as sp

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.schema import assemble_features
from mmlspark_tpu.models.gbdt import (BinMapper, LightGBMClassifier,
                                      LightGBMRegressor, train)


def make_sparse(n=500, f=12, density=0.25, seed=0, nan_frac=0.0):
    rng = np.random.default_rng(seed)
    mask = rng.random((n, f)) < density
    vals = rng.normal(0, 2, (n, f))
    dense = np.where(mask, vals, 0.0)
    if nan_frac:
        nan_mask = mask & (rng.random((n, f)) < nan_frac)
        dense[nan_mask] = np.nan
    return dense, sp.csr_matrix(dense)


def target_for(dense, seed=0):
    rng = np.random.default_rng(seed)
    logit = dense[:, 0] * 2 - np.nan_to_num(dense[:, 1]) \
        + 0.5 * np.nan_to_num(dense[:, 2])
    return (np.nan_to_num(logit) + rng.normal(0, 0.3, len(dense)) > 0) \
        .astype(np.float64)


class TestSparseBinning:
    def test_bins_match_dense(self):
        dense, csr = make_sparse()
        bm_d = BinMapper(max_bin=32).fit(dense)
        bm_s = BinMapper(max_bin=32).fit(csr)
        for bd, bs in zip(bm_d.upper_bounds, bm_s.upper_bounds):
            np.testing.assert_allclose(bd, bs)
        np.testing.assert_array_equal(bm_d.transform(dense),
                                      bm_s.transform(csr))
        # cross-application too: dense-fit mapper binning sparse input
        np.testing.assert_array_equal(bm_d.transform(csr),
                                      bm_d.transform(dense))

    def test_bins_match_dense_sampled_fit(self):
        # n above sample_cnt exercises the CSR row-sampling path
        dense, csr = make_sparse(n=900, f=5, density=0.1, seed=3)
        bm_d = BinMapper(max_bin=16, sample_cnt=256, seed=7).fit(dense)
        bm_s = BinMapper(max_bin=16, sample_cnt=256, seed=7).fit(csr)
        for bd, bs in zip(bm_d.upper_bounds, bm_s.upper_bounds):
            np.testing.assert_allclose(bd, bs)

    def test_nan_stored_values_hit_missing_bin(self):
        dense, csr = make_sparse(n=300, f=4, nan_frac=0.3, seed=1)
        bm = BinMapper(max_bin=16).fit(csr)
        xb = bm.transform(csr)
        np.testing.assert_array_equal(xb, bm.transform(dense))
        assert (xb[np.isnan(dense)] == 0).all()

    def test_zero_heavy_column_gets_zero_bin(self):
        # 99% zeros: the zero bin must exist and order must be preserved
        dense, csr = make_sparse(n=400, f=3, density=0.01, seed=2)
        bm = BinMapper(max_bin=8).fit(csr)
        xb = bm.transform(csr)
        j = 0
        order = np.argsort(dense[:, j], kind="stable")
        assert (np.diff(xb[order, j].astype(int)) >= 0).all()

    def test_csc_input_accepted(self):
        dense, csr = make_sparse(n=200, f=4)
        bm = BinMapper(max_bin=16).fit(csr.tocsc())
        np.testing.assert_array_equal(bm.transform(csr.tocsc()),
                                      BinMapper(max_bin=16)
                                      .fit(dense).transform(dense))


class TestSparseTraining:
    def test_train_identical_to_dense(self):
        dense, csr = make_sparse()
        y = target_for(dense)
        params = {"objective": "binary", "num_iterations": 20,
                  "num_leaves": 15, "min_data_in_leaf": 5}
        b_d = train(dict(params), dense, y)
        b_s = train(dict(params), csr, y)
        np.testing.assert_allclose(b_d.predict(dense), b_s.predict(csr),
                                   rtol=1e-6)
        np.testing.assert_allclose(b_s.predict(dense), b_s.predict(csr),
                                   rtol=1e-6)

    def test_prediction_paths_match_dense(self):
        dense, csr = make_sparse(n=300, f=6, seed=4)
        y = target_for(dense, seed=4)
        b = train({"objective": "binary", "num_iterations": 10,
                   "num_leaves": 7, "min_data_in_leaf": 5}, csr, y)
        np.testing.assert_allclose(b.raw_score(dense), b.raw_score(csr),
                                   rtol=1e-6)
        np.testing.assert_array_equal(b.predict_leaf(dense),
                                      b.predict_leaf(csr))
        np.testing.assert_allclose(b.shap_values(dense), b.shap_values(csr),
                                   rtol=1e-5, atol=1e-6)

    def test_sparse_valid_set_early_stopping(self):
        dense, csr = make_sparse(n=600, f=8, seed=5)
        y = target_for(dense, seed=5)
        b = train({"objective": "binary", "num_iterations": 60,
                   "num_leaves": 15, "min_data_in_leaf": 5,
                   "early_stopping_round": 5},
                  csr[:400], y[:400],
                  valid_sets=[(csr[400:], y[400:])])
        assert 0 < b.best_iteration <= 60

    def test_dart_and_goss_sparse_match_dense(self):
        dense, csr = make_sparse(n=400, f=6, seed=6)
        y = target_for(dense, seed=6)
        for boosting in ("dart", "goss"):
            params = {"objective": "binary", "boosting": boosting,
                      "num_iterations": 12, "num_leaves": 7,
                      "min_data_in_leaf": 5, "seed": 11}
            b_d = train(dict(params), dense, y)
            b_s = train(dict(params), csr, y)
            np.testing.assert_allclose(b_d.predict(dense), b_s.predict(csr),
                                       rtol=1e-5, atol=1e-7)

    def test_warm_start_sparse(self):
        dense, csr = make_sparse(n=300, f=5, seed=7)
        y = target_for(dense, seed=7)
        params = {"objective": "binary", "num_iterations": 5,
                  "num_leaves": 7, "min_data_in_leaf": 5}
        b0 = train(dict(params), csr, y)
        b1 = train(dict(params), csr, y, init_model=b0)
        assert b1.num_trees == 10

    def test_wide_sparse_trains(self):
        # wide + very sparse (hashed-text shape): trains without densifying
        rng = np.random.default_rng(8)
        n, f = 400, 512
        csr = sp.random(n, f, density=0.02, random_state=9, format="csr")
        y = (np.asarray(csr[:, 0].todense()).ravel()
             + rng.normal(0, 0.1, n) > 0.01).astype(np.float64)
        b = train({"objective": "binary", "num_iterations": 5,
                   "num_leaves": 7, "min_data_in_leaf": 5}, csr, y)
        assert b.predict(csr).shape == (n,)

    def test_categorical_with_sparse_rejected(self):
        dense, csr = make_sparse(n=100, f=4)
        y = target_for(dense)
        with pytest.raises(ValueError, match="categorical"):
            train({"objective": "binary", "num_iterations": 2,
                   "categorical_feature": [0]}, csr, y)


class TestSparseDataFrameAPI:
    def _df(self, csr, y):
        col = np.empty(csr.shape[0], dtype=object)
        for i in range(csr.shape[0]):
            col[i] = csr[i]
        return DataFrame({"features": col, "label": y})

    def test_assemble_features_sparse(self):
        dense, csr = make_sparse(n=50, f=6)
        df = self._df(csr, np.zeros(50))
        out = assemble_features(df, ["features"])
        assert sp.issparse(out)
        np.testing.assert_allclose(out.toarray(), dense)

    def test_assemble_features_mixed_rows_rejected(self):
        dense, csr = make_sparse(n=10, f=4)
        # a single sparse row anywhere makes the column sparse — mixing
        # with dense rows is rejected, never silently densified
        for flip in (0, 9):
            col = np.empty(10, dtype=object)
            for i in range(10):
                col[i] = dense[i] if i == flip else csr[i]
            with pytest.raises(ValueError, match="mixes sparse"):
                assemble_features(DataFrame({"features": col}), ["features"])

    def test_classifier_sparse_column_matches_dense(self):
        dense, csr = make_sparse(n=300, f=6, seed=10)
        y = target_for(dense, seed=10)
        df_s = self._df(csr, y)
        dcol = np.empty(len(dense), dtype=object)
        dcol[:] = list(dense.astype(np.float32))
        df_d = DataFrame({"features": dcol, "label": y})
        est = LightGBMClassifier(num_iterations=10, num_leaves=7,
                                 min_data_in_leaf=5)
        m_s = est.fit(df_s)
        m_d = est.fit(df_d)
        p_s = np.asarray(m_s.transform(df_s)["prediction"], dtype=np.float64)
        p_d = np.asarray(m_d.transform(df_d)["prediction"], dtype=np.float64)
        np.testing.assert_array_equal(p_s, p_d)

    def test_regressor_sparse_column(self):
        dense, csr = make_sparse(n=200, f=5, seed=11)
        y = dense[:, 0] * 3 + np.nan_to_num(dense[:, 1])
        df = self._df(csr, y)
        m = LightGBMRegressor(num_iterations=20, num_leaves=15,
                              min_data_in_leaf=5).fit(df)
        pred = np.asarray(m.transform(df)["prediction"], dtype=np.float64)
        assert 1 - np.var(y - pred) / max(np.var(y), 1e-9) > 0.5


class TestSparseExplainers:
    def test_vector_shap_over_sparse_model(self):
        # the follow-on a sparse-GBDT user reaches for next: KernelSHAP on
        # a sparse features column (rows densify one at a time)
        from mmlspark_tpu.explainers import VectorSHAP
        dense, csr = make_sparse(n=120, f=5, seed=12)
        y = target_for(dense, seed=12)
        col = np.empty(csr.shape[0], dtype=object)
        for i in range(csr.shape[0]):
            col[i] = csr[i]
        df = DataFrame({"features": col, "label": y})
        model = LightGBMClassifier(num_iterations=10, num_leaves=7,
                                   min_data_in_leaf=5).fit(df)
        shap = VectorSHAP(model=model, target_col="probability",
                          input_col="features", output_col="shap",
                          num_samples=32, seed=0)
        out = shap.transform(df.head(4))
        svals = np.stack([np.asarray(v) for v in out["shap"]])
        assert svals.shape[0] == 4 and np.isfinite(svals).all()
        # same explanation as the dense representation of the same rows
        dcol = np.empty(4, dtype=object)
        dcol[:] = list(dense[:4].astype(np.float64))
        out_d = shap.transform(DataFrame({"features": dcol}))
        dvals = np.stack([np.asarray(v) for v in out_d["shap"]])
        np.testing.assert_allclose(svals, dvals, rtol=1e-6, atol=1e-8)


class TestLibsvmSparse:
    def test_read_sparse_matches_dense(self, tmp_path):
        from mmlspark_tpu.io.libsvm import read_libsvm
        p = tmp_path / "t.svm"
        p.write_text("1 1:0.5 3:2.0\n0 2:1.5\n1 1:-1.0 4:0.25\n")
        df_d = read_libsvm(str(p))
        df_s = read_libsvm(str(p), sparse=True)
        X_d = assemble_features(df_d, ["features"])
        X_s = assemble_features(df_s, ["features"])
        assert sp.issparse(X_s) and not sp.issparse(X_d)
        np.testing.assert_allclose(X_s.toarray(), X_d)
        np.testing.assert_array_equal(np.asarray(df_s["label"]),
                                      np.asarray(df_d["label"]))

    def test_duplicate_indices_last_wins_both_modes(self, tmp_path):
        # CSR construction would SUM duplicates; the dense scatter takes
        # the last occurrence — both modes must agree (last wins)
        from mmlspark_tpu.io.libsvm import read_libsvm
        p = tmp_path / "dup.svm"
        p.write_text("1 1:0.5 1:2.0 3:1.0\n0 2:1.5\n")
        X_d = assemble_features(read_libsvm(str(p)), ["features"])
        X_s = assemble_features(read_libsvm(str(p), sparse=True),
                                ["features"])
        np.testing.assert_allclose(X_s.toarray(), X_d)
        assert X_d[0, 0] == 2.0
