"""Decoder → ONNX decode-step export, cross-validated against the zoo.

Stepping the exported graph (GroupQueryAttention with static kv caches +
fused rotary, SimplifiedLayerNormalization, tanh-Gelu) must reproduce the
native :func:`decode_step` logits within fp32 tolerance at EVERY position —
two independent implementations of the same decoder, one driving the ONNX
handler stack with learned weights."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.models.zoo.decoder_onnx import export_decoder_onnx
from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                 decode_step,
                                                 init_kv_cache,
                                                 init_transformer)
from mmlspark_tpu.onnx.convert import convert_model

CFG = TransformerConfig(vocab=97, layers=2, d_model=32, heads=4, max_len=16,
                        d_ff=64, dtype=jnp.float32, causal=True,
                        norm="rmsnorm", position="rope")


def test_onnx_decode_matches_native_per_step():
    params = init_transformer(CFG, seed=3)
    L = 10
    cm = convert_model(export_decoder_onnx(CFG, params, max_len=L))
    B = 2
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, CFG.vocab, (B, L))

    # native loop
    cache = init_kv_cache(CFG, B, L)
    native = []
    for t in range(L):
        logits, cache = decode_step(params,
                                    jnp.asarray(tokens[:, t]), t, cache, CFG)
        native.append(np.asarray(logits))

    # ONNX loop: ONE compiled step function, caches advancing in place
    H, hd = CFG.heads, CFG.d_model // CFG.heads
    feeds_cache = {}
    for i in range(CFG.layers):
        feeds_cache[f"past_k_{i}"] = np.zeros((B, H, L, hd), np.float32)
        feeds_cache[f"past_v_{i}"] = np.zeros((B, H, L, hd), np.float32)
    step = jax.jit(lambda p, f: cm(p, f))
    for t in range(L):
        feeds = {"token": tokens[:, t:t + 1].astype(np.int64),
                 "seqlens": np.full(B, t, np.int32),
                 "total": np.array(t + 1, np.int32), **feeds_cache}
        out = step(cm.params, feeds)
        np.testing.assert_allclose(np.asarray(out["logits"]), native[t],
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"step {t}")
        for i in range(CFG.layers):
            feeds_cache[f"past_k_{i}"] = np.asarray(out[f"present_k_{i}"])
            feeds_cache[f"past_v_{i}"] = np.asarray(out[f"present_v_{i}"])
        assert feeds_cache["past_k_0"].shape == (B, H, L, hd)  # static


def test_export_requires_decoder_switches():
    enc = CFG._replace(causal=False)
    with pytest.raises(ValueError, match="decoder switches"):
        export_decoder_onnx(enc, init_transformer(enc, seed=0), max_len=8)


def test_export_rejects_odd_head_dim():
    odd = TransformerConfig(vocab=32, layers=1, d_model=30, heads=6,
                            d_ff=32, max_len=8, dtype=jnp.float32,
                            causal=True, norm="rmsnorm", position="rope")
    with pytest.raises(ValueError, match="even head dim"):
        export_decoder_onnx(odd, init_transformer(odd, seed=0), max_len=8)
