"""Genuine torch.onnx.export artifacts through the converter.

The onnx shim (``interop/onnx_shim.py``) routes torch's single ``import
onnx`` use (the onnxscript-function scan) to this repo's own protobuf
parser, so ``torch.onnx.export`` emits REAL torch-serialized ONNX bytes in
a zero-egress image. These tests assert numeric parity of the converted
graphs against torch eval — the reference's bar is ORT executing arbitrary
exporter artifacts (``deep-learning/.../onnx/ONNXModel.scala:195-245``).
"""

import io
import warnings

import numpy as np
import pytest

from mmlspark_tpu.interop.onnx_shim import install_onnx_shim
from mmlspark_tpu.onnx.convert import convert_model

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402


def _export(model, args, **kw):
    install_onnx_shim()
    model.eval()
    buf = io.BytesIO()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        torch.onnx.export(model, args, buf, dynamo=False, **kw)
    return buf.getvalue()


def test_mlp_export_parity():
    torch.manual_seed(0)
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    x = torch.randn(2, 4)
    b = _export(m, (x,), input_names=["x"], output_names=["y"])
    cm = convert_model(b)
    assert cm.model.producer_name == "pytorch"   # genuine artifact
    got = np.asarray(cm(cm.params, {"x": x.numpy()})["y"])
    np.testing.assert_allclose(got, m(x).detach().numpy(),
                               rtol=1e-5, atol=1e-6)


class _BasicBlock(nn.Module):
    """torchvision-faithful BasicBlock (conv-bn-relu x2 + skip)."""

    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU(inplace=True)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x if self.down is None else self.down(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return self.relu(out + idn)


class _ResNet(nn.Module):
    """torchvision ResNet-18 topology at reduced width (the structure —
    stem, 4 stages, global pool, fc — is what the exporter exercises)."""

    def __init__(self, width=8, classes=10):
        super().__init__()
        w = width
        self.stem = nn.Sequential(
            nn.Conv2d(3, w, 7, 2, 3, bias=False), nn.BatchNorm2d(w),
            nn.ReLU(inplace=True), nn.MaxPool2d(3, 2, 1))
        self.layer1 = nn.Sequential(_BasicBlock(w, w), _BasicBlock(w, w))
        self.layer2 = nn.Sequential(_BasicBlock(w, 2 * w, 2),
                                    _BasicBlock(2 * w, 2 * w))
        self.layer3 = nn.Sequential(_BasicBlock(2 * w, 4 * w, 2),
                                    _BasicBlock(4 * w, 4 * w))
        self.layer4 = nn.Sequential(_BasicBlock(4 * w, 8 * w, 2),
                                    _BasicBlock(8 * w, 8 * w))
        self.pool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(8 * w, classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        return self.fc(torch.flatten(self.pool(x), 1))


def test_resnet_export_parity():
    torch.manual_seed(1)
    m = _ResNet()
    # BN with running stats in eval mode: run a forward pass in train mode
    # first so the stats are not the init values (a realistic checkpoint)
    m.train()
    with torch.no_grad():
        m(torch.randn(4, 3, 64, 64))
    m.eval()
    x = torch.randn(2, 3, 64, 64)
    b = _export(m, (x,), input_names=["image"], output_names=["logits"])
    cm = convert_model(b)
    got = np.asarray(cm(cm.params, {"image": x.numpy()})["logits"])
    want = m(x).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_hf_bert_export_parity():
    """A real transformers BERT (tiny config) through the real exporter:
    embeddings + LayerNorm + multi-head attention + pooler, exactly as HF
    emits them."""
    tr = pytest.importorskip("transformers")
    torch.manual_seed(2)
    cfg = tr.BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                        num_attention_heads=2, intermediate_size=64,
                        max_position_embeddings=32)
    m = tr.BertModel(cfg)
    m.eval()
    ids = torch.randint(0, 64, (2, 10))
    mask = torch.ones(2, 10, dtype=torch.long)
    mask[1, 6:] = 0                                  # real padding
    b = _export(m, (ids, mask),
                input_names=["input_ids", "attention_mask"],
                output_names=["last_hidden_state", "pooler_output"])
    cm = convert_model(b)
    out = cm(cm.params, {"input_ids": ids.numpy(),
                         "attention_mask": mask.numpy()})
    with torch.no_grad():
        want = m(ids, attention_mask=mask)
    np.testing.assert_allclose(
        np.asarray(out["last_hidden_state"]),
        want.last_hidden_state.numpy(), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(out["pooler_output"]),
        want.pooler_output.numpy(), rtol=1e-3, atol=1e-4)


def test_shim_is_scoped_and_removable():
    import sys
    from mmlspark_tpu.interop.onnx_shim import uninstall_onnx_shim
    install_onnx_shim()
    assert getattr(sys.modules["onnx"], "__mmlspark_tpu_shim__", False)
    uninstall_onnx_shim()
    assert "onnx" not in sys.modules
    install_onnx_shim()      # leave installed for other tests' exports
