"""Interop bridge tests: pandas round-trips always; pyspark when present
(parity role: the generated PySpark surface, codegen/Wrappable.scala)."""

import numpy as np
import pytest

from mmlspark_tpu.interop import (fit_pandas, make_pandas_udf_fn,
                                  spark_transform, transform_pandas)

pd = pytest.importorskip("pandas")


def _pdf(n=12, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "feats": [rng.normal(0, 1, 6).astype(np.float32) for _ in range(n)],
        "label": rng.integers(0, 2, n).astype(np.float64),
    })


def _fitted_model():
    from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier
    est = LightGBMClassifier(features_col="feats", label_col="label",
                             num_iterations=5, num_leaves=4)
    return fit_pandas(est, _pdf(40))


class TestPandasBridge:
    def test_fit_and_transform_pandas(self):
        model = _fitted_model()
        out = transform_pandas(model, _pdf(8, seed=1))
        assert "prediction" in out.columns and len(out) == 8
        assert set(np.unique(out["prediction"])) <= {0.0, 1.0}

    def test_transform_preserves_input_columns(self):
        model = _fitted_model()
        out = transform_pandas(model, _pdf(5, seed=2))
        assert "feats" in out.columns and "label" in out.columns

    def test_udf_fn_selects_output_cols(self):
        model = _fitted_model()
        fn = make_pandas_udf_fn(model, output_cols=["prediction"])
        out = fn(_pdf(6, seed=3))
        assert list(out.columns) == ["prediction"]

    def test_pipeline_through_pandas(self):
        from mmlspark_tpu.core.pipeline import Pipeline
        from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor
        from mmlspark_tpu.stages.misc import RenameColumn
        pdf = _pdf(30)
        pipe = Pipeline(stages=[
            RenameColumn(input_col="feats", output_col="scaled"),
            LightGBMRegressor(features_col="scaled", label_col="label",
                              num_iterations=3, num_leaves=4)])
        model = fit_pandas(pipe, pdf)
        out = transform_pandas(model, pdf)
        assert "prediction" in out.columns


class TestSparkBridge:
    def test_spark_transform_gated(self):
        model = _fitted_model()
        try:
            import pyspark  # noqa: F401
            has_pyspark = True
        except ImportError:
            has_pyspark = False
        if not has_pyspark:
            with pytest.raises(ImportError, match="pyspark"):
                spark_transform(model, None, sample_pdf=_pdf(2))
            return
        # pyspark available: full local-mode integration
        from pyspark.sql import SparkSession
        spark = (SparkSession.builder.master("local[1]")
                 .appName("interop-test").getOrCreate())
        try:
            pdf = _pdf(10, seed=4)
            sdf = spark.createDataFrame(
                pd.DataFrame({"feats": [v.tolist() for v in pdf["feats"]],
                              "label": pdf["label"]}))
            out = spark_transform(model, sdf, output_cols=["prediction"],
                                  sample_pdf=pdf.head(2))
            rows = out.collect()
            assert len(rows) == 10
        finally:
            spark.stop()
