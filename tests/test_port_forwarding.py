"""PortForwarder relay tests (parity: io/http/PortForwarding.scala)."""

import socket
import socketserver
import threading

from mmlspark_tpu.io.http.port_forwarding import (PortForwarder,
                                                  forward_port_via_ssh)


class _Echo(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            data = self.request.recv(4096)
            if not data:
                return
            self.request.sendall(b"echo:" + data)


def _echo_server():
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Echo)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


def test_forward_roundtrip():
    srv, port = _echo_server()
    try:
        with PortForwarder("127.0.0.1", port) as fwd:
            with socket.create_connection(("127.0.0.1", fwd.local_port),
                                          timeout=5) as c:
                c.sendall(b"hello")
                assert c.recv(4096) == b"echo:hello"
                c.sendall(b"again")
                assert c.recv(4096) == b"echo:again"
    finally:
        srv.shutdown()


def test_concurrent_connections():
    srv, port = _echo_server()
    try:
        with PortForwarder("127.0.0.1", port) as fwd:
            conns = [socket.create_connection(
                ("127.0.0.1", fwd.local_port), timeout=5) for _ in range(4)]
            for i, c in enumerate(conns):
                c.sendall(f"m{i}".encode())
            for i, c in enumerate(conns):
                assert c.recv(4096) == f"echo:m{i}".encode()
            for c in conns:
                c.close()
    finally:
        srv.shutdown()


def test_dead_backend_closes_client_after_retries():
    # a port with nothing listening: client conn must be closed, not hang
    with PortForwarder("127.0.0.1", 1, connect_retries=1,
                       backoff_s=0.01) as fwd:
        with socket.create_connection(("127.0.0.1", fwd.local_port),
                                      timeout=5) as c:
            c.settimeout(5)
            assert c.recv(4096) == b""  # EOF — forwarder gave up


def test_stop_releases_port():
    srv, port = _echo_server()
    try:
        fwd = PortForwarder("127.0.0.1", port).start()
        lp = fwd.local_port
        fwd.stop()
        # port is free again: a fresh bind succeeds
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", lp))
        s.close()
    finally:
        srv.shutdown()


def test_ssh_argv_shape():
    argv, proc = forward_port_via_ssh("10.0.0.5", 8898, 8898,
                                      ssh_host="gateway", ssh_user="u",
                                      key_file="/k", start=False)
    assert proc is None
    assert argv[0] == "ssh" and "-N" in argv
    assert "127.0.0.1:8898:10.0.0.5:8898" in " ".join(argv)
    assert argv[-1] == "u@gateway" and "-i" in argv
