"""Time-series plane: ring wraparound exactness, spike-preserving
downsample tiers, reset-tolerant rate(), sustained-signal hysteresis
under an injectable clock, the registry sampler's counter/gauge/histogram
reduction, fixed-memory byte accounting under a long synthetic run,
concurrent sample/query under the lock sanitizer, GET /debug/timeseries
on both transports, driver-side cluster series surviving an ungraceful
worker restart, /healthz alert reasons, and the mixed-tenant-chaos
acceptance drill (scorecard timeline dip+recovery around the restart,
queue-saturation alert firing during backlog and resolving after
quiesce).
"""

import http.client
import json
import threading
import time

import pytest

from mmlspark_tpu.observability import (counter, gauge, histogram,
                                        reset_all)
from mmlspark_tpu.observability.federation import FEDERATION_INTERVAL_ENV
from mmlspark_tpu.observability.ledger import reset_ledger
from mmlspark_tpu.observability.slo import reset_tracker
from mmlspark_tpu.observability.timeseries import (
    INTERVAL_ENV, AlertEngine, AlertRule, ClusterSampler, RegistrySampler,
    TimeSeriesStore, _Ring, default_alert_rules, get_alert_engine,
    get_sampler, get_store, parse_alert_rules, parse_tiers,
    render_sparklines, reset_alert_engine, reset_store, set_alert_engine,
    set_store)
from mmlspark_tpu.observability.watchdog import reset_watchdog
from mmlspark_tpu.reliability import get_injector, reset_breakers
from mmlspark_tpu.tuning.observations import (ObservationStore,
                                              set_store as set_obs_store,
                                              reset_store as reset_obs_store)


@pytest.fixture(autouse=True)
def _clean_slate():
    for reset in (reset_store, reset_alert_engine, reset_ledger,
                  reset_tracker, reset_watchdog, reset_breakers, reset_all):
        reset()
    get_injector().clear()
    set_obs_store(ObservationStore())
    yield
    for reset in (reset_store, reset_alert_engine, reset_ledger,
                  reset_tracker, reset_watchdog, reset_breakers,
                  reset_obs_store, reset_all):
        reset()
    get_injector().clear()


# ---------------------------------------------------------------------------
# ring + store core


def test_ring_wraparound_is_exact():
    """After wrapping, the ring holds exactly the last `slots` epochs —
    recycled buckets carry the new epoch's stats, never stale ones."""
    ring = _Ring(1.0, 8)
    for t in range(20):                      # 20 epochs through 8 slots
        ring.feed(float(t) + 0.5, float(t))
    rows = ring.buckets(now=19.5, seconds=8.0)
    assert [e for e, *_ in rows] == list(range(12, 20))
    for e, mn, mx, total, count, last in rows:
        assert mn == mx == last == float(e)
        assert count == 1.0 and total == float(e)
    # epochs older than the span are gone, not aliased
    assert ring.buckets(now=19.5, seconds=100.0) == rows


def test_downsample_tiers_preserve_min_max_mean():
    """A one-sample spike survives into the coarse tier's min/max even
    though the mean flattens it."""
    store = TimeSeriesStore(tiers=((1.0, 120), (10.0, 18)))
    for t in range(10):
        store.record("sig", 100.0 if t == 3 else 0.0, t=float(t))
    fine = store.range("sig", seconds=10.0, at=10.0, tier=0)
    assert [b["max"] for b in fine] == [0, 0, 0, 100, 0, 0, 0, 0, 0, 0]
    coarse = store.range("sig", seconds=10.0, at=10.0, tier=1)
    assert len(coarse) == 1
    b = coarse[0]
    assert b["min"] == 0.0 and b["max"] == 100.0
    assert b["mean"] == pytest.approx(10.0)
    assert b["count"] == 10 and b["last"] == 0.0


def test_range_picks_finest_covering_tier_and_merges_labels():
    store = TimeSeriesStore(tiers=((1.0, 10), (10.0, 10)))
    for t in range(30):
        store.record("depth", float(t % 7), {"port": "a"}, t=float(t))
        store.record("depth", float(t % 3), {"port": "b"}, t=float(t))
    # 30 s exceeds the fine tier's 10-slot span -> coarse tier
    buckets = store.range("depth", seconds=30.0, at=30.0)
    assert all(b["width"] == 10.0 for b in buckets)
    # labels=None merges: count sums both series
    assert all(b["count"] == 20 for b in buckets)
    one = store.range("depth", seconds=30.0, labels={"port": "b"}, at=30.0)
    assert all(b["max"] <= 2.0 for b in one)


def test_rate_tolerates_counter_reset():
    store = TimeSeriesStore(tiers=((1.0, 120),))
    for t, v in enumerate([0, 10, 20, 5, 15]):
        store.record("req_total", float(v), t=float(t), kind="counter")
    # increases: 10 + 10 + 5 (post-reset value) + 10 = 35 over 4 s
    assert store.rate("req_total", seconds=4.0, at=4.0) == \
        pytest.approx(8.75)
    # monotone series: plain delta over span
    store2 = TimeSeriesStore(tiers=((1.0, 120),))
    for t in range(5):
        store2.record("mono", float(10 * t), t=float(t), kind="counter")
    assert store2.rate("mono", seconds=4.0, at=4.0) == pytest.approx(10.0)
    # a single bucket is not evidence of a rate
    store3 = TimeSeriesStore(tiers=((1.0, 120),))
    store3.record("one", 5.0, t=0.0, kind="counter")
    assert store3.rate("one", seconds=4.0, at=0.5) is None


def test_sustained_requires_full_window_coverage():
    store = TimeSeriesStore(tiers=((1.0, 120),))
    store.record("hot", 9.0, t=10.0)
    # one fresh sample is never "sustained for 5s"
    assert not store.sustained("hot", lambda v: v > 1.0, 5.0, at=10.5)
    for t in range(11, 16):
        store.record("hot", 9.0, t=float(t))
    assert store.sustained("hot", lambda v: v > 1.0, 5.0, at=15.5)
    # one bad bucket inside the window breaks it
    store.record("hot", 0.0, t=16.0)
    assert not store.sustained("hot", lambda v: v > 1.0, 5.0, at=16.5)


def test_ewma_and_latest():
    store = TimeSeriesStore(tiers=((1.0, 60),))
    for t, v in enumerate([0.0, 0.0, 10.0]):
        store.record("sig", v, t=float(t))
    assert store.latest("sig") == (2.0, 10.0)
    ew = store.ewma("sig", seconds=3.0, at=3.0, alpha=0.5)
    assert 0.0 < ew < 10.0


def test_store_rejects_junk_and_parse_fallbacks():
    store = TimeSeriesStore(tiers=((1.0, 4),))
    assert not store.record("x", float("nan"))
    assert not store.record("x", "not-a-number")
    assert parse_tiers("garbage") == parse_tiers(None) or \
        parse_tiers("garbage") == parse_tiers("")
    assert parse_tiers("2x10,1x5") == ((1.0, 5), (2.0, 10))  # sorted
    rules = parse_alert_rules("q:series:gt:0.5:for=1:keep=2;bad;also:bad")
    assert len(rules) == 1
    assert rules[0].for_seconds == 1.0
    assert rules[0].keep_firing_seconds == 2.0


def test_byte_budget_bounded_under_long_synthetic_run():
    """The fixed-memory guarantee: a long run with more label sets than
    the cap never grows past byte_budget(), and overflow is counted as
    drops instead of allocation."""
    store = TimeSeriesStore(tiers=((1.0, 16), (8.0, 16)), max_series=16)
    budget = store.byte_budget()
    mid = None
    for i in range(50_000):
        store.record("m", float(i % 13), {"k": str(i % 40)},
                     t=float(i) * 0.01)
        if i == 25_000:
            mid = store.approx_bytes()
    assert store.approx_bytes() == mid        # flat after warm-up
    assert store.approx_bytes() <= budget
    stats = store.stats()
    assert stats["series"] == 16
    assert stats["dropped"] > 0               # the cap did its job
    assert stats["samples"] + stats["dropped"] == 50_000


def test_sparklines_render_shape():
    store = TimeSeriesStore(tiers=((1.0, 60),))
    for t in range(8):
        store.record("ramp", float(t), t=float(t) + 0.5)
    text = render_sparklines(store, seconds=8.0, at=8.0)
    assert text.startswith("ramp")
    assert "▁" in text and "█" in text
    assert "min=0" in text and "max=7" in text


# ---------------------------------------------------------------------------
# alert engine hysteresis


def _fake_clock():
    clock = {"t": 0.0}
    return clock, (lambda: clock["t"])


def test_alert_fires_only_when_sustained_and_does_not_flap():
    clock, fn = _fake_clock()
    store = TimeSeriesStore(tiers=((1.0, 120),), clock=fn)
    engine = AlertEngine(store, clock=fn, on_fire=())
    engine.add_rule(AlertRule("deep", "q", "gt", 5.0, for_seconds=3.0,
                              keep_firing_seconds=2.0, field="max"))
    transitions = []

    def step(t, value):
        clock["t"] = t
        store.record("q", value, t=t)
        transitions.extend(engine.evaluate())

    step(0.0, 9.0)
    step(1.0, 9.0)
    assert engine.firing() == []              # not sustained yet
    step(2.0, 9.0)
    step(3.0, 9.0)
    assert engine.firing() == ["deep"]
    # a one-bucket dip below threshold must NOT resolve (hysteresis)
    step(4.0, 1.0)
    assert engine.firing() == ["deep"]
    step(5.0, 9.0)                            # bad again: last_bad refreshed
    assert engine.firing() == ["deep"]
    # resolve only after keep_firing_seconds of continuously good evidence
    step(6.0, 1.0)
    assert engine.firing() == ["deep"]        # 6 - 5 = 1s < keep window
    step(7.0, 1.0)
    assert engine.firing() == []              # 7 - 5 = 2s: window elapsed
    kinds = [tr["to"] for tr in transitions]
    assert kinds == ["firing", "resolved"]    # exactly one cycle, no flap
    fire = transitions[0]
    assert fire["rule"] == "deep" and fire["window"]  # bundle-able context
    state = engine.state()["deep"]
    assert state["firing"] is False and state["op"] == "gt"


def test_alert_on_fire_hook_and_default_rules():
    clock, fn = _fake_clock()
    store = TimeSeriesStore(tiers=((1.0, 120),), clock=fn)
    seen = []
    engine = AlertEngine(store, clock=fn,
                         on_fire=[lambda rule, rec: seen.append(
                             (rule.name, rec["to"]))])
    engine.add_rule(AlertRule("hot", "s", "ge", 1.0, for_seconds=2.0))
    for t in range(3):
        clock["t"] = float(t)
        store.record("s", 2.0, t=float(t))
        engine.evaluate()
    assert seen == [("hot", "firing")]
    names = {r.name for r in default_alert_rules()}
    assert names == {"burn-rate", "queue-saturation", "breaker-flap",
                     "kv-quant-error"}


# ---------------------------------------------------------------------------
# registry sampler reduction


def test_sampler_reduces_counters_gauges_histograms():
    clock, fn = _fake_clock()
    store = TimeSeriesStore(tiers=((1.0, 120),), clock=fn)
    sampler = RegistrySampler(store, interval=0, clock=fn)
    c = counter("mmlspark_test_ts_total", "t", ("k",))
    g = gauge("mmlspark_test_ts_depth", "t")
    h = histogram("mmlspark_test_ts_lat", "t",
                  buckets=(0.1, 1.0, 10.0))
    g.set(7.0)
    sampler.tick(now=0.0)                     # baseline scrape
    c.inc(20, k="a")
    for _ in range(10):
        h.observe(0.5)
    g.set(9.0)
    clock["t"] = 2.0
    sampler.tick(now=2.0)
    # counter -> :rate over the 2 s interval
    assert store.latest("mmlspark_test_ts_total:rate",
                        {"k": "a"})[1] == pytest.approx(10.0)
    # gauge -> direct sample
    assert store.latest("mmlspark_test_ts_depth")[1] == 9.0
    # histogram -> interpolated p50/p99 from the interval's new counts
    p50 = store.latest("mmlspark_test_ts_lat:p50")[1]
    p99 = store.latest("mmlspark_test_ts_lat:p99")[1]
    assert 0.1 < p50 <= 1.0 and p50 <= p99 <= 1.0
    # counter reset (restart): rate records the post-reset value, not
    # a negative step
    c.inc(4, k="a")
    clock["t"] = 3.0
    sampler.tick(now=3.0)
    assert store.latest("mmlspark_test_ts_total:rate",
                        {"k": "a"})[1] == pytest.approx(4.0)
    # extra sources: sampled when they return a number, skipped on None
    vals = iter([0.25, None])
    sampler.add_source("mmlspark_test_ts_src", lambda: next(vals))
    clock["t"] = 4.0
    sampler.tick(now=4.0)
    clock["t"] = 5.0
    sampler.tick(now=5.0)
    assert store.latest("mmlspark_test_ts_src") == (4.0, 0.25)


# ---------------------------------------------------------------------------
# concurrency under the lock sanitizer


def test_concurrent_sample_and_query_under_lock_sanitizer(monkeypatch):
    import mmlspark_tpu.reliability.lock_sanitizer as ls
    monkeypatch.setenv(ls.SANITIZER_ENV, "1")
    ls.reset()
    assert ls.enabled()
    store = TimeSeriesStore(tiers=((0.01, 64), (0.1, 64)))
    engine = AlertEngine(store, on_fire=())
    engine.add_rule(AlertRule("busy", "m", "gt", 0.5, for_seconds=0.05))
    errors = []
    stop = threading.Event()

    def writer(i):
        try:
            n = 0
            while not stop.is_set():
                store.record("m", float(n % 10), {"w": str(i)})
                n += 1
        except Exception as exc:              # pragma: no cover
            errors.append(exc)

    def reader():
        try:
            while not stop.is_set():
                store.range("m", seconds=1.0)
                store.rate("m", seconds=1.0)
                store.snapshot(seconds=1.0)
                engine.evaluate()
        except Exception as exc:              # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(3)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.4)
    stop.set()
    for t in threads:
        t.join(timeout=2.0)
    assert errors == []
    assert ls.cycle_reports() == [], (
        "lock-order cycles in the time-series plane:\n" + "\n".join(
            " -> ".join(r["sites"]) for r in ls.cycle_reports()))
    assert store.stats()["samples"] > 0


# ---------------------------------------------------------------------------
# /debug/timeseries over HTTP, both transports


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read()
    ctype = r.getheader("Content-Type", "")
    conn.close()
    return r.status, ctype, body


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_debug_timeseries_route_both_transports(transport, monkeypatch):
    from mmlspark_tpu.serving.server import WorkerServer
    monkeypatch.setenv(INTERVAL_ENV, "0")     # tests drive tick() directly
    ws = WorkerServer(transport=transport)
    try:
        for _ in range(3):
            assert _get(ws.port, "/healthz")[0] == 200
        sampler = get_sampler()
        assert sampler is not None and sampler.interval == 0
        sampler.tick()
        time.sleep(0.05)
        sampler.tick()                        # second scrape: rates exist
        status, ctype, body = _get(ws.port, "/debug/timeseries?seconds=60")
        assert status == 200 and ctype.startswith("application/json")
        payload = json.loads(body)
        names = {s["name"] for s in payload["series"]}
        assert "mmlspark_queue_saturation" in names
        assert "mmlspark_serving_requests_total:rate" in names
        assert payload["point_fields"] == \
            ["t", "mean", "min", "max", "last", "count"]
        assert payload["stats"]["approx_bytes"] <= \
            payload["stats"]["byte_budget"]
        assert "queue-saturation" in payload["alerts"]
        assert payload["firing"] == []
        # name filter
        _, _, filtered = _get(
            ws.port, "/debug/timeseries?series=mmlspark_queue_saturation")
        fnames = {s["name"] for s in json.loads(filtered)["series"]}
        assert fnames == {"mmlspark_queue_saturation"}
        # text sparkline view
        status, ctype, text = _get(
            ws.port,
            "/debug/timeseries?format=text&seconds=60"
            "&series=mmlspark_queue_saturation")
        assert status == 200 and ctype.startswith("text/plain")
        assert "mmlspark_queue_saturation" in text.decode("utf-8")
    finally:
        ws.close()
    assert get_sampler() is None              # refcount drained on close


def test_sampler_refcount_shared_across_servers(monkeypatch):
    from mmlspark_tpu.serving.server import WorkerServer
    monkeypatch.setenv(INTERVAL_ENV, "0")
    a = WorkerServer(transport="threaded")
    b = WorkerServer(transport="threaded")
    try:
        assert get_sampler() is not None
        a.close()
        assert get_sampler() is not None      # b still holds a ref
    finally:
        a.close()                             # double-close: no over-release
        b.close()
    assert get_sampler() is None


# ---------------------------------------------------------------------------
# /healthz alert reasons (satellite: firing shows up, resolving clears it)


def test_healthz_reports_firing_alert_and_clears_on_resolve(monkeypatch):
    from mmlspark_tpu.serving.server import WorkerServer
    monkeypatch.setenv(INTERVAL_ENV, "0")
    clock, fn = _fake_clock()
    store = TimeSeriesStore(tiers=((1.0, 120),), clock=fn)
    set_store(store)
    engine = AlertEngine(store, clock=fn, on_fire=())
    engine.add_rule(AlertRule("test-burn", "burn", "gt", 1.0,
                              for_seconds=2.0, keep_firing_seconds=1.0))
    set_alert_engine(engine)
    ws = WorkerServer(transport="threaded")
    try:
        for t in range(3):
            clock["t"] = float(t)
            store.record("burn", 5.0, t=float(t))
            engine.evaluate()
        assert engine.firing() == ["test-burn"]
        _, _, body = _get(ws.port, "/healthz")
        health = json.loads(body)
        assert health["status"] == "degraded"
        assert "alert_firing:test-burn" in health["reasons"]
        for t in range(3, 7):
            clock["t"] = float(t)
            store.record("burn", 0.0, t=float(t))
            engine.evaluate()
        assert engine.firing() == []
        _, _, body = _get(ws.port, "/healthz")
        health = json.loads(body)
        assert not any(r.startswith("alert_firing:")
                       for r in health["reasons"])
    finally:
        ws.close()


# ---------------------------------------------------------------------------
# driver-side cluster series


def test_cluster_sampler_series_survive_worker_restart(monkeypatch):
    from mmlspark_tpu.serving.distributed import ServingCluster
    monkeypatch.setenv(FEDERATION_INTERVAL_ENV, "0")
    monkeypatch.setenv(INTERVAL_ENV, "0")
    cluster = ServingCluster(2, reply_timeout=5.0)
    try:
        for w in cluster.workers:
            assert w.heartbeat()
        ts = cluster.driver.timeseries
        keys = dict(ts.store.series_keys())
        assert "cluster_queue_depth" in keys
        assert "cluster_in_flight" in keys
        before = ts.store.latest("cluster_queue_depth",
                                 {"worker": "worker-0"})
        assert before is not None
        n_series = len(ts.store.series_keys())
        # ungraceful restart: same id, fresh process-side state
        replacement = cluster.restart_worker("worker-0")
        assert replacement.heartbeat()
        after = ts.store.latest("cluster_queue_depth",
                                {"worker": "worker-0"})
        assert after is not None and after[0] > before[0]
        # keyed by worker id: the restarted worker CONTINUED its series
        assert len(ts.store.series_keys()) == n_series
        view = cluster.driver.cluster_view()
        names = {s["name"] for s in view["timeseries"]["series"]}
        assert "cluster_queue_depth" in names
    finally:
        cluster.close()


# ---------------------------------------------------------------------------
# acceptance: mixed-tenant chaos — timeline dip+recovery, alert lifecycle


def test_chaos_timeline_and_queue_saturation_alert_e2e(monkeypatch):
    from mmlspark_tpu.loadgen import (cluster_echo_engine, get_scenario,
                                      run_scenario)
    from mmlspark_tpu.serving.distributed import ServingCluster

    monkeypatch.setenv(FEDERATION_INTERVAL_ENV, "0")
    # fast real-time sampling so queue saturation accrues evidence at
    # sub-run granularity; short alert windows so the default-rule-shaped
    # queue-saturation alert can fire AND resolve inside one test
    monkeypatch.setenv(INTERVAL_ENV, "0.05")
    engine = AlertEngine(get_store(), on_fire=())
    for rule in default_alert_rules(for_seconds=0.3,
                                    keep_firing_seconds=0.5):
        engine.add_rule(rule)
    set_alert_engine(engine)

    restart_at = 0.7
    scenario = get_scenario(
        "mixed-tenant-chaos", duration_s=1.5, rate=150.0,
        faults="enqueue:error:every=3:times=24",
        restart_at_s=restart_at, restart_worker="worker-1",
        deadline_s=3.0, max_retries=2)
    # queue depth (3 x 4) far below sender concurrency: guaranteed backlog
    cluster = ServingCluster(3, reply_timeout=5.0, max_queue=4)
    stop = threading.Event()
    echo = cluster_echo_engine(cluster, stop, service_s=0.04, batch=4)
    try:
        card = run_scenario(scenario, cluster, senders=32)
        # quiesce: traffic over, echo engine still draining; the global
        # sampler keeps scraping an emptying queue until the alert's
        # keep-firing window of good evidence elapses
        deadline = time.monotonic() + 6.0
        while "queue-saturation" in engine.firing() and \
                time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        stop.set()
        echo.join(timeout=2.0)
        cluster.close()

    assert card["lost"] == 0 and card["shed"] > 0

    # -- timeline: complete, consistent, dip visible, recovery after ----
    tl = card["timeline"]
    buckets = tl["buckets"]
    assert buckets, "scorecard timeline must not be empty"
    assert sum(b["ok"] + b["shed"] + b["errors"] for b in buckets) == \
        card["ok"] + card["shed"] + card["errors"]
    assert sum(b["arrivals"] for b in buckets) == card["arrivals"]
    # chaos left a dent somewhere (injected faults + tiny queue)
    assert card["shed"] + card["errors"] > 0
    # the mid-run restart stalls senders: goodput dips visibly in the
    # buckets right after restart_at, then recovers
    bw = tl["bucket_s"]
    pre = [b for b in buckets if b["t0"] < restart_at]
    post = [b for b in buckets if restart_at <= b["t0"] < restart_at + 4 * bw]
    tail = [b for b in buckets if b["t0"] >= restart_at + 4 * bw]
    assert pre and post and tail
    dip = min(b["goodput_rps"] for b in post)
    assert dip < 0.6 * max(b["goodput_rps"] for b in pre), \
        "no visible goodput dip after the worker restart"
    assert max(b["goodput_rps"] for b in tail) > dip, \
        "no goodput recovery after the restart dip"
    assert any(b["ok"] > 0 for b in tail)

    # -- alert lifecycle: fired during backlog, resolved after quiesce --
    from mmlspark_tpu.observability import snapshot
    snap = snapshot()
    trans = {}
    for row in snap["mmlspark_alert_transitions_total"]["series"]:
        labels = row["labels"]
        trans[(labels["rule"], labels["to"])] = row["value"]
    assert trans.get(("queue-saturation", "firing"), 0) >= 1, \
        "queue-saturation alert never fired under a guaranteed backlog"
    assert trans.get(("queue-saturation", "resolved"), 0) >= 1, \
        "queue-saturation alert never resolved after quiesce"
    assert "queue-saturation" not in engine.firing()
    firing_gauge = {
        row["labels"]["rule"]: row["value"]
        for row in snap["mmlspark_alerts_firing"]["series"]}
    assert firing_gauge["queue-saturation"] == 0.0

    # the global store accrued sampled history across the run
    names = set(get_store().names())
    assert "mmlspark_queue_saturation" in names
