"""JaxModel — the generic non-ONNX model path (parity: CNTKModel,
``deep-learning/.../cntk/CNTKModel.scala:250-330``, feed/fetch + coercion
``:387-434``). The CNTK format itself is deliberately subsumed: legacy graphs
convert to ONNX; native models are JAX callables run by this stage."""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.pipeline import PipelineStage
from mmlspark_tpu.models.jax_model import JaxModel


def linear_apply(params, feeds):
    """Module-level so save/load can persist it by import path."""
    import jax.numpy as jnp
    x = feeds["input"]
    h = jnp.maximum(x @ params["w1"] + params["b1"], 0.0)
    return {"logits": h @ params["w2"] + params["b2"],
            "hidden": h}


def _params(seed=0, din=6, dh=8, dout=3):
    rng = np.random.default_rng(seed)
    return {"w1": rng.normal(0, 0.5, (din, dh)).astype(np.float32),
            "b1": np.zeros(dh, dtype=np.float32),
            "w2": rng.normal(0, 0.5, (dh, dout)).astype(np.float32),
            "b2": np.zeros(dout, dtype=np.float32)}


def _df(n=11, din=6, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, din)).astype(np.float32)
    return DataFrame({"feats": [X[i] for i in range(n)]}, npartitions=2), X


def _ref(params, X):
    h = np.maximum(X @ params["w1"] + params["b1"], 0)
    return h @ params["w2"] + params["b2"]


class TestJaxModel:
    def test_transform_dict_outputs(self):
        params = _params()
        m = JaxModel(linear_apply, params, feed_dict={"input": "feats"},
                     mini_batch_size=4, pin_devices=False)
        df, X = _df()
        out = m.transform(df)
        np.testing.assert_allclose(np.stack(list(out["logits"])),
                                   _ref(params, X), rtol=1e-5, atol=1e-5)
        assert "hidden" in out.columns

    def test_fetch_dict_selects_and_renames(self):
        params = _params()
        m = JaxModel(linear_apply, params, feed_dict={"input": "feats"},
                     fetch_dict={"score": "logits"},
                     mini_batch_size=4, pin_devices=False)
        df, X = _df()
        out = m.transform(df)
        assert "score" in out.columns and "hidden" not in out.columns

    def test_single_array_output(self):
        m = JaxModel(lambda p, f: f["input"] * 2.0, None,
                     feed_dict={"input": "x"}, pin_devices=False)
        df = DataFrame({"x": np.arange(5, dtype=np.float32)})
        out = m.transform(df)
        np.testing.assert_allclose(out["output"],
                                   np.arange(5, dtype=np.float32) * 2)

    def test_bfloat16_compute(self):
        params = _params()
        m = JaxModel(linear_apply, params, feed_dict={"input": "feats"},
                     compute_dtype="bfloat16", mini_batch_size=4,
                     pin_devices=False)
        df, X = _df()
        out = m.transform(df)
        got = np.stack(list(out["logits"]))
        assert got.dtype == np.float32  # bf16 widened at the host boundary
        np.testing.assert_allclose(got, _ref(params, X), rtol=0.05, atol=0.05)

    def test_save_load_roundtrip_by_import_path(self, tmp_path):
        params = _params()
        m = JaxModel(linear_apply, params, feed_dict={"input": "feats"},
                     fetch_dict={"score": "logits"}, mini_batch_size=4,
                     pin_devices=False)
        df, X = _df()
        expect = np.stack(list(m.transform(df)["score"]))
        path = str(tmp_path / "jm")
        m.save(path)
        m2 = PipelineStage.load(path)
        assert m2.apply_fn is linear_apply  # resolved by import path
        got = np.stack(list(m2.transform(df)["score"]))
        np.testing.assert_allclose(got, expect, rtol=1e-6)

    def test_closure_is_transient_with_clear_error(self, tmp_path):
        m = JaxModel(lambda p, f: f["input"], None,
                     feed_dict={"input": "x"}, pin_devices=False)
        path = str(tmp_path / "jm")
        m.save(path)
        m2 = PipelineStage.load(path)
        df = DataFrame({"x": np.arange(3, dtype=np.float32)})
        with pytest.raises(ValueError, match="apply_fn is unset"):
            m2.transform(df)
        m2.set(apply_fn=lambda p, f: f["input"])
        assert len(m2.transform(df)) == 3

    def test_zoo_resnet_features(self):
        """The transfer-learning path: zoo network as a JaxModel."""
        from mmlspark_tpu.models.zoo.resnet import (RESNET18_CFG,
                                                    init_resnet,
                                                    resnet_apply)
        params = init_resnet(RESNET18_CFG, seed=0)

        def apply(p, feeds):
            return {"features": resnet_apply(p, feeds["image"], RESNET18_CFG,
                                             features_only=True)}

        rng = np.random.default_rng(0)
        imgs = rng.normal(0, 1, (3, 32, 32, 3)).astype(np.float32)
        df = DataFrame({"image": [imgs[i] for i in range(3)]})
        m = JaxModel(apply, params, feed_dict={"image": "image"},
                     mini_batch_size=2, pin_devices=False)
        out = m.transform(df)
        feats = np.stack(list(out["features"]))
        assert feats.shape[0] == 3 and feats.ndim == 2
        assert np.isfinite(feats).all()


def test_mesh_sharded_matches_unsharded(rng):
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.parallel.mesh import MeshContext
    import jax.numpy as jnp

    w = jnp.asarray(rng.normal(0, 0.5, (5, 3)), jnp.float32)

    def apply(params, feeds):
        return {"y": jnp.tanh(feeds["input"] @ params)}

    X = rng.normal(0, 1, (21, 5)).astype(np.float32)   # 21 % 8 != 0
    col = np.empty(len(X), object)
    col[:] = list(X)
    df = DataFrame({"x": col})
    plain = JaxModel(apply, w, feed_dict={"input": "x"},
                     mini_batch_size=16, pin_devices=False)
    want = np.stack(list(plain.transform(df)["y"]))
    with MeshContext({"data": 8}):
        sharded = JaxModel(apply, w, feed_dict={"input": "x"},
                           mini_batch_size=16, mesh_sharded=True)
        got = np.stack(list(sharded.transform(df)["y"]))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
