"""Step-level checkpoint/resume + profiling hooks."""

import os

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.utils.checkpoint import TrainingCheckpointer


def test_checkpointer_atomic_save_load(tmp_path):
    c = TrainingCheckpointer(str(tmp_path / "ck"), keep=2)
    assert c.latest() is None
    c.save(5, {"booster.txt": "model-at-5",
               "meta.json": {"completed_iterations": 5},
               "weights.npy": np.arange(4.0)})
    c.save(10, {"booster.txt": "model-at-10",
                "meta.json": {"completed_iterations": 10}})
    step, files = c.latest()
    assert step == 10
    assert TrainingCheckpointer.read_text(files["booster.txt"]) == "model-at-10"
    assert TrainingCheckpointer.read_json(files["meta.json"]) \
        == {"completed_iterations": 10}
    # pruning: keep=2 retains both; a third save drops step 5
    c.save(15, {"booster.txt": "x", "meta.json": {"completed_iterations": 15}})
    steps = sorted(int(d[5:]) for d in os.listdir(str(tmp_path / "ck"))
                   if d.startswith("step_"))
    assert steps == [10, 15]


def test_checkpointer_ignores_stale_latest(tmp_path):
    c = TrainingCheckpointer(str(tmp_path / "ck"))
    c.save(3, {"meta.json": {"completed_iterations": 3}})
    # simulate a crash that removed the step dir but left LATEST behind
    import shutil
    shutil.rmtree(os.path.join(str(tmp_path / "ck"), "step_00000003"))
    assert c.latest() is None


def _df(n=300, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    feats = np.empty(n, dtype=object)
    for i in range(n):
        feats[i] = X[i]
    return DataFrame({"features": feats, "label": y})


def test_gbdt_checkpoint_resume_matches_uninterrupted(tmp_path):
    """Train 12 iters straight vs 6 iters, 'crash', resume for 12 total —
    the resumed booster must end with the same number of trees and
    near-identical predictions."""
    from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier

    df = _df()
    common = dict(num_leaves=7, learning_rate=0.3, min_data_in_leaf=5, seed=0)
    full = LightGBMClassifier(num_iterations=12, **common).fit(df)

    ckdir = str(tmp_path / "gbdt_ck")
    LightGBMClassifier(num_iterations=6, checkpoint_dir=ckdir,
                       checkpoint_interval=2, **common).fit(df)
    c = TrainingCheckpointer(ckdir)
    assert c.latest_step() == 6

    resumed = LightGBMClassifier(num_iterations=12, checkpoint_dir=ckdir,
                                 checkpoint_interval=2, **common).fit(df)
    assert c.latest_step() == 12
    out_f = full.transform(df)["prediction"]
    out_r = resumed.transform(df)["prediction"]
    # tree-for-tree equality is not guaranteed (gradient state is recomputed
    # from scores at resume, which matches exactly for this loss) — require
    # prediction agreement
    assert (out_f == out_r).mean() > 0.98


def test_gbdt_checkpoint_noop_when_complete(tmp_path):
    from mmlspark_tpu.models.gbdt.train import train

    rng = np.random.default_rng(1)
    X = rng.normal(size=(100, 3))
    y = (X[:, 0] > 0).astype(float)
    ckdir = str(tmp_path / "ck2")
    b1 = train({"objective": "binary", "num_iterations": 5,
                "min_data_in_leaf": 2, "checkpoint_dir": ckdir,
                "checkpoint_interval": 1}, X, y)
    # re-invoking with the same budget trains 0 further iterations
    b2 = train({"objective": "binary", "num_iterations": 5,
                "min_data_in_leaf": 2, "checkpoint_dir": ckdir,
                "checkpoint_interval": 1}, X, y)
    assert b1.num_trees == b2.num_trees == 5


def test_profiling_annotate_and_stopwatch():
    from mmlspark_tpu.utils.profiling import StopWatch, annotate
    with annotate("test.scope"):
        pass   # must not raise outside a trace
    sw = StopWatch()
    sw.measure(lambda: sum(range(1000)))
    assert sw.elapsed_ns >= 0


def test_profiler_trace_writes_files(tmp_path):
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.utils.profiling import trace
    d = str(tmp_path / "prof")
    with trace(d):
        jnp.arange(16.0).sum().block_until_ready()
    found = []
    for root, _dirs, files in os.walk(d):
        found += files
    assert found, "profiler trace produced no files"


class TestShardedCheckpointer:
    """Mesh-sharded train-state checkpoints (orbax) on the virtual mesh."""

    @pytest.fixture(autouse=True)
    def _needs_orbax(self):
        pytest.importorskip("orbax.checkpoint")

    def _sharded_state(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
        rng = np.random.default_rng(0)
        params = {"w": jax.device_put(
                      rng.normal(0, 1, (8, 8)).astype(np.float32),
                      NamedSharding(mesh, P("dp", "tp"))),
                  "b": jax.device_put(np.zeros(8, np.float32),
                                      NamedSharding(mesh, P()))}
        opt = jax.tree.map(jnp.zeros_like, params)
        return mesh, {"params": params, "opt": opt,
                      "step": jnp.asarray(0, jnp.int32)}

    def test_save_restore_preserves_values_and_shardings(self, tmp_path):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from mmlspark_tpu.utils.checkpoint import ShardedCheckpointer
        mesh, state = self._sharded_state()
        with ShardedCheckpointer(str(tmp_path / "ck")) as ckpt:
            state["params"]["w"] = state["params"]["w"] + 1.0
            ckpt.save(3, state)
            fresh = jax.tree.map(jnp.zeros_like, state)
            back = ckpt.restore(target=fresh)
            np.testing.assert_allclose(np.asarray(back["params"]["w"]),
                                       np.asarray(state["params"]["w"]))
            assert back["params"]["w"].sharding == \
                state["params"]["w"].sharding
            assert ckpt.latest_step() == 3

    def test_retention_and_latest(self, tmp_path):
        import jax.numpy as jnp
        from mmlspark_tpu.utils.checkpoint import ShardedCheckpointer
        with ShardedCheckpointer(str(tmp_path / "ck"),
                                 max_to_keep=2) as ckpt:
            for s in (1, 2, 3):
                ckpt.save(s, {"x": jnp.asarray(float(s))})
            assert ckpt.all_steps() == [2, 3]
            assert float(ckpt.restore()["x"]) == 3.0

    def test_restore_empty_raises(self, tmp_path):
        from mmlspark_tpu.utils.checkpoint import ShardedCheckpointer
        with ShardedCheckpointer(str(tmp_path / "ck")) as ckpt:
            with pytest.raises(FileNotFoundError):
                ckpt.restore()

    def test_restore_target_with_scalar_leaf(self, tmp_path):
        """int/float leaves (step counters) must not crash the abstract
        tree construction."""
        import jax.numpy as jnp
        import numpy as np
        from mmlspark_tpu.utils.checkpoint import ShardedCheckpointer
        with ShardedCheckpointer(str(tmp_path / "ck")) as ckpt:
            ckpt.save(1, {"w": jnp.ones(3), "step": jnp.asarray(7)})
            back = ckpt.restore(target={"w": jnp.zeros(3), "step": 0})
            assert int(back["step"]) == 7
            np.testing.assert_allclose(np.asarray(back["w"]), 1.0)


class TestSpanTracer:
    def test_spans_nest_and_export(self, tmp_path):
        import json
        import time
        from mmlspark_tpu.utils.profiling import SpanTracer, span
        with SpanTracer() as t:
            with span("outer"):
                with span("inner", detail="x"):
                    time.sleep(0.01)
        names = [e["name"] for e in t.events]
        assert names == ["inner", "outer"]  # completion order
        assert t.total("inner") >= 0.01
        assert t.total("outer") >= t.total("inner")
        p = t.export(str(tmp_path / "run.trace.json"))
        doc = json.load(open(p))
        assert doc["traceEvents"][0]["ph"] == "X"
        assert doc["traceEvents"][0]["args"] == {"detail": "x"}

    def test_pipeline_stages_traced_automatically(self):
        import numpy as np
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier
        from mmlspark_tpu.utils.profiling import SpanTracer
        rng = np.random.default_rng(0)
        df = DataFrame({"features": [rng.normal(0, 1, 4).astype(np.float32)
                                     for _ in range(30)],
                        "label": rng.integers(0, 2, 30).astype(np.float64)})
        with SpanTracer() as t:
            model = LightGBMClassifier(num_iterations=2, num_leaves=4).fit(df)
            model.transform(df)
        names = {e["name"] for e in t.events}
        assert "LightGBMClassifier.fit" in names
        assert any(n.endswith(".transform") for n in names)

    def test_span_noop_without_tracer(self):
        from mmlspark_tpu.utils.profiling import span
        with span("orphan"):
            pass  # must not raise
