"""Boosting modes beyond gbdt: goss, dart, rf.

Parity surface: LightGBM's boostingType param
(``lightgbm/.../params/LightGBMParams.scala:389-393``) and the reference
quality CSV that pins per-mode accuracy
(``benchmarks_VerifyLightGBMClassifier.csv`` rows _gbdt/_rf/_dart/_goss).
"""

import numpy as np
import pytest

from mmlspark_tpu.models.gbdt.train import train


def _binary_data(rng, n=1200, f=10):
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    logit = X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (logit + rng.normal(0, 0.5, n) > 0).astype(np.float64)
    return X, y


def _auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    pos = y == 1
    n1, n0 = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


BASE = {"objective": "binary", "num_iterations": 30, "num_leaves": 15,
        "learning_rate": 0.1, "min_data_in_leaf": 5, "seed": 3}


class TestGoss:
    def test_quality_close_to_gbdt(self, rng):
        X, y = _binary_data(rng)
        auc_gbdt = _auc(y, train(BASE, X, y).predict(X))
        auc_goss = _auc(y, train({**BASE, "boosting": "goss"}, X, y)
                        .predict(X))
        assert auc_goss > 0.85
        assert abs(auc_gbdt - auc_goss) < 0.05

    def test_alias_boosting_type(self, rng):
        X, y = _binary_data(rng, n=400)
        b = train({**BASE, "num_iterations": 5, "boosting_type": "goss"},
                  X, y)
        assert b.num_trees == 5

    def test_rejects_bagging(self, rng):
        X, y = _binary_data(rng, n=200)
        with pytest.raises(ValueError, match="GOSS"):
            train({**BASE, "boosting": "goss", "bagging_freq": 1,
                   "bagging_fraction": 0.5}, X, y)

    def test_multiclass(self, rng):
        X = rng.normal(0, 1, (600, 6)).astype(np.float32)
        y = (X[:, 0] + X[:, 1] > 0).astype(int) + (X[:, 2] > 0.5)
        b = train({"objective": "multiclass", "num_class": 3,
                   "num_iterations": 10, "boosting": "goss",
                   "min_data_in_leaf": 5, "seed": 0}, X, y.astype(float))
        pred = b.predict(X)
        assert pred.shape == (600, 3)
        assert (pred.argmax(1) == y).mean() > 0.7


class TestDart:
    def test_quality_close_to_gbdt(self, rng):
        X, y = _binary_data(rng)
        auc_dart = _auc(y, train({**BASE, "boosting": "dart"}, X, y)
                        .predict(X))
        assert auc_dart > 0.85

    def test_trees_get_rescaled(self, rng):
        X, y = _binary_data(rng, n=500)
        # drop every iteration (skip_drop=0) with high drop_rate so the
        # k/(k+1) normalization must fire
        b = train({**BASE, "num_iterations": 10, "boosting": "dart",
                   "skip_drop": 0.0, "drop_rate": 0.9}, X, y)
        assert b.num_trees == 10
        # dart-normalized leaves shrink relative to plain gbdt's
        g = train({**BASE, "num_iterations": 10}, X, y)
        assert (np.abs(b.leaf_values).max()
                < np.abs(g.leaf_values).max() + 1e-6)

    def test_save_load_roundtrip(self, rng):
        from mmlspark_tpu.models.gbdt.booster import Booster
        X, y = _binary_data(rng, n=400)
        b = train({**BASE, "num_iterations": 8, "boosting": "dart",
                   "skip_drop": 0.0}, X, y)
        b2 = Booster.from_string(b.to_string())
        np.testing.assert_allclose(b2.predict(X), b.predict(X), rtol=1e-6)

    def test_early_stopping_valid_tracking(self, rng):
        X, y = _binary_data(rng, n=800)
        Xv, yv = _binary_data(rng, n=300)
        log = []
        b = train({**BASE, "boosting": "dart", "skip_drop": 0.0,
                   "early_stopping_round": 50, "metric": "auc"},
                  X, y, valid_sets=[(Xv, yv)], eval_log=log)
        # with early stopping requested, dart returns the best-iteration
        # snapshot (later drops rescale earlier trees, so only the snapshot
        # reproduces the logged metric) — a fresh evaluation must match the
        # BEST logged value exactly
        from mmlspark_tpu.models.gbdt.objectives import get_metric
        _, (metric_fn, _hb) = get_metric("auc", "binary")
        final_auc = metric_fn(yv, b.predict(Xv), np.ones(len(yv)))
        assert abs(max(e["auc"] for e in log) - final_auc) < 1e-9


class TestRf:
    def test_forest_beats_chance_and_averages(self, rng):
        X, y = _binary_data(rng)
        p = {**BASE, "boosting": "rf", "bagging_fraction": 0.6,
             "bagging_freq": 1, "feature_fraction": 0.7}
        b = train(p, X, y)
        assert b.num_trees == BASE["num_iterations"]
        assert _auc(y, b.predict(X)) > 0.85
        # raw score ≈ average of per-tree outputs: adding trees must NOT
        # scale predictions with T, so raw scores stay in one tree's range
        raw = b.predict(X, raw_score=True)
        assert np.abs(raw).max() < 5.0

    def test_requires_bagging(self, rng):
        X, y = _binary_data(rng, n=200)
        with pytest.raises(ValueError, match="bagging"):
            train({**BASE, "boosting": "rf"}, X, y)

    def test_rejects_early_stopping(self, rng):
        X, y = _binary_data(rng, n=200)
        with pytest.raises(ValueError, match="early stopping"):
            train({**BASE, "boosting": "rf", "bagging_fraction": 0.6,
                   "bagging_freq": 1, "early_stopping_round": 5}, X, y)

    def test_random_forest_alias(self, rng):
        X, y = _binary_data(rng, n=300)
        b = train({**BASE, "num_iterations": 5, "boosting": "random_forest",
                   "bagging_fraction": 0.6, "bagging_freq": 1}, X, y)
        assert b.num_trees == 5


class TestEstimatorSurface:
    def test_classifier_boosting_param(self, rng):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier

        X, y = _binary_data(rng, n=300, f=5)
        df = DataFrame({"features": [r for r in X], "label": y})
        m = LightGBMClassifier(features_col="features", label_col="label",
                           num_iterations=5, boosting_type="goss",
                           min_data_in_leaf=5).fit(df)
        out = m.transform(df)
        assert "prediction" in out


class TestReviewRegressions:
    def test_goss_counts_not_amplified(self, rng):
        # GOSS must amplify grad/hess only; the count channel (covers,
        # min_data_in_leaf) keeps 1 per selected row
        X, y = _binary_data(rng, n=1000)
        b = train({**BASE, "num_iterations": 3, "boosting": "goss"}, X, y)
        root_cover = b.covers[0][0]
        selected = int(np.ceil(0.2 * 1000) + np.ceil(0.1 * 1000))
        assert root_cover <= selected + 1, \
            f"root cover {root_cover} looks amplified (selected={selected})"

    def test_dart_warm_start_does_not_mutate_caller(self, rng):
        X, y = _binary_data(rng, n=500)
        b0 = train({**BASE, "num_iterations": 10}, X, y)
        before = b0.predict(X).copy()
        train({**BASE, "num_iterations": 10, "boosting": "dart",
               "skip_drop": 0.0, "drop_rate": 0.9}, X, y, init_model=b0)
        np.testing.assert_array_equal(b0.predict(X), before)

    def test_dart_early_stop_returns_best_snapshot(self, rng):
        X, y = _binary_data(rng, n=800)
        Xv, yv = _binary_data(rng, n=300)
        log = []
        b = train({**BASE, "num_iterations": 60, "boosting": "dart",
                   "skip_drop": 0.0, "drop_rate": 0.5,
                   "early_stopping_round": 5, "metric": "auc"},
                  X, y, valid_sets=[(Xv, yv)], eval_log=log)
        from mmlspark_tpu.models.gbdt.objectives import get_metric
        _, (metric_fn, _hb) = get_metric("auc", "binary")
        got = metric_fn(yv, b.predict(Xv), np.ones(len(yv)))
        best_logged = max(e["auc"] for e in log)
        # the returned model must reproduce the best logged metric — not a
        # truncation of later-rescaled trees
        assert abs(got - best_logged) < 1e-9


class TestVotingParallel:
    """PV-Tree voting (tree_learner=voting_parallel, top_k) on the virtual
    8-device CPU mesh."""

    def _mesh(self):
        import jax
        from jax.sharding import Mesh
        return Mesh(np.array(jax.devices("cpu")[:4]), ("data",))

    def test_top_k_covering_all_features_matches_data_parallel(self, rng):
        X, y = _binary_data(rng, n=600, f=10)
        p = {**BASE, "num_iterations": 5}
        mesh = self._mesh()
        dp = train({**p, "tree_learner": "data_parallel"}, X, y, mesh=mesh)
        # 2k >= F disables the comm saving but must reproduce data_parallel
        # through the same code path guard
        vp = train({**p, "tree_learner": "voting_parallel", "top_k": 10},
                   X, y, mesh=mesh)
        np.testing.assert_allclose(vp.predict(X), dp.predict(X), rtol=1e-6)

    def test_small_top_k_quality(self, rng):
        X, y = _binary_data(rng, n=800, f=10)
        mesh = self._mesh()
        vp = train({**BASE, "num_iterations": 15,
                    "tree_learner": "voting_parallel", "top_k": 2},
                   X, y, mesh=mesh)
        assert _auc(y, vp.predict(X)) > 0.85
        serial = train({**BASE, "num_iterations": 15}, X, y)
        assert abs(_auc(y, vp.predict(X)) - _auc(y, serial.predict(X))) < 0.05

    def test_voting_respects_feature_mask(self, rng):
        # feature_fraction < 1 exercises the per-node gathered mask path
        X, y = _binary_data(rng, n=600, f=10)
        vp = train({**BASE, "num_iterations": 8, "feature_fraction": 0.5,
                    "tree_learner": "voting_parallel", "top_k": 2},
                   X, y, mesh=self._mesh())
        assert _auc(y, vp.predict(X)) > 0.8


def test_dart_on_data_parallel_mesh(rng):
    import jax
    from jax.sharding import Mesh

    X, y = _binary_data(rng, n=600, f=8)
    mesh = Mesh(np.array(jax.devices("cpu")[:4]), ("data",))
    b = train({**BASE, "num_iterations": 10, "boosting": "dart",
               "skip_drop": 0.0, "drop_rate": 0.5,
               "tree_learner": "data_parallel"}, X, y, mesh=mesh)
    assert b.num_trees == 10
    assert _auc(y, b.predict(X)) > 0.85
