"""SLO engine: window rotation under a fake clock, burn-rate math, the
scorecard shape, GET /debug/slo on both transports, the ObservationStore
harvest, /healthz degraded reasons, and the multi-worker reconciliation
e2e (scorecard totals == mmlspark_serving_requests_total under seeded
faults).
"""

import http.client
import json
import threading
import time

import pytest

from mmlspark_tpu.io.http.schema import (EntityData, HeaderData,
                                         HTTPRequestData, HTTPResponseData,
                                         StatusLineData)
from mmlspark_tpu.observability import reset_all, snapshot
from mmlspark_tpu.observability.slo import (MAX_CLASSES, SloPolicy,
                                            SloTracker, classify_route,
                                            get_tracker, reset_tracker,
                                            set_tracker)
from mmlspark_tpu.observability.watchdog import configure as configure_watchdog
from mmlspark_tpu.observability.watchdog import reset_watchdog
from mmlspark_tpu.reliability import get_injector
from mmlspark_tpu.reliability.breaker import breaker_for, reset_breakers
from mmlspark_tpu.serving.server import WorkerServer
from mmlspark_tpu.tuning import observations as obs_mod
from mmlspark_tpu.tuning.observations import (ObservationStore,
                                              harvest_scorecard)


@pytest.fixture(autouse=True)
def _clean_slate():
    """Process-global state (tracker, injector, breakers, store, watchdog)
    must not leak across tests."""
    reset_tracker()
    reset_watchdog()
    reset_breakers()
    reset_all()
    get_injector().clear()
    # memory-only store: /debug/slo harvests here instead of any
    # MMLSPARK_TPU_TUNING_DIR the environment happens to carry
    obs_mod.set_store(ObservationStore())
    yield
    reset_tracker()
    reset_watchdog()
    reset_breakers()
    get_injector().clear()
    obs_mod.reset_store()
    reset_all()


def _series_sum(name):
    metric = snapshot().get(name)
    if not metric:
        return 0.0
    return sum(s["value"] for s in metric["series"])


# ---------------------------------------------------------------------------
# tracker unit behavior


def test_classify_route_collapses_paths():
    assert classify_route("/healthz") == "healthz"
    assert classify_route("/metrics?x=1") == "metrics"
    assert classify_route("/debug/slo") == "debug"
    assert classify_route("/debug/traces/abc") == "debug"
    assert classify_route("/") == "api"
    assert classify_route("/score?q=1") == "api"
    assert classify_route(None) == "api"


def test_window_rotation_under_fake_clock():
    now = [0.0]
    tr = SloTracker(window_seconds=12.0, num_buckets=4,
                    clock=lambda: now[0])
    for _ in range(5):
        tr.observe(transport="t", route="r", seconds=0.01)
    cls = tr.scorecard()["classes"][0]
    assert cls["window"]["count"] == 5
    assert cls["total"] == 5
    # half a window later the samples are still live
    now[0] = 6.0
    assert tr.scorecard()["classes"][0]["window"]["count"] == 5
    # past the window they rotate out; cumulative totals never decay
    now[0] = 13.0
    cls = tr.scorecard()["classes"][0]
    assert cls["window"]["count"] == 0
    assert cls["total"] == 5
    assert cls["p99"] is None           # empty window has no latency view
    # a recycled ring slot starts clean
    tr.observe(transport="t", route="r", seconds=0.02)
    cls = tr.scorecard()["classes"][0]
    assert cls["window"]["count"] == 1
    assert cls["total"] == 6


def test_burn_rate_math():
    now = [0.0]
    tr = SloTracker(policy=SloPolicy(availability=0.99),
                    window_seconds=60.0, num_buckets=6,
                    clock=lambda: now[0])
    # 2 errors in 100 requests against a 1% budget -> burn exactly 2.0
    for i in range(100):
        tr.observe(transport="t", route="r", seconds=0.001, error=(i < 2))
    assert tr.burn_rate("t", "r") == pytest.approx(2.0)
    cls = tr.scorecard()["classes"][0]
    assert cls["error_budget_burn"] == pytest.approx(2.0)
    assert cls["availability"] == pytest.approx(0.98)
    assert cls["availability_ok"] is False
    assert cls["errors_total"] == 2
    # an unknown class (and an idle window) burns nothing
    assert tr.burn_rate("t", "nope") == 0.0


def test_scorecard_shape_and_quantiles():
    now = [0.0]
    tr = SloTracker(window_seconds=60.0, num_buckets=6,
                    clock=lambda: now[0])
    for _ in range(100):
        tr.observe(transport="threaded", route="api", seconds=0.004)
    tr.shed(transport="threaded", route="api")
    card = tr.scorecard()
    assert set(card) == {"t", "window_seconds", "num_buckets", "policy",
                         "classes", "kv_quant"}
    assert card["policy"] == {"target_p99": 0.5, "availability": 0.999}
    (cls,) = card["classes"]
    assert set(cls) == {"transport", "route", "model", "tenant", "total",
                        "errors_total", "shed_total", "window", "p50",
                        "p99", "p999", "availability",
                        "error_budget_burn", "p99_ok", "availability_ok"}
    assert cls["tenant"] == "default"
    assert cls["shed_total"] == 1
    assert cls["window"]["shed"] == 1
    # sheds are load policy, not answered requests
    assert cls["total"] == 100
    # every sample sits in one sketch bucket: quantiles interpolate
    # inside it and stay near the true value
    assert 0.0 < cls["p50"] <= 0.01
    assert 0.0 < cls["p99"] <= 0.01
    assert cls["p99_ok"] is True
    assert cls["availability"] == 1.0
    # JSON-safe end to end
    json.dumps(card)


def test_class_cardinality_bound_overflows_to_other():
    tr = SloTracker(max_classes=2)
    tr.observe(transport="a", route="r")
    tr.observe(transport="b", route="r")
    tr.observe(transport="c", route="r")   # over the cap
    tr.observe(transport="d", route="r")   # joins the same overflow class
    keys = {(c["transport"], c["route"], c["model"], c["tenant"])
            for c in tr.scorecard()["classes"]}
    assert ("other", "other", "other", "other") in keys
    assert len(keys) == 3
    other = [c for c in tr.scorecard()["classes"]
             if c["transport"] == "other"][0]
    assert other["total"] == 2


def test_global_tracker_install_and_reset():
    tr = SloTracker()
    set_tracker(tr)
    assert get_tracker() is tr
    reset_tracker()
    assert get_tracker() is not tr
    assert isinstance(get_tracker(), SloTracker)


# ---------------------------------------------------------------------------
# ObservationStore harvest


def test_harvest_scorecard_row_shape():
    tr = SloTracker()
    for i in range(10):
        tr.observe(transport="threaded", route="api", seconds=0.002,
                   error=(i == 0))
    store = ObservationStore()
    n = harvest_scorecard(tr.scorecard(), store=store)
    assert n == 1
    (row,) = store.rows(source="slo_scorecard")
    assert row["sig"] == "slo:threaded/api/default"
    assert row["rows"] == 10
    assert row["seconds"] == 60.0
    assert row["rows_per_sec"] == pytest.approx(10 / 60.0, rel=1e-3)
    slo = row["slo"]
    assert slo["errors_total"] == 1
    assert slo["availability"] == pytest.approx(0.9)
    assert slo["p99"] is not None
    # the row satisfies the store's required schema and persists the same
    # way every other observation source does
    assert row["source"] == "slo_scorecard"
    assert "t" in row


def test_harvest_rows_reach_cost_model_store():
    """The CostModel reads get_store(); harvested scorecards must land in
    the same store unfiltered reads see."""
    tr = SloTracker()
    tr.observe(transport="bench", route="generation", seconds=0.1)
    harvest_scorecard(tr.scorecard())
    rows = obs_mod.get_store().rows(source="slo_scorecard")
    assert len(rows) == 1
    assert rows[0]["sig"].startswith("slo:")


# ---------------------------------------------------------------------------
# /debug/slo over HTTP, both transports


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_debug_slo_route_serves_scorecard(transport):
    ws = WorkerServer(transport=transport)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
        for _ in range(3):
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            assert r.status == 200
            r.read()
        conn.request("GET", "/debug/slo")
        r = conn.getresponse()
        assert r.status == 200
        card = json.loads(r.read())
        by_route = {(c["transport"], c["route"]): c
                    for c in card["classes"]}
        cls = by_route[(transport, "healthz")]
        assert cls["total"] == 3
        assert cls["p99"] is not None
        # the render harvested itself into the tuning store
        assert card["harvested"] >= 1
        rows = obs_mod.get_store().rows(source="slo_scorecard")
        assert any(r["sig"] == f"slo:{transport}/healthz/default"
                   for r in rows)
        # harvest=0 renders without appending more rows
        before = len(obs_mod.get_store())
        conn.request("GET", "/debug/slo?harvest=0")
        r = conn.getresponse()
        card2 = json.loads(r.read())
        assert "harvested" not in card2
        assert len(obs_mod.get_store()) == before
        conn.close()
    finally:
        ws.close()


def test_slo_metrics_mirror_requests_total():
    ws = WorkerServer(transport="threaded")
    try:
        conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
        for _ in range(4):
            conn.request("GET", "/healthz")
            conn.getresponse().read()
        conn.close()
    finally:
        ws.close()
    assert _series_sum("mmlspark_slo_requests_total") == \
        _series_sum("mmlspark_serving_requests_total") == 4


# ---------------------------------------------------------------------------
# /healthz degraded


def _get_healthz(ws):
    conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
    conn.request("GET", "/healthz")
    r = conn.getresponse()
    body = json.loads(r.read())
    conn.close()
    return r.status, body


def test_healthz_ok_when_nothing_is_wrong():
    ws = WorkerServer(transport="threaded")
    try:
        status, body = _get_healthz(ws)
        assert status == 200
        assert body["status"] == "ok"
        assert body["reasons"] == []
    finally:
        ws.close()


def test_healthz_degraded_on_open_breaker():
    ws = WorkerServer(transport="threaded")
    try:
        brk = breaker_for("10.0.0.9:8080", min_calls=1, failure_ratio=0.5)
        brk.record_failure()
        assert brk.state == "open"
        status, body = _get_healthz(ws)
        assert status == 200                # degraded is advisory, not 503
        assert body["status"] == "degraded"
        assert "breaker_open:10.0.0.9:8080" in body["reasons"]
    finally:
        ws.close()


def test_healthz_degraded_on_queue_pressure():
    ws = WorkerServer(transport="threaded", max_queue=5)
    try:
        for i in range(4):                  # 4/5 >= 80%
            ws._enqueue(HTTPRequestData(url="/", method="POST"))
        status, body = _get_healthz(ws)
        assert body["status"] == "degraded"
        assert any(r.startswith("queue_pressure:4/5")
                   for r in body["reasons"])
    finally:
        ws.close()


def test_healthz_degraded_on_recent_watchdog_stall():
    wd = configure_watchdog(enabled=True)
    wd.last_stall = {"wall": time.time(), "monotonic": wd._clock(),
                     "site": "runner_drain"}
    ws = WorkerServer(transport="threaded")
    try:
        status, body = _get_healthz(ws)
        assert body["status"] == "degraded"
        assert any(r.startswith("watchdog_stall:") for r in body["reasons"])
    finally:
        ws.close()


# ---------------------------------------------------------------------------
# multi-worker reconciliation e2e under seeded faults


def _resp(payload, status=200):
    return HTTPResponseData(
        headers=[HeaderData("Content-Type", "application/json")],
        entity=EntityData.from_string(json.dumps(payload)),
        status_line=StatusLineData(status_code=status))


def test_three_worker_reconciliation_with_seeded_faults():
    """Drive traffic across three in-process workers (both transports)
    with a deterministic enqueue fault seeded the MMLSPARK_TPU_FAULTS
    way; the /debug/slo scorecard totals must reconcile exactly with
    mmlspark_serving_requests_total, and the scorecard must land in the
    ObservationStore as source="slo_scorecard" rows."""
    # the env-spec grammar, applied programmatically (the module-import
    # parse of MMLSPARK_TPU_FAULTS runs once, long before this test)
    get_injector().configure("enqueue:error:every=5")
    workers = [WorkerServer(transport="threaded", reply_timeout=10.0),
               WorkerServer(transport="threaded", reply_timeout=10.0),
               WorkerServer(transport="async", reply_timeout=10.0)]
    stop = threading.Event()

    def engine(ws):
        while not stop.is_set():
            for c in ws.get_batch(16, timeout=0.05):
                body = json.loads(c.request.entity.string_content())
                ws.reply(c.request_id, _resp({"ok": body["i"]}))

    threads = [threading.Thread(target=engine, args=(w,), daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    n_per_worker = 10
    codes = []
    try:
        for ws in workers:
            conn = http.client.HTTPConnection("127.0.0.1", ws.port,
                                              timeout=10)
            for i in range(n_per_worker):
                conn.request("POST", "/", json.dumps({"i": i}).encode(),
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                codes.append(r.status)
                r.read()
            conn.close()
        # every 5th enqueue across the shared injector errored out as 500
        assert codes.count(500) == len(codes) // 5
        assert codes.count(200) == len(codes) - codes.count(500)

        conn = http.client.HTTPConnection("127.0.0.1", workers[0].port,
                                          timeout=10)
        conn.request("GET", "/debug/slo")
        card = json.loads(conn.getresponse().read())
        conn.close()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
        for ws in workers:
            ws.close()

    # the HTTP-rendered card is a snapshot taken just before its own
    # request is observed, so it holds exactly the 30 POSTs
    assert sum(c["total"] for c in card["classes"]) == 3 * n_per_worker
    # reconciliation: the tracker and the serving request counter
    # increment at the same observation point, so a scorecard taken after
    # the GET completes agrees with mmlspark_serving_requests_total
    # exactly — 30 POSTs + the /debug/slo GET itself
    live = get_tracker().scorecard()
    total = sum(c["total"] for c in live["classes"])
    assert total == _series_sum("mmlspark_serving_requests_total")
    assert total == 3 * n_per_worker + 1
    by_class = {(c["transport"], c["route"]): c for c in card["classes"]}
    api_threaded = by_class[("threaded", "api")]
    assert api_threaded["total"] == 2 * n_per_worker
    assert api_threaded["errors_total"] == 4       # faults 5,10,15,20
    api_async = by_class[("async", "api")]
    assert api_async["total"] == n_per_worker
    assert api_async["errors_total"] == 2          # faults 25,30

    # the harvest rows are in the store the CostModel reads
    rows = obs_mod.get_store().rows(source="slo_scorecard")
    assert {r["sig"] for r in rows} >= {"slo:threaded/api/default",
                                        "slo:async/api/default"}
    for r in rows:
        assert r["source"] == "slo_scorecard"
        assert "slo" in r and "error_budget_burn" in r["slo"]


def test_shed_is_tracked_per_class():
    ws = WorkerServer(transport="threaded", max_queue=1, reply_timeout=0.5)
    try:
        ws._enqueue(HTTPRequestData(url="/", method="POST"))  # fill queue
        conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
        conn.request("POST", "/", b'{"x": 1}')
        r = conn.getresponse()
        assert r.status == 429
        r.read()
        conn.close()
    finally:
        ws.close()
    card = get_tracker().scorecard()
    cls = [c for c in card["classes"]
           if (c["transport"], c["route"]) == ("threaded", "api")][0]
    assert cls["shed_total"] == 1
    assert _series_sum("mmlspark_slo_shed_total") == 1
