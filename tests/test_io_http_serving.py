"""Tests for io/http and serving — mirrors the reference's io.split1/split2
suites, which hit real localhost HTTP servers."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.io.http import (CustomOutputParser,
                                  HTTPRequestData,
                                  HTTPTransformer,
                                  JSONInputParser,
                                  JSONOutputParser,
                                  SimpleHTTPTransformer,
                                  StringOutputParser,
                                  send_with_retries)
from mmlspark_tpu.io.http.clients import shared_session
from mmlspark_tpu.serving import ServingEngine, WorkerServer


class _EchoHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    flaky_state = {"count": 0}

    def log_message(self, *a):
        pass

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        body = json.loads(self.rfile.read(n)) if n else None
        if self.path == "/echo":
            out = json.dumps({"echo": body}).encode()
            self.send_response(200)
        elif self.path == "/flaky":
            _EchoHandler.flaky_state["count"] += 1
            if _EchoHandler.flaky_state["count"] % 2 == 1:
                self.send_response(503)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            out = json.dumps({"ok": True}).encode()
            self.send_response(200)
        elif self.path == "/ratelimit":
            _EchoHandler.flaky_state["count"] += 1
            if _EchoHandler.flaky_state["count"] == 1:
                self.send_response(429)
                self.send_header("Retry-After", "0")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            out = json.dumps({"ok": True}).encode()
            self.send_response(200)
        else:
            out = b"{}"
            self.send_response(404)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)


@pytest.fixture(scope="module")
def echo_server():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _EchoHandler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def _req_df(n):
    vals = np.empty(n, dtype=object)
    for i in range(n):
        vals[i] = {"x": i}
    return DataFrame({"input": vals})


def test_json_input_parser(echo_server):
    df = _req_df(3)
    out = JSONInputParser(url=echo_server + "/echo",
                          output_col="req").transform(df)
    req = out["req"][1]
    assert isinstance(req, HTTPRequestData)
    assert req.method == "POST"
    assert json.loads(req.entity.string_content()) == {"x": 1}


def test_http_transformer_roundtrip(echo_server):
    df = JSONInputParser(url=echo_server + "/echo", output_col="req") \
        .transform(_req_df(5))
    out = HTTPTransformer(input_col="req", output_col="resp").transform(df)
    parsed = JSONOutputParser(input_col="resp", output_col="val").transform(out)
    assert [v["echo"]["x"] for v in parsed["val"]] == list(range(5))


def test_http_transformer_async_order(echo_server):
    df = JSONInputParser(url=echo_server + "/echo", output_col="req") \
        .transform(_req_df(20))
    out = HTTPTransformer(input_col="req", output_col="resp",
                          concurrency=8).transform(df)
    parsed = JSONOutputParser(input_col="resp", output_col="val").transform(out)
    assert [v["echo"]["x"] for v in parsed["val"]] == list(range(20))


def test_retry_on_5xx(echo_server):
    _EchoHandler.flaky_state["count"] = 0
    req = HTTPRequestData.from_json(echo_server + "/flaky", {})
    resp = send_with_retries(shared_session.get(), req, [10, 10, 10])
    assert resp.status_code == 200
    assert resp.json_content() == {"ok": True}


def test_429_does_not_consume_retries(echo_server):
    _EchoHandler.flaky_state["count"] = 0
    req = HTTPRequestData.from_json(echo_server + "/ratelimit", {})
    resp = send_with_retries(shared_session.get(), req, [10])
    assert resp.status_code == 200


def test_simple_http_transformer(echo_server):
    t = SimpleHTTPTransformer(
        input_col="input", output_col="val",
        input_parser=JSONInputParser(url=echo_server + "/echo"),
        concurrency=4)
    out = t.transform(_req_df(4))
    assert [v["echo"]["x"] for v in out["val"]] == list(range(4))
    assert all(e is None for e in out["error"])


def test_simple_http_transformer_error_split(echo_server):
    t = SimpleHTTPTransformer(
        input_col="input", output_col="val",
        input_parser=JSONInputParser(url=echo_server + "/nope"))
    out = t.transform(_req_df(2))
    assert all(v is None for v in out["val"])
    assert all(e["statusCode"] == 404 for e in out["error"])


def test_custom_and_string_output_parsers(echo_server):
    df = JSONInputParser(url=echo_server + "/echo", output_col="req") \
        .transform(_req_df(2))
    out = HTTPTransformer(input_col="req", output_col="resp").transform(df)
    s = StringOutputParser(input_col="resp", output_col="s").transform(out)
    assert json.loads(s["s"][0]) == {"echo": {"x": 0}}
    c = CustomOutputParser(input_col="resp", output_col="code",
                           udf=lambda r: r.status_code).transform(out)
    assert list(c["code"]) == [200, 200]


def test_simple_http_save_load(tmp_path, echo_server):
    t = SimpleHTTPTransformer(
        input_col="input", output_col="val",
        input_parser=JSONInputParser(url=echo_server + "/echo"))
    t.save(str(tmp_path / "stage"))
    t2 = SimpleHTTPTransformer.load(str(tmp_path / "stage"))
    out = t2.transform(_req_df(2))
    assert [v["echo"]["x"] for v in out["val"]] == [0, 1]


# ---------------------------------------------------------------------------
# serving
# ---------------------------------------------------------------------------

def test_worker_server_reply_routing():
    import requests
    server = WorkerServer()
    results = {}

    def client():
        results["resp"] = requests.post(
            server.address, json={"a": 1}, timeout=10)

    t = threading.Thread(target=client)
    t.start()
    batch = []
    for _ in range(100):
        batch = server.get_batch(10, timeout=0.1)
        if batch:
            break
    assert len(batch) == 1
    req = batch[0]
    assert json.loads(req.request.entity.string_content()) == {"a": 1}
    assert server.reply_json(req.request_id, {"b": 2})
    t.join(timeout=10)
    assert results["resp"].status_code == 200
    assert results["resp"].json() == {"b": 2}
    server.close()


def test_worker_server_replay_unanswered():
    import requests
    server = WorkerServer(reply_timeout=15)
    resps = []
    threads = [threading.Thread(
        target=lambda i=i: resps.append(
            requests.post(server.address, json={"i": i}, timeout=20)))
        for i in range(3)]
    for t in threads:
        t.start()
    got = []
    deadline = time.time() + 10
    while len(got) < 3 and time.time() < deadline:
        got += server.get_batch(10, timeout=0.1)
    assert len(got) == 3
    # engine "crashes" before replying; a restarted reader replays all 3
    n = server.replay_unanswered()
    assert n == 3
    replayed = []
    deadline = time.time() + 10
    while len(replayed) < 3 and time.time() < deadline:
        replayed += server.get_batch(10, timeout=0.1)
    assert {r.request_id for r in replayed} == {g.request_id for g in got}
    for r in replayed:
        server.reply_json(r.request_id, {"ok": True})
    for t in threads:
        t.join(timeout=10)
    assert all(r.status_code == 200 for r in resps)
    assert server.pending_count() == 0
    server.close()


def test_serving_engine_end_to_end():
    import requests

    def pipeline(df):
        return df.with_column("reply", np.asarray(df["x"]) * 2.0)

    with ServingEngine(pipeline, schema={"x": float}) as eng:
        r = requests.post(eng.address, json={"x": 21.0}, timeout=10)
        assert r.status_code == 200
        assert r.json() == 42.0
        # a burst gets batched together
        rs = []
        ts = [threading.Thread(
            target=lambda i=i: rs.append(
                requests.post(eng.address, json={"x": float(i)}, timeout=10)))
            for i in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert sorted(r2.json() for r2 in rs) == [2.0 * i for i in range(16)]


def test_serving_engine_error_path():
    import requests

    def bad_pipeline(df):
        raise RuntimeError("boom")

    with ServingEngine(bad_pipeline, schema={"x": float}) as eng:
        r = requests.post(eng.address, json={"x": 1.0}, timeout=10)
        assert r.status_code == 500
