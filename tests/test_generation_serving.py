"""HTTP generation endpoint (``serving/generation.py``): the continuous
batching decoder behind the WorkerServer. Pins the lifecycle delta vs the
stateless engine — a request parks across many ticks — plus the usual
serving contracts (errors as 4xx JSON, concurrent clients, clean stop)."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                 generate_cached,
                                                 init_transformer)
from mmlspark_tpu.serving.generation import GenerationEngine

CFG = TransformerConfig(vocab=128, layers=2, d_model=64, heads=4, d_ff=128,
                        max_len=64, causal=True, norm="rmsnorm",
                        position="rope", dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_transformer(CFG, seed=0)


def _post(url, payload, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _want(params, prompt, max_new):
    ids = generate_cached(params, np.asarray(prompt)[None], CFG,
                          max_new_tokens=max_new)
    return [int(t) for t in np.asarray(ids)[0, len(prompt):]]


def test_single_request_roundtrip(params):
    with GenerationEngine(params, CFG, max_slots=2, max_len=48) as eng:
        prompt = [5, 17, 9, 80]
        status, body = _post(eng.address, {"tokens": prompt, "max_new": 6})
        assert status == 200
        assert body["tokens"] == _want(params, prompt, 6)


def test_concurrent_clients_share_the_slot_pool(params):
    rng = np.random.default_rng(0)
    prompts = [[int(t) for t in rng.integers(0, CFG.vocab, 3 + i)]
               for i in range(5)]
    results = {}
    with GenerationEngine(params, CFG, max_slots=2, max_len=48) as eng:
        def client(i):
            results[i] = _post(eng.address,
                               {"tokens": prompts[i], "max_new": 5})
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    for i, prompt in enumerate(prompts):
        status, body = results[i]
        assert status == 200
        assert body["tokens"] == _want(params, prompt, 5), f"client {i}"


def test_default_max_new_and_eos(params):
    prompt = [3, 44, 7, 91]
    full = _want(params, prompt, 10)
    # pick an eos whose FIRST occurrence is mid-stream (random-init models
    # repeat tokens, so full[j] may appear earlier than j)
    j = next(j for j in range(1, len(full)) if full[j] not in full[:j])
    eos = full[j]
    eng = GenerationEngine(params, CFG, max_slots=1, max_len=48,
                           eos_id=eos, default_max_new=10)
    with eng:
        status, body = _post(eng.address, {"tokens": prompt})  # no max_new
        assert status == 200
        assert body["tokens"] == full[:j + 1]   # stopped at eos, inclusive


def test_bad_requests_get_400(params):
    with GenerationEngine(params, CFG, max_slots=1, max_len=16) as eng:
        for payload in ({"tokens": []},                 # empty
                        {"max_new": 4},                 # missing tokens
                        {"tokens": [1, CFG.vocab]},     # out-of-vocab id
                        {"tokens": [1, -3]},            # negative id
                        {"tokens": list(range(15)),     # over max_len
                         "max_new": 8}):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(eng.address, payload)
            assert ei.value.code == 400
            assert "error" in json.loads(ei.value.read())
        # the engine still serves good requests afterwards
        status, body = _post(eng.address, {"tokens": [1, 2], "max_new": 3})
        assert status == 200
        assert body["tokens"] == _want(params, [1, 2], 3)


def test_malformed_request_does_not_poison_inflight(params):
    """Code-review regression: one bad field must 400 only ITS request —
    concurrent healthy requests still complete correctly."""
    with GenerationEngine(params, CFG, max_slots=2, max_len=48) as eng:
        good_result = {}

        def good_client():
            good_result["r"] = _post(
                eng.address, {"tokens": [9, 2, 77], "max_new": 8})
        t = threading.Thread(target=good_client)
        t.start()
        for payload in ({"tokens": [1, 2], "max_new": "ten"},   # bad int
                        {"tokens": "nope"},                      # bad list
                        {"tokens": [[1], [2, 3]]}):              # ragged
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(eng.address, payload)
            assert ei.value.code == 400
        t.join(timeout=120)
        status, body = good_result["r"]
        assert status == 200
        assert body["tokens"] == _want(params, [9, 2, 77], 8)


def test_step_failure_fails_inflight_and_frees_pool(params):
    """Code-review regression: a raising decoder.step must 500 in-flight
    clients and release their slots, not hang them / leak the pool."""
    eng = GenerationEngine(params, CFG, max_slots=1, max_len=48).start()
    try:
        real_step = eng.decoder.step
        fail = threading.Event()

        def flaky_step():
            if fail.is_set():
                raise RuntimeError("injected device error")
            return real_step()
        eng.decoder.step = flaky_step
        fail.set()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(eng.address, {"tokens": [4, 5, 6], "max_new": 5})
        assert ei.value.code == 500
        fail.clear()
        # pool must be free again: a fresh request succeeds
        status, body = _post(eng.address, {"tokens": [4, 5, 6], "max_new": 5})
        assert status == 200
        assert body["tokens"] == _want(params, [4, 5, 6], 5)
    finally:
        eng.stop()


def test_sampling_fields_round_trip(params):
    """temperature/top_k/top_p/seed ride the JSON body; the reply matches
    the offline generator with the same sampling config."""
    prompt = [8, 3, 120, 44]
    with GenerationEngine(params, CFG, max_slots=2, max_len=48) as eng:
        status, body = _post(eng.address, {
            "tokens": prompt, "max_new": 6, "temperature": 0.9,
            "top_k": 10, "seed": 42})
        assert status == 200
        ids = generate_cached(params, np.asarray(prompt)[None], CFG,
                              max_new_tokens=6, temperature=0.9, top_k=10,
                              seed=42)
        assert body["tokens"] == [int(t) for t in np.asarray(ids)[0, 4:]]
        # invalid sampling params are a 400, not an engine failure
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(eng.address, {"tokens": prompt, "top_p": 0.0})
        assert ei.value.code == 400


def test_stop_is_clean(params):
    eng = GenerationEngine(params, CFG, max_slots=1, max_len=32).start()
    status, _ = _post(eng.address, {"tokens": [1, 2, 3], "max_new": 2})
    assert status == 200
    eng.stop()
    assert not eng._thread.is_alive()


def test_stop_fails_inflight_fast_with_503(params):
    """Code-review regression: stop() must answer parked clients now, not
    leave them hanging until reply_timeout."""
    import time
    eng = GenerationEngine(params, CFG, max_slots=1, max_len=48,
                           reply_timeout=60.0).start()
    result = {}

    def client():
        try:
            result["r"] = _post(eng.address,
                                {"tokens": [5, 6], "max_new": 40})
        except urllib.error.HTTPError as e:
            result["code"] = e.code
    t = threading.Thread(target=client)
    t.start()
    time.sleep(0.3)                       # let it admit and start decoding
    t0 = time.perf_counter()
    eng.stop()
    t.join(timeout=30)
    took = time.perf_counter() - t0
    assert not t.is_alive()
    # either it finished before stop() (rare on CPU: 40 ticks) or it was
    # failed fast with 503 — never parked until the 60 s timeout
    assert took < 20
    assert result.get("code") == 503 or "r" in result


def _post_stream(url, payload, timeout=120.0):
    """POST with stream:true, parse SSE events incrementally; returns the
    (events list, content_type)."""
    req = urllib.request.Request(
        url, data=json.dumps(dict(payload, stream=True)).encode(),
        headers={"Content-Type": "application/json"})
    events = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        ctype = r.headers.get("Content-Type", "")
        buf = b""
        while True:
            chunk = r.read(1)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                raw, buf = buf.split(b"\n\n", 1)
                if raw.startswith(b"data: "):
                    events.append(json.loads(raw[6:]))
    return events, ctype


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_streaming_sse_tokens(params, transport):
    with GenerationEngine(params, CFG, max_slots=2, max_len=48,
                          transport=transport,
                          steps_per_dispatch=3) as eng:
        prompt = [5, 17, 9, 80]
        events, ctype = _post_stream(eng.address,
                                     {"tokens": prompt, "max_new": 8})
        assert ctype.startswith("text/event-stream")
        assert events and events[-1].get("done") is True
        # incremental chunks concatenate to the final sequence, which
        # matches the offline generator exactly
        streamed = [t for e in events[:-1] for t in e.get("tokens", [])]
        assert streamed == events[-1]["tokens"]
        assert streamed == _want(params, prompt, 8)
        # more than one incremental event actually arrived (streaming,
        # not one blob at the end)
        assert len(events) >= 3


def test_streaming_and_plain_share_the_pool(params):
    with GenerationEngine(params, CFG, max_slots=2, max_len=48) as eng:
        prompt_a = [5, 17, 9]
        prompt_b = [80, 3, 41, 7]
        out = {}

        def stream_client():
            out["s"] = _post_stream(eng.address,
                                    {"tokens": prompt_a, "max_new": 6})[0]

        def plain_client():
            out["p"] = _post(eng.address,
                             {"tokens": prompt_b, "max_new": 6})[1]

        ts = [threading.Thread(target=stream_client),
              threading.Thread(target=plain_client)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert out["s"][-1]["tokens"] == _want(params, prompt_a, 6)
        assert out["p"]["tokens"] == _want(params, prompt_b, 6)


def test_streaming_bad_request_is_json_400(params):
    # a malformed STREAMING request still fails as a plain 400 (the
    # stream never opens: validation happens before reply_stream)
    with GenerationEngine(params, CFG, max_slots=1, max_len=48) as eng:
        req = urllib.request.Request(
            eng.address, data=json.dumps({"stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400


def test_k_step_pipelined_engine_is_token_identical(params):
    # the sweep-tuned serving operating point (steps_per_dispatch > 1 with
    # dispatch pipelining) must not change outputs — same invariance the
    # decoder-level suite pins, here through the HTTP engine lifecycle
    prompt = [5, 17, 9, 80]
    with GenerationEngine(params, CFG, max_slots=2, max_len=48,
                          steps_per_dispatch=4,
                          pipeline_depth=2) as eng:
        status, body = _post(eng.address, {"tokens": prompt, "max_new": 6})
        assert status == 200
        assert body["tokens"] == _want(params, prompt, 6)


def test_speculative_engine_through_http(params):
    """Draft plumbing through GenerationEngine: greedy replies stay
    bit-exact under speculation, sampled replies serve full length and
    vary by seed — the whole feature matrix reachable over HTTP."""
    d_cfg = CFG._replace(layers=1, d_model=32, heads=2, d_ff=64)
    draft = init_transformer(d_cfg, seed=11)
    prompt = [5, 17, 9, 80]
    with GenerationEngine(params, CFG, max_slots=2, max_len=48,
                          steps_per_dispatch=2, prefill_ahead=2,
                          draft_params=draft, draft_cfg=d_cfg,
                          gamma=3) as eng:
        status, body = _post(eng.address, {"tokens": prompt, "max_new": 6})
        assert status == 200
        assert body["tokens"] == _want(params, prompt, 6)
        _, a = _post(eng.address, {"tokens": prompt, "max_new": 6,
                                   "temperature": 1.1, "top_k": 8,
                                   "seed": 1})
        _, b = _post(eng.address, {"tokens": prompt, "max_new": 6,
                                   "temperature": 1.1, "top_k": 8,
                                   "seed": 2})
        assert len(a["tokens"]) == 6 and len(b["tokens"]) == 6
        assert a["tokens"] != b["tokens"]
