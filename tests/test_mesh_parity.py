"""Mesh-sharded paged decode parity (ISSUE 15's acceptance gate).

The Pallas paged-attention kernel mounts via ``jax.shard_map`` under a
mesh (heads over ``tp``, slots over ``dp``); the gather path is the
parity oracle. On 8 simulated CPU devices (tests/conftest.py forces
``--xla_force_host_platform_device_count=8`` for every test run, so
tier-1 keeps its usual device count) these tests assert:

* greedy engine output under dp-only, tp-only, and dp×tp meshes is
  token-identical between the kernel and gather impls, and equal to the
  offline :func:`generate_cached` reference;
* the layer-0 page pools end bitwise-identical between the impls —
  excluding trash page 0, a write sink whose content legitimately
  differs (gather re-writes old values for inactive rows, the mesh
  mount writes their fresh ones);
* the kernel actually ran sharded: ``attn_ticks_kernel`` counted,
  ``attn_ticks_gather`` and ``gather_bytes`` both zero;
* zero steady-state recompiles once the tick program is warm;
* speculative windows (gamma 1 and 4) and a mid-stream ``compact()``
  defrag preserve parity on the dp4×tp2 mesh;
* the raw op mount agrees with the unmounted kernel (context to f32
  tolerance, scattered pages bitwise).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                 generate_cached,
                                                 init_transformer)
from mmlspark_tpu.ops.compile_cache import jit_cache_size
from mmlspark_tpu.ops.paged_attention import (paged_attention,
                                              paged_attention_window)
from mmlspark_tpu.serving.continuous import ContinuousDecoder

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8,
    reason="needs 8 (simulated) devices — tier-1's conftest provides them")

CFG = TransformerConfig(vocab=128, layers=2, d_model=64, heads=4,
                        d_ff=128, max_len=96, causal=True, norm="rmsnorm",
                        position="rope", dtype=jnp.float32)
D_CFG = CFG._replace(layers=1, d_model=32, heads=2, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return init_transformer(CFG, seed=0)


@pytest.fixture(scope="module")
def d_params():
    return init_transformer(D_CFG, seed=1)


def make_mesh(kind: str) -> Mesh:
    devs = jax.devices()
    if kind == "dp2":
        return Mesh(np.array(devs[:2]), ("dp",))
    if kind == "tp2":
        return Mesh(np.array(devs[:2]), ("tp",))
    assert kind == "dp4xtp2"
    return Mesh(np.array(devs[:8]).reshape(4, 2), ("dp", "tp"))


def prompts(n=5, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, CFG.vocab, 4 + 3 * i).astype(np.int32)
            for i in range(n)]


def decode_all(eng, ps, max_new=10):
    reqs = [eng.submit(p, max_new_tokens=max_new) for p in ps]
    while any(r is not None for r in eng._slot_req) or eng._waiting:
        eng.step()
    return [list(r.tokens) for r in reqs]


def reference(params, ps, max_new=10):
    return [list(np.asarray(generate_cached(
        params, p[None, :], CFG, max_new_tokens=max_new))[0, len(p):])
        for p in ps]


@pytest.fixture(scope="module")
def ref_tokens(params):
    # the offline oracle is identical across every mesh case — compute
    # its 5 prompt decodes (and their compiles) once for the module
    return reference(params, prompts())


# dp4xtp2 is the acceptance mesh and stays in the tier-1 sweep; the
# single-axis meshes run in the dedicated mesh-parity CI stage, which
# invokes this file without the 'not slow' filter
class TestEngineMeshParity:
    @pytest.mark.parametrize("kind", [
        pytest.param("dp2", marks=pytest.mark.slow),
        pytest.param("tp2", marks=pytest.mark.slow),
        "dp4xtp2",
    ])
    def test_kernel_matches_gather_oracle_and_reference(self, params,
                                                        ref_tokens, kind):
        mesh = make_mesh(kind)
        ps = prompts()
        eng_k = ContinuousDecoder(params, CFG, max_slots=4, max_len=64,
                                  mesh=mesh, paged_attn="kernel")
        eng_g = ContinuousDecoder(params, CFG, max_slots=4, max_len=64,
                                  mesh=mesh, paged_attn="gather")
        out_k = decode_all(eng_k, ps)
        out_g = decode_all(eng_g, ps)
        assert out_k == out_g, f"kernel != gather oracle on {kind}"
        assert out_k == ref_tokens
        # the kernel REALLY ran sharded: no downgrade, no gather traffic
        assert eng_k._kv.stats["attn_ticks_kernel"] > 0
        assert eng_k._kv.stats["attn_ticks_gather"] == 0
        assert eng_k._kv.stats["gather_bytes"] == 0
        assert eng_k._attn_impl == "kernel"
        # layer-0 page pools bitwise-identical modulo trash page 0
        for kk in ("k", "v"):
            a = np.asarray(eng_k._kv.buffers[0][kk])[1:]
            b = np.asarray(eng_g._kv.buffers[0][kk])[1:]
            assert np.array_equal(a, b), f"layer-0 {kk} pages differ"

    def test_zero_steady_state_recompiles(self, params):
        mesh = make_mesh("dp4xtp2")
        eng = ContinuousDecoder(params, CFG, max_slots=4, max_len=64,
                                mesh=mesh, paged_attn="kernel")
        decode_all(eng, prompts(3))
        warm = jit_cache_size(eng._tick)
        decode_all(eng, prompts(4, seed=9))
        after = jit_cache_size(eng._tick)
        if warm is not None:
            assert after == warm, "steady-state tick recompiled"

    def test_mesh_and_single_chip_never_share_traces(self, params):
        # the mesh is part of the lru_cache program key — a sharded
        # engine and a single-chip engine with identical shapes must get
        # DIFFERENT compiled ticks (a shared trace would bake the wrong
        # shardings into one of them)
        eng_m = ContinuousDecoder(params, CFG, max_slots=4, max_len=64,
                                  mesh=make_mesh("dp4xtp2"),
                                  paged_attn="kernel")
        eng_s = ContinuousDecoder(params, CFG, max_slots=4, max_len=64,
                                  paged_attn="kernel")
        assert eng_m._tick is not eng_s._tick
        assert eng_m._mesh_shape == "dp4xtp2"
        assert eng_s._mesh_shape == "single"

    @pytest.mark.parametrize("gamma", [pytest.param(1, marks=pytest.mark.slow),
                                       4])
    def test_speculative_windows_on_mesh(self, params, d_params, ref_tokens,
                                         gamma):
        mesh = make_mesh("dp4xtp2")
        ps = prompts(4)  # a prefix of prompts(5): same rng seed/order
        out = {}
        for impl in ("kernel", "gather"):
            eng = ContinuousDecoder(params, CFG, max_slots=4, max_len=64,
                                    mesh=mesh, paged_attn=impl,
                                    draft_params=d_params, draft_cfg=D_CFG,
                                    gamma=gamma)
            out[impl] = decode_all(eng, ps)
            if impl == "kernel":
                assert eng._kv.stats["attn_ticks_kernel"] > 0
                assert eng._kv.stats["gather_bytes"] == 0
        assert out["kernel"] == out["gather"]
        assert out["kernel"] == ref_tokens[:4]

    def test_compact_defrag_midstream_on_mesh(self, params):
        # defrag_threshold=1: the short request's retirement compacts the
        # pool while the long request is still decoding — the permutation
        # applies per-shard and the survivor's stream must not notice
        mesh = make_mesh("dp4xtp2")
        eng = ContinuousDecoder(params, CFG, max_slots=4, max_len=64,
                                mesh=mesh, paged_attn="kernel",
                                page_size=4, defrag_threshold=1)
        rng = np.random.default_rng(7)
        p_short = rng.integers(1, CFG.vocab, 5).astype(np.int32)
        p_long = rng.integers(1, CFG.vocab, 9).astype(np.int32)
        rs = eng.submit(p_short, max_new_tokens=3)
        rl = eng.submit(p_long, max_new_tokens=24)
        while not (rs.done and rl.done):
            eng.step()
        want = reference(params, [p_long], max_new=24)[0]
        assert rl.tokens == want
        assert eng._kv.stats["defrag_moves"] > 0
        assert eng._kv.stats["attn_ticks_kernel"] > 0
        assert eng._kv.stats["gather_bytes"] == 0
        assert eng._kv.pages_in_use == 0


class TestQuantizedMeshParity:
    """int8 pages under the dp4×tp2 mesh.

    Token assertions run over a 4-token horizon: tp's row-parallel psum
    reduces in a different order than the single-chip matmul, and int8
    ``round()`` amplifies those 1-ulp differences into ±1 quant steps
    after a few steps. Parity through 4 greedy tokens is deterministic
    with fixed seeds; drift past that horizon is accumulation of the
    mesh's own numerics, not a quant data-plane bug (the fused scatter
    is bitwise-identical to the host-side writer, asserted below and in
    tests/test_kv_quant.py).
    """

    HORIZON = 4

    def test_int8_kernel_matches_gather_and_single_chip(self, params):
        mesh = make_mesh("dp4xtp2")
        ps = prompts(4)
        out = {}
        engs = {}
        for key, kw in (
                ("kernel", dict(mesh=mesh, paged_attn="kernel")),
                ("gather", dict(mesh=mesh, paged_attn="gather")),
                ("single", dict(paged_attn="gather"))):
            eng = ContinuousDecoder(params, CFG, max_slots=4, max_len=64,
                                    kv_dtype="int8", **kw)
            out[key] = decode_all(eng, ps, max_new=self.HORIZON)
            engs[key] = eng
        assert out["kernel"] == out["gather"], \
            "int8 kernel != gather oracle on dp4xtp2"
        assert out["kernel"] == out["single"], \
            "int8 mesh decode != single-chip within the parity horizon"
        # the quantized kernel REALLY ran sharded
        assert engs["kernel"]._kv.stats["attn_ticks_kernel"] > 0
        assert engs["kernel"]._kv.stats["gather_bytes"] == 0
        # quant pages AND their scale pools end bitwise-identical
        # between the fused in-kernel scatter and the gather-impl
        # writeback, modulo trash page 0 — a scale that didn't ride the
        # same block-table index_map would break this
        for kk in ("k", "v", "k_scale", "v_scale"):
            a = np.asarray(engs["kernel"]._kv.buffers[0][kk])[1:]
            b = np.asarray(engs["gather"]._kv.buffers[0][kk])[1:]
            assert np.array_equal(a, b), f"layer-0 {kk} differs"

    def test_int8_mesh_zero_steady_state_recompiles(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=4, max_len=64,
                                mesh=make_mesh("dp4xtp2"),
                                paged_attn="kernel", kv_dtype="int8")
        decode_all(eng, prompts(3), max_new=self.HORIZON)
        warm = jit_cache_size(eng._tick)
        decode_all(eng, prompts(4, seed=9), max_new=self.HORIZON)
        if warm is not None:
            assert jit_cache_size(eng._tick) == warm


class TestOpMountParity:
    def _pool(self, rng, B, H, page, hd, P):
        N = 1 + B * P
        kp = jnp.asarray(rng.normal(size=(N, H, page, hd))
                         .astype(np.float32))
        vp = jnp.asarray(rng.normal(size=(N, H, page, hd))
                         .astype(np.float32))
        bt = jnp.asarray((1 + np.arange(B)[:, None] * P
                          + np.arange(P)[None, :]).astype(np.int32))
        return kp, vp, bt

    def test_read_mount_matches_unmounted(self):
        rng = np.random.default_rng(0)
        B, H, page, hd, P = 8, 4, 8, 8, 3
        kp, vp, bt = self._pool(rng, B, H, page, hd, P)
        q = jnp.asarray(rng.normal(size=(B, H, 1, hd)).astype(np.float32))
        lens = jnp.asarray(
            rng.integers(0, page * P, B).astype(np.int32)).at[0].set(0)
        ref = paged_attention(q, kp, vp, bt, lens)
        got = paged_attention(q, kp, vp, bt, lens,
                              mesh=make_mesh("dp4xtp2"),
                              slot_axis="dp", head_axis="tp")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5)
        # lengths == 0 row follows the flash convention under the mount
        assert np.all(np.asarray(got)[0] == 0.0)

    def test_window_mount_pages_bitwise_vs_fused(self):
        rng = np.random.default_rng(1)
        B, H, page, hd, P, W = 8, 4, 8, 8, 3, 4
        kp, vp, bt = self._pool(rng, B, H, page, hd, P)
        q, kn, vn = (jnp.asarray(rng.normal(size=(B, H, W, hd))
                                 .astype(np.float32)) for _ in range(3))
        pos = jnp.asarray(
            np.array([0, 5, 8, 2, 17, 3, 9, 1], np.int32))
        active = jnp.asarray(
            np.array([1, 1, 0, 1, 1, 1, 1, 1], bool))
        ctx_f, kf, vf = paged_attention_window(q, kn, vn, kp, vp, bt,
                                               pos, active=active)
        ctx_m, km, vm = paged_attention_window(
            q, kn, vn, kp, vp, bt, pos, active=active,
            mesh=make_mesh("dp4xtp2"), slot_axis="dp", head_axis="tp")
        np.testing.assert_allclose(np.asarray(ctx_m), np.asarray(ctx_f),
                                   atol=1e-5)
        # scattered pages bitwise modulo the trash page write sink
        assert np.array_equal(np.asarray(km)[1:], np.asarray(kf)[1:])
        assert np.array_equal(np.asarray(vm)[1:], np.asarray(vf)[1:])

    def test_mount_rejects_indivisible_axes(self):
        rng = np.random.default_rng(2)
        B, H, page, hd, P = 3, 4, 8, 8, 2
        kp, vp, bt = self._pool(rng, B, H, page, hd, P)
        q = jnp.asarray(rng.normal(size=(B, H, 1, hd)).astype(np.float32))
        lens = jnp.full((B,), 4, jnp.int32)
        with pytest.raises(ValueError, match="divisible"):
            paged_attention(q, kp, vp, bt, lens,
                            mesh=make_mesh("dp4xtp2"),
                            slot_axis="dp", head_axis="tp")
