"""Deployment tooling tests (parity role: tools/docker + tools/helm +
pipeline.yaml in the reference)."""

import os
import subprocess
import sys

import pytest

yaml = pytest.importorskip("yaml")  # declared in the [test] extra

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HELM = os.path.join(REPO, "deploy", "helm", "mmlspark-tpu-serving")


class TestHelmChart:
    def test_chart_and_values_parse(self):
        with open(os.path.join(HELM, "Chart.yaml")) as f:
            chart = yaml.safe_load(f)
        assert chart["name"] == "mmlspark-tpu-serving"
        with open(os.path.join(HELM, "values.yaml")) as f:
            values = yaml.safe_load(f)
        assert values["workers"]["replicas"] >= 1
        assert "google.com/tpu" in values["workers"]["resources"]["limits"]

    def test_templates_render_to_valid_yaml(self):
        """Poor-man's `helm template`: substitute the values used by the
        templates and YAML-parse the result."""
        with open(os.path.join(HELM, "values.yaml")) as f:
            values = yaml.safe_load(f)

        def resolve(path, scope):
            cur = scope
            for part in path.split("."):
                cur = cur[part]
            return cur

        import re
        for name in ("driver.yaml", "workers.yaml"):
            with open(os.path.join(HELM, "templates", name)) as f:
                text = f.read()
            text = text.replace("{{ .Release.Name }}", "test")
            text = re.sub(
                r"\{\{ toYaml \.Values\.([\w.]+) \| indent (\d+) \}\}",
                lambda m: "\n".join(
                    " " * int(m.group(2)) + ln for ln in yaml.safe_dump(
                        resolve(m.group(1), values)).splitlines()),
                text)
            text = re.sub(r"\{\{ \.Values\.([\w.]+) \}\}",
                          lambda m: str(resolve(m.group(1), values)), text)
            text = re.sub(r"\{\{[^}]*\}\}", "placeholder", text)
            docs = list(yaml.safe_load_all(text))
            assert all(d and "kind" in d for d in docs), name

    def test_ci_pipeline_parses_and_covers_suites(self):
        # every suite must be executed SOMEWHERE in the pipeline — most in
        # the generated test-matrix, but some run in other stages
        # (test_deploy.py in the docs job, test_observability.py in
        # static-analysis), so collect scripts from every stage and job
        with open(os.path.join(REPO, "deploy", "ci", "pipeline.yaml")) as f:
            ci = yaml.safe_load(f)
        scripts = []
        for stage in ci["stages"]:
            scripts.append(stage.get("script") or "")
            scripts.extend(j["script"] for j in stage.get("jobs", []))
        referenced = " ".join(scripts)
        missing = []
        for fname in sorted(os.listdir(os.path.join(REPO, "tests"))):
            if fname.startswith("test_") and fname.endswith(".py"):
                if fname not in referenced:
                    missing.append(fname)
        assert not missing, f"test files absent from CI pipeline: {missing}"

    def test_ci_matrix_is_fresh(self):
        """pipeline.yaml is generated from tests/ — a new suite added
        without rerunning scripts/gen_ci_matrix.py must fail here, not rot
        silently (which is exactly how round 3 ended red)."""
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import gen_ci_matrix
        finally:
            sys.path.pop(0)
        with open(os.path.join(REPO, "deploy", "ci", "pipeline.yaml")) as f:
            assert f.read() == gen_ci_matrix.generate(), \
                "stale CI matrix: rerun scripts/gen_ci_matrix.py"

    def test_ci_has_packaging_stage(self):
        """The wheel-install-quickstart stage must stay in CI: it is the
        executable slice of the reference's packagePython/testPython
        discipline (CodegenPlugin.scala:55-67) and the only place the
        installed artifact (not the checkout) is exercised."""
        with open(os.path.join(REPO, "deploy", "ci", "pipeline.yaml")) as f:
            ci = yaml.safe_load(f)
        stage = next((s for s in ci["stages"] if s["name"] == "package"),
                     None)
        assert stage is not None, "CI lost its 'package' stage"
        assert "test_packaging.sh" in stage["script"]
        assert os.path.exists(os.path.join(REPO, "scripts",
                                           "test_packaging.sh"))
        assert os.path.exists(os.path.join(REPO, "scripts",
                                           "packaging_quickstart.py"))

    def test_dockerfile_mentions_entrypoint(self):
        with open(os.path.join(REPO, "deploy", "docker", "Dockerfile")) as f:
            text = f.read()
        assert "mmlspark_tpu.serving" in text


class TestServingCLI:
    def test_driver_and_worker_lifecycle(self):
        import json
        import urllib.request
        env = {**os.environ}
        drv = subprocess.Popen(
            [sys.executable, "-m", "mmlspark_tpu.serving", "--driver",
             "--host", "127.0.0.1", "--port", "0"],
            stdout=subprocess.PIPE, text=True, cwd=REPO, env=env)
        try:
            url = drv.stdout.readline().strip().split()[-1]
            wk = subprocess.Popen(
                [sys.executable, "-m", "mmlspark_tpu.serving",
                 "--driver-url", url, "--host", "127.0.0.1", "--port", "0",
                 "--worker-id", "w0"],
                stdout=subprocess.PIPE, text=True, cwd=REPO, env=env)
            try:
                assert "w0" in wk.stdout.readline()
                routing = json.loads(urllib.request.urlopen(
                    url + "/routing", timeout=10).read())
                assert "w0" in routing
            finally:
                wk.terminate()
                wk.wait(10)  # raises TimeoutExpired if SIGTERM is ignored
        finally:
            drv.terminate()
            drv.wait(10)
