"""WordPiece tokenizer tests (text → dense id/mask tensors for BERT-class
models; the text→ids step the reference delegates to upstream tooling)."""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.pipeline import PipelineStage
from mmlspark_tpu.featurize.tokenizer import (PAD, UNK, BertTokenizer,
                                              basic_tokenize,
                                              build_wordpiece_vocab,
                                              wordpiece)

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
         "the", "quick", "brown", "fox", "jump", "##ed", "##s",
         "un", "##believ", "##able", ",", "."]
IDX = {t: i for i, t in enumerate(VOCAB)}


class TestBasicTokenize:
    def test_whitespace_punct_lowercase(self):
        assert basic_tokenize("The quick, brown fox.") == \
            ["the", "quick", ",", "brown", "fox", "."]

    def test_no_lowercase(self):
        assert basic_tokenize("The Fox", lowercase=False) == ["The", "Fox"]


class TestWordPiece:
    def test_greedy_longest_match(self):
        assert wordpiece("jumped", IDX) == ["jump", "##ed"]
        assert wordpiece("jumps", IDX) == ["jump", "##s"]
        assert wordpiece("unbelievable", IDX) == ["un", "##believ", "##able"]

    def test_unknown_falls_back(self):
        assert wordpiece("zzz", IDX) == [UNK]


class TestBertTokenizer:
    def test_transform_shapes_and_mask(self):
        t = BertTokenizer(VOCAB, input_col="text", max_len=10)
        df = DataFrame({"text": np.array(
            ["the quick fox", "jumped", None], dtype=object)})
        out = t.transform(df)
        ids, mask = out["ids"], out["mask"]
        assert ids.shape == (3, 10) and ids.dtype == np.int32
        # [CLS] the quick fox [SEP] pad...
        assert list(ids[0][:5]) == [IDX["[CLS]"], IDX["the"], IDX["quick"],
                                    IDX["fox"], IDX["[SEP]"]]
        assert list(mask[0]) == [1] * 5 + [0] * 5
        assert list(ids[2][:2]) == [IDX["[CLS]"], IDX["[SEP]"]]  # None row
        assert ids[0][5] == IDX[PAD]

    def test_truncation(self):
        t = BertTokenizer(VOCAB, input_col="text", max_len=4)
        df = DataFrame({"text": ["the quick brown fox jumped"]})
        out = t.transform(df)
        assert out["mask"][0].sum() == 4  # CLS + 2 body + SEP

    def test_save_load_roundtrip(self, tmp_path):
        t = BertTokenizer(VOCAB, input_col="text", max_len=8)
        df = DataFrame({"text": ["unbelievable ."]})
        expect = t.transform(df)["ids"]
        t.save(str(tmp_path / "tok"))
        t2 = PipelineStage.load(str(tmp_path / "tok"))
        np.testing.assert_array_equal(t2.transform(df)["ids"], expect)

    def test_vocab_file(self, tmp_path):
        p = tmp_path / "vocab.txt"
        p.write_text("\n".join(VOCAB) + "\n")
        t = BertTokenizer(input_col="text", vocab_file=str(p), max_len=6)
        out = t.transform(DataFrame({"text": ["fox"]}))
        assert out["ids"][0][1] == IDX["fox"]

    def test_missing_vocab_clear_error(self):
        t = BertTokenizer(input_col="text")
        with pytest.raises(ValueError, match="vocab"):
            t.transform(DataFrame({"text": ["x"]}))


class TestVocabBuilder:
    def test_built_vocab_covers_corpus(self):
        corpus = ["the cat sat on the mat", "the dog sat on the log",
                  "cats and dogs"] * 5
        vocab = build_wordpiece_vocab(corpus, size=200)
        assert vocab[:5] == ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
        idx = {t: i for i, t in enumerate(vocab)}
        # frequent words are whole tokens; derived words split, not UNK
        assert "the" in idx and "sat" in idx
        assert UNK not in wordpiece("cats", idx)

    def test_tokenizer_into_bert_model(self):
        """Full text path: tokenize → BERT-shaped ONNX graph."""
        from mmlspark_tpu.models.onnx_model import ONNXModel
        from mmlspark_tpu.models.zoo.bert_onnx import (BertOnnxConfig,
                                                       export_bert_onnx)
        corpus = ["tiny text pipeline test", "text goes in ids come out"]
        vocab = build_wordpiece_vocab(corpus, size=128)
        cfg = BertOnnxConfig(vocab=128, layers=1, d_model=32, heads=2,
                             d_ff=64, max_len=16)
        tok = BertTokenizer(vocab, input_col="text", max_len=16)
        m = ONNXModel(export_bert_onnx(cfg, seed=0),
                      feed_dict={"input_ids": "ids",
                                 "attention_mask": "mask"},
                      fetch_dict={"emb": "last_hidden_state"},
                      mini_batch_size=4, pin_devices=False)
        df = DataFrame({"text": corpus})
        out = m.transform(tok.transform(df))
        emb = np.stack(list(out["emb"]))
        assert emb.shape[0] == 2 and np.isfinite(emb).all()


class TestReviewRegressions:
    def test_param_override_uses_new_vocab(self):
        t = BertTokenizer(VOCAB, input_col="text", max_len=6)
        df = DataFrame({"text": ["fox"]})
        assert t.transform(df)["ids"][0][1] == IDX["fox"]
        vocab_b = list(VOCAB)
        vocab_b[IDX["fox"]], vocab_b[IDX["the"]] = "the", "fox"
        out = t.transform(df, {"vocab": vocab_b})
        assert out["ids"][0][1] == IDX["the"]  # "fox" sits at the old "the" slot
        # and the original stage is untouched
        assert t.transform(df)["ids"][0][1] == IDX["fox"]

    def test_set_vocab_invalidates_cache(self):
        t = BertTokenizer(VOCAB, input_col="text", max_len=6)
        df = DataFrame({"text": ["fox"]})
        t.transform(df)
        vocab_b = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "fox"]
        t.set(vocab=vocab_b)
        assert t.transform(df)["ids"][0][1] == 5

    def test_tiny_max_len_clear_error(self):
        t = BertTokenizer(VOCAB, input_col="text", max_len=2)
        with pytest.raises(ValueError, match="max_len"):
            t.transform(DataFrame({"text": ["x"]}))
