import numpy as np
import pytest

from mmlspark_tpu.core import (ComplexParam, DataFrame, Estimator, Model,
                               Param, Pipeline, PipelineModel, PipelineStage,
                               Transformer, concat)
from mmlspark_tpu.core import schema as S


class AddConst(Transformer):
    input_col = Param(str, default="x", doc="in")
    output_col = Param(str, default="y", doc="out")
    amount = Param(float, default=1.0, doc="value to add")

    def _transform(self, df):
        return df.with_column(self.output_col, df[self.input_col] + self.amount)


class MeanCenter(Estimator):
    input_col = Param(str, default="x", doc="in")

    def _fit(self, df):
        return MeanCenterModel(mean=float(np.mean(df[self.input_col])),
                               input_col=self.input_col)


class MeanCenterModel(Model):
    input_col = Param(str, default="x", doc="in")
    mean = Param(float, default=0.0, doc="fitted mean")

    def _transform(self, df):
        return df.with_column(self.input_col, df[self.input_col] - self.mean)


class TestParams:
    def test_defaults_and_set(self):
        t = AddConst()
        assert t.amount == 1.0
        t.set(amount=2)
        assert t.amount == 2.0
        t.amount = 3.5
        assert t.get("amount") == 3.5

    def test_constructor_kwargs(self):
        t = AddConst(amount=5, input_col="a")
        assert t.amount == 5.0 and t.input_col == "a"

    def test_type_errors(self):
        with pytest.raises(TypeError):
            AddConst(amount="nope")
        with pytest.raises(KeyError):
            AddConst(bogus=1)

    def test_copy_isolated(self):
        t = AddConst(amount=1)
        t2 = t.copy({"amount": 9})
        assert t.amount == 1.0 and t2.amount == 9.0

    def test_explain(self):
        assert "value to add" in AddConst().explain_params()

    def test_uids_unique(self):
        assert AddConst().uid != AddConst().uid


class TestDataFrame:
    def test_basic(self):
        df = DataFrame({"x": [1.0, 2.0, 3.0], "s": ["a", "b", "c"]}, npartitions=2)
        assert len(df) == 3
        assert df.columns == ["x", "s"]
        assert df["s"].dtype == object
        assert df.schema()["x"] == "float64"

    def test_partitions(self):
        df = DataFrame({"x": np.arange(10)}, npartitions=3)
        parts = list(df.partitions())
        assert [len(p) for p in parts] == [4, 3, 3]
        assert np.array_equal(concat(parts)["x"], np.arange(10))

    def test_map_partitions(self):
        df = DataFrame({"x": np.arange(10, dtype=np.float64)}, npartitions=4)
        out = df.map_partitions(lambda p, i: p.with_column("pid", np.full(len(p), i)))
        assert len(out) == 10
        assert sorted(set(out["pid"])) == [0, 1, 2, 3]

    def test_map_partitions_runs_concurrently(self):
        # partitions must overlap in time — this is what makes round-robin
        # chip pinning actually use k chips at once. Asserted via an
        # in-flight counter (robust to machine load, unlike wall-clock).
        import threading
        import time
        df = DataFrame({"x": np.arange(8, dtype=np.float64)}, npartitions=4)
        lock = threading.Lock()
        state = {"cur": 0, "peak": 0}

        def slow(p, i):
            with lock:
                state["cur"] += 1
                state["peak"] = max(state["peak"], state["cur"])
            time.sleep(0.1)
            with lock:
                state["cur"] -= 1
            return p

        out = df.map_partitions(slow, max_workers=4)
        assert len(out) == 8
        assert state["peak"] >= 2, f"partitions never overlapped: {state}"

    def test_map_partitions_order_and_errors(self):
        df = DataFrame({"x": np.arange(12, dtype=np.int64)}, npartitions=3)
        out = df.map_partitions(lambda p, i: p)
        assert list(out["x"]) == list(range(12))  # partition order preserved
        import pytest
        with pytest.raises(ValueError, match="boom"):
            df.map_partitions(lambda p, i: (_ for _ in ()).throw(ValueError("boom")))
        # max_workers=1 forces the sequential path
        out = df.map_partitions(lambda p, i: p, max_workers=1)
        assert list(out["x"]) == list(range(12))

    def test_ops(self):
        df = DataFrame({"x": [1, 2, 3], "y": [4, 5, 6]})
        assert df.select(["y"]).columns == ["y"]
        assert df.drop("x").columns == ["y"]
        assert df.rename({"x": "z"}).columns == ["z", "y"]
        assert list(df.filter(np.array([True, False, True]))["x"]) == [1, 3]
        assert list(df.sort_values("x", ascending=False)["x"]) == [3, 2, 1]

    def test_pandas_roundtrip(self):
        import pandas as pd
        pdf = pd.DataFrame({"a": [1.5, 2.5], "b": ["x", "y"]})
        df = DataFrame.from_pandas(pdf, npartitions=2)
        back = df.to_pandas()
        assert list(back["a"]) == [1.5, 2.5]
        assert list(back["b"]) == ["x", "y"]

    def test_metadata_preserved(self):
        df = DataFrame({"x": [1, 2], "y": [3, 4]})
        df = S.set_categorical_metadata(df, "x", ["lo", "hi"])
        assert S.get_categorical_levels(df.select(["x"]), "x") == ["lo", "hi"]
        assert S.get_categorical_levels(df.with_column("z", [0, 0]), "x") == ["lo", "hi"]
        assert S.get_categorical_levels(df.rename({"x": "w"}), "w") == ["lo", "hi"]
        assert not S.is_categorical(df, "y")

    def test_metadata_survives_row_ops(self):
        # regression: the row-reshaping ops rebuild the frame — each must
        # carry column_metadata through, not silently drop it
        df = DataFrame({"x": [3, 1, 2], "y": [6, 4, 5]}, npartitions=2)
        df = S.set_categorical_metadata(df, "x", ["lo", "hi"])
        outs = {
            "filter": df.filter(np.array([True, False, True])),
            "take": df.take([0, 2]),
            "sort_values": df.sort_values("x"),
            "repartition": df.repartition(3),
            "head": df.head(2),
        }
        for op, out in outs.items():
            assert S.get_categorical_levels(out, "x") == ["lo", "hi"], op

    def test_unused_column_name(self):
        df = DataFrame({"x": [1], "x_1": [2]})
        assert S.find_unused_column_name("x", df) == "x_2"

    def test_assemble_vector(self):
        df = DataFrame({"a": [1.0, 2.0],
                        "v": [np.array([3.0, 4.0]), np.array([5.0, 6.0])]})
        X = S.assemble_vector(df, ["a", "v"])
        assert X.shape == (2, 3)
        assert list(X[1]) == [2.0, 5.0, 6.0]


class TestPipeline:
    def test_fit_transform(self):
        df = DataFrame({"x": [1.0, 2.0, 3.0]})
        pipe = Pipeline([MeanCenter(), AddConst(amount=10)])
        model = pipe.fit(df)
        out = model.transform(df)
        assert np.allclose(out["y"], [9.0, 10.0, 11.0])

    def test_transform_params_override(self):
        df = DataFrame({"x": [0.0]})
        out = AddConst().transform(df, {"amount": 7.0})
        assert out["y"][0] == 7.0


class TestSerialization:
    def test_transformer_roundtrip(self, tmp_save):
        t = AddConst(amount=3.25, output_col="zz")
        t.save(tmp_save)
        t2 = PipelineStage.load(tmp_save)
        assert isinstance(t2, AddConst)
        assert t2.amount == 3.25 and t2.output_col == "zz"
        assert t2.uid == t.uid

    def test_pipeline_model_roundtrip(self, tmp_save):
        df = DataFrame({"x": [1.0, 2.0, 3.0]})
        model = Pipeline([MeanCenter(), AddConst(amount=10)]).fit(df)
        model.save(tmp_save)
        model2 = PipelineModel.load(tmp_save)
        out1, out2 = model.transform(df), model2.transform(df)
        assert np.allclose(out1["y"], out2["y"])

    def test_complex_values(self, tmp_save):
        from mmlspark_tpu.core import serialize

        class Holder(Transformer):
            payload = ComplexParam(doc="arbitrary blob")

            def _transform(self, df):
                return df

        h = Holder()
        h.set(payload={"w": np.arange(6).reshape(2, 3).astype(np.float32),
                       "b": [np.ones(3), 2.0]})
        h.save(tmp_save)
        # class lives in a test function namespace → patch resolution
        loaded_meta_cls = serialize._resolve_class
        try:
            serialize._resolve_class = lambda p: Holder
            h2 = PipelineStage.load(tmp_save)
        finally:
            serialize._resolve_class = loaded_meta_cls
        p = h2.get("payload")
        assert np.array_equal(p["w"], h.get("payload")["w"])
        assert p["b"][1] == 2.0


def test_string_array_dtype_roundtrip(tmp_path):
    """'U'-dtype ndarrays keep their dtype through save/load (ADVICE r1)."""
    from mmlspark_tpu.core.serialize import load_value, save_value
    arr = np.array(["abc", "de", "f"])
    assert arr.dtype.kind == "U"
    p = str(tmp_path / "val")
    import os
    os.makedirs(p, exist_ok=True)
    tag = save_value({"labels": arr, "w": np.ones(2)}, p)
    back = load_value(tag, p)
    assert back["labels"].dtype == arr.dtype
    assert list(back["labels"]) == list(arr)


class TestSharedPartitionPool:
    def test_pool_reused_across_calls(self):
        from mmlspark_tpu.core import dataframe as dfmod
        a = dfmod._shared_pool(4)
        b = dfmod._shared_pool(4)
        assert a is b
        assert dfmod._shared_pool(2) is not a

    def test_map_partitions_unchanged_semantics(self):
        df = DataFrame({"x": np.arange(20)}, npartitions=4)
        out = df.map_partitions(
            lambda p, i: p.with_column("y", p["x"] * 2))
        np.testing.assert_array_equal(out["y"], np.arange(20) * 2)
        np.testing.assert_array_equal(out["x"], np.arange(20))

    def test_nested_map_partitions_does_not_deadlock(self):
        # inner call from a pool worker must take the sequential path
        # rather than queue on the same (possibly saturated) executor
        df = DataFrame({"x": np.arange(16)}, npartitions=4)

        def outer(p, i):
            inner = DataFrame({"x": np.asarray(p["x"])}, npartitions=2)
            return inner.map_partitions(
                lambda q, j: q.with_column("y", q["x"] + 1))

        out = df.map_partitions(outer)
        np.testing.assert_array_equal(out["y"], np.arange(16) + 1)

    def test_exception_still_propagates(self):
        df = DataFrame({"x": np.arange(8)}, npartitions=4)

        def boom(p, i):
            if i == 2:
                raise RuntimeError("partition 2 failed")
            return p

        with pytest.raises(RuntimeError, match="partition 2"):
            df.map_partitions(boom)
