"""Durable serving offsets: journal write-ahead, torn-tail recovery,
compaction, and the kill-the-PROCESS replay variant.

Parity: the reference makes serving progress durable through Spark's
checkpointed offsets (``HTTPSourceV2.scala:96-113,225-258``); an engine
restart there rehydrates history queues. Here the journal extends that to
worker process death.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from mmlspark_tpu.io.http.schema import (EntityData, HTTPRequestData,
                                         HTTPResponseData, StatusLineData)
from mmlspark_tpu.serving.journal import ServingJournal
from mmlspark_tpu.serving.server import WorkerServer


def _req(body: str) -> HTTPRequestData:
    return HTTPRequestData(entity=EntityData.from_string(body))


def _resp(payload, status=200) -> HTTPResponseData:
    return HTTPResponseData(entity=EntityData.from_string(json.dumps(payload)),
                            status_line=StatusLineData(status_code=status))


class TestServingJournal:
    def test_write_ahead_replay_roundtrip(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = ServingJournal(p)
        j.record_request("a", 0, _req('{"x":1}'))
        j.record_request("b", 0, _req('{"x":2}'))
        j.record_reply("a")
        j.record_epoch(1)
        j.close()
        epoch, pending = ServingJournal(p).replay()
        assert epoch == 1
        assert set(pending) == {"b"}
        ep, req = pending["b"]
        assert ep == 0 and req.entity.string_content() == '{"x":2}'

    def test_torn_tail_tolerated(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = ServingJournal(p)
        j.record_request("a", 0, _req("one"))
        j.close()
        with open(p, "a", encoding="utf-8") as fh:
            fh.write('{"t":"rep","id":"a')     # SIGKILL mid-append
        epoch, pending = ServingJournal(p).replay()
        assert set(pending) == {"a"}           # torn reply does not count

    def test_double_crash_preserves_post_restart_records(self, tmp_path):
        """Crash 1 leaves a torn tail; restart 1 appends more records;
        restart 2 must see ALL of them (the torn line is terminated at
        open and skipped at scan, not treated as end-of-journal)."""
        p = str(tmp_path / "j.jsonl")
        j = ServingJournal(p)
        j.record_request("a", 0, _req("one"))
        j.close()
        with open(p, "a", encoding="utf-8") as fh:
            fh.write('{"t":"req","id":"torn"')          # crash 1, mid-append
        j2 = ServingJournal(p)                          # restart 1
        j2.record_request("b", 1, _req("two"))
        j2.record_reply("a")
        j2.record_epoch(2)
        j2.close()                                      # crash 2 (clean here)
        epoch, pending = ServingJournal(p).replay()     # restart 2
        assert epoch == 2
        assert set(pending) == {"b"}

    def test_compaction_drops_answered(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = ServingJournal(p)
        for i in range(20):
            j.record_request(f"r{i}", 0, _req(str(i)))
            if i != 7:
                j.record_reply(f"r{i}")
        assert j.maybe_compact(epoch=3, min_lines=1)
        lines = open(p).read().strip().splitlines()
        assert len(lines) == 2                 # epoch marker + the one live req
        epoch, pending = j.replay()
        assert epoch == 3 and set(pending) == {"r7"}
        j.close()


class TestWorkerServerDurability:
    def _post(self, addr, payload, out, timeout=5):
        try:
            r = urllib.request.urlopen(urllib.request.Request(
                addr, data=json.dumps(payload).encode(),
                method="POST"), timeout=timeout)
            out[0] = ("ok", r.status)
        except Exception as e:
            out[0] = ("err", str(e))

    def test_engine_restart_same_process(self, tmp_path):
        """Journaled server: reply path clears the journal so a restart
        rehydrates nothing."""
        jp = str(tmp_path / "w.jsonl")
        ws = WorkerServer(journal_path=jp, reply_timeout=10.0)
        out = [None]
        t = threading.Thread(target=self._post, args=(ws.address, {"q": 1},
                                                      out, 10))
        t.start()
        batch = []
        deadline = time.time() + 5
        while not batch and time.time() < deadline:
            batch = ws.get_batch(4, timeout=0.2)
        assert len(batch) == 1 and not batch[0].replayed
        assert ws.reply(batch[0].request_id, _resp({"ok": 1}))
        t.join(timeout=10)
        assert out[0] == ("ok", 200)
        ws.commit_epoch()
        ws.close()
        ws2 = WorkerServer(journal_path=jp)
        assert ws2.pending_count() == 0
        assert ws2.get_batch(4, timeout=0.1) == []
        ws2.close()

    def test_kill_process_and_replay(self, tmp_path):
        """SIGKILL the worker process mid-request; a fresh process on the
        same journal rehydrates and answers the request (the data-level
        replay the reference gets from checkpointed offsets)."""
        jp = str(tmp_path / "w.jsonl")
        port_file = str(tmp_path / "port")
        child_src = (
            "import sys, time\n"
            "from mmlspark_tpu.serving.server import WorkerServer\n"
            "ws = WorkerServer(journal_path=sys.argv[1], reply_timeout=60)\n"
            "open(sys.argv[2], 'w').write(str(ws.port))\n"
            "time.sleep(300)\n")
        script = tmp_path / "child.py"
        script.write_text(child_src)
        env = dict(os.environ, PYTHONPATH="/root/repo")
        proc = subprocess.Popen([sys.executable, str(script), jp, port_file],
                                env=env)
        try:
            deadline = time.time() + 30
            while not os.path.exists(port_file) and time.time() < deadline:
                time.sleep(0.1)
            assert os.path.exists(port_file), "child never came up"
            port = int(open(port_file).read())
            out = [None]
            t = threading.Thread(target=self._post,
                                 args=(f"http://127.0.0.1:{port}/",
                                       {"q": 42}, out, 8))
            t.start()
            # wait until the request is durably journaled, then kill -9
            deadline = time.time() + 10
            while time.time() < deadline:
                if os.path.exists(jp) and '"t":"req"' in open(jp).read():
                    break
                time.sleep(0.05)
            else:
                pytest.fail("request never reached the journal")
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
            t.join(timeout=15)
            assert out[0][0] == "err"          # the connection died with it
        finally:
            if proc.poll() is None:
                proc.kill()
        # process 2: rehydrate from the journal alone
        ws = WorkerServer(journal_path=jp)
        try:
            batch = ws.get_batch(4, timeout=1.0)
            assert len(batch) == 1
            cached = batch[0]
            assert cached.replayed
            assert json.loads(cached.request.entity.string_content()) \
                == {"q": 42}
            assert ws.reply(cached.request_id, _resp({"answered": True}))
            assert ws.pending_count() == 0
        finally:
            ws.close()
        # process 3: nothing left to replay
        ws3 = WorkerServer(journal_path=jp)
        try:
            assert ws3.get_batch(4, timeout=0.2) == []
        finally:
            ws3.close()
