"""Widened text-analytics family: document batching, PII, the async
multi-task TextAnalyze, Healthcare, and the SDK aliases — against a local
mock server (the reference tests these with recorded replies the same way).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.dataframe import object_col
from mmlspark_tpu.services import (Healthcare, LanguageDetectorSDK, PII,
                                   TextAnalyze, TextSentiment)

_state = {"requests": [], "ops": {}, "op_counter": 0, "poll_queries": []}


class _TextMock(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _reply(self, obj, status=200, headers=()):
        out = json.dumps(obj).encode()
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def _start_op(self, kind):
        _state["op_counter"] += 1
        op = f"{kind}{_state['op_counter']}"
        _state["ops"][op] = 0
        host = self.headers["Host"]
        self._reply({}, status=202,
                    headers=[("Operation-Location",
                              f"http://{host}/poll/{op}")])

    def do_GET(self):
        path = urlparse(self.path)
        _state["poll_queries"].append(path.query)
        if path.path.startswith("/poll/"):
            op = path.path.rsplit("/", 1)[1]
            n = _state["ops"].get(op, 0)
            _state["ops"][op] = n + 1
            if n < 1:
                self._reply({"status": "running"})
            elif op.startswith("analyze"):
                docs = _state[f"docs_{op}"]
                self._reply({"status": "succeeded", "tasks": {
                    "entityRecognitionTasks": [{"state": "succeeded",
                        "results": {
                            "documents": [{"id": d["id"], "entities": [
                                {"text": d["text"], "category": "Noun"}]}
                                for d in docs],
                            "errors": []}}],
                    "sentimentAnalysisTasks": [{"state": "succeeded",
                        "results": {
                            "documents": [{"id": d["id"],
                                           "sentiment": "neutral"}
                                          for d in docs[:-1]],
                            "errors": [{"id": docs[-1]["id"],
                                        "error": {"code": "boom"}}]
                            if docs else []}}],
                }})
            else:  # health job
                docs = _state[f"docs_{op}"]
                self._reply({"status": "succeeded", "results": {
                    "documents": [{"id": d["id"],
                                   "entities": [{"text": "ibuprofen",
                                                 "category": "Drug"}],
                                   "relations": []} for d in docs],
                    "errors": []}})
        else:
            self._reply({"error": "not found"}, 404)

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        body = json.loads(self.rfile.read(n))
        path = urlparse(self.path)
        q = parse_qs(path.query)
        _state["requests"].append({"path": path.path, "query": q,
                                   "body": body})
        if path.path == "/sentiment":
            docs, errs = [], []
            for d in body["documents"]:
                if d["text"] == "ERR":
                    errs.append({"id": d["id"],
                                 "error": {"code": "InvalidDocument"}})
                else:
                    docs.append({"id": d["id"],
                                 "sentiment": "positive" if "good"
                                 in d["text"] else "negative",
                                 "confidenceScores": {"positive": 0.8}})
            self._reply({"documents": docs, "errors": errs})
        elif path.path == "/languages":
            self._reply({"documents": [
                {"id": d["id"], "detectedLanguage":
                    {"iso6391Name": (d.get("language") or "xx")[:2]}}
                for d in body["documents"]]})
        elif path.path == "/pii":
            self._reply({"documents": [
                {"id": d["id"], "redactedText": "*" * len(d["text"]),
                 "entities": [{"category": "Email"}]}
                for d in body["documents"]]})
        elif path.path == "/analyze":
            _state["op_counter"] += 1
            op = f"analyze{_state['op_counter']}"
            _state["ops"][op] = 0
            _state[f"docs_{op}"] = body["analysisInput"]["documents"]
            host = self.headers["Host"]
            self._reply({}, status=202,
                        headers=[("Operation-Location",
                                  f"http://{host}/poll/{op}")])
        elif path.path == "/health/jobs":
            _state["op_counter"] += 1
            op = f"health{_state['op_counter']}"
            _state["ops"][op] = 0
            _state[f"docs_{op}"] = body["documents"]
            host = self.headers["Host"]
            self._reply({}, status=202,
                        headers=[("Operation-Location",
                                  f"http://{host}/poll/{op}")])
        else:
            self._reply({"error": "not found"}, 404)


@pytest.fixture(scope="module")
def svc():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _TextMock)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def _sent_requests(path):
    return [r for r in _state["requests"] if r["path"] == path]


def test_auto_batching_groups_rows_and_scatters_results(svc):
    """batch_size groups scalar rows into one request; per-doc results and
    doc-level errors scatter back to the originating rows."""
    before = len(_sent_requests("/sentiment"))
    df = DataFrame({"txt": object_col(
        ["good a", "bad b", "ERR", "good c", "bad d"])})
    t = TextSentiment(url=svc + "/sentiment", output_col="out",
                      error_col="err", batch_size=2)
    t.set_vector_param("text", "txt")
    out = t.transform(df)
    sent = _sent_requests("/sentiment")[before:]
    assert [len(r["body"]["documents"]) for r in sent] == [2, 2, 1]
    assert out["out"][0]["sentiment"] == "positive"
    assert out["out"][1]["sentiment"] == "negative"
    # the doc-level error hits exactly its own row
    assert out["out"][2] is None
    assert out["err"][2]["error"]["code"] == "InvalidDocument"
    assert out["out"][3]["sentiment"] == "positive"
    assert out["out"][4]["sentiment"] == "negative"
    assert out["err"][0] is None and out["err"][4] is None


def test_user_batching_list_text_gives_array_output(svc):
    """A list-typed text value is one request; output is the per-doc array
    with errored docs in their slots (reference unpackBatchUDF order)."""
    df = DataFrame({"docs": object_col([["good x", "ERR", "bad y"]])})
    t = TextSentiment(url=svc + "/sentiment", output_col="out",
                      error_col="err")
    t.set_vector_param("text", "docs")
    out = t.transform(df)
    res = out["out"][0]
    assert len(res) == 3
    assert res[0]["sentiment"] == "positive"
    assert res[1] == {"error": {"code": "InvalidDocument"}}
    assert res[2]["sentiment"] == "negative"


def test_language_broadcast_single_hint_fills_batch(svc):
    """One language hint broadcasts across a user-batched document list
    (reference: Seq.fill when one language for N texts)."""
    df = DataFrame({"docs": object_col([["salut", "merci"]])})
    t = LanguageDetectorSDK(url=svc + "/languages", output_col="out")
    t.set_vector_param("text", "docs")
    t.set_scalar_param("language", "fr")
    out = t.transform(df)
    assert [d["iso6391Name"] for d in out["out"][0]] == ["fr", "fr"]


def test_sdk_alias_batch_default_is_five(svc):
    assert LanguageDetectorSDK(url="http://x/").get("batch_size") == 5
    assert TextSentiment(url="http://x/").get("batch_size") == 1


def test_pii_url_params_and_domain_validation(svc):
    before = len(_sent_requests("/pii"))
    df = DataFrame({"txt": object_col(["mail me at a@b.c"])})
    t = PII(url=svc + "/pii", output_col="out", error_col="err")
    t.set_vector_param("text", "txt")
    t.set_scalar_param("domain", "PHI")
    t.set_scalar_param("pii_categories", ["Email", "Address"])
    out = t.transform(df)
    req = _sent_requests("/pii")[before]
    assert req["query"]["domain"] == ["PHI"]
    assert req["query"]["piiCategories"] == ["Email,Address"]
    assert out["out"][0]["entities"][0]["category"] == "Email"
    assert out["out"][0]["redactedText"].startswith("*")
    # invalid domain → per-row build error, not an exception
    bad = PII(url=svc + "/pii", output_col="out", error_col="err")
    bad.set_vector_param("text", "txt")
    bad.set_scalar_param("domain", "everything")
    res = bad.transform(df)
    assert res["out"][0] is None
    assert "domain" in res["err"][0]["reasonPhrase"]


def test_text_analyze_multitask_async(svc):
    """TextAnalyze: one async job per batch, $top=25 forced onto the poll
    URL, per-document TAAnalyzeResult unpacking across task families."""
    df = DataFrame({"txt": object_col(["alpha", "beta"])})
    t = TextAnalyze(url=svc + "/analyze", output_col="out", error_col="err",
                    batch_size=25, polling_delay_ms=10,
                    entity_recognition_tasks=[
                        {"parameters": {"model-version": "latest"}}],
                    sentiment_analysis_tasks=[{"parameters": {}}])
    t.set_vector_param("text", "txt")
    out = t.transform(df)
    # $top=25 prefixes the poll query (reference modifyPollingURI)
    assert any(pq.startswith("$top=25") for pq in _state["poll_queries"])
    r0 = out["out"][0]
    assert r0["entityRecognition"][0]["result"]["entities"][0]["text"] \
        == "alpha"
    assert r0["sentimentAnalysis"][0]["result"]["sentiment"] == "neutral"
    # last doc's sentiment task errored server-side → error in its slot
    r1 = out["out"][1]
    assert r1["sentimentAnalysis"][0]["result"] is None
    assert r1["sentimentAnalysis"][0]["error"]["code"] == "boom"
    assert r1["entityRecognition"][0]["result"]["entities"][0]["text"] \
        == "beta"


def test_text_analyze_task_shape_validated(svc):
    df = DataFrame({"txt": object_col(["x"])})
    t = TextAnalyze(url=svc + "/analyze", output_col="out", error_col="err",
                    entity_recognition_tasks=[{"nope": 1}])
    t.set_vector_param("text", "txt")
    out = t.transform(df)
    assert out["out"][0] is None
    assert "parameters" in out["err"][0]["reasonPhrase"]


def test_healthcare_async_entities(svc):
    df = DataFrame({"txt": object_col(["took 200mg ibuprofen"])})
    t = Healthcare(url=svc + "/health/jobs", output_col="out",
                   error_col="err", polling_delay_ms=10)
    t.set_vector_param("text", "txt")
    out = t.transform(df)
    assert out["out"][0]["entities"][0]["category"] == "Drug"
    assert out["out"][0]["relations"] == []


def test_model_version_and_show_stats_ride_as_url_params(svc):
    before = len(_sent_requests("/sentiment"))
    df = DataFrame({"txt": object_col(["good z"])})
    t = TextSentiment(url=svc + "/sentiment", output_col="out")
    t.set_vector_param("text", "txt")
    t.set_scalar_param("model_version", "2022-01-01")
    t.set_scalar_param("show_stats", True)
    t.set_scalar_param("opinion_mining", True)
    t.transform(df)
    q = _sent_requests("/sentiment")[before]["query"]
    assert q["model-version"] == ["2022-01-01"]
    assert q["showStats"] == ["true"]
    assert q["opinionMining"] == ["true"]
