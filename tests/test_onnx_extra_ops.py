"""Long-tail ONNX ops (onnx/extra_ops.py): audio/DSP, integer-quantized,
vanilla RNN, losses, LRN/Lp pooling, bitwise.

References: numpy/scipy math written independently of the handlers, and
torch.nn.functional for the loss ops (real torch is in the image — the
strongest available oracle). Parity anchor: ORT's full standard opset
behind ``ONNXModel.scala:330``.
"""

import numpy as np
import pytest

import mmlspark_tpu.onnx as O
from mmlspark_tpu.onnx.convert import convert_model


def run(nodes, feeds, out_names, initializers=None):
    inputs = [O.make_tensor_value_info(k, v.dtype, list(v.shape))
              for k, v in feeds.items()]
    outs = [O.make_tensor_value_info(o, np.float32, ["?"])
            for o in out_names]
    g = O.make_graph(nodes, "g", inputs, outs,
                     initializers=initializers or {})
    cm = convert_model(O.make_model(g))
    res = cm(cm.params, {k: np.asarray(v) for k, v in feeds.items()})
    return [np.asarray(res[o]) for o in out_names]


class TestSmallOps:
    def test_reduce_log_sum(self):
        x = np.abs(np.random.default_rng(0).normal(1, 1, (3, 4))) \
            .astype(np.float32)
        (y,) = run([O.make_node("ReduceLogSum", ["x"], ["y"], axes=[1])],
                   {"x": x}, ["y"])
        np.testing.assert_allclose(y, np.log(x.sum(1, keepdims=True)),
                                   rtol=1e-5)

    def test_bitwise(self):
        a = np.array([0b1100, 0b1010], np.int32)
        b = np.array([0b1010, 0b0110], np.int32)
        for op, ref in [("BitwiseAnd", a & b), ("BitwiseOr", a | b),
                        ("BitwiseXor", a ^ b)]:
            (y,) = run([O.make_node(op, ["a", "b"], ["y"])],
                       {"a": a, "b": b}, ["y"])
            np.testing.assert_array_equal(y, ref)
        (y,) = run([O.make_node("BitwiseNot", ["a"], ["y"])], {"a": a}, ["y"])
        np.testing.assert_array_equal(y, ~a)

    def test_det(self):
        x = np.random.default_rng(1).normal(0, 1, (4, 3, 3)) \
            .astype(np.float32)
        (y,) = run([O.make_node("Det", ["x"], ["y"])], {"x": x}, ["y"])
        np.testing.assert_allclose(y, np.linalg.det(x), rtol=2e-4)

    def test_mvn(self):
        x = np.random.default_rng(2).normal(3, 2, (2, 3, 4, 5)) \
            .astype(np.float32)
        (y,) = run([O.make_node("MeanVarianceNormalization", ["x"], ["y"])],
                   {"x": x}, ["y"])
        mean = x.mean(axis=(0, 2, 3), keepdims=True)
        std = x.std(axis=(0, 2, 3), keepdims=True)
        np.testing.assert_allclose(y, (x - mean) / (std + 1e-7),
                                   rtol=1e-3, atol=1e-4)

    def test_lrn_matches_reference_loop(self):
        rng = np.random.default_rng(3)
        x = rng.normal(0, 1, (2, 7, 4, 4)).astype(np.float32)
        size, alpha, beta, bias = 3, 2e-4, 0.6, 1.5
        (y,) = run([O.make_node("LRN", ["x"], ["y"], size=size, alpha=alpha,
                                beta=beta, bias=bias)], {"x": x}, ["y"])
        C = x.shape[1]
        ref = np.empty_like(x)
        lo = (size - 1) // 2
        hi = size - 1 - lo
        for c in range(C):
            s = x[:, max(0, c - lo):min(C, c + hi + 1)] ** 2
            denom = (bias + (alpha / size) * s.sum(axis=1)) ** beta
            ref[:, c] = x[:, c] / denom
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)

    def test_lp_pool_and_global(self):
        rng = np.random.default_rng(4)
        x = rng.normal(0, 1, (1, 2, 6)).astype(np.float32)
        (y,) = run([O.make_node("LpPool", ["x"], ["y"], kernel_shape=[2],
                                strides=[2], p=2)], {"x": x}, ["y"])
        ref = np.sqrt((x.reshape(1, 2, 3, 2) ** 2).sum(-1))
        np.testing.assert_allclose(y, ref, rtol=1e-5)
        (g,) = run([O.make_node("GlobalLpPool", ["x"], ["g"], p=1)],
                   {"x": x}, ["g"])
        np.testing.assert_allclose(
            g, np.abs(x).sum(-1, keepdims=True), rtol=1e-5)

    def test_max_unpool(self):
        # 1x1x4 input pooled with k=2,s=2 -> values [5, 8] at flat idx 1, 3
        x = np.array([[[5.0, 8.0]]], np.float32)
        idx = np.array([[[1, 3]]], np.int64)
        (y,) = run([O.make_node("MaxUnpool", ["x", "i"], ["y"],
                                kernel_shape=[2], strides=[2])],
                   {"x": x, "i": idx}, ["y"])
        np.testing.assert_allclose(y, [[[0, 5, 0, 8]]])


class TestIntegerQuant:
    def test_matmul_integer(self):
        rng = np.random.default_rng(5)
        a = rng.integers(0, 255, (3, 4)).astype(np.uint8)
        b = rng.integers(-127, 127, (4, 2)).astype(np.int8)
        azp = np.uint8(128)
        bzp = np.int8(3)
        (y,) = run([O.make_node("MatMulInteger", ["a", "b", "azp", "bzp"],
                                ["y"])],
                   {"a": a, "b": b}, ["y"],
                   initializers={"azp": azp.reshape(()),
                                 "bzp": bzp.reshape(())})
        ref = (a.astype(np.int32) - 128) @ (b.astype(np.int32) - 3)
        np.testing.assert_array_equal(y, ref)

    def test_conv_integer(self):
        rng = np.random.default_rng(6)
        x = rng.integers(0, 20, (1, 1, 5, 5)).astype(np.uint8)
        w = rng.integers(-5, 5, (1, 1, 3, 3)).astype(np.int8)
        (y,) = run([O.make_node("ConvInteger", ["x", "w", "xzp"], ["y"])],
                   {"x": x}, ["y"],
                   initializers={"w": w, "xzp": np.uint8(2).reshape(())})
        import torch
        import torch.nn.functional as F
        ref = F.conv2d(torch.tensor(x.astype(np.float32) - 2),
                       torch.tensor(w.astype(np.float32))).numpy()
        np.testing.assert_array_equal(y, ref.astype(np.int32))

    def test_dynamic_quantize_linear(self):
        x = np.array([0.0, 2.0, -3.0, 1.5], np.float32)
        y, scale, zp = run(
            [O.make_node("DynamicQuantizeLinear", ["x"], ["y", "s", "z"])],
            {"x": x}, ["y", "s", "z"])
        assert y.dtype == np.uint8 and zp.dtype == np.uint8
        np.testing.assert_allclose(scale, 5.0 / 255.0, rtol=1e-6)
        # dequantized round-trips within one quantum
        deq = (y.astype(np.float32) - zp.astype(np.float32)) * scale
        np.testing.assert_allclose(deq, x, atol=float(scale) * 0.51)


class TestRNN:
    def _ref(self, X, W, R, B, h0, reverse=False):
        T, Bt, _ = X.shape
        H = W.shape[0]
        h = h0.copy()
        ys = []
        ts = range(T - 1, -1, -1) if reverse else range(T)
        for t in ts:
            h = np.tanh(X[t] @ W.T + B[:H] + h @ R.T + B[H:])
            ys.append(h)
        if reverse:
            ys = ys[::-1]
        return np.stack(ys), h

    def test_forward(self):
        rng = np.random.default_rng(7)
        T, Bt, I, H = 5, 2, 3, 4
        X = rng.normal(0, 1, (T, Bt, I)).astype(np.float32)
        W = rng.normal(0, 0.5, (1, H, I)).astype(np.float32)
        R = rng.normal(0, 0.5, (1, H, H)).astype(np.float32)
        B = rng.normal(0, 0.1, (1, 2 * H)).astype(np.float32)
        Y, Yh = run([O.make_node("RNN", ["x", "w", "r", "b"], ["Y", "Yh"],
                                 hidden_size=H)],
                    {"x": X}, ["Y", "Yh"],
                    initializers={"w": W, "r": R, "b": B})
        ys, h = self._ref(X, W[0], R[0], B[0], np.zeros((Bt, H), np.float32))
        np.testing.assert_allclose(Y[:, 0], ys, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(Yh[0], h, rtol=1e-5, atol=1e-6)

    def test_bidirectional_with_h0(self):
        rng = np.random.default_rng(8)
        T, Bt, I, H = 4, 3, 2, 3
        X = rng.normal(0, 1, (T, Bt, I)).astype(np.float32)
        W = rng.normal(0, 0.5, (2, H, I)).astype(np.float32)
        R = rng.normal(0, 0.5, (2, H, H)).astype(np.float32)
        B = rng.normal(0, 0.1, (2, 2 * H)).astype(np.float32)
        h0 = rng.normal(0, 1, (2, Bt, H)).astype(np.float32)
        Y, Yh = run([O.make_node("RNN", ["x", "w", "r", "b", "", "h0"],
                                 ["Y", "Yh"], hidden_size=H,
                                 direction="bidirectional")],
                    {"x": X}, ["Y", "Yh"],
                    initializers={"w": W, "r": R, "b": B, "h0": h0})
        fy, fh = self._ref(X, W[0], R[0], B[0], h0[0])
        ry, rh = self._ref(X, W[1], R[1], B[1], h0[1], reverse=True)
        np.testing.assert_allclose(Y[:, 0], fy, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(Y[:, 1], ry, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(Yh[1], rh, rtol=1e-5, atol=1e-6)


class TestActivationValidation:
    def test_gru_tanh_gates_rejected(self):
        # a GRU whose GATE activation is Tanh must be rejected, not
        # silently computed with sigmoid gates (regression: widening the
        # shared whitelist for RNN let this through)
        from mmlspark_tpu.onnx.convert import UnsupportedOp
        rng = np.random.default_rng(20)
        H, I = 3, 2
        X = rng.normal(0, 1, (4, 1, I)).astype(np.float32)
        W = rng.normal(0, 0.5, (1, 3 * H, I)).astype(np.float32)
        R = rng.normal(0, 0.5, (1, 3 * H, H)).astype(np.float32)
        with pytest.raises(UnsupportedOp, match="activations"):
            run([O.make_node("GRU", ["x", "w", "r"], ["Y", "Yh"],
                             hidden_size=H,
                             activations=["Tanh", "Tanh"])],
                {"x": X}, ["Y"], initializers={"w": W, "r": R})

    def test_rnn_sigmoid_rejected(self):
        from mmlspark_tpu.onnx.convert import UnsupportedOp
        rng = np.random.default_rng(21)
        X = rng.normal(0, 1, (4, 1, 2)).astype(np.float32)
        W = rng.normal(0, 0.5, (1, 3, 2)).astype(np.float32)
        R = rng.normal(0, 0.5, (1, 3, 3)).astype(np.float32)
        with pytest.raises(UnsupportedOp, match="activations"):
            run([O.make_node("RNN", ["x", "w", "r"], ["Y", "Yh"],
                             hidden_size=3, activations=["Sigmoid"])],
                {"x": X}, ["Y"], initializers={"w": W, "r": R})


class TestLosses:
    def test_nll_loss_vs_torch(self):
        import torch
        import torch.nn.functional as F
        rng = np.random.default_rng(9)
        logp = np.log(rng.dirichlet(np.ones(5), size=6)).astype(np.float32)
        tgt = rng.integers(0, 5, 6).astype(np.int64)
        w = rng.random(5).astype(np.float32)
        for reduction in ("mean", "sum", "none"):
            (y,) = run([O.make_node("NegativeLogLikelihoodLoss",
                                    ["x", "t", "w"], ["y"],
                                    reduction=reduction)],
                       {"x": logp, "t": tgt}, ["y"],
                       initializers={"w": w})
            ref = F.nll_loss(torch.tensor(logp), torch.tensor(tgt),
                             torch.tensor(w), reduction=reduction).numpy()
            np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)

    def test_nll_ignore_index(self):
        import torch
        import torch.nn.functional as F
        rng = np.random.default_rng(10)
        logp = np.log(rng.dirichlet(np.ones(4), size=5)).astype(np.float32)
        tgt = np.array([0, 1, 2, 3, 2], np.int64)
        (y,) = run([O.make_node("NegativeLogLikelihoodLoss", ["x", "t"],
                                ["y"], reduction="mean", ignore_index=2)],
                   {"x": logp, "t": tgt}, ["y"])
        ref = F.nll_loss(torch.tensor(logp), torch.tensor(tgt),
                         ignore_index=2).numpy()
        np.testing.assert_allclose(y, ref, rtol=1e-5)

    def test_softmax_cross_entropy_vs_torch(self):
        import torch
        import torch.nn.functional as F
        rng = np.random.default_rng(11)
        scores = rng.normal(0, 2, (7, 4)).astype(np.float32)
        tgt = rng.integers(0, 4, 7).astype(np.int64)
        y, logp = run([O.make_node("SoftmaxCrossEntropyLoss", ["x", "t"],
                                   ["y", "lp"])],
                      {"x": scores, "t": tgt}, ["y", "lp"])
        ref = F.cross_entropy(torch.tensor(scores),
                              torch.tensor(tgt)).numpy()
        np.testing.assert_allclose(y, ref, rtol=1e-5)
        np.testing.assert_allclose(
            logp, F.log_softmax(torch.tensor(scores), dim=1).numpy(),
            rtol=1e-5, atol=1e-6)


class TestRandom:
    def test_random_normal_stats_and_determinism(self):
        node = O.make_node("RandomNormal", [], ["y"], shape=[2000],
                           mean=1.0, scale=2.0, seed=7.0)
        (a,) = run([node], {"dummy": np.zeros(1, np.float32)}, ["y"])
        (b,) = run([node], {"dummy": np.zeros(1, np.float32)}, ["y"])
        np.testing.assert_array_equal(a, b)        # fixed seed → fixed draw
        assert abs(a.mean() - 1.0) < 0.2 and abs(a.std() - 2.0) < 0.2

    def test_random_uniform_like(self):
        x = np.zeros((500,), np.float32)
        (y,) = run([O.make_node("RandomUniformLike", ["x"], ["y"],
                                low=2.0, high=3.0)], {"x": x}, ["y"])
        assert y.shape == x.shape
        assert (y >= 2.0).all() and (y < 3.0).all()


class TestAudio:
    def test_windows_formulas(self):
        size = np.array(16, np.int64)
        for op, coeffs in [("HannWindow", [0.5, 0.5]),
                           ("HammingWindow", [25 / 46, 21 / 46]),
                           ("BlackmanWindow", [0.42, 0.5, 0.08])]:
            (w,) = run([O.make_node(op, ["n"], ["w"])], {"n": size}, ["w"])
            n = np.arange(16)
            ref = sum(((-1.0) ** k) * a * np.cos(2 * np.pi * k * n / 16)
                      for k, a in enumerate(coeffs))
            np.testing.assert_allclose(w, ref, rtol=1e-5, atol=1e-6)
            # symmetric variant uses N-1 in the denominator
            (ws,) = run([O.make_node(op, ["n"], ["w"], periodic=0)],
                        {"n": size}, ["w"])
            refs = sum(((-1.0) ** k) * a * np.cos(2 * np.pi * k * n / 15)
                       for k, a in enumerate(coeffs))
            np.testing.assert_allclose(ws, refs, rtol=1e-5, atol=1e-6)

    def test_dft_matches_numpy(self):
        rng = np.random.default_rng(12)
        x = rng.normal(0, 1, (2, 16, 1)).astype(np.float32)
        (y,) = run([O.make_node("DFT", ["x"], ["y"])], {"x": x}, ["y"])
        ref = np.fft.fft(x[..., 0], axis=1)
        np.testing.assert_allclose(y[..., 0], ref.real, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(y[..., 1], ref.imag, rtol=1e-4,
                                   atol=1e-4)
        # onesided real input
        (h,) = run([O.make_node("DFT", ["x"], ["y"], onesided=1)],
                   {"x": x}, ["y"])
        rref = np.fft.rfft(x[..., 0], axis=1)
        np.testing.assert_allclose(h[..., 0], rref.real, rtol=1e-4,
                                   atol=1e-4)
        # inverse round-trip
        (inv,) = run([O.make_node("DFT", ["y"], ["z"], inverse=1)],
                     {"y": np.stack([ref.real, ref.imag], -1)
                      .astype(np.float32)}, ["z"])
        np.testing.assert_allclose(inv[..., 0], x[..., 0], atol=1e-4)
        # negative axis counts on the FULL input rank: -2 on [B, N, 1] is
        # the signal axis (regression: was normalized against the complex
        # view's rank, off by one)
        (yn,) = run([O.make_node("DFT", ["x"], ["y"], axis=-2)],
                    {"x": x}, ["y"])
        np.testing.assert_allclose(yn[..., 0], ref.real, rtol=1e-4,
                                   atol=1e-4)

    def test_stft_matches_manual_framing(self):
        rng = np.random.default_rng(13)
        x = rng.normal(0, 1, (1, 32, 1)).astype(np.float32)
        win = np.hanning(8).astype(np.float32)
        (y,) = run([O.make_node("STFT", ["x", "step", "w"], ["y"],
                                onesided=1)],
                   {"x": x}, ["y"],
                   initializers={"step": np.array(4, np.int64), "w": win})
        n_frames = 1 + (32 - 8) // 4
        assert y.shape == (1, n_frames, 8 // 2 + 1, 2)
        for f in range(n_frames):
            seg = x[0, f * 4:f * 4 + 8, 0] * win
            ref = np.fft.rfft(seg)
            np.testing.assert_allclose(y[0, f, :, 0], ref.real, rtol=1e-4,
                                       atol=1e-4)
            np.testing.assert_allclose(y[0, f, :, 1], ref.imag, rtol=1e-4,
                                       atol=1e-4)

    def test_mel_weight_matrix(self):
        feeds = {"nm": np.array(8, np.int64)}
        (w,) = run([O.make_node("MelWeightMatrix",
                                ["nm", "dft", "sr", "lo", "hi"], ["w"])],
                   feeds, ["w"],
                   initializers={"dft": np.array(64, np.int64),
                                 "sr": np.array(8000, np.int64),
                                 "lo": np.array(0.0, np.float32),
                                 "hi": np.array(4000.0, np.float32)})
        assert w.shape == (33, 8)
        assert (w >= 0).all() and w.max() <= 1.0 + 1e-6
        # every mel bin has support, triangles peak once
        assert (w.sum(axis=0) > 0).all()
        # independently-computed triangle for one bin
        def mel(f):
            return 2595 * np.log10(1 + f / 700)
        edges = np.linspace(mel(0), mel(4000), 10)
        spec_mel = mel(np.arange(33) * 8000 / 64)
        j = 3
        up = (spec_mel - edges[j]) / (edges[j + 1] - edges[j])
        down = (edges[j + 2] - spec_mel) / (edges[j + 2] - edges[j + 1])
        ref = np.maximum(0, np.minimum(up, down))
        np.testing.assert_allclose(w[:, j], ref, rtol=1e-4, atol=1e-5)


class TestAsrPreprocessGraph:
    def test_log_mel_pipeline(self):
        """Whisper-style preprocessing as ONE graph: STFT → |.|² → mel
        projection → log — the audio front-end the reference reaches via
        its speech services."""
        rng = np.random.default_rng(14)
        sr, n = 8000, 512
        t = np.arange(n) / sr
        sig = (np.sin(2 * np.pi * 440 * t)
               + 0.5 * rng.normal(0, 0.1, n)).astype(np.float32)
        x = sig.reshape(1, n, 1)
        win = np.hanning(64).astype(np.float32)
        nodes = [
            O.make_node("STFT", ["x", "step", "w"], ["spec"], onesided=1),
            O.make_node("ReduceSumSquare", ["spec"], ["power"], axes=[-1],
                        keepdims=0),
            O.make_node("MelWeightMatrix",
                        ["nmel", "dft", "sr", "lo", "hi"], ["mel_w"]),
            O.make_node("MatMul", ["power", "mel_w"], ["mel"]),
            O.make_node("Add", ["mel", "eps"], ["mel_e"]),
            O.make_node("Log", ["mel_e"], ["logmel"]),
        ]
        (lm,) = run(nodes, {"x": x}, ["logmel"], initializers={
            "step": np.array(32, np.int64), "w": win,
            "nmel": np.array(10, np.int64), "dft": np.array(64, np.int64),
            "sr": np.array(sr, np.int64), "lo": np.array(20.0, np.float32),
            "hi": np.array(4000.0, np.float32),
            "eps": np.array(1e-6, np.float32)})
        n_frames = 1 + (n - 64) // 32
        assert lm.shape == (1, n_frames, 10)
        assert np.isfinite(lm).all()
        # the 440 Hz tone concentrates energy in one mel band
        band = lm[0].mean(axis=0)
        assert band.argmax() in range(1, 5)


class TestFusedConv:
    """ORT contrib FusedConv: Conv + folded activation (+ residual Z)."""

    def _x_w(self, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.normal(0, 1, (1, 3, 8, 8)).astype(np.float32)
        w = rng.normal(0, 0.3, (4, 3, 3, 3)).astype(np.float32)
        b = rng.normal(0, 0.1, 4).astype(np.float32)
        return x, w, b

    def test_matches_conv_plus_activation(self):
        x, w, b = self._x_w()
        (fused,) = run([O.make_node("FusedConv", ["x", "w", "b"], ["y"],
                                    domain="com.microsoft",
                                    activation="Relu")],
                       {"x": x}, ["y"], initializers={"w": w, "b": b})
        (plain,) = run([O.make_node("Conv", ["x", "w", "b"], ["c"]),
                        O.make_node("Relu", ["c"], ["y"])],
                       {"x": x}, ["y"], initializers={"w": w, "b": b})
        np.testing.assert_allclose(fused, plain, rtol=1e-6)

    def test_residual_and_param_activations(self):
        x, w, b = self._x_w(1)
        z = np.random.default_rng(2).normal(
            0, 1, (1, 4, 6, 6)).astype(np.float32)
        (y,) = run([O.make_node("FusedConv", ["x", "w", "b", "z"], ["y"],
                                domain="com.microsoft",
                                activation="LeakyRelu",
                                activation_params=[0.2])],
                   {"x": x}, ["y"], initializers={"w": w, "b": b, "z": z})
        (c,) = run([O.make_node("Conv", ["x", "w", "b"], ["c"])],
                   {"x": x}, ["c"], initializers={"w": w, "b": b})
        want = c + z
        want = np.where(want < 0, 0.2 * want, want)
        np.testing.assert_allclose(y, want, rtol=1e-5)

    def test_unknown_activation_rejected(self):
        x, w, b = self._x_w(3)
        with pytest.raises(Exception, match="activation"):
            run([O.make_node("FusedConv", ["x", "w", "b"], ["y"],
                             domain="com.microsoft", activation="Swoosh")],
                {"x": x}, ["y"], initializers={"w": w, "b": b})


class TestRelativePositionBias:
    """ORT contrib RelativePositionBias vs Hugging Face T5's own bucketing
    (the real torch implementation in this image is the oracle)."""

    @pytest.mark.parametrize("bidirectional", [True, False])
    def test_matches_t5_bucketing(self, bidirectional):
        import torch
        from transformers.models.t5.modeling_t5 import T5Attention

        num_buckets, heads, max_dist = 32, 4, 64
        q_len, k_len = 7, 11
        rng = np.random.default_rng(0)
        table = rng.normal(0, 1, (num_buckets, heads)).astype(np.float32)

        (got,) = run([O.make_node("RelativePositionBias",
                                  ["table", "ql", "kl"], ["bias"],
                                  domain="com.microsoft",
                                  max_distance=max_dist,
                                  is_bidirectional=int(bidirectional))],
                     {"table": table}, ["bias"],
                     initializers={"ql": np.array(q_len, np.int64),
                                   "kl": np.array(k_len, np.int64)})

        ctx = torch.arange(q_len)[:, None]
        mem = torch.arange(k_len)[None, :]
        buckets = T5Attention._relative_position_bucket(
            mem - ctx, bidirectional=bidirectional,
            num_buckets=num_buckets, max_distance=max_dist)
        want = table[buckets.numpy()].transpose(2, 0, 1)[None]
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert got.shape == (1, heads, q_len, k_len)
