"""Vision Transformer zoo model: torch-style ONNX export pinned against
the pure-numpy oracle, plus the cut-layer/featurizer surface the
reference's image models serve (``cntk/ImageFeaturizer.scala:100-108``)."""

import numpy as np
import pytest

from mmlspark_tpu.models.zoo.vit import (ViTConfig, export_vit_onnx,
                                         init_vit_params, vit_reference)
from mmlspark_tpu.onnx.convert import convert_model

CFG = ViTConfig(image_size=32, patch=8, d_model=64, heads=4, layers=2,
                d_ff=128, num_classes=5)


@pytest.fixture(scope="module")
def model():
    p = init_vit_params(CFG, seed=0)
    cm = convert_model(export_vit_onnx(CFG, params=p))
    return p, cm


class TestViTExport:
    def test_matches_numpy_oracle(self, model):
        p, cm = model
        px = np.random.default_rng(1).normal(
            0, 1, (3, 3, 32, 32)).astype(np.float32)
        out = cm(cm.params, {"pixel_values": px})
        feat_ref, logits_ref = vit_reference(p, px, CFG)
        np.testing.assert_allclose(np.asarray(out["feat"]), feat_ref,
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(out["logits"]), logits_ref,
                                   atol=2e-4)

    def test_output_shapes_and_batch_polymorphism(self, model):
        _, cm = model
        for b in (1, 4):
            px = np.zeros((b, 3, 32, 32), np.float32)
            out = cm(cm.params, {"pixel_values": px})
            assert np.asarray(out["feat"]).shape == (b, CFG.d_model)
            assert np.asarray(out["logits"]).shape == (b, CFG.num_classes)

    def test_image_featurizer_cut_layers(self, model):
        # the featurizer's default output names (feat/logits) are exactly
        # what the export emits — cut-layer semantics work unchanged
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.featurizer import ImageFeaturizer
        p, _ = model
        rng = np.random.default_rng(2)
        imgs = np.empty(3, object)
        for i in range(3):
            imgs[i] = rng.integers(0, 256, (32, 32, 3), np.uint8)
        df = DataFrame({"image": imgs})
        mb = export_vit_onnx(CFG, params=p)
        fz = ImageFeaturizer(mb, cut_output_layers=1, input_size=32,
                             mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5])
        out = fz.transform(df)
        feats = np.stack([np.asarray(v) for v in out["features"]])
        assert feats.shape == (3, CFG.d_model)
        head = ImageFeaturizer(mb, cut_output_layers=0, input_size=32,
                               mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5])
        logits = np.stack([np.asarray(v)
                           for v in head.transform(df)["features"]])
        assert logits.shape == (3, CFG.num_classes)

    def test_downloader_lists_vit(self, tmp_path):
        from mmlspark_tpu.models.zoo.downloader import (BUILTIN_MODELS,
                                                        ModelDownloader)
        assert "ViT-B-16" in BUILTIN_MODELS
        # materializing the 86M-param ViT-B is too heavy for a unit test;
        # the registry entry + the small-config export above cover it
        d = ModelDownloader(str(tmp_path))
        assert "ViT-B-16" in d.generators
        schema, _gen = d.generators["ViT-B-16"]
        assert schema.name == "ViT-B-16"
