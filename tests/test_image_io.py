"""Tests for the image package, binary/image IO, ImageFeaturizer, and the
model downloader — mirrors the reference's opencv + io + deep-learning
image suites."""

import os
import zipfile

import numpy as np

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.dataframe import object_col
from mmlspark_tpu.image import (Blur, CenterCropImage, ColorFormat, CropImage,
                                Flip, GaussianKernel, ImageSetAugmenter,
                                ImageTransformer, ResizeImage,
                                ResizeImageTransformer, Threshold,
                                UnrollBinaryImage, UnrollImage, decode_image,
                                encode_image, make_image)
from mmlspark_tpu.image.unroll import roll, unroll
from mmlspark_tpu.io import read_binary_files, read_images


def _checker(h=32, w=48):
    img = np.zeros((h, w, 3), dtype=np.uint8)
    img[::2, ::2] = [255, 0, 0]
    img[1::2, 1::2] = [0, 255, 0]
    return img


def _img_df(n=3, h=32, w=48):
    return DataFrame({"image": object_col(
        [make_image(_checker(h, w), origin=f"img{i}") for i in range(n)])})


def test_codec_roundtrip():
    img = make_image(_checker())
    raw = encode_image(img, ".png")
    back = decode_image(raw, origin="x")
    assert back["height"] == 32 and back["width"] == 48
    np.testing.assert_array_equal(back["data"], img["data"])


def test_resize_and_aspect():
    out = ImageTransformer(stages=[ResizeImage(height=16, width=24)]) \
        .transform(_img_df())
    im = out["image"][0]
    assert (im["height"], im["width"]) == (16, 24)
    # shorter-side resize keeps aspect
    out2 = ImageTransformer(
        stages=[ResizeImage(size=16, keep_aspect_ratio=True)]) \
        .transform(_img_df(h=32, w=48))
    im2 = out2["image"][0]
    assert im2["height"] == 16 and im2["width"] == 24


def test_crop_centercrop_flip():
    t = ImageTransformer(stages=[CropImage(x=4, y=2, height=10, width=20)])
    im = t.transform(_img_df())["image"][0]
    assert (im["height"], im["width"]) == (10, 20)
    t2 = ImageTransformer(stages=[CenterCropImage(height=10, width=20)])
    im2 = t2.transform(_img_df())["image"][0]
    assert (im2["height"], im2["width"]) == (10, 20)
    src = _img_df(1)
    lr = ImageTransformer(stages=[Flip(Flip.FLIP_LEFT_RIGHT)]).transform(src)
    np.testing.assert_array_equal(lr["image"][0]["data"],
                                  src["image"][0]["data"][:, ::-1])


def test_blur_threshold_gaussian_colorformat():
    import cv2
    df = _img_df(1)
    b = ImageTransformer(stages=[Blur(3, 3)]).transform(df)["image"][0]
    assert b["data"].shape == (32, 48, 3)
    th = ImageTransformer(stages=[
        ColorFormat(cv2.COLOR_BGR2GRAY),
        Threshold(127, 255, cv2.THRESH_BINARY)]).transform(df)["image"][0]
    assert th["nChannels"] == 1
    assert set(np.unique(th["data"])) <= {0, 255}
    g = ImageTransformer(stages=[GaussianKernel(3, 1.0)]).transform(df)["image"][0]
    assert g["data"].shape == (32, 48, 3)


def test_pipelined_ops_and_tensor_output():
    t = (ImageTransformer(to_tensor=True, normalize_mean=[0.485, 0.456, 0.406],
                          normalize_std=[0.229, 0.224, 0.225])
         .resize(height=8, width=8))
    out = t.transform(_img_df(2))
    x = out["image"][0]
    assert x.shape == (3, 8, 8) and x.dtype == np.float32


def test_image_transformer_save_load(tmp_path):
    t = ImageTransformer(stages=[ResizeImage(height=8, width=8), Flip(1)],
                         to_tensor=False)
    t.save(str(tmp_path / "it"))
    t2 = ImageTransformer.load(str(tmp_path / "it"))
    a = t.transform(_img_df(1))["image"][0]["data"]
    b = t2.transform(_img_df(1))["image"][0]["data"]
    np.testing.assert_array_equal(a, b)


def test_unroll_roll_roundtrip():
    img = make_image(_checker(4, 5))
    v = unroll(img)
    assert v.shape == (4 * 5 * 3,)
    # CHW order: first H*W entries are channel 0 (blue in BGR)
    np.testing.assert_array_equal(
        v[:20].reshape(4, 5), img["data"][:, :, 0].astype(np.float64))
    back = roll(v, img)
    np.testing.assert_array_equal(back["data"], img["data"])


def test_unroll_stages():
    df = _img_df(2, 8, 8)
    out = UnrollImage().transform(df)
    assert out["<image>"][0].shape == (8 * 8 * 3,)
    raw = DataFrame({"image": object_col(
        [encode_image(make_image(_checker(16, 16))) for _ in range(2)])})
    out2 = UnrollBinaryImage(height=8, width=8).transform(raw)
    assert out2["<image>"][0].shape == (8 * 8 * 3,)


def test_resize_image_transformer_and_augmenter():
    df = _img_df(2)
    out = ResizeImageTransformer(height=8, width=8).transform(df)
    assert out["image"][0]["height"] == 8
    aug = ImageSetAugmenter(flip_left_right=True, flip_up_down=True)
    out2 = aug.transform(df)
    assert len(out2) == 6


def test_binary_and_image_readers(tmp_path):
    d = tmp_path / "files"
    os.makedirs(d)
    for i in range(3):
        with open(d / f"img{i}.png", "wb") as f:
            f.write(encode_image(make_image(_checker(8, 8))))
    with open(d / "junk.txt", "wb") as f:
        f.write(b"not an image")
    with zipfile.ZipFile(d / "pack.zip", "w") as zf:
        zf.writestr("inner.bin", b"\x01\x02")
    raw = read_binary_files(str(d))
    assert len(raw) == 5  # 3 png + junk + zip member
    assert any(p.endswith("pack.zip/inner.bin") for p in raw["path"])
    pngs = read_binary_files(str(d), pattern="*.png")
    assert len(pngs) == 3
    imgs = read_images(str(d), pattern="*")
    assert len(imgs) == 3  # junk + zip member dropped
    assert all(im["height"] == 8 for im in imgs["image"])


def test_model_downloader_and_featurizer(tmp_path):
    from mmlspark_tpu.models.featurizer import ImageFeaturizer
    from mmlspark_tpu.models.zoo.downloader import (BUILTIN_MODELS,
                                                    ModelDownloader)
    assert "ResNet50" in BUILTIN_MODELS
    dl = ModelDownloader(str(tmp_path / "models"))
    schema = dl.download_model("ResNet18")
    assert os.path.isfile(schema.uri)
    assert schema.layer_names == ["logits", "feat"]
    # idempotent
    schema2 = dl.download_model("ResNet18")
    assert schema2.uri == schema.uri
    assert [m.name for m in dl.local_models()] == ["ResNet18"]

    model_bytes = dl.load_bytes("ResNet18")
    df = _img_df(3, 50, 40)
    feat = ImageFeaturizer(model_bytes, input_size=32, mini_batch_size=2,
                           output_col="features")
    out = feat.transform(df)
    f0 = np.asarray(out["features"][0])
    assert f0.shape == (512 * 4,)  # resnet18 final width (64*8 blocks *4)
    # cut_output_layers=0 → logits
    logits = ImageFeaturizer(model_bytes, input_size=32, cut_output_layers=0,
                             output_col="logits").transform(df)
    l0 = np.asarray(logits["logits"][0])
    assert l0.shape == (1000,)


def test_featurizer_drops_bad_rows():
    from mmlspark_tpu.models.featurizer import ImageFeaturizer
    from mmlspark_tpu.models.zoo.downloader import _gen_resnet18
    model_bytes = _gen_resnet18()
    cells = [make_image(_checker(8, 8)), None, b"garbagebytes"]
    df = DataFrame({"image": object_col(cells), "rowid": np.arange(3)})
    out = ImageFeaturizer(model_bytes, input_size=32).transform(df)
    assert len(out) == 1 and out["rowid"][0] == 0


# ---------------------------------------------------------------------------
# dense uint8 device column (transform_resident)


def _tensor_transformer():
    return ImageTransformer(
        to_tensor=True, normalize_mean=[0.485, 0.456, 0.406],
        normalize_std=[0.229, 0.224, 0.225]).resize(height=16, width=16)


def test_transform_resident_uint8_wire_bytes_and_parity():
    """The wire carries the uint8 pixels, not the float32 tensor: exactly
    ONE counted ingest h2d of N*H*W*C bytes (4x fewer than staging the
    host-normalized f32 batch), and the device-side normalize reproduces
    the host tensor path."""
    from mmlspark_tpu.core.residency import residency_stats
    from mmlspark_tpu.observability import reset_all

    df = _img_df(4, 24, 32)
    t = _tensor_transformer()
    reset_all()
    out = t.transform_resident(df)
    s = residency_stats()
    assert s["h2d_ops"]["ingest"] == 1
    assert s["h2d_bytes"]["ingest"] == 4 * 16 * 16 * 3   # uint8 itemsize
    assert s["d2h_ops"]["materialize"] == 0              # device-born, lazy
    want = t.transform(df)["image"]
    got = [np.asarray(out["image"][i]) for i in range(4)]
    assert got[0].shape == (3, 16, 16) and got[0].dtype == np.float32
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, np.asarray(w), atol=1e-5)
    # reading the device-born column back IS the counted materialize
    assert residency_stats()["d2h_ops"]["materialize"] >= 1


def test_transform_resident_slab_reuse():
    from mmlspark_tpu.models.runner import StagingSlabPool

    pool = StagingSlabPool()
    df = _img_df(3, 20, 20)
    t = _tensor_transformer()
    a = t.transform_resident(df, slab_pool=pool)
    b = t.transform_resident(df, slab_pool=pool)
    assert pool.allocs == 1 and pool.reuses == 1
    np.testing.assert_allclose(np.asarray(a["image"][0]),
                               np.asarray(b["image"][0]), atol=0)


def test_transform_resident_rejects_ragged_shapes():
    import pytest

    cells = [make_image(_checker(16, 16)), make_image(_checker(16, 24))]
    df = DataFrame({"image": object_col(cells)})
    # no resize stage: decoded shapes differ
    t = ImageTransformer(to_tensor=True)
    with pytest.raises(ValueError, match="uniform"):
        t.transform_resident(df)
