"""Training on imported ONNX graphs (onnx/train.py).

What the reference structurally cannot do: its ONNX path is a frozen ORT
session (``ONNXModel.scala:330``); here imported graphs are pure JAX over
an explicit params dict, so jax.grad + optax fine-tune them — including
genuine ``torch.onnx.export`` artifacts, with torch out of the loop.
"""

import numpy as np
import pytest

import mmlspark_tpu.onnx as O
from mmlspark_tpu.onnx.convert import convert_model
from mmlspark_tpu.onnx.train import fine_tune, make_train_step, value_and_grad


def mlp_with_loss(din=6, dhid=8, dout=3, seed=0):
    """MLP whose graph carries its OWN SoftmaxCrossEntropyLoss objective."""
    rng = np.random.default_rng(seed)
    w1 = rng.normal(0, 0.5, (din, dhid)).astype(np.float32)
    b1 = np.zeros(dhid, np.float32)
    w2 = rng.normal(0, 0.5, (dhid, dout)).astype(np.float32)
    b2 = np.zeros(dout, np.float32)
    nodes = [
        O.make_node("MatMul", ["x", "w1"], ["h0"]),
        O.make_node("Add", ["h0", "b1"], ["h1"]),
        O.make_node("Relu", ["h1"], ["h2"]),
        O.make_node("MatMul", ["h2", "w2"], ["l0"]),
        O.make_node("Add", ["l0", "b2"], ["logits"]),
        O.make_node("SoftmaxCrossEntropyLoss", ["logits", "labels"],
                    ["loss"]),
    ]
    g = O.make_graph(
        nodes, "mlp_train",
        inputs=[O.make_tensor_value_info("x", np.float32, ["N", din]),
                O.make_tensor_value_info("labels", np.int64, ["N"])],
        outputs=[O.make_tensor_value_info("loss", np.float32, []),
                 O.make_tensor_value_info("logits", np.float32,
                                          ["N", dout])],
        initializers={"w1": w1, "b1": b1, "w2": w2, "b2": b2})
    return O.make_model(g)


def toy_data(n=256, din=6, dout=3, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, din)).astype(np.float32)
    y = (X[:, :dout].argmax(axis=1)).astype(np.int64)
    return X, y


class TestValueAndGrad:
    def test_grads_flow_to_all_params(self):
        cm = convert_model(mlp_with_loss())
        X, y = toy_data(32)
        vg = value_and_grad(cm, output="loss")
        val, grads = vg(cm.params, {"x": X, "labels": y})
        assert np.isfinite(float(val))
        assert set(grads) == set(cm.params)
        for k, g in grads.items():
            assert np.asarray(g).shape == np.asarray(cm.params[k]).shape
            assert np.abs(np.asarray(g)).sum() > 0, f"zero grad for {k}"

    def test_loss_fn_form(self):
        cm = convert_model(mlp_with_loss())
        X, y = toy_data(16)

        def loss_fn(outputs, feeds):
            import jax.numpy as jnp
            onehot = jnp.eye(3)[feeds["labels"]]
            p = jnp.exp(outputs["logits"])
            p = p / p.sum(-1, keepdims=True)
            return jnp.mean(((p - onehot) ** 2))
        val, grads = value_and_grad(cm, loss_fn=loss_fn)(
            cm.params, {"x": X, "labels": y})
        assert np.isfinite(float(val))


class TestFineTune:
    def test_loss_decreases_and_accuracy_improves(self):
        import optax
        cm = convert_model(mlp_with_loss())
        X, y = toy_data(256)

        def batches():
            while True:
                yield {"x": X, "labels": y}

        params, losses = fine_tune(cm, batches(),
                                   optimizer=optax.adam(5e-2),
                                   output="loss", steps=60)
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        logits0 = np.asarray(cm(cm.params, {"x": X, "labels": y})["logits"])
        logits1 = np.asarray(cm(params, {"x": X, "labels": y})["logits"])
        acc0 = (logits0.argmax(1) == y).mean()
        acc1 = (logits1.argmax(1) == y).mean()
        assert acc1 > acc0 and acc1 > 0.85, (acc0, acc1)

    def test_frozen_backbone(self):
        import optax
        cm = convert_model(mlp_with_loss())
        X, y = toy_data(64)
        step, init = make_train_step(
            cm, optax.sgd(0.1), output="loss",
            trainable=lambda name: name in ("w2", "b2"))
        params = {k: np.asarray(v) for k, v in cm.params.items()}
        opt_state = init(params)
        new_params, _, _ = step(params, opt_state, {"x": X, "labels": y})
        np.testing.assert_array_equal(np.asarray(new_params["w1"]),
                                      params["w1"])   # frozen
        assert np.abs(np.asarray(new_params["w2"])
                      - params["w2"]).max() > 0       # trained

    def test_onnx_estimator_pipeline(self, tmp_path):
        """DataFrame-level: ONNXEstimator.fit → fitted ONNXModel whose
        weights_override carries the tuned weights; the training graph's
        loss subtree prunes away at inference (no labels fed)."""
        from mmlspark_tpu.core import DataFrame, PipelineStage
        from mmlspark_tpu.models.onnx_estimator import ONNXEstimator
        X, y = toy_data(128, seed=5)
        col = np.empty(len(X), dtype=object)
        col[:] = list(X)
        df = DataFrame({"features": col, "label": y})
        log = []
        est = ONNXEstimator(mlp_with_loss(),
                            feed_dict={"x": "features"},
                            fetch_dict={"logits": "logits"},
                            argmax_dict={"pred": "logits"},
                            loss_output="loss", label_input="labels",
                            epochs=25, batch_size=32, learning_rate=5e-2,
                            eval_log=log)
        model = est.fit(df)
        assert log[-1] < log[0] * 0.5, (log[0], log[-1])
        out = model.transform(df)          # no labels needed at inference
        acc = (np.asarray(out["pred"], dtype=np.int64) == y).mean()
        assert acc > 0.85, acc
        # save/load round-trips the override
        model.save(str(tmp_path / "m"))
        loaded = PipelineStage.load(str(tmp_path / "m"))
        out2 = loaded.transform(df)
        np.testing.assert_array_equal(np.asarray(out["pred"]),
                                      np.asarray(out2["pred"]))
        # and the tuned model differs from the untuned weights
        from mmlspark_tpu.models.onnx_model import ONNXModel
        raw = ONNXModel(mlp_with_loss(),
                        feed_dict={"x": "features"},
                        fetch_dict={"logits": "logits"},
                        argmax_dict={"pred": "logits"})
        acc_raw = (np.asarray(raw.transform(df)["pred"], dtype=np.int64)
                   == y).mean()
        assert acc > acc_raw

    def test_estimator_objective_mode_and_frozen_prefix(self):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.onnx_estimator import ONNXEstimator
        X, y = toy_data(96, seed=6)
        col = np.empty(len(X), dtype=object)
        col[:] = list(X)
        df = DataFrame({"features": col, "label": y})
        # graph WITHOUT a loss node: objective computed outside
        import mmlspark_tpu.onnx as O
        rng = np.random.default_rng(7)
        g = O.make_graph(
            [O.make_node("MatMul", ["x", "w1"], ["h"]),
             O.make_node("Relu", ["h"], ["hr"]),
             O.make_node("MatMul", ["hr", "w2"], ["logits"])],
            "plain",
            inputs=[O.make_tensor_value_info("x", np.float32, ["N", 6])],
            outputs=[O.make_tensor_value_info("logits", np.float32,
                                              ["N", 3])],
            initializers={
                "w1": rng.normal(0, 0.5, (6, 8)).astype(np.float32),
                "w2": rng.normal(0, 0.5, (8, 3)).astype(np.float32)})
        log = []
        est = ONNXEstimator(O.make_model(g),
                            feed_dict={"x": "features"},
                            fetch_dict={"logits": "logits"},
                            objective="softmax_cross_entropy",
                            target_output="logits",
                            trainable_prefix=["w2"],
                            epochs=10, batch_size=32, learning_rate=5e-2,
                            eval_log=log)
        model = est.fit(df)
        assert log[-1] < log[0]
        # frozen w1: the override equals the original for w1 only
        import io
        with np.load(io.BytesIO(model.get("weights_override"))) as z:
            ov = {k: z[k] for k in z.files}
        cm = convert_model(est.get("model_bytes"))
        np.testing.assert_array_equal(ov["w1"], cm.params["w1"])
        assert np.abs(ov["w2"] - cm.params["w2"]).max() > 0

    def test_estimator_default_fetch_excludes_loss(self):
        # empty fetch_dict + graph-carried loss: the fitted model must
        # serve the non-loss outputs, not crash on the unfed labels input
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.onnx_estimator import ONNXEstimator
        X, y = toy_data(64, seed=8)
        col = np.empty(len(X), dtype=object)
        col[:] = list(X)
        df = DataFrame({"features": col, "label": y})
        model = ONNXEstimator(mlp_with_loss(),
                              feed_dict={"x": "features"},
                              loss_output="loss", label_input="labels",
                              epochs=2, batch_size=32).fit(df)
        out = model.transform(df)
        assert np.asarray(out["logits"][0]).shape == (3,)
        assert "loss" not in out.columns

    def test_estimator_string_prefix_and_small_frame(self):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.onnx_estimator import ONNXEstimator
        X, y = toy_data(64, seed=9)
        col = np.empty(len(X), dtype=object)
        col[:] = list(X)
        df = DataFrame({"features": col, "label": y})
        est = ONNXEstimator(mlp_with_loss(), feed_dict={"x": "features"},
                            loss_output="loss", label_input="labels",
                            trainable_prefix="w2",      # bare string ok
                            epochs=1, batch_size=32)
        assert est.fit(df) is not None
        with pytest.raises(ValueError, match="fewer rows"):
            est.fit(df.head(8))

    def test_pruned_intermediate_fetch(self):
        # fetching an internal tensor = reference's cut-layer featurization
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.onnx_model import ONNXModel
        X, _ = toy_data(8)
        col = np.empty(len(X), dtype=object)
        col[:] = list(X)
        m = ONNXModel(mlp_with_loss(),
                      feed_dict={"x": "features"},
                      fetch_dict={"hidden": "h2"})
        out = m.transform(DataFrame({"features": col}))
        assert np.asarray(out["hidden"][0]).shape == (8,)

    def test_torch_exported_model_fine_tunes(self):
        torch = pytest.importorskip("torch")
        import io
        import optax
        from mmlspark_tpu.interop.onnx_shim import install_onnx_shim
        install_onnx_shim()

        class Net(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.fc1 = torch.nn.Linear(6, 10)
                self.fc2 = torch.nn.Linear(10, 3)

            def forward(self, x):
                return self.fc2(torch.relu(self.fc1(x)))

        net = Net().eval()
        buf = io.BytesIO()
        torch.onnx.export(net, (torch.zeros(4, 6),), buf,
                          input_names=["x"], output_names=["logits"],
                          dynamo=False,
                          dynamic_axes={"x": {0: "N"},
                                        "logits": {0: "N"}})
        cm = convert_model(buf.getvalue())
        X, y = toy_data(256, seed=3)

        def loss_fn(outputs, feeds):
            import jax
            import jax.numpy as jnp
            lp = jax.nn.log_softmax(outputs["logits"], axis=-1)
            return -jnp.take_along_axis(
                lp, feeds["labels"][:, None], axis=1).mean()

        def batches():
            while True:
                yield {"x": X, "labels": y}

        params, losses = fine_tune(cm, batches(),
                                   optimizer=optax.adam(5e-2),
                                   loss_fn=loss_fn, steps=50)
        assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
        logits = np.asarray(cm(params, {"x": X})["logits"])
        assert (logits.argmax(1) == y).mean() > 0.8


class TestEstimatorEarlyStopping:
    def _df(self, n=200, seed=20, val_frac=0.25):
        from mmlspark_tpu.core import DataFrame
        X, y = toy_data(n, seed=seed)
        col = np.empty(n, dtype=object)
        col[:] = list(X)
        val = np.zeros(n, bool)
        val[int(n * (1 - val_frac)):] = True
        return DataFrame({"features": col, "label": y, "val": val}), X, y, val

    def test_early_stop_uses_best_epoch(self):
        from mmlspark_tpu.models.onnx_estimator import ONNXEstimator
        df, X, y, val = self._df()
        log = []
        est = ONNXEstimator(mlp_with_loss(),
                            feed_dict={"x": "features"},
                            loss_output="loss", label_input="labels",
                            validation_indicator_col="val",
                            early_stopping_epochs=3,
                            epochs=200, batch_size=32,
                            learning_rate=0.1, eval_log=log)
        model = est.fit(df)
        epochs = [e for e in log if isinstance(e, dict)]
        assert 0 < len(epochs) < 200          # stopped early
        # the fitted model scores the holdout at (near) the best val loss
        out = model.transform(df.filter(val))
        logits = np.stack([np.asarray(v) for v in out["logits"]])
        acc = (logits.argmax(1) == y[val]).mean()
        assert acc > 0.8, acc

    def test_patience_without_val_col_rejected(self):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.onnx_estimator import ONNXEstimator
        df, *_ = self._df()
        df = df.drop("val")
        with pytest.raises(ValueError, match="validation_indicator_col"):
            ONNXEstimator(mlp_with_loss(), feed_dict={"x": "features"},
                          loss_output="loss", label_input="labels",
                          early_stopping_epochs=2, epochs=3,
                          batch_size=32).fit(df)


class TestLoRA:
    """Low-rank adapters over imported graphs (``onnx.train.lora_*``).

    The base stays frozen (bit-identical before/after), only rank·(n+m)
    adapter params train, and the merged deltas serve through the same
    ``weights_override`` layering full fine-tuning uses."""

    def test_zero_init_is_identity(self):
        from mmlspark_tpu.onnx.train import init_lora, lora_merge
        cm = convert_model(mlp_with_loss())
        lora = init_lora(cm, rank=2)
        merged = lora_merge({k: np.asarray(v) for k, v in cm.params.items()},
                            lora, alpha=2.0)
        for k in cm.params:
            np.testing.assert_array_equal(np.asarray(merged[k]),
                                          np.asarray(cm.params[k]))

    def test_targets_are_2d_and_wide_enough(self):
        from mmlspark_tpu.onnx.train import lora_targets
        cm = convert_model(mlp_with_loss())     # w1 (6,8), w2 (8,3), biases
        assert lora_targets(cm, 2) == ["w1", "w2"]
        assert lora_targets(cm, 4) == ["w1"]    # w2's min dim is 3
        assert lora_targets(cm, 2, lambda n: n == "w2") == ["w2"]

    def test_lora_learns_and_base_stays_frozen(self):
        from mmlspark_tpu.onnx.train import lora_fine_tune
        cm = convert_model(mlp_with_loss())
        X, y = toy_data(256, seed=2)
        base_before = {k: np.asarray(v).copy() for k, v in cm.params.items()}

        def batches():
            rng = np.random.default_rng(0)
            for _ in range(60):
                sel = rng.choice(len(X), 64, replace=False)
                yield {"x": X[sel], "labels": y[sel]}

        import optax
        merged, lora, losses = lora_fine_tune(cm, batches(), rank=3,
                                              optimizer=optax.adam(5e-2),
                                              output="loss")
        assert losses[-1] < 0.5 * losses[0]
        for k, v in cm.params.items():          # base untouched
            np.testing.assert_array_equal(np.asarray(v), base_before[k])
        # adapters only touch the 2-D targets; biases are bit-identical
        np.testing.assert_array_equal(np.asarray(merged["b1"]),
                                      base_before["b1"])
        assert not np.array_equal(np.asarray(merged["w1"]),
                                  base_before["w1"])
        # adapter param count is rank*(n+m) per target, way under full
        n_adapter = sum(int(np.prod(ab["a"].shape))
                        + int(np.prod(ab["b"].shape))
                        for ab in lora.values())
        n_full = sum(int(np.prod(np.asarray(v).shape))
                     for k, v in cm.params.items() if k in ("w1", "w2"))
        assert n_adapter < n_full

    def test_estimator_lora_mode(self):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.onnx_estimator import ONNXEstimator
        X, y = toy_data(128, seed=7)
        col = np.empty(len(X), dtype=object)
        col[:] = list(X)
        df = DataFrame({"features": col, "label": y})
        log = []
        est = ONNXEstimator(mlp_with_loss(),
                            feed_dict={"x": "features"},
                            fetch_dict={"logits": "logits"},
                            argmax_dict={"pred": "logits"},
                            loss_output="loss", label_input="labels",
                            epochs=60, batch_size=32, learning_rate=1e-1,
                            lora_rank=2, eval_log=log)
        model = est.fit(df)
        assert log[-1] < log[0] * 0.6, (log[0], log[-1])
        acc = (np.asarray(model.transform(df)["pred"], dtype=np.int64)
               == y).mean()
        assert acc > 0.8, acc
        # the override carries ONLY the adapted matrices
        import io as _io
        with np.load(_io.BytesIO(model.get("weights_override"))) as z:
            assert sorted(z.files) == ["w1", "w2"]

    def test_validation(self):
        from mmlspark_tpu.onnx.train import init_lora
        cm = convert_model(mlp_with_loss())
        with pytest.raises(ValueError, match="rank"):
            init_lora(cm, rank=0)
        with pytest.raises(ValueError, match="unknown"):
            init_lora(cm, rank=2, targets=["nope"])
        with pytest.raises(ValueError, match="no 2-D"):
            init_lora(cm, rank=100)
        with pytest.raises(ValueError, match="2-D"):
            init_lora(cm, rank=2, targets=["b1"])   # 1-D bias
