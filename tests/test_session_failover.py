"""Session survivability: durable session journaling (insert records,
append-only emitted-token tails, torn-tail repair, compaction), KV-page
export/adopt bitwise parity across pools (bf16 and the int8/fp8
quantized modes, scale pools included), cold-path re-prefill and
warm-path page adoption on ``ContinuousDecoder`` — both token-identical
to the uninterrupted run, the warm path with ZERO re-prefilled tokens —
and the cluster-level failover drill: a 3-worker ``ServingCluster``
where one worker is killed mid-decode (journal-replay reassignment over
``/_adopt``) and one is gracefully drained (exported page blobs ride
the same hop), with ``sessions_lost == 0``.
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                 init_transformer)
from mmlspark_tpu.serving.continuous import ContinuousDecoder
from mmlspark_tpu.serving.journal import ServingJournal
from mmlspark_tpu.serving.kv_pool import PagedKVPool

CFG = TransformerConfig(vocab=128, layers=2, d_model=64, heads=4, d_ff=128,
                        max_len=64, causal=True, norm="rmsnorm",
                        position="rope", dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_transformer(CFG, seed=0)


# ---------------------------------------------------------------------------
# durable session records in the journal


class TestJournalSessions:
    def test_session_round_trip(self, tmp_path):
        path = str(tmp_path / "w.journal")
        j = ServingJournal(path, fsync=False)
        j.record_session("s1", [5, 6, 7], {"max_new": 8, "temperature": 0.0,
                                           "seed": 3}, phash="abc")
        j.record_session_tokens("s1", [10])
        j.record_session_tokens("s1", [11, 12])
        j.record_session("s2", [1], {"max_new": 4})
        j.record_session_end("s2")
        j.close()
        got = ServingJournal.scan_sessions(path)
        # s2 completed (sess_end) so only s1 is live
        assert set(got) == {"s1"}
        assert got["s1"]["prompt"] == [5, 6, 7]
        assert got["s1"]["params"]["max_new"] == 8
        assert got["s1"]["phash"] == "abc"
        assert got["s1"]["emitted"] == [10, 11, 12]

    def test_torn_tail_keeps_prefix(self, tmp_path):
        """A crash mid-append leaves a half-written last line; every record
        before it must still scan."""
        path = str(tmp_path / "w.journal")
        j = ServingJournal(path, fsync=False)
        j.record_session("s1", [2], {"max_new": 6})
        j.record_session_tokens("s1", [20, 21])
        j.close()
        with open(path, "a") as fh:
            fh.write('{"t": "tail", "sid": "s1", "toks": [99')  # torn
        got = ServingJournal.scan_sessions(path)
        assert got["s1"]["emitted"] == [20, 21]
        # reopening repairs the tear so later appends stay parseable
        j2 = ServingJournal(path, fsync=False)
        j2.record_session_tokens("s1", [22])
        j2.close()
        assert ServingJournal.scan_sessions(path)["s1"]["emitted"] == \
            [20, 21, 22]

    def test_compaction_merges_tails(self, tmp_path):
        path = str(tmp_path / "w.journal")
        j = ServingJournal(path, fsync=False)
        j.record_session("s1", [3], {"max_new": 600})
        for k in range(400):
            j.record_session_tokens("s1", [k])
        assert j.maybe_compact(epoch=0, min_lines=64)
        # one sess + one merged tail, nothing lost
        with open(path) as fh:
            recs = [json.loads(line) for line in fh if line.strip()]
        kinds = [r["t"] for r in recs if r["t"] in ("sess", "tail")]
        assert kinds == ["sess", "tail"]
        j.close()
        assert ServingJournal.scan_sessions(path)["s1"]["emitted"] == \
            list(range(400))

    def test_replay_sessions_counts_metric(self, tmp_path):
        path = str(tmp_path / "w.journal")
        j = ServingJournal(path, fsync=False)
        j.record_session("s1", [4], {"max_new": 2})
        j.record_session_tokens("s1", [7])
        j.close()
        j2 = ServingJournal(path, fsync=False)
        live = j2.replay_sessions()
        assert live["s1"]["emitted"] == [7]
        d = j2.digest()
        assert d["live_sessions"] == 1 and not d["closed"]
        j2.close()
        assert j2.closed


# ---------------------------------------------------------------------------
# KV-page export / adopt


class TestPageExportAdopt:
    @pytest.mark.parametrize("kv_dtype", [None, "int8", "fp8"])
    def test_blob_round_trip_is_bitwise(self, kv_dtype):
        src = PagedKVPool(CFG, num_pages=8, page_size=4, kv_dtype=kv_dtype,
                          residency=False)
        dst = PagedKVPool(CFG, num_pages=8, page_size=4, kv_dtype=kv_dtype,
                          residency=False)
        pages = src.alloc(3)
        rng = np.random.default_rng(0)
        # scribble recognizable content into the source pages (values AND
        # scale pools when quantized)
        new = []
        for c in src.buffers:
            nc = {}
            for key, buf in c.items():
                fill = rng.standard_normal(
                    (len(pages),) + buf.shape[1:]).astype(np.float32)
                nc[key] = buf.at[jnp.asarray(pages)].set(
                    jnp.asarray(fill, buf.dtype))
            new.append(nc)
        src.buffers = new
        blob = src.export_session(pages, length=10)
        assert blob["length"] == 10 and blob["n_pages"] == 3
        assert blob["kv_dtype"] == src.kv_dtype
        got = dst.adopt_session(blob)
        assert len(got) == 3
        for sc, dc in zip(src.buffers, dst.buffers):
            for key in sc:
                a = np.asarray(sc[key][jnp.asarray(pages)])
                b = np.asarray(dc[key][jnp.asarray(got)])
                assert a.tobytes() == b.tobytes(), key
        assert src.stats["sessions_exported"] == 1
        assert dst.stats["sessions_adopted"] == 1

    def test_adopt_rejects_layout_mismatch(self):
        src = PagedKVPool(CFG, num_pages=4, page_size=4, residency=False)
        dst = PagedKVPool(CFG, num_pages=4, page_size=8, residency=False)
        blob = src.export_session(src.alloc(1), length=2)
        with pytest.raises(ValueError, match="layout mismatch"):
            dst.adopt_session(blob)

    def test_adopt_quant_mode_must_agree(self):
        src = PagedKVPool(CFG, num_pages=4, page_size=4, kv_dtype="int8",
                          residency=False)
        dst = PagedKVPool(CFG, num_pages=4, page_size=4, residency=False)
        blob = src.export_session(src.alloc(2), length=5)
        with pytest.raises(ValueError, match="layout mismatch"):
            dst.adopt_session(blob)


# ---------------------------------------------------------------------------
# decoder-level failover: cold re-prefill and warm page adoption


def _finish(eng, req, max_steps=400):
    for _ in range(max_steps):
        if req.done:
            break
        eng.step()
    assert req.done
    return eng.session_result(req)


class TestDecoderFailover:
    def _baseline(self, params, prompt, max_new):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=64)
        return _finish(eng, eng.submit(prompt, max_new))

    def test_cold_restore_matches_uninterrupted(self, params, tmp_path):
        """Kill mid-decode: the survivor re-prefills from the journal alone
        and the full session is token-identical to the uninterrupted
        run (greedy teacher-forcing)."""
        prompt = np.arange(5, 12, dtype=np.int32)
        want = self._baseline(params, prompt, 12)
        jpath = str(tmp_path / "a.journal")
        ja = ServingJournal(jpath, fsync=False)
        ea = ContinuousDecoder(params, CFG, max_slots=2, max_len=64,
                               journal=ja)
        ra = ea.submit(prompt, 12, session_id="sess-X")
        for _ in range(5):
            ea.step()
        assert ra.tokens and not ra.done   # genuinely mid-decode
        ja.close()                         # SIGKILL: journal is all that's left
        sessions = ServingJournal.scan_sessions(jpath)
        sess = dict(sessions["sess-X"], id="sess-X")
        assert sess["emitted"] == ra.tokens[:len(sess["emitted"])]
        eb = ContinuousDecoder(params, CFG, max_slots=2, max_len=64)
        rb = eb.restore_session(sess)
        assert rb.pre_emitted == sess["emitted"]
        assert _finish(eb, rb) == want

    def test_warm_adopt_zero_reprefill(self, params, tmp_path):
        """Graceful drain: exported pages adopt into the survivor's pool —
        token-identical AND zero prefills on the adopter."""
        prompt = np.arange(3, 10, dtype=np.int32)
        want = self._baseline(params, prompt, 10)
        ea = ContinuousDecoder(params, CFG, max_slots=2, max_len=64)
        ra = ea.submit(prompt, 10)
        for _ in range(4):
            ea.step()
        assert ra.tokens and not ra.done
        ckpt = ea.checkpoint_session(ra)
        assert ckpt["kv"] is not None
        assert ckpt["session"]["emitted"] == ra.tokens
        eb = ContinuousDecoder(params, CFG, max_slots=2, max_len=64)
        rb = eb.restore_session(ckpt["session"], kv_blob=ckpt["kv"])
        assert _finish(eb, rb) == want
        assert eb.stats["prefills"] == 0   # warm: no re-prefilled tokens

    def test_double_failover_round_trips(self, params):
        """checkpoint(restore(checkpoint(x))) stays canonical: a second
        hop neither re-forces the prompt nor loses emitted tokens."""
        prompt = np.arange(2, 8, dtype=np.int32)
        want = self._baseline(params, prompt, 12)
        ea = ContinuousDecoder(params, CFG, max_slots=2, max_len=64)
        ra = ea.submit(prompt, 12)
        for _ in range(4):
            ea.step()
        c1 = ea.checkpoint_session(ra)
        eb = ContinuousDecoder(params, CFG, max_slots=2, max_len=64)
        rb = eb.restore_session(c1["session"], kv_blob=c1["kv"])
        for _ in range(3):
            eb.step()
        c2 = eb.checkpoint_session(rb)
        # canonical: ORIGINAL prompt and budget, merged emitted tail
        assert c2["session"]["prompt"] == [int(t) for t in prompt]
        assert c2["session"]["params"]["max_new"] == 12
        ec = ContinuousDecoder(params, CFG, max_slots=2, max_len=64)
        rc = ec.restore_session(c2["session"], kv_blob=c2["kv"])
        assert _finish(ec, rc) == want

    def test_spent_session_restores_completed(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=64)
        req = eng.restore_session({"id": "done", "prompt": [1, 2],
                                   "params": {"max_new": 3},
                                   "emitted": [4, 5, 6]})
        assert req.done and eng.session_result(req) == [4, 5, 6]


# ---------------------------------------------------------------------------
# cluster-level orchestration: kill + drain over /_adopt


class TestClusterFailover:
    def test_kill_reassigns_journaled_sessions(self, tmp_path):
        from mmlspark_tpu.serving.distributed import ServingCluster
        cluster = ServingCluster(3, reply_timeout=5.0,
                                 journal_dir=str(tmp_path))
        try:
            w1 = cluster.worker("worker-1")
            w1.server._journal.record_session(
                "sess-A", [1, 2, 3], {"max_new": 8, "temperature": 0.0,
                                      "seed": 0})
            w1.server._journal.record_session_tokens("sess-A", [10, 11])
            out = cluster.reassign_sessions("worker-1")
            assert out and out.get("adopted") == 1
            adopter = cluster.worker(out["worker"])
            assert adopter.worker_id != "worker-1"
            entry = adopter.adopted_sessions[0]
            assert entry["session"]["id"] == "sess-A"
            assert entry["session"]["emitted"] == [10, 11]
            assert entry["kv"] is None     # kill path is cold
            # write-ahead on the adopter: a second failure replays from its
            # own journal
            got = adopter.server._journal.replay_sessions()
            assert got["sess-A"]["emitted"] == [10, 11]
        finally:
            cluster.close()

    def test_restart_rehydrates_sessions_from_journal(self, tmp_path):
        from mmlspark_tpu.serving.distributed import ServingCluster
        cluster = ServingCluster(2, reply_timeout=5.0,
                                 journal_dir=str(tmp_path))
        try:
            w1 = cluster.worker("worker-1")
            w1.server._journal.record_session(
                "sess-R", [7], {"max_new": 5, "temperature": 0.0})
            w1.server._journal.record_session_tokens("sess-R", [70])
            cluster.restart_worker("worker-1")
            # the replacement reopened the same journal and rehydrated the
            # live session for its engine to restore cold
            w1b = cluster.worker("worker-1")
            assert w1b.server.replayed_sessions["sess-R"]["emitted"] == [70]
        finally:
            cluster.close()

    def test_drain_ships_warm_blobs(self, tmp_path):
        from mmlspark_tpu.serving.distributed import ServingCluster
        cluster = ServingCluster(2, reply_timeout=5.0,
                                 journal_dir=str(tmp_path))
        try:
            w0 = cluster.worker("worker-0")
            blob = {"v": 1, "n_pages": 1, "length": 4, "data": []}
            w0.session_exporter = lambda: [{
                "session": {"id": "sess-W", "prompt": [9],
                            "params": {"max_new": 6}, "emitted": [3]},
                "kv": blob}]
            out = cluster.drain_worker("worker-0")
            assert out.get("adopted") == 1 and out.get("mode") == "warm"
            w1 = cluster.worker("worker-1")
            assert w1.adopted_sessions[0]["kv"] == blob
            # the drained worker is gone from the cluster AND the routing
            ids = [w.worker_id for w in cluster.workers]
            assert "worker-0" not in ids
            assert "worker-0" not in cluster.driver.routing_table()
        finally:
            cluster.close()

    def test_liveness_sweeper_evicts_dead_worker(self, tmp_path):
        import time
        from mmlspark_tpu.serving.distributed import ServingCluster
        cluster = ServingCluster(2, reply_timeout=5.0,
                                 liveness_interval=0.15,
                                 heartbeat_interval=0.05,
                                 journal_dir=str(tmp_path))
        try:
            assert "worker-1" in cluster.driver.routing_table()
            # stop worker-1's heartbeats without deregistering — a SIGKILL
            # as the driver sees it
            w1 = cluster.worker("worker-1")
            w1._hb_stop.set()
            w1._hb_thread.join(timeout=2.0)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if "worker-1" not in cluster.driver.routing_table():
                    break
                time.sleep(0.05)
            assert "worker-1" not in cluster.driver.routing_table()
            assert "worker-0" in cluster.driver.routing_table()
        finally:
            cluster.close()

    def test_session_drill_survives_worker_restart(self, tmp_path):
        """The decode-kill drill in miniature: live journal-backed decode
        sessions, one owning worker replaced mid-stream, every session
        finishes with the exact deterministic token stream."""
        import time
        from mmlspark_tpu.loadgen import SessionDrill
        from mmlspark_tpu.serving.distributed import ServingCluster
        cluster = ServingCluster(3, reply_timeout=5.0)
        try:
            drill = SessionDrill(cluster, n_sessions=4,
                                 tokens_per_session=30, tick_s=0.02,
                                 journal_dir=str(tmp_path)).start()
            time.sleep(0.2)
            cluster.restart_worker("worker-1")
            card = drill.finish(timeout=15.0)
            assert card["lost"] == 0
            assert card["recovered"] >= 1
            assert card["recovery_p99_ms"] is not None
        finally:
            cluster.close()
