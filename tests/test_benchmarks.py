"""Model-quality regression benchmarks.

Parity surface: the reference's ``Benchmarks`` trait
(``core/src/test/.../core/test/benchmarks/Benchmarks.scala:15-85``) — metric
values are pinned in a committed CSV with per-metric tolerance
(cf. ``benchmarks_VerifyLightGBMClassifier.csv``,
``benchmarks_VerifyTrainClassifier.csv``); a quality regression fails CI.
Datasets are synthetic fixed-seed (the repo vendors no data files).
"""

import csv
import os

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame

CSV = os.path.join(os.path.dirname(__file__), "benchmarks",
                   "benchmarks_quality.csv")


def _vec(X):
    o = np.empty(len(X), dtype=object)
    for i, r in enumerate(X):
        o[i] = r
    return o


def _make(seed, n=500, d=6, kind="binary"):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, d))
    if kind == "binary":
        y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
             + 0.3 * rng.normal(size=n) > 0).astype(float)
    else:
        y = X[:, 0] * 2 + X[:, 1] ** 2 + 0.1 * rng.normal(size=n)
    return DataFrame({"features": _vec(X), "label": y}), X, y


def _expected():
    with open(CSV) as f:
        return {r["name"]: (r["metric"], float(r["value"]),
                            float(r["tolerance"]))
                for r in csv.DictReader(f)}


def _measure(name):
    from mmlspark_tpu.models.gbdt.estimators import (LightGBMClassifier,
                                                     LightGBMRegressor)
    from mmlspark_tpu.models.linear import LogisticRegression
    from mmlspark_tpu.train.metrics import ComputeModelStatistics
    from mmlspark_tpu.train.train import TrainClassifier

    kind, seed = name.rsplit("synth", 1)
    seed = int(seed)
    if name.startswith("LightGBMClassifier"):
        df, _, _ = _make(seed)
        m = LightGBMClassifier(num_iterations=40, num_leaves=15,
                               learning_rate=0.2, seed=0).fit(df)
        s = ComputeModelStatistics(label_col="label").transform(m.transform(df))
        return float(s["AUC"][0])
    if name.startswith("LightGBMRegressor"):
        df, _, _ = _make(seed, kind="reg")
        m = LightGBMRegressor(num_iterations=60, num_leaves=15,
                              learning_rate=0.2, seed=0).fit(df)
        s = ComputeModelStatistics(
            label_col="label",
            evaluation_metric="regression").transform(m.transform(df))
        return float(s["R^2"][0])
    if name.startswith("TrainClassifier_LR"):
        _, X, y = _make(seed)
        df = DataFrame({"f0": X[:, 0], "f1": X[:, 1], "f2": X[:, 2],
                        "label": y})
        m = TrainClassifier(model=LogisticRegression(max_iter=200)).fit(df)
        s = ComputeModelStatistics(label_col="label").transform(m.transform(df))
        return float(s["AUC"][0])
    raise ValueError(name)


@pytest.mark.parametrize("name", sorted(_expected()))
def test_quality_regression(name):
    metric, value, tol = _expected()[name]
    got = _measure(name)
    assert abs(got - value) <= tol, (
        f"{name}: {metric} regressed — expected {value}±{tol}, got {got:.4f}")
