"""Monotone constraints (LightGBM ``monotone_constraints``).

Per-node value bounds propagate down the static depth-wise tree
(``trees.build_tree``): violating split candidates are masked in the gain
search, children tighten around the chosen split's mid value, and leaf
values clamp into their node's interval — so every tree (and any
positively-weighted sum of trees, i.e. the boosted model under every
boosting mode) is monotone in the constrained features.

The empirical check: sweep a constrained feature over a grid with all
other features held fixed; predictions must be non-decreasing (+1) /
non-increasing (-1) for every background row.
"""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.models.gbdt import LightGBMRegressor, train


def make_data(n=800, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, 4))
    # y increases with x0, decreases with x1 — but with enough noise that
    # an unconstrained fit wiggles locally
    y = (1.5 * X[:, 0] - 2.0 * X[:, 1] + 0.8 * np.sin(4 * X[:, 2])
         + rng.normal(0, 0.4, n))
    return X, y


def sweep(booster, feature, lo=-2.5, hi=2.5, n_bg=12, n_grid=40, seed=1):
    rng = np.random.default_rng(seed)
    bg = rng.normal(0, 1, (n_bg, 4))
    grid = np.linspace(lo, hi, n_grid)
    deltas = []
    for row in bg:
        pts = np.tile(row, (n_grid, 1))
        pts[:, feature] = grid
        pred = booster.predict(pts.astype(np.float32), raw_score=True)
        deltas.append(np.diff(pred))
    return np.concatenate(deltas)


PARAMS = {"objective": "regression", "num_iterations": 40,
          "num_leaves": 15, "min_data_in_leaf": 5, "learning_rate": 0.15}


class TestMonotone:
    def test_unconstrained_wiggles(self):
        X, y = make_data()
        b = train(dict(PARAMS), X, y)
        d0 = sweep(b, 0)
        assert (d0 < -1e-9).any()      # the fit is locally non-monotone

    def test_increasing_and_decreasing(self):
        X, y = make_data()
        b = train(dict(PARAMS, monotone_constraints=[1, -1, 0, 0]), X, y)
        assert (sweep(b, 0) >= -1e-6).all()     # non-decreasing in x0
        assert (sweep(b, 1) <= 1e-6).all()      # non-increasing in x1
        # unconstrained feature keeps its wiggle room
        assert (sweep(b, 2) < -1e-9).any()

    def test_quality_preserved(self):
        X, y = make_data()
        b_free = train(dict(PARAMS), X, y)
        b_mono = train(dict(PARAMS, monotone_constraints=[1, -1, 0, 0]),
                       X, y)
        r2 = lambda p: 1 - np.var(y - p) / np.var(y)      # noqa: E731
        assert r2(b_mono.predict(X)) > 0.9 * r2(b_free.predict(X))

    @pytest.mark.parametrize("boosting", ["goss", "dart"])
    def test_monotone_under_boosting_modes(self, boosting):
        X, y = make_data(seed=2)
        b = train(dict(PARAMS, boosting=boosting, seed=3,
                       monotone_constraints=[1, 0, 0, 0]), X, y)
        assert (sweep(b, 0) >= -1e-6).all()

    def test_monotone_with_sparse_and_bundling(self):
        import scipy.sparse as sp
        rng = np.random.default_rng(4)
        dense = np.where(rng.random((600, 4)) < 0.4,
                         rng.normal(0, 1, (600, 4)), 0.0)
        y = 2 * dense[:, 0] - dense[:, 1] + rng.normal(0, 0.2, 600)
        b = train(dict(PARAMS, monotone_constraints=[1, 0, 0, 0]),
                  sp.csr_matrix(dense), y)
        assert (sweep(b, 0) >= -1e-6).all()

    def test_data_parallel_monotone(self):
        import jax
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
        X, y = make_data(seed=5)
        b = train(dict(PARAMS, num_iterations=15,
                       monotone_constraints=[1, 0, 0, 0],
                       tree_learner="data_parallel"), X, y, mesh=mesh)
        assert (sweep(b, 0) >= -1e-6).all()

    def test_validation_errors(self):
        X, y = make_data(n=100)
        with pytest.raises(ValueError, match="one entry per feature"):
            train(dict(PARAMS, num_iterations=2,
                       monotone_constraints=[1, 0]), X, y)
        with pytest.raises(ValueError, match="-1, 0, or"):
            train(dict(PARAMS, num_iterations=2,
                       monotone_constraints=[2, 0, 0, 0]), X, y)
        with pytest.raises(ValueError, match="voting"):
            import jax
            from jax.sharding import Mesh
            mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
            train(dict(PARAMS, num_iterations=2,
                       monotone_constraints=[1, 0, 0, 0],
                       tree_learner="voting_parallel", top_k=1), X, y,
                  mesh=mesh)

    def test_monotone_with_extra_trees(self):
        # regression: the extra_trees random-threshold draw used to shadow
        # the monotone upper-bound vector (`hi`), breaking the combination
        X, y = make_data(seed=9)
        b = train(dict(PARAMS, extra_trees=True, seed=11,
                       monotone_constraints=[1, -1, 0, 0]), X, y)
        assert (sweep(b, 0) >= -1e-6).all()
        assert (sweep(b, 1) <= 1e-6).all()

    def test_empty_list_means_no_constraints(self):
        X, y = make_data(n=100, seed=7)
        b = train(dict(PARAMS, num_iterations=2,
                       monotone_constraints=[]), X, y)
        assert b.num_trees == 2

    def test_categorical_monotone_rejected(self):
        rng = np.random.default_rng(8)
        X = np.column_stack([rng.integers(0, 5, 200).astype(np.float64),
                             rng.normal(0, 1, 200)])
        y = rng.normal(0, 1, 200)
        with pytest.raises(ValueError, match="categorical"):
            train(dict(PARAMS, num_iterations=2,
                       categorical_feature=[0],
                       monotone_constraints=[1, 0]), X, y)

    def test_estimator_surface(self):
        X, y = make_data(n=300, seed=6)
        col = np.empty(len(X), dtype=object)
        col[:] = list(X.astype(np.float32))
        df = DataFrame({"features": col, "label": y})
        m = LightGBMRegressor(num_iterations=20, num_leaves=15,
                              min_data_in_leaf=5,
                              monotone_constraints=[1, -1, 0, 0]).fit(df)
        assert (sweep(m.booster, 0) >= -1e-6).all()
