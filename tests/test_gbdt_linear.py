"""Linear trees (LightGBM ``linear_tree``): a hessian-weighted ridge model
per leaf over the leaf's path features.

TPU-first formulation (``trees.fit_linear_leaves``): every leaf's normal
equations accumulate via one ``segment_sum`` of (D+1)x(D+1) outer products
and solve in a single batched ``jnp.linalg.solve`` — no per-leaf control
flow, and the data-parallel learner psums M/v so coefficients stay
bitwise-identical across shards. Parity anchor: LightGBM's linear_tree
param (the reference surfaces LightGBM params wholesale through
``params/LightGBMParams.scala``).
"""

import numpy as np
import pytest

from mmlspark_tpu.models.gbdt.booster import Booster
from mmlspark_tpu.models.gbdt.train import train
from mmlspark_tpu.models.gbdt.trees import path_features

BASE = {"objective": "regression", "num_iterations": 25, "num_leaves": 7,
        "learning_rate": 0.2, "seed": 3}


def _piecewise_linear(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = np.where(X[:, 0] > 0, 2.0 * X[:, 1] + 1.0, -1.5 * X[:, 2]) \
        + 0.05 * rng.normal(size=n)
    return X, y


class TestPathFeatures:
    def test_dedup_and_stubs(self):
        # depth 2: root splits f0; left child f1, right child is a stub
        feats = np.array([0, 1, -1], np.int32)
        pf = path_features(feats, 2)
        np.testing.assert_array_equal(pf[0], [0, 1])   # leaf 0: root->left
        np.testing.assert_array_equal(pf[2], [0, -1])  # leaf 2: stub level
        # duplicate feature on a path keeps the first slot only
        feats2 = np.array([0, 0, 0], np.int32)
        pf2 = path_features(feats2, 2)
        np.testing.assert_array_equal(pf2[0], [0, -1])


class TestLinearTreeTraining:
    def test_beats_constant_on_piecewise_linear(self):
        X, y = _piecewise_linear()
        const = train(BASE, X, y)
        lin = train(dict(BASE, linear_tree=True), X, y)
        assert lin.is_linear and not const.is_linear
        mc = float(np.mean((const.predict(X) - y) ** 2))
        ml = float(np.mean((lin.predict(X) - y) ** 2))
        assert ml < 0.5 * mc

    def test_deterministic(self):
        X, y = _piecewise_linear(n=600)
        a = train(dict(BASE, linear_tree=True), X, y)
        b = train(dict(BASE, linear_tree=True), X, y)
        assert np.array_equal(a.predict(X), b.predict(X))

    def test_binary_objective(self):
        X, y = _piecewise_linear(n=1200)
        yb = (y > 0).astype(np.float64)
        m = train(dict(BASE, objective="binary", linear_tree=True), X, yb)
        p = m.predict(X)
        acc = float(((p > 0.5) == yb).mean())
        assert acc > 0.95

    def test_linear_lambda_shrinks_weights(self):
        X, y = _piecewise_linear(n=800)
        small = train(dict(BASE, linear_tree=True, linear_lambda=0.0), X, y)
        big = train(dict(BASE, linear_tree=True, linear_lambda=1e4), X, y)
        wn = lambda b: float(np.abs(b.linear["coefs"][..., :-1]).mean())  # noqa: E731
        assert wn(big) < 0.1 * wn(small)

    def test_nan_features_contribute_zero(self):
        X, y = _piecewise_linear(n=800)
        m = train(dict(BASE, linear_tree=True), X, y)
        Xq = X[:10].copy()
        p_clean = m.predict(Xq)
        Xq2 = Xq.copy()
        Xq2[:, 4] = np.nan        # f4 is noise: routing unchanged, term -> 0
        p_nan = m.predict(Xq2)
        assert np.isfinite(p_nan).all()
        assert np.abs(p_nan - p_clean).max() < 1.0

    def test_goss_and_rf_compose(self):
        X, y = _piecewise_linear(n=1500)
        g = train(dict(BASE, linear_tree=True, boosting="goss"), X, y)
        r = train(dict(BASE, linear_tree=True, boosting="rf",
                       bagging_fraction=0.6, bagging_freq=1), X, y)
        for m in (g, r):
            assert m.is_linear
            assert float(np.mean((m.predict(X) - y) ** 2)) < float(np.var(y))

    def test_dart_composes(self):
        X, y = _piecewise_linear(n=1000)
        m = train(dict(BASE, linear_tree=True, boosting="dart",
                       drop_rate=0.3, skip_drop=0.0), X, y)
        assert m.is_linear
        assert float(np.mean((m.predict(X) - y) ** 2)) < float(np.var(y))

    def test_early_stopping_truncates_linear_arrays(self):
        X, y = _piecewise_linear()
        m = train(dict(BASE, num_iterations=60, linear_tree=True,
                       early_stopping_round=5),
                  X[:1500], y[:1500], valid_sets=[(X[1500:], y[1500:])])
        assert m.best_iteration > 0
        assert m.linear["coefs"].shape[0] == m.num_trees

    def test_warm_start_family_must_match(self):
        X, y = _piecewise_linear(n=400)
        lin = train(dict(BASE, num_iterations=5, linear_tree=True), X, y)
        with pytest.raises(ValueError, match="leaf model family"):
            train(dict(BASE, num_iterations=5), X, y, init_model=lin)
        cont = train(dict(BASE, num_iterations=5, linear_tree=True), X, y,
                     init_model=lin)
        assert cont.num_trees == 10 and cont.is_linear


class TestLinearBooster:
    def test_roundtrip_string(self):
        X, y = _piecewise_linear(n=600)
        m = train(dict(BASE, linear_tree=True), X, y)
        m2 = Booster.from_string(m.to_string())
        assert m2.is_linear
        np.testing.assert_array_equal(m.predict(X), m2.predict(X))

    def test_num_iteration_cap(self):
        X, y = _piecewise_linear(n=600)
        m = train(dict(BASE, linear_tree=True), X, y)
        p5 = m.predict(X, num_iteration=5)
        t5 = m.truncated(5)
        np.testing.assert_array_equal(p5, t5.predict(X))

    def test_unsupported_paths_raise(self):
        X, y = _piecewise_linear(n=400)
        m = train(dict(BASE, num_iterations=3, linear_tree=True), X, y)
        with pytest.raises(NotImplementedError):
            m.shap_values(X[:5])
        with pytest.raises(NotImplementedError):
            m.refit(X, y)
        from mmlspark_tpu.models.gbdt.onnx_export import booster_to_onnx
        with pytest.raises(ValueError, match="linear"):
            booster_to_onnx(m)

    def test_validation_rejections(self):
        X, y = _piecewise_linear(n=300)
        with pytest.raises(ValueError, match="dense"):
            import scipy.sparse as sp
            train(dict(BASE, linear_tree=True), sp.csr_matrix(X), y)
        with pytest.raises(ValueError, match="numerical"):
            train(dict(BASE, linear_tree=True, categorical_feature=[0]),
                  X, y)
        # leaf-level regularizers with no linear counterpart are rejected,
        # not silently ignored
        with pytest.raises(ValueError, match="monotone"):
            train(dict(BASE, linear_tree=True,
                       monotone_constraints=[1, 0, 0, 0, 0]), X, y)
        with pytest.raises(ValueError, match="lambda_l1"):
            train(dict(BASE, linear_tree=True, lambda_l1=0.5), X, y)
        with pytest.raises(ValueError, match="path_smooth"):
            train(dict(BASE, linear_tree=True, path_smooth=2.0), X, y)


class TestLinearMeshParity:
    def test_data_parallel_matches_serial(self):
        import jax
        from jax.sharding import Mesh

        X, y = _piecewise_linear(n=512)
        params = dict(BASE, num_iterations=8, linear_tree=True)
        serial = train(params, X, y)
        devs = np.array(jax.devices()[:4])
        mesh = Mesh(devs, ("data",))
        dp = train(dict(params, tree_learner="data_parallel"), X, y,
                   mesh=mesh)
        np.testing.assert_allclose(serial.predict(X), dp.predict(X),
                                   rtol=2e-3, atol=2e-4)


def _piecewise_linear_multi(n=1500, seed=7):
    """3-class argmax of linear score functions: linear leaves can model
    the within-region slopes constant leaves must staircase."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5)).astype(np.float32)
    z = np.stack([2.0 * X[:, 1] + 1.0,
                  -1.5 * X[:, 2],
                  X[:, 3] - X[:, 1]], axis=1)
    y = np.argmax(z + 0.05 * rng.normal(size=z.shape), axis=1)
    return X, y


class TestMulticlassLinear:
    """linear_tree + multiclass (LightGBM supports the combination): one
    structure per class per iteration, per-class leaf ridge models, tree
    t routed to class t % K at prediction."""

    PARAMS = dict(BASE, objective="multiclass", num_class=3,
                  num_iterations=20, linear_tree=True)

    def test_trains_and_predicts(self):
        X, y = _piecewise_linear_multi()
        b = train(self.PARAMS, X, y)
        p = b.predict(X)
        assert p.shape == (len(X), 3)
        np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-4)
        acc = (np.argmax(p, axis=1) == y).mean()
        assert acc > 0.85, acc
        assert b.is_linear and b.num_class == 3

    def test_beats_constant_leaves_on_linear_signal(self):
        X, y = _piecewise_linear_multi()
        lin = train(self.PARAMS, X, y)
        const = train(dict(self.PARAMS, linear_tree=False), X, y)
        acc_lin = (np.argmax(lin.predict(X), 1) == y).mean()
        acc_const = (np.argmax(const.predict(X), 1) == y).mean()
        assert acc_lin >= acc_const - 0.02, (acc_lin, acc_const)

    def test_save_load_roundtrip(self):
        X, y = _piecewise_linear_multi(n=400)
        b = train(dict(self.PARAMS, num_iterations=6), X, y)
        r = Booster.from_string(b.to_string())
        np.testing.assert_allclose(r.predict(X), b.predict(X), rtol=1e-6)
        assert r.is_linear and r.num_class == 3

    def test_num_iteration_cap_counts_iterations(self):
        X, y = _piecewise_linear_multi(n=400)
        b = train(dict(self.PARAMS, num_iterations=8), X, y)
        # 8 iterations x 3 classes = 24 trees; cap at 2 iterations = 6 trees
        assert b.num_trees == 24
        p2 = b.predict(X, num_iteration=2)
        assert p2.shape == (len(X), 3)
        assert np.abs(p2 - b.predict(X)).max() > 0

    def test_early_stopping_valid_path(self):
        X, y = _piecewise_linear_multi(n=900)
        b = train(dict(self.PARAMS, num_iterations=40,
                       early_stopping_round=5),
                  X[:600], y[:600], valid_sets=[(X[600:], y[600:])])
        p = b.predict(X[600:])
        acc = (np.argmax(p, 1) == y[600:]).mean()
        assert acc > 0.8, acc


def test_estimator_multiclass_linear_pipeline():
    # the user-facing path: LightGBMClassifier auto-detects 3 classes and
    # composes linear_tree through fit/transform/save/load
    import tempfile
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.models.gbdt import LightGBMClassifier

    X, y = _piecewise_linear_multi(n=600)
    col = np.empty(len(X), object)
    for i, r in enumerate(X):
        col[i] = r
    df = DataFrame({"features": col, "label": y.astype(np.float64)})
    m = LightGBMClassifier(num_iterations=10, num_leaves=7,
                           learning_rate=0.2, linear_tree=True).fit(df)
    assert m.booster.is_linear and m.booster.num_class == 3
    pred = np.asarray(m.transform(df)["prediction"])
    assert (pred == y).mean() > 0.85
    with tempfile.TemporaryDirectory() as d:
        m.save(d + "/m")
        from mmlspark_tpu.core import PipelineStage
        r = PipelineStage.load(d + "/m")
        np.testing.assert_array_equal(
            np.asarray(r.transform(df)["prediction"]), pred)
