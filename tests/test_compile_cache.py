"""AOT warm-up, persistent compilation cache, and feed/drain pipeline tests.

The contract under test: ``warm_up`` populates the jit executable cache for
every declared padding bucket so the first real batch of each bucket pays
zero compiles; the overlapped drain preserves row order (including a ragged
last batch) under prefetch; ``StageCounters`` account the pipeline stages;
``ONNXModel.set`` invalidates cached device params on any jit-visible change
(the ``compute_dtype`` regression); the serving engine runs its pre-serve
warm-up hook before draining traffic.
"""

import threading
import time

import numpy as np
import pytest

import mmlspark_tpu.onnx as O
from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.onnx_model import ONNXModel
from mmlspark_tpu.ops import compile_cache as cc
from mmlspark_tpu.ops.compile_cache import (StageCounters,
                                            enable_persistent_cache,
                                            jit_cache_size,
                                            resolve_input_specs)


def mlp_bytes(din=8, dout=3, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(0, 0.5, (din, dout)).astype(np.float32)
    b = rng.normal(0, 0.1, dout).astype(np.float32)
    nodes = [O.make_node("MatMul", ["x", "w"], ["h"]),
             O.make_node("Add", ["h", "b"], ["logits"])]
    graph = O.make_graph(
        nodes, "mlp",
        inputs=[O.make_tensor_value_info("x", np.float32, ["N", din])],
        outputs=[O.make_tensor_value_info("logits", np.float32,
                                          ["N", dout])],
        initializers={"w": w, "b": b})
    return O.make_model(graph), (w, b)


def mlp_onnx_model(n_parts=1, **kw):
    data, (w, b) = mlp_bytes()
    kw.setdefault("pin_devices", False)
    kw.setdefault("mini_batch_size", 8)
    m = ONNXModel(data, feed_dict={"x": "feats"},
                  fetch_dict={"logits": "logits"}, **kw)
    return m, (w, b)


def feats_df(n, din=8, seed=1, npartitions=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, din)).astype(np.float32)
    return DataFrame({"feats": [X[i] for i in range(n)]},
                     npartitions=npartitions), X


class TestStageCounters:
    def test_add_and_snapshot(self):
        c = StageCounters()
        c.add("h2d", 0.5, nbytes=100)
        c.add("h2d", 0.25, nbytes=50)
        c.add("compile", 1.0, count=3)
        snap = c.snapshot()
        assert snap["h2d"] == {"calls": 2, "seconds": 0.75, "bytes": 150}
        assert snap["compile"]["calls"] == 3
        assert c.total_seconds("h2d") == pytest.approx(0.75)
        assert c.total_seconds("missing") == 0.0

    def test_timer_context(self):
        c = StageCounters()
        with c.timer("pad", nbytes=7):
            time.sleep(0.01)
        snap = c.snapshot()
        assert snap["pad"]["calls"] == 1
        assert snap["pad"]["bytes"] == 7
        assert snap["pad"]["seconds"] >= 0.005

    def test_reset(self):
        c = StageCounters()
        c.add("d2h", 1.0)
        c.reset()
        assert c.snapshot() == {}

    def test_thread_safety(self):
        c = StageCounters()

        def work():
            for _ in range(500):
                c.add("x", 0.001, nbytes=1)

        ts = [threading.Thread(target=work) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        snap = c.snapshot()
        assert snap["x"]["calls"] == 4000
        assert snap["x"]["bytes"] == 4000


@pytest.fixture
def cache_config_guard():
    """Restore the persistent-cache wiring after a test mutates it."""
    import jax
    prev_dir = cc._cache_dir
    prev_cfg = jax.config.jax_compilation_cache_dir
    yield
    cc._cache_dir = prev_dir
    jax.config.update("jax_compilation_cache_dir", prev_cfg)


class TestPersistentCache:
    def test_explicit_dir(self, tmp_path, cache_config_guard):
        import jax
        d = str(tmp_path / "xla-cache")
        assert enable_persistent_cache(d) == d
        assert cc.persistent_cache_dir() == d
        assert jax.config.jax_compilation_cache_dir == d
        # idempotent re-enable
        assert enable_persistent_cache(d) == d

    def test_env_var_resolution(self, tmp_path, monkeypatch,
                                cache_config_guard):
        d = str(tmp_path / "from-env")
        monkeypatch.setenv(cc.CACHE_DIR_ENV, d)
        cc._cache_dir = None
        assert enable_persistent_cache() == d
        import os
        assert os.path.isdir(d)

    def test_no_dir_configured(self, monkeypatch, cache_config_guard):
        monkeypatch.delenv(cc.CACHE_DIR_ENV, raising=False)
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
        cc._cache_dir = None
        assert enable_persistent_cache() is None


class TestResolveInputSpecs:
    def _vi(self, name, dtype, shape):
        class VI:
            pass

        v = VI()
        v.name, v.numpy_dtype, v.shape = name, dtype, shape
        return v

    def test_plain(self):
        specs = resolve_input_specs([self._vi("x", np.float32, ["N", 8])],
                                    {"x": "feats"}, {})
        assert specs == {"x": (np.dtype(np.float32), (8,))}

    def test_unfed_inputs_skipped(self):
        specs = resolve_input_specs([self._vi("x", np.float32, ["N", 8]),
                                     self._vi("state", np.float32, ["N", 4])],
                                    {"x": "feats"}, {})
        assert list(specs) == ["x"]

    def test_transpose_inverted(self):
        # graph declares NCHW; the column feeds NHWC via transpose_dict
        specs = resolve_input_specs(
            [self._vi("img", np.float32, ["N", 3, 224, 224])],
            {"img": "image"}, {"img": [0, 3, 1, 2]})
        assert specs["img"] == (np.dtype(np.float32), (224, 224, 3))

    def test_symbolic_shape_raises(self):
        with pytest.raises(ValueError, match="input_specs"):
            resolve_input_specs([self._vi("x", np.float32, ["N", "D"])],
                                {"x": "feats"}, {})

    def test_override_wins(self):
        specs = resolve_input_specs(
            [self._vi("x", np.float32, ["N", "D"])], {"x": "feats"}, {},
            overrides={"x": (np.uint8, (5,))})
        assert specs["x"] == (np.dtype(np.uint8), (5,))

    def test_transpose_rank_mismatch(self):
        with pytest.raises(ValueError, match="permutes"):
            resolve_input_specs(
                [self._vi("img", np.float32, ["N", 3, 4])],
                {"img": "image"}, {"img": [0, 2, 3, 1]})


class TestWarmUp:
    def test_every_bucket_compiled_no_recompile_on_traffic(self):
        m, (w, b) = mlp_onnx_model(mini_batch_size=8)
        stats = m.warm_up(batch_sizes=[8, 3])
        # 3 pads to bucket 4, 8 stays 8 → two distinct compiled shapes
        assert stats["buckets"] == [4, 8]
        assert stats["compiles"] == 2
        assert stats["placements"] == 1
        jitted = m._ensure_jitted()
        size_after_warm = jit_cache_size(jitted)
        assert size_after_warm is not None and size_after_warm >= 2

        # 11 rows @ batch 8 → slices of 8 and 3: both buckets pre-warmed,
        # so real traffic must hit the cache every time
        df, X = feats_df(11)
        out = m.transform(df)
        assert jit_cache_size(jitted) == size_after_warm
        np.testing.assert_allclose(np.stack(list(out["logits"])),
                                   X @ w + b, rtol=1e-4, atol=1e-4)

    def test_default_sizes_use_mini_batch_size(self):
        m, _ = mlp_onnx_model(mini_batch_size=16)
        stats = m.warm_up()
        assert stats["buckets"] == [16]

    def test_warm_up_counts_compile_stage(self):
        m, _ = mlp_onnx_model()
        m.warm_up(batch_sizes=[8])
        snap = m.stage_counters.snapshot()
        assert snap["compile"]["calls"] >= 1
        assert snap["compile"]["seconds"] > 0

    def test_background_warm_up(self):
        m, _ = mlp_onnx_model()
        t = m.warm_up(batch_sizes=[8], background=True)
        assert isinstance(t, threading.Thread)
        t.join(timeout=60)
        assert not t.is_alive()
        assert jit_cache_size(m._ensure_jitted()) >= 1

    def test_unwarmed_bucket_counts_as_compile(self):
        m, _ = mlp_onnx_model(mini_batch_size=8)
        m.warm_up(batch_sizes=[8])
        df, _ = feats_df(3)   # bucket 4 — deliberately NOT warmed
        m.transform(df)
        snap = m.stage_counters.snapshot()
        # the cold bucket's stall is attributed to "compile", not "dispatch"
        assert snap["compile"]["calls"] >= 2  # 1 warm-up + 1 cold traffic

    def test_jax_model_warm_up(self):
        params = {"w": np.eye(4, dtype=np.float32)}

        def apply(p, feeds):
            return {"y": feeds["input"] @ p["w"]}

        m = JaxModel(apply, params, feed_dict={"input": "feats"},
                     mini_batch_size=4, pin_devices=False)
        stats = m.warm_up(input_specs={"input": (np.float32, (4,))},
                          batch_sizes=[4])
        assert stats["buckets"] == [4]
        assert stats["compiles"] == 1
        jitted = m._ensure_jitted()
        size = jit_cache_size(jitted)
        rng = np.random.default_rng(0)
        X = rng.normal(0, 1, (4, 4)).astype(np.float32)
        df = DataFrame({"feats": [X[i] for i in range(4)]})
        out = m.transform(df)
        assert jit_cache_size(jitted) == size  # no recompile on first batch
        np.testing.assert_allclose(np.stack(list(out["y"])), X,
                                   rtol=1e-5, atol=1e-5)


class TestDrainOrdering:
    @pytest.mark.parametrize("prefetch_depth", [0, 2])
    def test_row_order_and_ragged_tail(self, prefetch_depth):
        # 37 rows / batch 8 / 2 partitions → several full batches plus a
        # ragged tail per partition; values are row-indexed so any
        # reordering or tail corruption shows up as a value mismatch
        m, (w, b) = mlp_onnx_model(mini_batch_size=8,
                                   prefetch_depth=prefetch_depth)
        df, X = feats_df(37, npartitions=2)
        out = m.transform(df)
        assert len(out) == 37
        np.testing.assert_allclose(np.stack(list(out["logits"])),
                                   X @ w + b, rtol=1e-4, atol=1e-4)

    def test_single_row_partition(self):
        m, (w, b) = mlp_onnx_model(mini_batch_size=8, prefetch_depth=2)
        df, X = feats_df(1)
        out = m.transform(df)
        np.testing.assert_allclose(np.stack(list(out["logits"])),
                                   X @ w + b, rtol=1e-4, atol=1e-4)

    def test_stage_counters_populated(self):
        m, _ = mlp_onnx_model(mini_batch_size=8)
        df, _ = feats_df(20)
        m.transform(df)
        snap = m.stage_counters.snapshot()
        for stage in ["coerce", "pad", "h2d", "d2h"]:
            assert snap[stage]["calls"] >= 1, stage
        assert snap["h2d"]["bytes"] > 0
        assert snap["d2h"]["bytes"] > 0
        # every dispatch was either a hit (dispatch) or a compile
        assert (snap.get("dispatch", {}).get("calls", 0)
                + snap["compile"]["calls"]) >= 3


class TestSetInvalidation:
    def test_compute_dtype_change_invalidates_device_params(self):
        import jax.numpy as jnp
        m, _ = mlp_onnx_model()
        df, _ = feats_df(8)
        m.transform(df)
        assert m._device_params  # populated by the run
        key = next(iter(m._device_params))
        assert m._device_params[key]["w"].dtype == jnp.float32

        m.set(compute_dtype="bfloat16")
        # the regression: this cache used to survive a compute_dtype change,
        # leaving f32-cast weights serving a bf16 run
        assert m._device_params == {}
        m.transform(df)
        key = next(iter(m._device_params))
        assert m._device_params[key]["w"].dtype == jnp.bfloat16

    def test_unrelated_set_keeps_cache(self):
        m, _ = mlp_onnx_model()
        df, _ = feats_df(8)
        m.transform(df)
        cached = dict(m._device_params)
        m.set(mini_batch_size=4)
        assert m._device_params == cached


class TestServingEngineWarmUpHook:
    def test_hook_runs_before_serving(self):
        from mmlspark_tpu.serving.engine import ServingEngine
        calls = []
        eng = ServingEngine(lambda df: df, warm_up=lambda: calls.append(1))
        try:
            eng.start()
            assert calls == [1]
        finally:
            eng.stop()

    def test_hook_failure_is_not_fatal(self):
        from mmlspark_tpu.serving.engine import ServingEngine

        def boom():
            raise RuntimeError("no device")

        eng = ServingEngine(lambda df: df, warm_up=boom)
        try:
            eng.start()
            assert any(t.is_alive() for t in eng._threads)
        finally:
            eng.stop()
