"""ORT generation meta-ops (com.microsoft GreedySearch / BeamSearch).

The decoder subgraph here is a real causal single-layer GPT built from
standard ONNX ops (embeddings, fused-QKV attention with past/present
concat, causal + padding masks, tied unembedding). The oracle is the
SAME subgraph converted standalone and re-run from scratch each step
(full recompute, empty past) — for a causal decoder that equals cached
decoding, so the meta-op's padded-past machinery must reproduce it
token-for-token.
"""

import itertools

import numpy as np
import pytest

import mmlspark_tpu.onnx as O
from mmlspark_tpu.onnx.convert import convert_model

V, D, H = 8, 8, 2
HD = D // H
MAXP = 16


def _decoder_graph(seed=0):
    rng = np.random.default_rng(seed)
    init = {
        "tok_table": rng.normal(0, 0.7, (V, D)).astype(np.float32),
        "pos_table": rng.normal(0, 0.3, (MAXP, D)).astype(np.float32),
        "w_qkv": rng.normal(0, 0.5, (D, 3 * D)).astype(np.float32),
        "w_out": rng.normal(0, 0.5, (D, D)).astype(np.float32),
        "unembed": rng.normal(0, 0.7, (D, V)).astype(np.float32),
        "scale": np.array(1.0 / np.sqrt(HD), np.float32),
        "one_f": np.array([1.0], np.float32),
        "big_neg": np.array(-1e9, np.float32),
        "i1": np.array(1, np.int64),
        "perm_shape": np.array([0, 0, H, HD], np.int64),
        "merge_shape": np.array([0, 0, D], np.int64),
    }
    n = [
        # h = tok_emb + pos_emb
        O.make_node("Gather", ["tok_table", "input_ids"], ["te"]),
        O.make_node("Gather", ["pos_table", "position_ids"], ["pe"]),
        O.make_node("Add", ["te", "pe"], ["h"]),
        # fused qkv -> (B, S, H, hd) heads
        O.make_node("MatMul", ["h", "w_qkv"], ["qkv"]),
        O.make_node("Split", ["qkv"], ["q0", "k0", "v0"], axis=-1,
                    num_outputs=3),
        O.make_node("Reshape", ["q0", "perm_shape"], ["q1"]),
        O.make_node("Reshape", ["k0", "perm_shape"], ["k1"]),
        O.make_node("Reshape", ["v0", "perm_shape"], ["v1"]),
        O.make_node("Transpose", ["q1"], ["q"], perm=[0, 2, 1, 3]),
        O.make_node("Transpose", ["k1"], ["kn"], perm=[0, 2, 1, 3]),
        O.make_node("Transpose", ["v1"], ["vn"], perm=[0, 2, 1, 3]),
        # past (2, B, H, P, hd) -> concat on the sequence axis
        O.make_node("Gather", ["past_0", "i0_idx"], ["kp"], axis=0),
        O.make_node("Gather", ["past_0", "i1_idx"], ["vp"], axis=0),
        O.make_node("Concat", ["kp", "kn"], ["K"], axis=2),
        O.make_node("Concat", ["vp", "vn"], ["Vv"], axis=2),
        # scores + causal & padding masks
        O.make_node("Transpose", ["K"], ["Kt"], perm=[0, 1, 3, 2]),
        O.make_node("MatMul", ["q", "Kt"], ["s0"]),
        O.make_node("Mul", ["s0", "scale"], ["s1"]),
        O.make_node("Shape", ["input_ids"], ["ids_shape"]),
        O.make_node("Gather", ["ids_shape", "i1"], ["S_"], axis=0),
        O.make_node("Shape", ["attention_mask"], ["m_shape"]),
        O.make_node("Gather", ["m_shape", "i1"], ["T_"], axis=0),
        O.make_node("Sub", ["T_", "S_"], ["Ppast"]),
        O.make_node("Unsqueeze", ["S_"], ["S_u"], axes=[0]),
        O.make_node("Unsqueeze", ["T_"], ["T_u"], axes=[0]),
        O.make_node("Concat", ["S_u", "T_u"], ["st"], axis=0),
        O.make_node("Expand", ["one_f", "st"], ["ones_st"]),
        O.make_node("Trilu", ["ones_st", "Ppast"], ["tril"], upper=0),
        O.make_node("Sub", ["tril", "one_f"], ["tril0"]),
        O.make_node("Mul", ["tril0", "big_neg"], ["causal_neg"]),  # (S,T)
        O.make_node("Sub", ["one_f", "attention_mask"], ["padm"]),
        O.make_node("Mul", ["padm", "big_neg"], ["pad_neg"]),      # (B,T)
        O.make_node("Unsqueeze", ["pad_neg"], ["pad_neg4"],
                    axes=[1, 2]),                                  # B,1,1,T
        O.make_node("Add", ["s1", "causal_neg"], ["s2"]),
        O.make_node("Add", ["s2", "pad_neg4"], ["s3"]),
        O.make_node("Softmax", ["s3"], ["p"], axis=-1),
        O.make_node("MatMul", ["p", "Vv"], ["ctx"]),
        O.make_node("Transpose", ["ctx"], ["ctx1"], perm=[0, 2, 1, 3]),
        O.make_node("Reshape", ["ctx1", "merge_shape"], ["ctx2"]),
        O.make_node("MatMul", ["ctx2", "w_out"], ["ho"]),
        O.make_node("Add", ["h", "ho"], ["hf"]),
        O.make_node("MatMul", ["hf", "unembed"], ["logits"]),
        # present (2, B, H, T, hd)
        O.make_node("Unsqueeze", ["K"], ["K5"], axes=[0]),
        O.make_node("Unsqueeze", ["Vv"], ["V5"], axes=[0]),
        O.make_node("Concat", ["K5", "V5"], ["present_0"], axis=0),
    ]
    init["i0_idx"] = np.array(0, np.int64)
    init["i1_idx"] = np.array(1, np.int64)
    return O.make_graph(
        n, "gpt_step",
        inputs=[O.make_tensor_value_info("input_ids", np.int32,
                                         ["B", "S"]),
                O.make_tensor_value_info("position_ids", np.int32,
                                         ["B", "S"]),
                O.make_tensor_value_info("attention_mask", np.float32,
                                         ["B", "T"]),
                O.make_tensor_value_info("past_0", np.float32,
                                         [2, "B", H, "P", HD])],
        outputs=[O.make_tensor_value_info("logits", np.float32,
                                          ["B", "S", V]),
                 O.make_tensor_value_info("present_0", np.float32,
                                          [2, "B", H, "T", HD])],
        initializers=init)


@pytest.fixture(scope="module")
def oracle():
    """Standalone converted decoder + full-recompute greedy/logprob."""
    cm = convert_model(O.make_model(_decoder_graph()))

    def logits_for(ids_2d):
        ids = np.asarray(ids_2d, np.int32)
        B, S = ids.shape
        feeds = {"input_ids": ids,
                 "position_ids": np.tile(np.arange(S, dtype=np.int32),
                                         (B, 1)),
                 "attention_mask": np.ones((B, S), np.float32),
                 "past_0": np.zeros((2, B, H, 0, HD), np.float32)}
        return np.asarray(cm(cm.params, feeds)["logits"])

    def greedy(prompt_row, max_length):
        ids = list(map(int, prompt_row))
        while len(ids) < max_length:
            lg = logits_for([ids])[0, -1]
            ids.append(int(lg.argmax()))
        return ids

    def seq_logprob(prompt_row, gen):
        from scipy.special import logsumexp
        ids = list(map(int, prompt_row))
        lp = 0.0
        for t in gen:
            row = logits_for([ids])[0, -1]
            lp += row[t] - logsumexp(row)
            ids.append(int(t))
        return lp

    return logits_for, greedy, seq_logprob


def _greedy_model(**extra_inputs):
    ins = [O.make_tensor_value_info("input_ids", np.int32, ["B", "P"])]
    names = ["input_ids", "max_length"] + list(extra_inputs)
    node = O.make_node("GreedySearch", names, ["sequences"],
                       domain="com.microsoft", decoder=_decoder_graph(),
                       eos_token_id=V - 1, pad_token_id=0, model_type=0)
    g = O.make_graph(
        [node], "gen",
        inputs=ins,
        outputs=[O.make_tensor_value_info("sequences", np.int32,
                                          ["B", "L"])],
        initializers={"max_length": np.array(9, np.int64), **extra_inputs})
    return convert_model(O.make_model(g))


class TestGreedySearch:
    def test_matches_full_recompute_oracle(self, oracle):
        _, greedy, _ = oracle
        cm = _greedy_model()
        prompts = np.array([[1, 2, 3], [4, 0, 6]], np.int32)
        out = np.asarray(cm(cm.params, {"input_ids": prompts})["sequences"])
        assert out.shape == (2, 9)
        for r in range(2):
            want = greedy(prompts[r], 9)
            got = list(out[r])
            # compare up to the first eos; after it the op pads
            if V - 1 in want[3:]:
                stop = want.index(V - 1, 3)
                assert got[:stop + 1] == want[:stop + 1]
                assert all(t == 0 for t in got[stop + 1:])
            else:
                assert got == want

    def test_left_padded_batch_matches_per_row(self, oracle):
        """ORT's batching convention: shorter prompts left-pad and the
        attention_mask hides the pad K/V in BOTH prefill and decode
        steps; per-row positions continue the cumsum. Each padded row
        must generate exactly what it generates alone, unpadded."""
        _, greedy, _ = oracle
        ins = [O.make_tensor_value_info("input_ids", np.int32, ["B", "P"]),
               O.make_tensor_value_info("attention_mask", np.float32,
                                        ["B", "P"])]
        node = O.make_node(
            "GreedySearch",
            ["input_ids", "max_length", "", "", "", "", "attention_mask"],
            ["sequences"], domain="com.microsoft",
            decoder=_decoder_graph(), eos_token_id=V - 1, pad_token_id=0,
            model_type=0)
        g = O.make_graph(
            [node], "gen", inputs=ins,
            outputs=[O.make_tensor_value_info("sequences", np.int32,
                                              ["B", "L"])],
            initializers={"max_length": np.array(8, np.int64)})
        cm = convert_model(O.make_model(g))
        # row 0: 4 real tokens; row 1: 2 real tokens, left-padded by 2
        prompts = np.array([[1, 2, 3, 4], [0, 0, 5, 6]], np.int32)
        mask = np.array([[1, 1, 1, 1], [0, 0, 1, 1]], np.float32)
        out = np.asarray(cm(cm.params, {"input_ids": prompts,
                                        "attention_mask": mask})
                         ["sequences"])
        for r, real in enumerate([[1, 2, 3, 4], [5, 6]]):
            want = greedy(np.array(real, np.int32), len(real) + 4)
            got = [int(t) for t in out[r, 4:]]
            gen = want[len(real):]
            if V - 1 in gen:
                stop = gen.index(V - 1)
                assert got[:stop + 1] == gen[:stop + 1]
            else:
                assert got == gen

    def test_repetition_penalty_changes_output(self, oracle):
        cm = _greedy_model(repetition_penalty=np.array(9.0, np.float32),
                           min_length=np.array(0, np.int64))
        plain = _greedy_model()
        prompts = np.array([[1, 2, 3]], np.int32)
        a = np.asarray(cm(cm.params, {"input_ids": prompts})["sequences"])
        b = np.asarray(plain(plain.params,
                             {"input_ids": prompts})["sequences"])
        # a strong penalty forbids immediate repeats of seen tokens
        assert not np.array_equal(a, b) or len(set(b[0].tolist())) == 9


class TestBeamSearch:
    def _model(self, max_length, num_beams, num_return=1, extra=None):
        ins = [O.make_tensor_value_info("input_ids", np.int32,
                                        ["B", "P"])]
        extra = extra or {}
        names = (["input_ids", "max_length", "", "num_beams",
                  "num_return_sequences", "length_penalty"]
                 + list(extra))
        node = O.make_node("BeamSearch", names,
                           ["sequences", "sequences_scores"],
                           domain="com.microsoft",
                           decoder=_decoder_graph(),
                           eos_token_id=V - 1, pad_token_id=0,
                           model_type=0)
        g = O.make_graph(
            [node], "gen",
            inputs=ins,
            outputs=[O.make_tensor_value_info("sequences", np.int32,
                                              ["B", "R", "L"]),
                     O.make_tensor_value_info("sequences_scores",
                                              np.float32, ["B", "R"])],
            initializers={"max_length": np.array(max_length, np.int64),
                          "num_beams": np.array(num_beams, np.int64),
                          "num_return_sequences": np.array(num_return,
                                                           np.int64),
                          "length_penalty": np.array(1.0, np.float32),
                          **extra})
        return convert_model(O.make_model(g))

    def test_beam1_equals_greedy(self, oracle):
        _, greedy, _ = oracle
        cm = self._model(9, 1)
        prompts = np.array([[1, 2, 3]], np.int32)
        res = cm(cm.params, {"input_ids": prompts})
        got = list(np.asarray(res["sequences"])[0, 0])
        want = greedy(prompts[0], 9)
        if V - 1 in want[3:]:
            stop = want.index(V - 1, 3)
            assert got[:stop + 1] == want[:stop + 1]
        else:
            assert got == want

    def test_full_width_is_exhaustive(self, oracle):
        _, _, seq_logprob = oracle
        # W = V keeps every 1-token prefix: with 2 generated tokens the
        # best hypothesis equals brute force over all V^2 continuations
        # (no eos interference: compare against non-eos-ending winners
        # plus eos-banked ones — the op's answer must score >= every
        # enumerated sequence under the same penalty)
        cm = self._model(5, V)
        prompts = np.array([[1, 2, 3]], np.int32)
        res = cm(cm.params, {"input_ids": prompts})
        got = np.asarray(res["sequences"])[0, 0]
        score = float(np.asarray(res["sequences_scores"])[0, 0])

        def pen_score(gen):
            # mirror the op: cumulative logprob / generated length; an
            # eos-terminated prefix banks at its own length
            return seq_logprob(prompts[0], gen) / len(gen)

        best = -np.inf
        for cand in itertools.product(range(V), repeat=2):
            if cand[0] == V - 1:
                best = max(best, pen_score([cand[0]]))
            else:
                best = max(best, pen_score(list(cand)))
        assert score == pytest.approx(best, rel=1e-4)
        # and the returned tokens reproduce that score
        gen = [int(t) for t in got[3:] if True]
        if V - 1 in gen:
            gen = gen[:gen.index(V - 1) + 1]
        assert pen_score(gen) == pytest.approx(best, rel=1e-4)

    def test_num_return_sequences_sorted(self):
        cm = self._model(6, 4, num_return=3)
        prompts = np.array([[1, 2], [3, 4]], np.int32)
        res = cm(cm.params, {"input_ids": prompts})
        seqs = np.asarray(res["sequences"])
        scores = np.asarray(res["sequences_scores"])
        assert seqs.shape == (2, 3, 6)
        assert (np.diff(scores, axis=1) <= 1e-6).all()   # descending

    def test_validation(self):
        cm = self._model(6, 2, num_return=3)
        with pytest.raises(Exception, match="num_return_sequences"):
            cm(cm.params, {"input_ids": np.array([[1, 2]], np.int32)})
