"""Pipeline-parallel (GPipe over ppermute) tests on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from mmlspark_tpu.parallel.pipeline import (pipeline_apply,
                                            stack_stage_params,
                                            stage_shardings)

D = 8


def _mesh(pp):
    return Mesh(np.array(jax.devices()[:pp]), ("pp",))


def _stages(pp, seed=0):
    rng = np.random.default_rng(seed)
    return [{"w": rng.normal(0, 0.5, (D, D)).astype(np.float32),
             "b": rng.normal(0, 0.1, D).astype(np.float32)}
            for _ in range(pp)]


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _sequential(stages, x):
    for p in stages:
        x = np.tanh(x @ p["w"] + p["b"])
    return x


class TestPipeline:
    @pytest.mark.parametrize("pp,M", [(2, 3), (4, 8)])
    def test_matches_sequential(self, pp, M):
        mesh = _mesh(pp)
        stages = _stages(pp)
        stacked = jax.device_put(stack_stage_params(stages),
                                 stage_shardings(stack_stage_params(stages),
                                                 mesh))
        rng = np.random.default_rng(1)
        x = rng.normal(0, 1, (M, 4, D)).astype(np.float32)
        y = jax.jit(lambda p, x: pipeline_apply(p, x, _stage_fn, mesh))(
            stacked, jnp.asarray(x))
        expect = np.stack([_sequential(stages, x[m]) for m in range(M)])
        np.testing.assert_allclose(np.asarray(y), expect,
                                   rtol=1e-5, atol=1e-6)

    def test_grad_through_pipeline(self):
        pp, M = 4, 6
        mesh = _mesh(pp)
        stages = _stages(pp, seed=2)
        stacked = stack_stage_params(stages)
        stacked = jax.device_put(stacked, stage_shardings(stacked, mesh))
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(0, 1, (M, 4, D)).astype(np.float32))
        tgt = jnp.asarray(rng.normal(0, 1, (M, 4, D)).astype(np.float32))

        def loss(p, x):
            y = pipeline_apply(p, x, _stage_fn, mesh)
            return jnp.mean((y - tgt) ** 2)

        g = jax.jit(jax.grad(loss))(stacked, x)
        gw = np.asarray(g["w"])
        assert gw.shape[0] == pp
        assert np.isfinite(gw).all()
        # every stage gets signal (pipelined backprop reached them all)
        per_stage = np.abs(gw).reshape(pp, -1).sum(axis=1)
        assert (per_stage > 0).all(), per_stage

        # numerical check against the sequential loss for one leaf
        def seq_loss(p0w):
            ps = [dict(s) for s in stages]
            ps[0] = {"w": p0w, "b": stages[0]["b"]}
            y = jnp.stack([_jax_sequential(ps, x[m]) for m in range(M)])
            return jnp.mean((y - tgt) ** 2)

        g_seq = jax.grad(seq_loss)(jnp.asarray(stages[0]["w"]))
        np.testing.assert_allclose(gw[0], np.asarray(g_seq),
                                   rtol=1e-4, atol=1e-6)


def _jax_sequential(stages, x):
    for p in stages:
        x = jnp.tanh(x @ jnp.asarray(p["w"]) + jnp.asarray(p["b"]))
    return x
