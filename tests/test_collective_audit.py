"""Compiled-HLO collective auditor (``parallel/collective_audit.py``).

Covers the contract the CI ``collective-audit`` stage gates on:

* disabled = zero overhead: ``audit_program`` returns the callable
  itself, not a wrapper;
* HLO count exactness on hand-written HLO text and on a real
  shard_mapped psum (exactly one all-reduce, async pairs deduped);
* per-signature dedup: re-calling at a warmed shape re-audits nothing;
* the ``mmlspark_collective_ops_total`` / ``_bytes_total`` metrics
  mirror;
* ``harvest_collectives`` rows (``source="collective_audit"``);
* budget round-trip, exceed/unbudgeted violations vs under-budget
  drift, and CLI exit codes in ``--table`` mode;
* the committed ``tools/tpulint/collective_budget.json`` asserts the
  PR 15 invariant — ``tick_core`` at exactly one all-reduce, zero
  all-gathers — and a deliberately injected all-gather demonstrably
  fails against it (the acceptance negative test).
"""

import json
import io
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mmlspark_tpu.observability import reset_all, snapshot
from mmlspark_tpu.parallel import collective_audit as ca
from mmlspark_tpu.parallel.mesh import get_shard_map
from mmlspark_tpu.tuning.observations import (ObservationStore,
                                              harvest_collectives)

needs_devices = pytest.mark.skipif(
    jax.device_count() < 8,
    reason="needs 8 (simulated) devices — tier-1's conftest provides them")


@pytest.fixture
def audited(monkeypatch):
    """Audit enabled against a fresh auditor (and fresh metrics)."""
    monkeypatch.setenv(ca.ENV_FLAG, "1")
    ca.reset_auditor()
    reset_all()
    yield ca.get_auditor()
    ca.reset_auditor()
    reset_all()


def _psum_fn(n_dev=4):
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("dp",))
    shard_map, uncheck = get_shard_map()

    def body(x):
        return jax.lax.psum(x, "dp")

    return jax.jit(shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                             out_specs=P(), **uncheck))


# ---------------------------------------------------------------------------
# disabled = zero overhead


def test_disabled_returns_program_unchanged(monkeypatch):
    monkeypatch.delenv(ca.ENV_FLAG, raising=False)
    f = lambda x: x  # noqa: E731
    assert ca.audit_program("anything", f) is f
    assert not ca.enabled()


def test_enabled_flag_values(monkeypatch):
    for off in ("", "0", "false", "no", "NO"):
        monkeypatch.setenv(ca.ENV_FLAG, off)
        assert not ca.enabled()
    monkeypatch.setenv(ca.ENV_FLAG, "1")
    assert ca.enabled()


# ---------------------------------------------------------------------------
# HLO count exactness


_HLO_SAMPLE = """\
HloModule jit_step

ENTRY %main {
  %p0 = f32[4,8]{1,0} parameter(0)
  %ar = f32[4,8]{1,0} all-reduce(%p0), replica_groups={{0,1,2,3}}
  %ags = (f32[4,8]{1,0}, f32[16,8]{1,0}) all-gather-start(%ar)
  %ag = f32[16,8]{1,0} all-gather-done(%ags)
  %cp = bf16[2,4]{1,0} collective-permute(%p1), source_target_pairs={{0,1}}
  ROOT %t = (f32[16,8]{1,0}) tuple(%ag)
}
"""


def test_count_collectives_on_hlo_text():
    counts = ca.count_collectives(_HLO_SAMPLE)
    # the -start/-done async pair is ONE all-gather, not two
    assert counts["all-reduce"]["ops"] == 1
    assert counts["all-gather"]["ops"] == 1
    assert counts["collective-permute"]["ops"] == 1
    assert "all-to-all" not in counts
    # bytes: f32[4,8] = 128; the all-gather's tuple shape sums both
    # elements (128 + 512); bf16[2,4] = 16
    assert counts["all-reduce"]["bytes"] == 128
    assert counts["all-gather"]["bytes"] == 640
    assert counts["collective-permute"]["bytes"] == 16


@needs_devices
def test_shard_mapped_psum_counts_exactly_one_all_reduce(audited):
    fn = ca.audit_program("toy", _psum_fn())
    x = jnp.ones((8, 16), jnp.float32)
    np.testing.assert_array_equal(np.asarray(fn(x)), 4.0)
    row = audited.table()["toy"]
    assert row["sigs"] == 1
    assert row["kinds"]["all-reduce"]["ops"] == 1
    assert "all-gather" not in row["kinds"]
    assert row["kinds"]["all-reduce"]["bytes"] > 0


@needs_devices
def test_signature_dedup_and_new_shapes(audited):
    fn = ca.audit_program("toy", _psum_fn())
    fn(jnp.ones((8, 16), jnp.float32))
    fn(jnp.ones((8, 16), jnp.float32))     # warmed shape: no re-audit
    assert audited.table()["toy"]["sigs"] == 1
    fn(jnp.ones((8, 32), jnp.float32))     # new shape: one more audit
    assert audited.table()["toy"]["sigs"] == 2


# ---------------------------------------------------------------------------
# metrics mirror + ObservationStore harvest


@needs_devices
def test_metrics_mirror(audited):
    fn = ca.audit_program("toy", _psum_fn())
    fn(jnp.ones((8, 16), jnp.float32))
    snap = snapshot()
    ops = {tuple(sorted(s["labels"].items())): s["value"]
           for s in snap["mmlspark_collective_ops_total"]["series"]}
    key = (("kind", "all-reduce"), ("prog", "toy"))
    assert ops[key] == 1.0
    bts = {tuple(sorted(s["labels"].items())): s["value"]
           for s in snap["mmlspark_collective_bytes_total"]["series"]}
    assert bts[key] > 0


def test_harvest_collectives_rows():
    table = {
        "tick": {"sigs": 2, "kinds": {
            "all-reduce": {"ops": 9, "bytes": 1044},
            "all-gather": {"ops": 15, "bytes": 3472}}},
        "compact": {"sigs": 1, "kinds": {}},
    }
    store = ObservationStore()
    assert harvest_collectives(table, store=store) == 2
    rows = store.rows(source="collective_audit")
    assert len(rows) == 2
    tick = next(r for r in rows if r["prog"] == "tick")
    assert tick["sig"] == "collective:tick"
    assert tick["rows"] == 2
    assert tick["ops_total"] == 24
    assert tick["bytes_total"] == 4516
    assert tick["collectives"]["all-reduce"]["ops"] == 9
    quiet = next(r for r in rows if r["prog"] == "compact")
    assert quiet["ops_total"] == 0 and quiet["collectives"] == {}


# ---------------------------------------------------------------------------
# budget round-trip + violation semantics


def _table(kinds):
    return {"tick": {"sigs": 1, "kinds": kinds}}


def test_budget_roundtrip(tmp_path):
    table = _table({"all-reduce": {"ops": 1, "bytes": 64}})
    path = str(tmp_path / "budget.json")
    ca.write_budget(table, path)
    budget = ca.load_budget(path)
    assert budget == {"version": 1, "budgets": {"tick": {"all-reduce": 1}}}
    violations, drift = ca.check_budget(table, budget)
    assert not violations and not drift


def test_budget_exceed_unbudgeted_and_drift():
    budget = {"version": 1, "budgets": {"tick": {"all-reduce": 2}}}
    # exceed: one op over
    v, d = ca.check_budget(_table({"all-reduce": {"ops": 3, "bytes": 1}}),
                           budget)
    assert len(v) == 1 and "exceeds" in v[0] and not d
    # a kind the budget never allowed: zero-budget semantics
    v, d = ca.check_budget(
        _table({"all-reduce": {"ops": 2, "bytes": 1},
                "all-gather": {"ops": 1, "bytes": 1}}), budget)
    assert len(v) == 1 and "all-gather" in v[0]
    # unbudgeted program gates
    v, _ = ca.check_budget({"mystery": {"sigs": 1, "kinds": {}}}, budget)
    assert len(v) == 1 and "not in budget" in v[0]
    # under budget is drift, not a violation
    v, d = ca.check_budget(_table({"all-reduce": {"ops": 1, "bytes": 1}}),
                           budget)
    assert not v and len(d) == 1 and "under budget" in d[0]


def test_budget_load_rejects_bad_version(tmp_path):
    path = tmp_path / "b.json"
    path.write_text(json.dumps({"version": 9, "budgets": {}}))
    with pytest.raises(ValueError):
        ca.load_budget(str(path))


# ---------------------------------------------------------------------------
# CLI exit codes (--table mode: no program rebuild)


def _cli(args):
    out = io.StringIO()
    rc = ca.main(args, stdout=out)
    return rc, out.getvalue()


def test_cli_within_budget_exits_zero(tmp_path):
    table = _table({"all-reduce": {"ops": 1, "bytes": 64}})
    tpath, bpath = str(tmp_path / "t.json"), str(tmp_path / "b.json")
    with open(tpath, "w") as fh:
        json.dump(table, fh)
    ca.write_budget(table, bpath)
    rc, out = _cli(["--table", tpath, "--budget", bpath])
    assert rc == 0 and "within budget" in out


def test_cli_exceeded_budget_exits_nonzero(tmp_path):
    tpath, bpath = str(tmp_path / "t.json"), str(tmp_path / "b.json")
    ca.write_budget(_table({"all-reduce": {"ops": 1, "bytes": 64}}), bpath)
    with open(tpath, "w") as fh:
        json.dump(_table({"all-reduce": {"ops": 1, "bytes": 64},
                          "all-gather": {"ops": 1, "bytes": 64}}), fh)
    rc, out = _cli(["--table", tpath, "--budget", bpath])
    assert rc == 1 and "BUDGET EXCEEDED" in out and "all-gather" in out


def test_cli_missing_budget_exits_nonzero(tmp_path):
    tpath = str(tmp_path / "t.json")
    with open(tpath, "w") as fh:
        json.dump(_table({}), fh)
    rc, out = _cli(["--table", tpath,
                    "--budget", str(tmp_path / "absent.json")])
    assert rc == 1 and "--write-budget" in out


def test_cli_write_budget_then_check(tmp_path):
    tpath, bpath = str(tmp_path / "t.json"), str(tmp_path / "b.json")
    with open(tpath, "w") as fh:
        json.dump(_table({"all-to-all": {"ops": 4, "bytes": 9}}), fh)
    rc, _ = _cli(["--table", tpath, "--budget", bpath, "--write-budget"])
    assert rc == 0
    rc, out = _cli(["--table", tpath, "--budget", bpath])
    assert rc == 0 and "within budget" in out


# ---------------------------------------------------------------------------
# the committed budget: PR 15 invariant + the acceptance negative test


def _committed_budget():
    return ca.load_budget(ca.DEFAULT_BUDGET_PATH)


def test_committed_budget_asserts_tick_core_invariant():
    budget = _committed_budget()
    # the meshed decode tick's attention core: EXACTLY one all-reduce,
    # zero of everything else (absent kind = zero budget)
    assert budget["budgets"]["tick_core"] == {"all-reduce": 1}
    # and every engine program the reference build audits is budgeted
    for prog in ("tick", "tick_sampled", "spec_tick", "prefill",
                 "draft_prefill", "extend", "sp_step", "flash_step",
                 "moe_dispatch"):
        assert prog in budget["budgets"], prog


@needs_devices
def test_injected_all_gather_fails_committed_budget(audited):
    """The acceptance negative test: a deliberate extra all-gather in
    the meshed tick-core program must trip the committed budget."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    shard_map, uncheck = get_shard_map()

    def body(x):
        y = jax.lax.psum(x, "dp")
        return y + jax.lax.all_gather(x, "dp").sum(0)   # the regression

    fn = ca.audit_program("tick_core", jax.jit(
        shard_map(body, mesh=mesh, in_specs=(P("dp"),), out_specs=P(),
                  **uncheck)))
    fn(jnp.ones((8, 16), jnp.float32))
    row = audited.table()["tick_core"]["kinds"]
    assert row["all-gather"]["ops"] >= 1
    violations, _ = ca.check_budget(audited.table(), _committed_budget())
    assert any("tick_core" in v and "all-gather" in v for v in violations)


# ---------------------------------------------------------------------------
# warm_up_jitted hook


@needs_devices
def test_warm_up_jitted_records_under_prog(audited):
    from mmlspark_tpu.ops.compile_cache import warm_up_jitted

    fn = _psum_fn()
    jitted = jax.jit(lambda params, feeds: fn(feeds["x"] * params))
    specs = {"x": (np.dtype(np.float32), (16,))}
    res = warm_up_jitted(jitted, jnp.float32(2.0), specs,
                         batch_sizes=[8], prog="warm_toy")
    assert res["buckets"] == [8]
    row = audited.table()["warm_toy"]
    assert row["sigs"] == 1
    assert row["kinds"]["all-reduce"]["ops"] == 1
