"""Generated API surface (L6) tests.

Parity role: the reference's codegen CI job — wrappers are generated from
reflection and the build fails if the surface is stale or incomplete
(``codegen/CodeGen.scala:29-43``, ``project/CodegenPlugin.scala:55-67``).
"""

import ast
import glob
import importlib
import inspect
import os

import pytest

from mmlspark_tpu.codegen import (discover_stages, generate_all_stubs,
                                  generate_docs, param_annotation)
from mmlspark_tpu.core.pipeline import Model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_stubs_fresh_and_parse():
    """Checked-in .pyi files must match exactly what codegen emits now."""
    stubs = generate_all_stubs()
    assert stubs, "no stubs generated"
    for module_name, text in stubs.items():
        mod = importlib.import_module(module_name)
        path = os.path.splitext(inspect.getsourcefile(mod))[0] + ".pyi"
        assert os.path.exists(path), (
            f"missing stub {path}; run `python -m mmlspark_tpu.codegen`")
        with open(path) as f:
            on_disk = f.read()
        assert on_disk == text, (
            f"stale stub {path}; run `python -m mmlspark_tpu.codegen`")
        ast.parse(text, path)


def test_stub_base_names_all_defined():
    """ast.parse only checks syntax; every base class name must also be
    defined in or imported into its stub, or type checking breaks."""
    for module_name, text in generate_all_stubs().items():
        tree = ast.parse(text)
        defined = {n.name for n in ast.walk(tree)
                   if isinstance(n, ast.ClassDef)}
        imported = set()
        for n in ast.walk(tree):
            if isinstance(n, ast.ImportFrom):
                imported |= {a.asname or a.name for a in n.names}
        ok = defined | imported
        for n in ast.walk(tree):
            if isinstance(n, ast.ClassDef):
                for b in n.bases:
                    assert not (isinstance(b, ast.Name) and b.id not in ok), \
                        f"{module_name}: class {n.name} base {b.id} undefined"


def test_stub_core_methods_redeclared():
    """Stubs shadow their module; fit/transform must stay visible."""
    stubs = generate_all_stubs()
    pipeline = stubs["mmlspark_tpu.core.pipeline"]
    assert "def transform(self, df: DataFrame" in pipeline
    assert "def fit(self, df: DataFrame" in pipeline
    assert "def load(cls, path: str)" in pipeline
    onnx = stubs["mmlspark_tpu.models.onnx_model"]
    assert "model_bytes: Any = ..." in onnx  # positional arg preserved


def test_no_orphan_stubs():
    generated = set()
    for module_name in generate_all_stubs():
        mod = importlib.import_module(module_name)
        generated.add(os.path.splitext(inspect.getsourcefile(mod))[0] + ".pyi")
    on_disk = {os.path.abspath(p) for p in
               glob.glob(os.path.join(REPO, "mmlspark_tpu/**/*.pyi"),
                         recursive=True)}
    orphans = on_disk - {os.path.abspath(p) for p in generated}
    # stubs that declare themselves hand-written are allowed: codegen only
    # covers PipelineStage modules, and tpulint rule TPU006 (stub-drift)
    # keeps the hand-written ones in sync with their modules
    orphans = {p for p in orphans
               if "hand-written" not in open(p).readline().lower()}
    assert not orphans, f"stubs with no generating module: {sorted(orphans)}"


def test_every_stage_in_stub_and_docs():
    stages = [c for c in discover_stages()
              if not c.__qualname__.startswith("_")]
    stubs = generate_all_stubs()
    docs = generate_docs()
    for cls in stages:
        text = stubs.get(cls.__module__)
        assert text and f"class {cls.__name__}(" in text, (
            f"{cls.__qualname__} missing from stub of {cls.__module__}")
        if issubclass(cls, Model):
            continue
        pkg = cls.__module__.split(".")[1]
        assert f"### `{cls.__name__}`" in docs.get(pkg, ""), (
            f"{cls.__qualname__} missing from docs page {pkg}")


def test_docs_index_links_every_page():
    docs = generate_docs()
    index = docs["index"]
    for page in docs:
        if page != "index":
            assert f"({page}.md)" in index
    for page in docs:
        path = os.path.join(REPO, "docs", "api", f"{page}.md")
        assert os.path.exists(path), f"missing doc page {path}"


def test_param_annotations():
    from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier
    from mmlspark_tpu.core.params import HasWeightCol, HasBatchSize

    params = LightGBMClassifier.params()
    assert param_annotation(params["num_iterations"]) == "int"
    assert param_annotation(HasBatchSize.params()["batch_size"]) == "int"
    assert param_annotation(HasWeightCol.params()["weight_col"]) == "Optional[str]"
    tl = param_annotation(params["parallelism"])
    assert tl.startswith("Literal[") and "'data_parallel'" in tl


def test_py_typed_marker_exists():
    assert os.path.exists(os.path.join(REPO, "mmlspark_tpu", "py.typed"))


@pytest.mark.parametrize("page", ["index", "stages", "models"])
def test_docs_pages_nonempty(page):
    path = os.path.join(REPO, "docs", "api", f"{page}.md")
    with open(path) as f:
        assert len(f.read()) > 100


class TestRWrappers:
    """Generated R surface (RWrappable role, Wrappable.scala:93; package
    assembly CodeGen.scala:66-120) — reticulate-backed sparklyr-style
    functions from the same Param reflection."""

    def test_r_surface_fresh(self):
        from mmlspark_tpu.codegen import generate_r_wrappers
        files = generate_r_wrappers()
        assert set(files) >= {"DESCRIPTION", "NAMESPACE", "R/zzz.R"}
        for rel, text in files.items():
            path = os.path.join(REPO, "r", "mmlsparktpu", rel)
            assert os.path.exists(path), (
                f"missing {path}; run `python -m mmlspark_tpu.codegen`")
            with open(path) as f:
                assert f.read() == text, (
                    f"stale {path}; run `python -m mmlspark_tpu.codegen`")

    def test_every_export_is_defined(self):
        import re
        from mmlspark_tpu.codegen import generate_r_wrappers
        files = generate_r_wrappers()
        exported = set(re.findall(r"export\(([^)]+)\)", files["NAMESPACE"]))
        defined = set()
        for rel, text in files.items():
            if rel.startswith("R/"):
                defined |= set(re.findall(
                    r"^([A-Za-z_.][A-Za-z0-9_.]*) <- function", text,
                    re.MULTILINE))
        missing = exported - defined
        assert not missing, f"exported but never defined: {sorted(missing)}"
        assert len(exported) > 50       # the surface is the whole stage set

    def test_r_files_brace_balanced_and_int_coerced(self):
        from mmlspark_tpu.codegen import generate_r_wrappers
        files = generate_r_wrappers()
        for rel, text in files.items():
            if rel.startswith("R/"):
                # count only code lines — roxygen/doc comments legitimately
                # contain unbalanced parens
                code = "\n".join(ln for ln in text.splitlines()
                                 if not ln.lstrip().startswith("#"))
                assert code.count("{") == code.count("}"), rel
                assert code.count("(") == code.count(")"), rel
        # int params must cross reticulate as R integers
        assert "as.integer(num_iterations)" in files["R/models.R"]

    def test_function_names_are_snake_case(self):
        from mmlspark_tpu.codegen import _r_fn_name, discover_stages
        names = [_r_fn_name(c) for c in discover_stages()
                 if c.__qualname__ == c.__name__]
        assert all(n.startswith("sml_") and n == n.lower() for n in names)
        # the reference's ml_lightgbm_classifier analogue
        assert "sml_light_gbm_classifier" in names or \
            "sml_lightgbm_classifier" in names
