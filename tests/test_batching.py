import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.ops.padding import bucket_size, pad_batch, unpad
from mmlspark_tpu.stages.batching import (DynamicBufferedBatcher,
                                          DynamicMiniBatchTransformer,
                                          FixedMiniBatchTransformer,
                                          FlattenBatch, TimeIntervalBatcher)


class TestPadding:
    def test_bucket_size(self):
        assert bucket_size(0) == 1
        assert bucket_size(1) == 1
        assert bucket_size(5) == 8
        assert bucket_size(8) == 8
        assert bucket_size(9) == 16
        assert bucket_size(5, buckets=[4, 6, 10]) == 6
        with pytest.raises(ValueError):
            bucket_size(11, buckets=[4, 6, 10])

    def test_pad_batch_and_mask(self):
        pb = pad_batch({"x": np.ones((5, 3)), "y": np.arange(5)})
        assert pb["x"].shape == (8, 3)
        assert pb.mask.sum() == 5
        assert np.array_equal(unpad(pb["y"], pb.n_valid), np.arange(5))
        assert pb["x"][5:].sum() == 0

    def test_pad_batch_inconsistent(self):
        with pytest.raises(ValueError):
            pad_batch({"x": np.ones(3), "y": np.ones(4)})


class TestMiniBatch:
    def test_fixed_roundtrip(self):
        df = DataFrame({"x": np.arange(23, dtype=np.float64),
                        "s": [f"r{i}" for i in range(23)]}, npartitions=2)
        batched = FixedMiniBatchTransformer(batch_size=10).transform(df)
        # partition sizes 12 + 11 → batches [10,2] + [10,1]
        assert len(batched) == 4
        assert isinstance(batched["x"][0], np.ndarray)
        assert len(batched["x"][0]) == 10
        assert isinstance(batched["s"][0], list)
        flat = FlattenBatch().transform(batched)
        assert np.array_equal(np.sort(flat["x"]), np.arange(23))
        assert list(flat["s"][:3]) == ["r0", "r1", "r2"]

    def test_vector_column_stacks(self):
        df = DataFrame({"v": [np.full(4, i, dtype=np.float32) for i in range(6)]})
        b = FixedMiniBatchTransformer(batch_size=3).transform(df)
        assert b["v"][0].shape == (3, 4)
        flat = FlattenBatch().transform(b)
        assert flat["v"][5].shape == (4,)

    def test_dynamic(self):
        df = DataFrame({"x": np.arange(10)}, npartitions=3)
        b = DynamicMiniBatchTransformer().transform(df)
        assert len(b) == 3  # one batch per partition
        b2 = DynamicMiniBatchTransformer(max_batch_size=2).transform(df)
        assert all(len(cell) <= 2 for cell in b2["x"])

    def test_flatten_ragged_error(self):
        df = DataFrame({"a": [np.ones(2), np.ones(3)],
                        "b": [np.ones(2), np.ones(4)]})
        with pytest.raises(ValueError):
            FlattenBatch().transform(df)


class TestStreamingBatchers:
    def test_buffered_batcher_all_rows(self):
        rows = list(range(100))
        got = [r for batch in DynamicBufferedBatcher(iter(rows)) for r in batch]
        assert got == rows

    def test_buffered_batcher_propagates_error(self):
        def gen():
            yield 1
            raise RuntimeError("boom")
        with pytest.raises(RuntimeError):
            for _ in DynamicBufferedBatcher(gen()):
                pass

    def test_time_interval_batcher(self):
        rows = list(range(50))
        batches = list(TimeIntervalBatcher(iter(rows), millis=1, max_batch_size=7))
        assert [r for b in batches for r in b] == rows
        assert all(len(b) <= 7 for b in batches)


class TestMesh:
    def test_make_mesh_cpu(self):
        import jax
        from mmlspark_tpu.parallel import make_mesh
        n = len(jax.devices())
        assert n == 8  # conftest forces 8 virtual devices
        mesh = make_mesh({"data": -1})
        assert mesh.shape == {"data": 8}
        mesh2 = make_mesh({"data": 2, "model": -1})
        assert mesh2.shape == {"data": 2, "model": 4}

    def test_device_for_partition(self):
        from mmlspark_tpu.parallel import device_for_partition
        d0 = device_for_partition(0)
        d8 = device_for_partition(8)
        assert d0 == d8


class TestTimeIntervalMiniBatchTransformer:
    """Event-time windows on materialized frames (the stage-API half of
    ``TimeIntervalMiniBatchTransformer``, MiniBatchTransformer.scala:77)."""

    def _frame(self, ts):
        import numpy as np
        from mmlspark_tpu.core import DataFrame
        return DataFrame({"t": np.asarray(ts),
                          "v": np.arange(len(ts), dtype=np.float32)})

    def test_event_time_windows_epoch_millis(self):
        import numpy as np
        from mmlspark_tpu.stages.batching import TimeIntervalMiniBatchTransformer
        # windows of 100ms: [0,50,99] [100,180] [350]
        df = self._frame(np.array([0, 50, 99, 100, 180, 350], dtype=np.int64))
        t = TimeIntervalMiniBatchTransformer(millis_to_wait=100,
                                             timestamp_col="t")
        out = t.transform(df)
        assert [len(c) for c in out["v"]] == [3, 2, 1]
        np.testing.assert_array_equal(out["v"][0], [0, 1, 2])

    def test_event_time_windows_datetime64(self):
        import numpy as np
        from mmlspark_tpu.stages.batching import TimeIntervalMiniBatchTransformer
        base = np.datetime64("2026-01-01T00:00:00", "ms")
        ts = base + np.array([0, 10, 2000, 2500], dtype="timedelta64[ms]")
        t = TimeIntervalMiniBatchTransformer(millis_to_wait=1000,
                                             timestamp_col="t")
        out = t.transform(self._frame(ts))
        assert [len(c) for c in out["v"]] == [2, 2]

    def test_max_batch_size_caps_window(self):
        import numpy as np
        from mmlspark_tpu.stages.batching import TimeIntervalMiniBatchTransformer
        df = self._frame(np.zeros(5, dtype=np.int64))  # all same instant
        t = TimeIntervalMiniBatchTransformer(millis_to_wait=1000,
                                             timestamp_col="t",
                                             max_batch_size=2)
        out = t.transform(df)
        assert [len(c) for c in out["v"]] == [2, 2, 1]

    def test_without_timestamp_col_one_batch(self):
        import numpy as np
        from mmlspark_tpu.stages.batching import TimeIntervalMiniBatchTransformer
        df = self._frame(np.arange(4, dtype=np.int64))
        out = TimeIntervalMiniBatchTransformer().transform(df)
        assert len(out) == 1 and len(out["v"][0]) == 4


class TestPrefetchIterator:
    def test_order_preserved(self):
        from mmlspark_tpu.stages.batching import PrefetchIterator
        assert list(PrefetchIterator(iter(range(50)), depth=3)) \
            == list(range(50))

    def test_empty_source(self):
        from mmlspark_tpu.stages.batching import PrefetchIterator
        assert list(PrefetchIterator(iter([]), depth=2)) == []

    def test_producer_error_surfaces_on_consumer(self):
        from mmlspark_tpu.stages.batching import PrefetchIterator

        def gen():
            yield 1
            yield 2
            raise ValueError("producer died")

        it = iter(PrefetchIterator(gen(), depth=2))
        got = []
        with pytest.raises(ValueError, match="producer died"):
            for x in it:
                got.append(x)
        assert got == [1, 2]   # items before the error still arrive in order

    def test_depth_bounds_readahead(self):
        import threading
        from mmlspark_tpu.stages.batching import PrefetchIterator
        produced = []
        release = threading.Event()

        def gen():
            for i in range(100):
                produced.append(i)
                yield i

        it = iter(PrefetchIterator(gen(), depth=2))
        first = next(it)
        assert first == 0
        # give the producer time to run ahead as far as the queue allows:
        # at most depth queued + one in flight + the one consumed
        deadline = __import__("time").monotonic() + 2.0
        while len(produced) < 4 and __import__("time").monotonic() < deadline:
            __import__("time").sleep(0.01)
        assert 1 <= len(produced) <= 4
        assert list(it) == list(range(1, 100))
