"""Model registry: versioned lifecycle (load/warm-up/canary/promote/
retire with device release), deterministic canary split, SLO-window
auto-rollback, shadow-traffic joining, tenant config, the /models and
/debug/registry admin routes, and the multi-model multi-tenant cluster
chaos drill (weighted-fair goodput, canary auto-rollback under faults
with a mid-rollout worker restart, prefix-affine routing vs the
round-robin baseline, zero request loss).
"""

import json
import threading
import time
import types
import urllib.error
import urllib.request

import pytest

from mmlspark_tpu.observability import reset_all
from mmlspark_tpu.observability.ledger import reset_ledger
from mmlspark_tpu.observability.slo import get_tracker, reset_tracker
from mmlspark_tpu.observability.watchdog import reset_watchdog
from mmlspark_tpu.reliability import get_injector, reset_breakers
from mmlspark_tpu.serving.kv_pool import AFFINITY_HEADER
from mmlspark_tpu.serving.registry import (ModelRegistry, get_registry,
                                           reset_registry, set_registry)


@pytest.fixture(autouse=True)
def _clean_slate():
    reset_registry()
    reset_ledger()
    reset_tracker()
    reset_watchdog()
    reset_breakers()
    reset_all()
    get_injector().clear()
    yield
    reset_registry()
    reset_ledger()
    reset_tracker()
    reset_watchdog()
    reset_breakers()
    get_injector().clear()
    reset_all()


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _post(url, payload, headers=None, timeout=20.0):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode() or "{}")


class _Pool:
    def __init__(self):
        self.closed = False

    def close(self):
        self.closed = True
        return object()   # the returned ResidencyManager reservation


# ---------------------------------------------------------------------------
# lifecycle


def test_load_warm_up_then_live_and_canary_ordering():
    reg = ModelRegistry()
    warmed = []
    mv1 = reg.load("m", "v1", handle=lambda df: df,
                   warm_up=lambda: warmed.append("v1"))
    assert mv1.state == "live" and warmed == ["v1"]
    assert mv1.warmed_seconds is not None
    # second version of the same model arrives as a canary, not live
    mv2 = reg.load("m", "v2", handle=lambda df: df, canary_percent=25)
    assert mv2.state == "canary"
    assert [v.label for v in reg.versions("m")] == ["m@v1", "m@v2"]


def test_duplicate_load_rejected_until_retired():
    reg = ModelRegistry()
    reg.load("m", "v1")
    with pytest.raises(ValueError):
        reg.load("m", "v1")
    reg.retire("m", "v1")
    reg.load("m", "v1")   # a retired slot may be reloaded


def test_warm_up_failure_retires_with_error():
    reg = ModelRegistry()

    def boom():
        raise RuntimeError("compile exploded")

    mv = reg.load("m", "v1", warm_up=boom)
    assert mv.state == "retired"
    assert "compile exploded" in mv.error


def test_nonblocking_load_warms_off_request_path():
    reg = ModelRegistry()
    gate = threading.Event()
    mv = reg.load("m", "v1", warm_up=gate.wait, block=False)
    assert mv.state == "loading"
    # loading versions are NOT routable
    assert reg.resolve("m").label == "m"
    gate.set()
    deadline = time.monotonic() + 5.0
    while mv.state == "loading" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert mv.state == "live"


def test_retire_drains_then_releases_device_state():
    reg = ModelRegistry()
    handle = types.SimpleNamespace(_device_params={"slot0": object()},
                                   pool=_Pool())
    unloaded = []
    mv = reg.load("m", "v1", handle=handle,
                  unload_fn=lambda: unloaded.append(True))
    mv.in_flight = 2
    out = reg.retire("m", "v1", drain_timeout=0.05)
    assert out["drained"] is False          # in-flight never landed
    assert mv.state == "retired" and mv.handle is None
    assert handle._device_params == {}      # staged params released
    assert handle.pool.closed               # reservation returned
    assert unloaded == [True]
    # idempotent from any state
    assert reg.retire("m", "v1")["drained"] is True


def test_promote_retires_the_incumbent():
    reg = ModelRegistry()
    reg.load("m", "v1")
    reg.load("m", "v2")
    reg.promote("m", "v2")
    states = {v.version: v.state for v in reg.versions("m")}
    assert states == {"v1": "retired", "v2": "live"}
    with pytest.raises(ValueError):
        reg.promote("m", "v2")   # already live


# ---------------------------------------------------------------------------
# resolution: canary split + shadow sampling


def test_resolve_passthrough_for_unregistered_names():
    reg = ModelRegistry()
    res = reg.resolve("never-loaded")
    assert res.label == "never-loaded" and res.shadow is None
    assert res.decision == "passthrough"


def test_canary_split_is_deterministic_per_request_id():
    reg = ModelRegistry()
    reg.load("m", "v1")
    reg.load("m", "v2", canary_percent=50)
    first = {rid: reg.resolve("m", rid).label
             for rid in (f"req-{i}" for i in range(40))}
    again = {rid: reg.resolve("m", rid).label for rid in first}
    assert first == again, "retries of one request must stay on one version"
    assert set(first.values()) == {"m@v1", "m@v2"}


def test_canary_percent_bounds():
    reg = ModelRegistry()
    reg.load("m", "v1")
    reg.load("m", "v2", canary_percent=100)
    assert all(reg.resolve("m", f"r{i}").label == "m@v2" for i in range(20))
    reg2 = ModelRegistry()
    reg2.load("n", "v1")
    reg2.load("n", "v2", canary_percent=0)
    assert all(reg2.resolve("n", f"r{i}").label == "n@v1"
               for i in range(20))


def test_shadow_sampling_rides_incumbent_decisions_only():
    reg = ModelRegistry()
    reg.load("m", "v1")
    mv2 = reg.load("m", "v2", canary_percent=0, shadow_percent=100)
    res = reg.resolve("m", "some-request")
    assert res.label == "m@v1" and res.shadow == "m@v2"
    assert mv2.in_flight == 1          # the mirror is tracked in-flight
    reg.note_done(res.shadow)
    reg.note_done(res.label)
    assert mv2.in_flight == 0


def test_note_done_tracks_in_flight():
    reg = ModelRegistry()
    mv = reg.load("m", "v1")
    reg.resolve("m", "a")
    reg.resolve("m", "b")
    assert mv.in_flight == 2 and mv.resolved_total == 2
    reg.note_done("m@v1")
    assert mv.in_flight == 1
    reg.note_done("m@v1")
    reg.note_done("m@v1")     # extra note_done never goes negative
    assert mv.in_flight == 0


# ---------------------------------------------------------------------------
# canary governance (SLO-window auto-rollback)


def _feed(model, n, error=False, seconds=0.01):
    tracker = get_tracker()
    for _ in range(n):
        tracker.observe(transport="threaded", route="api", model=model,
                        seconds=seconds, error=error)


def test_auto_rollback_on_error_rate_breach():
    reg = ModelRegistry(min_requests=5)
    reg.load("m", "v1")
    reg.load("m", "v2", canary_percent=50)
    _feed("m@v1", 10, error=False)
    _feed("m@v2", 6, error=True)
    verdicts = reg.check_canaries()
    assert len(verdicts) == 1 and "error_rate" in verdicts[0]["breach"]
    states = {v.version: v.state for v in reg.versions("m")}
    assert states["v2"] == "retired" and states["v1"] == "live"
    snap = reg.snapshot()
    assert snap["rollbacks"] and \
        "error_rate" in snap["rollbacks"][-1]["reason"]


def test_auto_rollback_on_p99_breach():
    reg = ModelRegistry(min_requests=5, p99_margin=1.5)
    reg.load("m", "v1")
    reg.load("m", "v2", canary_percent=50)
    _feed("m@v1", 10, seconds=0.01)
    _feed("m@v2", 8, seconds=2.0)
    verdicts = reg.check_canaries()
    assert verdicts[0]["breach"] and "p99" in verdicts[0]["breach"]
    assert {v.version: v.state
            for v in reg.versions("m")}["v2"] == "retired"


def test_no_rollback_below_min_requests_or_within_margins():
    reg = ModelRegistry(min_requests=20)
    reg.load("m", "v1")
    reg.load("m", "v2", canary_percent=50)
    _feed("m@v1", 30)
    _feed("m@v2", 5, error=True)     # loud but below min_requests
    assert reg.check_canaries()[0]["breach"] is None
    assert {v.version: v.state
            for v in reg.versions("m")}["v2"] == "canary"
    # a healthy canary above min_requests also stays put
    reg.load("n", "v1")
    reg.load("n", "v2", canary_percent=50)
    _feed("n@v1", 30)
    _feed("n@v2", 25)
    verdicts = {v["model"]: v for v in reg.check_canaries()}
    assert verdicts["n"]["breach"] is None
    assert {v.version: v.state
            for v in reg.versions("n")}["v2"] == "canary"


# ---------------------------------------------------------------------------
# shadow joining


def test_shadow_join_diffs_both_orders():
    reg = ModelRegistry()
    reg.shadow_begin("p1", "s1", "m@v2", trace_id="t1")
    reg.shadow_result("p1", b'{"ok":1}', from_shadow=False)
    assert reg.shadow_diffs() == []            # half a pair is no verdict
    reg.shadow_result("p1", b'{"ok":1}', from_shadow=True)
    (d1,) = reg.shadow_diffs()
    assert d1["verdict"] == "match" and d1["trace_id"] == "t1"
    reg.shadow_begin("p2", "s2", "m@v2")
    reg.shadow_result("p2", b"A", from_shadow=True)   # shadow answers first
    reg.shadow_result("p2", b"B", from_shadow=False)
    assert reg.shadow_diffs()[-1]["verdict"] == "diff"
    # unknown primary ids are ignored, not an error
    reg.shadow_result("never-mirrored", b"x", from_shadow=True)


# ---------------------------------------------------------------------------
# tenant config


def test_tenant_weights():
    reg = ModelRegistry()
    assert reg.tenant_weight("anyone") == 1.0
    reg.set_tenant("acme", 3)
    assert reg.tenant_weight("acme") == 3.0
    assert reg.tenants() == {"acme": 3.0}
    with pytest.raises(ValueError):
        reg.set_tenant("bad", 0)


def test_global_singleton_idiom():
    a = get_registry()
    assert get_registry() is a
    reset_registry()
    assert get_registry() is not a
    mine = ModelRegistry()
    set_registry(mine)
    assert get_registry() is mine


# ---------------------------------------------------------------------------
# admin routes


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_models_and_debug_registry_routes(transport):
    from mmlspark_tpu.serving.server import WorkerServer
    server = WorkerServer(transport=transport)
    base = server.address.rstrip("/")
    try:
        status, body = _post(base + "/models",
                             {"action": "load", "name": "web",
                              "version": "1"})
        assert status == 200 and body["loaded"]["state"] == "live"
        status, body = _post(base + "/models",
                             {"action": "load", "name": "web",
                              "version": "2", "canary_percent": 10})
        assert body["loaded"]["state"] == "canary"
        status, body = _post(base + "/models",
                             {"action": "tenant", "tenant": "acme",
                              "weight": 3})
        assert body["tenants"] == {"acme": 3.0}
        snap = _get_json(base + "/models")
        assert {v["label"] for v in snap["models"]["web"]} == \
            {"web@1", "web@2"}
        status, body = _post(base + "/models",
                             {"action": "promote", "name": "web",
                              "version": "2"})
        assert body["promoted"]["state"] == "live"
        debug = _get_json(base + "/debug/registry")
        assert "web" in debug["registry"]["models"]
        assert "admission" in debug and "size" in debug["admission"]
        assert debug["canary_verdicts"] == []    # nothing canary anymore
        # bad requests answer 400, not 500
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + "/models", {"action": "promote", "name": "web"})
        assert exc.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + "/models", {"action": "load", "name": "web",
                                     "version": "2"})
        assert exc.value.code == 400   # duplicate registration
        # registry digest rides the health digest (heartbeat federation)
        digest = server.health_digest()
        assert digest["registry"]["models"]["web"]["live"] == "2"
        assert "admission" in digest
    finally:
        server.close()


# ---------------------------------------------------------------------------
# acceptance: the multi-model multi-tenant cluster chaos drill


def test_multi_model_multi_tenant_cluster_chaos_drill():
    """2 models x 2 versions over a 3-worker cluster, tenants weighted
    3/2/1, seeded enqueue faults and a mid-rollout worker restart.
    Asserts: canary auto-rollback fires from real traffic, weighted-fair
    goodput shares track 3/2/1 within 15%, prefix-affine routing beats
    the round-robin baseline on placement hits, shadow pairs join, and
    every request receives a definitive response (zero loss)."""
    from mmlspark_tpu.io.http.schema import (EntityData, HTTPResponseData,
                                             StatusLineData)
    from mmlspark_tpu.serving.distributed import ServingCluster

    set_registry(ModelRegistry(min_requests=5, check_every=10_000))
    registry = get_registry()
    registry.set_tenant("acme", 3)
    registry.set_tenant("beta", 2)
    registry.set_tenant("gamma", 1)
    registry.load("alpha", "v1")
    registry.load("alpha", "v2", canary_percent=50)    # the bad canary
    registry.load("bravo", "v1")
    registry.load("bravo", "v2", canary_percent=0, shadow_percent=100)

    cluster = ServingCluster(3, reply_timeout=20.0)
    stop = threading.Event()
    pause = threading.Event()
    lock = threading.Lock()
    drained = []          # (owner_id, tenant, model_label, body_key)

    def engine():
        while not stop.is_set():
            if pause.is_set():
                time.sleep(0.005)
                continue
            for owner, cached in cluster.get_batch(8, timeout=0.02):
                try:
                    body = json.loads(
                        cached.request.entity.content.decode())
                except Exception:
                    body = {}
                with lock:
                    drained.append((owner, cached.tenant,
                                    cached.model_label, body.get("k")))
                status = 500 if cached.model_label == "alpha@v2" else 200
                cluster.reply(owner, cached.request_id, HTTPResponseData(
                    entity=EntityData.from_string('{"ok": true}'),
                    status_line=StatusLineData(status_code=status)))

    # engine starts PAUSED: phase 1 builds a standing backlog first, so
    # the DRR dequeue order is measured over all three tenants at once
    pause.set()
    eng = threading.Thread(target=engine, daemon=True)
    eng.start()

    attempted = [0]
    answered = [0]

    def post(worker, payload, headers=None):
        attempted[0] += 1
        try:
            status, _ = _post(worker.server.address, payload,
                              headers=headers)
        except urllib.error.HTTPError as e:
            status = e.code
            assert status in (429, 500, 503, 504)
        answered[0] += 1
        return status

    try:
        # ---- phase 1: weighted-fair goodput under a standing backlog ----
        statuses = []

        def park(tenant, idx):
            # stagger connects: 36 simultaneous SYNs overflow the HTTP
            # server's small accept backlog; a reset connection was never
            # parked, so retrying it is safe
            time.sleep(idx * 0.01)
            for attempt in range(3):
                try:
                    statuses.append(post(cluster.workers[0], {"x": 1},
                                         headers={"X-Mmlspark-Tenant":
                                                  tenant}))
                    return
                except (ConnectionResetError, urllib.error.URLError):
                    attempted[0] -= 1
                    time.sleep(0.2 * (attempt + 1))
            raise AssertionError(f"park({tenant}) never connected")

        threads = [threading.Thread(target=park, args=(t, i), daemon=True)
                   for i, t in enumerate(
                       t for t in ("acme", "beta", "gamma")
                       for _ in range(12))]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 15.0
        while cluster.workers[0].server._queue.qsize() < 36 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert cluster.workers[0].server._queue.qsize() == 36
        pause.clear()
        for t in threads:
            t.join(timeout=20.0)
        assert statuses.count(200) == 36
        # while all three tenants stayed backlogged (first 24 dequeues:
        # 4 full DRR rounds of 3+2+1), shares track weights
        first24 = [t for _, t, _, _ in drained[:24]]
        for tenant, want in (("acme", 0.5), ("beta", 1 / 3),
                             ("gamma", 1 / 6)):
            share = first24.count(tenant) / 24
            assert abs(share - want) / want <= 0.15, \
                f"{tenant}: {share} vs {want}"

        # ---- phase 2: prefix-affine routing vs round-robin baseline ----
        with lock:
            drained.clear()
        fwd = cluster.workers[0]
        fwd.enable_forwarding()
        keys = [f"{k:016x}" for k in range(8)]
        for rep in range(4):
            for k in keys:
                post(fwd, {"k": k}, headers={AFFINITY_HEADER: k})
        # pseudo-key group size 3 is coprime with the 2-peer round-robin
        # rotation, so unkeyed placement genuinely alternates per "key"
        for i in range(16):
            post(fwd, {"k": f"rr-{i % 3}"})
        fwd.disable_forwarding()
        deadline = time.monotonic() + 10.0
        while len(drained) < 48 and time.monotonic() < deadline:
            time.sleep(0.01)

        def modal_hit_rate(rows):
            by_key = {}
            for owner, _, _, k in rows:
                by_key.setdefault(k, []).append(owner)
            rates = [max(owners.count(o) for o in set(owners))
                     / len(owners) for owners in by_key.values()]
            return sum(rates) / len(rates)

        with lock:
            affine = [r for r in drained if r[3] in keys]
            baseline = [r for r in drained
                        if r[3] and r[3].startswith("rr-")]
        assert len(affine) == 32 and len(baseline) == 16
        # a prefix-keyed request lands on its ring owner every time; the
        # unkeyed baseline round-robins across both serving peers
        assert modal_hit_rate(affine) > modal_hit_rate(baseline)
        assert modal_hit_rate(affine) == 1.0
        # worker-0 forwards only to its 2 peers (never itself)
        assert {r[0] for r in affine} <= {"worker-1", "worker-2"}

        # ---- phase 3: canary rollout under chaos + worker restart ----
        get_injector().configure("enqueue:error:every=5")
        canary_statuses = []
        for i in range(24):
            canary_statuses.append(
                post(cluster.workers[i % 3], {"i": i},
                     headers={"X-Mmlspark-Model": "alpha",
                              "X-Mmlspark-Tenant": "acme"}))
        # mid-rollout chaos: worker-1 dies ungracefully and comes back
        cluster.restart_worker("worker-1")
        for w in cluster.workers:
            assert len(w._ring) == 2    # ring rebuilt, peers only
        for i in range(24):
            canary_statuses.append(
                post(cluster.workers[i % 3], {"i": i},
                     headers={"X-Mmlspark-Model": "alpha"}))
        assert canary_statuses.count(500) > 0, "chaos/canary must bite"
        # shadow traffic on bravo: incumbent serves, candidate mirrors
        for i in range(8):
            post(cluster.workers[i % 3], {"i": i},
                 headers={"X-Mmlspark-Model": "bravo"})
        time.sleep(0.2)

        # heartbeats run the canary check off the request path AND carry
        # the registry digest to the driver
        for w in cluster.workers:
            assert w.heartbeat()
        states = {v.version: v.state for v in registry.versions("alpha")}
        assert states["v2"] == "retired", "canary auto-rollback must fire"
        assert states["v1"] == "live"
        snap = registry.snapshot()
        assert snap["rollbacks"] and \
            snap["rollbacks"][-1]["reason"] != "manual"
        # shadow pairs joined; identical replies diff as "match"
        diffs = registry.shadow_diffs()
        assert diffs and all(d["verdict"] == "match" for d in diffs)
        # registry state federated: the driver sees every worker's digest
        for info in cluster.driver.workers().values():
            models = info["digest"]["registry"]["models"]
            assert models["alpha"]["live"] == "v1"
            assert models["alpha"]["canary"] is None   # rolled back
            assert models["bravo"]["canary"] == "v2"

        # ---- zero request loss ----
        assert answered[0] == attempted[0]
    finally:
        stop.set()
        get_injector().clear()
        eng.join(timeout=5.0)
        cluster.close()
