"""Tests for runtime utilities (reference: ClusterUtil, FaultToleranceUtils,
AsyncUtils, SharedVariable — SURVEY.md §2.1 core/utils row)."""

import time

import pytest

from mmlspark_tpu.utils import (SharedSingleton,
                                SharedVariable,
                                StopWatch,
                                device_for_partition,
                                global_devices,
                                local_devices,
                                map_buffered,
                                num_tasks,
                                retry_with_backoff,
                                retry_with_timeout)


def test_cluster_topology():
    assert len(global_devices()) == 8  # virtual CPU mesh from conftest
    assert num_tasks() == 8
    assert num_tasks(3) == 3
    devs = local_devices()
    assert device_for_partition(0) == devs[0]
    assert device_for_partition(len(devs)) == devs[0]


def test_retry_with_timeout():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient")
        return "ok"

    assert retry_with_timeout(flaky, timeout_s=5, retries=5) == "ok"
    with pytest.raises(RuntimeError):
        retry_with_timeout(lambda: (_ for _ in ()).throw(RuntimeError("x")),
                           timeout_s=1, retries=2)


def test_retry_with_backoff():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise ValueError("no")
        return 42

    assert retry_with_backoff(flaky, waits_ms=[0, 1, 1]) == 42


def test_buffered_await_order():
    out = list(map_buffered(lambda x: x * x, range(10), concurrency=3))
    assert out == [x * x for x in range(10)]


def test_shared_variable_single_creation():
    count = []
    sv = SharedVariable(lambda: count.append(1) or "v")
    assert sv.get() == "v" and sv.get() == "v"
    assert len(count) == 1
    SharedSingleton.reset()
    a = SharedSingleton.get("k", lambda: object())
    b = SharedSingleton.get("k", lambda: object())
    assert a is b


def test_stopwatch():
    sw = StopWatch()
    with sw:
        time.sleep(0.01)
    assert sw.elapsed_s >= 0.01
    sw.measure(lambda: time.sleep(0.005))
    assert sw.elapsed_s >= 0.015


class TestDeviceDetection:
    """One is_tpu() for every TPU gate (VERDICT r3 weakness #7: scattered
    `== "tpu"` string checks silently mislabel plugin platforms)."""

    def test_is_tpu_false_on_cpu(self):
        from mmlspark_tpu.utils import device
        assert device.is_tpu() is False       # conftest pins CPU backend
        platform, kind = device.device_info()
        assert platform == "cpu"

    def test_force_override(self, monkeypatch):
        from mmlspark_tpu.utils import device
        monkeypatch.setenv("MMLSPARK_TPU_FORCE_PLATFORM", "tpu")
        assert device.is_tpu() is True
        monkeypatch.setenv("MMLSPARK_TPU_FORCE_PLATFORM", "cpu")
        assert device.is_tpu() is False

    def test_generation_none_off_tpu(self):
        from mmlspark_tpu.utils import device
        assert device.tpu_generation() is None

    def test_gates_follow_is_tpu(self, monkeypatch):
        """flash-attention interpret mode and the Pallas histogram gate
        both funnel through is_tpu()."""
        from mmlspark_tpu.ops import pallas_kernels
        from mmlspark_tpu.ops.flash_attention import _auto_interpret
        monkeypatch.setenv("MMLSPARK_TPU_FORCE_PLATFORM", "tpu")
        assert _auto_interpret() is False
        monkeypatch.delenv("MMLSPARK_TPU_PALLAS", raising=False)
        assert pallas_kernels.histogram_enabled() is True
        monkeypatch.setenv("MMLSPARK_TPU_FORCE_PLATFORM", "cpu")
        assert _auto_interpret() is True
        assert pallas_kernels.histogram_enabled() is False


class TestPersistentCompileCache:
    @pytest.fixture(autouse=True)
    def _restore_jax_cache_config(self):
        # these are PROCESS-GLOBAL jax settings: leak one test's tmp_path
        # cache dir and every later compile in this process writes there
        import jax
        saved = (jax.config.jax_compilation_cache_dir,
                 jax.config.jax_persistent_cache_min_entry_size_bytes,
                 jax.config.jax_persistent_cache_min_compile_time_secs)
        yield
        jax.config.update("jax_compilation_cache_dir", saved[0])
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          saved[1])
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          saved[2])

    def test_enable_sets_jax_config(self, tmp_path):
        import jax

        from mmlspark_tpu.utils.jit_cache import enable_persistent_cache
        d = tmp_path / "xla-cache"
        assert enable_persistent_cache(str(d)) is True
        assert jax.config.jax_compilation_cache_dir == str(d)
        assert d.is_dir()

    def test_off_by_default_without_env(self, monkeypatch):
        import jax
        monkeypatch.delenv("MMLSPARK_TPU_COMPILE_CACHE", raising=False)
        jax.config.update("jax_compilation_cache_dir", None)
        from mmlspark_tpu.utils.jit_cache import enable_persistent_cache
        # no dir given and no env: reports current state, flips nothing on
        assert enable_persistent_cache() is False

    def test_cross_process_warmup_drops(self, tmp_path):
        """The point of the knob: a second process re-running the same
        jitted program must start measurably faster (executables are
        reloaded from disk instead of recompiled)."""
        import os
        import subprocess
        import sys

        child = (
            "import os, time\n"
            "os.environ.pop('JAX_PLATFORMS', None)\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import mmlspark_tpu\n"
            "import jax.numpy as jnp\n"
            "t0 = time.perf_counter()\n"
            "f = jax.jit(lambda x: (x @ x.T).sum())\n"
            "float(f(jnp.arange(256*64, dtype=jnp.float32)"
            ".reshape(256, 64)))\n"
            "print('compile_s=%.3f' % (time.perf_counter() - t0))\n")
        env = {**os.environ,
               "MMLSPARK_TPU_COMPILE_CACHE": str(tmp_path / "cc")}
        env.pop("JAX_PLATFORMS", None)
        times = []
        for _ in range(2):
            r = subprocess.run([sys.executable, "-c", child], env=env,
                               capture_output=True, text=True, timeout=300)
            assert r.returncode == 0, r.stderr[-500:]
            times.append(float(r.stdout.strip().split("compile_s=")[1]))
        assert times[1] < times[0]
