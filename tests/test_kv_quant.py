"""Quantized KV data plane (``ops/kv_quant.py`` + the int8/fp8 page path).

The invariants this file pins, in order of importance:

1. PARITY — the int8-page engine (kernel AND gather attention) decodes
   token-identical to the full-precision reference on short greedy
   decodes (fixed seeds); where exact parity is not the contract (fp8,
   long horizons) the dequant error is a bounded relative RMS.
2. BYTES — the quantized layout's HBM traffic is counter-asserted, not
   estimated: at hd=64 a decode tick reads >= 1.9x fewer KV bytes than
   the bf16 layout at identical geometry, ``device_bytes()`` is exact to
   the buffer arithmetic, and the residency reservation is sized to the
   QUANTIZED itemsize (values + scales), so a fixed ``kv_pages`` budget
   really holds ~2x the contexts.
3. AGREEMENT — every writer (prefill scatter, gather-impl writeback,
   mesh row write, fused in-kernel scatter) quantizes through ONE helper
   and produces bit-identical pages AND scales; CoW prefix sharing and
   ``compact()`` defrag remap scales through the same permutation as
   pages (wrong remap would corrupt the survivor's decode — asserted by
   reference-equal tokens after defrag).
4. STEADY STATE — the quantized engine path compiles once per program
   shape: zero recompiles across steady-state ticks, probed through the
   jit cache itself.
5. GOVERNANCE — the dequant-oracle probe lands in pool stats, the
   ``mmlspark_kv_quant_error`` gauge, the SLO model window and scorecard,
   and a canary whose window quant error breaches the incumbent's by
   ``quant_margin`` auto-rolls back.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.models.zoo.transformer import (
    TransformerConfig, decode_step_paged, decode_step_ragged,
    generate_cached, init_kv_cache, init_paged_cache, init_transformer,
    paged_gather, paged_scatter_rows)
from mmlspark_tpu.ops.compile_cache import jit_cache_size
from mmlspark_tpu.ops.kv_quant import (SCALE_DTYPE, dequantize_kv,
                                       kv_bytes_per_position, kv_qmax,
                                       kv_store_dtype, quantize_kv,
                                       resolve_kv_dtype, supports_fp8)
from mmlspark_tpu.ops.paged_attention import (_pool_write_rows_quant,
                                              paged_attention_window)
from mmlspark_tpu.serving.continuous import ContinuousDecoder
from mmlspark_tpu.serving.kv_pool import PagedKVPool

CFG = TransformerConfig(vocab=128, layers=2, d_model=64, heads=4, d_ff=128,
                        max_len=64, causal=True, norm="rmsnorm",
                        position="rope", dtype=jnp.float32)

QUANT_DTYPES = ["int8"] + (["fp8"] if supports_fp8() else [])


@pytest.fixture(scope="module")
def params():
    return init_transformer(CFG, seed=0)


def _drain(eng):
    while any(r is not None for r in eng._slot_req) or eng._waiting:
        eng.step()


def _reference(params, prompt, n):
    want = generate_cached(params, prompt[None, :], CFG, max_new_tokens=n)
    return list(np.asarray(want)[0, len(prompt):])


# ---------------------------------------------------------------------------
# the quantization helper itself


class TestQuantizeKV:
    @pytest.mark.parametrize("kv_dtype", QUANT_DTYPES)
    def test_roundtrip_relative_rms_bounded(self, kv_dtype):
        store = kv_store_dtype(kv_dtype)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(64, 4, 64)), jnp.float32)
        q, s = quantize_kv(x, store)
        assert q.dtype == store and s.dtype == SCALE_DTYPE
        assert s.shape == x.shape[:-1]
        d = np.asarray(dequantize_kv(q, s)) - np.asarray(x)
        rms = np.sqrt((d * d).mean()) / np.sqrt((np.asarray(x) ** 2).mean())
        # int8 symmetric absmax on gaussians sits well under 1%; fp8's
        # 3-bit mantissa under 4%
        assert rms < (0.01 if kv_dtype == "int8" else 0.04)

    def test_absmax_element_hits_qmax_exactly(self):
        # the row max maps onto the lattice edge — no clipping loss
        x = jnp.asarray([[1.0, -4.0, 2.0]], jnp.float32)
        q, s = quantize_kv(x, jnp.int8)
        assert int(np.asarray(q)[0, 1]) == -int(kv_qmax(jnp.int8))

    def test_zero_rows_quantize_to_zero_with_unit_scale(self):
        q, s = quantize_kv(jnp.zeros((3, 5), jnp.float32), jnp.int8)
        assert np.all(np.asarray(q) == 0)
        assert np.all(np.asarray(s, np.float32) == 1.0)

    def test_division_uses_the_stored_scale(self):
        # writers divide by the bf16-ROUNDED scale, so what the kernel
        # multiplies back is exactly what the writer divided by: the
        # roundtrip of the absmax element is exact, not off by the
        # scale-rounding epsilon
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
        q, s = quantize_kv(x, jnp.int8)
        amax_idx = np.argmax(np.abs(np.asarray(x)), axis=-1)
        got = np.asarray(dequantize_kv(q, s))
        for i, j in enumerate(amax_idx):
            ref = np.float32(np.asarray(s)[i]) * np.round(
                np.asarray(x)[i, j] / np.float32(np.asarray(s)[i]))
            assert got[i, j] == pytest.approx(float(ref), abs=0.0)

    def test_resolve_kv_dtype_canonicalizes_and_rejects(self):
        assert resolve_kv_dtype(None) is None
        assert resolve_kv_dtype("bf16") is None
        assert resolve_kv_dtype("int8") == "int8"
        if supports_fp8():
            assert resolve_kv_dtype("float8_e4m3fn") == "fp8"
        with pytest.raises(ValueError):
            resolve_kv_dtype("int4")


# ---------------------------------------------------------------------------
# bytes: the >= 1.9x acceptance number, counter-asserted


class TestByteAccounting:
    def test_bytes_per_position_ratio_at_hd64(self):
        # bf16 values: 2 bytes/elem; int8 + one bf16 scale per (pos, head):
        # 128 vs 66 bytes per head-position = 1.9394x
        bf16 = kv_bytes_per_position(8, 64, jnp.bfloat16, False)
        q = kv_bytes_per_position(8, 64, jnp.int8, True)
        assert bf16 / q >= 1.9

    def test_engine_tick_bytes_ratio_at_hd64(self):
        cfg = CFG._replace(d_model=256, d_ff=256, dtype=jnp.bfloat16)
        pool_b = PagedKVPool(cfg, num_pages=8, page_size=4,
                             residency=False)
        pool_q = PagedKVPool(cfg, num_pages=8, page_size=4,
                             kv_dtype="int8", residency=False)
        ratio = pool_b.bytes_per_position() / pool_q.bytes_per_position()
        assert ratio >= 1.9
        # the engine's per-tick gather-bytes figure scales by the same
        # factor (identical S * Lc geometry)
        params = init_transformer(cfg, seed=0)
        e_b = ContinuousDecoder(params, cfg, max_slots=2, max_len=32,
                                page_size=4)
        e_q = ContinuousDecoder(params, cfg, max_slots=2, max_len=32,
                                page_size=4, kv_dtype="int8")
        assert e_b._gather_bytes_tick / e_q._gather_bytes_tick >= 1.9

    @pytest.mark.parametrize("kv_dtype", QUANT_DTYPES)
    def test_device_bytes_exact(self, kv_dtype):
        pool = PagedKVPool(CFG, num_pages=9, page_size=4,
                           kv_dtype=kv_dtype, residency=False)
        hd = CFG.d_model // CFG.heads
        vals = 9 * CFG.heads * 4 * hd * jnp.dtype(pool.value_dtype).itemsize
        scales = 9 * CFG.heads * 4 * jnp.dtype(SCALE_DTYPE).itemsize
        assert pool.device_bytes() == 2 * CFG.layers * (vals + scales)
        # ...and it is what the buffers actually hold
        nbytes = sum(int(b.nbytes) for c in pool.buffers
                     for b in c.values())
        assert pool.device_bytes() == nbytes

    def test_residency_reserve_sized_to_quantized_itemsize(self):
        from mmlspark_tpu.core.residency import get_residency_manager
        mgr = get_residency_manager()
        before = mgr.reserved_bytes()
        pool = PagedKVPool(CFG, num_pages=9, page_size=4, kv_dtype="int8")
        assert mgr.reserved_bytes() - before == pool.device_bytes()
        del pool   # finalizer releases the reservation
        assert mgr.reserved_bytes() == before

    def test_fixed_page_budget_holds_more_contexts(self):
        # the POINT of the quantized plane: same kv_pages byte budget,
        # ~2x the max_len contexts resident at hd=64
        bf16 = kv_bytes_per_position(4, 64, jnp.bfloat16, False)
        q = kv_bytes_per_position(4, 64, jnp.int8, True)
        budget = 64 * 16 * bf16            # 64 bf16 pages of 16 positions
        ctx_b = budget // (64 * bf16)      # 64-token contexts that fit
        ctx_q = budget // (64 * q)
        assert ctx_q >= int(1.9 * ctx_b)


# ---------------------------------------------------------------------------
# parity: kernel and gather vs the full-precision oracle


class TestEngineParity:
    @pytest.mark.parametrize("attn", ["kernel", "gather"])
    def test_int8_greedy_token_parity_short_decodes(self, params, attn):
        eng = ContinuousDecoder(params, CFG, max_slots=3, max_len=48,
                                page_size=4, kv_dtype="int8",
                                paged_attn=attn)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, CFG.vocab, n).astype(np.int32)
                   for n in (3, 7, 12)]
        reqs = [eng.submit(p, max_new_tokens=9) for p in prompts]
        _drain(eng)
        for p, r in zip(prompts, reqs):
            assert r.tokens == _reference(params, p, 9)
        assert eng._kv.pages_in_use == 0

    @pytest.mark.parametrize("kv_dtype", QUANT_DTYPES)
    def test_logits_relative_rms_bounded(self, params, kv_dtype):
        """Where token identity is not the contract (fp8, deeper
        contexts): the quantized paged step's logits stay within a small
        relative RMS of the full-precision paged step's."""
        B, L, page, steps = 3, 16, 4, 8
        rng = np.random.default_rng(0)
        cache = init_kv_cache(CFG, B, L)
        for t in range(steps):
            tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
            _, cache = decode_step_ragged(
                params, tok, jnp.full((B,), t, jnp.int32), cache, CFG)
        n_pages = L // page
        bt = jnp.asarray(
            1 + np.arange(B)[:, None] * n_pages + np.arange(n_pages),
            jnp.int32)
        rows = [{"k": c["k"], "v": c["v"]} for c in cache]
        ref_pages = paged_scatter_rows(
            init_paged_cache(CFG, 1 + B * n_pages, page), rows, bt, page)
        q_pages = paged_scatter_rows(
            init_paged_cache(CFG, 1 + B * n_pages, page,
                             kv_dtype=kv_dtype), rows, bt, page)
        tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
        pos = jnp.full((B,), steps, jnp.int32)
        want, _ = decode_step_paged(params, tok, pos, ref_pages, bt, CFG,
                                    page_size=page, length=L,
                                    impl="gather")
        got, _ = decode_step_paged(params, tok, pos, q_pages, bt, CFG,
                                   page_size=page, length=L,
                                   impl="gather")
        w, g = np.asarray(want, np.float64), np.asarray(got, np.float64)
        rms = np.sqrt(((g - w) ** 2).mean()) / np.sqrt((w ** 2).mean())
        assert rms < (0.05 if kv_dtype == "int8" else 0.15)

    @pytest.mark.skipif(not supports_fp8(), reason="no float8_e4m3fn")
    def test_fp8_engine_decodes_with_bounded_probe_error(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, kv_dtype="fp8",
                                quant_probe=1)
        rng = np.random.default_rng(3)
        reqs = [eng.submit(rng.integers(1, CFG.vocab, n).astype(np.int32),
                           max_new_tokens=6) for n in (4, 9)]
        _drain(eng)
        assert all(len(r.tokens) == 6 and r.error is None for r in reqs)
        assert eng._kv.stats["quant_error_probes"] >= 1
        assert eng._kv.stats["quant_error_last"] < 0.1

    def test_bf16_oracle_path_untouched(self, params):
        """kv_dtype=None is the byte-exact oracle: pool buffers carry the
        model dtype, no scale arrays exist, and the gather round-trips
        the scatter bitwise."""
        pool = PagedKVPool(CFG, num_pages=8, page_size=4,
                           residency=False)
        assert pool.kv_dtype is None and pool.scale_dtype is None
        assert set(pool.buffers[0]) == {"k", "v"}
        B, L, page = 2, 8, 4
        rng = np.random.default_rng(2)
        cache = init_kv_cache(CFG, B, L)
        for t in range(4):
            tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
            _, cache = decode_step_ragged(
                params, tok, jnp.full((B,), t, jnp.int32), cache, CFG)
        bt = jnp.asarray(1 + np.arange(B)[:, None] * 2 + np.arange(2),
                         jnp.int32)
        pages = paged_scatter_rows(
            init_paged_cache(CFG, 1 + B * 2, page),
            [{"k": c["k"], "v": c["v"]} for c in cache], bt, page)
        for got, want in zip(paged_gather(pages, bt, L), cache):
            assert np.array_equal(np.asarray(got["k"]),
                                  np.asarray(want["k"]))


# ---------------------------------------------------------------------------
# writer agreement: one quantizer, bit-identical pages and scales


class TestWriterAgreement:
    @pytest.mark.parametrize("kv_dtype", QUANT_DTYPES)
    def test_fused_kernel_scatter_matches_pool_write_rows(self, kv_dtype):
        store = kv_store_dtype(kv_dtype)
        rng = np.random.default_rng(0)
        B, H, W, hd, page, NP = 3, 4, 2, 16, 8, 17
        q = jnp.asarray(rng.normal(size=(B, H, W, hd)), jnp.float32)
        kn = jnp.asarray(rng.normal(size=(B, H, W, hd)), jnp.float32)
        vn = jnp.asarray(rng.normal(size=(B, H, W, hd)), jnp.float32)
        kp = jnp.zeros((NP, H, page, hd), store)
        vp = jnp.zeros((NP, H, page, hd), store)
        ks = jnp.ones((NP, H, page), SCALE_DTYPE)
        vs = jnp.ones((NP, H, page), SCALE_DTYPE)
        bt = jnp.asarray(1 + 2 * np.arange(B)[:, None] + np.arange(2),
                         jnp.int32)
        pos = jnp.asarray([0, 3, 6], jnp.int32)
        active = jnp.asarray([True, True, True])
        _, kp1, vp1, ks1, vs1 = paged_attention_window(
            q, kn, vn, kp, vp, bt, pos, active=active,
            k_scale=ks, v_scale=vs)
        kp2, ks2 = _pool_write_rows_quant(kp, ks, kn, bt, pos, active)
        vp2, vs2 = _pool_write_rows_quant(vp, vs, vn, bt, pos, active)
        for a, b in ((kp1, kp2), (vp1, vp2), (ks1, ks2), (vs1, vs2)):
            # trash page 0 is scratch for both paths — exclude it
            assert np.array_equal(np.asarray(a)[1:], np.asarray(b)[1:])

    def test_prefill_scatter_matches_writeback(self, params):
        """paged_scatter_rows (prefill) and the gather-impl writeback
        quantize through the same helper: scattering the same rows twice
        is idempotent bit-for-bit."""
        B, L, page = 2, 8, 4
        rng = np.random.default_rng(4)
        cache = init_kv_cache(CFG, B, L)
        for t in range(6):
            tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
            _, cache = decode_step_ragged(
                params, tok, jnp.full((B,), t, jnp.int32), cache, CFG)
        bt = jnp.asarray(1 + np.arange(B)[:, None] * 2 + np.arange(2),
                         jnp.int32)
        rows = [{"k": c["k"], "v": c["v"]} for c in cache]
        once = paged_scatter_rows(
            init_paged_cache(CFG, 1 + B * 2, page, kv_dtype="int8"),
            rows, bt, page)
        twice = paged_scatter_rows(once, rows, bt, page)
        for a, b in zip(once, twice):
            for kk in a:
                assert np.array_equal(np.asarray(a[kk]),
                                      np.asarray(b[kk]))

    def test_quant_gather_dequantizes_through_scales(self):
        rng = np.random.default_rng(5)
        B, L, page = 2, 8, 4
        rows = [{"k": jnp.asarray(rng.normal(size=(B, CFG.heads, L, 16)),
                                  jnp.float32),
                 "v": jnp.asarray(rng.normal(size=(B, CFG.heads, L, 16)),
                                  jnp.float32)}
                for _ in range(CFG.layers)]
        bt = jnp.asarray(1 + np.arange(B)[:, None] * 2 + np.arange(2),
                         jnp.int32)
        pages = paged_scatter_rows(
            init_paged_cache(CFG, 1 + B * 2, page, kv_dtype="int8"),
            rows, bt, page)
        for got, want in zip(paged_gather(pages, bt, L), rows):
            g, w = np.asarray(got["k"]), np.asarray(want["k"])
            rms = np.sqrt(((g - w) ** 2).mean()) / np.sqrt((w ** 2).mean())
            assert rms < 0.01


# ---------------------------------------------------------------------------
# CoW + defrag: scales ride the same permutation


class TestSharingAndDefrag:
    def test_quantized_cow_prefix_sharing_token_parity(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, kv_dtype="int8")
        rng = np.random.default_rng(4)
        prefix = rng.integers(1, CFG.vocab, 10).astype(np.int32)
        p_b = np.concatenate(
            [prefix, rng.integers(1, CFG.vocab, 3).astype(np.int32)])
        ra = eng.submit(prefix, max_new_tokens=6, prefix_key="sys")
        while not ra.done:
            eng.step()
        shared = eng._kv.stats["prefix_share_hits"]
        rb = eng.submit(p_b, max_new_tokens=6, prefix_key="sys")
        while not rb.done:
            eng.step()
        assert eng._kv.stats["prefix_share_hits"] - shared == 2
        for p, r in ((prefix, ra), (p_b, rb)):
            assert r.tokens == _reference(params, p, 6)

    def test_quantized_defrag_remaps_scales_with_pages(self, params):
        """Retire-triggered compact(): the survivor's pages AND scales
        move through the same permutation — a scale left behind would
        rescale the survivor's keys and corrupt its (reference-equal)
        greedy decode."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, kv_dtype="int8",
                                defrag_threshold=1)
        rng = np.random.default_rng(7)
        p_short = rng.integers(1, CFG.vocab, 5).astype(np.int32)
        p_long = rng.integers(1, CFG.vocab, 9).astype(np.int32)
        rs = eng.submit(p_short, max_new_tokens=3)
        rl = eng.submit(p_long, max_new_tokens=24)
        while not (rs.done and rl.done):
            eng.step()
        assert eng._kv.stats["defrag_moves"] > 0
        assert rl.tokens == _reference(params, p_long, 24)
        assert eng._kv.pages_in_use == 0

    def test_pool_reset_rebuilds_scale_buffers(self):
        pool = PagedKVPool(CFG, num_pages=8, page_size=4,
                           kv_dtype="int8", residency=False)
        pool.alloc(3)
        pool.reset()
        assert pool.pages_in_use == 0
        assert set(pool.buffers[0]) == {"k", "v", "k_scale", "v_scale"}
        assert pool.buffers[0]["k"].dtype == jnp.int8
        assert pool.buffers[0]["k_scale"].dtype == SCALE_DTYPE


# ---------------------------------------------------------------------------
# steady state: zero recompiles on the quantized path


class TestSteadyState:
    def test_zero_steadystate_recompiles_per_kv_dtype(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, kv_dtype="int8")
        rng = np.random.default_rng(9)
        warm = [eng.submit(rng.integers(1, CFG.vocab, n).astype(np.int32),
                           max_new_tokens=4) for n in (3, 7)]
        _drain(eng)
        size = jit_cache_size(eng._tick)
        assert size is not None and size >= 1
        more = [eng.submit(rng.integers(1, CFG.vocab, n).astype(np.int32),
                           max_new_tokens=6) for n in (4, 6)]
        _drain(eng)
        assert jit_cache_size(eng._tick) == size

    def test_program_cache_keys_split_on_kv_dtype(self, params):
        """Two engines over the same geometry but different kv_dtype get
        DIFFERENT tick programs (the quantized pytree carries scale
        leaves) — sharing one would retrace per call."""
        e_q = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, kv_dtype="int8")
        e_b = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4)
        assert e_q._tick is not e_b._tick


# ---------------------------------------------------------------------------
# governance: probe -> gauge/SLO window -> scorecard -> canary rollback


class TestQuantGovernance:
    def setup_method(self):
        from mmlspark_tpu.observability.slo import reset_tracker
        reset_tracker()

    teardown_method = setup_method

    def test_probe_feeds_pool_stats_and_slo(self, params):
        from mmlspark_tpu.observability.slo import get_tracker
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, kv_dtype="int8",
                                quant_probe=1, slo_model="m@quant")
        rng = np.random.default_rng(3)
        r = eng.submit(rng.integers(1, CFG.vocab, 6).astype(np.int32),
                       max_new_tokens=4)
        _drain(eng)
        stats = eng._kv.stats
        assert stats["quant_error_probes"] >= 1
        assert 0.0 < stats["quant_error_last"] < 0.05
        assert stats["quant_error_max"] >= stats["quant_error_last"]
        win = get_tracker().model_window("m@quant")
        assert win["kv_quant_samples"] >= 1
        assert 0.0 < win["kv_quant_error"] < 0.05
        card = get_tracker().scorecard()
        assert "m@quant" in card["kv_quant"]
        assert card["kv_quant"]["m@quant"]["count"] >= 1

    def test_gauge_exports_last_probe(self, params):
        from mmlspark_tpu.observability.slo import _M_KV_QUANT
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=32,
                                page_size=4, kv_dtype="int8",
                                quant_probe=1, slo_model="m@g")
        rng = np.random.default_rng(5)
        eng.submit(rng.integers(1, CFG.vocab, 5).astype(np.int32),
                   max_new_tokens=3)
        _drain(eng)
        assert _M_KV_QUANT.labels(model="m@g").get() == pytest.approx(
            eng._kv.stats["quant_error_last"])

    def test_unquantized_engine_never_probes(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=32,
                                page_size=4, quant_probe=1)
        rng = np.random.default_rng(6)
        eng.submit(rng.integers(1, CFG.vocab, 5).astype(np.int32),
                   max_new_tokens=3)
        _drain(eng)
        assert eng._kv.stats["quant_error_probes"] == 0

    def test_canary_rolls_back_on_quant_error_breach(self):
        from mmlspark_tpu.observability.slo import get_tracker
        from mmlspark_tpu.serving.registry import (ModelRegistry,
                                                   reset_registry)
        reset_registry()
        tracker = get_tracker()
        reg = ModelRegistry(min_requests=5, quant_margin=0.05)
        reg.load("m", "bf16")
        reg.load("m", "quant", canary_percent=50)
        for _ in range(10):
            tracker.observe(transport="threaded", route="api",
                            model="m@bf16", seconds=0.01, error=False)
            tracker.observe(transport="threaded", route="api",
                            model="m@quant", seconds=0.01, error=False)
        # incumbent reports no quant error; the canary's dequant oracle
        # drifts past the margin
        for _ in range(4):
            tracker.note_kv_quant_error("m@quant", 0.2)
        verdicts = reg.check_canaries()
        assert "kv_quant_error" in verdicts[0]["breach"]
        assert {v.version: v.state
                for v in reg.versions("m")}["quant"] == "retired"
        assert "kv_quant_error" in reg.snapshot()["rollbacks"][-1]["reason"]
        reset_registry()

    def test_canary_within_quant_margin_stays(self):
        from mmlspark_tpu.observability.slo import get_tracker
        from mmlspark_tpu.serving.registry import (ModelRegistry,
                                                   reset_registry)
        reset_registry()
        tracker = get_tracker()
        reg = ModelRegistry(min_requests=5, quant_margin=0.05)
        reg.load("m", "bf16")
        reg.load("m", "quant", canary_percent=50)
        for _ in range(10):
            tracker.observe(transport="threaded", route="api",
                            model="m@bf16", seconds=0.01, error=False)
            tracker.observe(transport="threaded", route="api",
                            model="m@quant", seconds=0.01, error=False)
        for _ in range(4):
            tracker.note_kv_quant_error("m@quant", 0.004)   # healthy int8
        assert reg.check_canaries()[0]["breach"] is None
        assert {v.version: v.state
                for v in reg.versions("m")}["quant"] == "canary"
        assert reg.snapshot()["margins"]["quant_margin"] == 0.05
        reset_registry()
