"""Tests for KNN/BallTree, SAR recommendation, isolation forest, and data
balance (reference: nn/, recommendation/, isolationforest/, exploratory/)."""

import numpy as np

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.exploratory import (AggregateBalanceMeasure,
                                      DistributionBalanceMeasure,
                                      FeatureBalanceMeasure)
from mmlspark_tpu.isolationforest import IsolationForest
from mmlspark_tpu.nn import KNN, BallTree, ConditionalKNN, brute_force_knn
from mmlspark_tpu.recommendation import (SAR, RankingEvaluator,
                                         RankingTrainValidationSplit,
                                         RecommendationIndexer)


def _vec_col(X):
    col = np.empty(len(X), dtype=object)
    for i in range(len(X)):
        col[i] = X[i]
    return col


def test_balltree_matches_bruteforce():
    rng = np.random.default_rng(0)
    X = rng.normal(0, 1, (200, 5))
    tree = BallTree(X, leaf_size=16)
    q = rng.normal(0, 1, 5)
    idx, dist = tree.query(q, k=7)
    expected = np.argsort(np.linalg.norm(X - q, axis=1))[:7]
    np.testing.assert_array_equal(np.sort(idx), np.sort(expected))
    bf_idx, bf_dist = brute_force_knn(X, q[None], 7)
    np.testing.assert_array_equal(np.sort(bf_idx[0]), np.sort(expected))
    np.testing.assert_allclose(np.sort(dist), np.sort(bf_dist[0]), atol=1e-4)


def test_balltree_serialization_roundtrip():
    rng = np.random.default_rng(1)
    X = rng.normal(0, 1, (50, 3))
    tree = BallTree(X, labels=np.arange(50) % 3)
    tree2 = BallTree.from_tree(tree.to_tree())
    q = rng.normal(0, 1, 3)
    assert tree.query(q, k=3)[0] == tree2.query(q, k=3)[0]


def test_knn_model(tmp_save):
    rng = np.random.default_rng(2)
    X = rng.normal(0, 1, (100, 4))
    df = DataFrame({"features": _vec_col(X),
                    "values": [f"doc{i}" for i in range(100)]})
    model = KNN(k=3).fit(df)
    out = model.transform(df.head(5))
    matches = out["output"][0]
    assert len(matches) == 3
    assert matches[0]["value"] == "doc0"  # self is its own nearest
    assert matches[0]["distance"] <= matches[1]["distance"]
    model.save(tmp_save)
    from mmlspark_tpu.nn import KNNModel
    loaded = KNNModel.load(tmp_save)
    out2 = loaded.transform(df.head(5))
    assert out2["output"][0][0]["value"] == "doc0"


def test_conditional_knn_label_filter():
    rng = np.random.default_rng(3)
    X = rng.normal(0, 1, (60, 3))
    labels = np.array(["a", "b", "c"] * 20)
    df = DataFrame({"features": _vec_col(X), "labels": labels,
                    "values": list(range(60))})
    model = ConditionalKNN(k=4).fit(df)
    qdf = DataFrame({"features": _vec_col(X[:3]),
                     "conditioner": [["a"], ["b"], ["a", "c"]]})
    out = model.transform(qdf)
    assert all(m["label"] == "a" for m in out["output"][0])
    assert all(m["label"] == "b" for m in out["output"][1])
    assert all(m["label"] in ("a", "c") for m in out["output"][2])


def test_sar_recommends_similar_items():
    # users 0-4 like items {0,1}, users 5-9 like items {2,3}
    rows = []
    for u in range(5):
        rows += [(u, 0), (u, 1)]
    for u in range(5, 10):
        rows += [(u, 2), (u, 3)]
    rows.append((0, 0))  # duplicate interaction
    df = DataFrame({"user": [r[0] for r in rows],
                    "item": [r[1] for r in rows]})
    model = SAR(support_threshold=1).fit(df)
    S = np.asarray(model.get("item_similarity"))
    assert S[0, 1] > S[0, 2]  # co-liked items are similar
    recs = model.recommend_for_all_users(k=2, remove_seen=True)
    # user 0 saw items 0,1; recommendations must be from {2,3}
    assert set(recs["recommendations"][0]) <= {2, 3}

    scored = model.transform(DataFrame({"user": [0, 0], "item": [1, 2]}))
    assert scored["prediction"][0] > scored["prediction"][1]


def test_recommendation_indexer():
    df = DataFrame({"user": ["u1", "u2", "u1"], "item": ["iA", "iB", "iB"]})
    model = RecommendationIndexer().fit(df)
    out = model.transform(df)
    assert out["user_idx"].dtype == np.int64
    assert model.recover_user(out["user_idx"][0]) == "u1"
    assert model.recover_item(out["item_idx"][1]) == "iB"


def test_ranking_evaluator():
    df = DataFrame({
        "recommendations": [[1, 2, 3], [4, 5, 6]],
        "labels": [[1, 3], [9]],
    })
    ev = RankingEvaluator(k=3)
    row = ev.transform(df)
    assert 0.0 < row["ndcgAt"][0] < 1.0
    assert row["recallAtK"][0] == 0.5  # user1 fully recalled, user2 zero
    assert ev.evaluate(df) == row["ndcgAt"][0]


def test_ranking_train_validation_split():
    rng = np.random.default_rng(4)
    rows = [(u, i) for u in range(6) for i in range(8)
            if rng.random() > 0.3]
    df = DataFrame({"user": [r[0] for r in rows],
                    "item": [r[1] for r in rows]})
    tvs = RankingTrainValidationSplit(
        recommender=SAR(support_threshold=1), train_ratio=0.7, k=4, seed=0)
    model = tvs.fit(df)
    assert tvs.validation_metrics is not None
    assert set(tvs.validation_metrics) == {"ndcgAt", "map", "precisionAtk",
                                           "recallAtK"}
    out = model.transform(df)
    assert "recommendations" in out.columns


def test_isolation_forest_flags_outliers(tmp_save):
    rng = np.random.default_rng(5)
    inliers = rng.normal(0, 0.5, (200, 2))
    outliers = np.array([[6.0, 6.0], [-7.0, 7.0], [8.0, -6.0]])
    X = np.vstack([inliers, outliers])
    df = DataFrame({"features": _vec_col(X)})
    model = IsolationForest(num_estimators=50, max_samples=64,
                            contamination=3 / 203).fit(df)
    out = model.transform(df)
    scores = out["outlierScore"]
    assert scores[200:].min() > scores[:200].mean()
    assert out["prediction"][200:].sum() == 3
    model.save(tmp_save)
    from mmlspark_tpu.isolationforest import IsolationForestModel
    loaded = IsolationForestModel.load(tmp_save)
    np.testing.assert_allclose(loaded.transform(df)["outlierScore"], scores)


def test_feature_balance_measure():
    df = DataFrame({
        "gender": ["m"] * 6 + ["f"] * 4,
        "label": [1, 1, 1, 1, 0, 0, 1, 0, 0, 0],
    })
    out = FeatureBalanceMeasure(sensitive_cols=["gender"],
                                label_col="label").transform(df)
    row = out.to_rows()[0]
    # P(pos|f)=0.25, P(pos|m)=2/3 → dp = P(pos|ClassA) - P(pos|ClassB)
    assert abs(abs(row["dp"]) - abs(2 / 3 - 0.25)) < 1e-9


def test_distribution_and_aggregate_balance():
    df = DataFrame({"col": ["a"] * 8 + ["b"] * 2})
    dist = DistributionBalanceMeasure(sensitive_cols=["col"]).transform(df)
    assert dist["kl_divergence"][0] > 0
    assert 0 < dist["total_variation_dist"][0] <= 1

    uniform = DataFrame({"col": ["a", "b"] * 5})
    d2 = DistributionBalanceMeasure(sensitive_cols=["col"]).transform(uniform)
    assert abs(d2["kl_divergence"][0]) < 1e-12

    agg = AggregateBalanceMeasure(sensitive_cols=["col"]).transform(df)
    agg_u = AggregateBalanceMeasure(sensitive_cols=["col"]).transform(uniform)
    assert agg["atkinson_index"][0] > agg_u["atkinson_index"][0]
    assert abs(agg_u["theil_t_index"][0]) < 1e-12
