"""Flash attention kernel vs the reference O(S²) attention.

All on the CPU interpreter (`interpret=True` auto-selected off-TPU);
numerical parity is against ``parallel/ring.local_attention`` and hand-built
masked softmax. On-chip timing lives in ``scripts/bench_long_context.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.ops.flash_attention import (flash_attention,
                                              flash_attention_sharded)
from mmlspark_tpu.parallel.ring import local_attention


def _rand_qkv(rng, B=2, H=2, S=256, D=64, dtype=jnp.float32):
    q = jnp.asarray(rng.normal(0, 1, (B, H, S, D)), dtype)
    k = jnp.asarray(rng.normal(0, 1, (B, H, S, D)), dtype)
    v = jnp.asarray(rng.normal(0, 1, (B, H, S, D)), dtype)
    return q, k, v


def _reference(q, k, v, causal=False, kv_mask=None):
    S = q.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(q.shape[-1])
    neg = jnp.float32(-1e30)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, neg)
    if causal:
        tri = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(tri[None, None], s, neg)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v).astype(q.dtype)


def test_matches_reference_full(rng):
    q, k, v = _rand_qkv(rng)
    out = flash_attention(q, k, v)
    ref = local_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_causal(rng):
    q, k, v = _rand_qkv(rng, S=256)
    out = flash_attention(q, k, v, causal=True)
    ref = _reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kv_mask(rng):
    q, k, v = _rand_qkv(rng, B=2, S=256)
    mask = jnp.asarray(rng.random((2, 256)) > 0.3)
    out = flash_attention(q, k, v, kv_mask=mask)
    ref = _reference(q, k, v, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_unaligned_seq_pads(rng):
    q, k, v = _rand_qkv(rng, S=200)
    out = flash_attention(q, k, v)
    ref = local_attention(q, k, v)
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_short_seq_single_block(rng):
    q, k, v = _rand_qkv(rng, S=48, D=32)
    out = flash_attention(q, k, v)
    ref = local_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_fully_masked_rows_yield_zero_not_nan(rng):
    q, k, v = _rand_qkv(rng, B=1, H=1, S=128)
    mask = jnp.zeros((1, 128), bool)
    out = np.asarray(flash_attention(q, k, v, kv_mask=mask))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out, 0.0)


def test_bfloat16_io(rng):
    q, k, v = _rand_qkv(rng, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = local_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(rng, causal):
    q, k, v = _rand_qkv(rng, B=1, H=2, S=128, D=32)
    mask = jnp.asarray(rng.random((1, 128)) > 0.2)
    ct = jnp.asarray(rng.normal(0, 1, q.shape), jnp.float32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       kv_mask=mask) * ct)

    def f_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, causal=causal, kv_mask=mask) * ct)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")


def test_sharded_matches_unsharded(rng):
    from jax.sharding import Mesh

    devs = jax.devices("cpu")[:4]
    mesh = Mesh(np.array(devs).reshape(2, 2), ("dp", "tp"))
    q, k, v = _rand_qkv(rng, B=4, H=4, S=128, D=32)
    mask = jnp.asarray(rng.random((4, 128)) > 0.3)
    out = flash_attention_sharded(q, k, v, mesh, kv_mask=mask)
    ref = flash_attention(q, k, v, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_transformer_use_flash_matches_dense(rng):
    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     init_transformer,
                                                     transformer_apply)

    cfg = TransformerConfig(vocab=64, layers=2, d_model=64, heads=2,
                            d_ff=128, max_len=64, dtype=jnp.float32)
    params = init_transformer(cfg, seed=0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab, (2, 48)))
    mask = jnp.asarray(rng.random((2, 48)) > 0.2)
    dense = transformer_apply(params, ids, cfg, mask=mask)
    flash = transformer_apply(params, ids, cfg._replace(use_flash=True),
                              mask=mask)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)


def test_transformer_use_flash_on_mesh(rng):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     init_transformer,
                                                     shardings_for,
                                                     transformer_apply)

    devs = jax.devices("cpu")[:4]
    mesh = Mesh(np.array(devs).reshape(2, 2), ("dp", "tp"))
    cfg = TransformerConfig(vocab=64, layers=2, d_model=64, heads=2,
                            d_ff=128, max_len=64, dtype=jnp.float32,
                            use_flash=True)
    params = init_transformer(cfg, seed=0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab, (4, 64)))
    sharded_p = jax.device_put(params, shardings_for(params, mesh))
    sharded_ids = jax.device_put(ids, NamedSharding(mesh, P("dp", None)))
    out = jax.jit(lambda p, i: transformer_apply(p, i, cfg, mesh))(
        sharded_p, sharded_ids)
    ref = transformer_apply(params, ids, cfg._replace(use_flash=False))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_mismatched_block_sizes(rng):
    # regression: Sp must be a multiple of BOTH block sizes (LCM), else
    # trailing query rows are silently never computed
    q, k, v = _rand_qkv(rng, B=1, H=1, S=128, D=32)
    out = flash_attention(q, k, v, block_q=96, block_k=128)
    ref = local_attention(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_matches_dense_ring(rng):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mmlspark_tpu.parallel.ring import wrap_ring_attention

    devs = jax.devices("cpu")[:4]
    mesh = Mesh(np.array(devs), ("sp",))
    q, k, v = _rand_qkv(rng, B=1, H=2, S=256, D=32)
    sh = NamedSharding(mesh, P(None, None, "sp", None))
    args = [jax.device_put(x, sh) for x in (q, k, v)]
    ref = wrap_ring_attention(mesh, "sp", impl="ring")(*args)
    out = wrap_ring_attention(mesh, "sp", impl="ring_flash")(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    full = local_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_backward_matches_xla_backward(rng, monkeypatch, causal):
    """The Pallas dKV/dQ kernels and the lax.scan recompute are two
    implementations of the same math; gradients must agree tightly."""
    q, k, v = _rand_qkv(rng, B=2, H=2, S=256, D=32)
    mask = jnp.asarray(rng.random((2, 256)) > 0.25)
    ct = jnp.asarray(rng.normal(0, 1, q.shape), jnp.float32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       kv_mask=mask) * ct)

    monkeypatch.setenv("MMLSPARK_TPU_FLASH_BWD", "pallas")
    g_pallas = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("MMLSPARK_TPU_FLASH_BWD", "xla")
    g_xla = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_pallas, g_xla, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"d{name} mismatch")


def test_pallas_backward_unaligned_and_masked_rows(rng, monkeypatch):
    monkeypatch.setenv("MMLSPARK_TPU_FLASH_BWD", "pallas")
    q, k, v = _rand_qkv(rng, B=1, H=2, S=200, D=32)
    mask = jnp.asarray(rng.random((1, 200)) > 0.3)
    ct = jnp.asarray(rng.normal(0, 1, q.shape), jnp.float32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, kv_mask=mask) * ct)

    def f_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, kv_mask=mask) * ct)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")


class TestDecoderConfig:
    """Llama-style decoder switches: causal + RMSNorm + RoPE."""

    def _cfg(self, **kw):
        from mmlspark_tpu.models.zoo.transformer import TransformerConfig
        base = dict(vocab=64, layers=2, d_model=64, heads=2, d_ff=128,
                    max_len=32, dtype=jnp.float32, causal=True,
                    norm="rmsnorm", position="rope")
        base.update(kw)
        return TransformerConfig(**base)

    def test_causality(self, rng):
        from mmlspark_tpu.models.zoo.transformer import (init_transformer,
                                                         transformer_apply)
        cfg = self._cfg()
        params = init_transformer(cfg, seed=0)
        ids = jnp.asarray(rng.integers(0, 64, (1, 16)))
        out1 = np.asarray(transformer_apply(params, ids, cfg))
        ids2 = np.asarray(ids).copy()
        ids2[0, 10] = (ids2[0, 10] + 1) % 64   # perturb a future token
        out2 = np.asarray(transformer_apply(params, jnp.asarray(ids2), cfg))
        np.testing.assert_allclose(out1[0, :10], out2[0, :10], rtol=1e-5,
                                   atol=1e-5)
        assert not np.allclose(out1[0, 10:], out2[0, 10:])

    def test_flash_matches_dense_decoder(self, rng):
        from mmlspark_tpu.models.zoo.transformer import (init_transformer,
                                                         transformer_apply)
        cfg = self._cfg()
        params = init_transformer(cfg, seed=1)
        ids = jnp.asarray(rng.integers(0, 64, (2, 24)))
        dense = transformer_apply(params, ids, cfg)
        flash = transformer_apply(params, ids, cfg._replace(use_flash=True))
        np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                                   rtol=2e-4, atol=2e-4)

    def test_decoder_trains_on_mesh(self, rng):
        import functools
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from mmlspark_tpu.models.zoo.transformer import (init_transformer,
                                                         shardings_for,
                                                         train_step)
        devs = jax.devices("cpu")[:4]
        mesh = Mesh(np.array(devs).reshape(2, 2), ("dp", "tp"))
        cfg = self._cfg(use_flash=True)
        params = init_transformer(cfg, seed=0)
        params = jax.device_put(params, shardings_for(params, mesh))
        opt = jax.tree.map(jnp.zeros_like, params)
        ids = jax.device_put(rng.integers(0, 64, (4, 32)),
                             NamedSharding(mesh, P("dp", None)))
        labels = jax.device_put(rng.integers(0, 64, (4, 32)),
                                NamedSharding(mesh, P("dp", None)))
        step = jax.jit(functools.partial(train_step, cfg=cfg, mesh=mesh))
        _p, _o, loss = step(params, opt, ids, labels)
        assert np.isfinite(float(loss))


def test_decoder_generate_greedy_and_sampled(rng):
    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     generate,
                                                     init_transformer,
                                                     transformer_apply)

    cfg = TransformerConfig(vocab=64, layers=2, d_model=64, heads=2,
                            d_ff=128, max_len=32, dtype=jnp.float32,
                            causal=True, norm="rmsnorm", position="rope")
    params = init_transformer(cfg, seed=0)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)))
    out = generate(params, prompt, cfg, max_new_tokens=6)
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out[:, :4]), np.asarray(prompt))
    # greedy consistency: token at position t is the argmax of the logits
    # given the prefix up to t
    hidden = transformer_apply(params, out, cfg)
    logits = np.asarray(hidden.astype(jnp.float32) @ params["lm_head"]["w"])
    for t in range(4, 10):
        assert int(np.asarray(out)[0, t]) == int(logits[0, t - 1].argmax())
    # sampling runs and differs across seeds (vocab 64, 6 steps)
    s1 = generate(params, prompt, cfg, max_new_tokens=6, temperature=1.0,
                  seed=1)
    s2 = generate(params, prompt, cfg, max_new_tokens=6, temperature=1.0,
                  seed=2)
    assert not np.array_equal(np.asarray(s1), np.asarray(s2))
    # non-causal configs and empty prompts are rejected
    with pytest.raises(ValueError, match="causal"):
        generate(params, prompt, cfg._replace(causal=False))
    with pytest.raises(ValueError, match="prompt token"):
        generate(params, prompt[:, :0], cfg)


def test_generate_cached_matches_full_recompute(rng):
    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     generate,
                                                     generate_cached,
                                                     init_transformer)
    for position, norm in [("rope", "rmsnorm"), ("learned", "layernorm")]:
        cfg = TransformerConfig(vocab=64, layers=2, d_model=64, heads=2,
                                d_ff=128, max_len=32, dtype=jnp.float32,
                                causal=True, norm=norm, position=position)
        params = init_transformer(cfg, seed=3)
        prompt = jnp.asarray(rng.integers(0, 64, (2, 5)))
        full = np.asarray(generate(params, prompt, cfg, max_new_tokens=7))
        cached = np.asarray(generate_cached(params, prompt, cfg,
                                            max_new_tokens=7))
        np.testing.assert_array_equal(cached, full,
                                      err_msg=str((position, norm)))


def test_generate_cached_sampling_seed_compatible(rng):
    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     generate,
                                                     generate_cached,
                                                     init_transformer)
    cfg = TransformerConfig(vocab=64, layers=2, d_model=64, heads=2,
                            d_ff=128, max_len=32, dtype=jnp.float32,
                            causal=True, norm="rmsnorm", position="rope")
    params = init_transformer(cfg, seed=4)
    prompt = jnp.asarray(rng.integers(0, 64, (1, 4)))   # P_len > 1
    a = np.asarray(generate(params, prompt, cfg, max_new_tokens=6,
                            temperature=1.0, seed=9))
    b = np.asarray(generate_cached(params, prompt, cfg, max_new_tokens=6,
                                   temperature=1.0, seed=9))
    np.testing.assert_array_equal(a, b)


def test_generate_top_k_top_p(rng):
    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     generate,
                                                     generate_cached,
                                                     init_transformer,
                                                     transformer_apply)
    cfg = TransformerConfig(vocab=64, layers=2, d_model=64, heads=2,
                            d_ff=128, max_len=32, dtype=jnp.float32,
                            causal=True, norm="rmsnorm", position="rope")
    params = init_transformer(cfg, seed=5)
    prompt = jnp.asarray(rng.integers(0, 64, (1, 4)))
    # top_k=1 at any temperature is greedy
    greedy = np.asarray(generate(params, prompt, cfg, max_new_tokens=6))
    k1 = np.asarray(generate(params, prompt, cfg, max_new_tokens=6,
                             temperature=1.0, top_k=1, seed=11))
    np.testing.assert_array_equal(greedy, k1)
    # every sampled token under top_k=3 is one of the 3 best given its prefix
    k3 = np.asarray(generate(params, prompt, cfg, max_new_tokens=6,
                             temperature=1.5, top_k=3, seed=7))
    hidden = transformer_apply(params, jnp.asarray(k3), cfg)
    logits = np.asarray(hidden.astype(jnp.float32) @ params["lm_head"]["w"])
    for t in range(4, 10):
        top3 = np.argsort(logits[0, t - 1])[-3:]
        assert int(k3[0, t]) in top3, (t, k3[0, t], top3)
    # cached path agrees with the full path under top_k/top_p sampling
    a = np.asarray(generate(params, prompt, cfg, max_new_tokens=6,
                            temperature=1.0, top_k=5, top_p=0.9, seed=3))
    b = np.asarray(generate_cached(params, prompt, cfg, max_new_tokens=6,
                                   temperature=1.0, top_k=5, top_p=0.9,
                                   seed=3))
    np.testing.assert_array_equal(a, b)


def test_top_p_mass_renormalized_after_top_k():
    """Nucleus mass must come from the top-k-filtered renormalized
    distribution (the HF convention). Discriminating case: probs
    [.6, .25, .15], top_k=2, top_p=0.7 — full-mass cum is [.6, .85, 1.0]
    so the pre-filter convention keeps ranks {0, 1}; top-2-renormalized
    cum is [.706, 1.0] so the HF convention keeps ONLY the argmax."""
    from mmlspark_tpu.models.zoo.transformer import _sample_logits
    logits = jnp.log(jnp.asarray([[0.6, 0.25, 0.15]], jnp.float32))
    seen = {int(_sample_logits(logits, jax.random.PRNGKey(s), 1.0,
                               top_k=2, top_p=0.7)[0])
            for s in range(64)}
    assert seen == {0}, seen


def test_generate_oversized_top_k_is_noop(rng):
    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     generate,
                                                     init_transformer)
    cfg = TransformerConfig(vocab=64, layers=2, d_model=64, heads=2,
                            d_ff=128, max_len=32, dtype=jnp.float32,
                            causal=True, norm="rmsnorm", position="rope")
    params = init_transformer(cfg, seed=6)
    prompt = jnp.asarray(rng.integers(0, 64, (1, 3)))
    plain = np.asarray(generate(params, prompt, cfg, max_new_tokens=5,
                                temperature=1.0, seed=2))
    big_k = np.asarray(generate(params, prompt, cfg, max_new_tokens=5,
                                temperature=1.0, top_k=10_000, seed=2))
    np.testing.assert_array_equal(plain, big_k)


class TestAttentionMemoryPlanner:
    """plan_attention_impl is calibrated against the r4/r5 on-chip
    campaigns: every feasibility verdict below matches an observed
    success (timed row) or failure (compile-time abort surfaced as a
    remote-compile 500) at B=1, H=12, D=64 on a 16 GB v5e."""

    HBM = 16e9

    def plan(self, impl, direction, S, sp=1):
        from mmlspark_tpu.parallel.ring import plan_attention_impl
        return plan_attention_impl(impl, direction, 1, 12, S,
                                   sp=sp, hbm_bytes=self.HBM)

    def test_observed_successes(self):
        # (impl, direction, S) legs that produced timed campaign rows
        for impl, direction, S in [
                ("full", "fwd", 4096), ("full", "bwd", 4096),
                ("full", "fwd", 16384),
                ("ring", "fwd", 16384), ("ring", "bwd", 16384),
                ("ulysses", "fwd", 16384),
                ("flash", "bwd", 65536), ("ring_flash", "bwd", 65536)]:
            assert self.plan(impl, direction, S)["feasible"], \
                (impl, direction, S)

    def test_observed_compile_failures(self):
        for impl, direction, S in [
                ("full", "bwd", 16384), ("ulysses", "bwd", 16384),
                ("full", "fwd", 65536), ("ring", "fwd", 65536),
                ("ulysses", "fwd", 65536), ("full", "bwd", 65536),
                ("ring", "bwd", 65536), ("ulysses", "bwd", 65536)]:
            assert not self.plan(impl, direction, S)["feasible"], \
                (impl, direction, S)

    def test_ring_min_sp_at_64k(self):
        # a 4-chip ring makes the dense 64k hops fit (12.9 GB/chip)
        assert self.plan("ring", "fwd", 65536)["min_sp"] == 4

    def test_full_never_shards(self):
        assert self.plan("full", "fwd", 65536)["min_sp"] is None

    def test_flash_is_linear_memory(self):
        from mmlspark_tpu.parallel.ring import attention_transient_bytes
        assert attention_transient_bytes(
            "ring_flash", "bwd", 1, 12, 1 << 20) == 0
