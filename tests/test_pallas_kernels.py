"""Pallas histogram kernel vs the XLA segment-sum reference (interpret mode
on CPU — the driver's real-TPU bench exercises the compiled path)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.ops.pallas_kernels import (histogram_enabled,
                                             level_histogram_pallas)


def _reference_hist(xb, node_rel, g, h, w, n_nodes, n_bins):
    data = jnp.stack([jnp.asarray(g), jnp.asarray(h), jnp.asarray(w)], axis=-1)

    def per_feature(bins_col):
        seg = jnp.asarray(node_rel) * n_bins + bins_col.astype(jnp.int32)
        return jax.ops.segment_sum(data, seg, num_segments=n_nodes * n_bins)

    hist = jax.vmap(per_feature, in_axes=1)(jnp.asarray(xb))
    return np.transpose(np.asarray(hist).reshape(xb.shape[1], n_nodes,
                                                 n_bins, 3), (1, 0, 2, 3))


@pytest.mark.parametrize("n,F,n_nodes,n_bins", [
    (700, 5, 1, 16),       # root level, ragged row count
    (1024, 3, 4, 32),      # mid level
    (333, 2, 8, 256),      # full default bin budget
])
def test_pallas_histogram_matches_segment_sum(rng, n, F, n_nodes, n_bins):
    xb = rng.integers(0, n_bins, (n, F)).astype(np.int32)
    node = rng.integers(0, n_nodes, n).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32)
    w = (rng.random(n) > 0.1).astype(np.float32)   # some bagged-out rows
    got = np.asarray(level_histogram_pallas(
        jnp.asarray(xb), jnp.asarray(node), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(w), n_nodes, n_bins, row_block=256, interpret=True))
    want = _reference_hist(xb, node, g, h, w, n_nodes, n_bins)
    assert got.shape == want.shape == (n_nodes, F, n_bins, 3)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_pallas_histogram_deep_level(rng):
    """A 32-node level — the depth where the v1 (3, R) @ (R, nodes*bpad)
    layout regressed — stays correct under the v2 stats-as-lanes layout,
    including with the tight-VMEM autotuned row block."""
    n, F, n_nodes, n_bins = 400, 2, 32, 32
    xb = rng.integers(0, n_bins, (n, F)).astype(np.int32)
    node = rng.integers(0, n_nodes, n).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32)
    w = np.ones(n, np.float32)
    got = np.asarray(level_histogram_pallas(
        jnp.asarray(xb), jnp.asarray(node), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(w), n_nodes, n_bins, interpret=True,
        combined_limit=256 * 1024))    # small budget -> minimum row block
    want = _reference_hist(xb, node, g, h, w, n_nodes, n_bins)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_bf16_stats_close_to_f32(rng):
    """stats_dtype=bfloat16 (use_quantized_grad): sums accumulate in f32,
    so the histogram matches the exact one to bf16 input-rounding error,
    and the 0/1 count channel stays EXACT (bf16 represents 0/1 exactly)."""
    n, F, n_nodes, n_bins = 900, 4, 4, 32
    xb = rng.integers(0, n_bins, (n, F)).astype(np.int32)
    node = rng.integers(0, n_nodes, n).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32)
    w = (rng.random(n) > 0.1).astype(np.float32)
    got = np.asarray(level_histogram_pallas(
        jnp.asarray(xb), jnp.asarray(node), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(w), n_nodes, n_bins, row_block=256, interpret=True,
        stats_dtype="bfloat16"))
    want = _reference_hist(xb, node, g, h, w, n_nodes, n_bins)
    np.testing.assert_array_equal(got[..., 2], want[..., 2])   # counts exact
    np.testing.assert_allclose(got[..., :2], want[..., :2],
                               rtol=2e-2, atol=2e-2)           # bf16 rounding


def test_gbdt_quantized_grad_trains(rng, monkeypatch):
    """use_quantized_grad end-to-end under forced Pallas interpret: the
    bf16 path must keep learning (guards the f32-accumulation contract)."""
    monkeypatch.setenv("MMLSPARK_TPU_PALLAS", "1")
    from mmlspark_tpu.models.gbdt.train import train
    n = 600
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    b = train({"objective": "binary", "num_iterations": 10, "num_leaves": 8,
               "max_bin": 32, "use_quantized_grad": True}, X, y)
    acc = ((b.predict(X) > 0.5) == y).mean()
    assert acc > 0.9


def test_histogram_enabled_env(monkeypatch):
    monkeypatch.setenv("MMLSPARK_TPU_PALLAS", "1")
    assert histogram_enabled()
    monkeypatch.setenv("MMLSPARK_TPU_PALLAS", "0")
    assert not histogram_enabled()
    monkeypatch.setenv("MMLSPARK_TPU_PALLAS", "auto")
    from mmlspark_tpu.utils.device import is_tpu
    assert histogram_enabled() == is_tpu()


def test_gbdt_training_with_pallas_interpret(rng, monkeypatch):
    """End-to-end GBDT fit with MMLSPARK_TPU_PALLAS=1 off-TPU: the trainer
    must select interpret mode itself (force-on contract)."""
    monkeypatch.setenv("MMLSPARK_TPU_PALLAS", "1")

    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier

    n = 400
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    feats = np.empty(n, dtype=object)
    for i in range(n):
        feats[i] = X[i]
    df = DataFrame({"features": feats, "label": y})
    clf = LightGBMClassifier(num_iterations=10, num_leaves=8, max_bin=32)
    model = clf.fit(df)
    out = model.transform(df)
    acc = (out["prediction"] == y).mean()
    assert acc > 0.9


class TestPallasPreferred:
    """Per-level builder choice (v5e-measured crossovers)."""

    def test_shallow_levels_prefer_pallas(self):
        from mmlspark_tpu.ops.pallas_kernels import pallas_preferred
        assert pallas_preferred(1_000_000, 8, 255)

    def test_deep_levels_prefer_pallas_since_v2(self):
        # the v1 layout lost to segment_sum at 32 nodes (922 vs 488 ms);
        # the v2 stats-as-lanes layout's cost is ~flat in node count until
        # 3*nodes fills the 128 lanes, so 32-node levels now take the kernel
        from mmlspark_tpu.ops.pallas_kernels import pallas_preferred
        assert pallas_preferred(1_000_000, 32, 255)

    def test_extreme_depth_prefers_segment_sum(self):
        from mmlspark_tpu.ops.pallas_kernels import pallas_preferred
        import os
        prev = os.environ.pop("MMLSPARK_TPU_PALLAS", None)
        try:
            assert not pallas_preferred(1_000_000, 512, 255)
        finally:
            if prev is not None:
                os.environ["MMLSPARK_TPU_PALLAS"] = prev

    def test_huge_row_counts_always_pallas(self):
        # segment_sum stops compiling entirely (57 GB one-hot temp)
        from mmlspark_tpu.ops.pallas_kernels import pallas_preferred
        assert pallas_preferred(4_000_000, 32, 255)

    def test_force_flag_wins(self, monkeypatch):
        from mmlspark_tpu.ops.pallas_kernels import pallas_preferred
        monkeypatch.setenv("MMLSPARK_TPU_PALLAS", "1")
        assert pallas_preferred(1_000, 64, 255)
