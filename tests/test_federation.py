"""Cluster-wide metrics federation: the compact heartbeat snapshot and
its size shedding, exact histogram merging, counter-reset detection
(merged counters never go backwards across a worker restart), departed-
worker history retention, the cluster SLO scorecard, the driver's
/workers + /debug/cluster routes, and the 3-worker ServingCluster
end-to-end drill under seeded chaos with a mid-run restart_worker.
"""

import json
import threading
import time
import urllib.request

import pytest

from mmlspark_tpu.observability import reset_all, snapshot
from mmlspark_tpu.observability.federation import (DEFAULT_MAX_BYTES,
                                                   FEDERATION_INTERVAL_ENV,
                                                   FEDERATION_MAX_BYTES_ENV,
                                                   ClusterAggregator,
                                                   snapshot_interval,
                                                   worker_snapshot)
from mmlspark_tpu.observability.ledger import reset_ledger
from mmlspark_tpu.observability.slo import get_tracker, reset_tracker
from mmlspark_tpu.observability.watchdog import reset_watchdog
from mmlspark_tpu.reliability import get_injector, reset_breakers
from mmlspark_tpu.serving.distributed import ServingCluster


@pytest.fixture(autouse=True)
def _clean_slate():
    reset_ledger()
    reset_tracker()
    reset_watchdog()
    reset_breakers()
    reset_all()
    get_injector().clear()
    yield
    reset_ledger()
    reset_tracker()
    reset_watchdog()
    reset_breakers()
    get_injector().clear()
    reset_all()


def _counter(name, value, **labels):
    return {"type": "counter", "help": "h",
            "series": [{"labels": labels, "value": value}]}


def _hist(name, total, count, buckets, **labels):
    return {"type": "histogram", "help": "h",
            "series": [{"labels": labels, "sum": total, "count": count,
                        "buckets": buckets}]}


def _telemetry(metrics=None, slo_classes=None):
    return {"metrics": metrics or {},
            "slo": {"classes": slo_classes or []}}


# ---------------------------------------------------------------------------
# worker snapshot + knobs


def test_worker_snapshot_carries_counters_histograms_and_slo_only():
    from mmlspark_tpu.observability import counter, gauge
    counter("fed_test_ctr", "h").inc(3)
    gauge("fed_test_gauge", "h").set(7)
    get_tracker().observe("threaded", "api", seconds=0.01)
    snap = worker_snapshot()
    assert snap["metrics"]["fed_test_ctr"]["type"] == "counter"
    assert "fed_test_gauge" not in snap["metrics"]   # gauges don't merge
    assert snap["slo"]["classes"], "SLO totals always ride along"
    row = snap["slo"]["classes"][0]
    assert set(row) >= {"transport", "route", "model", "tenant", "total",
                        "errors_total", "shed_total"}
    json.dumps(snap)


def test_worker_snapshot_sheds_histograms_then_metrics():
    from mmlspark_tpu.observability import counter, histogram
    counter("fed_shed_ctr", "h").inc()
    histogram("fed_shed_hist", "h").observe(0.5)
    full = worker_snapshot()
    assert "fed_shed_hist" in full["metrics"]
    mid = worker_snapshot(max_bytes=len(json.dumps(full)) - 1)
    assert all(m["type"] == "counter" for m in mid["metrics"].values())
    tiny = worker_snapshot(max_bytes=1)
    assert tiny["metrics"] == {}
    assert tiny["slo"]["slo_classes_only"] is True


def test_env_knobs(monkeypatch):
    assert snapshot_interval() == 0.0
    monkeypatch.setenv(FEDERATION_INTERVAL_ENV, "2.5")
    assert snapshot_interval() == 2.5
    monkeypatch.setenv(FEDERATION_INTERVAL_ENV, "-1")
    assert snapshot_interval() == -1.0
    monkeypatch.setenv(FEDERATION_INTERVAL_ENV, "bogus")
    assert snapshot_interval() == 0.0
    from mmlspark_tpu.observability import histogram
    histogram("fed_env_hist", "h").observe(0.5)
    monkeypatch.setenv(FEDERATION_MAX_BYTES_ENV, "1")
    assert worker_snapshot()["metrics"] == {}
    monkeypatch.setenv(FEDERATION_MAX_BYTES_ENV, str(DEFAULT_MAX_BYTES))
    assert "fed_env_hist" in worker_snapshot()["metrics"]


# ---------------------------------------------------------------------------
# merging


def test_histogram_merge_is_exact():
    agg = ClusterAggregator()
    agg.ingest("w0", _telemetry({"lat": _hist(
        "lat", 1.5, 3, {"0.1": 1, "1.0": 2, "+Inf": 3}, t="a")}))
    agg.ingest("w1", _telemetry({"lat": _hist(
        "lat", 2.5, 2, {"0.1": 0, "1.0": 1, "+Inf": 2}, t="a")}))
    # w0 reports again with MORE data — only the delta lands
    agg.ingest("w0", _telemetry({"lat": _hist(
        "lat", 2.0, 4, {"0.1": 1, "1.0": 3, "+Inf": 4}, t="a")}))
    merged = agg.merged_snapshot()["lat"]["series"][0]
    assert merged["sum"] == pytest.approx(4.5)       # 2.0 + 2.5, exactly
    assert merged["count"] == pytest.approx(6)       # 4 + 2
    assert merged["buckets"] == {"0.1": 1.0, "1.0": 4.0, "+Inf": 6.0}
    text = agg.render()
    assert '# TYPE lat histogram' in text
    assert 'lat_bucket{le="+Inf",t="a"} 6' in text or \
        'lat_bucket{t="a",le="+Inf"} 6' in text
    assert 'lat_count{t="a"} 6' in text


def test_counter_reset_never_drives_merge_backwards():
    agg = ClusterAggregator()
    agg.ingest("w0", _telemetry({"req": _counter("req", 10.0)}))
    agg.ingest("w1", _telemetry({"req": _counter("req", 4.0)}))
    before = agg.merged_snapshot()["req"]["series"][0]["value"]
    assert before == pytest.approx(14.0)
    # w0 restarts: its cumulative counter starts over from 3
    agg.ingest("w0", _telemetry({"req": _counter("req", 3.0)}))
    after = agg.merged_snapshot()["req"]["series"][0]["value"]
    assert after == pytest.approx(17.0)              # 10 (kept) + 3 + 4
    assert after >= before
    assert agg.resets == 1
    # the fresh incarnation keeps accumulating normally
    agg.ingest("w0", _telemetry({"req": _counter("req", 8.0)}))
    assert agg.merged_snapshot()["req"]["series"][0]["value"] == \
        pytest.approx(22.0)


def test_histogram_reset_detected_via_count():
    agg = ClusterAggregator()
    agg.ingest("w0", _telemetry({"h": _hist("h", 5.0, 5, {"+Inf": 5})}))
    agg.ingest("w0", _telemetry({"h": _hist("h", 1.0, 1, {"+Inf": 1})}))
    s = agg.merged_snapshot()["h"]["series"][0]
    assert s["count"] == pytest.approx(6)
    assert s["sum"] == pytest.approx(6.0)
    assert agg.resets == 1


def test_forget_keeps_history_but_drops_live_worker():
    agg = ClusterAggregator()
    agg.ingest("w0", _telemetry({"req": _counter("req", 5.0)}))
    agg.ingest("w1", _telemetry({"req": _counter("req", 2.0)}))
    agg.forget("w0")
    assert agg.merged_snapshot()["req"]["series"][0]["value"] == \
        pytest.approx(7.0)                           # history not deducted
    assert agg.scorecard()["workers"] == 1


def test_malformed_telemetry_is_skipped_not_fatal():
    agg = ClusterAggregator()
    agg.ingest("w0", "garbage")
    agg.ingest("w0", _telemetry({"bad": "not-a-dict",
                                 "gauge": {"type": "gauge", "series": []},
                                 "ok": _counter("ok", 1.0)}))
    agg.ingest("w0", {"metrics": {"x": {"type": "counter", "series": [
        {"labels": {"a": "b"}, "value": "NaN-ish"}, "not-a-dict"]}},
        "slo": {"classes": ["junk", {"transport": "t", "total": 2,
                                     "errors_total": 1}]}})
    snap = agg.merged_snapshot()
    assert snap["ok"]["series"][0]["value"] == pytest.approx(1.0)
    assert "gauge" not in snap
    card = agg.scorecard()
    assert card["classes"][0]["total"] == 2
    assert card["classes"][0]["availability"] == pytest.approx(0.5)


def test_scorecard_merges_slo_totals_with_reset_protection():
    agg = ClusterAggregator()
    row = {"transport": "threaded", "route": "api", "model": "default",
           "tenant": "acme", "total": 10, "errors_total": 2,
           "shed_total": 1}
    agg.ingest("w0", _telemetry(slo_classes=[row]))
    agg.ingest("w1", _telemetry(slo_classes=[dict(row, total=4,
                                                  errors_total=0,
                                                  shed_total=0)]))
    # w0 restarts and reports a smaller cumulative total
    agg.ingest("w0", _telemetry(slo_classes=[dict(row, total=2,
                                                  errors_total=1,
                                                  shed_total=0)]))
    card = agg.scorecard()
    assert card["workers"] == 2
    assert card["snapshots"] == 3
    assert card["counter_resets"] >= 1
    cls = card["classes"][0]
    assert cls["tenant"] == "acme"
    assert cls["total"] == 16                        # 10 + 4 + 2
    assert cls["errors_total"] == 3
    assert cls["availability"] == pytest.approx(13 / 16)


def test_cluster_driver_metrics_mirror_ingest():
    agg = ClusterAggregator()
    agg.ingest("w0", _telemetry({"req": _counter("req", 5.0)}))
    agg.ingest("w0", _telemetry({"req": _counter("req", 1.0)}))
    snap = snapshot()
    total = sum(s["value"]
                for s in snap["mmlspark_cluster_snapshots_total"]["series"])
    assert total == 2
    resets = sum(s["value"] for s in
                 snap["mmlspark_cluster_counter_resets_total"]["series"])
    assert resets == 1


# ---------------------------------------------------------------------------
# 3-worker end-to-end drill


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _post(url, payload, headers=None, timeout=15.0):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode() or "{}")


def test_three_worker_cluster_federation_e2e(monkeypatch):
    """Acceptance drill: requests over a 3-worker cluster under seeded
    enqueue faults plus one restart_worker. /debug/cluster's merged
    requests_total must equal the sum of the per-worker reported counters
    and never decrease; /workers carries live health digests; the
    scorecard sees every request."""
    from mmlspark_tpu.io.http.schema import (EntityData, HTTPResponseData,
                                             StatusLineData)

    monkeypatch.setenv(FEDERATION_INTERVAL_ENV, "0")
    get_injector().configure("enqueue:error:every=5")
    cluster = ServingCluster(3, reply_timeout=15.0)
    stop = threading.Event()
    try:
        def engine():
            while not stop.is_set():
                for owner, cached in cluster.get_batch(16, timeout=0.05):
                    resp = HTTPResponseData(
                        entity=EntityData.from_string(
                            json.dumps({"ok": True})),
                        status_line=StatusLineData(status_code=200))
                    cluster.reply(owner, cached.request_id, resp)

        eng = threading.Thread(target=engine, daemon=True)
        eng.start()

        def drive(n):
            ok = faulted = 0
            for i in range(n):
                w = cluster.workers[i % 3]
                try:
                    status, _ = _post(w.server.address, {"i": i},
                                      headers={"X-Mmlspark-Tenant": "acme"})
                    ok += status == 200
                except urllib.error.HTTPError as e:
                    assert e.code in (500, 503)
                    faulted += 1
            return ok, faulted

        ok1, faulted1 = drive(30)
        assert ok1 and faulted1, "chaos spec must actually bite"
        time.sleep(0.2)          # let post-reply counter bumps land
        for w in cluster.workers:
            assert w.heartbeat()
        url = cluster.driver.url
        view1 = _get_json(url + "/debug/cluster")
        merged1 = _merged_requests(view1["metrics"])
        assert merged1 > 0

        # kill worker-1 ungracefully, bring it back under the same id
        cluster.restart_worker("worker-1")
        ok2, _ = drive(30)
        assert ok2
        time.sleep(0.2)
        for w in cluster.workers:
            assert w.heartbeat()
        view2 = _get_json(url + "/debug/cluster")
        merged2 = _merged_requests(view2["metrics"])
        assert merged2 >= merged1, "merged counter went backwards"

        # each worker heartbeated at the same quiesced instant, so the
        # merged value must equal the sum of the per-worker reported
        # cumulative counters — federation loses nothing
        reported = sum(
            sum(s["value"] for s in
                worker_snapshot()["metrics"]
                ["mmlspark_serving_requests_total"]["series"])
            for _ in cluster.workers)
        assert merged2 == pytest.approx(reported)

        # scorecard saw every accepted request under the tenant class
        card = view2["scorecard"]
        acme = [c for c in card["classes"] if c["tenant"] == "acme"]
        assert acme and acme[0]["total"] >= (ok1 + ok2) * len(
            cluster.workers)
        assert card["workers"] == 3
        assert card["counter_resets"] >= 0

        # /workers: live health digests from the piggybacked heartbeat
        workers = _get_json(url + "/workers")
        assert set(workers) == {"worker-0", "worker-1", "worker-2"}
        for info in workers.values():
            digest = info["digest"]
            assert set(digest) >= {"queue_depth", "in_flight",
                                   "open_breakers", "stall_age_seconds",
                                   "degraded"}
            assert digest["degraded"] is False

        # the in-process twin mirrors the HTTP view
        card2 = cluster.scorecard()
        assert card2["worker_health"].keys() == workers.keys()
        assert card2["snapshots"] >= card["snapshots"]
    finally:
        stop.set()
        get_injector().clear()
        cluster.close()


def _merged_requests(prom_text):
    total = 0.0
    hits = 0
    for line in prom_text.splitlines():
        if line.startswith("mmlspark_serving_requests_total{"):
            total += float(line.rsplit(" ", 1)[1])
            hits += 1
    assert hits, "merged exposition lacks requests_total"
    return total
