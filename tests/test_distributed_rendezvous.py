"""Coordinator rendezvous tests (parity: the reference's driver-socket
host:port exchange, LightGBMBase.scala:399-437 / TrainUtils.scala:237-278 —
here it only bootstraps jax.distributed, no data plane)."""

import threading

import pytest

from mmlspark_tpu.parallel.distributed import (coordinator_rendezvous,
                                               find_open_port)


def test_driver_and_workers_agree_on_coordinator():
    port = find_open_port()
    results = {}

    def worker(i):
        results[i] = coordinator_rendezvous(
            "worker", "127.0.0.1", port, num_workers=3, timeout_s=15)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    coord = coordinator_rendezvous("driver", "127.0.0.1", port,
                                   num_workers=3, timeout_s=15)
    for t in threads:
        t.join(15)
    assert len(results) == 3
    assert set(results.values()) == {coord}
    host, p = coord.rsplit(":", 1)
    assert host == "127.0.0.1" and int(p) > 0


def test_worker_times_out_without_driver():
    with pytest.raises(TimeoutError, match="rendezvous"):
        coordinator_rendezvous("worker", "127.0.0.1", find_open_port(),
                               num_workers=1, timeout_s=1.0)


def test_workers_can_connect_before_driver_listens():
    """Workers retry until the driver's listener appears (task start order
    is arbitrary under gang scheduling)."""
    port = find_open_port()
    results = {}

    def late_worker():
        results["w"] = coordinator_rendezvous(
            "worker", "127.0.0.1", port, num_workers=1, timeout_s=15)

    t = threading.Thread(target=late_worker)
    t.start()
    import time
    time.sleep(0.5)  # worker is already retrying
    coord = coordinator_rendezvous("driver", "127.0.0.1", port,
                                   num_workers=1, timeout_s=15)
    t.join(15)
    assert results["w"] == coord


def test_driver_bind_conflict_surfaces():
    """A raced-away listen port must error in the driver, not strand the
    workers (the serve loop used to swallow EADDRINUSE in a thread)."""
    import socket
    blocker = socket.socket()
    blocker.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        with pytest.raises(OSError):
            coordinator_rendezvous("driver", "127.0.0.1", port,
                                   num_workers=1, timeout_s=2)
    finally:
        blocker.close()
