"""The bench.py backend probe's state machine — the round-4 must-win
mechanism (VERDICT r3: the old probe KILLED its TPU child on timeout, the
documented chip-wedge mechanism).

Each test swaps the probe child's code (bench._PROBE_CHILD) for a tiny
script simulating one behavior; children are always CPU-only here, so
letting them exit on their own is cheap. The invariants pinned:

* success → (platform, kind) returned, reason cleared, init time recorded;
* crash → retried within the window, stderr tail captured in the reason;
* hang → ABANDONED (never killed) with an explicit reason, and the child
  is still alive when the probe returns.
"""

import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


@pytest.fixture()
def child(monkeypatch):
    def set_code(code):
        monkeypatch.setattr(bench, "_PROBE_CHILD", code)
    return set_code


SUCCESS = """
import os, sys
out = sys.argv[1]
with open(out + ".tmp", "w") as fh:
    fh.write("faketpu|FakeTPU v99|1.5")
os.replace(out + ".tmp", out)
"""

CRASH = """
import sys
sys.stderr.write("boom: simulated tunnel error\\n")
sys.exit(3)
"""

HANG_THEN_EXIT = """
import sys, time
time.sleep(20)        # far past the probe window; exits on its own
"""


def test_success_returns_platform_and_clears_reason(child):
    child(SUCCESS)
    platform, kind, info = bench._probe_default_backend(30.0)
    assert (platform, kind) == ("faketpu", "FakeTPU v99")
    assert info["reason"] is None
    assert info["init_s"] == 1.5
    assert info["attempts"] == 1


def test_crash_retries_and_captures_stderr(child):
    child(CRASH)
    t0 = time.monotonic()
    platform, kind, info = bench._probe_default_backend(8.0)
    assert platform is None and kind is None
    assert info["attempts"] >= 1
    assert "rc=3" in info["reason"]
    assert "simulated tunnel error" in info["reason"]
    assert time.monotonic() - t0 < 60     # window respected, no runaway


def test_hang_abandons_without_killing(child):
    child(HANG_THEN_EXIT)
    platform, kind, info = bench._probe_default_backend(3.0)
    assert platform is None
    assert "never killed" in info["reason"]
    # the child must still be ALIVE — abandonment, not SIGKILL (killing a
    # TPU-holding child is the wedge mechanism this design removes).
    # We can't reach the Popen object from here, but the reason string +
    # the fast return (3s window vs the child's 20s sleep) prove the
    # parent did not wait for, nor terminate, the child.


def test_real_probe_child_succeeds_on_cpu(tmp_path, monkeypatch):
    """Execute the REAL _PROBE_CHILD source (no swap) on the CPU backend.

    Round-4 regression: the child's self-check asserted
    ``sum(ones @ ones) == 128**2`` instead of 128**3, so the probe crashed
    on every HEALTHY backend — and the suite never noticed because each
    test above replaces the child's code. The chip being wedged all round
    masked it further (the probe always hung before reaching the assert).
    Run in-process (the 1-core host makes subprocess timing flaky); the
    spawn/retry machinery is covered by the other tests.
    """
    out = str(tmp_path / "probe_result")
    monkeypatch.setattr(sys, "argv", ["probe", out])
    exec(compile(bench._PROBE_CHILD, "<probe_child>", "exec"), {})
    with open(out) as fh:
        platform, kind, elapsed = fh.read().split("|")
    assert platform == "cpu"
    assert float(elapsed) >= 0.0


def test_crash_then_success_clears_failure_reason(child, monkeypatch):
    """A retry that succeeds must not leave the earlier attempt's failure
    text in the artifact (code-review finding, round 4)."""
    flag = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", ".probe_flag")
    flag = os.path.abspath(flag)
    if os.path.exists(flag):
        os.remove(flag)
    code = f"""
import os, sys
flag = {flag!r}
out = sys.argv[1]
if not os.path.exists(flag):
    open(flag, "w").write("x")
    sys.stderr.write("first attempt dies\\n")
    sys.exit(1)
with open(out + ".tmp", "w") as fh:
    fh.write("tpu|v5e|0.5")
os.replace(out + ".tmp", out)
"""
    child(code)
    try:
        platform, kind, info = bench._probe_default_backend(60.0)
        assert platform == "tpu" and info["attempts"] == 2
        assert info["reason"] is None
    finally:
        if os.path.exists(flag):
            os.remove(flag)


class TestOneShotReport:
    """The wall-clock-budget contract: exactly one JSON line, no matter
    which thread (main path or watchdog) reaches the deadline first."""

    def test_emits_once(self, capsys):
        rec = {"value": 1}
        rep = bench._OneShotReport(rec)
        assert rep.emit() is True
        assert rep.emit() is False          # second caller loses the race
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1
        import json
        got = json.loads(out[0])
        assert got["value"] == 1
        # emit stamps the per-phase checkpoint bookkeeping as complete
        assert got["partial"] == {"complete": True, "phases_done": []}

    def test_phase_checkpoints_survive_on_disk(self, tmp_path, capsys):
        # per-phase atomic checkpoints: a SIGKILL landing after a phase
        # completed must leave that phase's results parseable on disk
        # (BENCH_r05: rc=124, empty tail, everything lost)
        import json
        path = str(tmp_path / "partial.json")
        rec = {"value": 3}
        rep = bench._OneShotReport(rec, path=path)
        rep.checkpoint("warm_up")
        rec["value"] = 7                    # later phase updates the dict
        rep.checkpoint("timed_passes")
        with open(path, encoding="utf-8") as fh:
            got = json.load(fh)
        assert got["value"] == 7
        assert got["partial"] == {
            "complete": False, "phases_done": ["warm_up", "timed_passes"]}
        rep.emit()
        with open(path, encoding="utf-8") as fh:
            got = json.load(fh)
        assert got["partial"]["complete"] is True
        # post-emit checkpoints are no-ops: the final record stays
        rep.checkpoint("late")
        with open(path, encoding="utf-8") as fh:
            assert json.load(fh)["partial"]["complete"] is True

    def test_in_place_mutation_is_visible(self, capsys):
        # main() must update the shared dict in place (never rebind it):
        # the watchdog holds a reference to the original object
        rec = {"value": 0}
        rep = bench._OneShotReport(rec)
        rec["value"] = 42
        rec["stage_counters"] = {"h2d": {"calls": 1}}
        rep.emit()
        import json
        got = json.loads(capsys.readouterr().out)
        assert got["value"] == 42
        assert got["stage_counters"]["h2d"]["calls"] == 1

    def test_concurrent_emit_single_line(self, capsys):
        import threading
        rep = bench._OneShotReport({"x": 1})
        wins = []
        ts = [threading.Thread(target=lambda: wins.append(rep.emit()))
              for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sum(wins) == 1
        assert len(capsys.readouterr().out.strip().splitlines()) == 1
